"""ozone-tpu CLI: shell, admin, freon, daemons, debug.

Mirror of the reference's CLI surface (hadoop-ozone/tools shell/
OzoneShell.java `ozone sh` volume/bucket/key verbs; `ozone admin`
safemode/datanode/container commands; `ozone freon` generators;
`ozone debug`; service starters). Talks to a running cluster over gRPC.

Usage examples:
  ozone-tpu scm-om --db /data/om.db --port 9860
  ozone-tpu datanode --root /data/dn1 --scm 127.0.0.1:9860
  ozone-tpu sh volume create /vol1 --om 127.0.0.1:9860
  ozone-tpu sh key put /vol1/bucket1/key1 ./file --om ...
  ozone-tpu admin safemode status --om ...
  ozone-tpu freon ockg -n 1000 -s 1048576 --om ...
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
from pathlib import Path

import numpy as np

from ozone_tpu.storage.ids import StorageError


def _client_tls():
    """CLI mTLS material for secure clusters, driven by environment:
    OZONE_TPU_CERT_DIR (where the client keypair/cert live) plus, for
    first contact, OZONE_TPU_ENROLL (the SCM enrollment address) and
    optional OZONE_TPU_ENROLL_SECRET."""
    import os

    cert_dir = os.environ.get("OZONE_TPU_CERT_DIR")
    if not cert_dir:
        return None
    from ozone_tpu.utils.ca import CertificateClient

    cc = CertificateClient(Path(cert_dir), "client-cli")
    if not cc.enrolled:
        enroll = os.environ.get("OZONE_TPU_ENROLL")
        if not enroll:
            print("error: OZONE_TPU_CERT_DIR set but not enrolled; set "
                  "OZONE_TPU_ENROLL to the SCM enrollment address",
                  file=sys.stderr)
            sys.exit(1)
        cc.enroll_remote(enroll,
                         secret=os.environ.get("OZONE_TPU_ENROLL_SECRET"))
    return cc.tls()


def _client(args):
    from ozone_tpu.client.dn_client import DatanodeClientFactory
    from ozone_tpu.client.ozone_client import OzoneClient
    from ozone_tpu.net.om_service import GrpcOmClient

    tls = _client_tls()
    clients = DatanodeClientFactory()
    clients.tls = tls
    om = GrpcOmClient(args.om, clients=clients, tls=tls)
    # learn datanode addresses up front
    from ozone_tpu.net.scm_service import AdminTokenFetcher, GrpcScmClient

    import os

    clients.location = os.environ.get("OZONE_TPU_CLIENT_LOCATION")
    try:
        scm = GrpcScmClient(args.om, tls=tls)
        addresses, locations = scm.node_topology()
        for dn_id, addr in addresses.items():
            clients.register_remote(dn_id, addr)
        clients.learn_locations(locations)
        if scm.status().get("block_tokens"):
            # dn-direct debug/repair verbs fetch operator tokens from
            # the SCM instead of holding the secret keys
            clients.tokens.issuer = AdminTokenFetcher(scm)
    except Exception:
        pass
    from ozone_tpu.net.ratis_service import RatisClientFactory

    ratis = RatisClientFactory(address_source=clients.remote_address)
    ratis.tls = tls
    return OzoneClient(om, clients, ratis_clients=ratis)


def _serve(stop_fn) -> int:
    """Run a daemon until SIGTERM/SIGINT, then shut it down cleanly —
    a TERM'd daemon must flush buffered state (OM double buffer) before
    the process dies."""
    import signal

    done = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: done.set())
    try:
        while not done.wait(3600):
            pass
    except KeyboardInterrupt:
        pass
    finally:
        stop_fn()
    return 0


def _parse_path(path: str) -> list[str]:
    return [p for p in path.strip("/").split("/") if p]


def _emit(obj) -> None:
    print(json.dumps(obj, indent=2, default=str))


def _quota_arg(v: str):
    """'10MB'/'1073741824' -> bytes; '' -> None (leave unchanged);
    'clear' -> -1 (unlimited)."""
    from ozone_tpu.utils.config import parse_size

    if not v:
        return None
    if v == "clear":
        return -1
    return int(parse_size(v))


#: verbs valid per sh object; anything else errors instead of no-opping
_SH_VERBS = {
    "volume": {"create", "delete", "info", "list", "setquota", "update"},
    "bucket": {"create", "delete", "info", "list", "setquota", "link",
               "set-replication", "set-smallobj"},
    "key": {"put", "get", "delete", "info", "list", "rename", "checksum",
            "cat", "cp", "rewrite"},
    "snapshot": {"create", "list", "info", "delete", "diff", "rename"},
    "token": {"get", "renew", "cancel", "print"},
}


def _sh_token(args, verb: str) -> int:
    """`ozone sh token get|renew|cancel|print` (reference shell token
    verbs over OzoneManager.getDelegationToken/renew/cancel). Tokens are
    portable JSON files; --token names the file, --renewer the renewer
    principal on get."""
    from ozone_tpu.net.om_service import GrpcOmClient

    def _read_token():
        if not args.token:
            print("error: --token FILE required", file=sys.stderr)
            return None
        try:
            with open(args.token) as f:
                return json.load(f)
        except (OSError, ValueError) as e:
            print(f"error: cannot read token file {args.token}: {e}",
                  file=sys.stderr)
            return None

    if verb == "print":
        tok = _read_token()
        if tok is None:
            return 2
        _emit(tok)
        return 0
    om = GrpcOmClient(args.om, tls=_client_tls())
    if verb == "get":
        if not args.renewer:
            print("error: --renewer required", file=sys.stderr)
            return 2
        # the token's owner is the local OS user (the reference binds
        # the Kerberos principal; the CLI analog is the login identity)
        import getpass

        with om.user_context(getpass.getuser()):
            tok = om.get_delegation_token(args.renewer)
        if args.token:
            with open(args.token, "w") as f:
                json.dump(tok, f)
            print(f"token written to {args.token}")
        else:
            _emit(tok)
        return 0
    tok = _read_token()
    if tok is None:
        return 2
    # renew/cancel require an authenticated caller (the OM refuses
    # anonymous remote renewals — an unauthenticated holder of the token
    # file must not be able to extend or revoke it); the CLI's identity
    # is the login user, same convention as `get`
    import getpass

    with om.user_context(getpass.getuser()):
        if verb == "renew":
            _emit({"expiry": om.renew_delegation_token(tok)})
        elif verb == "cancel":
            om.cancel_delegation_token(tok)
            print("token cancelled")
    return 0


# ---------------------------------------------------------------------- sh
def cmd_sh(args) -> int:
    kind, verb = args.object, args.verb
    if verb not in _SH_VERBS[kind]:
        print(f"error: '{verb}' is not a {kind} verb (expected one of "
              f"{sorted(_SH_VERBS[kind])})", file=sys.stderr)
        return 2
    if kind == "token":
        return _sh_token(args, verb)
    if not args.path:
        print(f"error: {kind} {verb} requires a /volume[/bucket[/key]] "
              f"path", file=sys.stderr)
        return 2
    oz = _client(args)
    parts = _parse_path(args.path)
    if kind == "volume":
        if verb == "list":  # accepts "/" (no volume component)
            _emit(oz.list_volumes())
            return 0
        (vol,) = parts
        if verb == "create":
            oz.create_volume(vol)
        elif verb == "delete":
            oz.om.delete_volume(vol)
        elif verb == "info":
            _emit(oz.om.volume_info(vol))
        elif verb == "setquota":
            _emit(oz.om.set_quota(
                vol, quota_bytes=_quota_arg(args.quota),
                quota_namespace=args.namespace_quota))
        elif verb == "update":
            if not args.user:
                print("error: volume update requires --user NEWOWNER",
                      file=sys.stderr)
                return 2
            _emit(oz.om.set_volume_owner(vol, args.user))
    elif kind == "bucket":
        if verb == "list":
            (vol,) = parts
            _emit(oz.om.list_buckets(vol))
        else:
            vol, bucket = parts
            if verb == "create":
                oz.om.create_bucket(vol, bucket, args.replication,
                                    layout=args.layout,
                                    encryption_key=args.encryption_key,
                                    gdpr=args.gdpr)
            elif verb == "delete":
                oz.om.delete_bucket(vol, bucket)
            elif verb == "info":
                _emit(oz.om.bucket_info(vol, bucket))
            elif verb == "setquota":
                _emit(oz.om.set_quota(
                    vol, bucket, quota_bytes=_quota_arg(args.quota),
                    quota_namespace=args.namespace_quota))
            elif verb == "set-smallobj":
                _emit(oz.om.set_bucket_smallobj(vol, bucket))
            elif verb == "link":
                if not args.to:
                    print("error: bucket link requires --to "
                          "/volume/bucket", file=sys.stderr)
                    return 1
                dvol, dbkt = _parse_path(args.to)
                oz.om.create_bucket_link(vol, bucket, dvol, dbkt)
                print(f"linked /{dvol}/{dbkt} -> /{vol}/{bucket}")
            elif verb == "set-replication":
                if not args.replication:
                    print("error: set-replication requires "
                          "--replication", file=sys.stderr)
                    return 2
                b = oz.om.set_bucket_replication(vol, bucket,
                                                 args.replication)
                _emit({"bucket": f"/{vol}/{bucket}",
                       "replication": b["replication"]})
    elif kind == "snapshot":
        if verb == "list":
            vol, bucket = parts
            _emit(oz.om.list_snapshots(vol, bucket))
        elif verb == "diff":
            vol, bucket = parts
            if not args.name:
                print("error: snapshot diff requires --name",
                      file=sys.stderr)
                return 1
            if args.page_size:
                # job-based paged flow (SnapshotDiffManager job model):
                # submit, poll to a terminal state, stream pages
                import time as _time

                job = oz.om.snapshot_diff_submit(vol, bucket, args.name,
                                                 args.to or None)
                deadline = _time.time() + 300
                while (job["status"] == "IN_PROGRESS"
                       and _time.time() < deadline):
                    _time.sleep(0.1)
                    job = oz.om.snapshot_diff_submit(
                        vol, bucket, args.name, args.to or None)
                if job["status"] != "DONE":
                    _emit(job)
                    return 1
                token = ""
                while True:
                    page = oz.om.snapshot_diff_page(
                        job["job_id"], token, args.page_size)
                    for e in page["entries"]:
                        print(json.dumps(e))
                    token = page["next_token"]
                    if not token:
                        break
                print(json.dumps({"job_id": job["job_id"],
                                  "total": page["total"],
                                  "mode": page["mode"]}),
                      file=sys.stderr)
            else:
                _emit(oz.om.snapshot_diff(vol, bucket, args.name,
                                          args.to or None))
        else:
            vol, bucket = parts
            if not args.name:
                print(f"error: snapshot {verb} requires --name",
                      file=sys.stderr)
                return 1
            if verb == "create":
                _emit(oz.om.create_snapshot(vol, bucket, args.name))
            elif verb == "rename":
                if not args.to:
                    print("error: snapshot rename requires --to",
                          file=sys.stderr)
                    return 1
                _emit(oz.om.rename_snapshot(vol, bucket, args.name,
                                            args.to))
            elif verb == "info":
                _emit(oz.om.snapshot_info(vol, bucket, args.name))
            elif verb == "delete":
                oz.om.delete_snapshot(vol, bucket, args.name)
                print(f"deleted snapshot {args.name}")
    elif kind == "key":
        if verb == "list":
            vol, bucket = parts
            _emit(oz.om.list_keys(vol, bucket, args.prefix,
                                  args.start_after, args.limit))
            return 0
        vol, bucket, *rest = parts
        key = "/".join(rest)
        b = oz.get_volume(vol).get_bucket(bucket)
        if verb == "put":
            data = Path(args.file).read_bytes()
            b.write_key(key, np.frombuffer(data, np.uint8),
                        args.replication if args.replication else None)
            print(f"wrote {len(data)} bytes to {args.path}")
        elif verb == "get":
            if args.offset or args.length is not None:
                info = b.lookup_key_info(key)
                size = int(info["size"])
                off = min(max(0, args.offset), size)
                ln = (size - off if args.length is None
                      else max(0, min(args.length, size - off)))
                data = b.read_key_info_range(info, off, ln)
            else:
                data = b.read_key(key)
            out = Path(args.file) if args.file else None
            if out:
                out.write_bytes(data.tobytes())
                print(f"read {data.size} bytes to {out}")
            else:
                sys.stdout.buffer.write(data.tobytes())
        elif verb == "delete":
            b.delete_key(key)
        elif verb == "info":
            _emit(oz.om.lookup_key(vol, bucket, key))
        elif verb == "checksum":
            _emit(b.file_checksum(key))
        elif verb == "rename":
            b.rename_key(key, args.to)
        elif verb == "cat":
            sys.stdout.buffer.write(b.read_key(key).tobytes())
        elif verb == "cp":
            if not args.to:
                print("error: cp requires --to /volume/bucket/key",
                      file=sys.stderr)
                return 2
            dparts = _parse_path(args.to)
            if len(dparts) < 3:
                print("error: cp --to needs a full /volume/bucket/key "
                      f"path, got {args.to!r}", file=sys.stderr)
                return 2
            dv, db_, *drest = dparts
            b.copy_key(key, oz.get_volume(dv).get_bucket(db_),
                       "/".join(drest),
                       replication=args.replication or None)
            print(f"copied {args.path} to {args.to}")
        elif verb == "rewrite":
            if not args.replication:
                print("error: rewrite requires --replication",
                      file=sys.stderr)
                return 2
            b.rewrite_key(key, args.replication)
            print(f"rewrote {args.path} as {args.replication}")
    return 0


# ---------------------------------------------------------------- acl/tenant
def cmd_acl(args) -> int:
    """Native ACL verbs (reference: ozone sh volume|bucket|key|prefix
    addacl/removeacl/setacl/getacl)."""
    oz = _client(args)
    parts = _parse_path(args.path)
    vol = parts[0]
    bucket = parts[1] if len(parts) > 1 else ""
    path = "/".join(parts[2:]) if len(parts) > 2 else ""
    if args.verb == "get":
        _emit(oz.om.get_acls(args.object, vol, bucket, path))
    else:
        op = {"add": "add", "remove": "remove", "set": "set"}[args.verb]
        changed = oz.om.modify_acl(args.object, vol, bucket, path, op,
                                   args.acl)
        print("changed" if changed else "unchanged")
    return 0


def cmd_tenant(args) -> int:
    """Tenant admin verbs (reference: ozone tenant create/delete/list,
    ozone tenant user assign/revoke/list)."""
    oz = _client(args)
    om = oz.om
    if args.verb == "create":
        om.create_tenant(args.tenant)
        print(f"tenant {args.tenant} created")
    elif args.verb == "delete":
        om.delete_tenant(args.tenant)
        print(f"tenant {args.tenant} deleted")
    elif args.verb == "list":
        _emit(om.list_tenants())
    elif args.verb == "assign":
        _emit(om.tenant_assign_user(args.tenant, args.user))
    elif args.verb == "revoke":
        om.tenant_revoke_access(args.access_id)
        print(f"revoked {args.access_id}")
    elif args.verb == "users":
        _emit(om.list_tenant_users(args.tenant))
    return 0


# ---------------------------------------------------------------------- fs
def cmd_fs(args) -> int:
    """Filesystem verbs against FSO buckets (reference: ozone fs via the
    Hadoop shell — mkdir/ls/stat/rm on o3fs paths)."""
    oz = _client(args)
    vol, bucket, *rest = _parse_path(args.path)
    path = "/".join(rest)
    om = oz.om
    if args.verb == "mkdir":
        om.create_directory(vol, bucket, path)
        print(f"created directory /{vol}/{bucket}/{path}")
    elif args.verb == "ls":
        _emit(om.list_status(vol, bucket, path))
    elif args.verb == "stat":
        _emit(om.get_file_status(vol, bucket, path))
    elif args.verb == "rm":
        st = om.get_file_status(vol, bucket, path)
        if st["type"] == "DIRECTORY":
            om.delete_directory(vol, bucket, path, recursive=args.recursive)
        else:
            om.delete_key(vol, bucket, path)
        print(f"deleted /{vol}/{bucket}/{path}")
    elif args.verb == "recover-lease":
        _emit(om.recover_lease(vol, bucket, path))
    return 0


def _cmd_audit(args) -> int:
    from ozone_tpu.tools.audit_parser import run_cli

    return run_cli(args)


# -------------------------------------------------------------------- admin
def cmd_admin(args) -> int:
    from ozone_tpu.net.scm_service import GrpcScmClient

    def usage(msg: str) -> int:
        print(f"error: {msg}", file=sys.stderr)
        return 2

    scm = GrpcScmClient(args.om, tls=_client_tls())
    subject, verb, target = args.subject, args.verb, args.target
    if subject == "safemode":
        if verb in ("enter", "exit"):
            _emit(scm.admin(f"safemode-{verb}"))
        elif verb in (None, "status"):
            st = scm.status()
            _emit({"safemode": st["safemode"], **st["safemode_status"]})
        else:
            return usage(f"unknown safemode verb {verb!r} "
                         "(expected enter|exit|status)")
    elif subject == "datanode":
        if verb in ("decommission", "recommission", "maintenance"):
            if not target:
                return usage(f"datanode {verb} needs a datanode id")
            _emit(scm.admin(verb, target))
        elif verb in (None, "list"):
            _emit(scm.status()["nodes"])
        else:
            return usage(f"unknown datanode verb {verb!r} (expected "
                         "list|decommission|recommission|maintenance)")
    elif subject == "pipeline":
        if verb == "close":
            if not target:
                return usage("pipeline close requires a pipeline id")
            _emit(scm.admin("close-pipeline", target))
        elif verb in (None, "list"):
            _emit(scm.admin("pipelines"))
        else:
            return usage(f"unknown pipeline verb {verb!r} "
                         "(expected list|close)")
    elif subject == "upgrade":
        # finalization progress view (`ozone admin scm finalizationstatus`
        # analog): which layout features are live vs gated
        _emit(scm.admin("upgrade-status"))
    elif subject == "finalizeupgrade":
        # non-rolling upgrade completion (ozone admin scm
        # finalizeupgrade analog): bump the metadata services' layout
        # and command every datanode to finalize
        _emit(scm.admin("finalize-upgrade"))
    elif subject == "container":
        if verb == "close":
            if not target:
                return usage("container close requires a container id")
            _emit(scm.admin("close-container", target))
        elif verb == "info":
            if not target:
                return usage("container info requires a container id")
            _emit(scm.admin("container-info", target))
        elif verb == "report":
            # ReplicationManagerReport analog: state + health census
            _emit(scm.admin("container-report"))
        elif verb in (None, "list"):
            _emit(scm.list_containers())
        else:
            return usage(f"unknown container verb {verb!r} "
                         "(expected list|info <id>|report|close <id>)")
    elif subject == "balancer":
        if verb not in (None, "status", "start", "stop"):
            return usage(f"unknown balancer verb {verb!r} "
                         "(expected start|stop|status)")
        cfg = {}
        if args.threshold is not None:
            cfg["threshold"] = args.threshold
        if args.max_moves is not None:
            cfg["max_moves_per_iteration"] = args.max_moves
        if args.max_size is not None:
            cfg["max_size_per_iteration"] = args.max_size
        if cfg and verb != "start":
            # config only applies at start; silently dropping it would
            # leave the operator believing the settings took
            return usage("balancer config flags require the 'start' verb")
        _emit(scm.admin(f"balancer-{verb or 'status'}", cfg or None))
    elif subject == "replicationmanager":
        _emit(scm.admin("replication-status"))
    elif subject == "ring":
        # metadata-ring membership (OM bootstrap / decommission-OM
        # analog): add a started-but-empty replica, or retire one
        if verb == "add":
            if not target or "=" not in target:
                return usage("ring add needs <id>=<host:port>")
            _emit(scm.admin("ring-add", target))
        elif verb == "remove":
            if not target:
                return usage("ring remove needs the replica id")
            _emit(scm.admin("ring-remove", target))
        elif verb == "transfer":
            # `ozone admin om transfer --node` analog: planned
            # leadership hand-off to the named replica
            if not target:
                return usage("ring transfer needs the target replica id")
            _emit(scm.admin("ring-transfer", target))
        elif verb in (None, "status", "roles"):
            # `ozone admin om roles` analog: role/term/leader from the
            # replica that answered (any replica, incl. followers)
            _emit(scm.admin("ring-status"))
        else:
            return usage(f"unknown ring verb {verb!r} "
                         "(expected add <id>=<addr>|remove <id>|"
                         "transfer <id>|status)")
    elif subject == "cert":
        # CA lifecycle (ozone admin cert list/revoke analog): answered
        # by the replica hosting the cluster CA
        if verb in (None, "list"):
            _emit(scm.admin("cert-list", None))
        elif verb == "revoke":
            if not target:
                return usage("cert revoke needs the cert serial")
            _emit(scm.admin("cert-revoke", target))
        else:
            return usage(f"unknown cert verb {verb!r} "
                         "(expected list|revoke <serial>)")
    elif subject == "kms":
        # TDE master-key authority (ozone admin + KMS keyadmin analog)
        from ozone_tpu.net.om_service import GrpcOmClient

        om = GrpcOmClient(args.om, tls=_client_tls())
        if verb == "create-key":
            if not target:
                return usage("kms create-key needs a key name")
            _emit(om.kms_create_key(target))
        elif verb == "rotate-key":
            if not target:
                return usage("kms rotate-key needs a key name")
            _emit(om.kms_create_key(target, rotate=True))
        elif verb in (None, "list"):
            _emit(om.kms_list_keys())
        elif verb == "info":
            if not target:
                return usage("kms info needs a key name")
            _emit(om.kms_key_info(target))
        else:
            return usage(f"unknown kms verb {verb!r} (expected "
                         "create-key|rotate-key|list|info)")
    elif subject == "om":
        from ozone_tpu.net.om_service import GrpcOmClient

        om = GrpcOmClient(args.om, tls=_client_tls())
        if verb == "prepare":
            _emit(om.prepare())
        elif verb == "cancelprepare":
            om.cancel_prepare()
            _emit({"prepared": False})
        elif verb == "list-open-files":
            vol = bkt = ""
            if args.target:
                parts = _parse_path(args.target)
                vol = parts[0] if parts else ""
                bkt = parts[1] if len(parts) > 1 else ""
            _emit(om.list_open_files(
                vol, bkt, prefix=args.prefix,
                start_after=args.start_after,
                limit=args.limit if args.limit is not None else 100))
        elif verb in (None, "status"):
            _emit(om.prepare_status())
        else:
            return usage(f"unknown om verb {verb!r} "
                         "(expected prepare|cancelprepare|status|"
                         "list-open-files)")
    elif subject == "shards":
        # sharded metadata plane: show the root shard map (epoch,
        # slot ownership, address book) as any routing client sees it
        from ozone_tpu.net.om_service import GrpcOmClient
        from ozone_tpu.om.sharding.shardmap import ShardMap

        om = GrpcOmClient(args.om, tls=_client_tls(), shard_aware=False)
        try:
            if verb in (None, "map", "status"):
                mj = om.get_shard_map()
                if not mj:
                    print("no shard map installed (unsharded deployment)")
                    return 0
                m = ShardMap.from_json(mj)
                counts: dict[str, int] = {}
                for idx in m.slots:
                    sid = m.shards[idx]
                    counts[sid] = counts.get(sid, 0) + 1
                _emit({
                    "epoch": m.epoch,
                    "slot_count": len(m.slots),
                    "shards": sorted(counts),
                    "slots_per_shard": counts,
                    "addresses": m.addresses,
                })
            else:
                return usage(f"unknown shards verb {verb!r} "
                             "(expected map|status)")
        finally:
            om.close()
    elif subject == "namespace":
        # `ozone admin namespace summary <path>` analog: per-directory
        # du / entity counts from Recon's NSSummary warehouse
        import urllib.request
        from urllib.parse import quote

        if not args.http:
            print("error: namespace summary requires --http host:port "
                  "(the Recon endpoint)", file=sys.stderr)
            return 2
        # `admin namespace summary /vol/bucket/dir` (or the path given
        # directly as the verb slot — paths always start with /)
        if verb == "summary":
            path = target or "/"
        elif verb is None or verb.startswith("/"):
            path = verb or "/"
        else:
            return usage(f"unknown namespace verb {verb!r} "
                         "(expected: summary <path>)")
        url = (f"http://{args.http}/api/nssummary?path="
               f"{quote(path, safe='/')}")
        try:
            with urllib.request.urlopen(url, timeout=10) as r:
                print(r.read().decode())
        except urllib.error.HTTPError as e:
            print(f"error: {e.code} {e.read().decode()}", file=sys.stderr)
            return 1
        except urllib.error.URLError as e:
            print(f"error: cannot reach {args.http}: {e.reason}",
                  file=sys.stderr)
            return 1
        return 0
    elif subject == "reconfig":
        # live reconfiguration (ozone admin reconfig analog over the
        # daemon's /reconfig HTTP endpoint, ReconfigureProtocol.proto)
        import urllib.request
        from urllib.parse import quote

        if not args.http:
            print("error: reconfig requires --http host:port (the "
                  "daemon's HTTP/metrics port)", file=sys.stderr)
            return 2
        if verb in (None, "properties"):
            url = f"http://{args.http}/reconfig/properties"
        elif verb == "set":
            if not args.target or args.value is None:
                print("error: reconfig set needs a KEY target and "
                      "--value", file=sys.stderr)
                return 2
            url = (f"http://{args.http}/reconfig?key={quote(args.target)}"
                   f"&value={quote(args.value)}")
        else:
            return usage(f"unknown reconfig verb {verb!r} "
                         "(expected properties|set)")
        try:
            with urllib.request.urlopen(url, timeout=10) as r:
                body = r.read().decode()
        except urllib.error.HTTPError as e:
            print(f"error: {e.code} {e.read().decode()}", file=sys.stderr)
            return 1
        except urllib.error.URLError as e:
            print(f"error: cannot reach {args.http}: {e.reason}",
                  file=sys.stderr)
            return 1
        print(body)
        return 0
    elif subject == "status":
        _emit(scm.status())
    return 0


# -------------------------------------------------------------------- freon
def cmd_freon(args) -> int:
    from ozone_tpu.tools import freon

    if args.generator == "ockg":
        oz = _client(args)
        rep = freon.ockg(
            oz, n_keys=args.num, size=args.size, threads=args.threads,
            replication=args.replication or None, validate=args.validate,
            warmup=args.warmup,
        )
        _emit(rep.summary())
    elif args.generator == "ockr":
        oz = _client(args)
        _emit(freon.ockr(oz, args.num, threads=args.threads).summary())
    elif args.generator == "ockrr":
        oz = _client(args)
        _emit(freon.ockrr(oz, args.num, size=args.size,
                          threads=args.threads,
                          n_keys=args.keys).summary())
    elif args.generator == "ockv":
        oz = _client(args)
        _emit(freon.ockv(oz, n_keys=args.num, size=args.size,
                         threads=args.threads).summary())
    elif args.generator == "fskg":
        oz = _client(args)
        _emit(freon.fskg(
            oz, n_files=args.num, size=args.size, threads=args.threads,
            replication=args.replication or None,
        ).summary())
    elif args.generator == "mpug":
        oz = _client(args)
        _emit(freon.mpug(
            oz, n_uploads=args.num, part_size=args.size,
            threads=args.threads,
            replication=args.replication or None,
        ).summary())
    elif args.generator == "fsg":
        _emit(freon.fsg(
            _client(args), n_files=args.num, size=args.size,
            threads=args.threads,
            replication=args.replication or None).summary())
    elif args.generator == "ecrd":
        from ozone_tpu.net.scm_service import GrpcScmClient

        scm = GrpcScmClient(args.om, tls=_client_tls())
        _emit(freon.ecrd(
            _client(args), scm, size=args.size, rounds=args.num,
            replication=args.replication or "rs-6-3-1048576",
        ))
    elif args.generator == "sdg":
        # -t is deliberately not honored: the snapshot chain is ordered
        _emit(freon.sdg(
            _client(args), n_rounds=args.num, size=args.size,
            replication=args.replication or None).summary())
    elif args.generator == "s3kg":
        _emit(freon.s3kg(
            args.endpoint, n_keys=args.num, size=args.size,
            threads=args.threads, validate=args.validate,
        ).summary())
    elif args.generator == "swarm":
        # closed-loop multi-tenant overload swarm against the S3
        # gateway; anonymous tenants from the CLI (signed tenants need
        # OM-provisioned credentials — the bench wires those)
        tenants = [{"name": f"tenant-{i}", "rate": 0.0}
                   for i in range(max(1, args.threads))]
        _emit(freon.swarm(
            args.endpoint, tenants, duration_s=args.duration,
            n_keys=args.num, tiny=args.tiny,
        ).summary())
    elif args.generator == "tinyg":
        oz = _client(args)
        _emit(freon.tinyg(
            oz, n_keys=args.num, size=args.size, threads=args.threads,
            replication=args.replication or "rs-3-2-4096",
            packer=not args.no_packer, mix=args.tiny,
            validate=args.validate,
        ).summary())
    elif args.generator == "lcg":
        oz = _client(args)
        _emit(freon.lcg(
            oz, n_keys=args.num, size=args.size, threads=args.threads,
            replication=args.replication or "RATIS/THREE",
            target=args.target,
        ).summary())
    elif args.generator == "geo":
        if not args.dest:
            print("error: freon geo needs --dest HOST:PORT (the "
                  "destination cluster endpoint)", file=sys.stderr)
            return 1
        oz = _client(args)
        _emit(freon.geo(
            oz, args.dest, n_keys=args.num, size=args.size,
            threads=args.threads,
            replication=args.replication or "RATIS/THREE",
            scheme=args.scheme,
        ).summary())
    elif args.generator == "hsg":
        oz = _client(args)
        _emit(freon.hsg(
            oz, n_keys=args.num, size=args.size, threads=args.threads,
            replication=args.replication or "RATIS/THREE",
        ).summary())
    elif args.generator == "rawcoder":
        _emit(
            freon.rawcoder_bench(
                schema=args.schema, cell=args.cell, batch=args.batch
            )
        )
    elif args.generator == "omkg":
        _emit(freon.omkg(_client(args), n_keys=args.num,
                         threads=args.threads).summary())
    elif args.generator == "ommg":
        _emit(freon.ommg(_client(args), n_ops=args.num,
                         threads=args.threads, mix=args.mix).summary())
    elif args.generator == "scmtb":
        _emit(freon.scmtb(
            _client(args), n_blocks=args.num, threads=args.threads,
            replication=args.replication or "rs-3-2-4096",
        ).summary())
    elif args.generator == "dnsim":
        from ozone_tpu.net.scm_service import GrpcScmClient

        scm = GrpcScmClient(args.om, tls=_client_tls())
        _emit(freon.dnsim(
            scm, n_datanodes=args.num, n_containers=args.containers,
            duration_s=args.duration, interval_s=args.interval,
            threads=args.threads,
        ).summary())
    elif args.generator == "cmdw":
        _emit(freon.cmdw(args.root or "/tmp/ozone-cmdw", n_chunks=args.num,
                         size=args.size, threads=args.threads).summary())
    elif args.generator == "dbgen":
        _emit(freon.dbgen(args.root or "/tmp/ozone-dbgen.db",
                          n_keys=args.num).summary())
    elif args.generator == "ralg":
        import tempfile

        root = args.root or tempfile.mkdtemp(prefix="ozone-ralg-")
        _emit(freon.ralg(root, n_entries=args.num, size=args.size,
                         threads=args.threads).summary())
    elif args.generator in ("dcg", "dcb", "dcv", "dsg", "dnbp"):
        oz = _client(args)
        dn_ids = list(oz.clients.known_ids())
        if not dn_ids:
            print(f"error: no datanodes known (is the SCM at {args.om} "
                  "reachable?)", file=sys.stderr)
            return 1
        if args.generator == "dnbp":
            _emit(freon.dnbp(oz.clients, dn_ids, args.num,
                             threads=args.threads).summary())
            return 0
        gen = {"dcg": freon.dcg, "dcb": freon.dcb, "dcv": freon.dcv,
               "dsg": freon.dsg}[args.generator]
        _emit(gen(oz.clients, dn_ids, args.num, size=args.size,
                  threads=args.threads).summary())
    return 0


# ------------------------------------------------------------------ daemons
def cmd_datanode(args) -> int:
    import logging

    from ozone_tpu.net.daemons import DatanodeDaemon

    logging.basicConfig(level=logging.INFO)
    dn_id = args.id or Path(args.root).name
    d = DatanodeDaemon(
        Path(args.root), dn_id, args.scm, port=args.port, rack=args.rack,
        scan_interval_s=args.scan_interval,
        ca_address=args.ca or None,
        enrollment_secret=args.enrollment_secret or None,
        num_volumes=args.volumes,
        volume_policy=args.volume_policy,
        replication_bandwidth_mbps=args.replication_bandwidth_mbps,
    )
    d.start()
    print(f"datanode {dn_id} serving on {d.address}, scm={args.scm}")
    return _serve(d.stop)


def cmd_cluster(args) -> int:
    """One-command local cluster (the reference's docker-compose
    ozone/ cluster analog): spawns a scm-om subprocess and N datanode
    subprocesses under one supervisor, waits until healthy, prints the
    endpoints, serves until SIGTERM/Ctrl-C, then tears every child
    down. For demos and smoke runs, not production layout."""
    import os
    import signal
    import subprocess
    import tempfile
    import time as _time

    root = Path(args.root or tempfile.mkdtemp(prefix="ozone-cluster-"))
    root.mkdir(parents=True, exist_ok=True)
    env = dict(os.environ, PYTHONPATH=str(Path(__file__).resolve()
                                          .parents[2]))
    procs: list = []

    def spawn(argv, log_name):
        logf = open(root / log_name, "w")
        p = subprocess.Popen(
            [sys.executable, "-m", "ozone_tpu.tools", *argv],
            stdout=logf, stderr=subprocess.STDOUT, env=env)
        procs.append(p)
        return p

    def teardown():
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()

    meta_args = ["scm-om", "--db", str(root / "om.db"),
                 "--port", str(args.port)]
    if args.http_port:
        meta_args += ["--http-port", str(args.http_port)]
    if args.recon_port:
        meta_args += ["--recon-port", str(args.recon_port)]
    spawn(meta_args, "scm-om.log")
    om = f"127.0.0.1:{args.port}"

    from ozone_tpu.net.scm_service import GrpcScmClient

    scm = GrpcScmClient(om)
    try:
        deadline = _time.time() + 60
        up = False
        while _time.time() < deadline:
            try:
                scm.status()
                up = True
                break
            except Exception:
                _time.sleep(0.5)
        if not up:
            teardown()
            print(f"error: metadata server did not come up (see "
                  f"{root}/scm-om.log)", file=sys.stderr)
            return 1
        for i in range(args.datanodes):
            spawn(["datanode", "--root", str(root / f"dn{i}"),
                   "--scm", om, "--id", f"dn{i}"], f"dn{i}.log")
        deadline = _time.time() + 60
        registered = False
        while _time.time() < deadline:
            try:
                st = scm.status()
                if len(st.get("nodes", [])) >= args.datanodes:
                    registered = True
                    break
            except Exception:
                pass
            _time.sleep(0.5)
        if not registered:
            teardown()
            print(f"error: datanodes did not register (see "
                  f"{root}/dn*.log)", file=sys.stderr)
            return 1
    except BaseException:
        teardown()
        raise
    finally:
        scm.close()
    print(f"cluster up: om={om} datanodes={args.datanodes} "
          f"root={root}")
    print(f"try: ozone-tpu sh volume create /v --om {om}")
    # _serve's own finally runs teardown; teardown is idempotent so a
    # second call on an exception path is safe but not needed here
    return _serve(teardown)


def cmd_scm_om(args) -> int:
    import logging

    from ozone_tpu.net.daemons import ScmOmDaemon

    logging.basicConfig(level=logging.INFO)
    ha_peers = None
    if args.peer:
        ha_peers = dict(p.split("=", 1) for p in args.peer)
        if not args.ha_id or args.ha_id not in ha_peers:
            print("--ha-id must name one of the --peer entries",
                  file=sys.stderr)
            return 1
    d = ScmOmDaemon(Path(args.db), port=args.port,
                    min_datanodes=args.min_datanodes,
                    http_port=args.http_port,
                    recon_port=args.recon_port,
                    ha_id=args.ha_id if ha_peers else None,
                    ha_peers=ha_peers,
                    block_tokens=args.block_tokens,
                    secure=args.secure,
                    enroll_port=args.enroll_port,
                    enrollment_secret=args.enrollment_secret or None,
                    ca_address=args.ca or None)
    d.start()
    print(f"scm+om serving on {d.address}"
          + (f" as HA node {args.ha_id}" if ha_peers else "")
          + (" [mTLS]" if d.tls is not None else "")
          + (f", enrollment on {d.enroll_address}" if d.enroll_server
             else "")
          + (f", http on {d.http.address}" if d.http else "")
          + (f", recon on {d.recon.address}" if d.recon else ""))
    return _serve(d.stop)


def cmd_s3g(args) -> int:
    """Run the S3 gateway daemon against a remote OM (reference:
    `ozone s3g`, s3gateway Gateway.java main)."""
    import logging

    from ozone_tpu.gateway.s3 import S3Gateway

    logging.basicConfig(level=logging.INFO)
    gw = S3Gateway(_client(args), port=args.port,
                   replication=args.replication,
                   require_auth=args.require_auth,
                   domain=args.domain or None)
    gw.start()
    print(f"s3 gateway serving on {gw.address}, om={args.om}")
    return _serve(gw.stop)


def cmd_httpfs(args) -> int:
    """Run the WebHDFS-compatible HttpFS gateway daemon (reference:
    `ozone httpfs`, httpfsgateway HttpFSServerWebServer)."""
    import logging

    from ozone_tpu.gateway.httpfs import HttpFSGateway

    logging.basicConfig(level=logging.INFO)
    gw = HttpFSGateway(_client(args), port=args.port,
                       replication=args.replication,
                       trash_interval_s=args.trash_interval or None)
    gw.start()
    print(f"httpfs gateway serving on {gw.address}, om={args.om}")
    return _serve(gw.stop)


def cmd_csi(args) -> int:
    """Run the CSI driver daemon (reference: `ozone csi`, csi
    CsiServer)."""
    import logging

    from ozone_tpu.gateway.csi import CsiServer

    logging.basicConfig(level=logging.INFO)
    srv = CsiServer(_client(args), s3_endpoint=args.s3_endpoint,
                    port=args.port, replication=args.replication)
    srv.start()
    print(f"csi driver serving on {srv.address}, om={args.om}")
    return _serve(srv.stop)


def cmd_s3(args) -> int:
    """S3 secret management (reference: `ozone s3 getsecret` /
    `revokesecret`)."""
    om = _client(args).om
    if args.verb == "getsecret":
        secret = om.get_s3_secret(args.access_id)
        _emit({"access_id": args.access_id, "secret": secret})
    elif args.verb == "revokesecret":
        om.revoke_s3_secret(args.access_id)
        _emit({"access_id": args.access_id, "revoked": True})
    return 0


def cmd_insight(args) -> int:
    """Per-subsystem introspection (ozone insight analog): list points,
    read metrics, tail logs, bump log levels on a running daemon."""
    from ozone_tpu.utils.insight import InsightClient

    cli = InsightClient(args.address or args.om, tls=_client_tls())
    try:
        if args.verb == "list":
            _emit(cli.list_points())
        elif args.verb == "metrics":
            _emit(cli.metrics())
        elif args.verb == "logs":
            for r in cli.logs(n=args.num, logger=args.logger,
                              level=args.level):
                print(f"{r['ts']:.3f} {r['level']:<8} {r['logger']}: "
                      f"{r['message']}")
        elif args.verb == "log-level":
            _emit(cli.set_log_level(args.logger, args.level or "DEBUG"))
        elif args.verb == "partition":
            if not args.dst:
                print("error INVALID: partition requires --dst",
                      file=sys.stderr)
                return 1
            _emit(cli.partition(args.dst, owner=args.owner))
        elif args.verb == "heal":
            if args.owner and not args.dst:
                print("error INVALID: heal --owner requires --dst",
                      file=sys.stderr)
                return 1
            _emit(cli.heal(args.dst, owner=args.owner))
        elif args.verb == "partitions":
            _emit({"blocked": cli.partition_list(),
                   "delayed": cli.delays()})
    finally:
        cli.close()
    return 0


def _scan_referenced_blocks(oz) -> set:
    """All (container, local) pairs referenced by committed keys."""
    referenced: set[tuple[int, int]] = set()
    for v in oz.om.list_volumes():
        for b in oz.om.list_buckets(v["name"]):
            for k in oz.om.list_keys(v["name"], b["name"]):
                for g in k.get("block_groups", []):
                    referenced.add(
                        (int(g["container_id"]), int(g["local_id"]))
                    )
    return referenced


def _repair_offline(args) -> int:
    """Offline OM-db surgery (reference: ozone repair's RDBRepair family
    — repair/om/SnapshotRepair.java re-points snapshot chain links,
    repair/TransactionInfoRepair.java resets the raft applied marker).
    Run against a STOPPED OM's db; dry-run unless --apply."""
    from pathlib import Path

    from ozone_tpu.om.metadata import OMMetadataStore
    from ozone_tpu.om.requests import snapmeta_key

    if not args.db:
        print("error: --db OM_DB_PATH required (service must be stopped)",
              file=sys.stderr)
        return 2
    if not Path(args.db).exists():
        # OMMetadataStore would happily create a fresh empty db at a
        # typo'd path and "repair" it, reporting success against nothing
        print(f"error: no OM db at {args.db}", file=sys.stderr)
        return 2
    store = OMMetadataStore(Path(args.db))
    try:
        if args.tool == "snapshot-chain":
            if not args.snap_path or not args.name:
                print("error: snapshot-chain requires --path /vol/bucket "
                      "and --name SNAPSHOT", file=sys.stderr)
                return 2
            vol, bkt = _parse_path(args.snap_path)
            k = snapmeta_key(vol, bkt, args.name)
            row = store.get("open_keys", k)
            if row is None:
                print(f"error: no snapshot {args.name} in "
                      f"/{vol}/{bkt}", file=sys.stderr)
                return 1
            if args.apply and args.previous is None:
                print("error: snapshot-chain --apply requires "
                      "--previous (use 'none' to clear the link)",
                      file=sys.stderr)
                return 2
            newprev = (None if args.previous in (None, "", "none")
                       else args.previous)
            if newprev is not None:
                if newprev == row.get("snap_id"):
                    print("error: --previous would make the snapshot "
                          "its own predecessor", file=sys.stderr)
                    return 1
                siblings = {
                    v["snap_id"]
                    for _, v in store.iterate(
                        "open_keys", snapmeta_key(vol, bkt, ""))
                } - {row.get("snap_id")}
                if newprev not in siblings:
                    print(f"error: --previous {newprev} is not a "
                          f"snapshot id in /{vol}/{bkt} "
                          f"(have: {sorted(siblings)})", file=sys.stderr)
                    return 1
            out = {"snapshot": args.name, "snap_id": row.get("snap_id"),
                   "previous": row.get("previous"),
                   "new_previous": newprev, "applied": False}
            if args.apply:
                row["previous"] = newprev
                store.put("open_keys", k, row)
                store.flush()
                out["applied"] = True
            _emit(out)
        else:  # transaction
            cur = store.get("system", "raft_applied")
            out = {"raft_applied": cur,
                   "new_index": args.index, "applied": False}
            if args.apply:
                if args.index is None:
                    print("error: transaction --apply requires --index",
                          file=sys.stderr)
                    return 2
                store.put("system", "raft_applied",
                          {"index": int(args.index)})
                store.flush()
                out["applied"] = True
            _emit(out)
        return 0
    finally:
        store.close()


def cmd_repair(args) -> int:
    """Repair tools (ozone repair analog). `orphans`: blocks present on
    datanodes but referenced by no key — left behind by failed writes or
    interrupted deletes; reports them, --delete reclaims.

    Deletion safety: blocks are enumerated BEFORE the namespace scan (a
    key committed mid-scan is still seen as referenced), OPEN containers
    are report-only (in-flight writes target OPEN containers exclusively,
    so closed containers cannot gain new blocks), and the namespace is
    re-checked immediately before each delete."""
    from ozone_tpu.net.scm_service import GrpcScmClient
    from ozone_tpu.storage.ids import BlockID

    if args.tool in ("snapshot-chain", "transaction"):
        return _repair_offline(args)
    oz = _client(args)
    if args.tool == "quota":
        if not args.volume:
            print("error: repair quota requires --volume", file=sys.stderr)
            return 1
        _emit(oz.om.repair_quota(args.volume))
        return 0
    scm = GrpcScmClient(args.om, tls=_client_tls())
    if args.tool != "orphans":
        print(f"unknown repair tool {args.tool}", file=sys.stderr)
        return 1
    # 1. candidates first: (pair, dn, container_state)
    candidates: list[tuple[tuple[int, int], str, str]] = []
    for c in scm.list_containers():
        if c["state"] == "DELETED":
            continue
        for rep in c["replicas"]:
            client = oz.clients.maybe_get(rep["dn_id"])
            if client is None:
                continue
            try:
                blocks = client.list_blocks(int(c["id"]))
            except Exception:
                continue
            for blk in blocks:
                candidates.append((
                    (blk.block_id.container_id, blk.block_id.local_id),
                    rep["dn_id"], c["state"],
                ))
    # 2. namespace after the block listing
    referenced = _scan_referenced_blocks(oz)
    orphans = [c for c in candidates if c[0] not in referenced]
    # 3. optional reclaim, with a final re-check right before deleting
    if args.delete and orphans:
        recheck = _scan_referenced_blocks(oz)
    report = []
    for pair, dn_id, state in orphans:
        entry = {
            "container_id": pair[0],
            "local_id": pair[1],
            "datanode": dn_id,
            "container_state": state,
            "action": "none",
        }
        if args.delete:
            if state == "OPEN":
                # an in-flight write may still commit this block
                entry["action"] = "skipped-open-container"
            elif pair in recheck:
                entry["action"] = "skipped-now-referenced"
            else:
                oz.clients.get(dn_id).delete_block(BlockID(*pair))
                entry["action"] = "deleted"
        report.append(entry)
    _emit({"orphans": report, "count": len(report)})
    return 0


def cmd_lifecycle(args) -> int:
    """Bucket lifecycle admin (`lifecycle set/get/clear/run-now/status`):
    age-based hot->warm tiering rules (replicated -> EC on device) and
    TTL expiry, enforced by the leader-singleton sweeper. A deliberate
    extension beyond Apache Ozone 1.5 (docs/PARITY.md)."""
    from ozone_tpu.net.om_service import GrpcOmClient

    def usage(msg: str) -> int:
        print(f"error: {msg}", file=sys.stderr)
        return 2

    om = GrpcOmClient(args.om, tls=_client_tls())
    verb = args.verb
    if verb in ("run-now", "status", "compact-slabs"):
        if verb == "run-now":
            _emit(om.run_lifecycle_once(args.max_keys))
        elif verb == "compact-slabs":
            _emit(om.run_slab_compaction_once())
        else:
            _emit(om.lifecycle_status())
        return 0
    if not args.path:
        return usage(f"lifecycle {verb} needs a /volume/bucket path")
    parts = _parse_path(args.path)
    if len(parts) != 2:
        return usage(f"expected /volume/bucket, got {args.path!r}")
    vol, bucket = parts
    if verb == "get":
        _emit(om.get_bucket_lifecycle(vol, bucket))
    elif verb == "clear":
        om.delete_bucket_lifecycle(vol, bucket)
        print(f"lifecycle cleared on /{vol}/{bucket}")
    elif verb == "set":
        action = {"transition": "TRANSITION_TO_EC",
                  "expire": "EXPIRE"}.get(args.action)
        if action is None:
            return usage(f"unknown action {args.action!r} "
                         "(expected transition|expire)")
        rules = (om.get_bucket_lifecycle(vol, bucket)
                 if args.append else [])
        rule = {
            "id": args.id or f"rule-{len(rules)}",
            "prefix": args.prefix,
            "age_days": args.age_days,
            "action": action,
            "enabled": True,
        }
        if action == "TRANSITION_TO_EC":
            rule["target"] = args.target
        rules = [*rules, rule]
        _emit(om.set_bucket_lifecycle(vol, bucket,
                                      rules).get("lifecycle", []))
    else:
        return usage(f"unknown lifecycle verb {verb!r}")
    return 0


def cmd_replication(args) -> int:
    """Geo replication admin (`replication set/get/clear/run-now/
    status`): per-bucket cross-cluster async replication rules,
    enforced by the leader-singleton WAL-tailing shipper. A deliberate
    extension beyond Apache Ozone 1.5 (docs/PARITY.md row 47)."""
    from ozone_tpu.net.om_service import GrpcOmClient

    def usage(msg: str) -> int:
        print(f"error: {msg}", file=sys.stderr)
        return 2

    om = GrpcOmClient(args.om, tls=_client_tls())
    verb = args.verb
    if verb in ("run-now", "status"):
        if verb == "run-now":
            _emit(om.run_geo_once(args.max_entries))
        else:
            _emit(om.geo_status())
        return 0
    if not args.path:
        return usage(f"replication {verb} needs a /volume/bucket path")
    parts = _parse_path(args.path)
    if len(parts) != 2:
        return usage(f"expected /volume/bucket, got {args.path!r}")
    vol, bucket = parts
    if verb == "get":
        _emit(om.get_bucket_geo_replication(vol, bucket))
    elif verb == "clear":
        om.delete_bucket_geo_replication(vol, bucket)
        print(f"replication cleared on /{vol}/{bucket}")
    elif verb == "set":
        if not args.dest:
            return usage("replication set needs --dest HOST:PORT "
                         "(the destination cluster endpoint)")
        rules = (om.get_bucket_geo_replication(vol, bucket)
                 if args.append else [])
        rule = {
            "id": args.id or f"rule-{len(rules)}",
            "endpoint": args.dest,
            "prefix": args.prefix,
            "bucket": args.dest_bucket,
            "volume": args.dest_volume,
            "scheme": args.scheme,
            "enabled": True,
        }
        rules = [*rules, rule]
        _emit(om.set_bucket_geo_replication(
            vol, bucket, rules).get("geo_replication", []))
    else:
        return usage(f"unknown replication verb {verb!r}")
    return 0


def cmd_version(args) -> int:
    """`ozone version` analog: framework + runtime stack versions.
    Must ALWAYS succeed — device discovery initializes the JAX backend,
    which can fail when another process owns the accelerator."""
    import jax
    import numpy

    import ozone_tpu

    try:
        devices = [str(d) for d in jax.devices()]
    except RuntimeError as e:
        devices = [f"unavailable: {e}"]
    _emit({
        "ozone_tpu": ozone_tpu.__version__,
        "jax": jax.__version__,
        "numpy": numpy.__version__,
        "python": sys.version.split()[0],
        "devices": devices,
    })
    return 0


def cmd_getconf(args) -> int:
    """`ozone getconf` analog: the generated defaults document for
    every typed config group (the @Config annotation surface)."""
    from ozone_tpu.utils.config import ALL_GROUPS, generate_defaults

    print(generate_defaults(list(ALL_GROUPS)))
    return 0


def cmd_trace(args) -> int:
    """`ozone-tpu trace slow|show`: the slow-request flight recorder —
    list traces retained past their per-op SLO, or print one trace's
    critical path (ordered stage -> micros latency attribution) from
    the cluster trace collector."""
    from ozone_tpu.net import wire
    from ozone_tpu.net.rpc import RpcChannel
    from ozone_tpu.utils.tracing import TRACING_SERVICE

    ch = RpcChannel(args.om.split(",")[0].strip(), tls=_client_tls())
    try:
        if args.verb == "slow":
            m, _ = wire.unpack(ch.call(
                TRACING_SERVICE, "Slow",
                wire.pack({"limit": args.limit})))
            _emit(m.get("traces", []))
            return 0
        if not args.trace_id:
            print("error: trace show requires a trace id",
                  file=sys.stderr)
            return 2
        m, _ = wire.unpack(ch.call(
            TRACING_SERVICE, "Slow",
            wire.pack({"trace_id": args.trace_id})))
        entry = m.get("trace")
        if not entry:
            print(f"error: trace {args.trace_id!r} not retained "
                  "(only over-SLO traces are pinned)", file=sys.stderr)
            return 1
        print(f"trace {entry['traceId']}  root={entry['root']}  "
              f"{entry['durationMs']}ms (slo {entry['sloMs']}ms)  "
              f"{len(entry['spans'])} spans")
        print("critical path:")
        total = sum(s["micros"] for s in entry["criticalPath"]) or 1
        for st in entry["criticalPath"]:
            share = 100.0 * st["micros"] / total
            print(f"  {st['stage']:<28} {st['micros']:>12} us  "
                  f"{share:5.1f}%")
        return 0
    finally:
        ch.close()


# -------------------------------------------------------------------- main
def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="ozone-tpu")
    sub = ap.add_subparsers(dest="command", required=True)

    ver = sub.add_parser("version", help="framework + stack versions")
    ver.set_defaults(fn=cmd_version)
    gc = sub.add_parser("getconf",
                        help="generated config defaults (ozone getconf)")
    gc.set_defaults(fn=cmd_getconf)

    sh = sub.add_parser("sh", help="object store shell (ozone sh analog)")
    sh.add_argument("object",
                    choices=["volume", "bucket", "key", "snapshot",
                             "token"])
    sh.add_argument("verb",
                    choices=["create", "delete", "info", "list", "put",
                             "get", "rename", "checksum", "setquota",
                             "diff", "link", "renew", "cancel", "print",
                             "cat", "cp", "rewrite",
                             "set-replication", "set-smallobj",
                             "update"])
    sh.add_argument("path", nargs="?", default="",
                    help="/volume[/bucket[/key]] (token verbs take none)")
    sh.add_argument("file", nargs="?", help="local file for key put/get")
    sh.add_argument("--om", default="127.0.0.1:9860")
    sh.add_argument("--replication", default="")
    sh.add_argument("--to", default="", help="rename target")
    sh.add_argument("--prefix", default="",
                    help="key list: name prefix filter")
    sh.add_argument("--offset", type=int, default=0,
                    help="key get: positioned read start byte")
    sh.add_argument("--length", type=int, default=None,
                    help="key get: positioned read byte count")
    sh.add_argument("--start-after", default="",
                    help="key list: resume after this key (paging)")
    sh.add_argument("--limit", type=int, default=None,
                    help="key list: page size")
    sh.add_argument("--name", default="",
                    help="snapshot verbs: snapshot name (diff: the "
                         "from-snapshot)")
    sh.add_argument("--user", default="",
                    help="volume update: new owner principal")
    sh.add_argument("--page-size", type=int, default=0,
                    help="snapshot diff: run as a paged job, streaming "
                         "entries as JSON lines (0 = one-shot report)")
    sh.add_argument("--renewer", default="",
                    help="token get: renewer principal")
    sh.add_argument("--token", default="",
                    help="token verbs: token file path")
    sh.add_argument("--quota", default="",
                    help="setquota: space quota (e.g. 10MB; 'clear' "
                         "for unlimited)")
    sh.add_argument("--namespace-quota", type=int, default=None,
                    help="setquota: max key count (-1 clears to "
                         "unlimited; omitted leaves unchanged)")
    sh.add_argument("--encryption-key", default="",
                    help="TDE: bucket master-key name (admin kms "
                         "create-key first)")
    sh.add_argument("--gdpr", action="store_true",
                    help="GDPR right-to-erasure bucket (per-key secret "
                         "destroyed on delete)")
    sh.add_argument("--layout", default="OBJECT_STORE",
                    choices=["OBJECT_STORE", "FILE_SYSTEM_OPTIMIZED",
                             "LEGACY"],
                    help="bucket layout (reference: ozone sh bucket create "
                         "--layout)")
    sh.set_defaults(fn=cmd_sh)

    fs = sub.add_parser("fs", help="file-system verbs on FSO buckets "
                                   "(ozone fs analog)")
    fs.add_argument("verb", choices=["mkdir", "ls", "stat", "rm",
                                     "recover-lease"])
    fs.add_argument("path", help="/volume/bucket[/dir/path]")
    fs.add_argument("-r", "--recursive", action="store_true")
    fs.add_argument("--om", default="127.0.0.1:9860")
    fs.set_defaults(fn=cmd_fs)

    acl = sub.add_parser("acl", help="native ACL grants (ozone sh "
                                     "addacl/removeacl/setacl/getacl analog)")
    acl.add_argument("object",
                     choices=["volume", "bucket", "key", "prefix"])
    acl.add_argument("verb", choices=["add", "remove", "set", "get"])
    acl.add_argument("path", help="/volume[/bucket[/key-or-prefix]]")
    acl.add_argument("-a", "--acl", action="append", default=[],
                     help="grant like user:alice:rwl[DEFAULT] (repeatable)")
    acl.add_argument("--om", default="127.0.0.1:9860")
    acl.set_defaults(fn=cmd_acl)

    tn = sub.add_parser("tenant", help="multi-tenant admin (ozone tenant "
                                       "analog)")
    tn.add_argument("verb", choices=["create", "delete", "list", "assign",
                                     "revoke", "users"])
    tn.add_argument("tenant", nargs="?", default="")
    tn.add_argument("--user", default="")
    tn.add_argument("--access-id", default="")
    tn.add_argument("--om", default="127.0.0.1:9860")
    tn.set_defaults(fn=cmd_tenant)

    ad = sub.add_parser("admin", help="cluster admin (ozone admin analog)")
    ad.add_argument("subject", choices=[
        "safemode", "datanode", "status", "pipeline", "container",
        "balancer", "replicationmanager", "om", "finalizeupgrade",
        "upgrade", "ring", "kms", "cert", "reconfig", "namespace",
        "shards",
    ])
    ad.add_argument("verb", nargs="?", default=None,
                    help="safemode: enter|exit; datanode: decommission|"
                         "recommission|maintenance <id>; balancer: "
                         "start|stop|status; container: "
                         "list|info <id>|report|close <id>")
    ad.add_argument("target", nargs="?", default=None,
                    help="datanode id for decommission/recommission/"
                         "maintenance")
    ad.add_argument("--om", default="127.0.0.1:9860")
    ad.add_argument("--threshold", type=float, default=None,
                    help="balancer start: utilization band around the "
                         "cluster average (e.g. 0.1)")
    ad.add_argument("--http", default="",
                    help="reconfig: daemon HTTP/metrics host:port")
    ad.add_argument("--value", default=None,
                    help="reconfig set: new value for the KEY target")
    ad.add_argument("--prefix", default="",
                    help="om list-open-files: key-name prefix filter")
    ad.add_argument("--start-after", default="",
                    help="om list-open-files: resume after this row "
                         "(previous page's continuation)")
    ad.add_argument("--limit", type=int, default=None,
                    help="om list-open-files: page size")
    ad.add_argument("--max-moves", type=int, default=None,
                    help="balancer start: moves per iteration")
    ad.add_argument("--max-size", type=int, default=None,
                    help="balancer start: bytes moved per iteration")
    ad.set_defaults(fn=cmd_admin)

    lc = sub.add_parser("lifecycle",
                        help="bucket lifecycle: age-based tiering "
                             "(replicated->EC) + TTL expiry")
    lc.add_argument("verb", choices=["set", "get", "clear", "run-now",
                                     "status", "compact-slabs"])
    lc.add_argument("path", nargs="?", default="",
                    help="/volume/bucket (set/get/clear)")
    lc.add_argument("--om", default="127.0.0.1:9860")
    lc.add_argument("--prefix", default="",
                    help="set: key-name prefix filter")
    lc.add_argument("--age-days", type=float, default=0.0,
                    help="set: minimum age before the action applies")
    lc.add_argument("--action", default="transition",
                    help="set: transition (replicated->EC) or expire")
    lc.add_argument("--target", default="rs-6-3-1024k",
                    help="set: EC scheme for transition rules")
    lc.add_argument("--id", default="",
                    help="set: rule id (default rule-<n>)")
    lc.add_argument("--append", action="store_true",
                    help="set: append to existing rules instead of "
                         "replacing them")
    lc.add_argument("--max-keys", type=int, default=None,
                    help="run-now: bound the sweep's scan")
    lc.set_defaults(fn=cmd_lifecycle)

    geo = sub.add_parser("replication",
                         help="cross-cluster async bucket replication "
                              "(geo-DR)")
    geo.add_argument("verb", choices=["set", "get", "clear", "run-now",
                                      "status"])
    geo.add_argument("path", nargs="?", default="",
                     help="/volume/bucket (set/get/clear)")
    geo.add_argument("--om", default="127.0.0.1:9860")
    geo.add_argument("--dest", default="",
                     help="set: destination cluster OM endpoint "
                          "HOST:PORT (comma-separated for HA)")
    geo.add_argument("--prefix", default="",
                     help="set: key-name prefix filter")
    geo.add_argument("--dest-bucket", default="",
                     help="set: destination bucket (default: same "
                          "name as the source bucket)")
    geo.add_argument("--dest-volume", default="",
                     help="set: destination volume (default: same "
                          "name as the source volume)")
    geo.add_argument("--scheme", default="",
                     help="set: destination replication scheme "
                          "(default: keep the source key's scheme; "
                          "an EC scheme re-encodes on device)")
    geo.add_argument("--id", default="",
                     help="set: rule id (default rule-<n>)")
    geo.add_argument("--append", action="store_true",
                     help="set: append to existing rules instead of "
                          "replacing them")
    geo.add_argument("--max-entries", type=int, default=None,
                     help="run-now: bound the WAL-delta scan")
    geo.set_defaults(fn=cmd_replication)

    fr = sub.add_parser("freon", help="load generators")
    fr.add_argument("generator",
                    choices=["ockg", "ockr", "ockrr", "ockv", "ecrd",
                             "rawcoder", "omkg",
                             "ommg", "scmtb", "cmdw", "dbgen", "dcg",
                             "dcb", "dcv", "dsg", "hsg", "dnbp", "ralg",
                             "fskg", "mpug", "s3kg", "fsg", "sdg",
                             "dnsim", "lcg", "geo", "swarm", "tinyg"])
    fr.add_argument("-n", "--num", type=int, default=100)
    fr.add_argument("-s", "--size", type=int, default=10240)
    fr.add_argument("--keys", type=int, default=1,
                    help="ockrr: size of the key pool to range-read over")
    fr.add_argument("--warmup", type=int, default=0,
                    help="unmeasured warm-up keys before the clock "
                    "(absorbs the first-dispatch XLA compile)")
    fr.add_argument("-t", "--threads", type=int, default=4)
    fr.add_argument("--om", default="127.0.0.1:9860")
    fr.add_argument("--replication", default="")
    fr.add_argument("--validate", action="store_true")
    fr.add_argument("--endpoint", default="127.0.0.1:9878",
                    help="s3kg: S3 gateway host:port")
    fr.add_argument("--schema", default="rs-6-3")
    fr.add_argument("--cell", type=int, default=1024 * 1024)
    fr.add_argument("--batch", type=int, default=8)
    fr.add_argument("--mix", default="crudl",
                    help="ommg op mix (c/r/u/d/l per char)")
    fr.add_argument("--target", default="rs-3-2-4096",
                    help="lcg: EC scheme the lifecycle rule tiers to")
    fr.add_argument("--no-packer", action="store_true",
                    help="tinyg: force the classic per-key stripe path "
                         "(the small-object before/after baseline)")
    fr.add_argument("--tiny", action="store_true",
                    help="tinyg/swarm: draw sizes from the tiny-key "
                         "mix instead of a fixed --size")
    fr.add_argument("--dest", default="",
                    help="geo: destination cluster OM endpoint")
    fr.add_argument("--scheme", default="",
                    help="geo: destination replication scheme "
                         "(default: keep the source scheme)")
    fr.add_argument("--root", default="",
                    help="local path for cmdw/dbgen")
    fr.add_argument("--containers", type=int, default=5,
                    help="dnsim: fabricated containers per simulated "
                         "datanode")
    fr.add_argument("--duration", type=float, default=5.0,
                    help="dnsim: seconds to heartbeat; "
                         "swarm: seconds to drive load")
    fr.add_argument("--interval", type=float, default=0.5,
                    help="dnsim: per-datanode heartbeat interval")
    fr.set_defaults(fn=cmd_freon)

    dn = sub.add_parser("datanode", help="run a datanode daemon")
    dn.add_argument("--root", required=True)
    dn.add_argument("--scm", required=True)
    dn.add_argument("--id", default="")
    dn.add_argument("--port", type=int, default=0)
    dn.add_argument("--rack", default="/default-rack")
    dn.add_argument("--volumes", type=int, default=1,
                    help="storage volumes under --root (hdds.datanode"
                         ".dir analog)")
    dn.add_argument("--volume-policy", default="round-robin",
                    choices=["round-robin", "capacity"],
                    help="volume chooser for new containers")
    dn.add_argument("--scan-interval", type=float, default=300.0,
                    help="seconds between background container scrubs "
                         "(0 disables)")
    dn.add_argument("--replication-bandwidth-mbps", type=float,
                    default=None,
                    help="cap container-replication traffic this node "
                         "pulls/serves (MiB/s; ReplicationSupervisor "
                         "limit analog; default unlimited)")
    dn.add_argument("--ca", default="",
                    help="SCM cert-enrollment address (host:port) — "
                         "enroll and serve/dial everything over mTLS")
    dn.add_argument("--enrollment-secret", default="",
                    help="shared bootstrap secret for CSR signing")
    dn.set_defaults(fn=cmd_datanode)

    s3g = sub.add_parser("s3g", help="run the S3 gateway daemon")
    s3g.add_argument("--om", default="127.0.0.1:9860")
    s3g.add_argument("--port", type=int, default=9878)
    s3g.add_argument("--replication", default="rs-6-3-1024k")
    s3g.add_argument("--domain", default="",
                     help="serve virtual-host-style addressing for "
                          "Host: <bucket>.<domain>")
    s3g.add_argument("--require-auth", action="store_true",
                     help="enforce SigV4 signatures")
    s3g.set_defaults(fn=cmd_s3g)

    hf = sub.add_parser("httpfs", help="run the WebHDFS-compatible gateway")
    hf.add_argument("--om", default="127.0.0.1:9860")
    hf.add_argument("--port", type=int, default=14000)
    hf.add_argument("--replication", default=None,
                    help="replication for implicitly created buckets")
    hf.add_argument("--trash-interval", type=float, default=0.0,
                    help="fs.trash.interval seconds: rotate + purge "
                         "trash checkpoints on this cadence (0 = off)")
    hf.set_defaults(fn=cmd_httpfs)

    csi = sub.add_parser("csi", help="run the CSI driver daemon")
    csi.add_argument("--om", default="127.0.0.1:9860")
    csi.add_argument("--port", type=int, default=9899)
    csi.add_argument("--s3-endpoint", default="")
    csi.add_argument("--replication", default=None)
    csi.set_defaults(fn=cmd_csi)

    s3 = sub.add_parser("s3", help="s3 secret management")
    s3.add_argument("verb", choices=["getsecret", "revokesecret"])
    s3.add_argument("access_id")
    s3.add_argument("--om", default="127.0.0.1:9860")
    s3.set_defaults(fn=cmd_s3)

    cl = sub.add_parser("cluster",
                        help="one-command local demo cluster "
                             "(compose analog): scm-om + N datanodes")
    cl.add_argument("--datanodes", type=int, default=5)
    cl.add_argument("--port", type=int, default=9860)
    cl.add_argument("--root", default="",
                    help="data directory (default: a fresh tmp dir)")
    cl.add_argument("--http-port", type=int, default=None)
    cl.add_argument("--recon-port", type=int, default=None)
    cl.set_defaults(fn=cmd_cluster)

    so = sub.add_parser("scm-om", help="run the SCM+OM metadata server")
    so.add_argument("--db", required=True)
    so.add_argument("--port", type=int, default=9860)
    so.add_argument("--min-datanodes", type=int, default=1)
    so.add_argument("--http-port", type=int, default=None,
                    help="serve /prom /prof /stacks /reconfig on this port")
    so.add_argument("--recon-port", type=int, default=None,
                    help="serve the Recon API + web UI on this port")
    so.add_argument("--ha-id", default=None,
                    help="this node's id in the metadata HA ring")
    so.add_argument("--peer", action="append", default=[],
                    help="HA ring member as id=host:port (repeat; must "
                         "include --ha-id itself)")
    so.add_argument("--block-tokens", action="store_true",
                    help="enforce HMAC block/container tokens on the "
                         "datanode datapath (hdds.block.token.enabled)")
    so.add_argument("--secure", action="store_true",
                    help="host the cluster CA and serve the main plane "
                         "over mutual TLS (grpc.tls.enabled)")
    so.add_argument("--enroll-port", type=int, default=0,
                    help="plaintext cert-enrollment port (secure mode)")
    so.add_argument("--enrollment-secret", default="",
                    help="shared bootstrap secret gating CSR signing")
    so.add_argument("--ca", default="",
                    help="primordial metadata server's enrollment "
                         "address (secure HA replicas enroll there "
                         "instead of hosting their own CA)")
    so.set_defaults(fn=cmd_scm_om)

    ins = sub.add_parser("insight",
                         help="subsystem introspection (ozone insight)")
    ins.add_argument("verb", choices=["list", "metrics", "logs",
                                      "log-level", "partition", "heal",
                                      "partitions"])
    ins.add_argument("--om", default="127.0.0.1:9860")
    ins.add_argument("--address", default="",
                     help="daemon address (defaults to --om)")
    ins.add_argument("--logger", default="")
    ins.add_argument("--level", default="")
    ins.add_argument("--dst", default="",
                     help="partition/heal: peer address to cut/restore")
    ins.add_argument("--owner", default="",
                     help="partition scope tag (default: whole process)")
    ins.add_argument("-n", "--num", type=int, default=100)
    ins.set_defaults(fn=cmd_insight)

    au = sub.add_parser("audit",
                        help="audit log parser (ozone auditparser analog)")
    au.add_argument("verb", choices=["parse", "top", "failures"])
    au.add_argument("logfile", help="audit log file (JSON lines)")
    au.add_argument("--user", default="")
    au.add_argument("--action", default="")
    au.add_argument("--result", default="")
    au.add_argument("--by", default="action",
                    choices=["action", "user", "result"])
    au.add_argument("-n", "--num", type=int, default=50)
    au.set_defaults(fn=_cmd_audit)

    rp = sub.add_parser("repair", help="repair tools (ozone repair analog)")
    rp.add_argument("tool", choices=["orphans", "quota", "snapshot-chain",
                                     "transaction"])
    rp.add_argument("--om", default="127.0.0.1:9860")
    rp.add_argument("--volume", default="",
                    help="quota: volume whose usage counters to rebuild")
    rp.add_argument("--delete", action="store_true",
                    help="reclaim orphaned blocks")
    rp.add_argument("--db", default="",
                    help="snapshot-chain/transaction: OM db path "
                         "(offline; stop the OM first)")
    rp.add_argument("--path", dest="snap_path", default="",
                    help="snapshot-chain: /volume/bucket")
    rp.add_argument("--name", default="",
                    help="snapshot-chain: snapshot name")
    rp.add_argument("--previous", default=None,
                    help="snapshot-chain: new previous snap_id "
                         "('none' clears the link); required with "
                         "--apply")
    rp.add_argument("--index", type=int, default=None,
                    help="transaction: new raft applied index")
    rp.add_argument("--apply", action="store_true",
                    help="snapshot-chain/transaction: write the change "
                         "(default dry-run)")
    rp.set_defaults(fn=cmd_repair)

    dbg = sub.add_parser("debug", help="debug tools (ozone debug analog)")
    dbg.add_argument("tool", choices=["ldb", "chunk-info", "verify-replicas",
                                      "export-container",
                                      "import-container", "trace",
                                      "container-list",
                                      "container-inspect"])
    dbg.add_argument("--root", default="",
                     help="container-list/inspect: local datanode root "
                          "directory (offline)")
    dbg.add_argument("target", nargs="?", default="",
                     help="db path (ldb), /vol/bucket/key, a container "
                          "id (export/import), or a trace id (trace; "
                          "empty = list recent)")
    dbg.add_argument("--table", default="keys")
    dbg.add_argument("--prefix", default="")
    dbg.add_argument("--om", default="127.0.0.1:9860")
    dbg.add_argument("--dn", default="",
                     help="export/import-container: datanode id")
    dbg.add_argument("--file", default="",
                     help="export/import-container: local tarball path")
    dbg.set_defaults(fn=cmd_debug)

    tr = sub.add_parser("trace", help="slow-request flight recorder: "
                                      "retained over-SLO traces and "
                                      "their critical paths")
    tr.add_argument("verb", choices=["slow", "show"],
                    help="slow = list retained slow traces; "
                         "show <id> = one trace's critical path")
    tr.add_argument("trace_id", nargs="?", default="")
    tr.add_argument("--om", default="127.0.0.1:9860")
    tr.add_argument("--limit", type=int, default=20,
                    help="slow: max traces to list")
    tr.set_defaults(fn=cmd_trace)

    fsck = sub.add_parser("fsck", help="namespace health walk "
                                       "(ozone fsck analog)")
    fsck.add_argument("--om", default="127.0.0.1:9860")
    fsck.add_argument("--volume", default="")
    fsck.add_argument("--bucket", default="")
    fsck.set_defaults(fn=cmd_fsck)

    return ap


# --------------------------------------------------------------------- fsck
def cmd_fsck(args) -> int:
    """Namespace-wide health walk (ozone fsck analog): for every key in
    scope, check each block group's unit metadata on its datanodes and
    classify HEALTHY (all units present) / DEGRADED (readable but
    missing units — EC with >= k survivors, replication with >= 1) /
    UNRECOVERABLE (too few units to reconstruct)."""
    from ozone_tpu.scm.pipeline import ReplicationType

    oz = _client(args)
    if not oz.clients.known_ids():
        print(f"error: no datanode addresses learned from {args.om} — "
              "cannot distinguish missing units from an unreachable "
              "SCM; aborting", file=sys.stderr)
        return 2
    vols = ([args.volume] if args.volume
            else [v["name"] for v in oz.om.list_volumes()])
    summary = {"HEALTHY": 0, "DEGRADED": 0, "UNRECOVERABLE": 0}
    issues = []
    for vol in vols:
        buckets = ([args.bucket] if args.bucket
                   else [b["name"] for b in oz.om.list_buckets(vol)])
        for bucket in buckets:
            try:
                binfo = oz.om.bucket_info(vol, bucket)
                if binfo.get("source"):
                    continue  # links resolve to their source: walking
                    # both would double-count every key
                keys = oz.om.list_keys(vol, bucket)
            except StorageError as e:
                issues.append({"bucket": f"/{vol}/{bucket}",
                               "state": e.code})
                continue
            for k in keys:
                # listed rows carry the full stored record; no per-key
                # lookup RPC needed
                groups = oz.om.key_block_groups(k)
                worst = "HEALTHY"
                missing: list[dict] = []
                for g in groups:
                    repl = g.pipeline.replication
                    # a short EC key legitimately never wrote its
                    # trailing data units: only units holding bytes are
                    # expected, and recovery needs as many survivors as
                    # there are non-zero data units (absent units are
                    # known-zero cells)
                    if repl.type is ReplicationType.EC:
                        from ozone_tpu.client.ec_writer import (
                            block_lengths,
                        )

                        lens = block_lengths(g.length, repl.ec.data_units,
                                             repl.ec.cell_size)
                        data_expected = [i for i, ln in enumerate(lens)
                                         if ln > 0]
                        expected = data_expected + (
                            list(range(repl.ec.data_units,
                                       len(g.pipeline.nodes)))
                            if g.length else [])
                        need = len(data_expected)
                    else:
                        expected = (list(range(len(g.pipeline.nodes)))
                                    if g.length else [])
                        need = 1 if expected else 0
                    present = 0
                    for i in expected:
                        dn_id = g.pipeline.nodes[i]
                        client = oz.clients.maybe_get(dn_id)
                        ok = False
                        if client is not None:
                            try:
                                client.get_block(g.block_id)
                                ok = True
                            except Exception:
                                ok = False
                        if ok:
                            present += 1
                        else:
                            missing.append({
                                "container_id": g.container_id,
                                "datanode": dn_id,
                                "replica_index": i + 1,
                            })
                    if present >= len(expected):
                        state = "HEALTHY"
                    elif present >= need:
                        state = "DEGRADED"
                    else:
                        state = "UNRECOVERABLE"
                    order = ["HEALTHY", "DEGRADED", "UNRECOVERABLE"]
                    if order.index(state) > order.index(worst):
                        worst = state
                summary[worst] += 1
                if worst != "HEALTHY":
                    issues.append({
                        "key": f"/{vol}/{bucket}/{k['name']}",
                        "state": worst,
                        "missing_units": missing,
                    })
    _emit({"keys": summary, "issues": issues})
    return 1 if summary["UNRECOVERABLE"] else 0


# -------------------------------------------------------------------- debug
def cmd_debug(args) -> int:
    if args.tool == "ldb":
        # OM/volume metadata explorer (ozone debug ldb analog)
        from ozone_tpu.om.metadata import OMMetadataStore

        store = OMMetadataStore(args.target)
        try:
            for k, v in store.iterate(args.table, args.prefix):
                print(json.dumps({"key": k, "value": v}, default=str))
        finally:
            store.close()
        return 0

    if args.tool in ("container-list", "container-inspect"):
        # offline container explorer against a LOCAL datanode root
        # (ozone debug container list/info/inspect analog: runs on the
        # datanode host with the service stopped). STRICTLY read-only:
        # volumes are opened by their DISCOVERED directories (a root
        # with vol0+vol2 loads both; nothing is fabricated) and an
        # inspect scan reports checksum errors without committing the
        # UNHEALTHY state the online scanner would
        from ozone_tpu.storage.container import HddsVolume
        from ozone_tpu.utils.checksum import Checksum, ChecksumError

        if not args.root:
            print("error: debug container verbs need --root DN_ROOT",
                  file=sys.stderr)
            return 2
        vol_dirs = sorted(p for p in Path(args.root).glob("vol*")
                          if p.is_dir())
        if not vol_dirs:
            print(f"error: no vol* directories under {args.root} — "
                  "not a datanode root", file=sys.stderr)
            return 2
        vols = []
        containers = []
        load_errors = []
        for d in vol_dirs:
            try:
                v = HddsVolume(d, readonly=True)
            except Exception as e:  # noqa: BLE001 - forensic tool
                load_errors.append(f"{d}: cannot open volume db: {e}")
                continue
            vols.append(v)
            containers.extend(
                v.load_containers(on_error=load_errors.append))
        try:
            containers.sort(key=lambda c: c.id)
            for err in load_errors:
                print(f"warning: {err}", file=sys.stderr)
            if args.tool == "container-list":
                rows = []
                for c in containers:
                    blocks = c.list_blocks()
                    rows.append({
                        "id": c.id,
                        "state": c.state.value,
                        "replica_index": c.replica_index,
                        "blocks": len(blocks),
                        "used_bytes": sum(b.length for b in blocks),
                        "path": str(c.root),
                    })
                _emit(rows)
            else:  # container-inspect <id>
                try:
                    cid = int(args.target)
                except ValueError:
                    print(f"error: container id must be numeric, got "
                          f"{args.target!r}", file=sys.stderr)
                    return 2
                c = next((c for c in containers if c.id == cid), None)
                if c is None:
                    print(f"error: no container {cid} under "
                          f"{args.root}", file=sys.stderr)
                    return 1
                errors = []
                blocks = c.list_blocks()
                for b in blocks:
                    for ci in b.chunks:
                        try:
                            data = c.chunks.read_chunk(b.block_id, ci)
                            if ci.checksum.checksums:
                                Checksum().verify(data, ci.checksum)
                        except (StorageError, ChecksumError) as e:
                            errors.append(
                                f"{b.block_id}/{ci.name}: {e}")
                _emit({
                    "id": c.id,
                    "state": c.state.value,
                    "replica_index": c.replica_index,
                    "path": str(c.root),
                    "blocks": [
                        {"local_id": b.block_id.local_id,
                         "length": b.length,
                         "chunks": len(b.chunks)}
                        for b in blocks
                    ],
                    "scan_errors": errors,
                })
        finally:
            for v in vols:
                v.close()
        return 0

    if args.tool != "trace" and not args.target:
        # target became optional only for `trace` (empty = recent list)
        print(f"error: debug {args.tool} requires a target",
              file=sys.stderr)
        return 1
    if args.tool == "trace":
        # cluster trace assembly (the Jaeger-query role): list recent
        # traces, or print one trace's span tree across services
        from ozone_tpu.net import wire
        from ozone_tpu.net.rpc import RpcChannel
        from ozone_tpu.utils.tracing import TRACING_SERVICE

        ch = RpcChannel(args.om.split(",")[0].strip(),
                        tls=_client_tls())
        try:
            if not args.target:
                m, _ = wire.unpack(ch.call(TRACING_SERVICE, "Recent",
                                           wire.pack({})))
                _emit(m["traces"])
                return 0
            m, _ = wire.unpack(ch.call(
                TRACING_SERVICE, "Query",
                wire.pack({"trace_id": args.target})))
            spans = m["spans"]
            if not spans:
                print(f"error: no trace {args.target!r}",
                      file=sys.stderr)
                return 1
            # roots = spans whose parent never reached the collector
            # (external clients usually don't export), not just
            # parentId == ""
            ids = {s["spanId"] for s in spans}
            by_parent: dict = {}
            roots = []
            for s in spans:
                pid = s.get("parentId", "")
                if pid and pid in ids:
                    by_parent.setdefault(pid, []).append(s)
                else:
                    roots.append(s)

            def walk(items, depth):
                for s in sorted(items, key=lambda x: x["start"]):
                    svc = s.get("service", "?")
                    print(f"{'  ' * depth}{s['name']}  "
                          f"[{svc}]  {s['durationMs']}ms")
                    walk(by_parent.get(s["spanId"], []), depth + 1)

            walk(roots, 0)
            return 0
        finally:
            ch.close()

    oz = _client(args)
    if args.tool in ("export-container", "import-container"):
        # container replica backup/restore over the replication-download
        # path (ozone debug container export/import analog)
        if not args.dn or not args.file:
            print("error: requires --dn <id> and --file <path>",
                  file=sys.stderr)
            return 1
        client = oz.clients.maybe_get(args.dn)
        if client is None:
            print(f"error: unknown datanode {args.dn!r}", file=sys.stderr)
            return 1
        try:
            cid = int(args.target)
        except ValueError:
            print(f"error: container id must be numeric: {args.target!r}",
                  file=sys.stderr)
            return 1
        if args.tool == "export-container":
            data = client.export_container(cid)
            Path(args.file).write_bytes(data)
            print(f"exported container {args.target} from {args.dn}: "
                  f"{len(data)} bytes -> {args.file}")
        else:
            data = Path(args.file).read_bytes()
            out = client.import_container(data, container_id=cid)
            print(f"imported container {out} on {args.dn}")
        return 0
    vol, bucket, *rest = _parse_path(args.target)
    key = "/".join(rest)
    info = oz.om.lookup_key(vol, bucket, key)
    groups = oz.om.key_block_groups(info)
    if args.tool == "chunk-info":
        out = []
        for g in groups:
            unit_chunks = {}
            for i, dn_id in enumerate(g.pipeline.nodes):
                client = oz.clients.maybe_get(dn_id)
                if client is None:
                    unit_chunks[dn_id] = "unreachable"
                    continue
                try:
                    bd = client.get_block(g.block_id)
                    unit_chunks[dn_id] = {
                        "replica_index": i + 1,
                        "chunks": [c.to_json() for c in bd.chunks],
                    }
                except Exception as e:
                    unit_chunks[dn_id] = f"error: {e}"
            out.append({
                "container_id": g.container_id,
                "local_id": g.local_id,
                "length": g.length,
                "replicas": unit_chunks,
            })
        _emit(out)
    elif args.tool == "verify-replicas":
        # read every unit with checksum verification (replicas verify analog)
        report = []
        for g in groups:
            for i, dn_id in enumerate(g.pipeline.nodes):
                client = oz.clients.maybe_get(dn_id)
                status = "ok"
                if client is None:
                    status = "unreachable"
                else:
                    try:
                        bd = client.get_block(g.block_id)
                        for c in bd.chunks:
                            client.read_chunk(g.block_id, c, verify=True)
                    except Exception as e:
                        status = f"corrupt/unavailable: {e}"
                report.append({
                    "container_id": g.container_id,
                    "datanode": dn_id,
                    "replica_index": i + 1,
                    "status": status,
                })
        _emit(report)
        bad = [r for r in report if r["status"] != "ok"]
        return 1 if bad else 0
    return 0


def _ship_spans(args) -> None:
    """One-shot span export for short-lived CLI invocations: daemons run
    a periodic SpanExporter, but a `sh key put` exits before any 2 s
    batch fires — without this flush the client:put root span (and the
    slow-trace retention it drives) never reaches the collector."""
    from ozone_tpu.utils.tracing import SpanExporter, Tracer

    om = getattr(args, "om", "")
    tracer = Tracer.instance()
    if not om or not tracer.spans:
        return
    exp = SpanExporter(tracer, service="cli",
                       address=om.split(",")[0].strip(),
                       tls=_client_tls())
    # the command's spans finished before the exporter existed, so they
    # never entered its queue — hand them over wholesale
    with tracer._lock:
        exp._q.extend(tracer.spans)
    while exp._q:
        shipped = exp.exported
        exp.flush()
        if exp.exported == shipped:
            break  # collector unreachable: lossy by design
    if exp._ch is not None:
        exp._ch.close()


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.fn(args)
    except StorageError as e:
        # one clean line, not a traceback (ozone sh prints the OMException
        # result code the same way)
        print(f"error {e.code}: {e.msg}", file=sys.stderr)
        return 1
    except BrokenPipeError:
        # downstream pager/head closed the pipe: exit quietly like any
        # well-behaved unix tool
        import os

        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        return 0
    finally:
        try:
            _ship_spans(args)
        except Exception:
            pass  # ozlint: allow[error-swallowing] -- best-effort span export on exit; tracing never fails a CLI verb


if __name__ == "__main__":
    sys.exit(main())
