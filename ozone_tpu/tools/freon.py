"""Freon: load generators and benchmarks.

Mirror of the reference's freon suite (hadoop-ozone/tools freon/
Freon.java:40-79 subcommand registry): BaseFreonGenerator-style harness
(thread pool task loop, progress, latency report — BaseFreonGenerator
.java:77,152,182,321) and the key generators:

- ockg: OzoneClientKeyGenerator.java:42 — write n keys of a given size
  through the full client stack, per-op timer, replication selectable.
- ocokr: key read/validate generator (OzoneClientKeyReadWriteOps analog).
- dcg: DatanodeChunkGenerator — raw WriteChunk straight to datanodes,
  bypassing OM/SCM (datapath-only throughput).
- rawcoder: RawErasureCoderBenchmark.java:42-49 — coder encode/decode
  MB/s per backend (numpy / cpp / jax-TPU), batch x cell matrix.
"""

from __future__ import annotations

import json
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Callable, Optional

import numpy as np

from ozone_tpu.utils.metrics import Timer


@dataclass
class FreonReport:
    name: str
    ops: int
    failures: int
    elapsed_s: float
    latencies_s: list[float] = field(default_factory=list)
    bytes_processed: int = 0
    #: generator-specific extra fields merged into summary()
    extras: dict = field(default_factory=dict)

    def summary(self) -> dict:
        lat = sorted(self.latencies_s)
        pct = lambda q: lat[min(len(lat) - 1, int(q * len(lat)))] if lat else 0.0
        return {
            **self.extras,
            "generator": self.name,
            "ops": self.ops,
            "failures": self.failures,
            "elapsed_s": round(self.elapsed_s, 3),
            "ops_per_s": round(self.ops / self.elapsed_s, 2)
            if self.elapsed_s
            else 0,
            "throughput_mib_s": round(
                self.bytes_processed / 2**20 / self.elapsed_s, 2
            )
            if self.elapsed_s
            else 0,
            "mean_ms": round(1e3 * sum(lat) / len(lat), 3) if lat else 0,
            "p50_ms": round(1e3 * pct(0.5), 3),
            "p75_ms": round(1e3 * pct(0.75), 3),
            "p90_ms": round(1e3 * pct(0.9), 3),
            "p95_ms": round(1e3 * pct(0.95), 3),
            "p99_ms": round(1e3 * pct(0.99), 3),
            "p999_ms": round(1e3 * pct(0.999), 3),
            "max_ms": round(1e3 * (lat[-1] if lat else 0), 3),
            "histogram": self.histogram(),
        }

    def histogram(self) -> list[dict]:
        """Power-of-two latency buckets (the HdrHistogram-style
        distribution the reference prints via printReport). PER-BUCKET
        counts: each entry counts ops whose latency falls in
        (previous_le_ms, le_ms] — not cumulative."""
        if not self.latencies_s:
            return []
        import math

        counts: dict[float, int] = {}
        for dt in self.latencies_s:
            ms = dt * 1e3
            le = 2 ** max(0, math.ceil(math.log2(max(ms, 1e-3))))
            counts[le] = counts.get(le, 0) + 1
        return [{"le_ms": k, "count": counts[k]}
                for k in sorted(counts)]


class BaseFreonGenerator:
    """Thread-pooled op loop with latency capture."""

    def __init__(self, name: str, n_ops: int, threads: int = 4):
        self.name = name
        self.n_ops = n_ops
        self.threads = threads
        self._lat: list[float] = []
        self._failures = 0
        self._bytes = 0
        self._lock = threading.Lock()

    def run(self, op: Callable[[int], int]) -> FreonReport:
        """op(i) -> bytes processed; runs n_ops times across the pool."""
        t0 = time.time()

        def task(i: int) -> None:
            s = time.perf_counter()
            try:
                nbytes = op(i) or 0
                dt = time.perf_counter() - s
                with self._lock:
                    self._lat.append(dt)
                    self._bytes += nbytes
            except Exception:
                with self._lock:
                    self._failures += 1

        with ThreadPoolExecutor(max_workers=self.threads) as pool:
            list(pool.map(task, range(self.n_ops)))
        return FreonReport(
            self.name,
            ops=self.n_ops - self._failures,
            failures=self._failures,
            elapsed_s=time.time() - t0,
            latencies_s=self._lat,
            bytes_processed=self._bytes,
        )


def _client_hist_extras() -> dict:
    """Scrape-side tail latency: p50/p95/p99 (ms) derived from the
    client-ops histograms — the same numbers a Prometheus
    histogram_quantile over `client_ops_{put,get}_seconds_bucket` would
    yield. Reported alongside the raw-list percentiles so workload runs
    record what the monitoring plane will actually see (bucket-quantile
    estimates over every op since process start, warmups included)."""
    from ozone_tpu.client.ozone_client import METRICS as client_ops

    out: dict = {}
    for verb in ("put", "get"):
        h = client_ops.histogram(f"{verb}_seconds")
        if h.count:
            out[f"hist_{verb}_ms"] = {
                p: round(1e3 * v, 3)
                for p, v in h.percentiles().items()}
    return out


def _det_payload(size: int, seed: int = 0) -> np.ndarray:
    """The deterministic ockg payload; ockv re-derives it to validate,
    so both MUST use this one helper (a drifting expression would read
    as cluster-wide corruption)."""
    return np.random.default_rng(seed).integers(0, 256, size,
                                                dtype=np.uint8)


def ockg(
    client,
    n_keys: int = 100,
    size: int = 10 * 1024,
    threads: int = 4,
    volume: str = "freon-vol",
    bucket: str = "freon-bucket",
    replication: Optional[str] = None,
    prefix: str = "key",
    validate: bool = False,
    warmup: int = 0,
) -> FreonReport:
    """Ozone Client Key Generator (freon ockg). `warmup` keys are
    written before the clock starts — on TPU the first fused-encode
    dispatch carries a 20-40 s XLA compile that would otherwise be
    billed to the measured throughput."""
    try:
        client.om.create_volume(volume)
    except Exception:
        pass
    try:
        client.om.create_bucket(volume, bucket,
                                replication or "rs-6-3-1024k")
    except Exception:
        pass
    b = client.get_volume(volume).get_bucket(bucket)
    payload = _det_payload(size)

    def op(i: int) -> int:
        b.write_key(f"{prefix}-{i}", payload, replication)
        if validate:
            got = b.read_key(f"{prefix}-{i}")
            assert np.array_equal(got, payload)
        return size

    for w in range(warmup):
        b.write_key(f"{prefix}-warmup-{w}", payload, replication)
    rep = BaseFreonGenerator("ockg", n_keys, threads).run(op)
    rep.extras.update(_client_hist_extras())
    return rep


def hsg(
    client,
    n_keys: int = 20,
    size: int = 10 * 1024,
    syncs: int = 4,
    threads: int = 4,
    volume: str = "freon-vol",
    bucket: str = "freon-hsync",
    replication: str = "RATIS/THREE",
) -> FreonReport:
    """Hsync generator (freon HsyncGenerator analog): each op opens a key,
    writes `syncs` slices with an hsync after every slice (the HBase
    WAL-style durability pattern), then closes. The timer therefore covers
    the full open -> (write+hsync)*n -> commit round trip."""
    try:
        client.om.create_volume(volume)
    except Exception:
        pass
    try:
        client.om.create_bucket(volume, bucket, replication)
    except Exception:
        pass
    b = client.get_volume(volume).get_bucket(bucket)
    rng = np.random.default_rng(0)
    payload = rng.integers(0, 256, size, dtype=np.uint8)

    def op(i: int) -> int:
        with b.open_key(f"hsync-{i}") as h:
            for _ in range(syncs):
                h.write(payload)
                h.hsync()
        return size * syncs

    return BaseFreonGenerator("hsg", n_keys, threads).run(op)


def lcg(client, n_keys: int = 20, size: int = 10 * 1024,
        threads: int = 4, volume: str = "freon-vol",
        bucket: str = "freon-tier", replication: str = "RATIS/THREE",
        target: str = "rs-3-2-4096", prefix: str = "tier",
        age_days: float = 0.0) -> FreonReport:
    """Lifecycle-churn workload (write -> age -> sweep -> verify): the
    soak/CI probe for the tiering subsystem. Writes `n_keys` replicated
    keys under an age-based TRANSITION_TO_EC rule, triggers a sweep
    (`lifecycle run-now`), then verifies every key reads back
    byte-exact AND erasure-coded. The timer covers the writes; the
    sweep/verify outcome rides the report extras (`transitioned`,
    `verify_failures`)."""
    try:
        client.om.create_volume(volume)
    except Exception:
        pass
    try:
        client.om.create_bucket(volume, bucket, replication)
    except Exception:
        pass
    client.om.set_bucket_lifecycle(volume, bucket, [{
        "id": "freon-tier", "prefix": prefix, "age_days": age_days,
        "action": "TRANSITION_TO_EC", "target": target,
    }])
    b = client.get_volume(volume).get_bucket(bucket)

    def op(i: int) -> int:
        b.write_key(f"{prefix}-{i}", _det_payload(size, seed=i),
                    replication)
        return size

    rep = BaseFreonGenerator("lcg", n_keys, threads).run(op)
    sweep = client.om.run_lifecycle_once()
    verify_failures = 0
    ec_count = 0
    for i in range(n_keys):
        try:
            info = client.om.lookup_key(volume, bucket, f"{prefix}-{i}")
            got = b.read_key_info(info)
            if not np.array_equal(got, _det_payload(size, seed=i)):
                verify_failures += 1
                continue
            if str(info.get("replication", "")).startswith("rs-"):
                ec_count += 1
        except Exception:
            verify_failures += 1
    rep.extras.update({
        "transitioned": sweep.get("transitioned", 0),
        "ec_keys": ec_count,
        "verify_failures": verify_failures,
        "sweep_bytes": sweep.get("bytes", 0),
        "sweep_dispatches": sweep.get("dispatches", 0),
    })
    return rep


#: the tiny-key size mix: 80/15/5 inline / needle / needle-ish — the
#: metadata-bound object population the small-object path exists for
TINY_SIZES = (512, 4 * 1024, 48 * 1024)


def _tiny_size(i: int, size: int, mix: bool) -> int:
    if not mix:
        return size
    r = i % 20
    if r < 16:
        return TINY_SIZES[0]
    if r < 19:
        return TINY_SIZES[1]
    return TINY_SIZES[2]


def tinyg(client, n_keys: int = 200, size: int = 4 * 1024,
          threads: int = 8, volume: str = "freon-vol",
          bucket: str = "freon-tiny",
          replication: str = "rs-3-2-4096", prefix: str = "tiny",
          packer: bool = True, mix: bool = False,
          validate: bool = True) -> FreonReport:
    """Tiny-key generator (freon tinyg): the small-object-path
    workload. Writes `n_keys` tiny keys into a smallobj-enabled EC
    bucket so PUTs route through the inline/needle fast path — inline
    values live in OM metadata, needles coalesce through the client
    SlabPacker into shared EC stripes committed via CommitKeys.

    `packer=False` keeps the same key population but passes an explicit
    per-key replication, forcing every key down the classic
    open/allocate/commit stripe path — the before/after pair the bench
    compares. `mix=True` draws sizes from TINY_SIZES (mostly inline,
    some needles) instead of the fixed `size`; the swarm overload
    workload reuses the same mix via its `tiny` flag.

    Extras report how the population landed (inline/needle/regular key
    counts, distinct slabs) plus byte-exact `verify_failures`."""
    try:
        client.om.create_volume(volume)
    except Exception:
        pass
    try:
        client.om.create_bucket(volume, bucket, replication)
    except Exception:
        pass
    if packer:
        client.om.set_bucket_smallobj(volume, bucket)
    b = client.get_volume(volume).get_bucket(bucket)
    # packer off => explicit replication pins the per-key stripe path
    # (write_key only consults the smallobj config when the caller
    # leaves replication unset)
    per_key_repl = None if packer else replication

    def op(i: int) -> int:
        sz = _tiny_size(i, size, mix)
        b.write_key(f"{prefix}-{i}", _det_payload(sz, seed=i),
                    per_key_repl)
        return sz

    rep = BaseFreonGenerator("tinyg", n_keys, threads).run(op)
    if packer:
        client.packer.flush()
    inline = needle = regular = verify_failures = 0
    slabs: set = set()
    for i in range(n_keys):
        try:
            info = client.om.lookup_key(volume, bucket,
                                        f"{prefix}-{i}")
            if info.get("inline") is not None:
                inline += 1
            elif info.get("needle"):
                needle += 1
                slabs.add(info["needle"]["slab"])
            else:
                regular += 1
            if validate:
                got = b.read_key_info(info)
                want = _det_payload(_tiny_size(i, size, mix), seed=i)
                if not np.array_equal(got, want):
                    verify_failures += 1
        except Exception:
            verify_failures += 1
    rep.extras.update({
        "packer": packer,
        "inline_keys": inline,
        "needle_keys": needle,
        "regular_keys": regular,
        "slabs": len(slabs),
        "verify_failures": verify_failures,
    })
    rep.extras.update(_client_hist_extras())
    return rep


def geo(client, dest_endpoint: str, n_keys: int = 20,
        size: int = 10 * 1024, threads: int = 4,
        volume: str = "freon-vol", bucket: str = "freon-geo",
        replication: str = "RATIS/THREE", scheme: str = "",
        prefix: str = "geo", dest_client=None) -> FreonReport:
    """Geo-replication churn (write -> overwrite -> delete -> ship ->
    verify): the soak/CI probe for the geo-DR subsystem. Writes
    `n_keys` keys under a replication rule pointing at
    `dest_endpoint`, overwrites a third, deletes a fifth, triggers a
    ship cycle (`replication run-now`), then verifies convergence:
    every surviving key reads back byte-exact FROM THE DESTINATION and
    every deleted key is gone there. The timer covers the writes; the
    ship/verify outcome rides the report extras (`shipped`,
    `verify_failures`, `lag_entries`)."""
    try:
        client.om.create_volume(volume)
    except Exception:
        pass
    try:
        client.om.create_bucket(volume, bucket, replication)
    except Exception:
        pass
    client.om.set_bucket_geo_replication(volume, bucket, [{
        "id": "freon-geo", "endpoint": dest_endpoint, "prefix": prefix,
        "scheme": scheme,
    }])
    b = client.get_volume(volume).get_bucket(bucket)

    def op(i: int) -> int:
        b.write_key(f"{prefix}-{i}", _det_payload(size, seed=i),
                    replication)
        return size

    rep = BaseFreonGenerator("geo", n_keys, threads).run(op)
    ship1 = client.om.run_geo_once()  # initial convergence
    # churn AFTER the first ship so overwrites supersede shipped
    # replicas and deletes retire them: every 3rd key overwritten,
    # every 5th (of the rest) deleted
    expect: dict[str, Optional[int]] = {
        f"{prefix}-{i}": i for i in range(n_keys)
    }
    for i in range(0, n_keys, 3):
        b.write_key(f"{prefix}-{i}", _det_payload(size, seed=i + 1000),
                    replication)
        expect[f"{prefix}-{i}"] = i + 1000
    for i in range(1, n_keys, 5):
        b.delete_key(f"{prefix}-{i}")
        expect[f"{prefix}-{i}"] = None
    ship = client.om.run_geo_once()
    ship = {k: ship.get(k, 0) + (ship1.get(k, 0)
                                 if isinstance(ship1.get(k), int)
                                 else 0)
            for k in ("keys_shipped", "deletes_shipped", "conflicts",
                      "bytes")}
    if dest_client is None:
        from ozone_tpu.replication_geo.shipper import resolve_cluster

        dest_client = resolve_cluster(dest_endpoint).oz
    db = dest_client.get_volume(volume).get_bucket(bucket)
    verify_failures = 0
    for name, seed in expect.items():
        try:
            info = dest_client.om.lookup_key(volume, bucket, name)
        except Exception:
            if seed is not None:
                verify_failures += 1  # should exist at the destination
            continue
        if seed is None:
            verify_failures += 1  # deleted at source, still at dest
            continue
        got = db.read_key_info(info)
        if not np.array_equal(got, _det_payload(size, seed=seed)):
            verify_failures += 1
    status = client.om.geo_status()
    rep.extras.update({
        "shipped": ship.get("keys_shipped", 0),
        "deletes_shipped": ship.get("deletes_shipped", 0),
        "conflicts": ship.get("conflicts", 0),
        "ship_bytes": ship.get("bytes", 0),
        "verify_failures": verify_failures,
        "lag_entries": (status.get("lag") or {}).get("entries", 0),
    })
    return rep


def ockr(client, n_keys: int, threads: int = 4, volume: str = "freon-vol",
         bucket: str = "freon-bucket", prefix: str = "key") -> FreonReport:
    """Key read generator (validation pass over ockg output)."""
    b = client.get_volume(volume).get_bucket(bucket)

    def op(i: int) -> int:
        data = b.read_key(f"{prefix}-{i}")
        return int(data.size)

    rep = BaseFreonGenerator("ockr", n_keys, threads).run(op)
    rep.extras.update(_client_hist_extras())
    return rep


def ockrr(client, n_reads: int, threads: int = 4, size: int = 65536,
          volume: str = "freon-vol", bucket: str = "freon-bucket",
          prefix: str = "key", n_keys: int = 0) -> FreonReport:
    """Random ranged-read generator over ockg output: each op reads
    `size` bytes at a random offset of a random key through the
    positioned path (round 4 — only the covering cells move). `n_keys`
    bounds the key pool (0 = probe with key 0's size and assume `n_reads`
    keys are NOT required; the pool is keys 0..max(1, n_keys)-1)."""
    b = client.get_volume(volume).get_bucket(bucket)
    rng = np.random.default_rng(4)
    pool = max(1, n_keys)
    # one metadata probe sizes the keys (ockg writes equal sizes)
    key_size = int(b.lookup_key_info(f"{prefix}-0")["size"])
    span = max(1, key_size - size + 1)
    # pre-drawn schedule: worker threads must not share a Generator
    keys = rng.integers(0, pool, size=n_reads)
    offs = rng.integers(0, span, size=n_reads)

    def op(i: int) -> int:
        off = int(offs[i])
        ln = min(size, key_size - off)
        data = b.read_key_range(f"{prefix}-{int(keys[i])}", off, ln)
        return int(data.size)

    return BaseFreonGenerator("ockrr", n_reads, threads).run(op)


def _ensure_container(clients, dn_ids: list[str], container_id: int) -> None:
    """Idempotently create the bench container on every target datanode."""
    from ozone_tpu.storage.ids import StorageError

    for dn in dn_ids:
        try:
            clients.get(dn).create_container(container_id)
        except StorageError as e:
            if e.code != "CONTAINER_EXISTS":
                raise


def dcg(
    clients,
    dn_ids: list[str],
    n_chunks: int = 100,
    size: int = 1024 * 1024,
    threads: int = 4,
    container_id: int = 10_000_000,
) -> FreonReport:
    """Datanode chunk generator: raw WriteChunk, bypasses OM/SCM
    (DatanodeChunkGenerator analog)."""
    from ozone_tpu.storage.ids import BlockID, ChunkInfo, StorageError
    from ozone_tpu.utils.checksum import Checksum, ChecksumType

    rng = np.random.default_rng(1)
    payload = rng.integers(0, 256, size, dtype=np.uint8)
    cs = Checksum(ChecksumType.CRC32C, 16 * 1024).compute(payload)
    _ensure_container(clients, dn_ids, container_id)

    def op(i: int) -> int:
        dn = dn_ids[i % len(dn_ids)]
        bid = BlockID(container_id, i + 1)
        info = ChunkInfo(f"chunk_{i}", 0, size, cs)
        clients.get(dn).write_chunk(bid, info, payload)
        return size

    return BaseFreonGenerator("dcg", n_chunks, threads).run(op)


def dcb(
    clients,
    dn_ids: list[str],
    n_blocks: int = 20,
    size: int = 1024 * 1024,
    batch: int = 8,
    threads: int = 4,
    container_id: int = 30_000_000,
) -> FreonReport:
    """Batched chunk generator: `batch` client-checksummed chunks + the
    piggybacked putBlock per ONE WriteChunksCommit stream — the raw-path
    isolation of round 4's batched write verb (dcg pays a transport
    round trip per chunk; this pays one per block)."""
    from ozone_tpu.storage.ids import BlockData, BlockID, ChunkInfo
    from ozone_tpu.utils.checksum import Checksum, ChecksumType

    rng = np.random.default_rng(3)
    payload = rng.integers(0, 256, size, dtype=np.uint8)
    cs = Checksum(ChecksumType.CRC32C, 16 * 1024).compute(payload)
    _ensure_container(clients, dn_ids, container_id)

    def op(i: int) -> int:
        dn = dn_ids[i % len(dn_ids)]
        bid = BlockID(container_id, i + 1)
        pairs = [
            (ChunkInfo(f"{bid}_chunk_{j}", j * size, size, cs), payload)
            for j in range(batch)
        ]
        clients.get(dn).write_chunks_commit(
            bid, pairs, commit=BlockData(bid, [c for c, _ in pairs]))
        return size * batch

    return BaseFreonGenerator("dcb", n_blocks, threads).run(op)


def dsg(
    clients,
    dn_ids: list[str],
    n_blocks: int = 20,
    size: int = 8 * 1024 * 1024,
    frame_size: int = 1024 * 1024,
    chunk_size: int = 4 * 1024 * 1024,
    threads: int = 4,
    container_id: int = 20_000_000,
) -> FreonReport:
    """Datanode streaming-write generator (StreamingGenerator analog):
    whole blocks over the client-streaming RPC, one commit ack each."""
    from ozone_tpu.storage.ids import BlockID, StorageError

    rng = np.random.default_rng(2)
    payload = rng.integers(0, 256, size, dtype=np.uint8).tobytes()
    _ensure_container(clients, dn_ids, container_id)

    def op(i: int) -> int:
        dn = dn_ids[i % len(dn_ids)]
        frames = (payload[o:o + frame_size]
                  for o in range(0, len(payload), frame_size))
        bd = clients.get(dn).stream_write_block(
            BlockID(container_id, i + 1), frames, chunk_size=chunk_size)
        assert bd.length == size
        return size

    return BaseFreonGenerator("dsg", n_blocks, threads).run(op)


def _freon_buckets(client, volume: str, bucket: str,
                   buckets: int) -> list[str]:
    """Create the generator's bucket set. buckets > 1 spreads ops over
    `bucket-<j>` names — on a sharded metadata plane the (volume,
    bucket) hash then fans the load across shard rings instead of
    serializing everything on one ring's slot."""
    try:
        client.om.create_volume(volume)
    except Exception:
        pass
    names = ([bucket] if buckets <= 1
             else [f"{bucket}-{j}" for j in range(buckets)])
    for name in names:
        try:
            client.om.create_bucket(volume, name)
        except Exception:
            pass
    return names


def omkg(client, n_keys: int = 1000, threads: int = 8,
         volume: str = "freon-vol", bucket: str = "freon-meta",
         buckets: int = 1) -> FreonReport:
    """Pure OM metadata op generator: open+commit empty keys without any
    datanode IO (OmKeyGenerator analog — measures namespace throughput)."""
    names = _freon_buckets(client, volume, bucket, buckets)

    def op(i: int) -> int:
        b = names[i % len(names)]
        s = client.om.open_key(volume, b, f"meta-{i}")
        client.om.commit_key(s, [], 0)
        return 0

    return BaseFreonGenerator("omkg", n_keys, threads).run(op)


def dcv(clients, dn_ids: list[str], n_chunks: int, size: int = 1024 * 1024,
        threads: int = 4, container_id: int = 10_000_000) -> FreonReport:
    """Datanode chunk validator: read back + checksum-verify chunks written
    by dcg (DatanodeChunkValidator analog)."""
    from ozone_tpu.storage.ids import BlockID, ChunkInfo
    from ozone_tpu.utils.checksum import Checksum, ChecksumType

    rng = np.random.default_rng(1)
    payload = rng.integers(0, 256, size, dtype=np.uint8)
    cs = Checksum(ChecksumType.CRC32C, 16 * 1024).compute(payload)

    def op(i: int) -> int:
        dn = dn_ids[i % len(dn_ids)]
        bid = BlockID(container_id, i + 1)
        info = ChunkInfo(f"chunk_{i}", 0, size, cs)
        data = clients.get(dn).read_chunk(bid, info, verify=True)
        assert data.size == size
        return size

    return BaseFreonGenerator("dcv", n_chunks, threads).run(op)


def cmdw(root, n_chunks: int = 200, size: int = 4 * 1024 * 1024,
         threads: int = 4) -> FreonReport:
    """Chunk-manager disk write: pure local chunk IO, no network, no
    OM/SCM (ChunkManagerDiskWrite analog — isolates the disk path)."""
    from pathlib import Path

    from ozone_tpu.storage.chunk_store import FilePerBlockStore
    from ozone_tpu.storage.ids import BlockID, ChunkInfo
    from ozone_tpu.utils.checksum import Checksum, ChecksumType

    store = FilePerBlockStore(Path(root))
    rng = np.random.default_rng(3)
    payload = rng.integers(0, 256, size, dtype=np.uint8)
    cs = Checksum(ChecksumType.CRC32C, 16 * 1024).compute(payload)

    def op(i: int) -> int:
        bid = BlockID(1 + i // 64, i + 1)
        store.write_chunk(bid, ChunkInfo(f"c{i}", 0, size, cs), payload)
        return size

    return BaseFreonGenerator("cmdw", n_chunks, threads).run(op)


def scmtb(client, n_blocks: int = 1000, threads: int = 8,
          replication: str = "rs-3-2-4096",
          block_size: int = 16 * 1024 * 1024) -> FreonReport:
    """SCM block-allocation throughput (SCMThroughputBenchmark analog):
    hammers allocateBlock without writing any data."""
    from ozone_tpu.scm.pipeline import ReplicationConfig

    cfg = ReplicationConfig.parse(replication)
    if hasattr(client.om, "scm") and not isinstance(client.om.scm, str):
        # in-process OM: call the SCM manager directly
        op_alloc = lambda: client.om.scm.allocate_block(cfg, block_size)
    else:
        # remote OM: the co-located SCM service honors block_size
        from ozone_tpu.net.scm_service import GrpcScmClient

        scm = GrpcScmClient(client.om.address,
                            tls=getattr(client.om, "tls", None))
        op_alloc = lambda: scm.allocate_block(replication, block_size)

    def op(i: int) -> int:
        op_alloc()
        return 0

    return BaseFreonGenerator("scmtb", n_blocks, threads).run(op)


def dnsim(scm, n_datanodes: int = 50, n_containers: int = 5,
          duration_s: float = 5.0, interval_s: float = 0.5,
          threads: int = 8, prefix: str = "simdn",
          fcr_every_rounds: int = 10) -> FreonReport:
    """Simulated-datanode fleet (freon DatanodeSimulator.java:122
    analog): registers n virtual datanodes with the SCM over the real
    register/heartbeat wire protocol, then heartbeats each of them from
    a thread pool for duration_s, carrying a fabricated full container
    report on the first beat and every fcr_every_rounds after (the
    reference's FCR cadence). Nodes register IN_MAINTENANCE so placement
    never selects them — the reference moves its simulated datanodes to
    read-only for the same reason — and fabricated container ids live in
    a high namespace no real allocation reaches, so the replication
    manager (which walks the container table, not the replica map)
    ignores them. Measures SCM heartbeat ingest: hb/s + latency
    percentiles."""
    ids = [f"{prefix}-{i}" for i in range(n_datanodes)]
    for i, dn_id in enumerate(ids):
        scm.register(dn_id, f"sim://{dn_id}", rack=f"/sim-rack-{i % 8}",
                     capacity_bytes=1 << 40, op_state="IN_MAINTENANCE")
    base = 50_000_000

    def report_for(i: int) -> list[dict]:
        return [{
            "container_id": base + i * n_containers + j,
            "state": "CLOSED",
            "replica_index": 0,
            "block_count": 64,
            "used_bytes": 4 << 20,
        } for j in range(n_containers)]

    lock = threading.Lock()
    lat: list[float] = []
    counts = {"hb": 0, "fcr": 0, "failures": 0}
    stop_at = time.time() + duration_s

    def worker(shard: list[int]) -> None:
        rounds = 0
        while time.time() < stop_at:
            round_t0 = time.time()
            for idx in shard:
                rep = (report_for(idx)
                       if rounds % fcr_every_rounds == 0 else None)
                s = time.perf_counter()
                try:
                    scm.heartbeat(ids[idx], container_report=rep,
                                  used_bytes=(4 << 20) * n_containers)
                except Exception:
                    with lock:
                        counts["failures"] += 1
                    continue
                dt = time.perf_counter() - s
                with lock:
                    lat.append(dt)
                    counts["hb"] += 1
                    if rep is not None:
                        counts["fcr"] += 1
            rounds += 1
            pause = interval_s - (time.time() - round_t0)
            if pause > 0:
                time.sleep(pause)

    threads = max(1, threads)
    shards = [list(range(w, n_datanodes, threads))
              for w in range(threads)]
    ts = [threading.Thread(target=worker, args=(s,), daemon=True)
          for s in shards if s]
    t0 = time.time()
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    return FreonReport(
        "dnsim", ops=counts["hb"], failures=counts["failures"],
        elapsed_s=time.time() - t0, latencies_s=lat,
        extras={"datanodes": n_datanodes, "fcrs": counts["fcr"],
                "containers_per_dn": n_containers})


def dbgen(db_path, n_keys: int = 10_000, volume: str = "genvol",
          bucket: str = "genbucket", threads: int = 1) -> FreonReport:
    """Offline OM metadata fabrication (freon GeneratorOm analog): writes
    a populated OM database directly — no cluster, no datanodes — for
    testing metadata-scale behavior (billion-key DBs in the reference)."""
    from pathlib import Path

    from ozone_tpu.om.metadata import OMMetadataStore, bucket_key, key_key, \
        volume_key

    store = OMMetadataStore(Path(db_path), flush_every=4096)
    store.put("volumes", volume_key(volume),
              {"name": volume, "owner": "freon", "quota_bytes": -1,
               "created": time.time()})
    store.put("buckets", bucket_key(volume, bucket),
              {"volume": volume, "name": bucket,
               "replication": "rs-6-3-1024k", "layout": "OBJECT_STORE",
               "versioning": False, "created": time.time()})

    def op(i: int) -> int:
        kk = key_key(volume, bucket, f"gen/{i // 1000}/key-{i}")
        store.put("keys", kk, {
            "volume": volume, "bucket": bucket,
            "name": f"gen/{i // 1000}/key-{i}",
            "replication": "rs-6-3-1024k",
            "checksum_type": "CRC32C", "bytes_per_checksum": 16384,
            "size": 1024, "block_groups": [], "created": time.time(),
            "modified": time.time(),
        })
        return 1024

    # single-threaded by design: sqlite writer; flush batching does the work
    report = BaseFreonGenerator("dbgen", n_keys, threads).run(op)
    store.close()
    return report


def ommg(client, n_ops: int = 1000, threads: int = 8,
         volume: str = "freon-vol", bucket: str = "freon-meta",
         mix: str = "crudl", buckets: int = 1) -> FreonReport:
    """Mixed OM metadata ops (OmMetadataGenerator analog): cycles
    create/read(lookup)/update(rename)/delete/list per the mix string."""
    bad = set(mix) - set("crudl")
    if not mix or bad:
        raise ValueError(f"mix must be chars from 'crudl', got {mix!r}")
    names = _freon_buckets(client, volume, bucket, buckets)
    # seed keys the read/delete ops can hit (every bucket gets the full
    # seed set: op i addresses bucket i % len(names))
    for name in names:
        for i in range(min(64, n_ops)):
            s = client.om.open_key(volume, name, f"mix-{i}")
            client.om.commit_key(s, [], 0)

    def op(i: int) -> int:
        kind = mix[i % len(mix)]
        b = names[i % len(names)]
        name = f"mix-{i % 64}"
        if kind == "c":
            s = client.om.open_key(volume, b, f"mix-new-{i}")
            client.om.commit_key(s, [], 0)
        elif kind == "r":
            client.om.lookup_key(volume, b, name)
        elif kind == "u":
            client.om.rename_key(volume, b, name, name + ".r")
            client.om.rename_key(volume, b, name + ".r", name)
        elif kind == "d":
            s = client.om.open_key(volume, b, f"mix-del-{i}")
            client.om.commit_key(s, [], 0)
            client.om.delete_key(volume, b, f"mix-del-{i}")
        elif kind == "l":
            client.om.list_keys(volume, b, "mix-")
        return 0

    return BaseFreonGenerator("ommg", n_ops, threads).run(op)


def rawcoder_bench(
    backends: Optional[list[str]] = None,
    schema: str = "rs-6-3",
    cell: int = 1024 * 1024,
    batch: int = 8,
    iters: int = 5,
) -> list[dict]:
    """Raw coder throughput matrix (RawErasureCoderBenchmark analog)."""
    from ozone_tpu.codec import CoderOptions, create_decoder, create_encoder
    from ozone_tpu.codec.registry import CodecRegistry

    parts = schema.split("-")
    opts = CoderOptions(int(parts[1]), int(parts[2]), parts[0], cell)
    backends = backends or CodecRegistry.instance().backends(opts.codec)
    rng = np.random.default_rng(2)
    data = rng.integers(0, 256, (batch, opts.data_units, cell), dtype=np.uint8)
    out = []
    for be in backends:
        try:
            enc = create_encoder(opts, be)
            enc.encode(data)  # warm
            t0 = time.time()
            for _ in range(iters):
                parity = enc.encode(data)
            enc_dt = (time.time() - t0) / iters

            dec = create_decoder(opts, be)
            units = np.concatenate([data, parity], axis=1)
            erased = list(range(min(2, opts.parity_units)))
            inputs = [
                None if i in erased else units[:, i]
                for i in range(opts.all_units)
            ]
            dec.decode(inputs, erased)  # warm
            t0 = time.time()
            for _ in range(iters):
                dec.decode(inputs, erased)
            dec_dt = (time.time() - t0) / iters
            gib = data.nbytes / 2**30
            out.append(
                {
                    "backend": be,
                    "schema": schema,
                    "encode_gib_s": round(gib / enc_dt, 3),
                    "decode_gib_s": round(gib / dec_dt, 3),
                }
            )
        except Exception as e:
            out.append({"backend": be, "schema": schema, "error": str(e)})
    return out


def dnbp(
    clients,
    dn_ids: list[str],
    n_blocks: int = 200,
    chunks_per_block: int = 4,
    size: int = 1024 * 1024,
    threads: int = 4,
    container_id: int = 30_000_000,
) -> FreonReport:
    """Datanode block putter (DatanodeBlockPutter analog): raw putBlock
    metadata commits against datanodes — block-manager throughput with no
    chunk IO on the timed path."""
    from ozone_tpu.storage.ids import BlockData, BlockID, ChunkInfo
    from ozone_tpu.utils.checksum import Checksum, ChecksumType

    rng = np.random.default_rng(3)
    sample = rng.integers(0, 256, 4096, dtype=np.uint8)
    cs = Checksum(ChecksumType.CRC32C, 4096).compute(sample)
    _ensure_container(clients, dn_ids, container_id)

    def op(i: int) -> int:
        dn = dn_ids[i % len(dn_ids)]
        bid = BlockID(container_id, i + 1)
        chunks = [
            ChunkInfo(f"{bid}_chunk_{c}", c * size, size, cs)
            for c in range(chunks_per_block)
        ]
        clients.get(dn).put_block(BlockData(bid, chunks))
        return 0

    return BaseFreonGenerator("dnbp", n_blocks, threads).run(op)


def ralg(
    root,
    n_entries: int = 2000,
    size: int = 1024,
    threads: int = 1,
) -> FreonReport:
    """Raft log append generator (LeaderAppendLogEntryGenerator analog):
    a local 3-node consensus ring commits payload entries through the
    leader — measures log append + quorum-commit throughput including
    durable log writes."""
    from pathlib import Path

    from ozone_tpu.consensus.raft import InProcessTransport, RaftNode

    root = Path(root)
    transport = InProcessTransport()
    ids = ["r0", "r1", "r2"]
    sink: list = []
    nodes = [
        RaftNode(nid, ids, root / nid, (lambda _e: None) if nid != "r0"
                 else sink.append, transport=transport)
        for nid in ids
    ]
    assert nodes[0].start_election()
    payload = "x" * size

    def op(i: int) -> int:
        nodes[0].propose(f"{i}:{payload}")
        return size

    try:
        return BaseFreonGenerator("ralg", n_entries, threads).run(op)
    finally:
        for n in nodes:
            n.stop()


def ockv(client, n_keys: int = 100, size: int = 10 * 1024,
         threads: int = 4, volume: str = "freon-vol",
         bucket: str = "freon-bucket",
         prefix: str = "key") -> FreonReport:
    """Key VALIDATOR (freon ockv / the validate-writes family): read
    back keys previously written by ockg and verify content — a
    deterministic per-key payload, so corruption anywhere in the path
    (datanode, codec, decrypt) fails the op rather than passing bytes
    through."""
    b = client.get_volume(volume).get_bucket(bucket)
    expect = _det_payload(size)

    def op(i: int) -> int:
        got = b.read_key(f"{prefix}-{i}")
        assert np.array_equal(got, expect), f"corrupt key {prefix}-{i}"
        return int(got.size)

    return BaseFreonGenerator("ockv", n_keys, threads).run(op)


def fskg(client, n_files: int = 100, size: int = 10 * 1024,
         depth: int = 3, threads: int = 4, volume: str = "freon-vol",
         bucket: str = "freon-fso",
         replication: Optional[str] = None) -> FreonReport:
    """Nested-file generator over an FSO bucket (the reference's
    HadoopNestedDirGenerator + file create family): each op creates a
    file `depth` directories down, exercising the directory-tree
    resolve/create path rather than the flat key table."""
    try:
        client.om.create_volume(volume)
    except Exception:
        pass
    try:
        client.om.create_bucket(volume, bucket,
                                replication or "rs-6-3-1024k",
                                layout="FILE_SYSTEM_OPTIMIZED")
    except Exception:
        pass
    b = client.get_volume(volume).get_bucket(bucket)
    payload = np.random.default_rng(1).integers(0, 256, size,
                                                dtype=np.uint8)

    def op(i: int) -> int:
        parts = [f"d{(i >> (4 * d)) & 0xF}" for d in range(depth)]
        b.write_key("/".join(parts) + f"/f{i}", payload, replication)
        return size

    return BaseFreonGenerator("fskg", n_files, threads).run(op)


def mpug(client, n_uploads: int = 20, parts: int = 3,
         part_size: int = 16 * 1024, threads: int = 4,
         volume: str = "freon-vol", bucket: str = "freon-mpu",
         replication: Optional[str] = None) -> FreonReport:
    """Multipart-upload generator (S3MultipartUpload freon family):
    each op runs initiate -> N part writes -> complete and counts the
    full upload round trip."""
    try:
        client.om.create_volume(volume)
    except Exception:
        pass
    try:
        client.om.create_bucket(volume, bucket,
                                replication or "rs-6-3-1024k")
    except Exception:
        pass
    b = client.get_volume(volume).get_bucket(bucket)
    payload = np.random.default_rng(2).integers(0, 256, part_size,
                                                dtype=np.uint8)

    def op(i: int) -> int:
        up = b.initiate_multipart_upload(f"mpu-{i}", replication)
        for p in range(1, parts + 1):
            up.write_part(p, payload)
        up.complete()
        return part_size * parts

    return BaseFreonGenerator("mpug", n_uploads, threads).run(op)


def s3kg(endpoint: str, n_keys: int = 100, size: int = 10 * 1024,
         threads: int = 4, bucket: str = "freon-s3",
         validate: bool = False) -> FreonReport:
    """S3 gateway key generator (freon s3kg): PUTs (and optionally
    GET-validates) through the HTTP gateway, covering the full
    XML/HTTP/auth surface rather than the native RPC path."""
    import urllib.request

    base = f"http://{endpoint}"
    try:
        urllib.request.urlopen(urllib.request.Request(
            f"{base}/{bucket}", method="PUT"))
    except Exception:
        pass
    payload = bytes(np.random.default_rng(3).integers(
        0, 256, size, dtype=np.uint8))

    def op(i: int) -> int:
        with urllib.request.urlopen(urllib.request.Request(
                f"{base}/{bucket}/k{i}", data=payload,
                method="PUT")) as r:
            r.read()
        if validate:
            with urllib.request.urlopen(f"{base}/{bucket}/k{i}") as r:
                got = r.read()
            assert got == payload, f"corrupt s3 key k{i}"
        return size * (2 if validate else 1)

    return BaseFreonGenerator("s3kg", n_keys, threads).run(op)


def fsg(client, n_files: int = 50, size: int = 10 * 1024,
        threads: int = 4, volume: str = "freon-vol",
        bucket: str = "freon-ofs",
        replication: Optional[str] = None) -> FreonReport:
    """ofs filesystem generator (HadoopFsGenerator analog): each op is
    a create + read-back through the RootedOzoneFileSystem adapter —
    the path HttpFS and Hadoop-compatible workloads take."""
    from ozone_tpu.gateway.fs import RootedOzoneFileSystem

    fs = RootedOzoneFileSystem(client,
                               replication=replication or "rs-6-3-1024k")
    fs.mkdirs(f"/{volume}/{bucket}")
    payload = bytes(np.random.default_rng(4).integers(
        0, 256, size, dtype=np.uint8))

    def op(i: int) -> int:
        p = f"/{volume}/{bucket}/d{i % 8}/f{i}"
        fs.create(p, payload)
        with fs.open(p) as f:
            got = f.read()
        assert len(got) == size
        return size * 2

    return BaseFreonGenerator("fsg", n_files, threads).run(op)


def sdg(client, n_rounds: int = 10, keys_per_round: int = 5,
        size: int = 2048, volume: str = "freon-vol",
        bucket: str = "freon-snap",
        replication: Optional[str] = None) -> FreonReport:
    """Snapshot-diff generator: each op writes a handful of keys,
    snapshots, and diffs against the previous snapshot — timing the
    incremental-diff path end to end. Single-threaded by design: round
    i diffs against round i-1's snapshot, so concurrency would race
    the chain. Snapshot names carry a per-run prefix so reruns against
    a live cluster don't collide with earlier runs' snapshots."""
    import uuid

    try:
        client.om.create_volume(volume)
    except Exception:
        pass
    try:
        client.om.create_bucket(volume, bucket,
                                replication or "rs-6-3-1024k")
    except Exception:
        pass
    b = client.get_volume(volume).get_bucket(bucket)
    payload = np.random.default_rng(6).integers(0, 256, size,
                                                dtype=np.uint8)
    run = uuid.uuid4().hex[:8]

    def op(i: int) -> int:
        for k in range(keys_per_round):
            b.write_key(f"{run}-r{i}-k{k}", payload)
        client.om.create_snapshot(volume, bucket, f"{run}-s{i}")
        if i > 0:
            d = client.om.snapshot_diff(volume, bucket,
                                        f"{run}-s{i - 1}",
                                        f"{run}-s{i}")
            added = set(d.get("added", []))
            assert all(f"{run}-r{i}-k{k}" in added
                       for k in range(keys_per_round)), d
        return keys_per_round * int(payload.size)

    return BaseFreonGenerator("sdg", n_rounds, threads=1).run(op)


def ecrd(
    client,
    scm,
    size: int = 64 * 1024 * 1024,
    rounds: int = 3,
    replication: str = "rs-6-3-1048576",
    volume: str = "freon-vol",
    bucket: str = "freon-ecrd",
) -> dict:
    """EC Reconstruction Drill: the END-TO-END repair path in BASELINE's
    unit (MiB/s/datanode). Writes an EC key, closes its containers,
    wipes one unit's replica, and times ECReconstructionCoordinator
    repairing it onto a spare datanode — survivor reads + device decode
    + target writes, all over the real wire
    (ECReconstructionCoordinator.java:146 reconstructECContainerGroup).
    """
    import time as _time

    from ozone_tpu.codec.api import CoderOptions
    from ozone_tpu.storage.reconstruction import (
        ECReconstructionCoordinator,
        ReconstructionCommand,
    )

    opts = CoderOptions.parse(replication)
    try:
        client.om.create_volume(volume)
    except Exception:
        pass
    try:
        client.om.create_bucket(volume, bucket, replication)
    except Exception:
        pass
    b = client.get_volume(volume).get_bucket(bucket)
    payload = _det_payload(size, seed=9)
    all_nodes = [n["dn_id"] for n in scm.status()["nodes"]]
    results = []
    for r in range(rounds):
        key = f"drill-{r}"
        b.write_key(key, payload, replication)
        groups = client.om.key_block_groups(
            client.om.lookup_key(volume, bucket, key))
        g = groups[0]
        # close replicas DIRECTLY on the datanodes (synchronous): going
        # through the SCM would queue close commands that arrive over
        # later heartbeats and race the drill's RECOVERING container
        for dn_id in set(g.pipeline.nodes):
            try:
                client.clients.get(dn_id).close_container(g.container_id)
            except Exception:
                pass
        lost = 1  # a data unit
        client.clients.get(g.pipeline.nodes[lost]).delete_container(
            g.container_id, force=True)
        # a node holding no replica of this group; when the pipeline
        # spans every node, the wiped node itself (it no longer holds
        # one) — matching the placement policy's candidate set
        spare = next((d for d in all_nodes
                      if d not in g.pipeline.nodes),
                     g.pipeline.nodes[lost])
        cmd = ReconstructionCommand(
            g.container_id, opts,
            sources={u + 1: g.pipeline.nodes[u]
                     for u in range(opts.all_units) if u != lost},
            targets={lost + 1: spare},
        )
        coord = ECReconstructionCoordinator(client.clients)
        t0 = _time.perf_counter()
        coord.reconstruct_container_group(cmd)
        dt = _time.perf_counter() - t0
        unit_bytes = -(-g.length // opts.data_units)
        results.append((unit_bytes, dt))
        b.delete_key(key)
    per_dn = [ub / 2**20 / dt for ub, dt in results]
    per_dn.sort()
    out = {
        "name": "ecrd",
        "rounds": rounds,
        "unit_mib": round(results[0][0] / 2**20, 2),
        "reconstruct_mib_s_per_datanode": round(
            per_dn[len(per_dn) // 2], 2),
        "best_mib_s_per_datanode": round(per_dn[-1], 2),
        "times_s": [round(dt, 3) for _, dt in results],
    }
    return out


def swarm(endpoint: str, tenants: list, duration_s: float = 4.0,
          threads_per_tenant: int = 2, n_keys: int = 64,
          sizes: tuple = (4 * 1024, 64 * 1024), zipf_a: float = 1.2,
          seed: int = 1234, bucket: str = "swarm",
          tiny: bool = False) -> FreonReport:
    """freon swarm: the standing multi-tenant overload workload.

    N simulated tenants drive the S3 gateway closed-loop through
    SigV4-signed HTTP — Zipfian key popularity over a bounded working
    set, mixed op sizes (mostly small, some bulk), mixed PUT/GET. Each
    tenant dict carries {"name", "access_id", "secret", "rate"}: rate
    is its offered ops/s (0 = unpaced, as fast as the loop turns), so
    the caller ramps offered load — 1x capacity, then 2x with an
    aggressor unpaced — without changing the workload shape.

    503 SlowDown responses are counted as SHED, not failures: a shed op
    is the admission system doing its job, and the report separates the
    three outcomes (ok / shed / errors) per tenant so shed-not-collapse
    is checkable — goodput and accepted-op latency per tenant, shed
    fraction overall.
    """
    import bisect
    import datetime
    import random as _random
    import urllib.error
    import urllib.request

    from ozone_tpu.gateway.s3_auth import sign_request

    if tiny:
        # tiny-key churn mode: the tinyg size mix drives the swarm, so
        # the overload drills exercise the inline/needle path too (the
        # gateway-side bucket must be smallobj-enabled by the caller)
        sizes = TINY_SIZES
    base = f"http://{endpoint}"

    def _amz_now() -> str:
        return datetime.datetime.now(datetime.timezone.utc).strftime(
            "%Y%m%dT%H%M%SZ")

    def _request(t: dict, method: str, path: str,
                 body: bytes = b"") -> None:
        url = f"{base}{path}"
        headers = {"host": endpoint, "x-amz-date": _amz_now()}
        if t.get("access_id"):
            headers = sign_request(t["access_id"], t["secret"], method,
                                   url, headers, body)
        req = urllib.request.Request(
            url, data=body if method in ("PUT", "POST") else None,
            method=method, headers=headers)
        with urllib.request.urlopen(req) as r:
            r.read()

    # Zipfian popularity: cumulative weights over key ranks, sampled by
    # bisect — rank 0 is the hot key, the tail cools as 1/rank^a
    cum: list[float] = []
    acc = 0.0
    for r in range(max(1, n_keys)):
        acc += 1.0 / (r + 1) ** zipf_a
        cum.append(acc)
    payloads = {sz: bytes(np.random.default_rng(11).integers(
        0, 256, sz, dtype=np.uint8)) for sz in sizes}

    for t in tenants:
        try:
            _request(t, "PUT", f"/{bucket}")
        except Exception:
            pass  # BucketAlreadyExists across phases

    lock = threading.Lock()
    stats = {t["name"]: {"offered": 0, "ok": 0, "shed": 0, "errors": 0,
                         "bytes": 0, "lat": []} for t in tenants}
    written: dict[str, set] = {t["name"]: set() for t in tenants}
    start = time.monotonic()
    end = start + duration_s

    def worker(t: dict, wid: int) -> None:
        st = stats[t["name"]]
        seen = written[t["name"]]
        rng = _random.Random(f"{seed}:{t['name']}:{wid}")
        rate = float(t.get("rate") or 0.0)
        interval = threads_per_tenant / rate if rate > 0 else 0.0
        next_t = time.monotonic() + rng.uniform(0, interval or 0.001)
        while True:
            now = time.monotonic()
            if now >= end:
                return
            if interval:
                # paced offered load: ops fire on a schedule, late ops
                # do NOT bunch up (the schedule advances regardless)
                if next_t >= end:
                    return
                if next_t > now:
                    time.sleep(next_t - now)
                next_t += interval
            rank = bisect.bisect_left(cum, rng.uniform(0.0, cum[-1]))
            key = f"{t['name']}-k{rank}"
            size = sizes[0] if rng.random() < 0.8 else sizes[-1]
            do_put = rank not in seen or rng.random() < 0.5
            s0 = time.perf_counter()
            try:
                if do_put:
                    _request(t, "PUT", f"/{bucket}/{key}",
                             payloads[size])
                else:
                    _request(t, "GET", f"/{bucket}/{key}")
                dt = time.perf_counter() - s0
                with lock:
                    st["offered"] += 1
                    st["ok"] += 1
                    st["bytes"] += size
                    st["lat"].append(dt)
                if do_put:
                    seen.add(rank)
            except urllib.error.HTTPError as e:
                e.close()
                with lock:
                    st["offered"] += 1
                    if e.code == 503:
                        st["shed"] += 1
                    else:
                        st["errors"] += 1
            except Exception:
                with lock:
                    st["offered"] += 1
                    st["errors"] += 1

    threads = [threading.Thread(target=worker, args=(t, w), daemon=True)
               for t in tenants for w in range(threads_per_tenant)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    elapsed = time.monotonic() - start

    def _p99(lat: list) -> float:
        if not lat:
            return 0.0
        ls = sorted(lat)
        return ls[min(len(ls) - 1, int(0.99 * len(ls)))]

    all_lat: list[float] = []
    per_tenant = {}
    offered = ok = shed = errors = nbytes = 0
    for name, st in stats.items():
        all_lat.extend(st["lat"])
        offered += st["offered"]
        ok += st["ok"]
        shed += st["shed"]
        errors += st["errors"]
        nbytes += st["bytes"]
        per_tenant[name] = {
            "offered": st["offered"],
            "ok": st["ok"],
            "shed": st["shed"],
            "errors": st["errors"],
            "goodput_ops_s": round(st["ok"] / elapsed, 2)
            if elapsed else 0.0,
            "p99_ms": round(1e3 * _p99(st["lat"]), 3),
        }
    return FreonReport(
        "swarm", ops=ok, failures=errors, elapsed_s=elapsed,
        latencies_s=all_lat, bytes_processed=nbytes,
        extras={
            "per_tenant": per_tenant,
            "offered": offered,
            "shed": shed,
            "shed_fraction": round(shed / offered, 4) if offered else 0.0,
            "goodput_ops_s": round(ok / elapsed, 2) if elapsed else 0.0,
        })
