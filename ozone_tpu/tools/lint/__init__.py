"""ozlint: AST-based invariant analyzer for the ozone_tpu tree.

The repo encodes hard invariants in prose (docs/LINT.md) — deadline
propagation, no blocking under a lock, fence-carrying ring commits,
constant-shape device dispatch, no swallowed datapath errors — and each
one has already cost a real bug (the native_dn 120 s connect literal,
the dial-before-bind channel wedge, the plan-cache recompile
bimodality). ozlint is the structural enforcement: `python -m
ozone_tpu.tools.lint ozone_tpu/` walks every file's AST and reports any
code that violates an invariant and does not carry an in-line
justification (`# ozlint: allow[rule-id] -- reason`).

This package must stay import-light: no jax, no ozone_tpu runtime
modules — the tier-1 gate runs it as a sub-second subprocess.
"""

from ozone_tpu.tools.lint.core import (  # noqa: F401
    Finding,
    LintError,
    RULES,
    SourceFile,
    format_findings,
    lint_paths,
    lint_source,
    rewrite_legacy_suppressions,
)

__all__ = [
    "Finding",
    "LintError",
    "RULES",
    "SourceFile",
    "format_findings",
    "lint_paths",
    "lint_source",
    "rewrite_legacy_suppressions",
]
