"""CLI: ``python -m ozone_tpu.tools.lint [paths...] [--check]``.

Exit status 0 = zero unsuppressed findings, 1 = findings, 2 = usage or
analysis error. Keep this import-light (no jax): the tier-1 gate runs
it as a subprocess with a <5 s budget (set ``OZONE_TPU_SKIP_JAX_PIN=1``
or an empty ``JAX_PLATFORMS`` so the package __init__ skips its eager
platform pin).
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from ozone_tpu.tools.lint import (
    LintError,
    RULES,
    format_findings,
    lint_paths,
    rewrite_legacy_suppressions,
)


def _default_target() -> list[str]:
    here = Path.cwd() / "ozone_tpu"
    if here.is_dir():
        return [str(here)]
    pkg = Path(__file__).resolve().parents[2]  # .../ozone_tpu
    return [str(pkg)]


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ozone_tpu.tools.lint",
        description="ozlint: AST-based invariant analyzer "
                    "(docs/LINT.md)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to analyze (default: ozone_tpu/)")
    ap.add_argument("--check", action="store_true",
                    help="CI mode: identical analysis, exit status is "
                         "the only contract (still prints findings)")
    ap.add_argument("--rules", default="",
                    help="comma-separated rule ids (default: all)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print rule ids with their invariants")
    ap.add_argument("--fix-suppressions", action="store_true",
                    help="rewrite legacy `# resilience-lint: allow` "
                         "markers to `# ozlint: allow[...] -- reason` "
                         "in place")
    args = ap.parse_args(argv)

    # force rule registration for --list-rules
    from ozone_tpu.tools.lint import rules as _rules  # noqa: F401

    if args.list_rules:
        for rid, rule in sorted(RULES.items()):
            print(f"{rid}: {rule.summary}")
        return 0

    paths = args.paths or _default_target()
    if args.fix_suppressions:
        for p in rewrite_legacy_suppressions(paths):
            print(f"rewrote legacy suppression markers in {p}")
        return 0

    rule_ids = [r.strip() for r in args.rules.split(",") if r.strip()] \
        or None
    try:
        findings = lint_paths(paths, rules=rule_ids, root=str(Path.cwd()))
    except LintError as e:
        print(f"ozlint: error: {e}", file=sys.stderr)
        return 2
    print(format_findings(findings))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
