"""ozlint framework: file model, suppressions, rule registry, runner.

Deliberately dependency-free (ast + re + pathlib only): the tier-1 gate
shells out to `python -m ozone_tpu.tools.lint --check` and must finish
in well under five seconds without importing jax or any runtime module.

Suppression grammar (per line)::

    some_call(timeout=5.0)  # ozlint: allow[deadline-propagation] -- why

- The marker must name the rule id(s) it waives and MUST carry a
  `-- reason`; a reasonless or unknown-rule marker is itself reported
  (rule id ``suppression-format``) so justifications cannot erode.
- A marker on its own comment line covers the next statement; a marker
  on a code line covers that line, and any multi-line statement whose
  span contains the marker line.
- Fixture/corpus files may carry a first-lines pragma
  ``# ozlint: path ozone_tpu/client/_fixture.py`` that sets the
  EFFECTIVE path rules use for scoping, so known-bad snippets exercise
  directory-scoped rules from anywhere on disk.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Iterable, Optional, Sequence

#: rule id for malformed/unknown suppression markers — always active
SUPPRESSION_FORMAT = "suppression-format"

_ALLOW_RE = re.compile(
    r"#\s*ozlint:\s*allow\[([^\]]*)\]\s*(?:--\s*(.*\S))?")
_PATH_PRAGMA_RE = re.compile(r"^#\s*ozlint:\s*path\s+(\S+)\s*$")
LEGACY_ALLOW = "resilience-lint: allow"


class LintError(Exception):
    """A file could not be analyzed (unreadable, syntax error)."""


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location.

    ``render()`` is the pinned output format (tests/test_lint.py golden
    test): ``path:line: rule-id: message``."""

    rule: str
    path: str
    line: int
    message: str
    #: (first, last) line of the flagged node — used only to let a
    #: suppression marker anywhere inside a multi-line statement apply
    span: tuple[int, int] = (0, 0)

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule}: {self.message}"


@dataclass
class Suppression:
    line: int
    rules: tuple[str, ...]
    reason: str
    own_line: bool  # marker is the whole line (covers the next stmt)


class Rule:
    """Base class: subclasses set ``id``/``summary`` and implement
    ``check(src) -> iterable of Finding``. Register with ``@register``."""

    id: str = ""
    summary: str = ""
    #: the invariant's origin story, shown by --list-rules and LINT.md
    rationale: str = ""

    def check(self, src: "SourceFile") -> Iterable[Finding]:
        raise NotImplementedError


RULES: dict[str, Rule] = {}


def register(cls: type) -> type:
    inst = cls()
    assert inst.id and inst.id not in RULES, f"bad rule registration {cls}"
    RULES[inst.id] = inst
    return cls


class SourceFile:
    """Parsed view of one file handed to every rule: AST, raw lines,
    per-line suppressions, and the EFFECTIVE module path for scoping."""

    def __init__(self, text: str, path: str = "<string>",
                 display_path: Optional[str] = None):
        self.text = text
        self.path = path
        self.display_path = display_path or path
        self.lines = text.splitlines()
        try:
            self.tree = ast.parse(text)
        except SyntaxError as e:
            raise LintError(f"{self.display_path}: syntax error at "
                            f"line {e.lineno}: {e.msg}") from e
        self.effective_path = self._effective_path()
        self.suppressions: list[Suppression] = []
        self.marker_findings: list[Finding] = []
        self._collect_suppressions()
        # shared node indexes so five rules don't re-walk the tree:
        # every node once, every Call paired with its enclosing def,
        # every (Async)FunctionDef
        self.nodes: list[ast.AST] = []
        self.calls_with_fn: list[tuple[ast.Call, Optional[ast.AST]]] = []
        self.functions: list[ast.AST] = []
        self._index(self.tree, None)

    def _index(self, node: ast.AST, fn: Optional[ast.AST]) -> None:
        stack = [(node, fn)]
        while stack:
            cur, cfn = stack.pop()
            self.nodes.append(cur)
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions.append(cur)
                cfn = cur
            elif isinstance(cur, ast.Call):
                self.calls_with_fn.append((cur, cfn))
            stack.extend((c, cfn) for c in ast.iter_child_nodes(cur))
        self._stmt_spans = self._collect_spans()

    # ----------------------------------------------------------- scoping
    def _effective_path(self) -> str:
        for raw in self.lines[:5]:
            m = _PATH_PRAGMA_RE.match(raw.strip())
            if m:
                return m.group(1)
        return self.display_path

    @property
    def module_parts(self) -> tuple[str, ...]:
        """Path segments after the last ``ozone_tpu`` in the effective
        path — ("client", "native_dn.py") — or all segments when the
        file lives outside the package."""
        parts = Path(self.effective_path).parts
        for i in range(len(parts) - 1, -1, -1):
            if parts[i] == "ozone_tpu":
                return tuple(parts[i + 1:])
        return tuple(parts)

    def in_dirs(self, *dirs: str) -> bool:
        mp = self.module_parts
        return bool(mp) and mp[0] in dirs

    def is_module(self, *rel: str) -> bool:
        return self.module_parts == rel

    # ------------------------------------------------------ suppressions
    def _comment_lines(self) -> dict[int, str]:
        """Real COMMENT tokens by line (tokenize, not raw text): a
        marker quoted inside a docstring or string literal is prose,
        not a suppression — matching raw lines would make the grammar
        impossible to document in-tree."""
        import io
        import tokenize

        out: dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(self.text).readline):
                if tok.type == tokenize.COMMENT:
                    out[tok.start[0]] = tok.string
        except (tokenize.TokenError, IndentationError):
            # ast.parse accepted it; a tokenizer hiccup just means no
            # suppressions are honored for the unreadable tail
            pass
        return out

    def _collect_suppressions(self) -> None:
        for i, raw in self._comment_lines().items():
            m = _ALLOW_RE.search(raw)
            if not m:
                continue
            ids = tuple(s.strip() for s in m.group(1).split(",")
                        if s.strip())
            reason = (m.group(2) or "").strip()
            # own-line = the comment IS the whole line (check the
            # original source line; `raw` is just the comment token)
            own = self.lines[i - 1].strip().startswith("#")
            bad: list[str] = []
            if not ids:
                bad.append("empty rule list")
            unknown = [r for r in ids
                       if r not in RULES and r != SUPPRESSION_FORMAT]
            if unknown:
                bad.append(f"unknown rule id(s) {', '.join(unknown)}")
            if not reason:
                bad.append("missing `-- reason`")
            if bad:
                self.marker_findings.append(Finding(
                    SUPPRESSION_FORMAT, self.display_path, i,
                    f"malformed ozlint suppression ({'; '.join(bad)}): "
                    f"expected `# ozlint: allow[rule-id] -- reason`",
                    span=(i, i)))
            # honor even a reasonless marker so the malformed-marker
            # finding is the ONE actionable signal, not a pile of three
            self.suppressions.append(Suppression(i, ids, reason, own))

    def _collect_spans(self) -> list[tuple[int, int, bool]]:
        """(first, last, is_compound) per statement — compound bodies
        are excluded from own-line marker coverage so a marker above a
        def/with/for waives only the header, never the whole body."""
        spans = []
        for n in self.nodes:
            if isinstance(n, ast.stmt):
                compound = bool(getattr(n, "body", None))
                hi = n.end_lineno or n.lineno
                if compound:
                    first_body = n.body[0].lineno if n.body else hi
                    hi = max(n.lineno, first_body - 1)
                spans.append((n.lineno, hi, compound))
        return spans

    def _next_code_line(self, after: int) -> Optional[int]:
        for i in range(after, len(self.lines)):
            s = self.lines[i].strip()
            if s and not s.startswith("#"):
                return i + 1
        return None

    def suppressed(self, f: Finding) -> bool:
        for s in self.suppressions:
            if f.rule not in s.rules:
                continue
            if s.line == f.line:
                return True
            if s.own_line and self._next_code_line(s.line) == f.line:
                return True
            lo, hi = f.span if f.span != (0, 0) else (f.line, f.line)
            if lo <= s.line <= hi:
                return True
            # own-line marker directly above a statement also covers a
            # finding anywhere in that statement — for compound
            # statements only the HEADER lines (through the line before
            # the body), so one waived def-line finding cannot silently
            # mask future violations inside the body
            if s.own_line:
                nxt = self._next_code_line(s.line)
                if nxt is not None and any(
                        a == nxt and a <= f.line <= b
                        for a, b, _comp in self._stmt_spans):
                    return True
        return False


# --------------------------------------------------------------- runner
def _iter_py_files(paths: Sequence[str]) -> Iterable[Path]:
    for p in paths:
        pp = Path(p)
        if pp.is_dir():
            for f in sorted(pp.rglob("*.py")):
                if "__pycache__" in f.parts:
                    continue
                yield f
        elif pp.suffix == ".py":
            yield pp


def _display(path: Path, root: Optional[Path]) -> str:
    if root is not None:
        try:
            return str(path.resolve().relative_to(root.resolve()))
        except ValueError:
            pass
    return str(path)


def lint_source(text: str, path: str = "<string>",
                rules: Optional[Sequence[str]] = None) -> list[Finding]:
    """Analyze one source string; ``path`` drives rule scoping (or use
    the in-file ``# ozlint: path ...`` pragma)."""
    _ensure_rules_loaded()
    src = SourceFile(text, path=path, display_path=path)
    return _check_one(src, rules)


def lint_paths(paths: Sequence[str],
               rules: Optional[Sequence[str]] = None,
               root: Optional[str] = None) -> list[Finding]:
    """Analyze files/directories; returns unsuppressed findings sorted
    by (path, line, rule). ``root`` makes display paths relative."""
    _ensure_rules_loaded()
    rootp = Path(root) if root else None
    findings: list[Finding] = []
    for f in _iter_py_files(paths):
        disp = _display(f, rootp)
        try:
            text = f.read_text()
        except OSError as e:
            raise LintError(f"{disp}: unreadable: {e}") from e
        src = SourceFile(text, path=str(f), display_path=disp)
        findings.extend(_check_one(src, rules))
    findings.sort(key=lambda x: (x.path, x.line, x.rule))
    return findings


def _check_one(src: SourceFile,
               rules: Optional[Sequence[str]]) -> list[Finding]:
    if rules:
        unknown = [r for r in rules
                   if r not in RULES and r != SUPPRESSION_FORMAT]
        if unknown:
            raise LintError(
                f"unknown rule id(s): {', '.join(unknown)} "
                f"(see --list-rules)")
        active = [RULES[r] for r in rules if r in RULES]
    else:
        active = list(RULES.values())
    out: list[Finding] = []
    for rule in active:
        for f in rule.check(src):
            if not src.suppressed(f):
                out.append(f)
    if rules is None or SUPPRESSION_FORMAT in rules:
        out.extend(src.marker_findings)
    return out


def format_findings(findings: Sequence[Finding]) -> str:
    lines = [f.render() for f in findings]
    lines.append(f"ozlint: {len(findings)} finding"
                 f"{'' if len(findings) == 1 else 's'}")
    return "\n".join(lines)


def _ensure_rules_loaded() -> None:
    if not RULES:
        from ozone_tpu.tools.lint import rules as _rules  # noqa: F401


# ------------------------------------------------- legacy marker rewrite
def rewrite_legacy_suppressions(paths: Sequence[str]) -> list[str]:
    """--fix-suppressions: convert `# resilience-lint: allow` markers to
    `# ozlint: allow[deadline-propagation] -- <reason>` in place,
    keeping any trailing text as the reason. Returns rewritten paths."""
    changed: list[str] = []
    for f in _iter_py_files(paths):
        text = f.read_text()
        if LEGACY_ALLOW not in text:
            continue
        out_lines = []
        for line in text.splitlines(keepends=True):
            if LEGACY_ALLOW in line:
                head, _, tail = line.partition("resilience-lint: allow")
                head = head.rstrip()
                if head.endswith("#"):
                    head = head[:-1].rstrip()
                reason = tail.strip(" -\n") or \
                    "migrated legacy exemption marker"
                nl = "\n" if line.endswith("\n") else ""
                line = (f"{head}  # ozlint: allow[deadline-propagation]"
                        f" -- {reason}{nl}")
            out_lines.append(line)
        f.write_text("".join(out_lines))
        changed.append(str(f))
    return changed
