"""The eight production ozlint rules.

Each rule guards an invariant the repo states in prose and has already
paid for in bugs (docs/LINT.md has the full origin stories):

- ``deadline-propagation``  every timeout in the client/net/lifecycle
  datapath and the codec service derives from ``resilience.Deadline``
  (PR 2's hardcoded-120s-connect class of bug). Strictly subsumes the
  old regex lint in tests/test_tools.py: constant folding + name
  resolution catch keyword args and computed literals the regex missed.
- ``blocking-under-lock``   no blocking call while holding a lock (the
  codec-service dispatcher/double-buffer race-detector shape).
- ``fence-carrying-commit`` ring mutations of term-fenced state carry
  their fencing term / expected object id (PR 4's deposed-leader and
  racing-overwrite class of bug).
- ``dispatch-shape-stability`` jitted device programs must not be keyed
  on known-varying values (PR 1/PR 6's plan-cache recompile
  bimodality).
- ``error-swallowing``      no silently dropped exceptions on datapath
  or consensus modules.
- ``span-on-dispatch``      codec device-dispatch edges run inside an
  active trace span (the latency-attribution contract), and RPC
  handlers register only through net/rpc.py's span guard.
- ``datapath-no-copy``      the wire-facing datapath modules never
  materialize payload bytes (``bytes(...)``, ``.tobytes()``,
  view ``.copy()``) — payloads travel as views over pooled buffers;
  control-plane copies carry a reasoned suppression.
- ``bounded-queue``         server-side packages construct no unbounded
  ``queue.Queue()``/``deque()`` — an unbounded queue at a service hop
  is admission control's blind spot (work piles up invisibly until the
  process collapses); bound it or suppress with the reason the depth
  is bounded elsewhere.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from ozone_tpu.tools.lint.core import Finding, Rule, SourceFile, register

# --------------------------------------------------------- AST helpers


def dotted(node: ast.AST) -> str:
    """Best-effort dotted name of an expression: ``socket.create_connection``,
    ``self._cond.wait`` -> empty string for non-name shapes."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    return ""


def last_name(node: ast.AST) -> str:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def receiver_name(call_func: ast.AST) -> str:
    """For ``a.b.wait(...)`` the receiver's final segment (``b``)."""
    if isinstance(call_func, ast.Attribute):
        return last_name(call_func.value)
    return ""


def _span(node: ast.AST) -> tuple[int, int]:
    return (node.lineno, getattr(node, "end_lineno", node.lineno)
            or node.lineno)


class _ConstEnv:
    """Single-assignment numeric-constant environment: module-level and
    function-local ``NAME = 120.0`` style bindings, poisoned on
    reassignment so only provably-constant names resolve."""

    def __init__(self) -> None:
        self._vals: dict[str, Optional[float]] = {}

    def bind(self, name: str, value: Optional[float]) -> None:
        if name in self._vals:
            self._vals[name] = None  # reassigned: no longer provable
        else:
            self._vals[name] = value

    def get(self, name: str) -> Optional[float]:
        return self._vals.get(name)


def _fold(node: ast.AST, env: _ConstEnv) -> Optional[float]:
    """Resolve an expression to a numeric constant, through unary/binary
    arithmetic and single-assignment name bindings. None = not provable."""
    if isinstance(node, ast.Constant):
        if isinstance(node.value, bool) or not isinstance(
                node.value, (int, float)):
            return None
        return float(node.value)
    if isinstance(node, ast.UnaryOp) and isinstance(
            node.op, (ast.USub, ast.UAdd)):
        v = _fold(node.operand, env)
        if v is None:
            return None
        return -v if isinstance(node.op, ast.USub) else v
    if isinstance(node, ast.BinOp):
        a, b = _fold(node.left, env), _fold(node.right, env)
        if a is None or b is None:
            return None
        try:
            if isinstance(node.op, ast.Add):
                return a + b
            if isinstance(node.op, ast.Sub):
                return a - b
            if isinstance(node.op, ast.Mult):
                return a * b
            if isinstance(node.op, ast.Div):
                return a / b
            if isinstance(node.op, ast.FloorDiv):
                return a // b
            if isinstance(node.op, ast.Pow):
                return a ** b
        except (ZeroDivisionError, OverflowError):
            return None
        return None
    if isinstance(node, ast.Name):
        return env.get(node.id)
    return None


def _scope_walk(body: Iterable[ast.stmt]):
    """Every node in this scope — including except-handler bodies, loop
    bodies, with-blocks — but NOT nested function/class scopes. Yields
    in SOURCE order (pre-order DFS): constant folding relies on seeing
    a name's first binding before its uses in later assignments."""
    stack = list(reversed(list(body)))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue  # separate scope
        stack.extend(reversed(list(ast.iter_child_nodes(node))))


def _collect_env(body: Iterable[ast.stmt], env: _ConstEnv,
                 *, recurse: bool = True) -> None:
    """Bind simple ``NAME = <expr>`` assignments (value folded eagerly;
    a second binding — or any dynamic one: loop targets, ``with … as``,
    except-handler rebinds, walrus — poisons the name, so partial
    knowledge never produces a false constant)."""
    nodes = _scope_walk(body) if recurse else list(body)
    for stmt in nodes:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name):
            env.bind(stmt.targets[0].id, _fold(stmt.value, env))
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None \
                and isinstance(stmt.target, ast.Name):
            env.bind(stmt.target.id, _fold(stmt.value, env))
        elif isinstance(stmt, ast.AugAssign) and isinstance(
                stmt.target, ast.Name):
            env.bind(stmt.target.id, None)
        elif isinstance(stmt, ast.Assign):
            # tuple/starred/attribute targets: poison every plain name
            for t in stmt.targets:
                for nn in ast.walk(t):
                    if isinstance(nn, ast.Name):
                        env.bind(nn.id, None)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            for nn in ast.walk(stmt.target):
                if isinstance(nn, ast.Name):
                    env.bind(nn.id, None)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                if item.optional_vars is not None:
                    for nn in ast.walk(item.optional_vars):
                        if isinstance(nn, ast.Name):
                            env.bind(nn.id, None)
        elif isinstance(stmt, ast.ExceptHandler) and stmt.name:
            env.bind(stmt.name, None)
        elif isinstance(stmt, ast.NamedExpr) and \
                isinstance(stmt.target, ast.Name):
            env.bind(stmt.target.id, None)


def _fn_env(module_env: _ConstEnv, fn) -> _ConstEnv:
    env = _ConstEnv()
    env._vals.update(module_env._vals)
    if fn is not None:
        # parameters are caller-supplied, never provably constant (and
        # a later local assignment over the same name stays poisoned)
        for a in list(fn.args.args) + list(fn.args.kwonlyargs) + \
                list(fn.args.posonlyargs):
            env.bind(a.arg, None)
        _collect_env(fn.body, env)
    return env


# ------------------------------------------------- deadline-propagation
@register
class DeadlinePropagation(Rule):
    id = "deadline-propagation"
    summary = ("timeouts in client/, net/, lifecycle/, "
               "replication_geo/ and the codec service must derive "
               "from resilience.Deadline, never from numeric "
               "literals; socket timeouts repo-wide")
    rationale = (
        "PR 2's root bug: native_dn hardcoded a 120 s connect timeout, "
        "so a dead peer consumed the whole operation budget before the "
        "first retry. Every hop's timeout must derive from the ambient "
        "resilience.Deadline (op_timeout()/Deadline.timeout()) or an "
        "EWMA/env-derived knob. Supersedes the regex lint in "
        "tests/test_tools.py, which missed keyword args and computed "
        "literals.")

    SLEEPS = {"sleep"}
    TIMEOUT_KWARGS = {"timeout", "timeout_s", "deadline_s"}
    POSITIONAL_WAITS = {"wait", "join", "result", "wait_for"}

    def check(self, src: SourceFile) -> Iterable[Finding]:
        if src.is_module("client", "resilience.py"):
            return  # the implementation of the discipline itself
        in_scope = (src.in_dirs("client", "net", "lifecycle",
                                "replication_geo")
                    or src.is_module("codec", "service.py")
                    # the sharded metadata plane retries through ring
                    # failovers — its waits must be deadline-derived
                    or src.module_parts[:2] == ("om", "sharding"))
        module_env = _ConstEnv()
        _collect_env(src.tree.body, module_env, recurse=False)
        # per-function env memo, scoped to THIS check pass: fn nodes
        # stay alive via src.tree, so id() keys cannot be recycled
        # (a process-global id-keyed cache could alias freed nodes)
        envs: dict[int, _ConstEnv] = {}
        for call, fn in src.calls_with_fn:
            key = id(fn)
            env = envs.get(key)
            if env is None:
                env = envs[key] = _fn_env(module_env, fn)
            name = last_name(call.func)
            dot = dotted(call.func)

            # socket timeouts: repo-wide (the 120 s connect class)
            if name == "create_connection":
                for kw in call.keywords:
                    if kw.arg == "timeout" and \
                            _fold(kw.value, env) is not None:
                        yield self._f(src, kw.value,
                                      "socket connect timeout is a "
                                      "numeric literal")
                if len(call.args) >= 2 and \
                        _fold(call.args[1], env) is not None:
                    yield self._f(src, call.args[1],
                                  "socket connect timeout is a "
                                  "numeric literal")
                continue
            if name == "settimeout" and call.args and \
                    _fold(call.args[0], env) is not None:
                yield self._f(src, call.args[0],
                              "socket timeout is a numeric literal")
                continue

            if not in_scope:
                continue

            # bare sleeps: backoff belongs to resilience.RetryPolicy
            if dot in ("time.sleep", "_time.sleep"):
                yield self._f(
                    src, call, "bare time.sleep on a deadline-scoped "
                    "path — retries/backoff must ride "
                    "resilience.RetryPolicy", what="call")
                continue

            # literal timeout keyword on any call
            for kw in call.keywords:
                if kw.arg in self.TIMEOUT_KWARGS and \
                        _fold(kw.value, env) is not None:
                    yield self._f(src, kw.value,
                                  f"literal `{kw.arg}=` on `{dot or name}()`")
            # literal positional timeout on the known blocking verbs
            if name in self.POSITIONAL_WAITS and len(call.args) == 1 \
                    and _fold(call.args[0], env) is not None:
                yield self._f(src, call.args[0],
                              f"literal timeout passed to `.{name}()`")

    def _f(self, src: SourceFile, node: ast.AST, what_msg: str,
           what: str = "timeout") -> Finding:
        msg = what_msg if what == "call" else (
            f"{what_msg} — derive it from resilience.op_timeout()/"
            f"Deadline.timeout() or a documented env knob")
        return Finding(self.id, src.display_path, node.lineno, msg,
                       span=_span(node))


# ------------------------------------------------- blocking-under-lock
@register
class BlockingUnderLock(Rule):
    id = "blocking-under-lock"
    summary = ("no blocking call (sleep, future/thread join, queue get, "
               "socket or device I/O) lexically inside a held lock")
    rationale = (
        "The codec-service dispatcher packs under self._cond but "
        "dispatches to the chip OUTSIDE it; holding any lock across a "
        "blocking call is the lock-convoy/deadlock shape that "
        "thread-sanitizer gates catch in mature storage systems. "
        "Condition.wait() is exempt — it releases the lock.")

    SOCKET_OPS = {"recv", "recv_into", "sendall", "accept", "connect",
                  "create_connection"}
    DEVICE_OPS = {"block_until_ready", "device_put", "wait_result",
                  "drain"}
    SUBPROC_OPS = {"communicate", "check_output", "check_call"}

    def check(self, src: SourceFile) -> Iterable[Finding]:
        if src.in_dirs("testing", "tools"):
            return
        findings: list[Finding] = []

        def lockish(expr: ast.AST) -> Optional[str]:
            n = last_name(expr).lower()
            if isinstance(expr, ast.Call):
                n = last_name(expr.func).lower()
            if any(t in n for t in ("lock", "mutex", "cond")) or \
                    n in ("cv", "_cv"):
                return n
            return None

        def condish(name: str) -> bool:
            n = name.lower()
            return "cond" in n or n in ("cv", "_cv")

        def classify(call: ast.Call) -> Optional[str]:
            name = last_name(call.func)
            dot = dotted(call.func)
            recv = receiver_name(call.func)
            if dot in ("time.sleep", "_time.sleep"):
                return "time.sleep"
            if name in self.SOCKET_OPS and recv not in ("self",):
                return f"socket .{name}()"
            if name in self.DEVICE_OPS or "dispatch" in name.lower():
                return f"device/pipeline `{name}()`"
            if dot.startswith("subprocess.") and name in (
                    self.SUBPROC_OPS | {"run", "call"}):
                return f"subprocess.{name}()"
            if name in self.SUBPROC_OPS:
                return f".{name}()"
            if name == "result":
                return "future .result()"
            if name == "join" and _join_is_thread_join(call):
                return "thread .join()"
            if name in ("wait", "wait_for") and not condish(recv):
                return f"non-condition .{name}()"
            if name == "get" and not call.args and (
                    not call.keywords or all(
                        k.arg in ("block", "timeout")
                        for k in call.keywords)):
                return "queue .get()"
            return None

        def _join_is_thread_join(call: ast.Call) -> bool:
            """Distinguish Thread.join([timeout]) from str.join(iter):
            zero args, a timeout kwarg, or a single numeric arg."""
            if any(k.arg == "timeout" for k in call.keywords):
                return True
            if not call.args and not call.keywords:
                return True
            return (len(call.args) == 1
                    and isinstance(call.args[0], ast.Constant)
                    and isinstance(call.args[0].value, (int, float)))

        def scan_expr(node: ast.AST, held: list[str]) -> None:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    kind = classify(sub)
                    if kind:
                        findings.append(Finding(
                            self.id, src.display_path, sub.lineno,
                            f"blocking {kind} while holding "
                            f"`{held[-1]}` — move the blocking work "
                            f"outside the lock or use a Condition",
                            span=_span(sub)))

        def scan_body(body: list[ast.stmt], held: list[str]) -> None:
            # NB: mutates the caller's `held` in place so a release()
            # inside a nested block (the acquire/try/finally:release
            # idiom) unwinds the lock for the statements that follow;
            # `with` blocks pass a fresh list since their lock scope
            # ends with the block
            for stmt in body:
                # acquire()/release() bracketing in this statement list
                if isinstance(stmt, ast.Expr) and isinstance(
                        stmt.value, ast.Call):
                    nm = last_name(stmt.value.func)
                    tgt = dotted(stmt.value.func)
                    recv = (stmt.value.func.value
                            if isinstance(stmt.value.func, ast.Attribute)
                            else stmt.value.func)
                    if nm == "acquire" and lockish(recv) is not None:
                        held.append(tgt.rsplit(".", 1)[0] or "lock")
                        continue
                    # only a LOCK-like receiver's release() unwinds —
                    # a buffer/semaphore release inside the region must
                    # not hide blocking calls that follow it
                    if nm == "release" and held and \
                            lockish(recv) is not None:
                        held.pop()
                        continue
                if isinstance(stmt, ast.With):
                    locks = [lockish(item.context_expr)
                             for item in stmt.items]
                    new_held = list(held) + [n for n in locks if n]
                    if held:
                        for item in stmt.items:
                            scan_expr(item.context_expr, held)
                    scan_body(stmt.body, new_held)
                    continue
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    # nested def: body runs later, not under this lock
                    scan_body(stmt.body, [])
                    continue
                if isinstance(stmt, ast.ClassDef):
                    scan_body(stmt.body, [])
                    continue
                if held:
                    # flag blocking calls in this statement's own
                    # expressions, then recurse into compound bodies
                    for field_name, value in ast.iter_fields(stmt):
                        if field_name in ("body", "orelse", "finalbody",
                                          "handlers"):
                            continue
                        if isinstance(value, ast.AST):
                            scan_expr(value, held)
                        elif isinstance(value, list):
                            for v in value:
                                if isinstance(v, ast.AST):
                                    scan_expr(v, held)
                for field_name in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, field_name, None)
                    if sub:
                        scan_body(sub, held)
                for h in getattr(stmt, "handlers", []) or []:
                    scan_body(h.body, held)

        scan_body(src.tree.body, [])
        yield from findings


# ----------------------------------------------- fence-carrying-commit
@register
class FenceCarryingCommit(Rule):
    id = "fence-carrying-commit"
    summary = ("ring requests that mutate term-fenced state must pass "
               "their fencing term / expected object id")
    rationale = (
        "PR 4's duplicate-allocation and lifecycle lessons: an unfenced "
        "mutation from a deposed leader or a background job racing a "
        "user overwrite silently loses data. LifecycleCheckpoint and "
        "GeoCheckpoint must carry `term`; "
        "CommitKey/CommitFile/DeleteKey must carry "
        "`expect_object_id` (\"\" only where unfenced semantics are the "
        "documented API, with an ozlint suppression saying why); the "
        "cross-shard 2PC verbs (ShardPrepare/ShardCommit/ShardAbort) "
        "must carry the coordinator's shard-map `epoch`.")

    #: constructor -> (required kwarg, positional index or None)
    FENCED = {
        "LifecycleCheckpoint": ("term", 0),
        "GeoCheckpoint": ("term", 0),
        "CommitKey": ("expect_object_id", None),
        "CommitFile": ("expect_object_id", None),
        "DeleteKey": ("expect_object_id", None),
        # cross-shard 2PC verbs: every phase record must carry the
        # coordinator's shard-map epoch (prepare fences on it; commit/
        # abort record it for the audit trail)
        "ShardPrepare": ("epoch", 3),
        "ShardCommit": ("epoch", 1),
        "ShardAbort": ("epoch", 1),
    }

    def check(self, src: SourceFile) -> Iterable[Finding]:
        if src.is_module("om", "requests.py") or \
                src.is_module("om", "fso.py") or \
                src.in_dirs("testing", "tools"):
            return
        for call, _fn in src.calls_with_fn:
            name = last_name(call.func)
            spec = self.FENCED.get(name)
            if spec is None:
                continue
            field_name, pos = spec
            has_kw = any(k.arg == field_name for k in call.keywords)
            has_pos = pos is not None and len(call.args) > pos
            if not (has_kw or has_pos):
                yield Finding(
                    self.id, src.display_path, call.lineno,
                    f"`{name}(...)` mutates term-fenced state but does "
                    f"not pass `{field_name}` — an unfenced commit can "
                    f"race a concurrent overwrite or a deposed leader",
                    span=_span(call))


# ------------------------------------------- dispatch-shape-stability
@register
class DispatchShapeStability(Rule):
    id = "dispatch-shape-stability"
    summary = ("jitted device programs must not be specialized on "
               "known-varying values (erasure pattern, batch width)")
    rationale = (
        "PR 1 made the recovery matrix a traced argument after per-"
        "erasure-pattern closures thrashed the jit cache; PR 6's bench "
        "bimodality was first-touch plan compiles hiding in the timed "
        "region. A `static_argnames` entry or an lru_cache key that "
        "varies per request compiles one XLA program per value.")

    VARYING = {"erased", "valid", "pattern", "erasure",
               "erasure_pattern", "batch", "width", "batch_width",
               "n_stripes", "stripes", "lost", "survivors", "recovery"}
    ARRAY_CTORS = {"zeros", "ones", "empty", "full", "arange"}

    def check(self, src: SourceFile) -> Iterable[Finding]:
        for node in src.functions:
            yield from self._check_def(src, node)

    # -- helpers -------------------------------------------------------
    def _jit_call(self, call: ast.Call) -> Optional[ast.Call]:
        """The jax.jit(...) call inside `jax.jit(...)` or
        `functools.partial(jax.jit, ...)`, else None."""
        if last_name(call.func) == "jit":
            return call
        if last_name(call.func) == "partial" and call.args and \
                last_name(call.args[0]) == "jit":
            return call
        return None

    def _static_names(self, call: ast.Call,
                      fn=None) -> list[tuple[str, ast.AST]]:
        names: list[tuple[str, ast.AST]] = []
        params = []
        if fn is not None:
            params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                for s in ast.walk(kw.value):
                    if isinstance(s, ast.Constant) and \
                            isinstance(s.value, str):
                        names.append((s.value, kw.value))
            elif kw.arg == "static_argnums" and params:
                for s in ast.walk(kw.value):
                    if isinstance(s, ast.Constant) and \
                            isinstance(s.value, int) and \
                            0 <= s.value < len(params):
                        names.append((params[s.value], kw.value))
        return names

    def _is_lru_cached(self, fn) -> bool:
        for dec in fn.decorator_list:
            name = last_name(dec.func) if isinstance(dec, ast.Call) \
                else last_name(dec)
            if name in ("lru_cache", "cache"):
                return True
        return False

    def _has_jit_marker(self, fn) -> bool:
        for dec in fn.decorator_list:
            if last_name(dec) == "jit" or (
                    isinstance(dec, ast.Call)
                    and self._jit_call(dec) is not None):
                return True
        return False

    # -- checks --------------------------------------------------------
    def _check_def(self, src: SourceFile, fn) -> Iterable[Finding]:
        # (a) static_argnames/static_argnums naming a varying value —
        # on the decorator or any jit() call inside the body
        for dec in fn.decorator_list:
            if isinstance(dec, ast.Call) and \
                    self._jit_call(dec) is not None:
                for nm, where in self._static_names(dec, fn):
                    if nm in self.VARYING:
                        yield Finding(
                            self.id, src.display_path, where.lineno,
                            f"jit static arg `{nm}` is a known-varying "
                            f"value — every new value compiles a new "
                            f"XLA program; pass it as a traced array "
                            f"(the PR 1 decode-plan treatment)",
                            span=_span(where))
        decorator_calls = {id(d) for d in fn.decorator_list}
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and \
                    id(node) not in decorator_calls:
                jc = self._jit_call(node)
                if jc is not None and jc is node:
                    for nm, where in self._static_names(node):
                        if nm in self.VARYING:
                            yield Finding(
                                self.id, src.display_path, where.lineno,
                                f"jit static arg `{nm}` is a known-"
                                f"varying value — every new value "
                                f"compiles a new XLA program",
                                span=_span(where))

        # (b) an lru_cache'd factory keyed on a varying parameter that
        # builds a jitted program per call = per-value compile
        if self._is_lru_cached(fn):
            varying = [a.arg for a in
                       fn.args.posonlyargs + fn.args.args +
                       fn.args.kwonlyargs if a.arg in self.VARYING]
            if varying and self._contains_jit(fn):
                yield Finding(
                    self.id, src.display_path, fn.lineno,
                    f"lru_cache'd jit-program factory keyed on varying "
                    f"parameter(s) {', '.join(varying)} — each value "
                    f"compiles a distinct XLA program; make it a "
                    f"traced argument or bound the key space",
                    span=(fn.lineno, fn.lineno))

        # (c) array constructors inside a jitted def whose shape pulls a
        # varying closure variable (not a parameter, not a local)
        if self._has_jit_marker(fn):
            params = {a.arg for a in fn.args.posonlyargs + fn.args.args
                      + fn.args.kwonlyargs}
            local = set(params)
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        for nn in ast.walk(t):
                            if isinstance(nn, ast.Name):
                                local.add(nn.id)
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) and \
                        last_name(node.func) in self.ARRAY_CTORS and \
                        node.args:
                    shape = node.args[0]
                    for nn in ast.walk(shape):
                        if isinstance(nn, ast.Name) and \
                                nn.id not in local and \
                                nn.id in self.VARYING:
                            yield Finding(
                                self.id, src.display_path, nn.lineno,
                                f"array shape inside a jitted function "
                                f"uses closure-captured varying value "
                                f"`{nn.id}` — the program re-traces "
                                f"per value; derive shapes from traced "
                                f"operand `.shape`",
                                span=_span(node))

    def _contains_jit(self, fn) -> bool:
        for node in ast.walk(fn):
            if node is fn:
                continue
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if self._has_jit_marker(node):
                    return True
            if isinstance(node, ast.Call) and \
                    last_name(node.func) == "jit":
                return True
        return False


# ---------------------------------------------------- datapath-no-copy
@register
class DatapathNoCopy(Rule):
    id = "datapath-no-copy"
    summary = ("the wire-facing datapath modules must not materialize "
               "payload bytes: no `bytes(...)`, `.tobytes()`, or "
               "`.copy()` of a fresh buffer view")
    rationale = (
        "The zero-copy datapath contract: payloads travel as "
        "memoryviews/ndarray views over pooled buffers "
        "(codec/hostmem.py) from socket to chip. One stray "
        "`bytes(frame)` on a 4 MiB chunk silently doubles the memory "
        "traffic of every request that crosses it — exactly the class "
        "of regression the copies/moved registry exists to catch. "
        "Control-plane materializations (STATUS/JSON headers, the one "
        "copy a transport's type contract forces) carry a reasoned "
        "`# ozlint: allow[datapath-no-copy] -- why`.")

    #: the wire-facing modules under the zero-copy contract
    MODULES = {
        ("client", "native_dn.py"),
        ("client", "ec_writer.py"),
        ("client", "ec_reader.py"),
        ("net", "dn_service.py"),
    }
    #: `.copy()` on the RESULT of one of these producers is a fresh
    #: view being materialized (np.frombuffer(...).copy() & friends)
    VIEW_PRODUCERS = {"frombuffer", "payload_array", "asarray",
                      "ascontiguousarray", "as_array"}

    def check(self, src: SourceFile) -> Iterable[Finding]:
        if tuple(src.module_parts) not in self.MODULES:
            return
        for call, _fn in src.calls_with_fn:
            name = last_name(call.func)
            if isinstance(call.func, ast.Name) and name == "bytes":
                # bytes(8) preallocates, bytes() is empty — neither
                # copies a payload; bytes(buf) does
                if len(call.args) == 1 and not call.keywords and not (
                        isinstance(call.args[0], ast.Constant)
                        and isinstance(call.args[0].value, int)):
                    yield Finding(
                        self.id, src.display_path, call.lineno,
                        "`bytes(...)` materializes a payload copy — "
                        "keep the memoryview/ndarray view (pooled "
                        "lease), or suppress with a reason if this is "
                        "control-plane framing",
                        span=_span(call))
            elif name == "tobytes" and isinstance(call.func,
                                                  ast.Attribute):
                yield Finding(
                    self.id, src.display_path, call.lineno,
                    "`.tobytes()` copies the array — pass the array "
                    "itself (wire.pack and the socket layer take "
                    "buffer views)",
                    span=_span(call))
            elif name == "copy" and isinstance(call.func, ast.Attribute) \
                    and isinstance(call.func.value, ast.Call) and \
                    last_name(call.func.value.func) in self.VIEW_PRODUCERS:
                yield Finding(
                    self.id, src.display_path, call.lineno,
                    f"`{last_name(call.func.value.func)}(...).copy()` "
                    f"defeats the zero-copy view it just made — return "
                    f"the view; consumers that need ownership copy at "
                    f"their edge (counted)",
                    span=_span(call))


# ---------------------------------------------------- error-swallowing
@register
class ErrorSwallowing(Rule):
    id = "error-swallowing"
    summary = ("no bare `except:` and no `except ...: pass` on "
               "datapath/consensus modules")
    rationale = (
        "A swallowed exception on the datapath converts a loud failure "
        "into silent data loss or a wedged control loop (the class of "
        "bug the round-4 soak post-mortems dug out of replay paths). "
        "Handle it, log it, or suppress with a written reason.")

    DIRS = ("client", "codec", "net", "storage", "consensus", "scm",
            "om", "lifecycle", "parallel", "replication_geo")

    def check(self, src: SourceFile) -> Iterable[Finding]:
        if not src.in_dirs(*self.DIRS):
            return
        for node in src.nodes:
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                yield Finding(
                    self.id, src.display_path, node.lineno,
                    "bare `except:` catches SystemExit/KeyboardInterrupt "
                    "and hides the real error — name the exception",
                    span=(node.lineno, node.lineno))
                continue
            if all(isinstance(s, (ast.Pass, ast.Continue)) or
                   (isinstance(s, ast.Expr) and isinstance(
                       s.value, ast.Constant)) for s in node.body):
                yield Finding(
                    self.id, src.display_path, node.lineno,
                    "exception swallowed without handling or logging — "
                    "a datapath error must be handled, logged, or "
                    "suppressed with a reason",
                    span=(node.lineno, node.lineno))


# ------------------------------------------------------- bounded-queue
@register
class BoundedQueue(Rule):
    id = "bounded-queue"
    summary = ("server-side packages (net/, om/, scm/, gateway/, "
               "codec/) must not construct unbounded queue.Queue / "
               "deque instances")
    rationale = (
        "The overload-protection contract (ozone_tpu/admission): every "
        "queue a service hop feeds must have an explicit bound, because "
        "an unbounded queue accepts work faster than it drains and "
        "converts overload into memory growth + unbounded latency — the "
        "collapse mode admission control exists to prevent. DAGOR-style "
        "shedding only works if there is nowhere for excess work to "
        "hide. A queue whose depth is provably bounded by other "
        "machinery (an ack window, an admission gate upstream) carries "
        "a reasoned `# ozlint: allow[bounded-queue] -- why`.")

    DIRS = ("net", "om", "scm", "gateway", "codec")
    #: client-side modules that batch work for server hops — the slab
    #: packer's pending set is a server-feeding queue in client clothing
    MODULES = (("client", "slab.py"),)
    #: queue-class constructors taking maxsize as kwarg or first arg
    QUEUE_CTORS = {"Queue", "LifoQueue", "PriorityQueue"}

    def check(self, src: SourceFile) -> Iterable[Finding]:
        if not src.in_dirs(*self.DIRS) and not any(
                src.is_module(*m) for m in self.MODULES):
            return
        module_env = _ConstEnv()
        _collect_env(src.tree.body, module_env, recurse=False)
        envs: dict[int, _ConstEnv] = {}
        for call, fn in src.calls_with_fn:
            name = last_name(call.func)
            if name not in self.QUEUE_CTORS and name not in (
                    "SimpleQueue", "deque"):
                continue
            key = id(fn)
            env = envs.get(key)
            if env is None:
                env = envs[key] = _fn_env(module_env, fn)
            if name == "SimpleQueue":
                yield Finding(
                    self.id, src.display_path, call.lineno,
                    "`SimpleQueue()` cannot be bounded — use "
                    "`queue.Queue(maxsize=...)` so excess work is "
                    "refused, not accumulated",
                    span=_span(call))
            elif name in self.QUEUE_CTORS:
                bound = None
                if call.args:
                    bound = call.args[0]
                for kw in call.keywords:
                    if kw.arg == "maxsize":
                        bound = kw.value
                if bound is None:
                    yield self._unbounded(src, call, name,
                                          "no `maxsize`")
                else:
                    v = _fold(bound, env)
                    if v is not None and v <= 0:
                        yield self._unbounded(
                            src, call, name,
                            f"`maxsize={int(v)}` (non-positive = "
                            f"unlimited)")
            else:  # deque
                bound = call.args[1] if len(call.args) >= 2 else None
                for kw in call.keywords:
                    if kw.arg == "maxlen":
                        bound = kw.value
                if bound is None or (
                        isinstance(bound, ast.Constant)
                        and bound.value is None):
                    yield self._unbounded(src, call, "deque",
                                          "no `maxlen`")

    def _unbounded(self, src: SourceFile, call: ast.Call, ctor: str,
                   why: str) -> Finding:
        return Finding(
            self.id, src.display_path, call.lineno,
            f"unbounded `{ctor}(...)` on a server-side module ({why}) "
            f"— give it an explicit bound so overload is refused at "
            f"admission instead of accumulating, or suppress with the "
            f"reason the depth is bounded elsewhere",
            span=_span(call))


@register
class SpanOnDispatch(Rule):
    id = "span-on-dispatch"
    summary = ("codec device-dispatch sites run inside an active trace "
               "span; RPC handlers register only through net/rpc.py's "
               "span guard")
    rationale = (
        "The latency-attribution contract: every device dispatch edge "
        "(async compute launch, eager D2H, block_until_ready) must be "
        "bracketed by a span — or fabricate one with record_span / "
        "carry one with activate — or the slow-request flight recorder "
        "attributes that time to the parent and critical paths lie. "
        "Likewise add_generic_rpc_handlers outside net/rpc.py bypasses "
        "the server interceptor that opens the server-side span and "
        "extracts the wire trace context.")

    #: calls that hand work to (or synchronize with) the device — the
    #: edges the request-path critical path must be able to name
    DISPATCH_EDGES = {"_start_d2h", "copy_to_host_async",
                      "block_until_ready"}
    #: any of these inside the same function satisfies the invariant
    TRACE_CALLS = {"span", "record_span", "activate"}

    def check(self, src: SourceFile) -> Iterable[Finding]:
        # (b) handler registration anywhere but net/rpc.py dodges the
        # guard that wraps every handler in a server:<method> span
        if not src.is_module("net", "rpc.py"):
            for call, _fn in src.calls_with_fn:
                if last_name(call.func) == "add_generic_rpc_handlers":
                    yield Finding(
                        self.id, src.display_path, call.lineno,
                        "RPC handlers registered outside net/rpc.py "
                        "bypass the span guard (no server span, no "
                        "trace-context extraction) — register through "
                        "RpcServer.add_service",
                        span=_span(call))
        # (a) codec and mesh (parallel/) functions containing a
        # dispatch edge must trace — the mesh executor's dispatch loop
        # is a request-path stage like any codec dispatch
        if not src.in_dirs("codec", "parallel"):
            return
        edges_by_fn: dict[int, list[ast.Call]] = {}
        traced_fns: set[int] = set()
        fns: dict[int, ast.AST] = {}
        for call, fn in src.calls_with_fn:
            if fn is None:
                continue
            name = last_name(call.func)
            if name in self.DISPATCH_EDGES:
                fns[id(fn)] = fn
                edges_by_fn.setdefault(id(fn), []).append(call)
            elif name in self.TRACE_CALLS:
                traced_fns.add(id(fn))
        for key, edges in edges_by_fn.items():
            if key in traced_fns:
                continue
            first = min(edges, key=lambda c: c.lineno)
            fn_name = getattr(fns[key], "name", "<fn>")
            yield Finding(
                self.id, src.display_path, first.lineno,
                f"device dispatch in `{fn_name}` without an active "
                "span — wrap it in Tracer.span()/record_span() (or "
                "activate() a carried context) so the flight "
                "recorder's critical path can name this stage",
                span=(first.lineno, first.lineno))
