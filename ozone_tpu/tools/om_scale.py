"""OM metadata-at-scale measurement (round-5 verdict item 2).

Fabricates an N-key OM store with the dbgen generator (freon GeneratorOm
analog — the reference uses it to build billion-key DBs), then measures
the operations whose latency must stay flat as the namespace grows:

- point lookup (OmMetadataManager getKeyTable().get analog)
- paged list-with-prefix (listKeys iterator page)
- open+commit of NEW keys on the populated store (namespace write path)
- quota repair wall time + the worst concurrent-writer stall while it
  runs (the round-5 paged repair must not block the apply path)
- snapshot create + incremental snapdiff

Usage:  python -m ozone_tpu.tools.om_scale --keys 1000000 \
            [--db /dev/shm/omscale.db] [--skip-snapshot]

Prints one JSON object; PERF.md's "OM at scale" table records the runs.
"""

from __future__ import annotations

import argparse
import json
import random
import statistics
import sys
import threading
import time
from pathlib import Path


def _pct(xs, p):
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(len(xs) * p))]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--keys", type=int, default=1_000_000)
    ap.add_argument("--db", default="/dev/shm/omscale.db")
    ap.add_argument("--lookups", type=int, default=2000)
    ap.add_argument("--commits", type=int, default=2000)
    ap.add_argument("--skip-snapshot", action="store_true")
    args = ap.parse_args(argv)

    from ozone_tpu.om.metadata import OMMetadataStore, key_key
    from ozone_tpu.om.om import OzoneManager
    from ozone_tpu.scm.scm import StorageContainerManager
    from ozone_tpu.tools import freon

    out: dict = {"keys": args.keys}
    db = Path(args.db)
    if db.exists():
        db.unlink()

    t0 = time.monotonic()
    rep = freon.dbgen(db, n_keys=args.keys)
    out["dbgen_s"] = round(time.monotonic() - t0, 1)
    out["dbgen_keys_per_s"] = round(args.keys / out["dbgen_s"])
    print(f"# dbgen: {args.keys} keys in {out['dbgen_s']}s "
          f"({out['dbgen_keys_per_s']}/s), failures={rep.failures}",
          file=sys.stderr)

    t0 = time.monotonic()
    store = OMMetadataStore(db)
    out["open_s"] = round(time.monotonic() - t0, 2)

    # ---- point lookups over random existing keys
    rng = random.Random(7)
    ids = [rng.randrange(args.keys) for _ in range(args.lookups)]
    lat = []
    for i in ids:
        kk = key_key("genvol", "genbucket", f"gen/{i // 1000}/key-{i}")
        t0 = time.perf_counter()
        row = store.get("keys", kk)
        lat.append((time.perf_counter() - t0) * 1e6)
        assert row is not None, kk
    out["lookup_us_p50"] = round(statistics.median(lat), 1)
    out["lookup_us_p99"] = round(_pct(lat, 0.99), 1)

    # ---- paged listing under a prefix (1000-row pages, the listKeys
    # backend), from cold starts spread across the namespace
    lat = []
    for i in range(50):
        pfx = f"/genvol/genbucket/gen/{rng.randrange(args.keys // 1000)}/"
        t0 = time.perf_counter()
        rows = store.iterate_range("keys", pfx, limit=1000)
        lat.append((time.perf_counter() - t0) * 1e3)
        assert rows
    out["list_page_ms_p50"] = round(statistics.median(lat), 2)
    out["list_page_ms_p99"] = round(_pct(lat, 0.99), 2)
    store.close()

    # ---- OM on top of the populated store: new-key open+commit
    scm = StorageContainerManager(stale_after_s=1e6, dead_after_s=2e6)
    for i in range(5):
        scm.register_datanode(f"dn{i}")
    om = OzoneManager(db, scm)
    t0 = time.monotonic()
    for i in range(args.commits):
        s = om.open_key("genvol", "genbucket", f"fresh/key-{i}")
        om.commit_key(s, [], 0)
    dt = time.monotonic() - t0
    out["commit_ops_per_s"] = round(args.commits / dt)

    # ---- paged quota repair + worst concurrent-writer stall
    stalls = []
    stop = threading.Event()

    def writer():
        n = 0
        while not stop.is_set():
            t0 = time.perf_counter()
            s = om.open_key("genvol", "genbucket", f"during/key-{n}")
            om.commit_key(s, [], 0)
            stalls.append(time.perf_counter() - t0)
            n += 1
            time.sleep(0.005)

    th = threading.Thread(target=writer, daemon=True)
    th.start()
    t0 = time.monotonic()
    rep = om.repair_quota("genvol")
    out["repair_quota_s"] = round(time.monotonic() - t0, 2)
    stop.set()
    th.join(timeout=10)
    out["repair_writer_stall_ms_max"] = round(max(stalls) * 1e3, 1)
    out["repair_key_count"] = rep["volume_key_count"]

    # ---- snapshots: create + incremental diff of 10 changes
    if not args.skip_snapshot:
        t0 = time.monotonic()
        om.create_snapshot("genvol", "genbucket", "s1")
        out["snapshot_create_s"] = round(time.monotonic() - t0, 2)
        for i in range(10):
            s = om.open_key("genvol", "genbucket", f"diff/key-{i}")
            om.commit_key(s, [], 0)
        om.create_snapshot("genvol", "genbucket", "s2")
        t0 = time.monotonic()
        diff = om.snapshot_diff("genvol", "genbucket", "s1", "s2")
        out["snapdiff_10changes_s"] = round(time.monotonic() - t0, 2)
        out["snapdiff_mode"] = diff.get("mode")
        out["snapdiff_entries"] = (
            len(diff.get("added", [])) + len(diff.get("deleted", []))
            + len(diff.get("modified", [])) + len(diff.get("renamed", [])))
    om.close()
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
