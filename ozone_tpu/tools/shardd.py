"""shardd: run ONE shard of the sharded metadata plane as a process.

The production deployment shape for the sharded OM: one `shardd`
process per shard ring member, each carrying its slice of the
namespace plus the replicated `system/shard_config` ownership row, with
the full address book baked into the shard map it serves to routing
clients (`GetShardMap` is answered by any shard, so clients can
bootstrap from whichever address they were given).

    python -m ozone_tpu.tools.shardd \
        --base /var/ozone/s0 --shard-id s0 \
        --shards s0=10.0.0.1:9860,s1=10.0.0.2:9860 --epoch 1

Every process must be started with the SAME --shards book and --epoch,
or the rings will disagree about slot ownership (the per-request
`check_shard` gate turns that misconfiguration into SHARD_MOVED
rejections rather than silent misplacement). `bench.py` boots its
shard-scaling measurement through this entrypoint — one process per
ring, the only configuration in which CPython can demonstrate
horizontal metadata scaling (a single interpreter serializes all rings
on the GIL).
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading
from pathlib import Path


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="shardd", description="one shard of the sharded OM plane")
    ap.add_argument("--base", required=True,
                    help="data directory for this shard's OM/SCM state")
    ap.add_argument("--shard-id", required=True,
                    help="this process's shard id (must appear in --shards)")
    ap.add_argument("--shards", required=True,
                    help="full address book: sid=host:port,sid=host:port")
    ap.add_argument("--epoch", type=int, default=1)
    ap.add_argument("--slot-count", type=int, default=None)
    args = ap.parse_args(argv)

    from ozone_tpu.net.daemons import ScmOmDaemon
    from ozone_tpu.om.sharding.shardmap import SLOT_COUNT, ShardMap

    book: dict[str, str] = {}
    for part in args.shards.split(","):
        sid, _, addr = part.partition("=")
        if not sid or not addr:
            ap.error(f"bad --shards entry {part!r} (want sid=host:port)")
        book[sid] = addr
    if args.shard_id not in book:
        ap.error(f"--shard-id {args.shard_id!r} not in --shards")
    m = ShardMap.uniform(list(book), epoch=args.epoch,
                         addresses=book,
                         slot_count=args.slot_count or SLOT_COUNT)
    daemon = ScmOmDaemon(
        Path(args.base) / "om.db",
        port=int(book[args.shard_id].rsplit(":", 1)[1]),
        stale_after_s=1000.0,
        dead_after_s=2000.0,
        background_interval_s=0.5,
        shard_config={
            "epoch": m.epoch,
            "shard_id": args.shard_id,
            "slot_count": m.slot_count,
            "owned": m.owned_slots(args.shard_id),
        },
        shard_map=m.to_json(),
    )
    daemon.start()
    print(f"shardd {args.shard_id} serving {book[args.shard_id]} "
          f"(epoch {m.epoch}, "
          f"{len(m.owned_slots(args.shard_id))}/{m.slot_count} slots)",
          flush=True)

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    daemon.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
