"""Structured audit logging.

Mirror of the reference's AuditLogger (hadoop-hdds/framework
ozone/audit/AuditLogger.java): every namespace/admin operation emits a
structured record (action, params, outcome) to a dedicated logger; parsers
can consume the line format (tools/audit parser analog).
"""

from __future__ import annotations

import json
import logging
import time
from typing import Any


class AuditLogger:
    def __init__(self, component: str):
        self.component = component
        self._log = logging.getLogger(f"audit.{component}")

    def log(self, action: str, params: dict[str, Any], ok: bool = True,
            error: str = "", user: str = "root") -> None:
        safe_params = {
            k: v
            for k, v in params.items()
            if isinstance(v, (str, int, float, bool, type(None)))
        }
        record = {
            "ts": time.time(),
            "user": user,
            "action": action,
            "params": safe_params,
            "result": "SUCCESS" if ok else "FAILURE",
        }
        if error:
            record["error"] = error
        self._log.info("%s", json.dumps(record, sort_keys=True))
