"""x509 certificate authority + per-role cert clients + gRPC TLS material.

Role analog of the reference's security infrastructure
(hadoop-hdds/framework hdds/security/x509/: SCM hosts a root CA;
every service role runs a certificate client that generates a keypair,
submits a CSR to the SCM CA, and stores the signed chain; gRPC datapath
and replication servers then run TLS with mutual authentication).

Here the CA is a library the SCM daemon owns: `CertificateAuthority`
self-signs a root, `CertificateClient.enroll()` produces a CSR and stores
the signed cert + chain under the role's metadata dir, and
`TlsMaterial.server()/client()` yields the grpc credential objects the
net/rpc layer plugs in. Kerberos/UGI has no equivalent here by design —
caller identity rides on block/container tokens (utils/security.py) and
mTLS peer names, the way the reference's token-only deployments work.
"""

from __future__ import annotations

import datetime
import ipaddress
from dataclasses import dataclass
from pathlib import Path
from typing import Optional

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.x509.oid import ExtendedKeyUsageOID, NameOID

_ONE_DAY = datetime.timedelta(days=1)


def _write_private(path: Path, data: bytes) -> None:
    """Owner-only private-key files (the reference stores keys 0600 via
    its KeyStorage permissions checks)."""
    import os

    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    try:
        os.write(fd, data)
    finally:
        os.close(fd)


def _name(common_name: str, org: str = "ozone-tpu") -> x509.Name:
    return x509.Name([
        x509.NameAttribute(NameOID.ORGANIZATION_NAME, org),
        x509.NameAttribute(NameOID.COMMON_NAME, common_name),
    ])


def _new_key():
    # P-256: small certs, fast handshakes; the reference defaults to RSA
    # but its SecurityConfig lets deployments pick — ECDSA is the modern
    # choice and half the handshake cost on the datapath
    return ec.generate_private_key(ec.SECP256R1())


def _pem_key(key) -> bytes:
    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    )


class CertificateAuthority:
    """Self-signed root CA (the SCM's DefaultCAServer analog).

    Persists root key + cert under `root_dir`; `sign_csr` issues leaf
    certificates with clientAuth+serverAuth EKUs so one cert serves a
    role's server and client sides (as the reference's service certs do).
    """

    def __init__(self, root_dir: Path, cluster_id: str = "ozone-tpu",
                 valid_days: int = 3650):
        self.root_dir = Path(root_dir)
        self.root_dir.mkdir(parents=True, exist_ok=True)
        self.valid_days = valid_days
        key_path = self.root_dir / "ca.key.pem"
        cert_path = self.root_dir / "ca.cert.pem"
        if key_path.exists() and cert_path.exists():
            self.key = serialization.load_pem_private_key(
                key_path.read_bytes(), password=None)
            self.cert = x509.load_pem_x509_certificate(cert_path.read_bytes())
        else:
            self.key = _new_key()
            now = datetime.datetime.now(datetime.timezone.utc)
            name = _name(f"{cluster_id}-root-ca")
            self.cert = (
                x509.CertificateBuilder()
                .subject_name(name)
                .issuer_name(name)
                .public_key(self.key.public_key())
                .serial_number(x509.random_serial_number())
                .not_valid_before(now - _ONE_DAY)
                .not_valid_after(now + datetime.timedelta(days=valid_days))
                .add_extension(x509.BasicConstraints(ca=True, path_length=1),
                               critical=True)
                .add_extension(
                    x509.KeyUsage(
                        digital_signature=True, key_cert_sign=True,
                        crl_sign=True, content_commitment=False,
                        key_encipherment=False, data_encipherment=False,
                        key_agreement=False, encipher_only=False,
                        decipher_only=False),
                    critical=True)
                .sign(self.key, hashes.SHA256())
            )
            _write_private(key_path, _pem_key(self.key))
            cert_path.write_bytes(self.cert.public_bytes(
                serialization.Encoding.PEM))

    @property
    def root_pem(self) -> bytes:
        return self.cert.public_bytes(serialization.Encoding.PEM)

    def sign_csr(self, csr_pem: bytes, valid_days: int = 398) -> bytes:
        """Issue a leaf cert for a CSR (DefaultApprover analog: SANs are
        taken from the CSR; subject is preserved)."""
        csr = x509.load_pem_x509_csr(csr_pem)
        if not csr.is_signature_valid:
            raise ValueError("CSR signature invalid")
        now = datetime.datetime.now(datetime.timezone.utc)
        builder = (
            x509.CertificateBuilder()
            .subject_name(csr.subject)
            .issuer_name(self.cert.subject)
            .public_key(csr.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - _ONE_DAY)
            .not_valid_after(now + datetime.timedelta(days=valid_days))
            .add_extension(x509.BasicConstraints(ca=False, path_length=None),
                           critical=True)
            .add_extension(
                x509.ExtendedKeyUsage([
                    ExtendedKeyUsageOID.SERVER_AUTH,
                    ExtendedKeyUsageOID.CLIENT_AUTH,
                ]),
                critical=False)
        )
        try:
            san = csr.extensions.get_extension_for_class(
                x509.SubjectAlternativeName)
            builder = builder.add_extension(san.value, critical=False)
        except x509.ExtensionNotFound:
            pass
        cert = builder.sign(self.key, hashes.SHA256())
        return cert.public_bytes(serialization.Encoding.PEM)


class CertificateClient:
    """Per-role cert client (DNCertificateClient / OMCertificateClient
    analog): keypair + CSR generation, enrollment against a CA, PEM
    storage under the role dir."""

    def __init__(self, role_dir: Path, role: str,
                 hostnames: Optional[list[str]] = None):
        self.role_dir = Path(role_dir)
        self.role_dir.mkdir(parents=True, exist_ok=True)
        self.role = role
        self.hostnames = hostnames or ["localhost", "127.0.0.1"]
        self.key_path = self.role_dir / f"{role}.key.pem"
        self.cert_path = self.role_dir / f"{role}.cert.pem"
        self.ca_path = self.role_dir / "ca.cert.pem"
        if self.key_path.exists():
            self.key = serialization.load_pem_private_key(
                self.key_path.read_bytes(), password=None)
        else:
            self.key = _new_key()
            _write_private(self.key_path, _pem_key(self.key))

    def make_csr(self) -> bytes:
        sans: list[x509.GeneralName] = []
        for h in self.hostnames:
            try:
                sans.append(x509.IPAddress(ipaddress.ip_address(h)))
            except ValueError:
                sans.append(x509.DNSName(h))
        csr = (
            x509.CertificateSigningRequestBuilder()
            .subject_name(_name(self.role))
            .add_extension(x509.SubjectAlternativeName(sans), critical=False)
            .sign(self.key, hashes.SHA256())
        )
        return csr.public_bytes(serialization.Encoding.PEM)

    def install(self, cert_pem: bytes, ca_pem: bytes) -> None:
        self.cert_path.write_bytes(cert_pem)
        self.ca_path.write_bytes(ca_pem)

    def enroll(self, ca: CertificateAuthority) -> None:
        """In-process enrollment (daemons co-located with the SCM CA or
        test clusters); remote enrollment ships make_csr() over the SCM
        RPC and installs the response the same way."""
        self.install(ca.sign_csr(self.make_csr()), ca.root_pem)

    def enroll_remote(self, address: str,
                      secret: Optional[str] = None) -> None:
        """Enroll against the SCM CA's plaintext enrollment endpoint
        (SCMSecurityProtocol getDataNodeCertificate analog; the
        reference authenticates the CSR channel with Kerberos — here an
        optional shared bootstrap secret gates signing)."""
        from ozone_tpu.net import wire
        from ozone_tpu.net.rpc import RpcChannel

        ch = RpcChannel(address)
        try:
            resp = ch.call(
                ENROLL_SERVICE, "SignCsr",
                wire.pack({"csr": self.make_csr().decode(),
                           "secret": secret}))
            m, _ = wire.unpack(resp)
            self.install(m["cert"].encode(), m["ca"].encode())
        finally:
            ch.close()

    @property
    def enrolled(self) -> bool:
        return self.cert_path.exists() and self.ca_path.exists()

    def tls(self) -> "TlsMaterial":
        if not self.enrolled:
            raise RuntimeError(f"{self.role}: not enrolled")
        return TlsMaterial(
            key_pem=self.key_path.read_bytes(),
            cert_pem=self.cert_path.read_bytes(),
            ca_pem=self.ca_path.read_bytes(),
        )


ENROLL_SERVICE = "ozone.tpu.CertEnrollment"


class EnrollmentService:
    """CSR-signing endpoint served PLAINTEXT on its own RpcServer (the
    chicken-and-egg breaker: a fresh datanode has no cert yet, so it
    cannot reach the mTLS plane; the reference solves this with a
    Kerberos-authenticated SCMSecurityProtocol — here an optional shared
    `secret` gates who may obtain a certificate, and everything issued
    is a leaf cert whose trust is still rooted in the SCM CA)."""

    def __init__(self, ca: CertificateAuthority, server,
                 secret: Optional[str] = None):
        self.ca = ca
        self.secret = secret
        server.add_service(ENROLL_SERVICE, {
            "SignCsr": self._sign,
            "RootCert": self._root,
        })

    def _sign(self, req: bytes) -> bytes:
        import hmac as _hmac

        from ozone_tpu.net import wire

        m, _ = wire.unpack(req)
        if self.secret is not None and not _hmac.compare_digest(
                str(m.get("secret") or ""), self.secret):
            raise PermissionError("bad enrollment secret")
        cert = self.ca.sign_csr(m["csr"].encode())
        return wire.pack({"cert": cert.decode(),
                          "ca": self.ca.root_pem.decode()})

    def _root(self, req: bytes) -> bytes:
        from ozone_tpu.net import wire

        return wire.pack({"ca": self.ca.root_pem.decode()})


@dataclass(frozen=True)
class TlsMaterial:
    """PEM bundle -> grpc credentials (the SecurityConfig/GrpcTlsConfig
    analog). mutual=True enforces client certs (the reference's
    datanode<->datanode replication and Ratis TLS mode)."""

    key_pem: bytes
    cert_pem: bytes
    ca_pem: bytes

    def server_credentials(self, mutual: bool = True):
        import grpc

        return grpc.ssl_server_credentials(
            [(self.key_pem, self.cert_pem)],
            root_certificates=self.ca_pem if mutual else None,
            require_client_auth=mutual,
        )

    def channel_credentials(self):
        import grpc

        return grpc.ssl_channel_credentials(
            root_certificates=self.ca_pem,
            private_key=self.key_pem,
            certificate_chain=self.cert_pem,
        )
