"""x509 certificate authority + per-role cert clients + gRPC TLS material.

Role analog of the reference's security infrastructure
(hadoop-hdds/framework hdds/security/x509/: SCM hosts a root CA;
every service role runs a certificate client that generates a keypair,
submits a CSR to the SCM CA, and stores the signed chain; gRPC datapath
and replication servers then run TLS with mutual authentication).

Here the CA is a library the SCM daemon owns: `CertificateAuthority`
self-signs a root, `CertificateClient.enroll()` produces a CSR and stores
the signed cert + chain under the role's metadata dir, and
`TlsMaterial.server()/client()` yields the grpc credential objects the
net/rpc layer plugs in. Kerberos/UGI has no equivalent here by design —
caller identity rides on block/container tokens (utils/security.py) and
mTLS peer names, the way the reference's token-only deployments work.
"""

from __future__ import annotations

import datetime
import ipaddress
import json
import threading
from dataclasses import dataclass
from pathlib import Path
from typing import Optional

try:
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import ExtendedKeyUsageOID, NameOID

    HAVE_CRYPTOGRAPHY = True
except ImportError:  # pragma: no cover - depends on the image
    # `cryptography` is an optional dependency: insecure deployments
    # (and minimal images) never need x509 material. Secure mode fails
    # with an actionable error at CA/client construction instead of an
    # opaque import error deep inside daemon bring-up; tests skip via
    # pytest.importorskip("cryptography").
    x509 = hashes = serialization = ec = None
    ExtendedKeyUsageOID = NameOID = None
    HAVE_CRYPTOGRAPHY = False


def require_cryptography(what: str) -> None:
    if not HAVE_CRYPTOGRAPHY:
        raise RuntimeError(
            f"{what} requires the optional `cryptography` module, "
            "which is not installed in this image; install it or run "
            "without secure mode (secure=False)")


_ONE_DAY = datetime.timedelta(days=1)


def _write_private(path: Path, data: bytes) -> None:
    """Owner-only private-key files (the reference stores keys 0600 via
    its KeyStorage permissions checks)."""
    import os

    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    try:
        os.write(fd, data)
    finally:
        os.close(fd)


def _name(common_name: str, org: str = "ozone-tpu") -> x509.Name:
    return x509.Name([
        x509.NameAttribute(NameOID.ORGANIZATION_NAME, org),
        x509.NameAttribute(NameOID.COMMON_NAME, common_name),
    ])


def _new_key():
    # P-256: small certs, fast handshakes; the reference defaults to RSA
    # but its SecurityConfig lets deployments pick — ECDSA is the modern
    # choice and half the handshake cost on the datapath
    return ec.generate_private_key(ec.SECP256R1())


def _pem_key(key) -> bytes:
    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    )


class CertificateAuthority:
    """Self-signed root CA (the SCM's DefaultCAServer analog).

    Persists root key + cert under `root_dir`; `sign_csr` issues leaf
    certificates with clientAuth+serverAuth EKUs so one cert serves a
    role's server and client sides (as the reference's service certs do).
    """

    def __init__(self, root_dir: Path, cluster_id: str = "ozone-tpu",
                 valid_days: int = 3650):
        require_cryptography("CertificateAuthority (secure mode)")
        self.root_dir = Path(root_dir)
        self.root_dir.mkdir(parents=True, exist_ok=True)
        self.valid_days = valid_days
        self.cluster_id = cluster_id
        #: serializes issued.json / crl.json read-modify-writes: the
        #: enrollment endpoint signs from a 16-worker thread pool, and
        #: a lost issuance record would make that cert unrevocable
        self._ledger_lock = threading.Lock()
        key_path = self.root_dir / "ca.key.pem"
        cert_path = self.root_dir / "ca.cert.pem"
        gen_path = self.root_dir / "generation"
        self.generation = (int(gen_path.read_text())
                           if gen_path.exists() else 0)
        if key_path.exists() and cert_path.exists():
            self.key = serialization.load_pem_private_key(
                key_path.read_bytes(), password=None)
            self.cert = x509.load_pem_x509_certificate(cert_path.read_bytes())
        else:
            self.key = _new_key()
            now = datetime.datetime.now(datetime.timezone.utc)
            # each rotation generation gets a DISTINCT subject DN:
            # trust stores select anchors by subject, and two roots
            # sharing one subject make the TLS stack verify against
            # whichever key it finds first (BAD_SIGNATURE failures)
            suffix = f"-g{self.generation}" if self.generation else ""
            name = _name(f"{cluster_id}-root-ca{suffix}")
            self.cert = (
                x509.CertificateBuilder()
                .subject_name(name)
                .issuer_name(name)
                .public_key(self.key.public_key())
                .serial_number(x509.random_serial_number())
                .not_valid_before(now - _ONE_DAY)
                .not_valid_after(now + datetime.timedelta(days=valid_days))
                .add_extension(x509.BasicConstraints(ca=True, path_length=1),
                               critical=True)
                .add_extension(
                    x509.KeyUsage(
                        digital_signature=True, key_cert_sign=True,
                        crl_sign=True, content_commitment=False,
                        key_encipherment=False, data_encipherment=False,
                        key_agreement=False, encipher_only=False,
                        decipher_only=False),
                    critical=True)
                .sign(self.key, hashes.SHA256())
            )
            _write_private(key_path, _pem_key(self.key))
            cert_path.write_bytes(self.cert.public_bytes(
                serialization.Encoding.PEM))

    @property
    def root_pem(self) -> bytes:
        """Trust bundle: the active root, plus the previous root while a
        rotation is in flight (leaves issued by either still verify)."""
        pem = self.cert.public_bytes(serialization.Encoding.PEM)
        prev = self.root_dir / "ca.cert.prev.pem"
        if prev.exists():
            pem += prev.read_bytes()
        return pem

    def rotate_root(self) -> None:
        """Root-CA rotation (reference: root-CA rotation in
        hadoop-hdds/framework security/x509): mint a NEW root key+cert,
        keep the old root in the trust bundle so existing leaf certs
        keep verifying, and issue all future leaves from the new root.
        Once every leaf has renewed, `retire_previous_root()` drops the
        old trust anchor."""
        prev = self.root_dir / "ca.cert.prev.pem"
        if prev.exists():
            # a second rotation would silently drop the generation-N-1
            # anchor while leaves issued under it may still be live,
            # failing mutual TLS cluster-wide; the operator must finish
            # the in-flight transition (all leaves renewed, then
            # retire_previous_root) before rotating again
            raise RuntimeError(
                "root rotation already in flight: previous root not "
                "yet retired (call retire_previous_root once every "
                "leaf has renewed under the new root)")
        old_cert = self.root_dir / "ca.cert.pem"
        prev.write_bytes(old_cert.read_bytes())
        (self.root_dir / "generation").write_text(
            str(self.generation + 1))
        (self.root_dir / "ca.key.pem").unlink()
        old_cert.unlink()
        # re-run the constructor's bootstrap path for the new root
        self.__init__(self.root_dir, cluster_id=self.cluster_id,
                      valid_days=self.valid_days)

    def retire_previous_root(self) -> None:
        prev = self.root_dir / "ca.cert.prev.pem"
        if prev.exists():
            prev.unlink()

    def sign_csr(self, csr_pem: bytes, valid_days: int = 398) -> bytes:
        """Issue a leaf cert for a CSR (DefaultApprover analog: SANs are
        taken from the CSR; subject is preserved)."""
        csr = x509.load_pem_x509_csr(csr_pem)
        if not csr.is_signature_valid:
            raise ValueError("CSR signature invalid")
        now = datetime.datetime.now(datetime.timezone.utc)
        builder = (
            x509.CertificateBuilder()
            .subject_name(csr.subject)
            .issuer_name(self.cert.subject)
            .public_key(csr.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - _ONE_DAY)
            .not_valid_after(now + datetime.timedelta(days=valid_days))
            .add_extension(x509.BasicConstraints(ca=False, path_length=None),
                           critical=True)
            .add_extension(
                x509.ExtendedKeyUsage([
                    ExtendedKeyUsageOID.SERVER_AUTH,
                    ExtendedKeyUsageOID.CLIENT_AUTH,
                ]),
                critical=False)
        )
        try:
            san = csr.extensions.get_extension_for_class(
                x509.SubjectAlternativeName)
            builder = builder.add_extension(san.value, critical=False)
        except x509.ExtensionNotFound:
            pass
        cert = builder.sign(self.key, hashes.SHA256())
        self._log_issued(cert, csr.subject)
        return cert.public_bytes(serialization.Encoding.PEM)

    # --------------------------------------------------- issued certs / CRL
    def _issued_path(self):
        return self.root_dir / "issued.json"

    def _crl_path(self):
        return self.root_dir / "crl.json"

    def _log_issued(self, cert: x509.Certificate, subject) -> None:
        with self._ledger_lock:
            p = self._issued_path()
            rows = json.loads(p.read_text()) if p.exists() else []
            rows.append({
                "serial": cert.serial_number,
                "subject": subject.rfc4514_string(),
                "not_after": cert.not_valid_after_utc.isoformat(),
            })
            p.write_text(json.dumps(rows))

    def issued(self) -> list[dict]:
        p = self._issued_path()
        rows = json.loads(p.read_text()) if p.exists() else []
        crl = self.crl()
        for r in rows:
            r["revoked"] = r["serial"] in crl
        return rows

    def crl(self) -> set:
        p = self._crl_path()
        return set(json.loads(p.read_text())) if p.exists() else set()

    def revoke(self, serial: int) -> None:
        """Add a leaf serial to the CRL (the reference's SCM CA cert
        revocation). Distribution rides the MAC'd trust-refresh
        responses; enforcement happens per-RPC on every server that
        installed the CRL — revocation takes effect without waiting for
        the cert to expire."""
        with self._ledger_lock:
            p = self._issued_path()
            rows = json.loads(p.read_text()) if p.exists() else []
            if not any(r["serial"] == serial for r in rows):
                raise ValueError(
                    f"serial {serial} was never issued here")
            crl = self.crl()
            crl.add(serial)
            self._crl_path().write_text(json.dumps(sorted(crl)))


class CertificateClient:
    """Per-role cert client (DNCertificateClient / OMCertificateClient
    analog): keypair + CSR generation, enrollment against a CA, PEM
    storage under the role dir."""

    def __init__(self, role_dir: Path, role: str,
                 hostnames: Optional[list[str]] = None,
                 valid_days: int = 398):
        require_cryptography("CertificateClient (secure mode)")
        self.role_dir = Path(role_dir)
        self.role_dir.mkdir(parents=True, exist_ok=True)
        self.role = role
        self.hostnames = hostnames or ["localhost", "127.0.0.1"]
        #: requested leaf lifetime for in-process enrollment/renewal
        #: (short-lived certs + auto-renewal are the hardened posture)
        self.valid_days = valid_days
        self.key_path = self.role_dir / f"{role}.key.pem"
        self.cert_path = self.role_dir / f"{role}.cert.pem"
        self.ca_path = self.role_dir / "ca.cert.pem"
        if self.key_path.exists():
            self.key = serialization.load_pem_private_key(
                self.key_path.read_bytes(), password=None)
        else:
            self.key = _new_key()
            _write_private(self.key_path, _pem_key(self.key))

    def make_csr(self, key=None) -> bytes:
        sans: list[x509.GeneralName] = []
        for h in self.hostnames:
            try:
                sans.append(x509.IPAddress(ipaddress.ip_address(h)))
            except ValueError:
                sans.append(x509.DNSName(h))
        csr = (
            x509.CertificateSigningRequestBuilder()
            .subject_name(_name(self.role))
            .add_extension(x509.SubjectAlternativeName(sans), critical=False)
            .sign(key or self.key, hashes.SHA256())
        )
        return csr.public_bytes(serialization.Encoding.PEM)

    def install(self, cert_pem: bytes, ca_pem: bytes,
                crl: Optional[list] = None) -> None:
        self.cert_path.write_bytes(cert_pem)
        self.ca_path.write_bytes(ca_pem)
        if crl is not None:
            self._install_crl(crl)

    @property
    def crl_path(self):
        return self.role_dir / "crl.json"

    def crl(self) -> set:
        p = self.crl_path
        return set(json.loads(p.read_text())) if p.exists() else set()

    def _install_crl(self, crl: list) -> bool:
        new = set(crl)
        if new == self.crl():
            return False
        self.crl_path.write_text(json.dumps(sorted(new)))
        return True

    def enroll(self, ca: CertificateAuthority) -> None:
        """In-process enrollment (daemons co-located with the SCM CA or
        test clusters); remote enrollment ships make_csr() over the SCM
        RPC and installs the response the same way."""
        self.install(ca.sign_csr(self.make_csr(),
                                 valid_days=self.valid_days),
                     ca.root_pem, crl=sorted(ca.crl()))

    @staticmethod
    def _require_mac(secret: Optional[str], domain: bytes,
                     payload: bytes, mac: Optional[str]) -> None:
        """When this client holds the bootstrap secret, the server's
        response MUST carry a matching HMAC — the enrollment plane is
        plaintext, and an unauthenticated response would let a MITM
        substitute a rogue CA bundle (trust poisoning)."""
        import hmac as _hmac

        if secret is None:
            return
        expect = _hmac.new(secret.encode(), domain + payload,
                           "sha256").hexdigest()
        if not (mac and _hmac.compare_digest(expect, mac)):
            raise PermissionError(
                "enrollment response failed authentication (missing or "
                "bad response MAC) — possible MITM on the CSR channel")

    def _sign_csr_remote(
            self, address: str, csr: bytes,
            secret: Optional[str]) -> tuple[bytes, bytes, list]:
        from ozone_tpu.net import wire
        from ozone_tpu.net.rpc import RpcChannel

        ch = RpcChannel(address)
        try:
            resp = ch.call(
                ENROLL_SERVICE, "SignCsr",
                wire.pack({"csr": csr.decode(), "secret": secret}))
            m, _ = wire.unpack(resp)
        finally:
            ch.close()
        cert, ca_pem = m["cert"].encode(), m["ca"].encode()
        crl = m.get("crl", [])
        self._require_mac(
            secret, b"enroll:",
            csr + cert + ca_pem + json.dumps(sorted(crl)).encode(),
            m.get("mac"))
        return cert, ca_pem, crl

    def enroll_remote(self, address: str,
                      secret: Optional[str] = None) -> None:
        """Enroll against the SCM CA's plaintext enrollment endpoint
        (SCMSecurityProtocol getDataNodeCertificate analog; the
        reference authenticates the CSR channel with Kerberos — here
        the shared bootstrap secret both gates signing server-side and
        authenticates the response client-side)."""
        csr = self.make_csr()
        cert, ca_pem, crl = self._sign_csr_remote(address, csr, secret)
        self.install(cert, ca_pem, crl=crl)

    @property
    def enrolled(self) -> bool:
        return self.cert_path.exists() and self.ca_path.exists()

    # ------------------------------------------------------- lifecycle
    @property
    def cert(self) -> x509.Certificate:
        return x509.load_pem_x509_certificate(self.cert_path.read_bytes())

    @property
    def expires_at(self) -> datetime.datetime:
        return self.cert.not_valid_after_utc

    def remaining_fraction(self) -> float:
        """Fraction of the cert's lifetime still ahead (0.0 = expired)."""
        c = self.cert
        now = datetime.datetime.now(datetime.timezone.utc)
        total = (c.not_valid_after_utc
                 - c.not_valid_before_utc).total_seconds()
        left = (c.not_valid_after_utc - now).total_seconds()
        return max(0.0, left / total) if total > 0 else 0.0

    def needs_renewal(self, threshold: float = 0.25) -> bool:
        """True once less than `threshold` of the lifetime remains (the
        reference renews inside its renewal grace window)."""
        return self.enrolled and self.remaining_fraction() < threshold

    def _commit_renewal(self, new_key, cert_pem: bytes,
                        ca_pem: bytes) -> None:
        """Persist a successful renewal. The fresh key lives only in
        memory until the CA signed its CSR — a failed renewal RPC must
        leave the on-disk key/cert pair matched, or the next reload or
        restart serves a cert whose public key the private key can't
        back."""
        _write_private(self.key_path, _pem_key(new_key))
        self.key = new_key
        self.install(cert_pem, ca_pem)

    def renew(self, ca: CertificateAuthority) -> None:
        # renewal mints a FRESH keypair (reference cert clients do the
        # same: a long-lived private key defeats short-lived certs)
        new_key = _new_key()
        cert = ca.sign_csr(self.make_csr(key=new_key),
                           valid_days=self.valid_days)
        self._commit_renewal(new_key, cert, ca.root_pem)
        self._install_crl(sorted(ca.crl()))

    def renew_remote(self, address: str,
                     secret: Optional[str] = None) -> None:
        """Re-enroll over the enrollment endpoint with a fresh keypair;
        nothing touches disk until the CA answers (and, with a secret,
        until the response authenticates)."""
        new_key = _new_key()
        csr = self.make_csr(key=new_key)
        cert, ca_pem, crl = self._sign_csr_remote(address, csr, secret)
        self._commit_renewal(new_key, cert, ca_pem)
        self._install_crl(crl)

    def refresh_trust(self, ca: CertificateAuthority) -> bool:
        """Adopt the CA's CURRENT trust bundle + CRL (phase 1 of a root
        rotation; revocations propagate the same way). Returns True
        when either changed."""
        crl_changed = self._install_crl(sorted(ca.crl()))
        return self._install_trust(ca.root_pem) or crl_changed

    def refresh_trust_remote(self, address: str,
                             secret: Optional[str] = None) -> bool:
        """Periodic trust refresh. With a bootstrap secret, the fetch
        is challenge-response authenticated (client nonce, HMAC'd
        reply): a recurring UNauthenticated fetch would turn the
        one-shot enrollment bootstrap into a lifelong MITM
        trust-poisoning vector."""
        import os as _os

        from ozone_tpu.net import wire
        from ozone_tpu.net.rpc import RpcChannel

        nonce = _os.urandom(16).hex()
        ch = RpcChannel(address)
        try:
            m, _ = wire.unpack(ch.call(ENROLL_SERVICE, "RootCert",
                                       wire.pack({"nonce": nonce})))
        finally:
            ch.close()
        bundle = m["ca"].encode()
        crl = m.get("crl", [])
        self._require_mac(
            secret, b"root:",
            nonce.encode() + bundle
            + json.dumps(sorted(crl)).encode(),
            m.get("mac"))
        crl_changed = self._install_crl(crl)
        return self._install_trust(bundle) or crl_changed

    def _install_trust(self, bundle: bytes) -> bool:
        if self.ca_path.exists() and self.ca_path.read_bytes() == bundle:
            return False
        self.ca_path.write_bytes(bundle)
        return True

    def tls(self) -> "TlsMaterial":
        if not self.enrolled:
            raise RuntimeError(f"{self.role}: not enrolled")
        return TlsMaterial(
            key_pem=self.key_path.read_bytes(),
            cert_pem=self.cert_path.read_bytes(),
            ca_pem=self.ca_path.read_bytes(),
        )

    def rotating_tls(self) -> "RotatingTls":
        return RotatingTls(self)


ENROLL_SERVICE = "ozone.tpu.CertEnrollment"


class EnrollmentService:
    """CSR-signing endpoint served PLAINTEXT on its own RpcServer (the
    chicken-and-egg breaker: a fresh datanode has no cert yet, so it
    cannot reach the mTLS plane; the reference solves this with a
    Kerberos-authenticated SCMSecurityProtocol — here an optional shared
    `secret` gates who may obtain a certificate, and everything issued
    is a leaf cert whose trust is still rooted in the SCM CA)."""

    def __init__(self, ca: CertificateAuthority, server,
                 secret: Optional[str] = None,
                 leaf_valid_days: int = 398):
        self.ca = ca
        self.secret = secret
        self.leaf_valid_days = leaf_valid_days
        server.add_service(ENROLL_SERVICE, {
            "SignCsr": self._sign,
            "RootCert": self._root,
        })

    def _mac(self, domain: bytes, payload: bytes) -> Optional[str]:
        import hmac as _hmac

        if self.secret is None:
            return None
        return _hmac.new(self.secret.encode(), domain + payload,
                         "sha256").hexdigest()

    def _sign(self, req: bytes) -> bytes:
        import hmac as _hmac

        from ozone_tpu.net import wire

        m, _ = wire.unpack(req)
        if self.secret is not None and not _hmac.compare_digest(
                str(m.get("secret") or ""), self.secret):
            raise PermissionError("bad enrollment secret")
        csr = m["csr"].encode()
        cert = self.ca.sign_csr(csr, valid_days=self.leaf_valid_days)
        ca_pem = self.ca.root_pem
        crl = sorted(self.ca.crl())
        # response authentication: the plaintext channel is only safe
        # because both sides can prove knowledge of the bootstrap secret
        return wire.pack({
            "cert": cert.decode(),
            "ca": ca_pem.decode(),
            "crl": crl,
            "mac": self._mac(
                b"enroll:",
                csr + cert + ca_pem + json.dumps(crl).encode()),
        })

    def _root(self, req: bytes) -> bytes:
        from ozone_tpu.net import wire

        m, _ = wire.unpack(req)
        nonce = str(m.get("nonce") or "")
        bundle = self.ca.root_pem
        crl = sorted(self.ca.crl())
        return wire.pack({
            "ca": bundle.decode(),
            "crl": crl,
            "mac": self._mac(
                b"root:",
                nonce.encode() + bundle + json.dumps(crl).encode()),
        })


class RotatingTls:
    """Live TLS view over a CertificateClient (the reference's
    certificate-reload path: renewed certs are picked up WITHOUT a
    restart). Servers built from this use gRPC dynamic server
    credentials — every new handshake reads the current cert — and
    channel pools compare `version` to drop connections that present a
    retired identity."""

    def __init__(self, client: CertificateClient):
        self._client = client
        self._version = 0
        self._cached = client.tls()
        self._crl = client.crl()

    @property
    def version(self) -> int:
        return self._version

    def current(self) -> "TlsMaterial":
        return self._cached

    def reload(self) -> None:
        """Re-read the PEMs + CRL after a renewal/rotation/revocation."""
        self._cached = self._client.tls()
        self._crl = self._client.crl()
        self._version += 1

    def crl(self) -> set:
        """Revoked serials (live view for RpcServer.crl_provider)."""
        return self._crl

    # --- grpc credential builders (same surface as TlsMaterial) ---
    def server_credentials(self, mutual: bool = True):
        import grpc

        def fetch():
            m = self._cached
            return grpc.ssl_server_certificate_configuration(
                [(m.key_pem, m.cert_pem)], root_certificates=m.ca_pem)

        return grpc.dynamic_ssl_server_credentials(
            fetch(), lambda: fetch(),
            require_client_authentication=mutual)

    def channel_credentials(self):
        return self._cached.channel_credentials()


class CertRenewalService:
    """Background auto-renewal (DefaultCertificateClient's renewal
    monitor analog): wakes periodically, renews once the cert is inside
    the grace window, and reloads the live TLS view so servers hand out
    the new identity on the next handshake — no restart, no dropped
    RPCs."""

    def __init__(self, tls: RotatingTls, renew_fn, trust_fn=None,
                 check_interval_s: float = 60.0,
                 threshold: float = 0.25):
        self.tls = tls
        self.renew_fn = renew_fn  # () -> None; performs the re-enroll
        #: () -> bool; refreshes the trust bundle (root-rotation phase 1)
        #: and reports whether it changed. None = no trust refresh.
        self.trust_fn = trust_fn
        self.check_interval_s = check_interval_s
        self.threshold = threshold
        self.renewals = 0
        import threading

        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        import threading

        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="cert-renewal")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)

    def check_once(self) -> bool:
        """One renewal check (the loop body; tests drive this
        directly). Returns True when a renewal happened."""
        if self.trust_fn is not None and self.trust_fn():
            # the root rotated: serve the new bundle right away so
            # peers holding new-root leaves are accepted
            self.tls.reload()
        if not self._client_needs_renewal():
            return False
        self.renew_fn()
        self.tls.reload()
        self.renewals += 1
        import logging

        logging.getLogger(__name__).info(
            "cert renewed for %s; now valid until %s",
            self.tls._client.role, self.tls._client.expires_at)
        return True

    def _client_needs_renewal(self) -> bool:
        try:
            return self.tls._client.needs_renewal(self.threshold)
        except Exception:
            return False

    def _loop(self) -> None:
        while not self._stop.wait(self.check_interval_s):
            try:
                self.check_once()
            except Exception:
                import logging

                logging.getLogger(__name__).exception(
                    "certificate renewal failed; will retry")


@dataclass(frozen=True)
class TlsMaterial:
    """PEM bundle -> grpc credentials (the SecurityConfig/GrpcTlsConfig
    analog). mutual=True enforces client certs (the reference's
    datanode<->datanode replication and Ratis TLS mode)."""

    key_pem: bytes
    cert_pem: bytes
    ca_pem: bytes

    def server_credentials(self, mutual: bool = True):
        import grpc

        return grpc.ssl_server_credentials(
            [(self.key_pem, self.cert_pem)],
            root_certificates=self.ca_pem if mutual else None,
            require_client_auth=mutual,
        )

    def channel_credentials(self):
        import grpc

        return grpc.ssl_channel_credentials(
            root_certificates=self.ca_pem,
            private_key=self.key_pem,
            certificate_chain=self.cert_pem,
        )
