"""Host-side checksums: CRC32 / CRC32C / SHA256 / MD5 over chunk slices.

Capability mirror of the reference's Checksum/ChecksumData (hadoop-hdds
common ozone/common/Checksum.java:73-96: enum NONE/CRC32/CRC32C/SHA256/MD5,
one checksum per bytesPerChecksum slice; defaults from hdds client
OzoneClientConfig.java:164-179 — type CRC32, 16 KiB per checksum).

CRCs here use the GF(2)-linear decomposition (crc = L(M) xor crc(0^N),
L(M) = XOR of per-bit contributions) — the same math the device kernel in
codec/crc_device.py runs as a bit-matmul — implemented with vectorized
numpy XOR-reduction over a cached per-length contribution vector. A plain
table-driven implementation is kept for small inputs and as the test
cross-check.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from enum import Enum
from functools import lru_cache

import numpy as np

#: Reflected polynomials.
CRC32_POLY = 0xEDB88320  # IEEE, matches zlib.crc32
CRC32C_POLY = 0x82F63B78  # Castagnoli, matches java.util.zip.CRC32C


@lru_cache(maxsize=None)
def _table(poly: int) -> np.ndarray:
    """256-entry byte-step table for a reflected CRC."""
    t = np.zeros(256, dtype=np.uint64)
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        t[i] = c
    return t.astype(np.uint32)


def crc_table_driven(data, poly: int, crc: int = 0) -> int:
    """Classic table-driven reflected CRC with init/xorout 0xFFFFFFFF.

    `crc` is the running *finalized* value of previous data (0 for none),
    matching zlib.crc32's incremental contract.
    """
    tab = _table(poly)
    state = crc ^ 0xFFFFFFFF
    for b in np.asarray(data, dtype=np.uint8).reshape(-1).tolist():
        state = (state >> 8) ^ int(tab[(state ^ b) & 0xFF])
    return state ^ 0xFFFFFFFF


@lru_cache(maxsize=64)
def _linear_parts(n: int, poly: int) -> tuple[np.ndarray, int]:
    """(contribution vector K32 [n*8] uint32, crc_of_n_zero_bytes).

    K32[i] = linear-CRC contribution of message bit i (byte i//8, bit i%8
    LSB-first) for an n-byte message:  crc(M) = XOR_{set bits} K32[i] ^ Z_n.
    Built by iterating the one-zero-byte advance backwards from the last
    byte: contribution columns of byte j satisfy C[j-1] = step(C[j]).
    """
    tab = _table(poly).astype(np.uint32)
    k = np.zeros((n, 8), dtype=np.uint32)
    # contribution of the last byte's bits to the raw (linear) state:
    # injecting bit value 2^b into the last byte changes state by
    # step(e_b) where step is the one-byte advance on the xor-ed state.
    cur = tab[(1 << np.arange(8)).astype(np.uint8)]  # [8] uint32
    if n > 0:
        k[n - 1] = cur
        for j in range(n - 2, -1, -1):
            cur = (cur >> np.uint32(8)) ^ tab[cur & np.uint32(0xFF)]
            k[j] = cur
    # crc of n zero bytes (with init/xorout)
    state = np.uint32(0xFFFFFFFF)
    # advance init state through n zero bytes using matrix-free doubling is
    # overkill; n iterations of the table step on a scalar is fine (cached).
    s = int(state)
    tab_l = tab
    for _ in range(n):
        s = (s >> 8) ^ int(tab_l[s & 0xFF])
    zeros_crc = s ^ 0xFFFFFFFF
    return k.reshape(n * 8), zeros_crc


def crc_linear(data, poly: int) -> int:
    """Vectorized CRC via the linear decomposition (single shot, init/xorout
    0xFFFFFFFF). Bit-exact with crc_table_driven."""
    data = np.asarray(data, dtype=np.uint8).reshape(-1)
    n = data.size
    k32, zeros_crc = _linear_parts(n, poly)
    bits = np.unpackbits(data, bitorder="little")
    sel = k32[bits.astype(bool)]
    if sel.size:
        return int(np.bitwise_xor.reduce(sel)) ^ zeros_crc
    return zeros_crc


_NATIVE_LIB = False  # tri-state: False = unprobed, None = unavailable


def _native_lib():
    global _NATIVE_LIB
    if _NATIVE_LIB is False:
        try:
            from ozone_tpu import native

            _NATIVE_LIB = native.load()
        except Exception:  # noqa: BLE001 - pure-python fallback
            _NATIVE_LIB = None
    return _NATIVE_LIB


def crc32c(data, crc: int = 0) -> int:
    """CRC32C (Castagnoli). Hardware (SSE4.2) via the native library
    when present — this sits on the datanode read-verify hot path —
    with the table/linear numpy path as the portable fallback."""
    data = np.ascontiguousarray(data, dtype=np.uint8).reshape(-1)
    lib = _native_lib()
    if lib is not None:
        return int(lib.crc32c_hw(data.ctypes.data, data.size, crc))
    if crc == 0 and data.size > 256:
        return crc_linear(data, CRC32C_POLY)
    return crc_table_driven(data, CRC32C_POLY, crc)


def crc32(data, crc: int = 0) -> int:
    """CRC32 (IEEE), zlib-compatible — and computed BY zlib (C speed)."""
    import zlib

    data = np.ascontiguousarray(data, dtype=np.uint8).reshape(-1)
    return int(zlib.crc32(memoryview(data), crc))


class ChecksumType(Enum):
    NONE = "NONE"
    CRC32 = "CRC32"
    CRC32C = "CRC32C"
    SHA256 = "SHA256"
    MD5 = "MD5"


@dataclass(frozen=True)
class ChecksumData:
    """Per-chunk checksum list: one entry per bytesPerChecksum slice
    (reference ozone/common/ChecksumData.java)."""

    type: ChecksumType
    bytes_per_checksum: int
    checksums: tuple[bytes, ...] = ()

    def to_lists(self) -> dict:
        return {
            "type": self.type.value,
            "bytes_per_checksum": self.bytes_per_checksum,
            "checksums": [c.hex() for c in self.checksums],
        }

    @classmethod
    def from_lists(cls, d: dict) -> "ChecksumData":
        return cls(
            ChecksumType(d["type"]),
            int(d["bytes_per_checksum"]),
            tuple(bytes.fromhex(c) for c in d["checksums"]),
        )


class ChecksumError(Exception):
    pass


class Checksum:
    """Compute/verify slice-wise checksums over a chunk buffer
    (reference Checksum.computeChecksum / verifyChecksum:247-276)."""

    def __init__(self, type_: ChecksumType = ChecksumType.CRC32C,
                 bytes_per_checksum: int = 16 * 1024):
        self.type = type_
        self.bpc = bytes_per_checksum

    def _one(self, piece: np.ndarray) -> bytes:
        if self.type is ChecksumType.CRC32:
            return int(crc32(piece)).to_bytes(4, "big")
        if self.type is ChecksumType.CRC32C:
            return int(crc32c(piece)).to_bytes(4, "big")
        if self.type is ChecksumType.SHA256:
            return hashlib.sha256(piece.tobytes()).digest()
        if self.type is ChecksumType.MD5:
            return hashlib.md5(piece.tobytes()).digest()
        raise ValueError(self.type)

    def compute(self, data) -> ChecksumData:
        if self.type is ChecksumType.NONE:
            return ChecksumData(self.type, self.bpc)
        data = np.asarray(data, dtype=np.uint8).reshape(-1)
        sums = tuple(
            self._one(data[o : o + self.bpc]) for o in range(0, data.size, self.bpc)
        )
        return ChecksumData(self.type, self.bpc, sums)

    def verify(self, data, expected: ChecksumData, offset_hint: str = "") -> None:
        if expected.type is ChecksumType.NONE:
            return
        actual = Checksum(expected.type, expected.bytes_per_checksum).compute(data)
        if actual.checksums != expected.checksums:
            bad = [
                i
                for i, (a, e) in enumerate(
                    zip(actual.checksums, expected.checksums)
                )
                if a != e
            ]
            raise ChecksumError(
                f"checksum mismatch {offset_hint} at slices {bad[:8]} "
                f"(type={expected.type.value})"
            )
