"""Typed configuration system.

Capability mirror of the reference's two-tier config (hadoop-hdds/config:
@Config/@ConfigGroup annotations materialized by reflection, a compile-time
ConfigFileGenerator.java:48 emitting ozone-default-generated.xml, plus
ozone-default.xml): here config groups are dataclasses whose fields carry
metadata (key, description, tags); values resolve from defaults < config
file (json/ini-style) < environment (OZONE_TPU_ prefixed) < overrides, and
`generate_defaults()` emits the documented default file — the
ConfigFileGenerator analog. Size/duration strings parse like StorageSize /
TimeDurationUtil ("64MB", "16kb", "30s", "5m").
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from dataclasses import dataclass, field, fields
from pathlib import Path
from typing import Any, Optional, Type, TypeVar, get_type_hints

T = TypeVar("T")

_SIZE_RE = re.compile(r"^\s*([\d.]+)\s*([kmgtp]?i?b?)\s*$", re.I)
_SIZE_MULT = {
    "": 1, "b": 1,
    "k": 1024, "kb": 1024, "kib": 1024,
    "m": 1024**2, "mb": 1024**2, "mib": 1024**2,
    "g": 1024**3, "gb": 1024**3, "gib": 1024**3,
    "t": 1024**4, "tb": 1024**4, "tib": 1024**4,
    "p": 1024**5, "pb": 1024**5, "pib": 1024**5,
}
_TIME_RE = re.compile(r"^\s*([\d.]+)\s*(ms|s|m|h|d)?\s*$", re.I)
_TIME_MULT = {"ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0,
              None: 1.0, "": 1.0}


def parse_size(v) -> int:
    if isinstance(v, (int, float)):
        return int(v)
    m = _SIZE_RE.match(str(v))
    if not m:
        raise ValueError(f"cannot parse size {v!r}")
    return int(float(m.group(1)) * _SIZE_MULT[m.group(2).lower()])


def env_float(name: str, default: float) -> float:
    """Float env knob with a safe fallback: unset, empty, or junk
    values fall back to `default` instead of crashing a daemon (the
    lifecycle tuning knobs and friends all parse through here so the
    error handling cannot drift between copies)."""
    import os

    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def env_int(name: str, default: int) -> int:
    try:
        import os

        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def parse_duration(v) -> float:
    if isinstance(v, (int, float)):
        return float(v)
    m = _TIME_RE.match(str(v))
    if not m:
        raise ValueError(f"cannot parse duration {v!r}")
    return float(m.group(1)) * _TIME_MULT[(m.group(2) or "").lower()]


def conf(key: str, description: str = "", tags: tuple[str, ...] = (),
         kind: str = "auto", **kw):
    """Field factory carrying config metadata (@Config analog)."""
    meta = {"key": key, "description": description, "tags": tags,
            "kind": kind}
    return field(metadata=meta, **kw)


def _convert(raw: Any, ftype: Any, kind: str) -> Any:
    if kind == "size":
        return parse_size(raw)
    if kind == "duration":
        return parse_duration(raw)
    if ftype is bool:
        if isinstance(raw, bool):
            return raw
        return str(raw).strip().lower() in ("1", "true", "yes", "on")
    if ftype is int:
        return int(raw)
    if ftype is float:
        return float(raw)
    return raw


class OzoneConfiguration:
    """Layered key/value source: defaults < file < env < overrides."""

    ENV_PREFIX = "OZONE_TPU_"

    def __init__(self, config_file: Optional[Path] = None,
                 overrides: Optional[dict[str, Any]] = None):
        self._file_values: dict[str, Any] = {}
        if config_file and Path(config_file).exists():
            self._file_values = json.loads(Path(config_file).read_text())
        self._overrides = dict(overrides or {})

    def raw(self, key: str) -> Optional[Any]:
        if key in self._overrides:
            return self._overrides[key]
        env_key = self.ENV_PREFIX + key.upper().replace(".", "_").replace("-", "_")
        if env_key in os.environ:
            return os.environ[env_key]
        return self._file_values.get(key)

    def set(self, key: str, value: Any) -> None:
        self._overrides[key] = value

    def get_object(self, cls: Type[T]) -> T:
        """Materialize a config dataclass (ConfigurationReflectionUtil
        analog)."""
        hints = get_type_hints(cls)
        kwargs = {}
        for f in fields(cls):
            key = f.metadata.get("key")
            if not key:
                continue
            raw = self.raw(key)
            if raw is not None:
                kwargs[f.name] = _convert(
                    raw, hints.get(f.name), f.metadata.get("kind", "auto")
                )
        return cls(**kwargs)


def generate_defaults(groups: list[type]) -> str:
    """Emit the documented defaults file (ConfigFileGenerator analog)."""
    out: dict[str, Any] = {}
    lines = ["# ozone-tpu generated defaults", "#"]
    for g in groups:
        lines.append(f"# --- {g.__name__}: {(g.__doc__ or '').strip()}")
        inst = g()
        for f in fields(g):
            key = f.metadata.get("key")
            if not key:
                continue
            val = getattr(inst, f.name)
            desc = f.metadata.get("description", "")
            lines.append(f"#   {key} (default: {val!r}) - {desc}")
            out[key] = val
    return "\n".join(lines) + "\n" + json.dumps(out, indent=2, sort_keys=True)


class ReconfigurationHandler:
    """Live reconfiguration (reference: ReconfigureProtocol.proto +
    ReconfigurableConfig, doc feature/Reconfigurability.md): services
    register reconfigurable keys with an apply callback (and optional
    validator); `reconfigure` validates, updates the layered config's
    override tier, and applies — no restart. Non-registered keys are
    rejected, like the reference's getReconfigurableProperties contract.
    """

    def __init__(self, conf_obj: "OzoneConfiguration"):
        self.conf = conf_obj
        self._props: dict[str, dict] = {}

    def register(self, key: str, apply, validator=None,
                 description: str = "") -> None:
        self._props[key] = {
            "apply": apply,
            "validator": validator,
            "description": description,
        }

    def properties(self) -> list[dict]:
        return [
            {"key": k, "description": p["description"],
             "current": self.conf.raw(k)}
            for k, p in sorted(self._props.items())
        ]

    def reconfigure(self, key: str, value: Any) -> dict:
        p = self._props.get(key)
        if p is None:
            raise KeyError(f"{key} is not reconfigurable")
        if p["validator"] is not None:
            value = p["validator"](value)
        old = self.conf.raw(key)
        self.conf.set(key, value)
        p["apply"](value)
        return {"key": key, "old": old, "new": value}


# ------------------------------------------------------------- config groups
@dataclass
class ClientConfig:
    """Client-side IO settings (reference OzoneClientConfig analog)."""

    checksum_type: str = conf(
        "client.checksum.type",
        "Checksum type: NONE/CRC32/CRC32C/SHA256/MD5",
        default="CRC32C",
    )
    bytes_per_checksum: int = conf(
        "client.bytes.per.checksum",
        "Bytes covered by one checksum slice",
        kind="size",
        default=16 * 1024,
    )
    stripe_batch: int = conf(
        "client.ec.stripe.batch",
        "Stripes batched per device encode dispatch",
        default=8,
    )
    max_retries: int = conf(
        "client.max.retries", "Stripe/chunk write retries", default=3
    )


@dataclass
class ScmConfig:
    """SCM settings."""

    container_size: int = conf(
        "scm.container.size", "Container size", kind="size",
        default=5 * 1024**3,
    )
    min_datanodes: int = conf(
        "scm.safemode.min.datanodes",
        "Datanodes required to exit safemode",
        default=1,
    )
    stale_node_interval: float = conf(
        "scm.stale.node.interval", "Heartbeat age before STALE",
        kind="duration", default=9.0,
    )
    dead_node_interval: float = conf(
        "scm.dead.node.interval", "Heartbeat age before DEAD",
        kind="duration", default=30.0,
    )


@dataclass
class DatanodeConfig:
    """Datanode settings."""

    num_volumes: int = conf(
        "dn.volumes", "Storage volumes per datanode", default=1
    )
    heartbeat_interval: float = conf(
        "dn.heartbeat.interval", "Heartbeat period", kind="duration",
        default=1.0,
    )


@dataclass
class OmConfig:
    """OM settings."""

    block_size: int = conf(
        "om.block.size", "Logical block (group) size", kind="size",
        default=16 * 1024 * 1024,
    )
    flush_batch: int = conf(
        "om.db.flush.batch",
        "Metadata double-buffer flush batch size",
        default=64,
    )


ALL_GROUPS = [ClientConfig, ScmConfig, DatanodeConfig, OmConfig]
