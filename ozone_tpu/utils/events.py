"""Typed pub/sub event queue.

Mirror of the reference's EventQueue (hadoop-hdds/framework
hdds/server/events/EventQueue.java): handlers subscribe to topics; publish
dispatches synchronously by default (deterministic for tests) or to an
executor when async is requested, like FixedThreadPoolWithAffinityExecutor.
"""

from __future__ import annotations

import logging
import queue
import threading
from collections import defaultdict
from typing import Any, Callable

log = logging.getLogger(__name__)

Handler = Callable[[Any], None]


class EventQueue:
    def __init__(self, async_dispatch: bool = False):
        self._handlers: dict[str, list[Handler]] = defaultdict(list)
        self._lock = threading.Lock()
        self._async = async_dispatch
        self._q: "queue.Queue[tuple[str, Any]]" = queue.Queue()
        self._worker: threading.Thread | None = None
        if async_dispatch:
            self._worker = threading.Thread(
                target=self._drain, name="event-queue", daemon=True
            )
            self._worker.start()

    def subscribe(self, topic: str, handler: Handler) -> None:
        with self._lock:
            self._handlers[topic].append(handler)

    def publish(self, topic: str, payload: Any = None) -> None:
        if self._async:
            self._q.put((topic, payload))
        else:
            self._dispatch(topic, payload)

    def _dispatch(self, topic: str, payload: Any) -> None:
        for h in list(self._handlers.get(topic, ())):
            try:
                h(payload)
            except Exception:  # handler errors must not break the publisher
                log.exception("event handler for %s failed", topic)

    def _drain(self) -> None:
        while True:
            topic, payload = self._q.get()
            try:
                self._dispatch(topic, payload)
            finally:
                self._q.task_done()

    def flush(self) -> None:
        """Wait for queued async events to drain (tests)."""
        if self._async:
            self._q.join()


class EventWatcher:
    """Command-ack tracking with lease timeout (reference EventWatcher,
    hdds/server/events/EventWatcher.java + LeaseManager): a started
    event is tracked by id until its completion event arrives; if the
    lease expires first the original payload is re-published on the
    start topic (retry) and the timeout hook fires. check_leases() is
    deterministic for tests; start_timer() runs it in the background.
    """

    def __init__(
        self,
        queue: EventQueue,
        start_topic: str,
        completion_topic: str,
        lease_timeout_s: float = 10.0,
        on_timeout: Handler | None = None,
        max_retries: int = 3,
    ):
        import time

        self._time = time.monotonic
        self.queue = queue
        self.start_topic = start_topic
        self.completion_topic = completion_topic
        self.lease_timeout_s = lease_timeout_s
        self.on_timeout = on_timeout
        self.max_retries = max_retries
        #: id -> (payload, deadline, retries)
        self._pending: dict[Any, tuple[Any, float, int]] = {}
        self._lock = threading.Lock()
        self._timer: threading.Thread | None = None
        self._stop = threading.Event()
        queue.subscribe(completion_topic, self._on_completion)

    # ------------------------------------------------------------- tracking
    def watch(self, event_id: Any, payload: Any) -> None:
        """Publish on the start topic and track until completion/ack."""
        with self._lock:
            self._pending[event_id] = (
                payload, self._time() + self.lease_timeout_s, 0)
        self.queue.publish(self.start_topic, payload)

    def _on_completion(self, event_id: Any) -> None:
        with self._lock:
            self._pending.pop(event_id, None)

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    def check_leases(self) -> list[Any]:
        """Expire overdue leases: re-publish (up to max_retries), then
        drop and invoke on_timeout. Returns the ids that timed out."""
        now = self._time()
        expired: list[tuple[Any, tuple[Any, float, int]]] = []
        with self._lock:
            for eid, entry in list(self._pending.items()):
                if entry[1] <= now:
                    expired.append((eid, entry))
        timed_out = []
        for eid, entry in expired:
            payload, _deadline, retries = entry
            with self._lock:
                # between collecting the expiry and acting on it the
                # completion may have landed — and the same id may have
                # been re-watched with a fresh lease. Only act if the
                # exact expired lease object is still the tracked one;
                # a fresh lease must be neither overwritten nor timed out
                if self._pending.get(eid) is not entry:
                    continue
                if retries < self.max_retries:
                    self._pending[eid] = (
                        payload, self._time() + self.lease_timeout_s,
                        retries + 1)
                    retry = True
                else:
                    self._pending.pop(eid, None)
                    retry = False
            if retry:
                self.queue.publish(self.start_topic, payload)
            else:
                timed_out.append(eid)
                if self.on_timeout is not None:
                    try:
                        self.on_timeout(payload)
                    except Exception:
                        log.exception("event watcher timeout hook failed")
        return timed_out

    # ------------------------------------------------------------- timer
    def start_timer(self, interval_s: float = 1.0) -> None:
        if self._timer is not None:
            return

        def loop():
            while not self._stop.wait(interval_s):
                self.check_leases()

        self._timer = threading.Thread(target=loop, daemon=True,
                                       name="event-watcher")
        self._timer.start()

    def stop(self) -> None:
        self._stop.set()
        if self._timer is not None:
            self._timer.join(timeout=1.0)
            self._timer = None
