"""Typed pub/sub event queue.

Mirror of the reference's EventQueue (hadoop-hdds/framework
hdds/server/events/EventQueue.java): handlers subscribe to topics; publish
dispatches synchronously by default (deterministic for tests) or to an
executor when async is requested, like FixedThreadPoolWithAffinityExecutor.
"""

from __future__ import annotations

import logging
import queue
import threading
from collections import defaultdict
from typing import Any, Callable

log = logging.getLogger(__name__)

Handler = Callable[[Any], None]


class EventQueue:
    def __init__(self, async_dispatch: bool = False):
        self._handlers: dict[str, list[Handler]] = defaultdict(list)
        self._lock = threading.Lock()
        self._async = async_dispatch
        self._q: "queue.Queue[tuple[str, Any]]" = queue.Queue()
        self._worker: threading.Thread | None = None
        if async_dispatch:
            self._worker = threading.Thread(
                target=self._drain, name="event-queue", daemon=True
            )
            self._worker.start()

    def subscribe(self, topic: str, handler: Handler) -> None:
        with self._lock:
            self._handlers[topic].append(handler)

    def publish(self, topic: str, payload: Any = None) -> None:
        if self._async:
            self._q.put((topic, payload))
        else:
            self._dispatch(topic, payload)

    def _dispatch(self, topic: str, payload: Any) -> None:
        for h in list(self._handlers.get(topic, ())):
            try:
                h(payload)
            except Exception:  # handler errors must not break the publisher
                log.exception("event handler for %s failed", topic)

    def _drain(self) -> None:
        while True:
            topic, payload = self._q.get()
            try:
                self._dispatch(topic, payload)
            finally:
                self._q.task_done()

    def flush(self) -> None:
        """Wait for queued async events to drain (tests)."""
        if self._async:
            self._q.join()
