"""Per-service HTTP endpoint: prometheus metrics, status, config, insight.

Capability mirror of the reference's BaseHttpServer + PrometheusMetricsSink
(hadoop-hdds/framework hdds/server/http/ — on-by-default /prom endpoint,
docs Observability.md:32), with the `ozone insight`-style introspection
endpoints (/metrics JSON snapshot, /conf, /logs level control;
hadoop-ozone/insight exposes the same triple per component).
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Optional
from urllib.parse import parse_qs, urlparse

from ozone_tpu.utils import metrics as metrics_mod

log = logging.getLogger(__name__)


class ServiceHttpServer:
    def __init__(self, service_name: str, host: str = "127.0.0.1",
                 port: int = 0,
                 status_provider: Optional[Callable[[], dict]] = None,
                 config_provider: Optional[Callable[[], dict]] = None):
        self.service_name = service_name
        self.status_provider = status_provider or (lambda: {})
        self.config_provider = config_provider or (lambda: {})
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                log.debug("http: " + fmt, *args)

            def _send(self, code: int, body: str,
                      ctype: str = "application/json") -> None:
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                u = urlparse(self.path)
                if u.path == "/prom":
                    self._send(200, metrics_mod.prometheus_text(),
                               "text/plain; version=0.0.4")
                elif u.path == "/metrics":
                    snap = {
                        name: reg.snapshot()
                        for name, reg in metrics_mod._all_registries.items()
                    }
                    self._send(200, json.dumps(snap, indent=2))
                elif u.path == "/status":
                    self._send(200, json.dumps(outer.status_provider(),
                                               indent=2, default=str))
                elif u.path == "/conf":
                    self._send(200, json.dumps(outer.config_provider(),
                                               indent=2, default=str))
                elif u.path == "/logLevel":
                    q = parse_qs(u.query)
                    name = q.get("log", [""])[0]
                    level = q.get("level", [""])[0]
                    if name and level:
                        logging.getLogger(name).setLevel(level.upper())
                        self._send(200, json.dumps({"log": name,
                                                    "level": level}))
                    else:
                        self._send(400, json.dumps(
                            {"error": "need ?log=<name>&level=<level>"}))
                else:
                    self._send(404, json.dumps({"error": "not found",
                                                "endpoints": [
                                                    "/prom", "/metrics",
                                                    "/status", "/conf",
                                                    "/logLevel"]}))

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_port
        self.host = host
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"http-{self.service_name}",
            daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
