"""Per-service HTTP endpoint: prometheus metrics, status, config, insight.

Capability mirror of the reference's BaseHttpServer + PrometheusMetricsSink
(hadoop-hdds/framework hdds/server/http/ — on-by-default /prom endpoint,
docs Observability.md:32), with the `ozone insight`-style introspection
endpoints (/metrics JSON snapshot, /conf, /logs level control;
hadoop-ozone/insight exposes the same triple per component).
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Optional
from urllib.parse import parse_qs, urlparse

from ozone_tpu.utils import metrics as metrics_mod

log = logging.getLogger(__name__)


def sample_stacks(duration_s: float = 1.0,
                  interval_s: float = 0.01) -> str:
    """Sampling profiler over sys._current_frames (the ProfileServlet /
    async-profiler analog, hadoop-hdds/framework http/ProfileServlet.java):
    samples every thread's stack for `duration_s` and emits
    flamegraph-collapsed lines `frame;frame;frame count` — feed straight
    into speedscope / flamegraph.pl."""
    import sys
    import time
    import traceback
    from collections import Counter

    counts: Counter = Counter()
    deadline = time.monotonic() + duration_s
    me = threading.get_ident()
    while time.monotonic() < deadline:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            stack = traceback.extract_stack(frame)
            key = ";".join(
                f"{f.name} ({f.filename.rsplit('/', 1)[-1]}:{f.lineno})"
                for f in stack
            )
            counts[key] += 1
        time.sleep(interval_s)
    return "\n".join(f"{k} {v}" for k, v in counts.most_common())


def thread_dump() -> str:
    """jstack-style dump of every live thread (the /stacks servlet)."""
    import sys
    import traceback

    frames = sys._current_frames()
    by_id = {t.ident: t for t in threading.enumerate()}
    out = []
    for tid, frame in frames.items():
        t = by_id.get(tid)
        out.append(f'Thread "{t.name if t else tid}" '
                   f"daemon={getattr(t, 'daemon', '?')}:")
        out.extend("    " + ln.strip()
                   for ln in traceback.format_stack(frame))
        out.append("")
    return "\n".join(out)


class ServiceHttpServer:
    def __init__(self, service_name: str, host: str = "127.0.0.1",
                 port: int = 0,
                 status_provider: Optional[Callable[[], dict]] = None,
                 config_provider: Optional[Callable[[], dict]] = None,
                 reconfig=None):
        self.service_name = service_name
        self.status_provider = status_provider or (lambda: {})
        self.config_provider = config_provider or (lambda: {})
        #: utils/config.ReconfigurationHandler wired by the daemon; the
        #: /reconfig endpoints 404 without one
        self.reconfig = reconfig
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                log.debug("http: " + fmt, *args)

            def _send(self, code: int, body: str,
                      ctype: str = "application/json") -> None:
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                u = urlparse(self.path)
                if u.path == "/prom":
                    self._send(200, metrics_mod.prometheus_text(),
                               "text/plain; version=0.0.4")
                elif u.path == "/metrics":
                    snap = {
                        name: reg.snapshot()
                        for name, reg in metrics_mod._all_registries.items()
                    }
                    self._send(200, json.dumps(snap, indent=2))
                elif u.path == "/status":
                    self._send(200, json.dumps(outer.status_provider(),
                                               indent=2, default=str))
                elif u.path == "/conf":
                    self._send(200, json.dumps(outer.config_provider(),
                                               indent=2, default=str))
                elif u.path == "/logLevel":
                    q = parse_qs(u.query)
                    name = q.get("log", [""])[0]
                    level = q.get("level", [""])[0]
                    if name and level:
                        logging.getLogger(name).setLevel(level.upper())
                        self._send(200, json.dumps({"log": name,
                                                    "level": level}))
                    else:
                        self._send(400, json.dumps(
                            {"error": "need ?log=<name>&level=<level>"}))
                elif u.path == "/prof":
                    # sampling profiler (ProfileServlet analog): collapsed
                    # flamegraph stacks over ?duration=S&interval=S
                    q = parse_qs(u.query)
                    try:
                        dur = min(float(q.get("duration", ["1"])[0]), 30.0)
                        iv = max(float(q.get("interval", ["0.01"])[0]),
                                 0.001)
                    except ValueError as e:
                        self._send(400, json.dumps({"error": str(e)}))
                        return
                    self._send(200, sample_stacks(dur, iv), "text/plain")
                elif u.path == "/stacks":
                    self._send(200, thread_dump(), "text/plain")
                elif u.path == "/reconfig/properties":
                    if outer.reconfig is None:
                        self._send(404, json.dumps(
                            {"error": "no reconfiguration handler"}))
                    else:
                        self._send(200, json.dumps(
                            outer.reconfig.properties(), indent=2,
                            default=str))
                elif u.path == "/reconfig":
                    q = parse_qs(u.query)
                    key = q.get("key", [""])[0]
                    value = q.get("value", [""])[0]
                    if outer.reconfig is None:
                        self._send(404, json.dumps(
                            {"error": "no reconfiguration handler"}))
                    elif not key:
                        self._send(400, json.dumps(
                            {"error": "need ?key=<k>&value=<v>"}))
                    else:
                        try:
                            self._send(200, json.dumps(
                                outer.reconfig.reconfigure(key, value),
                                default=str))
                        except (KeyError, ValueError) as e:
                            self._send(400, json.dumps({"error": str(e)}))
                else:
                    self._send(404, json.dumps({"error": "not found",
                                                "endpoints": [
                                                    "/prom", "/metrics",
                                                    "/status", "/conf",
                                                    "/logLevel", "/prof",
                                                    "/stacks",
                                                    "/reconfig",
                                                    "/reconfig/properties",
                                                ]}))

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_port
        self.host = host
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"http-{self.service_name}",
            daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
