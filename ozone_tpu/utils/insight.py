"""Insight: per-subsystem operator introspection over RPC.

Mirror of the reference's `ozone insight` (hadoop-ozone/insight: per-
subsystem InsightPoint classes expose the related loggers, metrics and
configuration of om/scm/datanode components; the CLI streams component
logs by bumping log levels at runtime and reads metrics endpoints).

Here: a static registry of insight points (loggers + metrics registries
per subsystem), a bounded in-memory ring of log records captured by a
logging.Handler installed in every daemon, and an RPC service exposing
ListPoints / Metrics / Logs / SetLogLevel so the CLI can introspect any
running daemon.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Optional

from ozone_tpu.net import wire
from ozone_tpu.net.rpc import RpcServer

SERVICE = "ozone.tpu.Insight"

# subsystem -> related loggers + metrics registries (BaseInsightPoint
# catalogs per service; reference insight/om/, scm/, datanode/)
INSIGHT_POINTS: dict[str, dict] = {
    "om.key-manager": {
        "loggers": ["ozone_tpu.om.om", "ozone_tpu.om.requests"],
        "metrics": ["om"],
        "description": "key create/commit/lookup path",
    },
    "om.fso": {
        "loggers": ["ozone_tpu.om.fso"],
        "metrics": ["om"],
        "description": "FSO directory tree requests",
    },
    "scm.node-manager": {
        "loggers": ["ozone_tpu.scm.node_manager"],
        "metrics": ["scm"],
        "description": "datanode membership + liveness",
    },
    "scm.replication-manager": {
        "loggers": ["ozone_tpu.scm.replication_manager"],
        "metrics": ["scm"],
        "description": "under/over-replication control loop",
    },
    "scm.block-manager": {
        "loggers": ["ozone_tpu.scm.container_manager",
                    "ozone_tpu.scm.block_deletion"],
        "metrics": ["scm"],
        "description": "block allocation + deletion chain",
    },
    "datanode.dispatcher": {
        "loggers": ["ozone_tpu.storage.datanode",
                    "ozone_tpu.net.dn_service"],
        "metrics": ["datanode"],
        "description": "container command dispatch",
    },
    "datanode.reconstruction": {
        "loggers": ["ozone_tpu.storage.reconstruction"],
        "metrics": ["datanode"],
        "description": "EC offline reconstruction",
    },
}


class RingLogHandler(logging.Handler):
    """Bounded in-memory log capture (the insight log-streaming source)."""

    _installed: Optional["RingLogHandler"] = None

    def __init__(self, capacity: int = 4096):
        super().__init__(level=logging.DEBUG)
        self.records: deque = deque(maxlen=capacity)
        self._lock2 = threading.Lock()

    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = record.getMessage()
        except Exception:  # noqa: BLE001
            msg = str(record.msg)
        with self._lock2:
            self.records.append({
                "ts": record.created,
                "level": record.levelname,
                "logger": record.name,
                "message": msg,
            })

    def tail(self, n: int = 100, logger_prefix: str = "",
             level: str = "") -> list[dict]:
        want = logging.getLevelName(level.upper()) if level else 0
        if not isinstance(want, int):
            want = 0
        with self._lock2:
            records = list(self.records)
        out = []
        for r in reversed(records):
            if logger_prefix and not r["logger"].startswith(logger_prefix):
                continue
            lv = logging.getLevelName(r["level"])
            if isinstance(lv, int) and lv < want:
                continue
            out.append(r)
            if len(out) >= n:
                break
        return list(reversed(out))

    @classmethod
    def install(cls, capacity: int = 4096) -> "RingLogHandler":
        if cls._installed is None:
            h = cls(capacity)
            logging.getLogger().addHandler(h)
            cls._installed = h
        return cls._installed


class InsightService:
    """RPC surface for the insight CLI, added to any daemon's server."""

    def __init__(self, server: RpcServer, component: str):
        self.component = component
        self.ring = RingLogHandler.install()
        server.add_service(SERVICE, {
            "ListPoints": self._list_points,
            "Metrics": self._metrics,
            "Logs": self._logs,
            "SetLogLevel": self._set_log_level,
            "Partition": self._partition,
            "Delay": self._delay,
            "Heal": self._heal,
            "PartitionList": self._partition_list,
        })

    def _list_points(self, req: bytes) -> bytes:
        return wire.pack({
            "component": self.component,
            "points": INSIGHT_POINTS,
        })

    def _metrics(self, req: bytes) -> bytes:
        from ozone_tpu.utils.metrics import _all_registries

        return wire.pack({
            "ts": time.time(),
            "registries": {
                name: reg.snapshot()
                for name, reg in _all_registries.items()
            },
        })

    def _logs(self, req: bytes) -> bytes:
        m, _ = wire.unpack(req)
        return wire.pack({
            "records": self.ring.tail(
                n=int(m.get("n", 100)),
                logger_prefix=m.get("logger", ""),
                level=m.get("level", ""),
            ),
        })

    def _set_log_level(self, req: bytes) -> bytes:
        m, _ = wire.unpack(req)
        logger = logging.getLogger(m["logger"] or None)
        logger.setLevel(m["level"].upper())
        return wire.pack({"logger": m["logger"], "level": m["level"]})

    # ---- network-partition injection (blockade analog): cut/restore this
    # process's outbound links remotely during fault drills
    def _partition(self, req: bytes) -> bytes:
        from ozone_tpu.net import partition
        from ozone_tpu.storage.ids import StorageError

        m, _ = wire.unpack(req)
        if not m.get("dst"):
            raise StorageError("INVALID", "partition requires a dst address")
        partition.block(m["dst"], m.get("owner") or partition.ANY)
        return wire.pack({"blocked": partition.blocked()})

    def _delay(self, req: bytes) -> bytes:
        from ozone_tpu.net import partition
        from ozone_tpu.storage.ids import StorageError

        m, _ = wire.unpack(req)
        if not m.get("dst"):
            raise StorageError("INVALID", "delay requires a dst address")
        partition.delay(m["dst"], float(m.get("seconds", 0.1)),
                        m.get("owner") or partition.ANY)
        return wire.pack({"blocked": partition.blocked(),
                          "delayed": partition.delayed()})

    def _heal(self, req: bytes) -> bytes:
        from ozone_tpu.net import partition
        from ozone_tpu.storage.ids import StorageError

        m, _ = wire.unpack(req)
        if m.get("dst"):
            partition.heal(m["dst"], m.get("owner") or partition.ANY)
        elif m.get("owner"):
            # an owner without a dst is ambiguous — refuse rather than
            # silently clearing every rule mid-drill
            raise StorageError("INVALID", "heal: owner given without dst")
        else:
            partition.clear()
        return wire.pack({"blocked": partition.blocked()})

    def _partition_list(self, req: bytes) -> bytes:
        from ozone_tpu.net import partition

        return wire.pack({"blocked": partition.blocked(),
                          "delayed": partition.delayed()})


class InsightClient:
    def __init__(self, address: str, tls=None):
        from ozone_tpu.net.rpc import RpcChannel

        self._ch = RpcChannel(address, tls=tls)

    def _call(self, method: str, **m) -> dict:
        out, _ = wire.unpack(self._ch.call(SERVICE, method, wire.pack(m)))
        return out

    def list_points(self) -> dict:
        return self._call("ListPoints")

    def metrics(self) -> dict:
        return self._call("Metrics")

    def logs(self, n: int = 100, logger: str = "",
             level: str = "") -> list[dict]:
        return self._call("Logs", n=n, logger=logger, level=level)["records"]

    def set_log_level(self, logger: str, level: str) -> dict:
        return self._call("SetLogLevel", logger=logger, level=level)

    def partition(self, dst: str, owner: str = "") -> dict:
        """Cut the target process's outbound link(s) to dst."""
        return self._call("Partition", dst=dst, owner=owner)

    def delay(self, dst: str, seconds: float, owner: str = "") -> dict:
        """Add latency to the target process's calls to dst."""
        return self._call("Delay", dst=dst, seconds=seconds, owner=owner)

    def heal(self, dst: str = "", owner: str = "") -> dict:
        """Restore a cut link, or all links when dst is empty."""
        return self._call("Heal", dst=dst, owner=owner)

    def partition_list(self) -> list:
        return self._call("PartitionList")["blocked"]

    def delays(self) -> list:
        """Active latency-injection rules on the target process."""
        return self._call("PartitionList")["delayed"]

    def close(self) -> None:
        self._ch.close()
