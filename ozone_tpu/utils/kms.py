"""KMS analog: master keys, envelope encryption, datapath ciphers.

Role analog of the reference's KMS integration (OzoneKMSUtil +
KMSClientProvider + BucketEncryptionKeyInfo): a bucket is created
against a named master key; every key write gets a fresh data
encryption key (DEK), stored ONLY in wrapped form (EDEK = DEK encrypted
under the master key, AES-GCM so tampering is detected); readers unwrap
the EDEK through the metadata server (access-checked) and decrypt the
stream client-side. The datapath, datanodes, scrubber, reconstruction,
and checksums all see ciphertext only.

Unlike the reference there is no external Hadoop KMS process — the
master keys live in the metadata server's replicated store (the same
trust domain that holds the namespace), rotated by admin verbs. GDPR
buckets (right-to-erasure) instead store a per-key plaintext secret in
the key row; deleting the key destroys the secret in the same raft
apply, rendering the (asynchronously purged) blocks unreadable
immediately — crypto-erasure, the reference's GDPR_FLAG semantics.

Stream cipher: AES-CTR. Counter-mode keeps random access (an hsync'd
prefix decrypts without the tail) and needs no padding; integrity is
already covered by the datapath chunk checksums + the EDEK's GCM tag.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

MASTER_PREFIX = "kms/mk/"


def _aesgcm(key: bytes):
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM

    return AESGCM(key)


def ctr_crypt(data, key: bytes, iv: bytes, offset: int = 0) -> np.ndarray:
    """Encrypt/decrypt (same operation) a byte stream at ANY byte
    `offset` with AES-256-CTR. The counter derives from the offset, so
    a writer streaming in several calls and a reader starting
    mid-stream (an hsync'd prefix, a ranged read) line up on the same
    keystream. Unaligned offsets are handled by generating the partial
    leading block's keystream and discarding it."""
    from cryptography.hazmat.primitives.ciphers import (
        Cipher,
        algorithms,
        modes,
    )

    pad = offset % 16
    base = int.from_bytes(iv, "big") + (offset - pad) // 16
    counter = (base % (1 << 128)).to_bytes(16, "big")
    enc = Cipher(algorithms.AES(key), modes.CTR(counter)).encryptor()
    buf = (data.tobytes() if isinstance(data, np.ndarray)
           else bytes(data))
    out = enc.update(b"\x00" * pad + buf) + enc.finalize()
    return np.frombuffer(out, np.uint8)[pad:]


class KeyProvider:
    """Master-key store + EDEK wrap/unwrap over the OM's replicated
    metadata (DefaultKeyProvider / KMSClientProvider role). Master keys
    are versioned; rotation adds a version — existing EDEKs name the
    version that wrapped them and stay decryptable."""

    def __init__(self, store):
        self.store = store  # OMMetadataStore ("system" table)

    # ------------------------------------------------------ master keys
    def _row(self, name: str) -> Optional[dict]:
        return self.store.get("system", MASTER_PREFIX + name)

    def master_key_names(self) -> list[str]:
        return [k[len(MASTER_PREFIX):]
                for k, _ in self.store.iterate("system", MASTER_PREFIX)]

    @staticmethod
    def _missing(name) -> Exception:
        # OMError so daemons reply with a clean code, not INTERNAL
        from ozone_tpu.om.requests import INVALID_REQUEST, OMError

        return OMError(INVALID_REQUEST, f"no master key {name!r}")

    def master_info(self, name: str) -> dict:
        row = self._row(name)
        if row is None:
            raise self._missing(name)
        return {"name": name, "versions": len(row["versions"])}

    # ------------------------------------------------------------ EDEKs
    def generate_edek(self, master: str) -> dict:
        """Fresh DEK wrapped under the master key's CURRENT version
        (KeyProviderCryptoExtension.generateEncryptedKey analog).
        Returns the key-row bundle; the plaintext DEK never persists."""
        row = self._row(master)
        if row is None:
            raise self._missing(master)
        version = len(row["versions"]) - 1
        mk = bytes.fromhex(row["versions"][version])
        dek = os.urandom(32)
        nonce = os.urandom(12)
        wrapped = _aesgcm(mk).encrypt(nonce, dek, master.encode())
        return {
            "master": master,
            "version": version,
            "nonce": nonce.hex(),
            "edek": wrapped.hex(),
            "iv": os.urandom(16).hex(),  # CTR IV for the data stream
        }

    def unwrap_edek(self, bundle: dict) -> bytes:
        """EDEK -> DEK (decryptEncryptedKey). GCM authenticates: a
        tampered EDEK or wrong master raises instead of yielding a
        garbage key that would 'decrypt' to noise."""
        row = self._row(bundle["master"])
        if row is None:
            raise self._missing(bundle["master"])
        mk = bytes.fromhex(row["versions"][int(bundle["version"])])
        return _aesgcm(mk).decrypt(
            bytes.fromhex(bundle["nonce"]),
            bytes.fromhex(bundle["edek"]),
            bundle["master"].encode(),
        )
