"""Metrics: counters/gauges/timers/histograms with Prometheus export.

Capability mirror of the reference's metrics2 registries +
PrometheusMetricsSink (hadoop-hdds/framework hdds/server/http/
PrometheusMetricsSink.java — on-by-default /prom endpoint,
docs Observability.md:32). Every subsystem creates a MetricsRegistry and
the HTTP layer exposes `prometheus_text()` of the global registry set.

Histograms carry optional trace-id exemplars (OpenMetrics exemplar
syntax) so a scraped tail bucket links back to a retained slow trace.
"""

from __future__ import annotations

import math
import threading
import time
from collections import defaultdict
from typing import Callable, Optional

_all_registries: dict[str, "MetricsRegistry"] = {}
_all_lock = threading.RLock()  # registry() constructs while holding it

# Installed by utils/tracing at import; lets Histogram.observe stamp the
# active trace id on outlier observations without a metrics->tracing
# import edge (tracing already imports nothing from metrics, but the
# provider keeps the layering one-directional either way).
_trace_id_provider: Optional[Callable[[], str]] = None


def set_trace_id_provider(fn: Callable[[], str]) -> None:
    global _trace_id_provider
    _trace_id_provider = fn


class Counter:
    def __init__(self):
        self._v = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._v += n

    @property
    def value(self) -> int:
        return self._v


class Gauge:
    def __init__(self):
        self._v = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._v = v

    @property
    def value(self) -> float:
        with self._lock:
            return self._v


class Timer:
    """Latency accumulator: count, total, min/max (freon-style reports)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = 0.0

    def update(self, seconds: float) -> None:
        with self._lock:
            self.count += 1
            self.total += seconds
            self.min = min(self.min, seconds)
            self.max = max(self.max, seconds)

    def time(self):
        timer = self

        class _Ctx:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *a):
                timer.update(time.perf_counter() - self.t0)

        return _Ctx()

    @property
    def mean(self) -> float:
        with self._lock:
            return self.total / self.count if self.count else 0.0


def log_buckets(lo: float = 1e-4, hi: float = 100.0,
                per_decade: int = 3) -> tuple[float, ...]:
    """Log-spaced bucket upper bounds covering [lo, hi]."""
    n = int(round(per_decade * math.log10(hi / lo)))
    return tuple(
        round(lo * (hi / lo) ** (i / n), 10) for i in range(n + 1)
    )


DEFAULT_BUCKETS = log_buckets()  # 100us .. 100s, 3 per decade


class Histogram:
    """Bucketed latency distribution (Prometheus histogram semantics).

    Cumulative `le` buckets over log-spaced bounds, plus sum/count and
    min/max, so p50/p95/p99 are derivable both server-side (quantile())
    and by a scraper. Observations above `exemplar_min` (or landing past
    the median bucket) stamp the active trace id as an exemplar on their
    bucket, linking the tail of the distribution to retained traces.
    """

    def __init__(self, bounds: Optional[tuple[float, ...]] = None):
        self.bounds: tuple[float, ...] = tuple(bounds or DEFAULT_BUCKETS)
        self._counts = [0] * (len(self.bounds) + 1)  # last = +Inf
        self._lock = threading.Lock()
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = 0.0
        # bucket index -> (value, trace_id, unix_ts); bounded by bucket
        # count, latest outlier wins
        self._exemplars: dict[int, tuple[float, str, float]] = {}

    def _bucket_index(self, v: float) -> int:
        lo, hi = 0, len(self.bounds)
        while lo < hi:
            mid = (lo + hi) // 2
            if v <= self.bounds[mid]:
                hi = mid
            else:
                lo = mid + 1
        return lo

    def observe(self, seconds: float, trace_id: str = "") -> None:
        idx = self._bucket_index(seconds)
        if not trace_id and _trace_id_provider is not None:
            try:
                trace_id = _trace_id_provider() or ""
            except Exception:
                trace_id = ""
        with self._lock:
            self._counts[idx] += 1
            self.count += 1
            self.total += seconds
            self.min = min(self.min, seconds)
            self.max = max(self.max, seconds)
            if trace_id and seconds * 2 >= (self.total / self.count):
                # outlier-ish (at/above half the running mean covers the
                # tail without a quantile pass per observation)
                self._exemplars[idx] = (seconds, trace_id, time.time())

    def time(self):
        hist = self

        class _Ctx:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *a):
                hist.observe(time.perf_counter() - self.t0)

        return _Ctx()

    @property
    def mean(self) -> float:
        with self._lock:
            return self.total / self.count if self.count else 0.0

    def quantile(self, q: float) -> float:
        """Estimate the q-quantile by linear interpolation within the
        containing bucket (what a PromQL histogram_quantile would see)."""
        with self._lock:
            if not self.count:
                return 0.0
            target = q * self.count
            cum = 0
            for i, c in enumerate(self._counts):
                if not c:
                    continue
                if cum + c >= target:
                    lo = self.bounds[i - 1] if i > 0 else 0.0
                    hi = (self.bounds[i] if i < len(self.bounds)
                          else max(self.max, lo))
                    frac = (target - cum) / c
                    return lo + (hi - lo) * frac
                cum += c
            return self.max

    def percentiles(self) -> dict:
        return {"p50": self.quantile(0.50), "p95": self.quantile(0.95),
                "p99": self.quantile(0.99)}

    def render(self, metric: str, lines: list[str]) -> None:
        """Append exposition lines for one histogram family."""
        with self._lock:
            counts = list(self._counts)
            exemplars = dict(self._exemplars)
            total, count = self.total, self.count
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            le = (_format_float(self.bounds[i]) if i < len(self.bounds)
                  else "+Inf")
            line = f'{metric}_bucket{{le="{le}"}} {cum}'
            ex = exemplars.get(i)
            if ex is not None:
                val, tid, ts = ex
                line += (f' # {{trace_id="{escape_label(tid)}"}} '
                         f"{_format_float(val)} {round(ts, 3)}")
            lines.append(line)
        lines.append(f"{metric}_sum {total}")
        lines.append(f"{metric}_count {count}")


def _format_float(v: float) -> str:
    s = f"{v:.10f}".rstrip("0").rstrip(".")
    return s if s else "0"


class MetricsRegistry:
    def __init__(self, name: str):
        self.name = name
        self._counters: dict[str, Counter] = defaultdict(Counter)
        self._gauges: dict[str, Gauge] = defaultdict(Gauge)
        self._timers: dict[str, Timer] = defaultdict(Timer)
        self._histograms: dict[str, Histogram] = {}
        self._hist_lock = threading.Lock()
        with _all_lock:
            _all_registries[name] = self

    def counter(self, name: str) -> Counter:
        return self._counters[name]

    def gauge(self, name: str) -> Gauge:
        return self._gauges[name]

    def timer(self, name: str) -> Timer:
        return self._timers[name]

    def histogram(self, name: str,
                  bounds: Optional[tuple[float, ...]] = None) -> Histogram:
        with self._hist_lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(bounds)
            return h

    def snapshot(self) -> dict:
        return {
            **{k: c.value for k, c in self._counters.items()},
            **{k: g.value for k, g in self._gauges.items()},
            **{
                f"{k}_mean_s": t.mean for k, t in self._timers.items() if t.count
            },
            **{
                f"{k}_{p}_s": v
                for k, h in self._histograms.items() if h.count
                for p, v in h.percentiles().items()
            },
        }


def registry(name: str) -> MetricsRegistry:
    """Get-or-create a named registry (components that may be
    instantiated repeatedly — e.g. one RaftNode per pipeline group —
    share one registry instead of orphaning the previous one)."""
    with _all_lock:
        r = _all_registries.get(name)
        if r is None:
            # construct under the lock: MetricsRegistry.__init__ inserts
            # itself, and a racing create would orphan the loser
            r = MetricsRegistry(name)
        return r


def _sanitize(s: str) -> str:
    return s.replace(".", "_").replace("-", "_")


def escape_label(v: str) -> str:
    """Escape a label value per the exposition format: backslash,
    double-quote and newline must be escaped inside `label="..."`."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def prometheus_text(registry: Optional[MetricsRegistry] = None) -> str:
    """Prometheus exposition text for one or all registries. Every
    metric renders a # HELP and # TYPE pair (the exposition-format
    contract scrapers and the golden test check) with a stable
    `<registry>_<name>` identifier; registries and metrics emit in
    sorted order so successive scrapes diff cleanly."""
    with _all_lock:
        regs = ([registry] if registry
                else [_all_registries[k] for k in sorted(_all_registries)])
    lines: list[str] = []
    for r in regs:
        base = _sanitize(r.name)
        for k in sorted(r._counters):
            c = r._counters[k]
            m = f"{base}_{_sanitize(k)}"
            lines.append(f"# HELP {m} counter {k} of registry {r.name}")
            lines.append(f"# TYPE {m} counter")
            lines.append(f"{m} {c.value}")
        for k in sorted(r._gauges):
            g = r._gauges[k]
            m = f"{base}_{_sanitize(k)}"
            lines.append(f"# HELP {m} gauge {k} of registry {r.name}")
            lines.append(f"# TYPE {m} gauge")
            lines.append(f"{m} {g.value}")
        for k in sorted(r._timers):
            t = r._timers[k]
            m = f"{base}_{_sanitize(k)}"
            lines.append(f"# HELP {m}_seconds latency summary {k} of "
                         f"registry {r.name}")
            lines.append(f"# TYPE {m}_seconds summary")
            lines.append(f"{m}_seconds_count {t.count}")
            lines.append(f"{m}_seconds_sum {t.total}")
        with r._hist_lock:
            hists = sorted(r._histograms.items())
        for k, h in hists:
            m = f"{base}_{_sanitize(k)}"
            lines.append(f"# HELP {m} latency histogram {k} of "
                         f"registry {r.name}")
            lines.append(f"# TYPE {m} histogram")
            h.render(m, lines)
    return "\n".join(lines) + "\n"


def get_registry(name: str) -> Optional[MetricsRegistry]:
    return _all_registries.get(name)
