"""Metrics: counters/gauges/timers with Prometheus text export.

Capability mirror of the reference's metrics2 registries +
PrometheusMetricsSink (hadoop-hdds/framework hdds/server/http/
PrometheusMetricsSink.java — on-by-default /prom endpoint,
docs Observability.md:32). Every subsystem creates a MetricsRegistry and
the HTTP layer exposes `prometheus_text()` of the global registry set.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from typing import Optional

_all_registries: dict[str, "MetricsRegistry"] = {}
_all_lock = threading.RLock()  # registry() constructs while holding it


class Counter:
    def __init__(self):
        self._v = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._v += n

    @property
    def value(self) -> int:
        return self._v


class Gauge:
    def __init__(self):
        self._v = 0.0

    def set(self, v: float) -> None:
        self._v = v

    @property
    def value(self) -> float:
        return self._v


class Timer:
    """Latency accumulator: count, total, min/max (freon-style reports)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = 0.0

    def update(self, seconds: float) -> None:
        with self._lock:
            self.count += 1
            self.total += seconds
            self.min = min(self.min, seconds)
            self.max = max(self.max, seconds)

    def time(self):
        timer = self

        class _Ctx:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *a):
                timer.update(time.perf_counter() - self.t0)

        return _Ctx()

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0


class MetricsRegistry:
    def __init__(self, name: str):
        self.name = name
        self._counters: dict[str, Counter] = defaultdict(Counter)
        self._gauges: dict[str, Gauge] = defaultdict(Gauge)
        self._timers: dict[str, Timer] = defaultdict(Timer)
        with _all_lock:
            _all_registries[name] = self

    def counter(self, name: str) -> Counter:
        return self._counters[name]

    def gauge(self, name: str) -> Gauge:
        return self._gauges[name]

    def timer(self, name: str) -> Timer:
        return self._timers[name]

    def snapshot(self) -> dict:
        return {
            **{k: c.value for k, c in self._counters.items()},
            **{k: g.value for k, g in self._gauges.items()},
            **{
                f"{k}_mean_s": t.mean for k, t in self._timers.items() if t.count
            },
        }


def registry(name: str) -> MetricsRegistry:
    """Get-or-create a named registry (components that may be
    instantiated repeatedly — e.g. one RaftNode per pipeline group —
    share one registry instead of orphaning the previous one)."""
    with _all_lock:
        r = _all_registries.get(name)
        if r is None:
            # construct under the lock: MetricsRegistry.__init__ inserts
            # itself, and a racing create would orphan the loser
            r = MetricsRegistry(name)
        return r


def _sanitize(s: str) -> str:
    return s.replace(".", "_").replace("-", "_")


def prometheus_text(registry: Optional[MetricsRegistry] = None) -> str:
    """Prometheus exposition text for one or all registries. Every
    metric renders a # HELP and # TYPE pair (the exposition-format
    contract scrapers and the golden test check) with a stable
    `<registry>_<name>` identifier."""
    regs = [registry] if registry else list(_all_registries.values())
    lines: list[str] = []
    for r in regs:
        base = _sanitize(r.name)
        for k, c in r._counters.items():
            m = f"{base}_{_sanitize(k)}"
            lines.append(f"# HELP {m} counter {k} of registry {r.name}")
            lines.append(f"# TYPE {m} counter")
            lines.append(f"{m} {c.value}")
        for k, g in r._gauges.items():
            m = f"{base}_{_sanitize(k)}"
            lines.append(f"# HELP {m} gauge {k} of registry {r.name}")
            lines.append(f"# TYPE {m} gauge")
            lines.append(f"{m} {g.value}")
        for k, t in r._timers.items():
            m = f"{base}_{_sanitize(k)}"
            lines.append(f"# HELP {m}_seconds latency summary {k} of "
                         f"registry {r.name}")
            lines.append(f"# TYPE {m}_seconds summary")
            lines.append(f"{m}_seconds_count {t.count}")
            lines.append(f"{m}_seconds_sum {t.total}")
    return "\n".join(lines) + "\n"


def get_registry(name: str) -> Optional[MetricsRegistry]:
    return _all_registries.get(name)
