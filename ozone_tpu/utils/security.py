"""Block tokens: HMAC-signed per-block capability tokens.

Mirror of the reference's token infrastructure (hadoop-hdds/framework
hdds/security/: symmetric SecretKeyManager rotating HMAC keys,
OzoneBlockTokenSecretManager issuing per-block tokens carried on datanode
requests, BlockTokenVerifier.java checking mode/expiry/signature on the
DN; Kerberos/x509 cover the control plane in the reference and are out of
scope here). Tokens authorize READ/WRITE on one block for a bounded
lifetime and verify against any non-expired secret (rotation-safe).
"""

from __future__ import annotations

import hashlib
import hmac
import json
import secrets
import threading
import time
from dataclasses import dataclass
from enum import Enum
from typing import Optional

from ozone_tpu.storage.ids import BlockID


class AccessMode(Enum):
    READ = "READ"
    WRITE = "WRITE"


class TokenError(Exception):
    pass


@dataclass(frozen=True)
class SecretKey:
    key_id: str
    material: bytes
    created: float
    expires: float


class SecretKeyManager:
    """Rotating symmetric keys (security/symmetric/SecretKeyManager.java)."""

    def __init__(self, rotation_s: float = 3600.0, lifetime_s: float = 7200.0):
        self.rotation_s = rotation_s
        self.lifetime_s = lifetime_s
        self._keys: dict[str, SecretKey] = {}
        self._current: Optional[SecretKey] = None
        self._lock = threading.Lock()
        self.rotate()

    def rotate(self) -> SecretKey:
        with self._lock:
            now = time.time()
            k = SecretKey(
                key_id=secrets.token_hex(8),
                material=secrets.token_bytes(32),
                created=now,
                expires=now + self.lifetime_s,
            )
            self._keys[k.key_id] = k
            self._current = k
            # drop expired keys
            for kid in [k2 for k2, v in self._keys.items()
                        if v.expires < now]:
                del self._keys[kid]
            return k

    def current(self) -> SecretKey:
        with self._lock:
            if (
                self._current is None
                or time.time() - self._current.created > self.rotation_s
            ):
                pass  # rotation is caller-driven (background service)
            return self._current

    def get(self, key_id: str) -> Optional[SecretKey]:
        return self._keys.get(key_id)

    def import_key(self, key: SecretKey) -> None:
        """Distribute secrets to verifiers (SCM -> DN in the reference)."""
        with self._lock:
            self._keys[key.key_id] = key
            if self._current is None:
                self._current = key


def _payload(block_id: BlockID, modes: list[AccessMode], owner: str,
             expiry: float, key_id: str) -> bytes:
    return json.dumps(
        {
            "b": block_id.to_json(),
            "m": sorted(m.value for m in modes),
            "o": owner,
            "e": round(expiry, 3),
            "k": key_id,
        },
        sort_keys=True,
        separators=(",", ":"),
    ).encode()


class BlockTokenIssuer:
    """OM/SCM-side token minting (OzoneBlockTokenSecretManager analog)."""

    def __init__(self, secrets_mgr: SecretKeyManager,
                 token_lifetime_s: float = 600.0):
        self.secrets = secrets_mgr
        self.lifetime = token_lifetime_s

    def issue(self, block_id: BlockID, modes: list[AccessMode],
              owner: str = "client") -> dict:
        key = self.secrets.current()
        expiry = time.time() + self.lifetime
        payload = _payload(block_id, modes, owner, expiry, key.key_id)
        sig = hmac.new(key.material, payload, hashlib.sha256).hexdigest()
        return {
            "block_id": block_id.to_json(),
            "modes": sorted(m.value for m in modes),
            "owner": owner,
            "expiry": round(expiry, 3),
            "key_id": key.key_id,
            "sig": sig,
        }


class BlockTokenVerifier:
    """Datanode-side verification (BlockTokenVerifier.java analog)."""

    def __init__(self, secrets_mgr: SecretKeyManager, enabled: bool = True):
        self.secrets = secrets_mgr
        self.enabled = enabled

    def verify(self, token: Optional[dict], block_id: BlockID,
               mode: AccessMode) -> None:
        if not self.enabled:
            return
        if token is None:
            raise TokenError("missing block token")
        if token.get("expiry", 0) < time.time():
            raise TokenError("block token expired")
        if mode.value not in token.get("modes", []):
            raise TokenError(f"token lacks {mode.value} access")
        tb = BlockID.from_json(token["block_id"])
        if tb != block_id:
            raise TokenError(f"token is for {tb}, not {block_id}")
        key = self.secrets.get(token.get("key_id", ""))
        if key is None:
            raise TokenError("unknown/expired secret key")
        payload = _payload(
            block_id,
            [AccessMode(m) for m in token["modes"]],
            token.get("owner", ""),
            token["expiry"],
            token["key_id"],
        )
        expect = hmac.new(key.material, payload, hashlib.sha256).hexdigest()
        if not hmac.compare_digest(expect, token.get("sig", "")):
            raise TokenError("bad token signature")
