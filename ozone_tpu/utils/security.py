"""Block tokens: HMAC-signed per-block capability tokens.

Mirror of the reference's token infrastructure (hadoop-hdds/framework
hdds/security/: symmetric SecretKeyManager rotating HMAC keys,
OzoneBlockTokenSecretManager issuing per-block tokens carried on datanode
requests, BlockTokenVerifier.java checking mode/expiry/signature on the
DN; Kerberos/x509 cover the control plane in the reference and are out of
scope here). Tokens authorize READ/WRITE on one block for a bounded
lifetime and verify against any non-expired secret (rotation-safe).
"""

from __future__ import annotations

import hashlib
import hmac
import json
import secrets
import threading
import time
from dataclasses import dataclass
from enum import Enum
from typing import Optional

from ozone_tpu.storage.ids import BlockID


class AccessMode(Enum):
    READ = "READ"
    WRITE = "WRITE"


class TokenError(Exception):
    pass


@dataclass(frozen=True)
class SecretKey:
    key_id: str
    material: bytes
    created: float
    expires: float

    def to_json(self) -> dict:
        return {
            "key_id": self.key_id,
            "material": self.material.hex(),
            "created": self.created,
            "expires": self.expires,
        }

    @classmethod
    def from_json(cls, d: dict) -> "SecretKey":
        return cls(d["key_id"], bytes.fromhex(d["material"]),
                   float(d["created"]), float(d["expires"]))


class SecretKeyManager:
    """Rotating symmetric keys (security/symmetric/SecretKeyManager.java).

    `generate=False` builds an empty manager that only holds imported
    keys — the datanode-side verifier state, fed by the SCM over the
    register/heartbeat channel the way the reference's
    SecretKeyProtocol distributes keys to DNs."""

    def __init__(self, rotation_s: float = 3600.0, lifetime_s: float = 7200.0,
                 generate: bool = True, activation_s: float = 0.0):
        self.rotation_s = rotation_s
        self.lifetime_s = lifetime_s
        #: a freshly minted key becomes the SIGNING key only after this
        #: many seconds — verifiers (datanodes) learn keys over the
        #: heartbeat channel, so signing with a key nobody can verify
        #: yet would fail every request for one heartbeat interval after
        #: each rotation. Verification accepts all non-expired keys
        #: immediately; only signing waits.
        self.activation_s = activation_s
        self._keys: dict[str, SecretKey] = {}
        self._current: Optional[SecretKey] = None
        self._lock = threading.Lock()
        if generate:
            self.rotate()

    def rotate(self) -> SecretKey:
        with self._lock:
            now = time.time()
            k = SecretKey(
                key_id=secrets.token_hex(8),
                material=secrets.token_bytes(32),
                created=now,
                expires=now + self.lifetime_s,
            )
            self._keys[k.key_id] = k
            self._current = k
            # drop expired keys
            for kid in [k2 for k2, v in self._keys.items()
                        if v.expires < now]:
                del self._keys[kid]
            return k

    def current(self) -> Optional[SecretKey]:
        """The signing key: the newest key past its activation delay,
        falling back to the newest key at all (bootstrap: the first key
        must sign immediately or nothing works)."""
        with self._lock:
            if self._current is None or self.activation_s <= 0:
                return self._current
            cutoff = time.time() - self.activation_s
            eligible = [k for k in self._keys.values()
                        if k.created <= cutoff]
            if not eligible:
                return self._current
            return max(eligible, key=lambda k: k.created)

    def get(self, key_id: str) -> Optional[SecretKey]:
        return self._keys.get(key_id)

    def import_key(self, key: SecretKey) -> None:
        """Distribute secrets to verifiers (SCM -> DN in the reference).
        The newest imported key becomes the signing key, so a follower
        OM or a datanode-side self-issuer always signs with the same key
        the cluster verifies against."""
        with self._lock:
            self._keys[key.key_id] = key
            if self._current is None or key.created > self._current.created:
                self._current = key
            # verifier-side managers never call rotate(), so expired
            # material is pruned here or it accumulates forever
            now = time.time()
            for kid in [k2 for k2, v in self._keys.items()
                        if v.expires < now]:
                del self._keys[kid]

    def needs_rotation(self) -> bool:
        cur = self._current
        return cur is None or time.time() - cur.created > self.rotation_s

    def new_key(self) -> SecretKey:
        """Mint a fresh key WITHOUT installing it (HA: the leader mints,
        replicates through the ring, and every replica — itself included
        — installs via import_key when the decision applies)."""
        now = time.time()
        return SecretKey(
            key_id=secrets.token_hex(8),
            material=secrets.token_bytes(32),
            created=now,
            expires=now + self.lifetime_s,
        )

    def export_keys(self) -> list[dict]:
        """All non-expired keys, for distribution to verifiers."""
        now = time.time()
        with self._lock:
            return [k.to_json() for k in self._keys.values()
                    if k.expires >= now]

    def import_keys(self, keys: list[dict]) -> None:
        for d in keys:
            self.import_key(SecretKey.from_json(d))


def _payload(scope: str, subject, modes: list[AccessMode], owner: str,
             expiry: float, key_id: str) -> bytes:
    """Signed bytes. `scope` separates block ("b") from container ("c")
    tokens so one can never be replayed as the other (the reference keeps
    OzoneBlockTokenIdentifier and ContainerTokenIdentifier distinct)."""
    return json.dumps(
        {
            "s": scope,
            "b": subject,
            "m": sorted(m.value for m in modes),
            "o": owner,
            "e": round(expiry, 3),
            "k": key_id,
        },
        sort_keys=True,
        separators=(",", ":"),
    ).encode()


class BlockTokenIssuer:
    """OM/SCM-side token minting (OzoneBlockTokenSecretManager +
    ContainerTokenSecretManager analog). Datanodes build one over their
    imported keys to self-sign reconstruction traffic, the way the
    reference's ec/reconstruction/TokenHelper does."""

    def __init__(self, secrets_mgr: SecretKeyManager,
                 token_lifetime_s: float = 600.0):
        self.secrets = secrets_mgr
        self.lifetime = token_lifetime_s

    def _sign(self, scope: str, subject, modes: list[AccessMode],
              owner: str) -> dict:
        key = self.secrets.current()
        if key is None:
            raise TokenError("no signing key available")
        expiry = time.time() + self.lifetime
        payload = _payload(scope, subject, modes, owner, expiry, key.key_id)
        sig = hmac.new(key.material, payload, hashlib.sha256).hexdigest()
        return {
            "scope": scope,
            "subject": subject,
            "modes": sorted(m.value for m in modes),
            "owner": owner,
            "expiry": round(expiry, 3),
            "key_id": key.key_id,
            "sig": sig,
        }

    def issue(self, block_id: BlockID, modes: list[AccessMode],
              owner: str = "client") -> dict:
        return self._sign("b", block_id.to_json(), modes, owner)

    def issue_container(self, container_id: int,
                        modes: Optional[list[AccessMode]] = None,
                        owner: str = "client") -> dict:
        return self._sign("c", int(container_id),
                          modes or [AccessMode.READ, AccessMode.WRITE],
                          owner)


class BlockTokenVerifier:
    """Datanode-side verification (BlockTokenVerifier.java analog)."""

    def __init__(self, secrets_mgr: SecretKeyManager, enabled: bool = True):
        self.secrets = secrets_mgr
        self.enabled = enabled

    def _check(self, token: Optional[dict], scope: str, subject,
               what: str, mode: AccessMode) -> None:
        if not self.enabled:
            return
        if token is None:
            raise TokenError(f"missing {what} token")
        if token.get("scope", "b") != scope:
            raise TokenError(f"not a {what} token")
        if token.get("expiry", 0) < time.time():
            raise TokenError(f"{what} token expired")
        if mode.value not in token.get("modes", []):
            raise TokenError(f"token lacks {mode.value} access")
        if token.get("subject") != subject:
            raise TokenError(
                f"token is for {token.get('subject')}, not {subject}")
        key = self.secrets.get(token.get("key_id", ""))
        if key is None:
            raise TokenError("unknown/expired secret key")
        try:
            modes = [AccessMode(m) for m in token["modes"]]
        except ValueError as e:
            raise TokenError(f"malformed token mode: {e}")
        payload = _payload(
            scope,
            subject,
            modes,
            token.get("owner", ""),
            token["expiry"],
            token["key_id"],
        )
        expect = hmac.new(key.material, payload, hashlib.sha256).hexdigest()
        if not hmac.compare_digest(expect, token.get("sig", "")):
            raise TokenError("bad token signature")

    def verify(self, token: Optional[dict], block_id: BlockID,
               mode: AccessMode) -> None:
        self._check(token, "b", block_id.to_json(), "block", mode)

    def verify_container(self, token: Optional[dict], container_id: int,
                         mode: AccessMode = AccessMode.WRITE) -> None:
        self._check(token, "c", int(container_id), "container", mode)
