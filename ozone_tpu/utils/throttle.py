"""Token-bucket bandwidth throttle for background transfers.

Role analog of the reference's replication bandwidth limits
(ReplicationSupervisor / ReplicationServer per-datanode limits,
ReplicationConfig's replication.outofservice.limit family): container
replication and repair traffic must not starve foreground client IO on
shared disks/links. One Throttle instance paces all replication work a
datanode does; `take(n)` blocks until `n` bytes of budget accumulate.
"""

from __future__ import annotations

import threading
import time


class Throttle:
    def __init__(self, bytes_per_s: float, burst_s: float = 0.25,
                 metrics=None):
        if bytes_per_s <= 0:
            raise ValueError("bytes_per_s must be positive")
        self.rate = float(bytes_per_s)
        self.burst = self.rate * burst_s
        self._tokens = self.burst
        self._t = time.monotonic()
        self._lock = threading.Lock()
        #: MetricsRegistry hook: records throttled sleep milliseconds
        #: and paced bytes so operators can SEE the cap biting
        self.metrics = metrics

    def take(self, n: int) -> float:
        """Consume `n` bytes of budget, sleeping as needed; returns the
        seconds slept. Requests larger than the burst window are paid
        across multiple refills (never refused)."""
        slept = 0.0
        with self._lock:
            now = time.monotonic()
            self._tokens = min(
                self.burst, self._tokens + (now - self._t) * self.rate)
            self._t = now
            self._tokens -= n
            if self._tokens < 0:
                slept = -self._tokens / self.rate
        if slept > 0:
            # sleep OUTSIDE the lock (virtual-scheduling pacing): the
            # deficit stays booked on the bucket, so a second taker
            # arriving mid-sleep sees its request stacked behind this
            # one's (an even deeper deficit = a longer sleep) — same
            # one-shared-link queueing as sleeping under the lock, but
            # other threads can book their demand and pace in parallel
            # instead of serializing on a held mutex
            time.sleep(slept)
        if self.metrics is not None and slept > 0:
            self.metrics.counter("replication_throttle_ms").inc(
                int(slept * 1000))
        if self.metrics is not None:
            self.metrics.counter("replication_throttled_bytes").inc(n)
        return slept

    def try_take(self, n: float) -> float:
        """Admission-control variant of :meth:`take`: consume `n` units
        of budget only if available RIGHT NOW. Returns 0.0 when the
        request was admitted (budget booked), otherwise the seconds
        until `n` units will have accumulated — a Retry-After hint —
        WITHOUT booking anything, so a refused caller leaves the bucket
        untouched for better-behaved traffic."""
        with self._lock:
            now = time.monotonic()
            self._tokens = min(
                self.burst, self._tokens + (now - self._t) * self.rate)
            self._t = now
            if self._tokens >= n:
                self._tokens -= n
                return 0.0
            return (n - self._tokens) / self.rate
