"""Distributed tracing: spans with cross-RPC propagation.

Capability mirror of the reference's tracing layer (hadoop-hdds/common
hdds/tracing/TracingUtil.java — Jaeger spans with the trace context
carried as a string `traceID` field on every proto request,
DatanodeClientProtocol.proto:184; GrpcClientInterceptor/
GrpcServerInterceptor propagate it). Here spans are collected in-process
(ring buffer, queryable/exportable) and the context string rides the
net/wire.py JSON header under "traceId"; the RPC layer injects/extracts
automatically.
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Optional

_local = threading.local()


@dataclass
class Span:
    trace_id: str
    span_id: str
    parent_id: str
    name: str
    start: float
    duration: float = 0.0
    tags: dict = field(default_factory=dict)


class Tracer:
    """Process-wide tracer with a bounded span buffer."""

    _instance: Optional["Tracer"] = None

    def __init__(self, max_spans: int = 10_000, sample_rate: float = 1.0):
        self.spans: deque[Span] = deque(maxlen=max_spans)
        self.sample_rate = sample_rate
        self._lock = threading.Lock()

    @classmethod
    def instance(cls) -> "Tracer":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    @staticmethod
    def _new_id() -> str:
        return f"{random.getrandbits(64):016x}"

    def current(self) -> Optional[Span]:
        return getattr(_local, "span", None)

    @contextmanager
    def span(self, name: str, child_of: Optional[str] = None, **tags):
        """Start a span; child_of is an imported context string
        ("traceid:spanid") from a remote caller."""
        parent = self.current()
        if child_of:
            trace_id, parent_id = (child_of.split(":") + [""])[:2]
        elif parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        else:
            trace_id, parent_id = self._new_id(), ""
        s = Span(trace_id, self._new_id(), parent_id, name, time.time(),
                 tags=dict(tags))
        prev = self.current()
        _local.span = s
        try:
            yield s
        finally:
            s.duration = time.time() - s.start
            _local.span = prev
            if random.random() < self.sample_rate:
                with self._lock:
                    self.spans.append(s)

    def inject(self) -> str:
        """Export the current context for the wire ("traceID" field analog);
        empty string when not tracing."""
        s = self.current()
        return f"{s.trace_id}:{s.span_id}" if s else ""

    def traces(self, trace_id: Optional[str] = None) -> list[Span]:
        with self._lock:
            out = list(self.spans)
        if trace_id:
            out = [s for s in out if s.trace_id == trace_id]
        return out

    def export_json(self) -> list[dict]:
        return [
            {
                "traceId": s.trace_id,
                "spanId": s.span_id,
                "parentId": s.parent_id,
                "name": s.name,
                "start": s.start,
                "durationMs": round(s.duration * 1e3, 3),
                "tags": s.tags,
            }
            for s in self.traces()
        ]
