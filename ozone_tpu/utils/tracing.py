"""Distributed tracing: spans with cross-RPC propagation.

Capability mirror of the reference's tracing layer (hadoop-hdds/common
hdds/tracing/TracingUtil.java — Jaeger spans with the trace context
carried as a string `traceID` field on every proto request,
DatanodeClientProtocol.proto:184; GrpcClientInterceptor/
GrpcServerInterceptor propagate it). Here spans are collected in-process
(ring buffer, queryable/exportable) and the context string rides the
net/wire.py JSON header under "traceId"; the RPC layer injects/extracts
automatically.
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Optional

_local = threading.local()


@dataclass
class Span:
    trace_id: str
    span_id: str
    parent_id: str
    name: str
    start: float
    duration: float = 0.0
    tags: dict = field(default_factory=dict)


class Tracer:
    """Process-wide tracer with a bounded span buffer."""

    _instance: Optional["Tracer"] = None

    def __init__(self, max_spans: int = 10_000, sample_rate: float = 1.0):
        self.spans: deque[Span] = deque(maxlen=max_spans)
        self.sample_rate = sample_rate
        self._lock = threading.Lock()
        #: filled by an attached SpanExporter; None = local-only mode
        self._export_q: Optional[deque] = None

    @classmethod
    def instance(cls) -> "Tracer":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    @staticmethod
    def _new_id() -> str:
        return f"{random.getrandbits(64):016x}"

    def current(self) -> Optional[Span]:
        return getattr(_local, "span", None)

    @contextmanager
    def span(self, name: str, child_of: Optional[str] = None, **tags):
        """Start a span; child_of is an imported context string
        ("traceid:spanid") from a remote caller."""
        parent = self.current()
        if child_of:
            trace_id, parent_id = (child_of.split(":") + [""])[:2]
        elif parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        else:
            trace_id, parent_id = self._new_id(), ""
        s = Span(trace_id, self._new_id(), parent_id, name, time.time(),
                 tags=dict(tags))
        prev = self.current()
        _local.span = s
        try:
            yield s
        finally:
            s.duration = time.time() - s.start
            _local.span = prev
            if random.random() < self.sample_rate:
                with self._lock:
                    self.spans.append(s)
                    if self._export_q is not None:
                        self._export_q.append(s)

    def inject(self) -> str:
        """Export the current context for the wire ("traceID" field analog);
        empty string when not tracing."""
        s = self.current()
        return f"{s.trace_id}:{s.span_id}" if s else ""

    def traces(self, trace_id: Optional[str] = None) -> list[Span]:
        with self._lock:
            out = list(self.spans)
        if trace_id:
            out = [s for s in out if s.trace_id == trace_id]
        return out

    def export_json(self) -> list[dict]:
        return [
            {
                "traceId": s.trace_id,
                "spanId": s.span_id,
                "parentId": s.parent_id,
                "name": s.name,
                "start": s.start,
                "durationMs": round(s.duration * 1e3, 3),
                "tags": s.tags,
            }
            for s in self.traces()
        ]


def span_json(s: Span, service: str = "") -> dict:
    return {
        "traceId": s.trace_id,
        "spanId": s.span_id,
        "parentId": s.parent_id,
        "name": s.name,
        "start": s.start,
        "durationMs": round(s.duration * 1e3, 3),
        "tags": s.tags,
        **({"service": service} if service else {}),
    }


TRACING_SERVICE = "ozone.tpu.Tracing"


class SpanExporter:
    """Ship finished spans to a cluster collector (the reference sends
    every span to Jaeger via the jaeger-client sender — spans here ride
    the existing gRPC plane in batches). Lossy by design: the deque is
    bounded and a down collector just drops batches; tracing must never
    backpressure the datapath."""

    def __init__(self, tracer: Tracer, service: str, address: str = "",
                 tls=None, interval_s: float = 2.0,
                 max_batch: int = 512, collector=None):
        self.tracer = tracer
        self.service = service
        self.address = address
        self.tls = tls
        #: in-process collector: the metadata server feeds its own
        #: spans straight in, no loopback RPC
        self.collector = collector
        self.interval_s = interval_s
        self.max_batch = max_batch
        self.exported = 0
        self._q: deque[Span] = deque(maxlen=10_000)
        tracer._export_q = self._q
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._ch = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"trace-export-{self.service}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)
        self.flush()
        if self._ch is not None:
            self._ch.close()
            self._ch = None

    def flush(self) -> None:
        """Drain and ship everything pending (one batch per call chunk);
        errors drop the batch (collector down != datapath problem)."""
        from ozone_tpu.net import wire as _wire

        batch = []
        while self._q and len(batch) < self.max_batch:
            s = self._q.popleft()
            if TRACING_SERVICE in s.name:
                continue  # never trace the tracing plane itself
            batch.append(s)
        if not batch:
            return
        if self.collector is not None:
            self.collector.add(self.service,
                               [span_json(s) for s in batch])
            self.exported += len(batch)
            return
        try:
            if self._ch is None:
                from ozone_tpu.net.rpc import RpcChannel

                self._ch = RpcChannel(self.address, tls=self.tls,
                                      traced=False)
            self._ch.call(TRACING_SERVICE, "Report", _wire.pack({
                "service": self.service,
                "spans": [span_json(s) for s in batch],
            }))
            self.exported += len(batch)
        except Exception:
            # reconnect next round; spans already popped are dropped
            if self._ch is not None:
                try:
                    self._ch.close()
                except Exception:
                    pass
                self._ch = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.flush()


class TraceCollector:
    """Cluster-wide trace assembly (the Jaeger-collector role): every
    daemon's exporter reports finished spans here; queries see ONE
    trace stitched across services. Bounded LRU over trace ids."""

    def __init__(self, server=None, max_traces: int = 2000):
        from collections import OrderedDict

        self._traces: "OrderedDict[str, dict]" = OrderedDict()
        self.max_traces = max_traces
        self._lock = threading.Lock()
        if server is not None:
            server.add_service(TRACING_SERVICE, {
                "Report": self._report,
                "Query": self._query,
                "Recent": self._recent,
            })

    # ------------------------------------------------------------ ingest
    def add(self, service: str, spans: list[dict]) -> None:
        with self._lock:
            for sp in spans:
                tid = sp.get("traceId", "")
                if not tid:
                    continue
                sp = dict(sp)
                sp.setdefault("service", service)
                t = self._traces.get(tid)
                if t is None:
                    t = self._traces[tid] = {
                        "spans": [], "services": set(),
                        "start": sp["start"], "end": 0.0,
                    }
                    while len(self._traces) > self.max_traces:
                        self._traces.popitem(last=False)
                t["spans"].append(sp)
                t["services"].add(sp.get("service") or service)
                t["start"] = min(t["start"], sp["start"])
                t["end"] = max(t["end"],
                               sp["start"] + sp["durationMs"] / 1e3)

    def _report(self, req: bytes) -> bytes:
        from ozone_tpu.net import wire as _wire

        m, _ = _wire.unpack(req)
        self.add(m.get("service", ""), m.get("spans", []))
        return _wire.pack({"ok": True})

    # ------------------------------------------------------------- query
    def trace(self, trace_id: str) -> list[dict]:
        with self._lock:
            t = self._traces.get(trace_id)
            return sorted((dict(s) for s in t["spans"]),
                          key=lambda s: s["start"]) if t else []

    def recent(self, limit: int = 50) -> list[dict]:
        with self._lock:
            # deep-enough copies: concurrent Report RPCs mutate the
            # per-trace spans list and services set under the lock
            items = [
                (tid, {"spans": list(t["spans"]),
                       "services": set(t["services"]),
                       "start": t["start"], "end": t["end"]})
                for tid, t in list(self._traces.items())[-limit:]
            ]
        out = []
        for tid, t in reversed(items):
            roots = [s["name"] for s in t["spans"]
                     if not s.get("parentId")]
            out.append({
                "traceId": tid,
                "spans": len(t["spans"]),
                "services": sorted(t["services"]),
                "root": roots[0] if roots else t["spans"][0]["name"],
                "start": t["start"],
                "durationMs": round((t["end"] - t["start"]) * 1e3, 3),
            })
        return out

    def _query(self, req: bytes) -> bytes:
        from ozone_tpu.net import wire as _wire

        m, _ = _wire.unpack(req)
        return _wire.pack({"spans": self.trace(m.get("trace_id", ""))})

    def _recent(self, req: bytes) -> bytes:
        from ozone_tpu.net import wire as _wire

        m, _ = _wire.unpack(req)
        return _wire.pack({"traces": self.recent(m.get("limit", 50))})
