"""Distributed tracing: spans with cross-RPC propagation.

Capability mirror of the reference's tracing layer (hadoop-hdds/common
hdds/tracing/TracingUtil.java — Jaeger spans with the trace context
carried as a string `traceID` field on every proto request,
DatanodeClientProtocol.proto:184; GrpcClientInterceptor/
GrpcServerInterceptor propagate it). Here spans are collected in-process
(ring buffer, queryable/exportable) and the context string rides the
net/wire.py JSON header under "traceId"; the RPC layer injects/extracts
automatically.
"""

from __future__ import annotations

import random
import re
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Optional

_local = threading.local()


@dataclass
class Span:
    trace_id: str
    span_id: str
    parent_id: str
    name: str
    start: float
    duration: float = 0.0
    tags: dict = field(default_factory=dict)
    #: point-in-time annotations ({"t", "name", ...attrs}); retry /
    #: hedge / breaker decisions land here rather than as child spans
    events: list = field(default_factory=list)


class Tracer:
    """Process-wide tracer with a bounded span buffer."""

    _instance: Optional["Tracer"] = None

    def __init__(self, max_spans: int = 10_000, sample_rate: float = 1.0):
        self.spans: deque[Span] = deque(maxlen=max_spans)
        self.sample_rate = sample_rate
        self._lock = threading.Lock()
        #: filled by an attached SpanExporter; None = local-only mode
        self._export_q: Optional[deque] = None
        #: tail-based slow-trace retention (per-op SLO, env-tunable)
        self.recorder = FlightRecorder()

    @classmethod
    def instance(cls) -> "Tracer":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    @staticmethod
    def _new_id() -> str:
        return f"{random.getrandbits(64):016x}"

    def current(self) -> Optional[Span]:
        return getattr(_local, "span", None)

    @contextmanager
    def span(self, name: str, child_of: Optional[str] = None, **tags):
        """Start a span; child_of is an imported context string
        ("traceid:spanid") from a remote caller."""
        parent = self.current()
        if child_of:
            trace_id, parent_id = (child_of.split(":") + [""])[:2]
        elif parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        else:
            trace_id, parent_id = self._new_id(), ""
        s = Span(trace_id, self._new_id(), parent_id, name, time.time(),
                 tags=dict(tags))
        prev = self.current()
        _local.span = s
        try:
            yield s
        finally:
            s.duration = time.time() - s.start
            _local.span = prev
            self._finish(s)

    def _finish(self, s: Span) -> None:
        if random.random() < self.sample_rate:
            with self._lock:
                self.spans.append(s)
                if self._export_q is not None:
                    self._export_q.append(s)
            if not s.parent_id:
                # root finished last: the whole local trace is in the
                # buffer, so tail-based retention can decide now
                self.recorder.offer(s, self.traces(s.trace_id))

    def record_span(self, name: str, *, child_of: str = "",
                    start: float, duration: float, span_id: str = "",
                    **tags) -> Span:
        """Record an already-measured interval as a finished span.

        Needed where the measuring thread is not the owning thread —
        e.g. the codec-service dispatcher closing out a submission's
        queue-wait on behalf of the submitting request — so a
        contextmanager span can't bracket the interval."""
        if child_of:
            trace_id, parent_id = (child_of.split(":") + [""])[:2]
        else:
            cur = self.current()
            if cur is not None:
                trace_id, parent_id = cur.trace_id, cur.span_id
            else:
                trace_id, parent_id = self._new_id(), ""
        s = Span(trace_id, span_id or self._new_id(), parent_id, name,
                 start, duration, tags=dict(tags))
        self._finish(s)
        return s

    def event(self, name: str, **attrs) -> None:
        """Annotate the current span (no-op outside any span). Retry,
        breaker-skip, hedge and deadline decisions record as events so
        a slow trace shows *why* the path was taken."""
        s = self.current()
        if s is not None:
            s.events.append({"t": time.time(), "name": name, **attrs})

    @contextmanager
    def activate(self, ctx: str):
        """Re-establish a trace context on a worker thread. The span
        stack is thread-local, so pool workers (ec-writer, ec-read,
        hedge) must carry the submitter's context explicitly — the
        exact analog of resilience.activate for deadlines."""
        if not ctx:
            yield
            return
        tid, sid = (ctx.split(":") + [""])[:2]
        prev = self.current()
        # context holder only — never finished, never recorded
        _local.span = Span(tid, sid, "", "<activated>", time.time())
        try:
            yield
        finally:
            _local.span = prev

    def inject(self) -> str:
        """Export the current context for the wire ("traceID" field analog);
        empty string when not tracing."""
        s = self.current()
        return f"{s.trace_id}:{s.span_id}" if s else ""

    def current_trace_id(self) -> str:
        s = self.current()
        return s.trace_id if s else ""

    def traces(self, trace_id: Optional[str] = None) -> list[Span]:
        with self._lock:
            out = list(self.spans)
        if trace_id:
            out = [s for s in out if s.trace_id == trace_id]
        return out

    def export_json(self) -> list[dict]:
        return [
            {
                "traceId": s.trace_id,
                "spanId": s.span_id,
                "parentId": s.parent_id,
                "name": s.name,
                "start": s.start,
                "durationMs": round(s.duration * 1e3, 3),
                "tags": s.tags,
            }
            for s in self.traces()
        ]


def span_json(s: Span, service: str = "") -> dict:
    return {
        "traceId": s.trace_id,
        "spanId": s.span_id,
        "parentId": s.parent_id,
        "name": s.name,
        "start": s.start,
        "durationMs": round(s.duration * 1e3, 3),
        "tags": s.tags,
        **({"events": list(s.events)} if s.events else {}),
        **({"service": service} if service else {}),
    }


def critical_path(spans: list[dict]) -> list[dict]:
    """Reduce a trace to ordered (stage, micros) wall-clock attribution.

    Every instant of the root span's duration is attributed to exactly
    one span: a parent keeps the time no child covers, overlapping
    siblings are swept first-started-first so parallel hops (hedges,
    fan-out) never double-count. Output is aggregated by span name,
    ordered by first occurrence; the micros sum equals the root span's
    duration by construction."""
    spans = [s for s in spans if s.get("spanId")]
    if not spans:
        return []
    ids = {s["spanId"] for s in spans}
    children: dict[str, list[dict]] = {}
    roots = []
    for s in spans:
        pid = s.get("parentId", "")
        if pid and pid in ids:
            children.setdefault(pid, []).append(s)
        else:
            roots.append(s)
    root = min(roots or spans, key=lambda s: s["start"])
    stages: dict[str, list] = {}  # name -> [seconds, first_start]

    def visit(s: dict, w0: float, w1: float) -> None:
        kids = sorted(children.get(s["spanId"], []),
                      key=lambda c: c["start"])
        cur = w0
        consumed = 0.0
        for c in kids:
            c0 = max(c["start"], cur)
            c1 = min(c["start"] + c.get("durationMs", 0.0) / 1e3, w1)
            if c1 <= c0:
                continue
            visit(c, c0, c1)
            consumed += c1 - c0
            cur = c1
        st = stages.setdefault(s["name"], [0.0, w0])
        st[0] += max(0.0, (w1 - w0) - consumed)
        st[1] = min(st[1], w0)

    visit(root, root["start"],
          root["start"] + root.get("durationMs", 0.0) / 1e3)
    return [
        {"stage": name, "micros": int(round(sec * 1e6))}
        for name, (sec, _first) in sorted(stages.items(),
                                          key=lambda kv: kv[1][1])
    ]


class FlightRecorder:
    """Tail-based slow-trace retention: any trace whose ROOT span
    exceeds its per-op SLO is pinned — with its critical path — into a
    bounded ring, surviving the span buffer / collector LRU. The
    always-on flight recorder that answers "where did that P99 PUT
    spend its time" after the fact (tail sampling, not head sampling)."""

    def __init__(self, max_traces: int = 0):
        from collections import OrderedDict

        from ozone_tpu.utils.config import env_int

        self.max_traces = max_traces or env_int(
            "OZONE_TPU_TRACE_SLOW_RING", 64)
        self._ring: "OrderedDict[str, dict]" = OrderedDict()
        self._lock = threading.Lock()

    @staticmethod
    def slo_s(op: str) -> float:
        """Per-op SLO threshold: OZONE_TPU_TRACE_SLO_<OP>_MS (op is the
        root span name, uppercased, non-alnum -> _), falling back to
        OZONE_TPU_TRACE_SLO_MS (default 1000 ms). Read live so
        operators can retune a running daemon's env between restarts
        and tests can tighten it per-case."""
        from ozone_tpu.utils.config import env_float

        default = env_float("OZONE_TPU_TRACE_SLO_MS", 1000.0)
        key = re.sub(r"[^A-Za-z0-9]+", "_", op).strip("_").upper()
        return env_float(f"OZONE_TPU_TRACE_SLO_{key}_MS", default) / 1e3

    def offer(self, root, spans: list) -> bool:
        """Retain the trace if its root exceeded the op's SLO. `root`
        and `spans` may be Span objects or span_json dicts."""
        rj = span_json(root) if isinstance(root, Span) else root
        if rj.get("durationMs", 0.0) / 1e3 < self.slo_s(rj["name"]):
            return False
        sj = [span_json(s) if isinstance(s, Span) else s for s in spans]
        entry = {
            "traceId": rj["traceId"],
            "root": rj["name"],
            "start": rj["start"],
            "durationMs": rj["durationMs"],
            "sloMs": round(self.slo_s(rj["name"]) * 1e3, 3),
            "spans": sj,
            "criticalPath": critical_path(sj),
        }
        with self._lock:
            self._ring[rj["traceId"]] = entry
            while len(self._ring) > self.max_traces:
                self._ring.popitem(last=False)
        return True

    def append(self, trace_id: str, spans: list[dict]) -> None:
        """Late span arrivals for an already-pinned trace (collector
        assembly is cross-service and out of order)."""
        with self._lock:
            e = self._ring.get(trace_id)
            if e is None:
                return
            e["spans"].extend(spans)
            e["criticalPath"] = critical_path(e["spans"])

    def is_pinned(self, trace_id: str) -> bool:
        with self._lock:
            return trace_id in self._ring

    def slow(self, limit: int = 50) -> list[dict]:
        """Newest-first summaries of retained slow traces."""
        with self._lock:
            entries = list(self._ring.values())[-limit:]
        return [
            {k: e[k] for k in
             ("traceId", "root", "start", "durationMs", "sloMs")}
            | {"spans": len(e["spans"])}
            for e in reversed(entries)
        ]

    def trace(self, trace_id: str) -> Optional[dict]:
        with self._lock:
            e = self._ring.get(trace_id)
            return None if e is None else {
                **e, "spans": list(e["spans"]),
                "criticalPath": list(e["criticalPath"]),
            }


TRACING_SERVICE = "ozone.tpu.Tracing"


class SpanExporter:
    """Ship finished spans to a cluster collector (the reference sends
    every span to Jaeger via the jaeger-client sender — spans here ride
    the existing gRPC plane in batches). Lossy by design: the deque is
    bounded and a down collector just drops batches; tracing must never
    backpressure the datapath."""

    def __init__(self, tracer: Tracer, service: str, address: str = "",
                 tls=None, interval_s: float = 2.0,
                 max_batch: int = 512, collector=None):
        self.tracer = tracer
        self.service = service
        self.address = address
        self.tls = tls
        #: in-process collector: the metadata server feeds its own
        #: spans straight in, no loopback RPC
        self.collector = collector
        self.interval_s = interval_s
        self.max_batch = max_batch
        self.exported = 0
        self._q: deque[Span] = deque(maxlen=10_000)
        tracer._export_q = self._q
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._ch = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"trace-export-{self.service}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)
        self.flush()
        if self._ch is not None:
            self._ch.close()
            self._ch = None

    def flush(self) -> None:
        """Drain and ship everything pending (one batch per call chunk);
        errors drop the batch (collector down != datapath problem)."""
        from ozone_tpu.net import wire as _wire

        batch = []
        while self._q and len(batch) < self.max_batch:
            s = self._q.popleft()
            if TRACING_SERVICE in s.name:
                continue  # never trace the tracing plane itself
            batch.append(s)
        if not batch:
            return
        if self.collector is not None:
            self.collector.add(self.service,
                               [span_json(s) for s in batch])
            self.exported += len(batch)
            return
        try:
            if self._ch is None:
                from ozone_tpu.net.rpc import RpcChannel

                self._ch = RpcChannel(self.address, tls=self.tls,
                                      traced=False)
            self._ch.call(TRACING_SERVICE, "Report", _wire.pack({
                "service": self.service,
                "spans": [span_json(s) for s in batch],
            }))
            self.exported += len(batch)
        except Exception:
            # reconnect next round; spans already popped are dropped
            if self._ch is not None:
                try:
                    self._ch.close()
                except Exception:
                    pass
                self._ch = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.flush()


class TraceCollector:
    """Cluster-wide trace assembly (the Jaeger-collector role): every
    daemon's exporter reports finished spans here; queries see ONE
    trace stitched across services. Bounded LRU over trace ids."""

    def __init__(self, server=None, max_traces: int = 2000):
        from collections import OrderedDict

        self._traces: "OrderedDict[str, dict]" = OrderedDict()
        self.max_traces = max_traces
        self._lock = threading.Lock()
        #: cluster-side flight recorder: roots reported over the wire
        #: pin their whole assembled trace past the LRU
        self.recorder = FlightRecorder()
        if server is not None:
            server.add_service(TRACING_SERVICE, {
                "Report": self._report,
                "Query": self._query,
                "Recent": self._recent,
                "Slow": self._slow,
            })

    # ------------------------------------------------------------ ingest
    def add(self, service: str, spans: list[dict]) -> None:
        slow_roots = []
        late: dict[str, list[dict]] = {}
        with self._lock:
            for sp in spans:
                tid = sp.get("traceId", "")
                if not tid:
                    continue
                sp = dict(sp)
                sp.setdefault("service", service)
                t = self._traces.get(tid)
                if t is None:
                    t = self._traces[tid] = {
                        "spans": [], "services": set(),
                        "start": sp["start"], "end": 0.0,
                    }
                    while len(self._traces) > self.max_traces:
                        self._traces.popitem(last=False)
                t["spans"].append(sp)
                t["services"].add(sp.get("service") or service)
                t["start"] = min(t["start"], sp["start"])
                t["end"] = max(t["end"],
                               sp["start"] + sp["durationMs"] / 1e3)
                if not sp.get("parentId"):
                    slow_roots.append(sp)
                elif self.recorder.is_pinned(tid):
                    late.setdefault(tid, []).append(sp)
        # tail retention outside the assembly lock: offer() re-reads the
        # trace and evaluates the SLO, never blocking concurrent Reports
        for root in slow_roots:
            self.recorder.offer(root, self.trace(root["traceId"]))
        for tid, sps in late.items():
            self.recorder.append(tid, sps)

    def _report(self, req: bytes) -> bytes:
        from ozone_tpu.net import wire as _wire

        m, _ = _wire.unpack(req)
        self.add(m.get("service", ""), m.get("spans", []))
        return _wire.pack({"ok": True})

    # ------------------------------------------------------------- query
    def trace(self, trace_id: str) -> list[dict]:
        with self._lock:
            t = self._traces.get(trace_id)
            if t is not None:
                return sorted((dict(s) for s in t["spans"]),
                              key=lambda s: s["start"])
        # evicted from the LRU but pinned as slow: still answerable
        pinned = self.recorder.trace(trace_id)
        return (sorted(pinned["spans"], key=lambda s: s["start"])
                if pinned else [])

    def recent(self, limit: int = 50) -> list[dict]:
        with self._lock:
            # deep-enough copies: concurrent Report RPCs mutate the
            # per-trace spans list and services set under the lock
            items = [
                (tid, {"spans": list(t["spans"]),
                       "services": set(t["services"]),
                       "start": t["start"], "end": t["end"]})
                for tid, t in list(self._traces.items())[-limit:]
            ]
        out = []
        for tid, t in reversed(items):
            roots = [s["name"] for s in t["spans"]
                     if not s.get("parentId")]
            out.append({
                "traceId": tid,
                "spans": len(t["spans"]),
                "services": sorted(t["services"]),
                "root": roots[0] if roots else t["spans"][0]["name"],
                "start": t["start"],
                "durationMs": round((t["end"] - t["start"]) * 1e3, 3),
            })
        return out

    def _query(self, req: bytes) -> bytes:
        from ozone_tpu.net import wire as _wire

        m, _ = _wire.unpack(req)
        return _wire.pack({"spans": self.trace(m.get("trace_id", ""))})

    def _recent(self, req: bytes) -> bytes:
        from ozone_tpu.net import wire as _wire

        m, _ = _wire.unpack(req)
        return _wire.pack({"traces": self.recent(m.get("limit", 50))})

    def _slow(self, req: bytes) -> bytes:
        from ozone_tpu.net import wire as _wire

        m, _ = _wire.unpack(req)
        tid = m.get("trace_id", "")
        if tid:
            return _wire.pack({"trace": self.recorder.trace(tid)})
        return _wire.pack(
            {"traces": self.recorder.slow(m.get("limit", 50))})


# Histogram exemplars stamp the active trace id (outlier observations
# link a scraped tail bucket to a retained slow trace); registered here
# so metrics stays import-independent of tracing.
from ozone_tpu.utils import metrics as _metrics  # noqa: E402

_metrics.set_trace_id_provider(
    lambda: Tracer.instance().current_trace_id())
