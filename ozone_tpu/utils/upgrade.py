"""Layout-version / upgrade-finalization framework.

Mirror of the reference's non-rolling upgrade machinery (hadoop-hdds/common
ozone/upgrade/: LayoutFeature catalogs HDDSLayoutFeature.java:29 /
OMLayoutFeature.java, BasicUpgradeFinalizer.java:55, request gating by
layout version): each service persists a metadata layout version; new
features declare the version they need; requests/feature paths are gated
until an explicit finalize step runs the feature upgrade actions and bumps
the persisted version.
"""

from __future__ import annotations

import json
import logging
from dataclasses import dataclass, field
from enum import Enum
from pathlib import Path
from typing import Callable, Optional

log = logging.getLogger(__name__)


@dataclass(frozen=True)
class LayoutFeature:
    name: str
    version: int
    description: str = ""


#: Feature catalog (grows monotonically; versions never reused).
INITIAL_VERSION = 0
BUCKET_SNAPSHOTS = LayoutFeature(
    "BUCKET_SNAPSHOTS", 3,
    "bucket snapshot create/delete verbs (OMLayoutFeature analog: "
    "FILESYSTEM_SNAPSHOT)",
)
S3_CHUNKED_UPLOAD = LayoutFeature(
    "S3_CHUNKED_UPLOAD", 4,
    "aws-chunked signed streaming uploads at the S3 gateway",
)
RATIS_STREAMING_WRITE = LayoutFeature(
    "RATIS_STREAMING_WRITE", 5,
    "client-streaming block writes on the datanode "
    "(HDDSLayoutFeature analog: RATIS_DATASTREAM_PORT...)",
)
FEATURES = [
    LayoutFeature("INITIAL", 0, "base layout"),
    LayoutFeature(
        "EC_DEVICE_CODEC", 1,
        "TPU fused encode+CRC chunk checksums on EC writes",
    ),
    LayoutFeature(
        "OM_REPLICATED_LOG", 2, "OM HA request-log replication"
    ),
    BUCKET_SNAPSHOTS,
    S3_CHUNKED_UPLOAD,
    RATIS_STREAMING_WRITE,
]
LATEST_VERSION = max(f.version for f in FEATURES)

#: OM request classes gated on a layout feature — the admission path
#: (OzoneManager.submit) refuses these before the cluster finalizes,
#: the RequestFeatureValidator mechanism
#: (request/validation/RequestFeatureValidator.java:33,84 routed by
#: RequestValidations.java:108). Keyed by request class name so the
#: request module needs no import of this one.
GATED_OM_REQUESTS = {
    "CreateSnapshot": BUCKET_SNAPSHOTS,
    "DeleteSnapshot": BUCKET_SNAPSHOTS,
    "RenameSnapshot": BUCKET_SNAPSHOTS,
}

PRE_FINALIZE_ERROR = "NOT_SUPPORTED_OPERATION_PRIOR_FINALIZATION"


class FinalizationState(Enum):
    ALREADY_FINALIZED = "ALREADY_FINALIZED"
    FINALIZATION_REQUIRED = "FINALIZATION_REQUIRED"
    FINALIZATION_DONE = "FINALIZATION_DONE"


class LayoutVersionManager:
    """Per-service persisted layout version + feature gating.

    Downgrade contract (the reference's non-rolling upgrade promise,
    BasicUpgradeFinalizer.java:55 + Nonrolling-Upgrade.md): a component
    may restart at an OLDER software version any time BEFORE the
    operator finalizes — pre-finalize, new-format features were gated,
    so the on-disk state is old-format by construction. Only a store
    whose version was reached by an explicit finalize refuses older
    software. A pre-finalize downgrade runs CLAMPED to the older
    software's version in memory; the persisted file is untouched, so
    re-upgrading restores the stored version.
    """

    def __init__(self, version_file: Path,
                 software_version: int = LATEST_VERSION):
        self.path = Path(version_file)
        self.software_version = software_version
        #: version the store actually records (>= metadata_version
        #: while running downgraded)
        self.persisted_version = software_version
        self.finalized_marker = False
        if self.path.exists():
            data = json.loads(self.path.read_text())
            self.persisted_version = data["layout_version"]
            # files from before this marker existed were written by
            # fresh installs (never explicitly finalized) -> downgradable
            self.finalized_marker = bool(data.get("finalized", False))
            self.metadata_version = self.persisted_version
        else:
            # fresh install starts at the software version (reference
            # behavior: new clusters don't need finalization)
            self.metadata_version = software_version
            self._persist()
        if self.metadata_version > software_version:
            if self.finalized_marker:
                raise RuntimeError(
                    f"metadata layout {self.metadata_version} was "
                    f"FINALIZED past software {software_version}; "
                    f"post-finalize downgrade not supported"
                )
            log.warning(
                "pre-finalize downgrade: store records layout %d, "
                "software is %d — running clamped to %d (persisted "
                "version kept for re-upgrade)",
                self.persisted_version, software_version,
                software_version)
            self.metadata_version = software_version

    def _persist(self) -> None:
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self.persisted_version = self.metadata_version
        self.path.write_text(
            json.dumps({"layout_version": self.metadata_version,
                        "finalized": self.finalized_marker})
        )

    def is_allowed(self, feature: LayoutFeature) -> bool:
        return feature.version <= self.metadata_version

    def check_allowed(self, feature: LayoutFeature) -> None:
        """Request gating (reference request/validation layer)."""
        if not self.is_allowed(feature):
            raise RuntimeError(
                f"feature {feature.name} needs layout {feature.version}, "
                f"cluster is at {self.metadata_version}; run finalize"
            )

    def needs_finalization(self) -> bool:
        return self.metadata_version < self.software_version


class UpgradeFinalizer:
    """Runs per-feature upgrade actions in version order and bumps the
    persisted version (BasicUpgradeFinalizer.java:55)."""

    def __init__(self, manager: LayoutVersionManager):
        self.manager = manager
        self._actions: dict[int, list[Callable[[], None]]] = {}

    def register_action(self, feature: LayoutFeature,
                        action: Callable[[], None]) -> None:
        self._actions.setdefault(feature.version, []).append(action)

    def finalize(self) -> FinalizationState:
        m = self.manager
        if not m.needs_finalization():
            return FinalizationState.ALREADY_FINALIZED
        # finalization is the operator's point of no return: from here
        # on, older software is refused (the downgrade window closes —
        # BasicUpgradeFinalizer contract)
        m.finalized_marker = True
        for f in sorted(FEATURES, key=lambda f: f.version):
            if m.metadata_version < f.version <= m.software_version:
                for action in self._actions.get(f.version, ()):
                    log.info("running upgrade action for %s", f.name)
                    action()
                m.metadata_version = f.version
                m._persist()
        return FinalizationState.FINALIZATION_DONE

    def status(self) -> dict:
        return {
            "metadata_version": self.manager.metadata_version,
            "software_version": self.manager.software_version,
            "needs_finalization": self.manager.needs_finalization(),
            "features": [
                {
                    "name": f.name,
                    "version": f.version,
                    "allowed": self.manager.is_allowed(f),
                }
                for f in FEATURES
            ],
        }
