"""Test harness config: force JAX onto 8 virtual CPU devices.

Multi-chip sharding is validated on a virtual CPU mesh (the driver
separately dry-runs the multichip path); real-TPU runs happen in bench.py.
The axon environment pins JAX_PLATFORMS=axon via sitecustomize, so env
vars alone don't stick — jax.config.update after import does.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
