"""Test harness config: force JAX onto 8 virtual CPU devices.

Multi-chip sharding is validated on a virtual CPU mesh (the driver
separately dry-runs the multichip path); real-TPU runs happen in bench.py.
The axon environment pins JAX_PLATFORMS=axon via sitecustomize, so env
vars alone don't stick — jax.config.update after import does.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running; excluded from tier-1 runs")
    config.addinivalue_line(
        "markers",
        "serial: subprocess-heavy suites that fork jax-importing "
        "children; serialized behind a cross-process file lock so "
        "parallel runners cannot starve their spawn deadlines")


@pytest.fixture(autouse=True)
def _serialize_marked(request):
    """Cross-process exclusive lock for @pytest.mark.serial tests: the
    subprocess launcher / secure-HA acceptance suites fork whole
    process trees whose jax imports take tens of seconds on a loaded
    one-core rig — two such suites overlapping (xdist, parallel CI
    shards) starve each other's spawn deadlines (CHANGES.md PR 2)."""
    if request.node.get_closest_marker("serial") is None:
        yield
        return
    import fcntl
    import tempfile
    from pathlib import Path

    path = Path(tempfile.gettempdir()) / "ozone_tpu_serial_tests.lock"
    with open(path, "w") as f:
        fcntl.flock(f, fcntl.LOCK_EX)
        try:
            yield
        finally:
            fcntl.flock(f, fcntl.LOCK_UN)
