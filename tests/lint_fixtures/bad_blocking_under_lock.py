# ozlint: path ozone_tpu/storage/_fixture.py
"""Known-bad corpus for `blocking-under-lock`: blocking calls lexically
inside a held lock — the dispatcher/double-buffer race-detector shape."""
import time


class Worker:
    def tick(self):
        with self._lock:
            time.sleep(0.5)  # convoy: every other thread queues here

    def collect(self, fut):
        with self._state_lock:
            return fut.result()  # future join under the lock

    def pump(self):
        self._mutex.acquire()
        item = self._queue.get()  # queue wait between acquire/release
        self._mutex.release()
        return item

    def flush(self, batch):
        with self._cond:
            self._dispatch(batch)  # device dispatch while holding it
