# ozlint: path ozone_tpu/net/_fixture.py
"""Known-bad corpus for `bounded-queue`: unbounded queue construction
on server-side modules — each shape accumulates work without limit, the
collapse mode admission control exists to prevent."""

import collections
import queue


class Dispatcher:
    def __init__(self):
        # no maxsize: accepts work faster than it drains
        self.requests = queue.Queue()
        # deque without maxlen is just as unbounded
        self.backlog = collections.deque()

    def make_priority(self):
        # maxsize=0 means UNLIMITED, not zero
        return queue.PriorityQueue(0)

    def make_simple(self):
        # SimpleQueue has no bound at all
        return queue.SimpleQueue()
