# ozlint: path ozone_tpu/client/native_dn.py
"""Known-bad corpus for `datapath-no-copy`: payload bytes materialized
on a wire-facing datapath module — each shape doubles the memory
traffic of the chunk that crosses it."""
import numpy as np


def recv_frame(conn):
    tag, body = conn.recv(5), conn.recv_body()
    return tag, bytes(body)  # materializes the whole payload


def send_frames(sock, frames):
    sock.sendall(b"".join(bytes(f) for f in frames))


def read_chunk(payload):
    return np.frombuffer(payload, dtype=np.uint8).copy()


def pack_chunk(arr):
    return arr.tobytes()
