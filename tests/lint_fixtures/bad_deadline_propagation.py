# ozlint: path ozone_tpu/client/_fixture.py
"""Known-bad corpus for `deadline-propagation`: every timeout shape the
old regex lint missed — keyword args, computed literals, and constants
resolved through module-level names."""
import socket
import time

CONNECT_TIMEOUT = 60.0 * 2  # computed literal behind a name


def connect(host, port):
    # literal via module constant AND a keyword arg (regex-invisible)
    sock = socket.create_connection((host, port),
                                    timeout=CONNECT_TIMEOUT)
    sock.settimeout(30)  # direct literal socket arm
    return sock


def wait_for(fut, t):
    return fut.result(timeout=5.0)  # literal timeout kwarg


def retry_loop(op):
    for _ in range(3):
        try:
            return op()
        except OSError:
            time.sleep(0.25)  # bare retry sleep, no jitter, no deadline
