# ozlint: path ozone_tpu/codec/_fixture.py
"""Known-bad corpus for `dispatch-shape-stability`: device programs
specialized on known-varying values — one XLA compile per erasure
pattern / batch width (the pre-PR-1 plan-cache thrash)."""
import functools
from functools import lru_cache

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("erased",))
def decode_static_pattern(units, a_bits, erased):
    # every distinct erasure tuple compiles a fresh program
    return units @ a_bits


@lru_cache(maxsize=512)
def decode_plan(options, pattern):
    # per-value jitted closure factory keyed on the varying pattern
    @jax.jit
    def fn(units):
        return units + 1

    return fn


def make_padder(batch):
    @jax.jit
    def pad(x):
        # closure-captured varying width: re-traces per batch size
        return x + jnp.zeros((batch, x.shape[1]), x.dtype)

    return pad
