# ozlint: path ozone_tpu/net/_fixture.py
"""Known-bad corpus for `error-swallowing`: silently dropped datapath
exceptions — a loud failure converted into silent loss."""


def apply_entry(store, entry):
    try:
        store.apply(entry)
    except Exception:
        pass  # swallowed: the replica silently diverges


def read_frame(sock):
    try:
        return sock.recv(4096)
    except:  # bare except: even KeyboardInterrupt vanishes
        return b""
