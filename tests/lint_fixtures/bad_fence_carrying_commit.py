# ozlint: path ozone_tpu/lifecycle/_fixture.py
"""Known-bad corpus for `fence-carrying-commit`: ring mutations of
term-fenced state issued WITHOUT their fencing field — a deposed
leader's late commit or a background job racing a user overwrite."""
from ozone_tpu.om import requests as rq


def expire_key(om, volume, bucket, key):
    # background delete with no rewrite fence: destroys a concurrent
    # user overwrite instead of losing to it
    om.submit(rq.DeleteKey(volume, bucket, key))


def commit_converted(om, session, groups, size):
    om.submit(rq.CommitKey(
        session.volume, session.bucket, session.key,
        session.client_id, size, groups))  # no expect_object_id


def checkpoint_cursor(om, cursor):
    # no `term`: a deposed sweeper's stale cursor could regress the scan
    om.submit(rq.LifecycleCheckpoint(cursor=cursor, stats={}))
