# ozlint: path ozone_tpu/codec/_fixture.py
"""Known-bad corpus for `span-on-dispatch`: device dispatch edges with
no active span (the flight recorder attributes their time to the
parent and the critical path lies), plus an RPC handler registration
that dodges net/rpc.py's server-span guard."""
import numpy as np


def submit_untraced(fn, batch):
    # async dispatch + eager D2H with no span anywhere in the function
    outs = fn(batch)
    _start_d2h(outs)
    return np.asarray(outs)


def sync_pull(arr):
    # a bare device sync: this wall time is invisible to attribution
    arr.block_until_ready()
    return np.asarray(arr)


def eager_hint(out):
    # raw D2H hint outside any span or carried context
    out.copy_to_host_async()
    return out


def register_handlers(server, service):
    # bypasses RpcServer.add_service, so no server:<method> span and no
    # wire trace-context extraction
    server.add_generic_rpc_handlers((service,))


def _start_d2h(out):
    return out
