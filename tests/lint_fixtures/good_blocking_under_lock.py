# ozlint: path ozone_tpu/storage/_fixture.py
"""Known-good corpus for `blocking-under-lock`: state mutation under the
lock, blocking work outside it; Condition.wait is exempt (it releases)."""
import time


class Worker:
    def tick(self):
        with self._lock:
            wait = self._deficit / self._rate
        time.sleep(wait)  # paced OUTSIDE the lock

    def collect(self, fut):
        out = fut.result()  # join first...
        with self._state_lock:
            self._results.append(out)  # ...book under the lock
        return out

    def pump(self):
        with self._cond:
            while not self._ready:
                self._cond.wait(self._next_wakeup())  # releases the lock
            batch = self._take_locked()
        self._dispatch(batch)  # chip dispatch with no lock held
        return batch
