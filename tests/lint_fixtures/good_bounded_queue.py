# ozlint: path ozone_tpu/net/_fixture.py
"""Known-good corpus for `bounded-queue`: every server-side queue
carries an explicit bound (or a reasoned suppression naming the
machinery that bounds its depth)."""

import collections
import queue

DEPTH = 256


class Dispatcher:
    def __init__(self, depth):
        self.requests = queue.Queue(maxsize=DEPTH)
        self.backlog = collections.deque(maxlen=depth)
        # bound as the second positional arg is a bound too
        self.recent = collections.deque([], 64)

    def make_priority(self, depth):
        # a non-constant bound is assumed deliberate
        return queue.PriorityQueue(depth)

    def make_acked(self):
        # depth provably bounded elsewhere: callers block on the ack
        return queue.Queue()  # ozlint: allow[bounded-queue] -- fixture: callers block on an ack condition, depth capped by the ack window
