# ozlint: path ozone_tpu/client/native_dn.py
"""Known-good corpus for `datapath-no-copy`: payloads travel as views;
control-plane materializations carry a reasoned suppression; size
preallocations are not copies."""
import json

import numpy as np


def recv_frame(conn):
    tag, body = conn.recv(5), conn.recv_body()
    return tag, memoryview(body)  # view over the pooled recv buffer


def send_frames(sock, views):
    sock.sendmsg([memoryview(v) for v in views])  # gathered, no join


def read_chunk(payload):
    return np.frombuffer(payload, dtype=np.uint8)  # zero-copy view


def parse_status(body):
    # a STATUS frame is tens of bytes of JSON, not payload
    return json.loads(bytes(body))  # ozlint: allow[datapath-no-copy] -- control-plane STATUS JSON, not payload


def make_scratch():
    return bytes(4096)  # size preallocation, nothing copied
