# ozlint: path ozone_tpu/client/_fixture.py
"""Known-good corpus for `deadline-propagation`: every timeout derives
from the ambient Deadline, an env knob, or a dynamic expression."""
import socket

from ozone_tpu.client import resilience


def connect(host, port, default_s):
    sock = socket.create_connection(
        (host, port),
        timeout=resilience.op_timeout(default_s, "connect"))
    sock.settimeout(resilience.op_timeout(default_s, "io"))
    return sock


def wait_for(fut, deadline):
    return fut.result(timeout=deadline.remaining())


def retry_loop(op, policy):
    for attempt in range(8):
        try:
            return op()
        except OSError:
            if not policy.sleep(attempt):  # jittered + deadline-clipped
                raise
