# ozlint: path ozone_tpu/codec/_fixture.py
"""Known-good corpus for `dispatch-shape-stability`: varying values ride
as traced arrays; caches are keyed only on config-stable values."""
import functools
from functools import lru_cache

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("zeros_crc",))
def decode_apply(units, a_bits, zeros_crc):
    # recovery matrix is a traced ARG: one program for every pattern
    return units @ a_bits + zeros_crc


@lru_cache(maxsize=16)
def encode_plan(options, checksum, bpc):
    # cache keyed on config-stable coder options only
    @jax.jit
    def fn(data):
        return data + jnp.zeros((data.shape[0], 1), data.dtype)

    return fn
