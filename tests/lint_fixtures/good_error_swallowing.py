# ozlint: path ozone_tpu/net/_fixture.py
"""Known-good corpus for `error-swallowing`: handled, logged, or
suppressed with a written reason."""
import logging

log = logging.getLogger(__name__)


def apply_entry(store, entry):
    try:
        store.apply(entry)
    except Exception as e:
        log.warning("apply of %s failed: %s", entry, e)
        raise


def close_quietly(sock):
    try:
        sock.close()
    except OSError:  # ozlint: allow[error-swallowing] -- best-effort teardown, nothing to recover
        pass
