# ozlint: path ozone_tpu/lifecycle/_fixture.py
"""Known-good corpus for `fence-carrying-commit`: every fenced mutation
carries its term / expected object id."""
from ozone_tpu.om import requests as rq


def expire_key(om, volume, bucket, key, info):
    om.submit(rq.DeleteKey(volume, bucket, key,
                           expect_object_id=info["object_id"]))


def commit_converted(om, session, groups, size, info):
    om.submit(rq.CommitKey(
        session.volume, session.bucket, session.key,
        session.client_id, size, groups,
        expect_object_id=info["object_id"]))


def checkpoint_cursor(om, term, cursor):
    om.submit(rq.LifecycleCheckpoint(term, cursor=cursor, stats={}))
