# ozlint: path ozone_tpu/codec/_fixture.py
"""Known-good corpus for `span-on-dispatch`: every dispatch edge runs
under an open span, a fabricated record_span, or a carried context, and
handlers register through RpcServer.add_service (the span guard)."""
import numpy as np

from ozone_tpu.utils.tracing import Tracer


def submit_traced(fn, batch):
    with Tracer.instance().span("codec:dispatch", rows=len(batch)):
        outs = fn(batch)
        _start_d2h(outs)
    return np.asarray(outs)


def sync_pull_fabricated(arr, t0, t1):
    # completion thread: fabricate the finished span around the sync
    arr.block_until_ready()
    Tracer.instance().record_span("codec:device_dispatch", t0, t1)
    return np.asarray(arr)


def eager_hint_carried(out, ctx):
    # worker thread carrying the submitter's trace context
    with Tracer.instance().activate(ctx):
        out.copy_to_host_async()
    return out


def register_handlers(server, service):
    # the one sanctioned path: wraps every handler in server:<method>
    server.add_service(service)


def _start_d2h(out):
    return out
