"""Acceptance suite: real daemon subprocesses driven through the CLI.

Analog of the reference's robot-framework smoketests run against
docker-compose clusters (hadoop-ozone/dist smoketest/ + compose/): here
the scm-om and datanode daemons run as actual OS processes and every
interaction goes through the public `ozone-tpu` CLI, validating the
process entry points end-to-end (basic + EC suite).
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent

#: every test here forks daemon process trees whose jax imports cost
#: seconds each; overlapping another subprocess-heavy suite on a
#: one-core rig starves the spawn deadlines (CHANGES.md PR 2) — the
#: serial marker takes a cross-process lock (conftest) so at most one
#: such suite runs at a time
pytestmark = pytest.mark.serial


def _budget(base_s: float) -> float:
    """Load-aware deadline: scale a spawn/poll allowance by how
    oversubscribed the CPU is. A fixed constant is wrong in both
    directions — too tight on a loaded one-core rig (where forking a
    jax-importing child takes many times longer) and needlessly long on
    an idle machine. Capped at 4x so a pathological load average can't
    turn a real hang into an hour-long wait."""
    try:
        load = os.getloadavg()[0]
    except OSError:  # platform without getloadavg
        return base_s
    scale = load / max(1, os.cpu_count() or 1)
    return base_s * min(4.0, max(1.0, scale))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _cli(args: list[str], check=True, timeout=60) -> subprocess.CompletedProcess:
    env = dict(os.environ, PYTHONPATH=str(REPO), JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, "-m", "ozone_tpu.tools", *args],
        capture_output=True, text=True, timeout=_budget(timeout),
        check=check, cwd=str(REPO), env=env,
    )


@pytest.fixture(scope="module")
def live_cluster(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("acc")
    port = _free_port()
    env = dict(os.environ, PYTHONPATH=str(REPO), JAX_PLATFORMS="cpu")
    procs = []
    with open(tmp / "meta.log", "w") as meta_log:
        meta = subprocess.Popen(
            [sys.executable, "-m", "ozone_tpu.tools", "scm-om",
             "--db", str(tmp / "om.db"), "--port", str(port)],
            stdout=meta_log, stderr=subprocess.STDOUT, text=True,
            cwd=str(REPO), env=env,
        )  # the child holds its own duplicated descriptor
    procs.append(meta)
    om = f"127.0.0.1:{port}"
    # wait for the metadata server (generous: each status poll is a
    # full CLI process whose jax import costs seconds under suite load;
    # the loop exits as soon as the server answers)
    t0 = time.time()
    # budget re-derived per poll: the spawned cluster itself
    # drives the load average up mid-test
    while time.time() - t0 < _budget(90):
        try:
            _cli(["admin", "status", "--om", om], timeout=10)
            break
        except (subprocess.CalledProcessError, subprocess.TimeoutExpired):
            time.sleep(0.5)
    else:
        meta.kill()
        pytest.fail("scm-om daemon did not come up")
    for i in range(5):
        p = subprocess.Popen(
            [sys.executable, "-m", "ozone_tpu.tools", "datanode",
             "--root", str(tmp / f"dn{i}"), "--scm", om, "--id", f"dn{i}"],
            stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT, text=True,
            cwd=str(REPO), env=env,
        )
        procs.append(p)
    # wait for registrations (same contention headroom as above)
    t0 = time.time()
    # budget re-derived per poll: the spawned cluster itself
    # drives the load average up mid-test
    while time.time() - t0 < _budget(90):
        out = _cli(["admin", "datanode", "--om", om]).stdout
        if len(json.loads(out)) == 5:
            break
        time.sleep(0.5)
    else:
        pytest.fail("datanodes did not register")
    yield om, tmp
    for p in procs:
        p.send_signal(signal.SIGTERM)
    for p in procs:
        try:
            p.wait(timeout=5)
        except subprocess.TimeoutExpired:
            p.kill()


def test_smoke_basic_namespace(live_cluster):
    om, tmp = live_cluster
    _cli(["sh", "volume", "create", "/vol1", "--om", om])
    _cli(["sh", "bucket", "create", "/vol1/b1", "--om", om,
          "--replication", "rs-3-2-4096"])
    out = _cli(["sh", "bucket", "list", "/vol1", "--om", om]).stdout
    assert [b["name"] for b in json.loads(out)] == ["b1"]


def test_smoke_ec_key_roundtrip(live_cluster):
    om, tmp = live_cluster
    _cli(["sh", "volume", "create", "/vol2", "--om", om])
    _cli(["sh", "bucket", "create", "/vol2/ec", "--om", om,
          "--replication", "rs-3-2-4096"])
    payload = bytes(np.random.default_rng(0).integers(0, 256, 100_000,
                                                      dtype=np.uint8))
    src = tmp / "in.bin"
    src.write_bytes(payload)
    _cli(["sh", "key", "put", "/vol2/ec/key1", str(src), "--om", om])
    dst = tmp / "out.bin"
    _cli(["sh", "key", "get", "/vol2/ec/key1", str(dst), "--om", om])
    assert dst.read_bytes() == payload
    info = json.loads(
        _cli(["sh", "key", "info", "/vol2/ec/key1", "--om", om]).stdout
    )
    assert info["size"] == 100_000
    # replica verification over the wire
    rep = _cli(["debug", "verify-replicas", "/vol2/ec/key1", "--om", om])
    statuses = {r["status"] for r in json.loads(rep.stdout)}
    assert statuses == {"ok"}


def test_smoke_freon_ockg(live_cluster):
    om, tmp = live_cluster
    out = _cli(["freon", "ockg", "-n", "10", "-s", "4096", "-t", "2",
                "--om", om, "--replication", "rs-3-2-4096"],
               timeout=120).stdout
    rep = json.loads(out)
    assert rep["ops"] == 10 and rep["failures"] == 0


def test_smoke_data_lifecycle_verbs(live_cluster):
    """The session's lifecycle surface end-to-end through the CLI:
    quota, snapshots (+.snapshot reads), composite checksum, bucket
    links, hsync freon, audit parser (robot ec/ + admincli parity)."""
    om, tmp = live_cluster
    _cli(["sh", "volume", "create", "/lc", "--om", om])
    _cli(["sh", "bucket", "create", "/lc/b", "--om", om,
          "--replication", "rs-3-2-4096"])
    payload = bytes(np.random.default_rng(7).integers(0, 256, 30_000,
                                                      dtype=np.uint8))
    src = tmp / "lc.bin"
    src.write_bytes(payload)

    # quota: set, exceed, inspect
    _cli(["sh", "bucket", "setquota", "/lc/b", "--om", om,
          "--quota", "40KB"])
    _cli(["sh", "key", "put", "/lc/b/doc", str(src), "--om", om])
    over = _cli(["sh", "key", "put", "/lc/b/doc2", str(src), "--om", om],
                check=False)
    assert over.returncode != 0 and "QUOTA_EXCEEDED" in over.stderr
    info = json.loads(
        _cli(["sh", "bucket", "info", "/lc/b", "--om", om]).stdout)
    assert info["used_bytes"] == 30_000

    # composite checksum equals a local CRC32C of the payload
    cs = json.loads(
        _cli(["sh", "key", "checksum", "/lc/b/doc", "--om", om]).stdout)
    from ozone_tpu.utils.checksum import crc32c

    assert int(cs["checksum"], 16) == crc32c(
        np.frombuffer(payload, np.uint8))

    # snapshot + .snapshot read + diff
    _cli(["sh", "snapshot", "create", "/lc/b", "--om", om,
          "--name", "s1"])
    _cli(["sh", "key", "delete", "/lc/b/doc", "--om", om])
    diff = json.loads(_cli(["sh", "snapshot", "diff", "/lc/b", "--om",
                            om, "--name", "s1"]).stdout)
    assert diff["deleted"] == ["doc"]
    snap_out = tmp / "snap.bin"
    _cli(["sh", "key", "get", "/lc/b/.snapshot/s1/doc", str(snap_out),
          "--om", om])
    assert snap_out.read_bytes() == payload
    _cli(["sh", "snapshot", "delete", "/lc/b", "--om", om,
          "--name", "s1"])

    # bucket link: write through the alias, read from the source
    _cli(["sh", "volume", "create", "/lk", "--om", om])
    _cli(["sh", "bucket", "link", "/lc/b", "--to", "/lk/alias",
          "--om", om])
    _cli(["sh", "bucket", "setquota", "/lc/b", "--om", om,
          "--quota", "clear"])
    _cli(["sh", "key", "put", "/lk/alias/via-link", str(src),
          "--om", om])
    got = tmp / "via.bin"
    _cli(["sh", "key", "get", "/lc/b/via-link", str(got), "--om", om])
    assert got.read_bytes() == payload

    # hsync generator (RATIS replication)
    rep = json.loads(_cli(["freon", "hsg", "-n", "4", "-s", "4096",
                           "--om", om], timeout=120).stdout)
    assert rep["failures"] == 0

    # audit parser over the REAL daemon log: this suite's own verbs
    # must appear in the aggregation
    top = json.loads(
        _cli(["audit", "top", str(tmp / "meta.log")]).stdout)
    actions = {row["action"] for row in top}
    assert {"CreateVolume", "CommitKey", "CreateSnapshot"} <= actions


def test_ha_cluster_subprocesses(tmp_path):
    """HA acceptance: three scm-om OS processes on one raft ring, five
    datanode processes, CLI writes through the failover address list,
    SIGKILL the leader process, writes continue, old data intact."""
    from ozone_tpu.testing.minicluster import free_ports

    env = dict(os.environ, PYTHONPATH=str(REPO), JAX_PLATFORMS="cpu")
    ports = free_ports(3)
    peers = {f"m{i}": f"127.0.0.1:{ports[i]}" for i in range(3)}
    peer_flags = []
    for mid, addr in peers.items():
        peer_flags += ["--peer", f"{mid}={addr}"]
    procs: dict[str, subprocess.Popen] = {}

    def start_meta(mid: str) -> None:
        procs[mid] = subprocess.Popen(
            [sys.executable, "-m", "ozone_tpu.tools", "scm-om",
             "--db", str(tmp_path / mid / "om.db"),
             "--port", peers[mid].rsplit(":", 1)[1],
             "--ha-id", mid, *peer_flags],
            stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT, text=True,
            cwd=str(REPO), env=env,
        )

    oms = ",".join(peers.values())
    dn_procs = []
    try:
        for mid in peers:
            start_meta(mid)
        t0 = time.time()
        # budget re-derived per poll: the spawned cluster itself
        # drives the load average up mid-test
        while time.time() - t0 < _budget(90):
            try:
                _cli(["admin", "status", "--om", oms], timeout=10)
                break
            except (subprocess.CalledProcessError,
                    subprocess.TimeoutExpired):
                time.sleep(0.5)
        else:
            pytest.fail("HA ring did not come up")
        for i in range(5):
            p = subprocess.Popen(
                [sys.executable, "-m", "ozone_tpu.tools", "datanode",
                 "--root", str(tmp_path / f"dn{i}"), "--scm", oms,
                 "--id", f"dn{i}"],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                text=True, cwd=str(REPO), env=env,
            )
            dn_procs.append(p)
        t0 = time.time()
        # budget re-derived per poll: the spawned cluster itself
        # drives the load average up mid-test
        while time.time() - t0 < _budget(90):
            try:
                out = _cli(["admin", "status", "--om", oms],
                           timeout=20).stdout
            except (subprocess.CalledProcessError,
                    subprocess.TimeoutExpired):
                time.sleep(0.5)
                continue
            if out.count("HEALTHY") >= 5 and '"safemode": false' in out:
                break
            time.sleep(0.5)

        payload = np.random.default_rng(3).integers(
            0, 256, 120_000, dtype=np.uint8).tobytes()
        src = tmp_path / "payload.bin"
        src.write_bytes(payload)
        _cli(["sh", "volume", "create", "/v", "--om", oms])
        _cli(["sh", "bucket", "create", "/v/b", "--om", oms,
              "--replication", "rs-3-2-4096"])
        _cli(["sh", "key", "put", "/v/b/k1", str(src), "--om", oms])

        # find and SIGKILL the leader process: a follower's error names
        # the leader address
        leader_addr = None
        for mid, addr in peers.items():
            r = _cli(["admin", "om", "prepare", "--om", addr],
                     check=False, timeout=15)
            if r.returncode != 0 and "OM_NOT_LEADER" in r.stderr:
                hint = r.stderr.rsplit(":", 1)[-1].strip()
                if hint.isdigit():
                    leader_addr = f"127.0.0.1:{hint}"
                    break
            elif r.returncode == 0:
                leader_addr = addr  # this one IS the leader
                _cli(["admin", "om", "cancelprepare", "--om", addr],
                     timeout=15)
                break
        assert leader_addr, "could not locate the leader"
        leader_id = next(m for m, a in peers.items() if a == leader_addr)
        procs[leader_id].kill()
        procs[leader_id].wait(timeout=10)

        # failover: writes and reads continue against the survivors
        _cli(["sh", "key", "put", "/v/b/k2", str(src), "--om", oms],
             timeout=90)
        for key in ("k1", "k2"):
            dst = tmp_path / f"out_{key}.bin"
            _cli(["sh", "key", "get", f"/v/b/{key}", str(dst),
                  "--om", oms], timeout=90)
            assert dst.read_bytes() == payload, key
    finally:
        for p in dn_procs:
            p.send_signal(signal.SIGTERM)
        for p in procs.values():
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in [*dn_procs, *procs.values()]:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def test_smoke_round3_verbs(live_cluster):
    """This round's operator surface end-to-end through the CLI:
    delegation tokens, key rewrite/cat/cp, bucket set-replication,
    volume owner update, list-open-files, paged snapshot diff, live
    reconfig, dnsim."""
    om, tmp = live_cluster
    _cli(["sh", "volume", "create", "/r3", "--om", om])
    _cli(["sh", "bucket", "create", "/r3/b", "--om", om,
          "--replication", "RATIS/THREE"])
    payload = bytes(np.random.default_rng(11).integers(
        0, 256, 20_000, dtype=np.uint8))
    src = tmp / "r3.bin"
    src.write_bytes(payload)
    _cli(["sh", "key", "put", "/r3/b/k", str(src), "--om", om])

    # delegation tokens: get -> print -> renew -> cancel -> renew fails
    tok = tmp / "tok.json"
    # renewer must be the CLI's login identity: anonymous remote renew
    # is refused since round 4, and the CLI renews as the login user
    import getpass

    _cli(["sh", "token", "get", "--om", om, "--renewer",
          getpass.getuser(), "--token", str(tok)])
    assert json.loads(tok.read_text())["renewer"] == getpass.getuser()
    _cli(["sh", "token", "renew", "--om", om, "--token", str(tok)])
    _cli(["sh", "token", "cancel", "--om", om, "--token", str(tok)])
    dead = _cli(["sh", "token", "renew", "--om", om,
                 "--token", str(tok)], check=False)
    assert dead.returncode != 0 and "TOKEN_ERROR" in dead.stderr

    # rewrite RATIS -> EC, data intact, cat matches
    _cli(["sh", "key", "rewrite", "/r3/b/k", "--om", om,
          "--replication", "rs-3-2-4096"])
    info = json.loads(
        _cli(["sh", "key", "info", "/r3/b/k", "--om", om]).stdout)
    assert info["replication"] == "rs-3-2-4096"
    # cat streams raw bytes to stdout: run binary-mode
    cat = subprocess.run(
        [sys.executable, "-m", "ozone_tpu.tools", "sh", "key", "cat",
         "/r3/b/k", "--om", om],
        capture_output=True, timeout=60, check=True, cwd=str(REPO),
        env=dict(os.environ, PYTHONPATH=str(REPO), JAX_PLATFORMS="cpu"),
    )
    assert cat.stdout == payload
    out = tmp / "cat.bin"
    _cli(["sh", "key", "get", "/r3/b/k", str(out), "--om", om])
    assert out.read_bytes() == payload

    # cp into a second bucket; destination bucket's replication applies
    _cli(["sh", "bucket", "create", "/r3/b2", "--om", om,
          "--replication", "rs-3-2-4096"])
    _cli(["sh", "key", "cp", "/r3/b/k", "--om", om, "--to", "/r3/b2/k2"])
    got = tmp / "cp.bin"
    _cli(["sh", "key", "get", "/r3/b2/k2", str(got), "--om", om])
    assert got.read_bytes() == payload

    # bucket set-replication + volume owner update
    _cli(["sh", "bucket", "set-replication", "/r3/b", "--om", om,
          "--replication", "rs-3-2-4096"])
    binfo = json.loads(
        _cli(["sh", "bucket", "info", "/r3/b", "--om", om]).stdout)
    assert binfo["replication"] == "rs-3-2-4096"
    _cli(["sh", "volume", "update", "/r3", "--om", om, "--user", "alice"])
    vinfo = json.loads(
        _cli(["sh", "volume", "info", "/r3", "--om", om]).stdout)
    assert vinfo["owner"] == "alice"

    # paged snapshot diff as JSON lines
    _cli(["sh", "snapshot", "create", "/r3/b", "--om", om,
          "--name", "d1"])
    _cli(["sh", "key", "delete", "/r3/b/k", "--om", om])
    _cli(["sh", "snapshot", "create", "/r3/b", "--om", om,
          "--name", "d2"])
    paged = _cli(["sh", "snapshot", "diff", "/r3/b", "--om", om,
                  "--name", "d1", "--to", "d2", "--page-size", "1"])
    lines = [json.loads(line) for line in paged.stdout.splitlines()]
    assert {"op": "DELETE", "key": "k"} in lines

    # list-open-files over gRPC (no sessions open right now)
    lof = json.loads(_cli(["admin", "om", "list-open-files", "/r3/b",
                           "--om", om]).stdout)
    assert lof["open_files"] == []

    # dnsim registers simulated nodes without polluting placement
    rep = json.loads(_cli(["freon", "dnsim", "-n", "4", "--containers",
                           "2", "--duration", "1", "--interval", "0.3",
                           "--om", om], timeout=120).stdout)
    assert rep["failures"] == 0 and rep["datanodes"] == 4
    nodes = json.loads(_cli(["admin", "datanode", "--om", om]).stdout)
    sims = [n for n in nodes if n["dn_id"].startswith("simdn")]
    assert len(sims) == 4
    assert all(n["op_state"] == "IN_MAINTENANCE" for n in sims)


def test_cluster_launcher_supervises_and_tears_down(tmp_path):
    """`ozone-tpu cluster`: the one-command compose-cluster analog
    spawns scm-om + datanodes, serves traffic, and SIGTERM reaps every
    child."""
    env = dict(os.environ, PYTHONPATH=str(REPO), JAX_PLATFORMS="cpu")
    port = _free_port()
    sup = subprocess.Popen(
        [sys.executable, "-m", "ozone_tpu.tools", "cluster",
         "--datanodes", "2", "--port", str(port),
         "--root", str(tmp_path / "cl")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=str(REPO), env=env,
    )
    om = f"127.0.0.1:{port}"
    try:
        t0 = time.time()
        ready = False
        # budget re-derived per poll: the launcher's children drive the
        # load average up mid-test
        while time.time() - t0 < _budget(60):
            try:
                out = _cli(["admin", "datanode", "--om", om],
                           timeout=10).stdout
                if len(json.loads(out)) == 2:
                    ready = True
                    break
            except (subprocess.CalledProcessError,
                    subprocess.TimeoutExpired):
                pass
            time.sleep(0.5)
        assert ready, "cluster launcher never became healthy"
        _cli(["sh", "volume", "create", "/clv", "--om", om])
    finally:
        sup.send_signal(signal.SIGTERM)
        try:
            sup.wait(timeout=20)
        except subprocess.TimeoutExpired:
            sup.kill()
    # all children reaped: the om port stops answering
    t0 = time.time()
    gone = False
    while time.time() - t0 < _budget(15):
        r = _cli(["admin", "status", "--om", om], check=False, timeout=10)
        if r.returncode != 0:
            gone = True
            break
        time.sleep(0.5)
    assert gone, "children survived supervisor teardown"


def test_secure_ha_gateway_combined(tmp_path, monkeypatch):
    """The verdict-3 combined-dimension acceptance (reference's
    ozonesecure compose + omha smoketests in ONE cluster): CA + mTLS +
    block tokens on, THREE metadata replicas on one ring, five
    datanodes, S3 and HttpFS gateway processes — run a workload, SIGKILL
    the ring leader, and assert gateway requests ride the failover with
    certs and tokens intact (old objects still GET, new PUTs land)."""
    # the secure stack needs the cryptography package; on rigs without
    # it every secure daemon dies at import and this test burned minutes
    # of suite budget "waiting" for a ring that could never form — skip
    # cleanly instead (the unit TLS suites hit the same gate as
    # collection errors)
    pytest.importorskip("cryptography")
    import urllib.request

    from ozone_tpu.testing.minicluster import free_ports

    secret = "combined-drill"
    ports = free_ports(4)
    enroll_port = ports[3]
    enroll = f"127.0.0.1:{enroll_port}"
    peers = {f"m{i}": f"127.0.0.1:{ports[i]}" for i in range(3)}
    oms = ",".join(peers.values())
    peer_flags = []
    for mid, addr in peers.items():
        peer_flags += ["--peer", f"{mid}={addr}"]
    cert_dir = tmp_path / "client-certs"
    # in os.environ so the shared _cli helper (admin status, etc.)
    # presents a client cert too — every control call needs mTLS here
    monkeypatch.setenv("OZONE_TPU_CERT_DIR", str(cert_dir))
    monkeypatch.setenv("OZONE_TPU_ENROLL", enroll)
    monkeypatch.setenv("OZONE_TPU_ENROLL_SECRET", secret)
    env = dict(os.environ, PYTHONPATH=str(REPO), JAX_PLATFORMS="cpu")
    metas: dict[str, subprocess.Popen] = {}
    others: list[subprocess.Popen] = []

    def start_meta(mid: str) -> None:
        sec = (["--secure", "--block-tokens", "--enroll-port",
                str(enroll_port), "--enrollment-secret", secret]
               if mid == "m0" else
               ["--secure", "--block-tokens", "--ca", enroll,
                "--enrollment-secret", secret])
        metas[mid] = subprocess.Popen(
            [sys.executable, "-m", "ozone_tpu.tools", "scm-om",
             "--db", str(tmp_path / mid / "om.db"),
             "--port", peers[mid].rsplit(":", 1)[1],
             "--ha-id", mid, *peer_flags, *sec],
            stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
            text=True, cwd=str(REPO), env=env)

    def http(method, url, data=None, timeout=30):
        req = urllib.request.Request(url, data=data, method=method)
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.read()

    try:
        # the primordial hosts the CA; replicas enroll there before
        # joining the ring, so it must come up first
        start_meta("m0")
        t0 = time.time()
        # budget re-derived per poll: the spawned cluster itself
        # drives the load average up mid-test
        while time.time() - t0 < _budget(60):
            r = _cli(["admin", "status", "--om", peers["m0"]],
                     check=False, timeout=15)
            if r.returncode == 0 or "NOT_LEADER" in (r.stderr or ""):
                break
            time.sleep(0.5)
        for mid in ("m1", "m2"):
            start_meta(mid)
        t0 = time.time()
        # budget re-derived per poll: the spawned cluster itself
        # drives the load average up mid-test
        while time.time() - t0 < _budget(120):
            r = _cli(["admin", "status", "--om", oms], check=False,
                     timeout=15)
            if r.returncode == 0:
                break
            time.sleep(0.5)
        else:
            pytest.fail("secure HA ring did not come up")

        for i in range(5):
            others.append(subprocess.Popen(
                [sys.executable, "-m", "ozone_tpu.tools", "datanode",
                 "--root", str(tmp_path / f"dn{i}"), "--scm", oms,
                 "--id", f"dn{i}", "--ca", enroll,
                 "--enrollment-secret", secret],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                text=True, cwd=str(REPO), env=env))
        t0 = time.time()
        # budget re-derived per poll: the spawned cluster itself
        # drives the load average up mid-test
        while time.time() - t0 < _budget(120):
            r = _cli(["admin", "status", "--om", oms], check=False,
                     timeout=20)
            if r.returncode == 0 and r.stdout.count("HEALTHY") >= 5 \
                    and '"safemode": false' in r.stdout:
                break
            time.sleep(0.5)
        else:
            pytest.fail("datanodes never registered over mTLS")
        # block-token enforcement is actually ON ring-wide
        assert '"block_tokens": true' in _cli(
            ["admin", "status", "--om", oms], timeout=20).stdout

        s3_port, hf_port = free_ports(2)
        # gateway processes enroll their own client certs (separate
        # dirs: each is its own identity, like real deployments)
        s3_env = dict(env, OZONE_TPU_CERT_DIR=str(tmp_path / "s3-certs"))
        hf_env = dict(env, OZONE_TPU_CERT_DIR=str(tmp_path / "hf-certs"))
        others.append(subprocess.Popen(
            [sys.executable, "-m", "ozone_tpu.tools", "s3g",
             "--om", oms, "--port", str(s3_port),
             "--replication", "rs-3-2-4096"],
            stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
            text=True, cwd=str(REPO), env=s3_env))
        others.append(subprocess.Popen(
            [sys.executable, "-m", "ozone_tpu.tools", "httpfs",
             "--om", oms, "--port", str(hf_port),
             "--replication", "rs-3-2-4096"],
            stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
            text=True, cwd=str(REPO), env=hf_env))
        s3 = f"http://127.0.0.1:{s3_port}"
        hf = f"http://127.0.0.1:{hf_port}/webhdfs/v1"
        t0 = time.time()
        # budget re-derived per poll: the spawned cluster itself
        # drives the load average up mid-test
        while time.time() - t0 < _budget(90):
            try:
                http("GET", f"{s3}/", timeout=5)
                http("GET", f"{hf}/?op=LISTSTATUS", timeout=5)
                break
            except OSError:
                time.sleep(1.0)
        else:
            pytest.fail("gateways never came up")

        payload = np.random.default_rng(11).integers(
            0, 256, 60_000, dtype=np.uint8).tobytes()
        # workload through BOTH gateways (tokens + mTLS under the hood)
        http("PUT", f"{s3}/combined")
        http("PUT", f"{s3}/combined/before", data=payload)
        assert http("GET", f"{s3}/combined/before") == payload
        http("PUT", f"{hf}/v1/hbkt?op=MKDIRS")
        r = urllib.request.urlopen(urllib.request.Request(
            f"{hf}/v1/hbkt/f1?op=CREATE&data=true", data=payload,
            method="PUT"), timeout=60)
        assert r.status in (200, 201)

        # locate + SIGKILL the ring leader process
        leader_addr = None
        for mid, addr in peers.items():
            r = _cli(["admin", "om", "prepare", "--om", addr],
                     check=False, timeout=20)
            if r.returncode != 0 and "OM_NOT_LEADER" in r.stderr:
                hint = r.stderr.rsplit(":", 1)[-1].strip()
                if hint.isdigit():
                    leader_addr = f"127.0.0.1:{hint}"
                    break
            elif r.returncode == 0:
                leader_addr = addr
                _cli(["admin", "om", "cancelprepare", "--om", addr],
                     timeout=20)
                break
        assert leader_addr, "could not locate the leader"
        leader_id = next(m for m, a in peers.items()
                         if a == leader_addr)
        metas[leader_id].kill()
        metas[leader_id].wait(timeout=10)

        # the gateways must ride the failover: old data still GETs, new
        # PUTs land, all THROUGH the same gateway processes (their OM
        # clients rotate to a surviving replica; fresh block tokens are
        # minted by the new leader; mTLS certs stay valid)
        def retry(fn, deadline_s=120):
            last = None
            t0 = time.time()
            while time.time() - t0 < _budget(deadline_s):
                try:
                    return fn()
                except OSError as e:
                    last = e
                    time.sleep(2.0)
            raise AssertionError(f"gateway never recovered: {last}")

        assert retry(lambda: http(
            "GET", f"{s3}/combined/before")) == payload
        retry(lambda: http("PUT", f"{s3}/combined/after", data=payload))
        assert retry(lambda: http(
            "GET", f"{s3}/combined/after")) == payload
        got = retry(lambda: http("GET", f"{hf}/v1/hbkt/f1?op=OPEN"))
        assert got == payload
    finally:
        for p in others:
            p.send_signal(signal.SIGTERM)
        for p in metas.values():
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in [*others, *metas.values()]:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
