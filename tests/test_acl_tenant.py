"""Native ACLs (volume/bucket/key/prefix) + multi-tenancy.

Mirrors the reference's ACL/tenant test surface (TestOzoneNativeAuthorizer,
TestOmAcls, PrefixManager tests, TestOMTenantCreateRequest et al.):
grant parse/merge semantics, authorizer resolution order with
longest-prefix override, DEFAULT-scope inheritance, deny auditing, and
tenant lifecycle with access-id S3 secrets.
"""

import pytest

from ozone_tpu.om import requests as rq
from ozone_tpu.om.acl import (
    ACLDeniedError,
    ACLIdentityType,
    ACLRight,
    ACLScope,
    OzoneAcl,
    add_acl,
    remove_acl,
)
from ozone_tpu.om.om import OzoneManager
from ozone_tpu.scm.scm import StorageContainerManager


@pytest.fixture
def om(tmp_path):
    scm = StorageContainerManager(stale_after_s=1e6, dead_after_s=2e6)
    for i in range(5):
        scm.register_datanode(f"dn{i}")
    om = OzoneManager(tmp_path / "om.db", scm)
    om.create_volume("v1", owner="owner1")
    om.create_bucket("v1", "b1", "rs-3-2-4096")
    yield om
    om.close()


def test_acl_parse_and_string_roundtrip():
    a = OzoneAcl.parse("user:alice:rwl[DEFAULT]")
    assert a.id_type is ACLIdentityType.USER
    assert a.rights == {ACLRight.READ, ACLRight.WRITE, ACLRight.LIST}
    assert a.scope is ACLScope.DEFAULT
    assert OzoneAcl.parse(str(a)) == a
    world = OzoneAcl.parse("world::a")
    assert world.rights == ACLRight.all()
    assert world.scope is ACLScope.ACCESS


def test_add_remove_merge_semantics():
    acls, ch = add_acl([], OzoneAcl.parse("user:u:r"))
    assert ch
    acls, ch = add_acl(acls, OzoneAcl.parse("user:u:w"))
    assert ch and len(acls) == 1
    assert set(acls[0]["rights"]) == {"r", "w"}
    acls, ch = add_acl(acls, OzoneAcl.parse("user:u:r"))
    assert not ch  # idempotent
    acls, ch = remove_acl(acls, OzoneAcl.parse("user:u:w"))
    assert ch and set(acls[0]["rights"]) == {"r"}
    acls, ch = remove_acl(acls, OzoneAcl.parse("user:u:r"))
    assert ch and acls == []


def test_authorizer_volume_bucket_key_chain(om):
    om.enable_acls()
    om.modify_acl("bucket", "v1", "b1", op="add",
                  acls=["user:alice:rl"])
    # alice can READ at bucket scope, bob cannot
    om.check_access("v1", "b1", None, "READ", user="alice")
    with pytest.raises(ACLDeniedError):
        om.check_access("v1", "b1", None, "READ", user="bob")
    # owner and superuser always pass
    om.check_access("v1", "b1", None, "WRITE", user="owner1")
    om.check_access("v1", "b1", None, "WRITE", user="root")
    # key ACLs require the key row to exist (reference KEY_NOT_FOUND)
    with pytest.raises(rq.OMError):
        om.modify_acl("key", "v1", "b1", "k-missing", op="add",
                      acls=["user:bob:r"])
    # group grants
    om.modify_acl("bucket", "v1", "b1", op="add", acls=["group:devs:w"])
    om.check_access("v1", "b1", None, "WRITE", user="carol", groups=["devs"])
    with pytest.raises(ACLDeniedError):
        om.check_access("v1", "b1", None, "DELETE", user="carol", groups=["devs"])


def test_prefix_acls_longest_match(om):
    om.enable_acls()
    om.modify_acl("prefix", "v1", "b1", "logs/", op="add",
                  acls=["user:reader:rl"])
    om.modify_acl("prefix", "v1", "b1", "logs/secret/", op="add",
                  acls=["user:reader:l"])  # narrower prefix: no READ
    om.check_access("v1", "b1", "logs/app.log", "READ", user="reader")
    with pytest.raises(ACLDeniedError):
        om.check_access("v1", "b1", "logs/secret/x", "READ", user="reader")
    om.check_access("v1", "b1", "logs/secret/x", "LIST", user="reader")
    assert om.get_acls("prefix", "v1", "b1", "logs/")


def test_default_scope_inheritance(om):
    om.modify_acl("volume", "v1", op="add",
                  acls=["user:team:rwcl[DEFAULT]"])
    om.create_bucket("v1", "b2", "rs-3-2-4096")
    grants = om.get_acls("bucket", "v1", "b2")
    assert any(g["name"] == "team" and g["scope"] == "ACCESS"
               for g in grants)
    # pre-existing bucket b1 is unaffected
    assert not any(g.get("name") == "team"
                   for g in om.get_acls("bucket", "v1", "b1"))


def test_modify_acl_missing_object(om):
    with pytest.raises(rq.OMError):
        om.modify_acl("bucket", "v1", "nope", op="add", acls=["user:u:r"])
    with pytest.raises(rq.OMError):
        om.modify_acl("badtype", "v1", op="add", acls=["user:u:r"])


def test_tenant_lifecycle(om):
    om.create_tenant("acme")
    assert om.volume_info("acme")["name"] == "acme"
    assert [t["tenant"] for t in om.list_tenants()] == ["acme"]
    with pytest.raises(rq.OMError):
        om.create_tenant("acme")

    grant = om.tenant_assign_user("acme", "alice")
    assert grant["access_id"] == "acme$alice"
    assert len(grant["secret"]) == 40
    # S3 auth path: secret resolvable, tenant mapped
    assert om.get_s3_secret("acme$alice", create=False) == grant["secret"]
    assert om.tenant_for_access_id("acme$alice")["volume"] == "acme"
    assert om.list_tenant_users("acme")[0]["user"] == "alice"

    # non-empty tenant refuses deletion
    with pytest.raises(rq.OMError):
        om.delete_tenant("acme")
    om.tenant_revoke_access("acme$alice")
    assert om.get_s3_secret("acme$alice", create=False) is None
    assert om.tenant_for_access_id("acme$alice") is None
    om.delete_tenant("acme")
    assert om.list_tenants() == []
    with pytest.raises(rq.OMError):
        om.tenant_revoke_access("acme$alice")


def test_enforcement_in_om_verbs(om):
    """enable_acls + a bound user identity actually gates the verbs
    (reference OzoneNativeAuthorizer wired through OzoneManager)."""
    import numpy as np

    om.enable_acls()
    om.modify_acl("bucket", "v1", "b1", op="add", acls=["user:alice:rcl"])
    with om.user_context("alice"):
        om.list_keys("v1", "b1")            # LIST granted
        om.open_key("v1", "b1", "k1")       # CREATE granted
        with pytest.raises(ACLDeniedError):
            om.delete_key("v1", "b1", "k1")  # DELETE not granted
        with pytest.raises(ACLDeniedError):
            om.create_volume("valice")       # admin-only
        with pytest.raises(ACLDeniedError):
            om.create_tenant("talice")       # admin-only
        with pytest.raises(ACLDeniedError):
            om.modify_acl("bucket", "v1", "b1", op="add",
                          acls=["user:alice:a"])  # WRITE_ACL not granted
    with om.user_context("mallory"):
        with pytest.raises(ACLDeniedError):
            om.list_keys("v1", "b1")
        with pytest.raises(ACLDeniedError):
            om.open_key("v1", "b1", "k2")
    # unbound (in-process trusted) callers are unaffected
    om.list_keys("v1", "b1")


def test_tenant_cannot_hijack_existing_volume(om):
    with pytest.raises(rq.OMError) as ei:
        om.create_tenant("sneaky", volume="v1")
    assert ei.value.code == rq.VOLUME_ALREADY_EXISTS
    # assign twice -> refuses to rotate the issued secret
    om.create_tenant("tx")
    om.tenant_assign_user("tx", "u")
    with pytest.raises(rq.OMError) as ei:
        om.tenant_assign_user("tx", "u")
    assert ei.value.code == rq.ACCESS_ID_ALREADY_EXISTS
    # unknown acl op is rejected, not treated as remove
    with pytest.raises(rq.OMError):
        om.modify_acl("bucket", "v1", "b1", op="REPLACE",
                      acls=["user:u:r"])


def test_fso_key_acls(om):
    om.create_bucket("v1", "fso", "rs-3-2-4096",
                     layout="FILE_SYSTEM_OPTIMIZED")
    # write a small file through the normal FSO path
    s = om.open_key("v1", "fso", "dir/sub/file.txt")
    om.commit_key(s, [], 0)
    assert om.modify_acl("key", "v1", "fso", "dir/sub/file.txt", op="add",
                         acls=["user:fred:r"]) is True
    grants = om.get_acls("key", "v1", "fso", "dir/sub/file.txt")
    assert grants and grants[0]["name"] == "fred"
    om.enable_acls()
    om.check_access("v1", "fso", "dir/sub/file.txt", "READ", user="fred")
    with pytest.raises(ACLDeniedError):
        om.check_access("v1", "fso", "dir/sub/file.txt", "WRITE",
                        user="fred")


def test_remote_identity_enforcement(tmp_path):
    """The _user identity rides the OM RPC and is enforced server-side."""
    from ozone_tpu.net.daemons import ScmOmDaemon
    from ozone_tpu.net.om_service import GrpcOmClient
    from ozone_tpu.storage.ids import StorageError

    meta = ScmOmDaemon(tmp_path / "om.db", stale_after_s=1e6,
                       dead_after_s=2e6)
    meta.start()
    try:
        om = GrpcOmClient(meta.address)
        om.create_volume("v")
        om.create_bucket("v", "b", "rs-3-2-4096")
        meta.om.enable_acls()
        om.modify_acl("bucket", "v", "b", op="add", acls=["user:alice:l"])
        with om.user_context("alice"):
            om.list_keys("v", "b")
            with pytest.raises(StorageError) as ei:
                om.delete_bucket("v", "b")
            assert ei.value.code == "PERMISSION_DENIED"
        om.list_keys("v", "b")  # unbound: trusted
    finally:
        meta.stop()


def test_acl_tenant_over_grpc(tmp_path):
    """Remote OM path: ModifyAcl/GetAcls + tenant verbs over the wire."""
    from ozone_tpu.net.daemons import ScmOmDaemon
    from ozone_tpu.net.om_service import GrpcOmClient

    meta = ScmOmDaemon(tmp_path / "om.db", stale_after_s=1e6,
                       dead_after_s=2e6)
    meta.start()
    try:
        om = GrpcOmClient(meta.address)
        om.create_volume("v")
        om.create_bucket("v", "b", "rs-3-2-4096")
        assert om.modify_acl("bucket", "v", "b", op="add",
                             acls=["user:alice:rl"]) is True
        grants = om.get_acls("bucket", "v", "b")
        assert grants and grants[0]["name"] == "alice"

        om.create_tenant("corp")
        tok = om.tenant_assign_user("corp", "bob")
        assert tok["access_id"] == "corp$bob"
        assert om.list_tenant_users("corp")[0]["user"] == "bob"
        assert [t["tenant"] for t in om.list_tenants()] == ["corp"]
        om.tenant_revoke_access("corp$bob")
        om.delete_tenant("corp")
    finally:
        meta.stop()


def test_tenant_requests_replicate_deterministically(tmp_path):
    """Tenant + ACL requests flow through the replicated request log like
    every other OM write (serde roundtrip + follower apply)."""
    r = rq.AssignUserToTenant("t", "u", access_id="t$u", secret="s" * 40)
    assert rq.OMRequest.from_json(r.to_json()) == r
    a = rq.ModifyAcl("bucket", "v", "b", op="add",
                     acls=[OzoneAcl.parse("user:x:r").to_json()])
    assert rq.OMRequest.from_json(a.to_json()) == a


def test_volume_owner_transfer(om):
    """ozone sh volume update --user (OMVolumeSetOwnerRequest): owner or
    superuser transfers; others denied when ACLs are on."""
    out = om.set_volume_owner("v1", "owner2")
    assert out["owner"] == "owner2"
    om.enable_acls(superusers=("root",))
    with om.user_context("mallory"):
        with pytest.raises(rq.OMError):
            om.set_volume_owner("v1", "mallory")
    with om.user_context("owner2"):
        assert om.set_volume_owner("v1", "owner3")["owner"] == "owner3"
    with om.user_context("root"):
        assert om.set_volume_owner("v1", "owner4")["owner"] == "owner4"
    assert om.volume_info("v1")["owner"] == "owner4"
