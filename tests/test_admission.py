"""End-to-end overload protection (docs/OPERATIONS.md "Admission
control").

Three layers of proof:

1. Unit contracts — the non-booking ``Throttle.try_take`` hint, tenant
   bucket isolation, the bounded in-flight gate, SLO shedding by
   priority, and the ``retry_after_s=`` hint round-trip.
2. The wire contract — a bucket-refused S3 request maps to a
   DETERMINISTIC 503 SlowDown with a Retry-After header, and an
   OM-side refusal is honored by the client as backoff-not-failure
   (same peer, floor from the hint, op still succeeds).
3. Isolation on a live cluster — a flooding tenant is shed while an
   interactive victim keeps its tail latency budget, with every
   rejection visible in the ``admission`` registry.
"""

import contextlib
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from ozone_tpu import admission
from ozone_tpu.admission import (
    AdmissionController,
    InflightGate,
    SloShedder,
    TenantBuckets,
    busy_error,
    retry_after_hint,
)
from ozone_tpu.client import resilience
from ozone_tpu.gateway.s3 import S3Gateway
from ozone_tpu.gateway.s3_auth import sign_request
from ozone_tpu.storage.ids import StorageError
from ozone_tpu.testing.minicluster import (
    MiniOzoneCluster,
    MiniOzoneHACluster,
)
from ozone_tpu.utils.metrics import registry
from ozone_tpu.utils.throttle import Throttle

EC = "rs-3-2-4096"


@contextlib.contextmanager
def _admit_env(**knobs):
    """Set OZONE_TPU_ADMIT_<K>=v knobs, drop the controller cache so
    they take effect, and restore + reset on the way out."""
    saved = {}
    try:
        for k, v in knobs.items():
            key = f"OZONE_TPU_ADMIT_{k}"
            saved[key] = os.environ.get(key)
            os.environ[key] = str(v)
        admission.reset_for_tests()
        yield
    finally:
        for key, v in saved.items():
            if v is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = v
        admission.reset_for_tests()


def _scale() -> float:
    """Load-aware latency-budget multiplier (same discipline as
    test_soak._starve_floor): an oversubscribed rig runs every thread
    in slow motion, so tail budgets scale with load instead of flaking."""
    try:
        load = os.getloadavg()[0]
    except OSError:
        return 1.0
    return min(4.0, max(1.0, load / max(1, os.cpu_count() or 1)))


# ------------------------------------------------------- unit contracts
def test_throttle_try_take_admits_now_or_hints_without_booking():
    th = Throttle(10.0, burst_s=1.0)  # 10 tokens of burst
    assert th.try_take(10) == 0.0  # whole burst admitted instantly
    hint1 = th.try_take(5)
    assert hint1 > 0.0  # refused: bucket empty
    hint2 = th.try_take(5)
    # the refusal did NOT book the 5 tokens: the second hint is not a
    # doubled wait, it is the same ~0.5 s until 5 tokens accumulate
    assert hint2 == pytest.approx(hint1, abs=0.2)


def test_tenant_buckets_isolate_tenants():
    b = TenantBuckets(ops_per_s=2.0, burst_s=1.0)
    assert b.enabled
    assert b.try_admit("noisy") == (None, 0.0)
    assert b.try_admit("noisy") == (None, 0.0)
    reason, wait = b.try_admit("noisy")
    assert reason == "ops" and wait > 0.0
    # a different tenant's bucket is untouched by the noisy one
    assert b.try_admit("quiet") == (None, 0.0)


def test_tenant_buckets_bytes_dimension_caps_single_charge():
    b = TenantBuckets(bytes_per_s=1000.0, burst_s=1.0)
    # a single request larger than the whole burst is admitted once
    # (charge capped at the bucket size) rather than being unservable
    assert b.try_admit("t", nbytes=50_000) == (None, 0.0)
    reason, wait = b.try_admit("t", nbytes=100)
    assert reason == "bytes" and wait > 0.0


def test_inflight_gate_bounds_and_zero_disables():
    g = InflightGate(2)
    assert g.try_enter() and g.try_enter()
    assert not g.try_enter()
    g.exit()
    assert g.try_enter()
    off = InflightGate(0)
    assert all(off.try_enter() for _ in range(100))


def test_slo_shedder_sheds_bulk_spares_interactive():
    depth = registry("codec.service").gauge("queue_depth")
    prev = depth.value
    try:
        s = SloShedder(codec_depth=4, cache_s=0.0)
        assert s.enabled
        depth.set(10)
        assert s.over_budget() == "slo_codec_depth"
        assert s.should_shed("bulk") == "slo_codec_depth"
        assert s.should_shed("interactive") is None
        depth.set(0)
        assert s.over_budget() is None
        assert not SloShedder().enabled  # all thresholds 0 = off
    finally:
        depth.set(prev)


def test_retry_after_hint_roundtrip_and_cap():
    e = busy_error("om", "ops", 0.5)
    assert e.code == admission.SERVER_BUSY
    assert "om overloaded (ops)" in str(e)
    assert retry_after_hint(str(e)) == pytest.approx(0.5)
    # a deranged hint is capped so a client never parks for minutes
    assert retry_after_hint("retry_after_s=999") == 30.0
    assert retry_after_hint("no hint here") is None


def test_controller_queue_gate_rejects_and_counts():
    m = registry("admission")
    ctl = AdmissionController("testhop", queue_limit=1,
                              exempt=("Heartbeat",))
    before = m.counter("testhop_rejected_queue").value
    with ctl.admit("PutKey"):
        with pytest.raises(StorageError) as ei:
            with ctl.admit("PutKey"):
                pass
        assert ei.value.code == admission.SERVER_BUSY
        assert retry_after_hint(str(ei.value)) is not None
        # exempt control-plane verbs ride through a full queue
        with ctl.admit("Heartbeat"):
            pass
    assert m.counter("testhop_rejected_queue").value == before + 1
    assert m.counter("testhop_rejected_total").value >= before + 1
    assert ctl.gate.inflight == 0


def test_controller_charge_rejects_per_tenant():
    m = registry("admission")
    ctl = AdmissionController("testhop2", ops_per_s=1.0, burst_s=1.0)
    before = m.counter("testhop2_tenant_rejections").value
    ctl.charge("tenant-a")
    with pytest.raises(StorageError) as ei:
        ctl.charge("tenant-a")
    assert ei.value.code == admission.SERVER_BUSY
    assert retry_after_hint(str(ei.value)) > 0.0
    ctl.charge("tenant-b")  # other tenants unaffected
    assert m.counter("testhop2_tenant_rejections").value == before + 1
    assert m.counter("testhop2_rejected_ops").value >= 1


def test_server_busy_is_not_a_transport_fault():
    """The load-bearing classification: pushback comes from a healthy
    peer, so it must never trip circuit breakers or failover rotation —
    that would turn graceful shedding into a cascading brownout."""
    assert resilience.SERVER_BUSY not in resilience.TRANSPORT_FAULT_CODES


def test_server_pushback_floor_classifies_and_counts():
    before = resilience.METRICS.counter("server_busy").value
    floor = resilience.server_pushback_floor(
        busy_error("om", "ops", 0.4), "om")
    assert floor == pytest.approx(0.4)
    assert resilience.METRICS.counter("server_busy").value == before + 1
    assert resilience.METRICS.counter("server_busy_om").value >= 1
    # anything that is not SERVER_BUSY is not a pushback
    assert resilience.server_pushback_floor(
        StorageError("TIMEOUT", "deadline"), "om") is None
    assert resilience.server_pushback_floor(ValueError("x"), "om") is None
    assert resilience.METRICS.counter("server_busy").value == before + 1


def test_retry_policy_sleep_honors_pushback_floor():
    p = resilience.RetryPolicy(base_s=0.001, cap_s=0.002, max_attempts=4)
    t0 = time.monotonic()
    assert p.sleep(0, floor_s=0.08)
    took = time.monotonic() - t0
    assert took >= 0.08  # hint is a FLOOR under the jittered draw
    t0 = time.monotonic()
    assert p.sleep(0)  # no floor: the tiny backoff stays tiny
    assert time.monotonic() - t0 < 0.05


def test_qos_class_map_and_ambient_context():
    with _admit_env(CLASS="batchco=bulk, liveco = interactive"):
        assert admission.qos_class_for("batchco") == "bulk"
        assert admission.qos_class_for("liveco") == "interactive"
        assert admission.qos_class_for("unknown") == "interactive"
        assert admission.current_tenant() is None
        assert admission.ambient_qos("bulk") == "bulk"  # default passes
        with admission.tenant_context("batchco"):
            assert admission.current_tenant() == "batchco"
            assert admission.ambient_qos() == "bulk"
        assert admission.current_tenant() is None


def test_per_hop_knob_override():
    with _admit_env(OPS="0", OPS_GATEWAY="7"):
        gw = admission.controller("gateway")
        om = admission.controller("om")
        assert gw.buckets.ops_per_s == 7.0
        assert om.buckets.ops_per_s == 0.0


# --------------------------------------------------------- live cluster
@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    c = MiniOzoneCluster(
        tmp_path_factory.mktemp("admission"),
        num_datanodes=5,
        block_size=8 * 4096,
        container_size=4 * 1024 * 1024,
        stale_after_s=1000.0,
        dead_after_s=2000.0,
    )
    yield c
    c.close()


@pytest.fixture(scope="module")
def gw(cluster):
    g = S3Gateway(cluster.client(), replication=EC, require_auth=True)
    g.start()
    yield g
    g.stop()


def _signed(gw, creds, method, path, body=b""):
    access, secret = creds
    url = f"http://{gw.address}{path}"
    headers = {
        "host": gw.address,
        "x-amz-date": time.strftime("%Y%m%dT%H%M%SZ", time.gmtime()),
    }
    headers = sign_request(access, secret, method, url, headers, body)
    req = urllib.request.Request(url, data=body or None, method=method,
                                 headers=headers)
    return urllib.request.urlopen(req)


def test_gateway_maps_server_busy_to_503_slowdown(gw, cluster):
    """Satellite 1: the S3 wire contract. With a 1 op/s tenant budget
    the second back-to-back request is DETERMINISTICALLY refused: 503,
    S3 ``SlowDown`` error code, and a Retry-After header the SDKs'
    retry middlewares already honor."""
    secret = cluster.client().om.get_s3_secret("admituser")
    creds = ("admituser", secret)
    with _admit_env(OPS_GATEWAY="1", BURST_S="1"):
        assert _signed(gw, creds, "PUT", "/admitbkt").status == 200
        with pytest.raises(urllib.error.HTTPError) as ei:
            _signed(gw, creds, "PUT", "/admitbkt")
        e = ei.value
        body = e.read().decode()
        assert e.code == 503
        assert "<Code>SlowDown</Code>" in body
        assert "retry_after_s=" in body  # machine-readable hint survives
        ra = e.headers.get("Retry-After")
        assert ra is not None and int(ra) >= 1
        e.close()


@pytest.fixture(scope="module")
def grpc_cluster(tmp_path_factory):
    """gRPC-served OM (MiniOzoneCluster wires the OM in-process without
    the network layer, so OM-hop admission never runs there)."""
    c = MiniOzoneHACluster(tmp_path_factory.mktemp("admissionha"),
                           num_meta=1, num_datanodes=1)
    yield c
    c.shutdown()


def test_om_pushback_is_backoff_not_failure(grpc_cluster):
    """Satellite 2: a SERVER_BUSY refusal from the OM is absorbed by
    the client retry loop — backoff to the hinted floor, SAME peer (no
    failover rotation, no breaker trip) — so a paced-down caller still
    succeeds on every op."""
    oz = grpc_cluster.client()
    m = registry("admission")
    rej_before = m.counter("om_rejected_ops").value
    busy_before = resilience.METRICS.counter("server_busy").value
    with _admit_env(OPS_OM="4", BURST_S="0.5"):
        for _ in range(8):  # unpaced: ~2 tokens of burst, 4/s refill
            oz.om.list_volumes()  # must never raise
    assert m.counter("om_rejected_ops").value > rej_before, \
        "flood never tripped the OM bucket — test proved nothing"
    assert resilience.METRICS.counter("server_busy").value > busy_before


def test_per_tenant_isolation_under_flood(gw, cluster):
    """The tentpole acceptance: an aggressor tenant flooding the
    gateway is shed (visibly, in admission.*) while an interactive
    victim tenant keeps its unloaded tail budget — isolation, not
    fate-sharing."""
    om = cluster.client().om
    om.create_tenant("victimco")
    victim = ("victimco-creds", "")
    grant = om.tenant_assign_user("victimco", "vuser")
    victim = (grant["access_id"], grant["secret"])
    om.create_tenant("floodco")
    grant = om.tenant_assign_user("floodco", "fuser")
    flood = (grant["access_id"], grant["secret"])

    m = registry("admission")
    with _admit_env(OPS_GATEWAY="10", BURST_S="1",
                    CLASS="floodco=bulk"):
        _signed(gw, victim, "PUT", "/vb")
        _signed(gw, victim, "PUT", "/vb/obj", b"v" * 1024)
        _signed(gw, flood, "PUT", "/fb")
        time.sleep(0.4)  # refill what setup spent

        def victim_pass(n=10):
            lat = []
            for _ in range(n):
                t0 = time.perf_counter()
                _signed(gw, victim, "GET", "/vb/obj").read()
                lat.append(time.perf_counter() - t0)
                time.sleep(0.12)  # ~8 ops/s: inside the 10/s budget
            return max(lat)

        p99_unloaded = victim_pass()

        rej_before = m.counter("gateway_rejected_total").value
        shed = {"n": 0, "errors": 0}
        stop = threading.Event()

        def aggressor():
            body = b"f" * 2048
            i = 0
            while not stop.is_set():
                try:
                    _signed(gw, flood, "PUT", f"/fb/k{i % 8}", body)
                except urllib.error.HTTPError as e:
                    if e.code == 503:
                        shed["n"] += 1
                    else:
                        shed["errors"] += 1
                    e.close()
                i += 1

        th = threading.Thread(target=aggressor, daemon=True)
        th.start()
        try:
            p99_loaded = victim_pass()
        finally:
            stop.set()
            th.join(timeout=10)

    # the aggressor was shed, deterministically and observably
    assert shed["n"] > 0, "flood was never refused"
    assert shed["errors"] == 0, f"flood hit non-503 errors: {shed}"
    assert m.counter("gateway_rejected_total").value > rej_before
    assert m.counter("gateway_tenant_rejections").value > 0
    # the victim's tail stayed inside its unloaded budget (load-aware)
    budget = 2.0 * max(p99_unloaded, 0.05) * _scale()
    assert p99_loaded <= budget, (
        f"victim p99 {p99_loaded * 1e3:.1f} ms > budget "
        f"{budget * 1e3:.1f} ms (unloaded {p99_unloaded * 1e3:.1f} ms)")


def test_admission_snapshot_shape():
    """/api/admission contract: every installed controller reports its
    knobs, live in-flight depth, tenants seen, and shed state."""
    with _admit_env(OPS_OM="4"):
        admission.controller("om").charge("tenant-x")
        snaps = {hop: c.snapshot()
                 for hop, c in admission.controllers().items()}
        assert "om" in snaps
        s = snaps["om"]
        assert s["enabled"] and s["ops_per_s"] == 4.0
        assert s["queue_limit"] == 256
        assert isinstance(s["tenants"], list) and s["tenants"]
        assert set(s["shed"]) == {"p99_ms", "codec_depth", "mesh_depth",
                                  "over_budget"}


# -------------------------------------------------- small-object packer
def test_packer_flush_charges_bulk_and_isolates_tenants(tmp_path):
    """Satellite: slab-flush traffic is admission-visible. Packer PUTs
    charge the OWNING tenant's gateway byte bucket at ``bulk`` QoS, so
    (a) a mass-ingest tenant is shed at the gateway with the typed
    SERVER_BUSY + Retry-After contract while a light tenant on the same
    cluster keeps writing, and (b) the moment the SLO shedder crosses a
    budget, packer traffic is dropped FIRST (bulk class) while an
    interactive charge on the very same controller still admits."""
    import numpy as np

    c = MiniOzoneCluster(tmp_path, num_datanodes=5,
                         stale_after_s=1000.0, dead_after_s=2000.0)
    try:
        oz = c.client()
        for vol in ("floodco", "liveco"):
            oz.create_volume(vol)
            oz.get_volume(vol).create_bucket("b", replication=EC)
            c.om.set_bucket_smallobj(vol, "b")
        payload = np.random.default_rng(0).integers(
            0, 256, 9_000, dtype=np.uint8)
        m = registry("admission")

        # (a) per-tenant byte buckets: 30 KB/s of gateway budget admits
        # ~3 needles then refuses; the other tenant's bucket is full
        with _admit_env(BYTES_GATEWAY="30000", BURST_S="1"):
            rej0 = m.counter("gateway_tenant_rejections").value
            flood = oz.get_volume("floodco").get_bucket("b")
            shed = None
            for i in range(16):
                try:
                    flood.write_key(f"f-{i}", payload)
                except StorageError as e:
                    shed = e
                    break
            assert shed is not None, "flood tenant was never shed"
            assert shed.code == resilience.SERVER_BUSY
            assert retry_after_hint(shed) > 0.0
            assert m.counter("gateway_tenant_rejections").value > rej0
            # isolation: the victim's OWN bucket is untouched by the
            # flood tenant's exhaustion
            live = oz.get_volume("liveco").get_bucket("b")
            for i in range(3):
                live.write_key(f"l-{i}", payload)
            np.testing.assert_array_equal(live.read_key("l-0"), payload)

        # (b) bulk-class shed: cross the codec backlog budget and the
        # packer's charge (bulk) is refused while interactive admits
        with _admit_env(SLO_CODEC_DEPTH_GATEWAY="2", BYTES_GATEWAY="0"):
            registry("codec.service").gauge("queue_depth").set(10)
            try:
                with pytest.raises(StorageError) as ei:
                    oz.get_volume("floodco").get_bucket("b").write_key(
                        "bulk-shed", payload)
                assert ei.value.code == resilience.SERVER_BUSY
                # same controller, interactive priority: still admitted
                admission.controller("gateway").charge(
                    "liveco", 9_000, priority="interactive")
            finally:
                registry("codec.service").gauge("queue_depth").set(0)
    finally:
        c.close()
