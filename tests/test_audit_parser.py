"""Audit parser tool (ozone auditparser analog): tolerant JSON-line
parsing, filters, frequency aggregation, failures view, CLI."""

import json

from ozone_tpu.tools.audit_parser import (
    aggregate,
    failures,
    filter_records,
    parse_file,
    parse_line,
)
from ozone_tpu.utils.audit import AuditLogger


def test_parse_line_tolerates_logging_prefix():
    rec = parse_line(
        'INFO 2026-07-30 audit.om: {"ts": 1.0, "user": "alice", '
        '"action": "CreateVolume", "params": {}, "result": "SUCCESS"}'
    )
    assert rec["action"] == "CreateVolume" and rec["user"] == "alice"
    assert parse_line("not json at all") is None
    assert parse_line('{"no_action": true}') is None


def test_roundtrip_through_real_audit_logger(tmp_path, caplog):
    import logging

    logfile = tmp_path / "audit.log"
    handler = logging.FileHandler(logfile)
    logger = logging.getLogger("audit.test-component")
    logger.addHandler(handler)
    logger.setLevel(logging.INFO)
    try:
        al = AuditLogger("test-component")
        al.log("CreateVolume", {"volume": "v"}, user="alice")
        al.log("CreateBucket", {"bucket": "b"}, user="alice")
        al.log("DeleteKey", {"key": "k"}, ok=False, error="nope",
               user="bob")
        handler.flush()
    finally:
        logger.removeHandler(handler)
        handler.close()
    recs = list(parse_file(logfile))
    assert len(recs) == 3
    assert [r["action"] for r in recs] == [
        "CreateVolume", "CreateBucket", "DeleteKey"]
    assert aggregate(recs, by="user")[0] == {"user": "alice", "count": 2}
    fails = failures(recs)
    assert len(fails) == 1 and fails[0]["error"] == "nope"
    only_bob = list(filter_records(recs, user="bob"))
    assert len(only_bob) == 1 and only_bob[0]["action"] == "DeleteKey"


def test_cli_top_and_failures(tmp_path, capsys):
    from ozone_tpu.tools.cli import main

    logfile = tmp_path / "a.log"
    lines = []
    for i in range(5):
        lines.append(json.dumps({
            "ts": float(i), "user": "u", "action": "Put",
            "params": {}, "result": "SUCCESS"}))
    lines.append(json.dumps({
        "ts": 9.0, "user": "u", "action": "Get", "params": {},
        "result": "FAILURE", "error": "boom"}))
    logfile.write_text("\n".join(lines) + "\n")

    assert main(["audit", "top", str(logfile)]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out[0] == {"action": "Put", "count": 5}

    assert main(["audit", "failures", str(logfile)]) == 0
    out = json.loads(capsys.readouterr().out)
    assert len(out) == 1 and out[0]["error"] == "boom"
