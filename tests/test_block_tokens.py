"""Block/container token enforcement on the datanode datapath.

The reference verifies a token on every dispatcher op
(hadoop-hdds/container-service HddsDispatcher + framework
BlockTokenVerifier.java); these tests prove the same over real gRPC:
a secure cluster serves tokened clients normally and refuses untokened,
mis-scoped, wrong-block, and expired requests with
BLOCK_TOKEN_VERIFICATION_FAILED.
"""

import time

import numpy as np
import pytest

from ozone_tpu.client.dn_client import DatanodeClientFactory, TokenStore
from ozone_tpu.client.ozone_client import OzoneClient
from ozone_tpu.net.daemons import DatanodeDaemon, ScmOmDaemon
from ozone_tpu.net.dn_service import GrpcDatanodeClient
from ozone_tpu.net.om_service import GrpcOmClient
from ozone_tpu.storage.ids import BlockID, ChunkInfo, StorageError
from ozone_tpu.utils.checksum import Checksum, ChecksumType
from ozone_tpu.utils.security import (
    AccessMode,
    BlockTokenIssuer,
    BlockTokenVerifier,
    SecretKeyManager,
    TokenError,
)

EC = "rs-3-2-4096"


# ---------------------------------------------------------------- unit level
def test_container_token_roundtrip():
    keys = SecretKeyManager()
    issuer = BlockTokenIssuer(keys)
    verifier = BlockTokenVerifier(keys)
    tok = issuer.issue_container(42)
    verifier.verify_container(tok, 42)
    with pytest.raises(TokenError):
        verifier.verify_container(tok, 43)


def test_scope_confusion_refused():
    """A block token must not authorize container ops and vice versa."""
    keys = SecretKeyManager()
    issuer = BlockTokenIssuer(keys)
    verifier = BlockTokenVerifier(keys)
    btok = issuer.issue(BlockID(7, 1), [AccessMode.READ, AccessMode.WRITE])
    ctok = issuer.issue_container(7)
    with pytest.raises(TokenError):
        verifier.verify_container(btok, 7)
    with pytest.raises(TokenError):
        verifier.verify(ctok, BlockID(7, 1), AccessMode.READ)


def test_token_store_self_issuer():
    """Datanode-side TokenHelper analog: with the secret keys installed,
    the store mints tokens for blocks it has never seen."""
    keys = SecretKeyManager()
    store = TokenStore(issuer=BlockTokenIssuer(keys))
    verifier = BlockTokenVerifier(keys)
    tok = store.block_token(BlockID(5, 9))
    verifier.verify(tok, BlockID(5, 9), AccessMode.WRITE)
    ctok = store.container_token(5)
    verifier.verify_container(ctok, 5)


def test_secret_key_export_import():
    src = SecretKeyManager()
    dst = SecretKeyManager(generate=False)
    assert dst.current() is None
    dst.import_keys(src.export_keys())
    issuer = BlockTokenIssuer(src)
    tok = issuer.issue(BlockID(1, 1), [AccessMode.READ])
    BlockTokenVerifier(dst).verify(tok, BlockID(1, 1), AccessMode.READ)


# ------------------------------------------------------------- secure cluster
#: the full reference security posture: mutual TLS on every channel
#: (the CA lives in the SCM; datanodes enroll over the plaintext
#: CSR endpoint gated by a bootstrap secret) + HMAC block tokens
#: enforced on the datapath. Secret keys ride only the mTLS channels.
ENROLL_SECRET = "drill-secret"


@pytest.fixture(scope="module")
def secure_cluster(tmp_path_factory):
    # secure mode mints x509 material via utils/ca.py; images without
    # the optional `cryptography` module skip the secure-cluster tests
    # cleanly (the unit-level token tests above still run — HMAC block
    # tokens themselves need only the stdlib)
    pytest.importorskip("cryptography")
    tmp_path = tmp_path_factory.mktemp("secure")
    meta = ScmOmDaemon(
        tmp_path / "om.db",
        block_size=4 * 4096,
        container_size=1024 * 1024,
        stale_after_s=1000.0,
        dead_after_s=2000.0,
        background_interval_s=0.2,
        block_tokens=True,
        secure=True,
        enrollment_secret=ENROLL_SECRET,
    )
    meta.start()
    dns = []
    for i in range(5):
        d = DatanodeDaemon(
            tmp_path / f"dn{i}", f"dn{i}", meta.address,
            heartbeat_interval_s=0.2,
            ca_address=meta.enroll_address,
            enrollment_secret=ENROLL_SECRET,
        )
        d.start()
        dns.append(d)
    yield meta, dns
    for d in dns:
        d.stop()
    meta.stop()


@pytest.fixture(scope="module")
def client_tls(secure_cluster, tmp_path_factory):
    """An enrolled CLIENT certificate: the mTLS ticket onto the wire —
    deliberately separate from any token, so the tests can model an
    authenticated-but-unauthorized caller."""
    from ozone_tpu.utils.ca import CertificateClient

    meta, _ = secure_cluster
    cc = CertificateClient(tmp_path_factory.mktemp("cli"), "client-cli")
    cc.enroll_remote(meta.enroll_address, secret=ENROLL_SECRET)
    return cc.tls()


def _client(meta, tls=None) -> OzoneClient:
    clients = DatanodeClientFactory()
    clients.tls = tls
    om = GrpcOmClient(meta.address, clients=clients, tls=tls)
    return OzoneClient(om, clients)


def test_enforcement_active_on_datanodes(secure_cluster):
    meta, dns = secure_cluster
    assert meta.scm.block_tokens
    assert meta.om.token_issuer is not None
    for d in dns:
        assert d.verifier.enabled, f"{d.dn.id} never enabled enforcement"
        assert d.secrets.current() is not None


def test_tokened_write_and_read(secure_cluster, client_tls):
    """The normal client path works unchanged: allocation carries WRITE
    tokens, lookup mints READ tokens, everything verifies on the DN."""
    meta, dns = secure_cluster
    oz = _client(meta, client_tls)
    b = oz.create_volume("v").create_bucket("b", replication=EC)
    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, 50_000, dtype=np.uint8)
    b.write_key("k", data)
    assert np.array_equal(b.read_key("k"), data)


def test_untokened_write_refused(secure_cluster, client_tls):
    """An AUTHENTICATED caller (holds a CA cert, so it gets through the
    mTLS handshake) without tokens must NOT be able to write — the
    round-1 gap: machinery existed, the wire never checked."""
    meta, dns = secure_cluster
    c = GrpcDatanodeClient("dn0", dns[0].address, tls=client_tls)
    data = np.zeros(512, dtype=np.uint8)
    cs = Checksum(ChecksumType.CRC32C, 4096).compute(data)
    info = ChunkInfo("c0", 0, data.size, cs)
    with pytest.raises(StorageError) as e:
        c.create_container(7777)
    assert e.value.code == "BLOCK_TOKEN_VERIFICATION_FAILED"
    with pytest.raises(StorageError) as e:
        c.write_chunk(BlockID(7777, 1), info, data)
    assert e.value.code == "BLOCK_TOKEN_VERIFICATION_FAILED"
    c.close()


def test_untokened_read_refused(secure_cluster, client_tls):
    """Committed data is unreadable without a token, per verb."""
    meta, dns = secure_cluster
    oz = _client(meta, client_tls)
    b = oz.get_volume("v").get_bucket("b")
    info = oz.om.lookup_key("v", "b", "k")
    g = info["block_groups"][0]
    bid = BlockID(int(g["container_id"]), int(g["local_id"]))
    dn_id = g["nodes"][0]
    addr = next(d.address for d in dns if d.dn.id == dn_id)
    c = GrpcDatanodeClient(dn_id, addr, tls=client_tls)  # no token store
    with pytest.raises(StorageError) as e:
        c.get_block(bid)
    assert e.value.code == "BLOCK_TOKEN_VERIFICATION_FAILED"
    with pytest.raises(StorageError) as e:
        c.list_blocks(bid.container_id)
    assert e.value.code == "BLOCK_TOKEN_VERIFICATION_FAILED"
    with pytest.raises(StorageError) as e:
        c.get_committed_block_length(bid)
    assert e.value.code == "BLOCK_TOKEN_VERIFICATION_FAILED"
    c.close()


def test_wrong_block_token_refused(secure_cluster, client_tls):
    """A valid token for block A does not open block B."""
    meta, dns = secure_cluster
    oz = _client(meta, client_tls)
    info = oz.om.lookup_key("v", "b", "k")
    g = info["block_groups"][0]
    bid = BlockID(int(g["container_id"]), int(g["local_id"]))
    other = BlockID(bid.container_id, bid.local_id + 999)
    # mint a REAL token (signed with the cluster key) for a different block
    tok = meta.om.token_issuer.issue(other, [AccessMode.READ])
    dn_id = g["nodes"][0]
    addr = next(d.address for d in dns if d.dn.id == dn_id)
    store = TokenStore()
    store.put_block_token(bid, tok)  # deliberately mismatched
    c = GrpcDatanodeClient(dn_id, addr, tokens=store, tls=client_tls)
    with pytest.raises(StorageError) as e:
        c.get_block(bid)
    assert e.value.code == "BLOCK_TOKEN_VERIFICATION_FAILED"
    c.close()


def test_expired_token_refused(secure_cluster, client_tls):
    meta, dns = secure_cluster
    oz = _client(meta, client_tls)
    info = oz.om.lookup_key("v", "b", "k")
    g = info["block_groups"][0]
    bid = BlockID(int(g["container_id"]), int(g["local_id"]))
    issuer = BlockTokenIssuer(meta.scm.secret_keys, token_lifetime_s=-1.0)
    tok = issuer.issue(bid, [AccessMode.READ])
    dn_id = g["nodes"][0]
    addr = next(d.address for d in dns if d.dn.id == dn_id)
    store = TokenStore()
    store.put_block_token(bid, tok)
    c = GrpcDatanodeClient(dn_id, addr, tokens=store, tls=client_tls)
    with pytest.raises(StorageError) as e:
        c.get_block(bid)
    assert e.value.code == "BLOCK_TOKEN_VERIFICATION_FAILED"
    c.close()


def test_mode_enforced(secure_cluster, client_tls):
    """A READ token does not authorize writes on the same block."""
    meta, dns = secure_cluster
    oz = _client(meta, client_tls)
    info = oz.om.lookup_key("v", "b", "k")
    g = info["block_groups"][0]
    bid = BlockID(int(g["container_id"]), int(g["local_id"]))
    tok = meta.om.token_issuer.issue(bid, [AccessMode.READ])
    dn_id = g["nodes"][0]
    addr = next(d.address for d in dns if d.dn.id == dn_id)
    store = TokenStore()
    store.put_block_token(bid, tok)
    c = GrpcDatanodeClient(dn_id, addr, tokens=store, tls=client_tls)
    c.get_block(bid)  # READ is fine
    data = np.zeros(16, dtype=np.uint8)
    cs = Checksum(ChecksumType.CRC32C, 4096).compute(data)
    with pytest.raises(StorageError) as e:
        c.write_chunk(bid, ChunkInfo("cx", 10**9, 16, cs), data)
    assert e.value.code == "BLOCK_TOKEN_VERIFICATION_FAILED"
    c.close()


def test_reconstruction_self_signs(secure_cluster, client_tls):
    """Datanode-to-datanode repair traffic self-signs with the imported
    secret keys (ec/reconstruction/TokenHelper.java analog) — kill a
    replica, let the replication manager reconstruct it."""
    meta, dns = secure_cluster
    oz = _client(meta, client_tls)
    b = oz.get_volume("v").get_bucket("b")
    rng = np.random.default_rng(4)
    data = rng.integers(0, 256, 30_000, dtype=np.uint8)
    b.write_key("k2", data)
    info = oz.om.lookup_key("v", "b", "k2")
    g = info["block_groups"][0]
    cid = int(g["container_id"])
    # close the container everywhere so reconstruction may run
    for d in dns:
        if d.dn.id in g["nodes"]:
            try:
                d.dn.close_container(cid)
            except StorageError:
                pass
    victim_id = g["nodes"][0]
    victim = next(d for d in dns if d.dn.id == victim_id)
    victim.dn.delete_container(cid, force=True)
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            if victim.dn.get_container(cid) is not None:
                break
        except StorageError:
            pass
        time.sleep(0.3)
    else:
        pytest.fail("reconstruction did not restore the replica")
    assert np.array_equal(b.read_key("k2"), data)


def test_uncertified_caller_rejected_at_transport(secure_cluster):
    """No CA-issued certificate -> the mTLS handshake itself fails; the
    caller never reaches a verb, let alone the secret keys (closes the
    bypass where anyone could Register and receive the signing keys)."""
    meta, dns = secure_cluster
    c = GrpcDatanodeClient("dn0", dns[0].address)  # plaintext channel
    with pytest.raises(StorageError) as e:
        c.echo(b"hi")
    assert e.value.code in ("UNAVAILABLE", "IO_EXCEPTION")
    c.close()
    from ozone_tpu.net.scm_service import GrpcScmClient

    scm = GrpcScmClient(meta.address)  # plaintext against the mTLS plane
    with pytest.raises(StorageError):
        scm.register("evil", "127.0.0.1:1", rack="/r")
    assert not scm.security.get("secret_keys")
    scm.close()


def test_bad_enrollment_secret_refused(secure_cluster, tmp_path):
    """The bootstrap secret gates certificate issuance."""
    from ozone_tpu.utils.ca import CertificateClient

    meta, _ = secure_cluster
    cc = CertificateClient(tmp_path / "rogue", "client-rogue")
    with pytest.raises(StorageError):
        cc.enroll_remote(meta.enroll_address, secret="wrong")
    assert not cc.enrolled


def test_live_cert_renewal_on_secure_cluster(secure_cluster, client_tls):
    """Rotation drill on a LIVE secure cluster: a datanode's cert is
    forced into the grace window, the renewal service re-enrolls it
    over the enrollment endpoint, and tokened traffic keeps flowing
    over the renewed mTLS identity with no daemon restart."""
    meta, dns = secure_cluster
    d = dns[0]
    assert d.cert_renewal is not None and meta.cert_renewal is not None
    old_serial = d.cert_client.cert.serial_number
    # not in the window: the periodic check is a no-op
    assert d.cert_renewal.check_once() is False
    # force-expire the leaf (sign a 0-day cert), then drive one check
    d.cert_client.install(
        meta.ca.sign_csr(d.cert_client.make_csr(), valid_days=0),
        meta.ca.root_pem)
    d.tls.reload()
    assert d.cert_renewal.check_once() is True
    assert d.cert_client.cert.serial_number != old_serial
    assert d.cert_client.remaining_fraction() > 0.9
    # end-to-end traffic through the renewed identity (own namespace:
    # no dependency on earlier tests in this file)
    oz = _client(meta, client_tls)
    b = oz.create_volume("vrenew").create_bucket("b", replication=EC)
    data = np.random.default_rng(9).integers(0, 256, 30_000,
                                             dtype=np.uint8)
    b.write_key("post-renewal", data)
    assert np.array_equal(b.read_key("post-renewal"), data)
