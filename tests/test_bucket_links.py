"""Link buckets (ozone sh bucket link analog): a named alias whose key
operations resolve to the source bucket; dangling links error on use;
deleting a link never touches source data.
"""

import numpy as np
import pytest

from ozone_tpu.om.requests import OMError
from ozone_tpu.testing.minicluster import MiniOzoneCluster

EC = "rs-3-2-4096"


@pytest.fixture
def cluster(tmp_path):
    c = MiniOzoneCluster(
        tmp_path,
        num_datanodes=5,
        block_size=4 * 4096,
        container_size=1024 * 1024,
        stale_after_s=1000.0,
        dead_after_s=2000.0,
    )
    yield c
    c.close()


def _data(n, seed=0):
    return np.random.default_rng(seed).integers(0, 256, n, dtype=np.uint8)


def test_link_bucket_read_write_through(cluster):
    oz = cluster.client()
    oz.create_volume("v").create_bucket("src", replication=EC)
    oz.create_volume("lv")
    oz.om.create_bucket_link("v", "src", "lv", "alias")
    alias = oz.get_volume("lv").get_bucket("alias")
    src = oz.get_volume("v").get_bucket("src")
    data = _data(15_000)
    alias.write_key("k", data)  # write through the link
    assert np.array_equal(src.read_key("k"), data)  # lands in the source
    assert np.array_equal(alias.read_key("k"), data)
    assert [k["name"] for k in alias.list_keys()] == ["k"]
    # effective replication/layout comes from the source
    info = oz.om.bucket_info("lv", "alias")
    assert info["replication"] == EC
    assert info["source"] == {"volume": "v", "bucket": "src"}
    # delete through the link removes the source key
    alias.delete_key("k")
    with pytest.raises(OMError):
        src.read_key("k")


def test_link_chain_and_loop_detection(cluster):
    oz = cluster.client()
    oz.create_volume("v").create_bucket("real", replication=EC)
    oz.om.create_bucket_link("v", "real", "v", "l1")
    oz.om.create_bucket_link("v", "l1", "v", "l2")  # link -> link -> real
    b = oz.get_volume("v").get_bucket("l2")
    b.write_key("k", _data(2_000, 1))
    assert oz.get_volume("v").get_bucket("real").read_key("k").size == 2_000
    # loop: l3 -> l4 -> l3
    oz.om.create_bucket_link("v", "l4", "v", "l3")
    oz.om.create_bucket_link("v", "l3", "v", "l4")
    with pytest.raises(OMError) as ei:
        oz.om.list_keys("v", "l3")
    assert ei.value.code == "DANGLING_LINK"


def test_dangling_link_errors_on_use_and_link_delete_is_safe(cluster):
    oz = cluster.client()
    oz.create_volume("v").create_bucket("src", replication=EC)
    oz.om.create_bucket_link("v", "src", "v", "alias")
    src_b = oz.get_volume("v").get_bucket("src")
    src_b.write_key("k", _data(1_000, 2))
    # deleting the LINK leaves source data intact
    oz.om.delete_bucket("v", "alias")
    assert src_b.read_key("k").size == 1_000
    # a link to a missing bucket errors as DANGLING_LINK on use
    oz.om.create_bucket_link("v", "ghost", "v", "dangling")
    with pytest.raises(OMError) as ei:
        oz.om.list_keys("v", "dangling")
    assert ei.value.code == "DANGLING_LINK"


def test_multipart_through_link(cluster):
    oz = cluster.client()
    oz.create_volume("v").create_bucket("src", replication=EC)
    oz.om.create_bucket_link("v", "src", "v", "alias")
    alias = oz.get_volume("v").get_bucket("alias")
    data = _data(18_000, 3)
    mpu = alias.initiate_multipart_upload("big")
    mpu.write_part(1, data[:9_000])
    mpu.write_part(2, data[9_000:])
    mpu.complete()
    assert np.array_equal(
        oz.get_volume("v").get_bucket("src").read_key("big"), data)


def test_link_write_through_remote_om(tmp_path):
    """The remote-protocol session must carry link-RESOLVED names, or the
    commit targets the alias's empty keyspace (caught by the live-CLI
    drive; regression guard)."""
    from ozone_tpu.client.dn_client import DatanodeClientFactory
    from ozone_tpu.client.ozone_client import OzoneClient
    from ozone_tpu.net.daemons import DatanodeDaemon, ScmOmDaemon
    from ozone_tpu.net.om_service import GrpcOmClient

    meta = ScmOmDaemon(tmp_path / "om.db", block_size=4 * 4096,
                       stale_after_s=1000.0, dead_after_s=2000.0,
                       background_interval_s=0.5)
    meta.start()
    dns = [DatanodeDaemon(tmp_path / f"dn{i}", f"dn{i}", meta.address,
                          heartbeat_interval_s=0.2) for i in range(5)]
    for d in dns:
        d.start()
    try:
        clients = DatanodeClientFactory()
        oz = OzoneClient(GrpcOmClient(meta.address, clients=clients),
                         clients)
        oz.create_volume("v").create_bucket("src", replication=EC)
        oz.create_volume("links")
        oz.om.create_bucket_link("v", "src", "links", "alias")
        data = _data(8_000, 5)
        oz.get_volume("links").get_bucket("alias").write_key("doc", data)
        assert np.array_equal(
            oz.get_volume("v").get_bucket("src").read_key("doc"), data)
        # MPU through the link over the remote protocol
        mpu = oz.get_volume("links").get_bucket("alias") \
            .initiate_multipart_upload("big")
        mpu.write_part(1, data)
        mpu.complete()
        assert np.array_equal(
            oz.get_volume("v").get_bucket("src").read_key("big"), data)
    finally:
        for d in dns:
            d.stop()
        meta.stop()


def test_fsck_skips_links_and_reports_dangling(tmp_path):
    """fsck walks source buckets once (no double-count through links)
    and reports a dangling link instead of crashing."""
    import io
    import json
    from contextlib import redirect_stdout

    from ozone_tpu.client.dn_client import DatanodeClientFactory
    from ozone_tpu.client.ozone_client import OzoneClient
    from ozone_tpu.net.daemons import DatanodeDaemon, ScmOmDaemon
    from ozone_tpu.net.om_service import GrpcOmClient
    from ozone_tpu.tools.cli import build_parser

    meta = ScmOmDaemon(tmp_path / "om.db", block_size=4 * 4096,
                       stale_after_s=1000.0, dead_after_s=2000.0,
                       background_interval_s=0.5)
    meta.start()
    dns = [DatanodeDaemon(tmp_path / f"dn{i}", f"dn{i}", meta.address,
                          heartbeat_interval_s=0.2) for i in range(5)]
    for d in dns:
        d.start()
    try:
        clients = DatanodeClientFactory()
        oz = OzoneClient(GrpcOmClient(meta.address, clients=clients),
                         clients)
        oz.create_volume("v").create_bucket("src", replication=EC)
        oz.om.create_bucket_link("v", "src", "v", "alias")
        oz.om.create_bucket_link("v", "ghost", "v", "dangling")
        b = oz.get_volume("v").get_bucket("src")
        b.write_key("k", _data(8_000, 20))

        args = build_parser().parse_args(["fsck", "--om", meta.address])
        buf = io.StringIO()
        with redirect_stdout(buf):
            rc = args.fn(args)
        out = json.loads(buf.getvalue())
        assert rc == 0
        assert out["keys"]["HEALTHY"] == 1  # not 2: the link is skipped
        dangling = [i for i in out["issues"]
                    if i.get("bucket") == "/v/dangling"]
        assert dangling and dangling[0]["state"] == "DANGLING_LINK"
    finally:
        for d in dns:
            d.stop()
        meta.stop()
