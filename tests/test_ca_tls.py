"""x509 CA, cert enrollment, and TLS/mTLS on the gRPC plane.

Mirrors the reference's security test surface (hdds/security x509 tests +
secure MiniOzoneCluster suites): root CA self-sign, CSR issuance with SAN
passthrough, per-role enrollment, TLS handshake against issued certs,
and rejection of clients without certificates in mutual mode.
"""

import grpc
import pytest

# x509 material rides the optional `cryptography` module: skip the
# whole CA/TLS surface cleanly on images without it
pytest.importorskip("cryptography")

from cryptography import x509  # noqa: E402

from ozone_tpu.net.rpc import RpcChannel, RpcServer  # noqa: E402
from ozone_tpu.storage.ids import StorageError  # noqa: E402
from ozone_tpu.utils.ca import (  # noqa: E402
    CertificateAuthority,
    CertificateClient,
)


def test_root_ca_persistence(tmp_path):
    ca1 = CertificateAuthority(tmp_path / "ca", cluster_id="c1")
    ca2 = CertificateAuthority(tmp_path / "ca")
    assert ca1.root_pem == ca2.root_pem
    cert = x509.load_pem_x509_certificate(ca1.root_pem)
    assert cert.extensions.get_extension_for_class(
        x509.BasicConstraints).value.ca


def test_enrollment_issues_leaf_with_sans(tmp_path):
    ca = CertificateAuthority(tmp_path / "ca")
    cc = CertificateClient(tmp_path / "dn1", "datanode-dn1",
                           hostnames=["localhost", "127.0.0.1", "dn1.rack0"])
    cc.enroll(ca)
    assert cc.enrolled
    cert = x509.load_pem_x509_certificate(cc.cert_path.read_bytes())
    assert cert.issuer == x509.load_pem_x509_certificate(ca.root_pem).subject
    san = cert.extensions.get_extension_for_class(
        x509.SubjectAlternativeName).value
    assert "dn1.rack0" in san.get_values_for_type(x509.DNSName)
    assert not cert.extensions.get_extension_for_class(
        x509.BasicConstraints).value.ca


def test_csr_tamper_rejected(tmp_path):
    ca = CertificateAuthority(tmp_path / "ca")
    with pytest.raises(ValueError):
        ca.sign_csr(b"-----BEGIN CERTIFICATE REQUEST-----\nnope\n"
                    b"-----END CERTIFICATE REQUEST-----\n")


def _echo_service():
    return {"Echo": lambda req: b"echo:" + req}


def test_mtls_end_to_end(tmp_path):
    ca = CertificateAuthority(tmp_path / "ca")
    server_cc = CertificateClient(tmp_path / "srv", "datanode-srv")
    client_cc = CertificateClient(tmp_path / "cli", "client-cli")
    server_cc.enroll(ca)
    client_cc.enroll(ca)

    srv = RpcServer(port=0, tls=server_cc.tls())
    srv.add_service("Test", _echo_service())
    srv.start()
    try:
        ch = RpcChannel(srv.address, tls=client_cc.tls(),
                        server_name="localhost")
        assert ch.call("Test", "Echo", b"hi") == b"echo:hi"
        ch.close()
    finally:
        srv.stop()


def test_mutual_mode_rejects_certless_client(tmp_path):
    ca = CertificateAuthority(tmp_path / "ca")
    server_cc = CertificateClient(tmp_path / "srv", "datanode-srv")
    server_cc.enroll(ca)
    srv = RpcServer(port=0, tls=server_cc.tls(), mutual=True)
    srv.add_service("Test", _echo_service())
    srv.start()
    try:
        # TLS without a client certificate: handshake must fail
        creds = grpc.ssl_channel_credentials(root_certificates=ca.root_pem)
        ch = grpc.secure_channel(
            srv.address, creds,
            options=[("grpc.ssl_target_name_override", "localhost")])
        fn = ch.unary_unary("/Test/Echo")
        with pytest.raises(grpc.RpcError):
            fn(b"hi", timeout=3.0)
        ch.close()
    finally:
        srv.stop()


def test_untrusted_ca_rejected(tmp_path):
    ca = CertificateAuthority(tmp_path / "ca")
    rogue = CertificateAuthority(tmp_path / "rogue")
    server_cc = CertificateClient(tmp_path / "srv", "datanode-srv")
    server_cc.enroll(ca)
    rogue_cc = CertificateClient(tmp_path / "rcli", "client-rogue")
    rogue_cc.enroll(rogue)

    srv = RpcServer(port=0, tls=server_cc.tls())
    srv.add_service("Test", _echo_service())
    srv.start()
    try:
        ch = RpcChannel(srv.address, tls=rogue_cc.tls(),
                        server_name="localhost")
        with pytest.raises(StorageError):
            ch.call("Test", "Echo", b"hi", timeout=3.0)
        ch.close()
    finally:
        srv.stop()


# ------------------------------------------------ certificate lifecycle
def test_renewal_grace_window_math(tmp_path):
    ca = CertificateAuthority(tmp_path / "ca")
    cc = CertificateClient(tmp_path / "dn", "datanode-dn")
    cc.enroll(ca)  # default 398d: nowhere near the grace window
    assert not cc.needs_renewal(threshold=0.25)
    assert 0.9 < cc.remaining_fraction() <= 1.0
    # re-issue with an already-expired leaf -> inside the window
    cc.install(ca.sign_csr(cc.make_csr(), valid_days=0), ca.root_pem)
    assert cc.needs_renewal(threshold=0.25)


def test_renew_mints_fresh_key_and_serial(tmp_path):
    ca = CertificateAuthority(tmp_path / "ca")
    cc = CertificateClient(tmp_path / "dn", "datanode-dn")
    cc.enroll(ca)
    old_serial = cc.cert.serial_number
    old_key = cc.key_path.read_bytes()
    cc.renew(ca)
    assert cc.cert.serial_number != old_serial
    assert cc.key_path.read_bytes() != old_key
    # the renewed identity still handshakes against the same root
    srv = RpcServer(port=0, tls=cc.tls())
    srv.add_service("Test", _echo_service())
    srv.start()
    try:
        cli = CertificateClient(tmp_path / "cli", "client-cli")
        cli.enroll(ca)
        ch = RpcChannel(srv.address, tls=cli.tls(),
                        server_name="localhost")
        assert ch.call("Test", "Echo", b"hi") == b"echo:hi"
        ch.close()
    finally:
        srv.stop()


def test_live_renewal_no_dropped_rpcs(tmp_path):
    """The rotation drill: RPCs flow continuously while the server's
    cert is renewed; the dynamic server credentials serve the new cert
    on the next handshake with zero downtime and zero dropped calls."""
    from ozone_tpu.utils.ca import CertRenewalService

    ca = CertificateAuthority(tmp_path / "ca")
    server_cc = CertificateClient(tmp_path / "srv", "datanode-srv")
    client_cc = CertificateClient(tmp_path / "cli", "client-cli")
    server_cc.enroll(ca)
    client_cc.enroll(ca)
    rot = server_cc.rotating_tls()
    srv = RpcServer(port=0, tls=rot, mutual=True)
    srv.add_service("Test", _echo_service())
    srv.start()
    renewal = CertRenewalService(rot, lambda: server_cc.renew(ca),
                                 threshold=0.25)
    try:
        ch = RpcChannel(srv.address, tls=client_cc.tls(),
                        server_name="localhost")
        assert ch.call("Test", "Echo", b"a") == b"echo:a"
        # not in the window yet -> no-op
        assert renewal.check_once() is False
        # force into the window (expired leaf), then drive the check
        server_cc.install(ca.sign_csr(server_cc.make_csr(),
                                      valid_days=0), ca.root_pem)
        rot.reload()
        assert renewal.check_once() is True
        assert renewal.renewals == 1
        # the EXISTING connection keeps working (no forced reset)...
        assert ch.call("Test", "Echo", b"b") == b"echo:b"
        ch.close()
        # ...and a brand-new handshake gets the renewed cert
        ch2 = RpcChannel(srv.address, tls=client_cc.tls(),
                         server_name="localhost")
        assert ch2.call("Test", "Echo", b"c") == b"echo:c"
        ch2.close()
        assert server_cc.remaining_fraction() > 0.9
    finally:
        srv.stop()


def test_root_ca_rotation_trust_bundle(tmp_path):
    """Root rotation: the trust bundle carries old+new roots during the
    transition, so pre-rotation leaves and post-rotation leaves verify
    against each other; retiring the old root ends the transition."""
    ca = CertificateAuthority(tmp_path / "ca")
    old_root = x509.load_pem_x509_certificate(ca.root_pem)
    server_cc = CertificateClient(tmp_path / "srv", "datanode-srv")
    server_cc.enroll(ca)  # leaf from the OLD root

    ca.rotate_root()
    new_root = x509.load_pem_x509_certificate(ca.root_pem)
    assert new_root.serial_number != old_root.serial_number
    assert b"BEGIN CERTIFICATE" in ca.root_pem
    assert ca.root_pem.count(b"BEGIN CERTIFICATE") == 2  # bundle of 2

    # phase 1: the pre-rotation party adopts the new trust bundle
    # (without this, mutual TLS rejects new-root peers mid-transition)
    assert server_cc.refresh_trust(ca) is True
    assert server_cc.refresh_trust(ca) is False  # idempotent

    # a client enrolled AFTER rotation can reach a server still serving
    # its pre-rotation cert (old root in the bundle)
    client_cc = CertificateClient(tmp_path / "cli", "client-cli")
    client_cc.enroll(ca)
    rot = server_cc.rotating_tls()
    srv = RpcServer(port=0, tls=rot, mutual=True)
    srv.add_service("Test", _echo_service())
    srv.start()
    try:
        ch = RpcChannel(srv.address, tls=client_cc.tls(),
                        server_name="localhost")
        assert ch.call("Test", "Echo", b"x") == b"echo:x"
        ch.close()
        # server renews onto the new root mid-flight; new handshakes OK
        server_cc.renew(ca)
        rot.reload()
        ch2 = RpcChannel(srv.address, tls=client_cc.tls(),
                         server_name="localhost")
        assert ch2.call("Test", "Echo", b"y") == b"echo:y"
        ch2.close()
        issuer = server_cc.cert.issuer
        assert issuer == new_root.subject
    finally:
        srv.stop()
    ca.retire_previous_root()
    assert ca.root_pem.count(b"BEGIN CERTIFICATE") == 1


def test_failover_pool_drops_channels_on_cert_rotation(tmp_path):
    """FailoverChannels watches RotatingTls.version and reconnects with
    the renewed client identity instead of presenting a retired cert."""
    from ozone_tpu.net.rpc import FailoverChannels

    ca = CertificateAuthority(tmp_path / "ca")
    server_cc = CertificateClient(tmp_path / "srv", "datanode-srv")
    client_cc = CertificateClient(tmp_path / "cli", "client-cli")
    server_cc.enroll(ca)
    client_cc.enroll(ca)
    srv = RpcServer(port=0, tls=server_cc.tls())
    srv.add_service("Test", _echo_service())
    srv.start()
    try:
        rot = client_cc.rotating_tls()
        pool = FailoverChannels(srv.address, tls=rot)
        _, ch1 = pool.channel()
        _, same = pool.channel()
        assert ch1 is same  # cached
        client_cc.renew(ca)
        rot.reload()
        _, ch2 = pool.channel()
        assert ch2 is not ch1  # rebuilt under the new identity
        pool.close()
    finally:
        srv.stop()


def test_failed_renewal_leaves_matched_key_and_cert(tmp_path):
    """A renewal whose RPC fails must not touch the on-disk identity:
    the fresh key lives only in memory until the CA answers, so a
    retry loop never leaves a cert whose public key the stored private
    key can't back."""
    ca = CertificateAuthority(tmp_path / "ca")
    cc = CertificateClient(tmp_path / "srv", "datanode-srv")
    cc.enroll(ca)
    key_before = cc.key_path.read_bytes()
    cert_before = cc.cert_path.read_bytes()
    with pytest.raises(Exception):
        cc.renew_remote("127.0.0.1:1")  # nothing listens there
    assert cc.key_path.read_bytes() == key_before
    assert cc.cert_path.read_bytes() == cert_before
    # the untouched identity still works end-to-end
    srv = RpcServer(port=0, tls=cc.tls())
    srv.add_service("Test", _echo_service())
    srv.start()
    try:
        cli = CertificateClient(tmp_path / "cli", "client-cli")
        cli.enroll(ca)
        ch = RpcChannel(srv.address, tls=cli.tls(),
                        server_name="localhost")
        assert ch.call("Test", "Echo", b"ok") == b"echo:ok"
        ch.close()
    finally:
        srv.stop()


def test_enrollment_response_mac_required(tmp_path):
    """A client that holds the bootstrap secret REFUSES enrollment /
    trust responses that don't authenticate — otherwise a MITM on the
    plaintext CSR channel could substitute a rogue CA bundle."""
    from ozone_tpu.utils.ca import EnrollmentService

    ca = CertificateAuthority(tmp_path / "ca")
    srv = RpcServer(port=0)
    EnrollmentService(ca, srv, secret=None)  # server never MACs
    srv.start()
    try:
        cc = CertificateClient(tmp_path / "dn", "datanode-dn")
        with pytest.raises(PermissionError):
            cc.enroll_remote(srv.address, secret="client-has-secret")
        assert not cc.enrolled
        with pytest.raises(PermissionError):
            cc.refresh_trust_remote(srv.address,
                                    secret="client-has-secret")
    finally:
        srv.stop()


def test_enrollment_response_mac_roundtrip(tmp_path):
    """With the secret on both sides, enroll + renew + trust refresh
    all verify their response MACs and succeed."""
    from ozone_tpu.utils.ca import EnrollmentService

    ca = CertificateAuthority(tmp_path / "ca")
    srv = RpcServer(port=0)
    EnrollmentService(ca, srv, secret="s3cr3t")
    srv.start()
    try:
        cc = CertificateClient(tmp_path / "dn", "datanode-dn")
        cc.enroll_remote(srv.address, secret="s3cr3t")
        assert cc.enrolled
        old_serial = cc.cert.serial_number
        cc.renew_remote(srv.address, secret="s3cr3t")
        assert cc.cert.serial_number != old_serial
        assert cc.refresh_trust_remote(srv.address,
                                       secret="s3cr3t") is False
        ca.rotate_root()
        assert cc.refresh_trust_remote(srv.address,
                                       secret="s3cr3t") is True
    finally:
        srv.stop()


def test_double_root_rotation_refused(tmp_path):
    """A second rotation while the previous root is still in the trust
    bundle would strand every generation-0 leaf; the CA refuses until
    the operator retires the old anchor."""
    ca = CertificateAuthority(tmp_path / "ca")
    ca.rotate_root()
    with pytest.raises(RuntimeError):
        ca.rotate_root()
    ca.retire_previous_root()
    ca.rotate_root()  # transition finished: next rotation allowed
    assert ca.generation == 2


def test_cert_revocation_enforced_live(tmp_path):
    """CRL lifecycle: the CA logs issued certs, revocation rides the
    MAC'd trust refresh, and a server refuses a revoked-but-unexpired
    peer per-RPC while other peers keep working — no waiting for
    expiry, no restart."""
    from ozone_tpu.utils.ca import EnrollmentService

    ca = CertificateAuthority(tmp_path / "ca")
    server_cc = CertificateClient(tmp_path / "srv", "datanode-srv")
    good_cc = CertificateClient(tmp_path / "good", "client-good")
    bad_cc = CertificateClient(tmp_path / "bad", "client-bad")
    for cc in (server_cc, good_cc, bad_cc):
        cc.enroll(ca)
    issued = ca.issued()
    assert len(issued) == 3 and not any(r["revoked"] for r in issued)
    bad_serial = bad_cc.cert.serial_number
    assert any(r["serial"] == bad_serial for r in issued)

    rot = server_cc.rotating_tls()
    srv = RpcServer(port=0, tls=rot, mutual=True)
    srv.crl_provider = rot.crl
    srv.add_service("Test", _echo_service())
    srv.start()
    try:
        chb = RpcChannel(srv.address, tls=bad_cc.tls(),
                         server_name="localhost")
        assert chb.call("Test", "Echo", b"ok") == b"echo:ok"
        # revoke + distribute (phase: trust refresh installs the CRL)
        ca.revoke(bad_serial)
        with pytest.raises(ValueError):
            ca.revoke(12345)  # never issued here
        assert server_cc.refresh_trust(ca) is True
        rot.reload()
        with pytest.raises(StorageError) as ei:
            chb.call("Test", "Echo", b"again")
        assert ei.value.code == "CERTIFICATE_REVOKED"
        chb.close()
        # an unrevoked peer is untouched
        chg = RpcChannel(srv.address, tls=good_cc.tls(),
                         server_name="localhost")
        assert chg.call("Test", "Echo", b"fine") == b"echo:fine"
        chg.close()
    finally:
        srv.stop()
    # the CRL rides the MAC'd enrollment-plane responses
    esrv = RpcServer(port=0)
    EnrollmentService(ca, esrv, secret="s")
    esrv.start()
    try:
        late = CertificateClient(tmp_path / "late", "client-late")
        late.enroll_remote(esrv.address, secret="s")
        assert bad_serial in late.crl()
        assert late.refresh_trust_remote(esrv.address,
                                         secret="s") is False
    finally:
        esrv.stop()
