"""x509 CA, cert enrollment, and TLS/mTLS on the gRPC plane.

Mirrors the reference's security test surface (hdds/security x509 tests +
secure MiniOzoneCluster suites): root CA self-sign, CSR issuance with SAN
passthrough, per-role enrollment, TLS handshake against issued certs,
and rejection of clients without certificates in mutual mode.
"""

import grpc
import pytest
from cryptography import x509

from ozone_tpu.net.rpc import RpcChannel, RpcServer
from ozone_tpu.storage.ids import StorageError
from ozone_tpu.utils.ca import CertificateAuthority, CertificateClient


def test_root_ca_persistence(tmp_path):
    ca1 = CertificateAuthority(tmp_path / "ca", cluster_id="c1")
    ca2 = CertificateAuthority(tmp_path / "ca")
    assert ca1.root_pem == ca2.root_pem
    cert = x509.load_pem_x509_certificate(ca1.root_pem)
    assert cert.extensions.get_extension_for_class(
        x509.BasicConstraints).value.ca


def test_enrollment_issues_leaf_with_sans(tmp_path):
    ca = CertificateAuthority(tmp_path / "ca")
    cc = CertificateClient(tmp_path / "dn1", "datanode-dn1",
                           hostnames=["localhost", "127.0.0.1", "dn1.rack0"])
    cc.enroll(ca)
    assert cc.enrolled
    cert = x509.load_pem_x509_certificate(cc.cert_path.read_bytes())
    assert cert.issuer == x509.load_pem_x509_certificate(ca.root_pem).subject
    san = cert.extensions.get_extension_for_class(
        x509.SubjectAlternativeName).value
    assert "dn1.rack0" in san.get_values_for_type(x509.DNSName)
    assert not cert.extensions.get_extension_for_class(
        x509.BasicConstraints).value.ca


def test_csr_tamper_rejected(tmp_path):
    ca = CertificateAuthority(tmp_path / "ca")
    with pytest.raises(ValueError):
        ca.sign_csr(b"-----BEGIN CERTIFICATE REQUEST-----\nnope\n"
                    b"-----END CERTIFICATE REQUEST-----\n")


def _echo_service():
    return {"Echo": lambda req: b"echo:" + req}


def test_mtls_end_to_end(tmp_path):
    ca = CertificateAuthority(tmp_path / "ca")
    server_cc = CertificateClient(tmp_path / "srv", "datanode-srv")
    client_cc = CertificateClient(tmp_path / "cli", "client-cli")
    server_cc.enroll(ca)
    client_cc.enroll(ca)

    srv = RpcServer(port=0, tls=server_cc.tls())
    srv.add_service("Test", _echo_service())
    srv.start()
    try:
        ch = RpcChannel(srv.address, tls=client_cc.tls(),
                        server_name="localhost")
        assert ch.call("Test", "Echo", b"hi") == b"echo:hi"
        ch.close()
    finally:
        srv.stop()


def test_mutual_mode_rejects_certless_client(tmp_path):
    ca = CertificateAuthority(tmp_path / "ca")
    server_cc = CertificateClient(tmp_path / "srv", "datanode-srv")
    server_cc.enroll(ca)
    srv = RpcServer(port=0, tls=server_cc.tls(), mutual=True)
    srv.add_service("Test", _echo_service())
    srv.start()
    try:
        # TLS without a client certificate: handshake must fail
        creds = grpc.ssl_channel_credentials(root_certificates=ca.root_pem)
        ch = grpc.secure_channel(
            srv.address, creds,
            options=[("grpc.ssl_target_name_override", "localhost")])
        fn = ch.unary_unary("/Test/Echo")
        with pytest.raises(grpc.RpcError):
            fn(b"hi", timeout=3.0)
        ch.close()
    finally:
        srv.stop()


def test_untrusted_ca_rejected(tmp_path):
    ca = CertificateAuthority(tmp_path / "ca")
    rogue = CertificateAuthority(tmp_path / "rogue")
    server_cc = CertificateClient(tmp_path / "srv", "datanode-srv")
    server_cc.enroll(ca)
    rogue_cc = CertificateClient(tmp_path / "rcli", "client-rogue")
    rogue_cc.enroll(rogue)

    srv = RpcServer(port=0, tls=server_cc.tls())
    srv.add_service("Test", _echo_service())
    srv.start()
    try:
        ch = RpcChannel(srv.address, tls=rogue_cc.tls(),
                        server_name="localhost")
        with pytest.raises(StorageError):
            ch.call("Test", "Echo", b"hi", timeout=3.0)
        ch.close()
    finally:
        srv.stop()
