"""Chaos tests: committed keys survive random datanode kills
(mini-chaos-tests strategy analog)."""

import pytest

from ozone_tpu.testing.chaos import run_chaos
from ozone_tpu.testing.minicluster import MiniOzoneCluster


@pytest.mark.parametrize("seed", [0, 1])
def test_chaos_writes_survive_node_kills(tmp_path, seed):
    cluster = MiniOzoneCluster(
        tmp_path,
        num_datanodes=7,
        block_size=8 * 4096,
        container_size=4 * 1024 * 1024,
        stale_after_s=1000.0,
        dead_after_s=2000.0,
    )
    try:
        result = run_chaos(
            cluster, duration_s=4.0, max_down=1, seed=seed,
            replication="rs-3-2-4096",
        )
        assert result.kills >= 1, "chaos must actually kill nodes"
        assert len(result.keys_written) >= 3
        assert result.read_mismatches == []
        assert result.read_errors == []
    finally:
        cluster.close()
