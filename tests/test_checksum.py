"""Checksum tests: host reference vs zlib/test-vectors, device vs host."""

import zlib

import numpy as np
import pytest

from ozone_tpu.utils import checksum as cs
from ozone_tpu.utils.checksum import (
    Checksum,
    ChecksumData,
    ChecksumError,
    ChecksumType,
)


def test_crc32c_test_vector():
    # RFC 3720 / known Castagnoli vector
    v = np.frombuffer(b"123456789", dtype=np.uint8)
    assert cs.crc32c(v) == 0xE3069283


def test_crc32_matches_zlib():
    rng = np.random.default_rng(0)
    for n in (0, 1, 9, 255, 256, 1024, 16384, 100_000):
        d = rng.integers(0, 256, n, dtype=np.uint8)
        assert cs.crc32(d) == zlib.crc32(d.tobytes()), n


def test_linear_equals_table():
    rng = np.random.default_rng(1)
    for poly in (cs.CRC32_POLY, cs.CRC32C_POLY):
        for n in (1, 7, 64, 1000, 16384):
            d = rng.integers(0, 256, n, dtype=np.uint8)
            assert cs.crc_linear(d, poly) == cs.crc_table_driven(d, poly)


def test_checksum_compute_verify():
    rng = np.random.default_rng(2)
    data = rng.integers(0, 256, 70_000, dtype=np.uint8)
    for t in ChecksumType:
        c = Checksum(t, 16 * 1024)
        cd = c.compute(data)
        c.verify(data, cd)
        if t is ChecksumType.NONE:
            continue
        assert len(cd.checksums) == 5  # ceil(70000 / 16384)
        corrupted = data.copy()
        corrupted[40_000] ^= 0xFF
        with pytest.raises(ChecksumError):
            c.verify(corrupted, cd)


def test_checksum_data_serde():
    cd = Checksum(ChecksumType.CRC32C, 1024).compute(
        (np.arange(2048) % 256).astype(np.uint8)
    )
    rt = ChecksumData.from_lists(cd.to_lists())
    assert rt == cd


def test_device_crc_matches_host():
    from ozone_tpu.codec.crc_device import make_crc_fn

    rng = np.random.default_rng(3)
    bpc = 512
    cells = rng.integers(0, 256, (4, 3, 4 * bpc), dtype=np.uint8)
    fn = make_crc_fn(bpc, cs.CRC32C_POLY)
    got = np.asarray(fn(cells))
    assert got.shape == (4, 3, 4)
    for b in range(4):
        for u in range(3):
            for s in range(4):
                expect = cs.crc32c(cells[b, u, s * bpc : (s + 1) * bpc])
                assert int(got[b, u, s]) == expect, (b, u, s)


def test_device_crc_crc32_poly():
    from ozone_tpu.codec.crc_device import make_crc_fn

    rng = np.random.default_rng(4)
    cells = rng.integers(0, 256, (2, 2048), dtype=np.uint8)
    fn = make_crc_fn(1024, cs.CRC32_POLY)
    got = np.asarray(fn(cells))
    for b in range(2):
        for s in range(2):
            assert int(got[b, s]) == zlib.crc32(
                cells[b, s * 1024 : (s + 1) * 1024].tobytes()
            )


def test_fused_encode_crc():
    from ozone_tpu.codec.api import CoderOptions
    from ozone_tpu.codec.fused import FusedSpec, make_fused_encoder
    from ozone_tpu.codec.numpy_coder import NumpyRSEncoder

    rng = np.random.default_rng(5)
    opts = CoderOptions(6, 3, "rs", cell_size=2048)
    spec = FusedSpec(opts, ChecksumType.CRC32C, bytes_per_checksum=512)
    fn = make_fused_encoder(spec)
    data = rng.integers(0, 256, (3, 6, 2048), dtype=np.uint8)
    parity, crcs = (np.asarray(x) for x in fn(data))
    assert parity.shape == (3, 3, 2048)
    assert crcs.shape == (3, 9, 4)
    # parity matches the numpy reference coder
    expect_parity = NumpyRSEncoder(opts).encode(data)
    assert np.array_equal(parity, expect_parity)
    # crcs match host checksums of data+parity
    units = np.concatenate([data, parity], axis=1)
    for b in range(3):
        for u in range(9):
            for s in range(4):
                assert int(crcs[b, u, s]) == cs.crc32c(
                    units[b, u, s * 512 : (s + 1) * 512]
                )


def test_fused_reencode_crc():
    """XOR(1)->RS re-encode as one composed matrix: recovering the lost
    unit, the RS parity of the full group, and the CRCs of the whole EC
    layout must all match the two-step reference computation."""
    from ozone_tpu.codec.api import CoderOptions
    from ozone_tpu.codec.fused import (
        FusedSpec,
        make_fused_reencoder,
        reencode_layout_crcs,
    )
    from ozone_tpu.codec.numpy_coder import NumpyRSEncoder

    rng = np.random.default_rng(7)
    opts = CoderOptions(6, 3, "rs", cell_size=2048)
    spec = FusedSpec(opts, ChecksumType.CRC32C, bytes_per_checksum=512)
    data = rng.integers(0, 256, (2, 6, 2048), dtype=np.uint8)
    for lost in (0, 3, 5):
        units = data.copy()
        # slot `lost` carries the XOR parity of the FULL group
        units[:, lost] = np.bitwise_xor.reduce(data, axis=1)
        fn = make_fused_reencoder(spec, lost=lost)
        out, ucrcs, ocrcs = (np.asarray(x) for x in fn(units))
        assert np.array_equal(out[:, 0], data[:, lost])
        assert np.array_equal(out[:, 1:], NumpyRSEncoder(opts).encode(data))
        crcs = reencode_layout_crcs(ucrcs, ocrcs, lost)
        layout = np.concatenate([data, out[:, 1:]], axis=1)
        for b in range(2):
            for u in range(9):
                for s in range(4):
                    assert int(crcs[b, u, s]) == cs.crc32c(
                        layout[b, u, s * 512:(s + 1) * 512])


def test_fused_decode_crc():
    from ozone_tpu.codec.api import CoderOptions
    from ozone_tpu.codec.fused import FusedSpec, make_fused_decoder
    from ozone_tpu.codec.numpy_coder import NumpyRSEncoder

    rng = np.random.default_rng(6)
    opts = CoderOptions(6, 3, "rs", cell_size=1024)
    spec = FusedSpec(opts, ChecksumType.CRC32C, bytes_per_checksum=256)
    data = rng.integers(0, 256, (2, 6, 1024), dtype=np.uint8)
    parity = NumpyRSEncoder(opts).encode(data)
    units = np.concatenate([data, parity], axis=1)
    erased = [1, 7]
    valid = [i for i in range(9) if i not in erased][:6]
    fn = make_fused_decoder(spec, valid, erased)
    rec, crcs = (np.asarray(x) for x in fn(units[:, valid]))
    assert np.array_equal(rec, units[:, erased])
    for b in range(2):
        for e in range(2):
            for s in range(4):
                assert int(crcs[b, e, s]) == cs.crc32c(
                    rec[b, e, s * 256 : (s + 1) * 256]
                )
