"""Raw coder SPI tests: encode -> erase -> decode -> compare.

Mirrors the strategy of the reference's TestRawCoderBase (erasurecode
src/test .../rawcoder/TestRawCoderBase.java): randomized data, randomized
erasure sets across data+parity units, multiple chunk sizes, and
cross-backend bit-compatibility (numpy vs jax, the analog of the reference's
Java vs ISA-L interop guarantee, RSRawEncoder.java:25-28).
"""

import time

import numpy as np
import pytest

from ozone_tpu.codec import CoderOptions, create_decoder, create_encoder
from ozone_tpu.codec.registry import CodecRegistry
from ozone_tpu.utils.checksum import ChecksumType

SCHEMAS = [("rs", 3, 2), ("rs", 6, 3), ("rs", 10, 4), ("xor", 4, 1)]
BACKENDS = ["numpy", "jax"]


def _roundtrip(codec, k, p, backend, batch, cell, rng, n_erase=None):
    opts = CoderOptions(k, p, codec, cell_size=cell)
    enc = create_encoder(opts, backend)
    dec = create_decoder(opts, backend)
    shape = (batch, k, cell) if batch else (k, cell)
    data = rng.integers(0, 256, shape, dtype=np.uint8)
    parity = enc.encode(data)
    units = np.concatenate([data, parity], axis=-2)

    max_erase = 1 if codec == "xor" else p
    n_erase = n_erase or max_erase
    erased = sorted(rng.choice(k + p, size=n_erase, replace=False).tolist())
    inputs = [None if i in erased else units[..., i, :] for i in range(k + p)]
    rec = dec.decode(inputs, erased)
    assert np.array_equal(rec, units[..., erased, :]), (codec, k, p, erased)


@pytest.mark.parametrize("codec,k,p", SCHEMAS)
@pytest.mark.parametrize("backend", BACKENDS)
def test_roundtrip_unbatched(codec, k, p, backend):
    _roundtrip(codec, k, p, backend, batch=0, cell=257, rng=np.random.default_rng(7))


@pytest.mark.parametrize("codec,k,p", SCHEMAS)
@pytest.mark.parametrize("backend", BACKENDS)
def test_roundtrip_batched(codec, k, p, backend):
    _roundtrip(codec, k, p, backend, batch=5, cell=128, rng=np.random.default_rng(8))


@pytest.mark.parametrize("codec,k,p", SCHEMAS)
def test_backends_bit_identical(codec, k, p):
    rng = np.random.default_rng(9)
    opts = CoderOptions(k, p, codec, cell_size=512)
    data = rng.integers(0, 256, (3, k, 512), dtype=np.uint8)
    outs = [create_encoder(opts, b).encode(data) for b in BACKENDS]
    assert np.array_equal(outs[0], outs[1])


@pytest.mark.parametrize("backend", BACKENDS)
def test_rs_all_erasure_patterns_small(backend):
    """Exhaustive erasure patterns for RS(3,2)."""
    import itertools

    rng = np.random.default_rng(10)
    opts = CoderOptions(3, 2, "rs", cell_size=64)
    enc = create_encoder(opts, backend)
    dec = create_decoder(opts, backend)
    data = rng.integers(0, 256, (3, 64), dtype=np.uint8)
    parity = enc.encode(data)
    units = np.concatenate([data, parity], axis=0)
    for n in (1, 2):
        for erased in itertools.combinations(range(5), n):
            inputs = [None if i in erased else units[i] for i in range(5)]
            rec = dec.decode(inputs, list(erased))
            assert np.array_equal(rec, units[list(erased)]), erased


def test_known_vector_rs_3_2():
    """Pin parity bytes for a fixed input so any coder regression or
    incompatibility with the ISA-L matrix layout shows up as a diff."""
    opts = CoderOptions(3, 2, "rs", cell_size=8)
    enc = create_encoder(opts, "numpy")
    data = np.arange(24, dtype=np.uint8).reshape(3, 8)
    parity = enc.encode(data)
    # recompute from first principles: P = enc_matrix rows k..k+p
    from ozone_tpu.codec import gf256, rs_math

    expected = gf256.gf_matmul(rs_math.parity_matrix(3, 2), data)
    assert np.array_equal(parity, expected)


def test_dummy_coder():
    opts = CoderOptions(3, 2, "dummy")
    enc = create_encoder(opts)
    data = np.ones((3, 16), dtype=np.uint8)
    assert np.array_equal(enc.encode(data), np.zeros((2, 16), np.uint8))


def test_registry_priority_and_fallback():
    reg = CodecRegistry.instance()
    assert "numpy" in reg.backends("rs")
    # jax should be present in this environment and preferred
    assert reg.backends("rs")[0] == "jax"
    with pytest.raises(ValueError):
        create_encoder(CoderOptions(3, 2, "nosuch"))


def test_options_parse_roundtrip():
    o = CoderOptions.parse("rs-6-3-1024k")
    assert (o.data_units, o.parity_units, o.cell_size) == (6, 3, 1024 * 1024)
    assert str(o) == "rs-6-3-1m"
    o2 = CoderOptions.parse("xor-4-1-4096")
    assert o2.cell_size == 4096


def test_decoder_input_validation():
    opts = CoderOptions(3, 2, "rs")
    dec = create_decoder(opts, "numpy")
    units = [np.zeros(8, np.uint8)] * 5
    with pytest.raises(ValueError):
        dec.decode(units[:4], [0])  # wrong length
    with pytest.raises(ValueError):
        dec.decode(units, [0])  # erased index not None
    inputs = [None, None, None, units[3], units[4]]
    with pytest.raises(ValueError):
        dec.decode(inputs, [0, 1, 2])  # only 2 available


def test_adaptive_backend_probe(monkeypatch):
    """Round-4 adaptive selection (CodecUtil.createRawEncoderWithFallback
    analog): with an accelerator present, a measured-bandwidth probe
    steers degraded-link clients to the native twin and healthy-link
    clients to the device path."""
    from ozone_tpu.codec import fused

    opts = CoderOptions(6, 3, "rs", cell_size=4096)
    monkeypatch.delenv("OZONE_TPU_FUSED_BACKEND", raising=False)
    monkeypatch.setenv("OZONE_TPU_LINK_PROBE", "1")
    monkeypatch.setattr(fused.jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(fused, "_native_lib_available", lambda: True)
    monkeypatch.setattr(fused, "_native_rate_sample", lambda o: 1400.0)

    try:
        fused._PROBE_CACHE.clear()
        # tunnel-degraded link (this rig: h2d 23 MiB/s on a bad day):
        # the native twin wins
        monkeypatch.setattr(fused, "_measure_link", lambda: (12.0, 10.0))
        assert fused._prefer_host_coder(opts) is True

        fused._PROBE_CACHE.clear()
        # healthy PCIe-class link: the device path wins
        monkeypatch.setattr(fused, "_measure_link",
                            lambda: (8000.0, 8000.0))
        assert fused._prefer_host_coder(opts) is False
        # decode transfer shape gets its own verdict (e/valid, not p/k)
        assert fused._prefer_host_coder(opts, out_ratio=1 / 6) is False

        fused._PROBE_CACHE.clear()
        # probe failure falls back to the device path (never worse than
        # round 3's static choice)
        def boom():
            raise RuntimeError("no device")
        monkeypatch.setattr(fused, "_measure_link", boom)
        assert fused._prefer_host_coder(opts) is False

        # cached verdict is truly lock-free: neither the loader nor the
        # probe may run again once the key is in the cache (flag-based
        # sentinels — a raising sentinel in _measure_link would be
        # swallowed by the watchdog thread and read as "probe failed")
        called: list = []
        monkeypatch.setattr(fused, "_native_lib_available",
                            lambda: called.append("lib") or True)
        monkeypatch.setattr(fused, "_measure_link",
                            lambda: called.append("probe") or (1.0, 1.0))
        assert fused._prefer_host_coder(opts) is False
        assert not called

        fused._PROBE_CACHE.clear()
        # non-CRC32C spec: no native twin exists for it — device path,
        # and the ~1 s probe is never paid
        assert fused._prefer_host_coder(
            opts, checksum=ChecksumType.CRC32) is False
        assert "probe" not in called
        monkeypatch.setattr(fused, "_native_lib_available", lambda: True)

        fused._PROBE_CACHE.clear()
        # wedged tunnel (uninterruptible device transfer): the watchdog
        # times the probe out instead of deadlocking every coder thread,
        # and steers to the native twin — the device path would hang too
        monkeypatch.setattr(fused, "_measure_link",
                            lambda: time.sleep(2.5))
        monkeypatch.setattr(fused, "_PROBE_WALL_S", 0.2)
        assert fused._prefer_host_coder(opts) is True
        monkeypatch.setattr(fused, "_PROBE_WALL_S", 10.0)

        fused._PROBE_CACHE.clear()
        # no native twin to fall back to: device path without probing
        monkeypatch.setattr(fused, "_native_lib_available", lambda: False)
        assert fused._prefer_host_coder(opts) is False

        # env force still wins over everything
        monkeypatch.setenv("OZONE_TPU_FUSED_BACKEND", "native")
        assert fused._prefer_host_coder(opts) is True
        monkeypatch.setenv("OZONE_TPU_FUSED_BACKEND", "jax")
        assert fused._prefer_host_coder(opts) is False
    finally:
        fused._PROBE_CACHE.clear()
