"""Shared codec service: cross-request continuous batching tests.

The tentpole contract (ROADMAP item 1): stripes from DIFFERENT in-flight
operations coalesce into one fused device dispatch; a lone stripe is
bounded by the linger knob; a near-expiry deadline forces a partial
batch instead of DEADLINE_EXCEEDED; weighted fair QoS keeps a bulk
sweep from starving interactive submissions; and every refactored
datapath falls back to its per-operation pipeline when the service is
disabled, byte-exact either way.
"""

import itertools
import threading
import time

import numpy as np
import pytest

from ozone_tpu.client.dn_client import DatanodeClientFactory
from ozone_tpu.client.ec_reader import ECBlockGroupReader
from ozone_tpu.client.ec_writer import BlockGroup, ECKeyWriter
from ozone_tpu.codec import service as cs
from ozone_tpu.codec.api import CoderOptions
from ozone_tpu.codec.fused import FusedSpec, make_fused_encoder
from ozone_tpu.scm.pipeline import Pipeline, ReplicationConfig
from ozone_tpu.storage.datanode import Datanode
from ozone_tpu.utils.checksum import ChecksumType

CELL = 4096
OPTS = CoderOptions(3, 2, "rs", cell_size=CELL)
SPEC = FusedSpec(OPTS, ChecksumType.CRC32C, 1024)


@pytest.fixture
def svc():
    cs.reset_for_tests()
    yield cs.get_service()
    cs.reset_for_tests()


@pytest.fixture
def fresh_service_env(monkeypatch):
    """Re-create the singleton AFTER knob monkeypatches apply."""
    def make(**env):
        for k, v in env.items():
            monkeypatch.setenv(k, v)
        cs.reset_for_tests()
        return cs.get_service()

    yield make
    cs.reset_for_tests()


class MiniEC:
    """Tiny in-process cluster (the test_ec_pipeline harness, local so
    this suite stands alone)."""

    def __init__(self, tmp_path, n_dn=6, opts=OPTS):
        self.opts = opts
        self.dns = [Datanode(tmp_path / f"dn{i}", dn_id=f"dn{i}")
                    for i in range(n_dn)]
        self.clients = DatanodeClientFactory()
        for dn in self.dns:
            self.clients.register_local(dn)
        self._cid = itertools.count(1)
        self._lid = itertools.count(1)

    def allocate(self, excluded):
        nodes = [d.id for d in self.dns
                 if d.id not in excluded][: self.opts.all_units]
        return BlockGroup(
            container_id=next(self._cid), local_id=next(self._lid),
            pipeline=Pipeline(ReplicationConfig.from_ec(self.opts),
                              nodes))

    def writer(self, **kw):
        kw.setdefault("block_size", 8 * CELL)
        kw.setdefault("bytes_per_checksum", 1024)
        kw.setdefault("stripe_batch", 4)
        return ECKeyWriter(self.opts, self.allocate, self.clients, **kw)

    def close(self):
        for d in self.dns:
            d.close()


@pytest.fixture
def cluster(tmp_path):
    c = MiniEC(tmp_path)
    yield c
    c.close()


def _rand(shape, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, shape, dtype=np.uint8)


# ------------------------------------------------------------ coalescing
def test_cross_request_stripes_share_one_dispatch(svc):
    """Two distinct operations' stripes land in ONE fused dispatch, and
    each gets exactly its own slice of the batched outputs."""
    fn = make_fused_encoder(SPEC)
    a, b = _rand((2, 3, CELL), 1), _rand((2, 3, CELL), 2)
    d0 = cs.METRICS.counter("dispatches").value
    x0 = cs.METRICS.counter("multi_op_dispatches").value
    f1 = svc.submit(cs.encode_key(SPEC), fn, a, width=4)
    f2 = svc.submit(cs.encode_key(SPEC), fn, b, width=4)
    p1, c1 = cs.wait_result(f1)
    p2, c2 = cs.wait_result(f2)
    ref_p, ref_c = (np.asarray(x) for x in fn(np.concatenate([a, b])))
    assert np.array_equal(np.concatenate([p1, p2]), ref_p)
    assert np.array_equal(np.concatenate([c1, c2]), ref_c)
    assert cs.METRICS.counter("dispatches").value - d0 == 1
    assert cs.METRICS.counter("multi_op_dispatches").value - x0 == 1


def test_large_submission_splits_across_constant_shape_batches(svc):
    """A submission wider than the lane batch splits into width-sized
    dispatches and reassembles in order — outputs byte-exact vs one
    direct call."""
    fn = make_fused_encoder(SPEC)
    data = _rand((11, 3, CELL), 3)
    d0 = cs.METRICS.counter("dispatches").value
    out_p, out_c = cs.wait_result(
        svc.submit(cs.encode_key(SPEC), fn, data, width=4))
    ref_p, ref_c = (np.asarray(x) for x in fn(data))
    assert np.array_equal(out_p, ref_p)
    assert np.array_equal(out_c, ref_c)
    assert cs.METRICS.counter("dispatches").value - d0 == 3  # 4+4+3pad


def test_mismatched_widths_never_pad_against_each_other(svc):
    """Lanes are keyed by (key, width): an 8-wide submitter and a
    2-wide submitter compile/batch separately."""
    fn = make_fused_encoder(SPEC)
    a = _rand((2, 3, CELL), 4)
    f1 = svc.submit(cs.encode_key(SPEC), fn, a, width=8)
    f2 = svc.submit(cs.encode_key(SPEC), fn, a, width=2)
    p1, _ = cs.wait_result(f1)
    p2, _ = cs.wait_result(f2)
    assert np.array_equal(p1, p2)


# ----------------------------------------------------- linger + deadline
def test_lone_stripe_completes_within_linger_plus_dispatch(
        fresh_service_env):
    """Acceptance: a lone 1-stripe submit into a wide lane completes
    within linger + one dispatch time, via the forced (linger) flush."""
    svc = fresh_service_env(OZONE_TPU_CODEC_LINGER_MS="40")
    fn = make_fused_encoder(SPEC)
    fn(_rand((1, 3, CELL)))  # absorb compile/first-touch cost
    ff0 = cs.METRICS.counter("forced_flushes").value
    t0 = time.monotonic()
    p, _ = cs.wait_result(
        svc.submit(cs.encode_key(SPEC), fn, _rand((1, 3, CELL), 5),
                   width=8, tail=True))
    dt = time.monotonic() - t0
    assert p.shape == (1, 2, CELL)
    # linger (40 ms) + generous dispatch allowance on a loaded CI rig
    assert dt < 0.04 + 1.0, f"lone stripe took {dt:.3f}s"
    assert dt >= 0.8 * 0.04, "linger path was skipped entirely"
    assert cs.METRICS.counter("forced_flushes").value == ff0 + 1
    assert cs.METRICS.gauge("batch_fill_pct").value < 100.0


def test_near_expiry_deadline_forces_partial_flush(fresh_service_env):
    """Acceptance: a submitter whose Deadline is about to expire gets a
    partial-batch dispatch instead of DEADLINE_EXCEEDED — even when the
    linger says to keep waiting for fill."""
    from ozone_tpu.client import resilience

    svc = fresh_service_env(OZONE_TPU_CODEC_LINGER_MS="5000")
    fn = make_fused_encoder(SPEC)
    fn(_rand((1, 3, CELL)))  # absorb compile cost outside the budget
    df0 = cs.METRICS.counter("deadline_flushes").value
    with resilience.start("near_expiry_put", seconds=0.25):
        t0 = time.monotonic()
        p, _ = cs.wait_result(
            svc.submit(cs.encode_key(SPEC), fn,
                       _rand((2, 3, CELL), 6), width=8))
        dt = time.monotonic() - t0
    assert p.shape == (2, 2, CELL)
    assert dt < 2.0, f"deadline flush never fired ({dt:.3f}s)"
    assert cs.METRICS.counter("deadline_flushes").value >= df0 + 1


# ---------------------------------------------------------------- QoS
def test_bulk_sweep_cannot_starve_interactive(fresh_service_env):
    """A saturating bulk sweep and an interactive submitter run
    concurrently: both make progress and the interactive P95 queue wait
    stays bounded while the sweep owns most of the device."""
    svc = fresh_service_env(OZONE_TPU_CODEC_LINGER_MS="1",
                            OZONE_TPU_CODEC_QOS="interactive=4,bulk=1")

    def slow_fn(batch):  # ~3 ms of fake device time per dispatch
        t_end = time.monotonic() + 0.003
        while time.monotonic() < t_end:
            pass
        return (batch.copy(),)

    def fast_fn(batch):
        return (batch.copy(),)

    stop = threading.Event()
    bulk_done = [0]

    def bulk():
        data = _rand((8, 3, CELL), 7)
        while not stop.is_set():
            cs.wait_result(svc.submit(("bulk-lane",), slow_fn, data,
                                      width=8, qos="bulk"))
            bulk_done[0] += 1

    threads = [threading.Thread(target=bulk) for _ in range(3)]
    for t in threads:
        t.start()
    try:
        time.sleep(0.05)  # let the sweep saturate the dispatcher
        waits = []
        one = _rand((1, 3, CELL), 8)
        for _ in range(25):
            t0 = time.monotonic()
            (out,) = cs.wait_result(svc.submit(
                ("interactive-lane",), fast_fn, one, width=1,
                qos="interactive"))
            waits.append(time.monotonic() - t0)
            assert np.array_equal(out, one)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
    assert bulk_done[0] >= 3, "the bulk sweep made no progress"
    waits.sort()
    p95 = waits[int(0.95 * (len(waits) - 1))]
    # bounded: ~2 in-flight bulk dispatches (3 ms each) + scheduling
    # slack on a loaded CI rig — NOT the whole sweep's runtime
    assert p95 < 0.25, f"interactive P95 wait {p95:.3f}s — starved"


def test_starvation_guard_preempts_pathological_weights(
        fresh_service_env):
    """Even with weights pathologically inverted, the starvation guard
    serves an over-aged queue head (and counts the trip)."""
    svc = fresh_service_env(
        OZONE_TPU_CODEC_LINGER_MS="1",
        OZONE_TPU_CODEC_STARVE_MS="20",
        OZONE_TPU_CODEC_QOS="interactive=0.000001,bulk=1000")

    def slow_fn(batch):
        t_end = time.monotonic() + 0.002
        while time.monotonic() < t_end:
            pass
        return (batch.copy(),)

    one = _rand((1, 3, CELL), 10)
    # first interactive dispatch is FREE (vtime 0); it inflates the
    # class's virtual time so fairness alone would now park the class
    # behind the 1000x-weighted bulk queue for the whole backlog
    cs.wait_result(svc.submit(("interactive-lane",), slow_fn, one,
                              width=1, qos="interactive"))
    g0 = cs.METRICS.counter("starvation_guard_trips").value
    # a PRE-QUEUED bulk backlog keeps the bulk lane continuously
    # occupied (~160 ms of fake device time) — no submitter round-trips
    # to race, so the only way interactive gets served inside the
    # backlog window is the starvation guard
    data = _rand((4, 3, CELL), 9)
    bulk_futs = [svc.submit(("bulk-lane",), slow_fn, data, width=4,
                            qos="bulk") for _ in range(80)]
    t0 = time.monotonic()
    (out,) = cs.wait_result(svc.submit(
        ("interactive-lane",), slow_fn, one, width=1,
        qos="interactive"))
    dt = time.monotonic() - t0
    assert np.array_equal(out, one)
    assert cs.METRICS.counter("starvation_guard_trips").value > g0
    # served at ~starve_ms (20 ms), NOT after the whole 160 ms backlog
    assert dt < 0.12, f"guard served the interactive head at {dt:.3f}s"
    for f in bulk_futs:
        cs.wait_result(f)  # the sweep itself still completes


def test_idle_class_activation_floors_virtual_time(fresh_service_env):
    """SFQ activation floor: a class idle through a long burst of the
    other class joins at the system virtual clock — its stale LOW
    virtual time must not buy it a monopoly window (and the returning
    class must not be parked behind it for its past service)."""
    svc = fresh_service_env(OZONE_TPU_CODEC_LINGER_MS="1",
                            OZONE_TPU_CODEC_STARVE_MS="5000",
                            OZONE_TPU_CODEC_QOS="interactive=4,bulk=1")

    def slow_fn(batch):
        t_end = time.monotonic() + 0.002
        while time.monotonic() < t_end:
            pass
        return (batch.copy(),)

    one = _rand((1, 3, CELL), 11)
    # interactive-only phase: its virtual time climbs while bulk idles
    for _ in range(10):
        cs.wait_result(svc.submit(("interactive-lane",), slow_fn, one,
                                  width=1, qos="interactive"))
    # bulk becomes active with a ~100 ms backlog; without the floor its
    # vtime would be 0 << interactive's and fairness would serve ALL of
    # it before the next interactive submission (starve guard is far
    # away at 5 s, so only the floor can bound this)
    data = _rand((4, 3, CELL), 12)
    bulk_futs = [svc.submit(("bulk-lane",), slow_fn, data, width=4,
                            qos="bulk") for _ in range(50)]
    t0 = time.monotonic()
    cs.wait_result(svc.submit(("interactive-lane",), slow_fn, one,
                              width=1, qos="interactive"))
    dt = time.monotonic() - t0
    assert dt < 0.05, (
        f"interactive waited {dt:.3f}s behind an idle-activated bulk "
        f"backlog — the WFQ activation floor is broken")
    assert svc._vtime["bulk"] > 0.0  # joined at the clock, not at zero
    for f in bulk_futs:
        cs.wait_result(f)


# ------------------------------------------------------- datapath wiring
def test_concurrent_writers_coalesce_and_stay_byte_exact(
        cluster, fresh_service_env):
    """The end-to-end tentpole proof at test scale: concurrent small
    PUTs (each ONE stripe — far below the batch width) share fused
    dispatches across operations, and every key reads back byte-exact."""
    fresh_service_env(OZONE_TPU_CODEC_LINGER_MS="250")
    n_ops = 4
    datas = [_rand(3 * CELL, 20 + i) for i in range(n_ops)]
    groups: list = [None] * n_ops
    x0 = cs.METRICS.counter("multi_op_dispatches").value
    t0 = cs.METRICS.counter("tail_flushes").value
    barrier = threading.Barrier(n_ops)

    def put(i):
        barrier.wait()
        w = cluster.writer()
        w.write(datas[i])
        groups[i] = w.close()

    threads = [threading.Thread(target=put, args=(i,))
               for i in range(n_ops)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert all(g is not None for g in groups)
    # all four 1-stripe tails landed within the linger: at least one
    # dispatch carried stripes from MULTIPLE distinct operations
    assert cs.METRICS.counter("multi_op_dispatches").value > x0
    # the partial flushes rode the linger path and were counted
    assert cs.METRICS.counter("tail_flushes").value >= t0 + n_ops
    for i in range(n_ops):
        got = np.concatenate([
            ECBlockGroupReader(g, OPTS, cluster.clients,
                               bytes_per_checksum=1024).read_all()
            for g in groups[i]])
        assert np.array_equal(got, datas[i])


def test_degraded_read_routes_through_service(cluster, svc):
    """A degraded read decodes through the shared service (dispatch
    counters move) and stays byte-exact."""
    data = _rand(6 * CELL, 30)
    w = cluster.writer()
    w.write(data)
    groups = w.close()
    d0 = cs.METRICS.counter("dispatches").value
    for g in groups:
        cluster.dns[[d.id for d in cluster.dns].index(
            g.pipeline.nodes[0])].delete_container(
                g.container_id, force=True)
    got = np.concatenate([
        ECBlockGroupReader(g, OPTS, cluster.clients,
                           bytes_per_checksum=1024).read_all()
        for g in groups])
    assert np.array_equal(got, data)
    assert cs.METRICS.counter("dispatches").value > d0


def test_disabled_service_falls_back_byte_exact(cluster, monkeypatch):
    """OZONE_TPU_CODEC_SERVICE=0: writers/readers keep their
    per-operation pipelines; bytes identical, service untouched."""
    monkeypatch.setenv("OZONE_TPU_CODEC_SERVICE", "0")
    assert cs.maybe_service() is None
    s0 = cs.METRICS.counter("submissions").value
    data = _rand(7 * CELL + 11, 31)
    w = cluster.writer()
    w.write(data)
    groups = w.close()
    for g in groups:
        cluster.dns[[d.id for d in cluster.dns].index(
            g.pipeline.nodes[1])].delete_container(
                g.container_id, force=True)
    got = np.concatenate([
        ECBlockGroupReader(g, OPTS, cluster.clients,
                           bytes_per_checksum=1024).read_all()
        for g in groups])
    assert np.array_equal(got, data)
    assert cs.METRICS.counter("submissions").value == s0


def test_service_error_propagates_to_submitter(svc):
    """A fused fn failing mid-dispatch surfaces on the submitter's
    future, not as a dead dispatcher."""
    def broken(batch):
        raise RuntimeError("device fault")

    with pytest.raises(RuntimeError, match="device fault"):
        cs.wait_result(svc.submit(("broken-lane",), broken,
                                  _rand((1, 3, CELL), 32), width=1))
    # the dispatcher survived: a healthy lane still serves
    fn = make_fused_encoder(SPEC)
    p, _ = cs.wait_result(
        svc.submit(cs.encode_key(SPEC), fn, _rand((1, 3, CELL), 33),
                   width=1))
    assert p.shape == (1, 2, CELL)


def test_stats_snapshot_shape(svc):
    """The Recon /api/codec payload: fill ratio, ops/dispatch, queue
    depth and knob echo are always present."""
    fn = make_fused_encoder(SPEC)
    cs.wait_result(svc.submit(cs.encode_key(SPEC), fn,
                              _rand((2, 3, CELL), 34), width=2))
    out = svc.stats()
    for want in ("fill_ratio", "ops_per_dispatch", "queue_depth",
                 "lanes", "inflight", "linger_ms", "weights", "enabled"):
        assert want in out, want
    assert 0.0 < out["fill_ratio"] <= 1.0
    assert out["enabled"] is True
