"""Native C++ coder tests: bit-compat with numpy backend + hardware CRC."""

import numpy as np
import pytest

from ozone_tpu import native
from ozone_tpu.codec import CoderOptions, create_decoder, create_encoder

pytestmark = pytest.mark.skipif(
    native.load() is None, reason="native toolchain unavailable"
)


@pytest.mark.parametrize("k,p", [(3, 2), (6, 3), (10, 4)])
def test_cpp_encode_matches_numpy(k, p):
    rng = np.random.default_rng(0)
    opts = CoderOptions(k, p, "rs", cell_size=1000)  # odd size: AVX2 tail
    data = rng.integers(0, 256, (3, k, 1000), dtype=np.uint8)
    a = create_encoder(opts, "cpp").encode(data)
    b = create_encoder(opts, "numpy").encode(data)
    assert np.array_equal(a, b)


def test_cpp_decode_roundtrip():
    rng = np.random.default_rng(1)
    opts = CoderOptions(6, 3, "rs", cell_size=513)
    enc = create_encoder(opts, "cpp")
    dec = create_decoder(opts, "cpp")
    data = rng.integers(0, 256, (2, 6, 513), dtype=np.uint8)
    parity = enc.encode(data)
    units = np.concatenate([data, parity], axis=1)
    erased = [0, 4, 7]
    inputs = [None if i in erased else units[:, i] for i in range(9)]
    rec = dec.decode(inputs, erased)
    assert np.array_equal(rec, units[:, erased])


def test_native_crc32c_matches_host():
    from ozone_tpu.codec.cpp_coder import crc32c_native
    from ozone_tpu.utils.checksum import crc32c

    rng = np.random.default_rng(2)
    for n in (0, 1, 7, 8, 9, 1000, 16384):
        d = rng.integers(0, 256, n, dtype=np.uint8)
        assert crc32c_native(d) == crc32c(d), n
    assert crc32c_native(np.frombuffer(b"123456789", np.uint8)) == 0xE3069283


def test_registry_ordering_includes_cpp():
    from ozone_tpu.codec.registry import CodecRegistry

    backends = CodecRegistry.instance().backends("rs")
    assert backends.index("jax") < backends.index("cpp") < backends.index("numpy")


def test_multithreaded_batch_matches_single_thread():
    """The threaded batch kernel must be byte-identical to the serial
    one (stripes are independent; only the split differs)."""
    import numpy as np

    from ozone_tpu.codec.api import CoderOptions
    from ozone_tpu.codec.cpp_coder import CppRSEncoder, _apply

    opts = CoderOptions(4, 2, "rs", cell_size=8192)
    enc = CppRSEncoder(opts)
    data = np.random.default_rng(3).integers(
        0, 256, (13, 4, 8192), dtype=np.uint8)  # odd batch: uneven split
    single = _apply(enc._lib, enc._tables, 2, 4, data, threads=1)
    multi = _apply(enc._lib, enc._tables, 2, 4, data, threads=5)
    assert np.array_equal(single, multi)
