"""CSI driver + Recon warehouse/delta-tailing tests.

Mirrors the reference's CSI service tests (csi/ TestControllerService,
TestNodeService) and Recon task/warehouse tests (recon/ task +
OMDBUpdatesHandler tests)."""

import json
import urllib.request

import numpy as np
import pytest

from ozone_tpu.gateway.csi import CsiClient, CsiServer
from ozone_tpu.recon.recon import (
    ContainerKeyIndex,
    ReconServer,
    ReconWarehouse,
)
from ozone_tpu.testing.minicluster import MiniOzoneCluster

EC = "rs-3-2-4096"


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    c = MiniOzoneCluster(
        tmp_path_factory.mktemp("csirecon"),
        num_datanodes=5,
        block_size=8 * 4096,
        container_size=4 * 1024 * 1024,
        stale_after_s=1000.0,
        dead_after_s=2000.0,
    )
    yield c
    c.close()


# --------------------------------------------------------------------- CSI
@pytest.fixture(scope="module")
def csi(cluster):
    srv = CsiServer(cluster.client(), s3_endpoint="127.0.0.1:9878",
                    replication=EC)
    srv.start()
    cli = CsiClient(srv.address)
    yield cli
    cli.close()
    srv.stop()


def test_csi_identity(csi):
    info = csi.plugin_info()
    assert info["name"].startswith("org.apache.hadoop.ozone")
    assert csi.probe()["ready"] is True


def test_csi_create_list_delete_volume(csi):
    v = csi.create_volume("pvc-1234", capacity_bytes=1 << 30)
    assert v["volume"]["volume_id"] == "pvc-1234"
    # idempotent re-create
    csi.create_volume("pvc-1234")
    assert "pvc-1234" in [e["volume_id"] for e in csi.list_volumes()]
    assert csi.validate("pvc-1234")["confirmed"] is True
    csi.delete_volume("pvc-1234")
    assert "pvc-1234" not in [e["volume_id"] for e in csi.list_volumes()]
    # idempotent re-delete
    csi.delete_volume("pvc-1234")


def test_csi_publish_unpublish(csi, tmp_path):
    csi.create_volume("pvc-mount")
    target = tmp_path / "mnt" / "vol"
    csi.publish("pvc-mount", str(target))
    desc = json.loads((target / ".ozone-csi.json").read_text())
    assert desc["bucket"] == "pvc-mount"
    assert desc["s3_endpoint"] == "127.0.0.1:9878"
    csi.unpublish("pvc-mount", str(target))
    assert not target.exists()
    assert csi.node_info()["node_id"]


# -------------------------------------------------------------------- Recon
def _write_keys(cluster, bucket, names):
    oz = cluster.client()
    try:
        vol = oz.create_volume("rv")
    except Exception:
        vol = oz.get_volume("rv")
    try:
        b = vol.create_bucket(bucket, replication=EC)
    except Exception:
        b = vol.get_bucket(bucket)
    for n in names:
        b.write_key(n, np.arange(5000, dtype=np.uint8) % 251)
    return b


def test_container_key_index_incremental(cluster):
    b = _write_keys(cluster, "idx", ["a", "b"])
    idx = ContainerKeyIndex(cluster.om)
    m0 = idx.container_key_map()
    paths = {p for ps in m0.values() for p in ps}
    assert any(p.endswith("/a") for p in paths)
    rebuilds = idx.full_rebuilds
    # new key arrives via delta, not rebuild
    b.write_key("c", np.zeros(100, np.uint8))
    m1 = idx.container_key_map()
    paths = {p for ps in m1.values() for p in ps}
    assert any(p.endswith("/c") for p in paths)
    assert idx.full_rebuilds == rebuilds
    # delete removes the mapping
    b.delete_key("c")
    m2 = idx.container_key_map()
    paths = {p for ps in m2.values() for p in ps}
    assert not any(p.endswith("/c") for p in paths)
    assert idx.full_rebuilds == rebuilds


def test_container_key_index_fso_paths(cluster):
    """Regression: FSO files must be reported by their real namespace
    path, not the parent-object-id store key."""
    oz = cluster.client()
    try:
        vol = oz.create_volume("rv")
    except Exception:
        vol = oz.get_volume("rv")
    cluster.om.create_bucket("rv", "fsob", EC, "FILE_SYSTEM_OPTIMIZED")
    b = vol.get_bucket("fsob")
    b.write_key("deep/nested/file.dat", np.ones(2048, np.uint8))
    idx = ContainerKeyIndex(cluster.om)
    paths = {p for ps in idx.container_key_map().values() for p in ps}
    assert "/rv/fsob/deep/nested/file.dat" in paths


def test_index_rebuild_when_journal_trimmed(cluster):
    _write_keys(cluster, "trim", ["x"])
    idx = ContainerKeyIndex(cluster.om)
    rebuilds = idx.full_rebuilds
    # simulate journal truncation beyond the consumer's txid
    store = cluster.om.store
    store._updates = store._updates[-1:] if store._updates else []
    idx._txid = 0
    idx.refresh()
    assert idx.full_rebuilds == rebuilds + 1


def test_warehouse_history(cluster, tmp_path):
    _write_keys(cluster, "wh", ["k1", "k2"])
    recon = ReconServer(cluster.om, cluster.scm,
                        db_path=tmp_path / "recon.db")
    recon.start()
    try:
        recon.run_tasks_once()
        recon.run_tasks_once()
        hist = recon.warehouse.history("namespace")
        assert len(hist) == 2
        assert hist[0]["keys"] >= 2
        # REST endpoint
        base = f"http://{recon.address}"
        got = json.loads(
            urllib.request.urlopen(f"{base}/api/history/namespace").read()
        )
        assert len(got) == 2
        keymap = json.loads(
            urllib.request.urlopen(f"{base}/api/containers/keys").read()
        )
        assert any(
            any(p.endswith("/k1") for p in ps) for ps in keymap.values()
        )
    finally:
        recon.stop()


def test_warehouse_persists_across_restart(cluster, tmp_path):
    db = tmp_path / "persist.db"
    w = ReconWarehouse(db)
    w.record("namespace", {"keys": 7})
    w.close()
    w2 = ReconWarehouse(db)
    assert w2.latest("namespace")["keys"] == 7
    w2.close()


def test_get_updates_since_contract(cluster):
    store = cluster.om.store
    # baseline at the current txid: deltas from here must be complete
    _, txid, _ = store.get_updates_since(store.txid)
    _write_keys(cluster, "delta", ["d1"])
    updates2, txid2, complete2 = store.get_updates_since(txid)
    assert complete2
    assert txid2 > txid
    assert all(u[0] > txid for u in updates2)


# --------------------------------------------------- round-2 task breadth
def test_nssummary_fso_du(cluster):
    """Delta-fed per-directory namespace summaries over an FSO bucket
    (NSSummaryTaskWithFSO analog): direct vs recursive totals, du
    children, and incremental updates without a rebuild."""
    from ozone_tpu.recon.recon import NSSummaryIndex

    oz = cluster.client()
    try:
        vol = oz.create_volume("rv")
    except Exception:
        vol = oz.get_volume("rv")
    cluster.om.create_bucket("rv", "nsfso", EC, "FILE_SYSTEM_OPTIMIZED")
    b = vol.get_bucket("nsfso")
    b.write_key("a/one.dat", np.zeros(1000, np.uint8))
    b.write_key("a/b/two.dat", np.zeros(2000, np.uint8))
    b.write_key("top.dat", np.zeros(400, np.uint8))
    ns = NSSummaryIndex(cluster.om)
    root = ns.du("/rv/nsfso")
    assert root["files"] == 1 and root["bytes"] == 400  # direct
    assert root["total_files"] == 3
    assert root["total_bytes"] == 3400
    a = ns.du("/rv/nsfso/a")
    assert a["files"] == 1 and a["total_files"] == 2
    assert a["total_bytes"] == 3000
    assert [c["path"] for c in a["children"]] == ["/rv/nsfso/a/b"]
    # incremental: new file + delete ride the WAL delta, no rebuild
    rebuilds = ns.full_rebuilds
    b.write_key("a/b/three.dat", np.zeros(500, np.uint8))
    assert ns.du("/rv/nsfso/a/b")["total_bytes"] == 2500
    b.delete_key("a/b/three.dat")
    assert ns.du("/rv/nsfso/a/b")["total_bytes"] == 2000
    assert ns.full_rebuilds == rebuilds
    with pytest.raises(KeyError):
        ns.du("/rv/nsfso/nope")


def test_nssummary_obs_and_volume_rollup(cluster):
    from ozone_tpu.recon.recon import NSSummaryIndex

    _write_keys(cluster, "nsobs", ["p/x", "p/y"])
    ns = NSSummaryIndex(cluster.om)
    b = ns.du("/rv/nsobs")
    assert b["total_files"] == 2 and b["total_bytes"] == 10000
    vol = ns.du("/rv")
    assert any(c["path"] == "/rv/nsobs" for c in vol["children"])
    assert vol["total_files"] >= 2


def test_table_insights(cluster):
    from ozone_tpu.recon.recon import TableInsights

    _write_keys(cluster, "ins", ["k1", "k2"])
    ti = TableInsights(cluster.om)
    counts = ti.table_counts()
    assert counts["keys"] >= 2
    assert counts["volumes"] >= 1 and counts["buckets"] >= 1
    # an open (uncommitted) key shows up with its age
    sess = cluster.om.open_key("rv", "ins", "leaked", replication=EC)
    rows = ti.open_keys()
    assert any("leaked" in r["key"] for r in rows)
    assert all(r["age_s"] >= 0 for r in rows)
    del sess
    # deleted keys await the purge chain with pending ages
    oz = cluster.client()
    oz.get_volume("rv").get_bucket("ins").delete_key("k1")
    assert any("k1" in r["key"] for r in ti.deleted_keys())


def test_unhealthy_containers_endpoint(cluster, tmp_path):
    """Unhealthy-container detail (reference /containers/unhealthy):
    killing replicas surfaces UNDER_REPLICATED with per-replica rack
    placement; single-rack clusters report MIS_REPLICATED."""
    import urllib.error

    _write_keys(cluster, "uh", ["k"])
    recon = ReconServer(cluster.om, cluster.scm,
                        db_path=tmp_path / "r.db")
    recon.start()
    try:
        rows = json.loads(urllib.request.urlopen(
            f"http://{recon.address}/api/containers/unhealthy").read())
        # the minicluster puts every DN in one rack: rack-scatter says
        # mis-replicated (capacity placement is rack-blind)
        if rows:
            assert all("states" in r and "replicas" in r for r in rows)
        # filter: a state nothing is in returns empty
        none = json.loads(urllib.request.urlopen(
            f"http://{recon.address}/api/containers/unhealthy"
            "?state=MISSING").read())
        assert none == [] or all("MISSING" in r["states"] for r in none)
        # insights endpoints serve over HTTP too
        counts = json.loads(urllib.request.urlopen(
            f"http://{recon.address}/api/insights/tables").read())
        assert counts["keys"] >= 1
        du = json.loads(urllib.request.urlopen(
            f"http://{recon.address}/api/nssummary?path=/rv/uh").read())
        assert du["total_files"] >= 1
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://{recon.address}/api/nssummary?path=/rv/zzz/q")
        assert ei.value.code == 404
    finally:
        recon.stop()


def test_unhealthy_detail_under_replication(cluster):
    """Dropping a replica from the SCM's view surfaces the container
    with per-replica detail and the right state tags."""
    from ozone_tpu.recon.recon import ReconScmView
    from ozone_tpu.storage.ids import ContainerState

    _write_keys(cluster, "uh2", ["kk"])
    cluster.heartbeat_all()  # replicas enter the SCM via reports
    view = ReconScmView(cluster.scm)
    c = next(c for c in cluster.scm.containers.containers()
             if c.replicas)
    dn, saved = next(iter(c.replicas.items()))
    prev_state = c.state
    c.state = ContainerState.CLOSED
    del c.replicas[dn]
    try:
        rows = view.unhealthy_containers("UNDER_REPLICATED")
        row = next(r for r in rows if r["container"] == c.id)
        assert "UNDER_REPLICATED" in row["states"]
        assert row["actual"] == row["expected"] - 1
        assert all(rep["dn"] != dn for rep in row["replicas"])
        if row["replication"].startswith("rs"):
            assert len(row["missing_indexes"]) == 1
    finally:
        c.replicas[dn] = saved
        c.state = prev_state


def test_recon_ui_contract(cluster, tmp_path):
    """The dashboard's JS contract holds: every /api endpoint the page
    fetches answers 200, and every DOM id the script addresses exists
    in the served HTML (no headless browser in CI, so the contract is
    pinned structurally)."""
    import re

    recon = ReconServer(cluster.om, cluster.scm,
                        db_path=tmp_path / "ui.db")
    recon.start()
    try:
        html = urllib.request.urlopen(
            f"http://{recon.address}/").read().decode()
        urls = sorted(set(re.findall(
            r'fetch\(\s*"(/api/[^"?]+)', html)))
        assert any("nssummary" in u for u in urls), urls
        assert urls, "UI fetches nothing?"
        for u in urls:
            full = u + ("?path=/" if "nssummary" in u else "")
            assert urllib.request.urlopen(
                f"http://{recon.address}{full}").status == 200, u
        ids = set(re.findall(r'getElementById\("([^"]+)"\)', html))
        ids |= {m.split(" ")[0] for m in
                re.findall(r'querySelector\("#([^" ]+)', html)}
        missing = [i for i in ids
                   if f'id="{i}"' not in html and i != "du-root"]
        assert not missing, missing
        for o, c in ("{}", "()", "[]"):
            assert html.count(o) >= html.count(c) - 2  # sanity only
    finally:
        recon.stop()


def test_admin_namespace_summary_cli(tmp_path, capsys):
    """ozone admin namespace summary analog over Recon's NSSummary."""
    import json as _json
    import time as _time

    from ozone_tpu.net.daemons import ScmOmDaemon
    from ozone_tpu.tools.cli import main as cli_main

    meta = ScmOmDaemon(tmp_path / "om.db", stale_after_s=1e6,
                       dead_after_s=2e6, recon_port=0,
                       recon_interval_s=0.2)
    meta.start()
    try:
        om = meta.om
        om.create_volume("nsv")
        om.create_bucket("nsv", "b", "rs-3-2-4096")
        # a zero-byte committed key gives the summary a real row
        s = om.open_key("nsv", "b", "k0")
        om.commit_key(s, [], 0)
        recon_http = meta.recon.address
        deadline = _time.time() + 15
        out = None
        while _time.time() < deadline:
            rc = cli_main(["admin", "namespace", "summary", "/nsv/b",
                           "--http", recon_http])
            raw = capsys.readouterr().out
            if rc == 0 and raw.strip():
                d = _json.loads(raw)
                if d.get("total_files") == 1:
                    out = d
                    break
            _time.sleep(0.3)
        assert out is not None, "summary never showed the committed key"
        assert out["total_bytes"] == 0
        # unknown verb is a usage error; missing --http likewise
        assert cli_main(["admin", "namespace", "du", "/nsv/b",
                         "--http", recon_http]) == 2
        capsys.readouterr()
        assert cli_main(["admin", "namespace", "summary", "/nsv/b"]) == 2
    finally:
        meta.stop()
