"""Batched decode pipeline + persistent decode-plan cache tests.

The read/repair twin of the writer's batched device pipeline: degraded
reads and offline reconstruction must issue ONE device dispatch per
stripe batch (not per stripe), and erasure-pattern churn must never
recompile the decode executable — the plan cache swaps the tiny device
matrix under one jitted program per shape.
"""

import itertools

import numpy as np
import pytest

from tests.test_ec_pipeline import CELL, OPTS, MiniEC, _write_key
from ozone_tpu.codec.api import CoderOptions
from ozone_tpu.codec.pipeline import (
    DeviceBatchPipeline,
    batched,
    decode_batch_size,
)
from ozone_tpu.storage.ids import StorageError


@pytest.fixture
def cluster(tmp_path):
    c = MiniEC(tmp_path, n_dn=8)
    yield c
    c.close()


# ------------------------------------------------------------- plan cache
def test_pattern_churn_never_recompiles(monkeypatch):
    """Every 2-erasure pattern of RS(6,3) decodes through the SAME
    compiled program: the per-pattern work is a small device matrix from
    the plan cache, not a fresh jit (the compile-count probe that would
    have caught the recompile cliff behind BENCH_r05's 21% spread)."""
    monkeypatch.setenv("OZONE_TPU_FUSED_BACKEND", "jax")
    from ozone_tpu.codec import fused
    from ozone_tpu.utils.checksum import Checksum, ChecksumType

    cell, bpc = 2048, 512
    opts = CoderOptions(6, 3, "rs", cell_size=cell)
    spec = fused.FusedSpec(opts, ChecksumType.CRC32C, bpc)
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (3, 6, cell), dtype=np.uint8)
    parity, _ = (np.asarray(x) for x in fused.make_fused_encoder(spec)(data))
    units = np.concatenate([data, parity], axis=1)

    host = Checksum(ChecksumType.CRC32C, bpc)
    before = fused.decode_jit_cache_size()
    patterns = list(itertools.combinations(range(9), 2))
    for erased in patterns:
        valid = [u for u in range(9) if u not in erased][:6]
        fn = fused.make_fused_decoder(spec, valid, list(erased))
        rec, crcs = (np.asarray(x) for x in fn(units[:, valid]))
        assert np.array_equal(rec, units[:, list(erased)]), erased
        # device CRCs of the recovered cells match the host checksummer
        got = tuple(int(v).to_bytes(4, "big") for v in crcs[0, 0].tolist())
        assert got == host.compute(units[0, erased[0]]).checksums, erased
    grew = fused.decode_jit_cache_size() - before
    assert grew <= 1, (
        f"{grew} compiles across {len(patterns)} erasure patterns — the "
        "decode-plan cache must reuse ONE executable per shape")


def test_sharded_pattern_churn_never_recompiles(monkeypatch):
    """Same property for the sharded-DP decode: one SPMD executable per
    (mesh, shape) serves every erasure pattern."""
    monkeypatch.setenv("OZONE_TPU_FUSED_BACKEND", "jax")
    from ozone_tpu.codec import fused
    from ozone_tpu.parallel import sharded
    from ozone_tpu.utils.checksum import ChecksumType

    cell = 1024
    opts = CoderOptions(6, 3, "rs", cell_size=cell)
    spec = fused.FusedSpec(opts, ChecksumType.CRC32C, 512)
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, (4, 6, cell), dtype=np.uint8)
    parity, _ = (np.asarray(x) for x in fused.make_fused_encoder(spec)(data))
    units = np.concatenate([data, parity], axis=1)

    mesh = sharded.make_mesh(4)
    sharded._sharded_decode_apply_cached.cache_clear()
    for erased in itertools.combinations(range(9), 2):
        valid = [u for u in range(9) if u not in erased][:6]
        fn = sharded.make_sharded_decoder(spec, valid, list(erased), mesh)
        rec, _ = (np.asarray(x) for x in fn(units[:, valid]))
        assert np.array_equal(rec, units[:, list(erased)]), erased
    info = sharded._sharded_decode_apply_cached.cache_info()
    assert info.currsize == 1, info


def test_ring_pattern_churn_never_recompiles(monkeypatch):
    """And for the survivor-sharded ppermute ring (use_ring clusters):
    one ring executable per (mesh, shape) serves every erasure pattern —
    OPERATIONS.md promises operators no recompile stalls on degraded
    clusters regardless of the decode topology."""
    monkeypatch.setenv("OZONE_TPU_FUSED_BACKEND", "jax")
    from ozone_tpu.codec import fused
    from ozone_tpu.parallel import sharded
    from ozone_tpu.utils.checksum import ChecksumType

    cell = 1024
    opts = CoderOptions(6, 3, "rs", cell_size=cell)
    spec = fused.FusedSpec(opts, ChecksumType.CRC32C, 512)
    rng = np.random.default_rng(2)
    data = rng.integers(0, 256, (4, 6, cell), dtype=np.uint8)
    parity, _ = (np.asarray(x) for x in fused.make_fused_encoder(spec)(data))
    units = np.concatenate([data, parity], axis=1)

    mesh = sharded.make_mesh(4)
    sharded._ring_apply_cached.cache_clear()
    for erased in itertools.combinations(range(9), 2):
        valid = [u for u in range(9) if u not in erased][:6]
        fn = sharded.make_ring_decoder(spec, valid, list(erased), mesh)
        rec, _ = (np.asarray(x) for x in fn(units[:, valid]))
        assert np.array_equal(rec, units[:, list(erased)]), erased
    info = sharded._ring_apply_cached.cache_info()
    assert info.currsize == 1, info


def test_decode_batch_size_knob(monkeypatch):
    monkeypatch.delenv("OZONE_TPU_DECODE_BATCH", raising=False)
    assert decode_batch_size() == 8
    monkeypatch.setenv("OZONE_TPU_DECODE_BATCH", "3")
    assert decode_batch_size() == 3
    monkeypatch.setenv("OZONE_TPU_DECODE_BATCH", "0")
    assert decode_batch_size() == 1  # floor: at least one stripe
    monkeypatch.setenv("OZONE_TPU_DECODE_BATCH", "junk")
    assert decode_batch_size() == 8


# --------------------------------------------------------------- pipeline
def test_device_batch_pipeline_order_and_depth():
    """submit(N) returns batch N-1's results; exactly one batch stays in
    flight; drain flushes the tail — and every input goes through fn
    exactly once, in order."""
    seen = []

    def fn(batch):
        seen.append(batch.copy())
        return batch + 1, batch * 2

    pipe = DeviceBatchPipeline(fn)
    batches = [np.full((2, 2), i, dtype=np.int64) for i in range(5)]
    got = []
    for i, b in enumerate(batches):
        out = pipe.submit(b, ctx=i)
        if i == 0:
            assert out is None  # depth-1: nothing to hand back yet
        if out is not None:
            got.append(out)
    out = pipe.drain()
    assert out is not None
    got.append(out)
    assert pipe.drain() is None
    assert [ctx for ctx, _ in got] == list(range(5))
    for i, (_ctx, (plus, times)) in enumerate(got):
        assert np.array_equal(plus, batches[i] + 1)
        assert np.array_equal(times, batches[i] * 2)
    assert len(seen) == 5


def test_batched_slices():
    assert [list(b) for b in batched(list(range(7)), 3)] == [
        [0, 1, 2], [3, 4, 5], [6]]
    assert list(batched([], 3)) == []


# ---------------------------------------------------------- degraded read
def _kill_unit(cluster, group, u):
    dn = next(d for d in cluster.dns if d.id == group.pipeline.nodes[u])
    try:
        dn.delete_block(group.block_id)
    except StorageError:
        pass


def test_degraded_read_one_dispatch_per_stripe_batch(cluster, monkeypatch):
    """A degraded whole-group read decodes through the batched pipeline:
    one device dispatch per stripe batch — NOT per stripe — and the
    bytes are exact."""
    import ozone_tpu.client.ec_reader as ec_reader_mod

    monkeypatch.setenv("OZONE_TPU_DECODE_BATCH", "2")
    rng = np.random.default_rng(11)
    data = rng.integers(0, 256, 11 * CELL + 13, dtype=np.uint8)
    groups = _write_key(cluster, data)

    calls: list[int] = []
    real = ec_reader_mod.make_fused_decoder

    def spy(spec, valid, erased):
        fn = real(spec, valid, erased)

        def wrapped(batch):
            calls.append(int(np.asarray(batch).shape[0]))
            return fn(batch)

        return wrapped

    monkeypatch.setattr(ec_reader_mod, "make_fused_decoder", spy)
    total_stripes = 0
    expected_dispatches = 0
    parts = []
    for g in groups:
        _kill_unit(cluster, g, 1)  # lose data unit 1 in every group
        r = cluster.reader(g)
        total_stripes += r.num_stripes
        expected_dispatches += -(-r.num_stripes // 2)
        parts.append(r.read_all())
    got = np.concatenate(parts)
    assert np.array_equal(got, data)
    assert calls, "degraded read never reached the device decoder"
    assert sum(calls) == total_stripes
    assert max(calls) <= 2  # the configured batch depth
    # one dispatch per BATCH, not per stripe
    assert len(calls) == expected_dispatches
    assert len(calls) < total_stripes


def test_recover_cells_iter_streams_batches(cluster, monkeypatch):
    """recover_cells_iter yields (stripe_batch, (rec, crcs)) in stripe
    order with the configured granularity, and matches the one-shot
    recover_cells_with_crcs output."""
    monkeypatch.setenv("OZONE_TPU_DECODE_BATCH", "2")
    rng = np.random.default_rng(12)
    data = rng.integers(0, 256, 12 * CELL, dtype=np.uint8)  # 4 stripes
    g = _write_key(cluster, data)[0]
    _kill_unit(cluster, g, 0)

    r = cluster.reader(g)
    yielded = list(r.recover_cells_iter([0]))
    assert [sb for sb, _ in yielded] == [[0, 1], [2, 3]]
    rec = np.concatenate([out[0] for _, out in yielded])
    r2 = cluster.reader(g)
    cells, crcs = r2.recover_cells_with_crcs([0])
    assert np.array_equal(rec, cells)
    assert crcs.shape[0] == r2.num_stripes
    # recovered unit-0 cells are the original data column
    for s in range(4):
        start = s * 3 * CELL
        assert np.array_equal(cells[s, 0], data[start:start + CELL])


def test_recover_cells_iter_restarts_on_midstream_failure(
        cluster, monkeypatch):
    """A survivor dying AFTER batches were already yielded restarts the
    recovery with the unit excluded and re-yields every batch — and the
    streaming reconstruction consumer, which already wrote the first
    batch's chunks, overwrites idempotently and still commits a
    byte-exact replica."""
    import ozone_tpu.client.ec_reader as er
    import ozone_tpu.storage.reconstruction as recon_mod
    from ozone_tpu.storage.reconstruction import (
        ECReconstructionCoordinator,
        ReconstructionCommand,
    )

    # batch depth 1: the depth-1 pipeline yields batch [0] at submit of
    # stripe 1, so the fault at stripe 2 fires AFTER batch 0's chunks
    # were already streamed to the target — the restart must overwrite
    monkeypatch.setenv("OZONE_TPU_DECODE_BATCH", "1")
    rng = np.random.default_rng(14)
    data = rng.integers(0, 256, 12 * CELL, dtype=np.uint8)  # 4 stripes
    g = _write_key(cluster, data)[0]
    lost = 1
    dn_lost = next(d for d in cluster.dns if d.id == g.pipeline.nodes[lost])
    dn_lost.delete_container(g.container_id, force=True)

    real = er.ECBlockGroupReader._read_cell_checked
    state = {"fired": False, "streamed_before_failure": 0}
    real_stream = recon_mod.write_unit_stream

    def counting_stream(*a, **kw):
        if not state["fired"]:
            state["streamed_before_failure"] += 1
        return real_stream(*a, **kw)

    monkeypatch.setattr(recon_mod, "write_unit_stream", counting_stream)

    def flaky(self, u, s):
        if not state["fired"] and u == 0 and s >= 2:
            state["fired"] = True
            raise er._UnitReadError(u, ConnectionError("injected"))
        return real(self, u, s)

    monkeypatch.setattr(er.ECBlockGroupReader, "_read_cell_checked", flaky)

    sources = {
        u + 1: g.pipeline.nodes[u]
        for u in range(OPTS.all_units) if u != lost
    }
    cmd = ReconstructionCommand(
        g.container_id, OPTS, sources, {lost + 1: "dn7"})
    coord = ECReconstructionCoordinator(
        cluster.clients, bytes_per_checksum=1024)
    coord.reconstruct_container_group(cmd)
    assert state["fired"], "the injected mid-stream failure never fired"
    assert state["streamed_before_failure"] > 0, (
        "failure fired before any batch streamed — the restart-after-"
        "partial-write path was not exercised")

    dn7 = next(d for d in cluster.dns if d.id == "dn7")
    blk = dn7.get_block(g.block_id)
    for info in blk.chunks:
        dn7.read_chunk(g.block_id, info, verify=True)
    g.pipeline.nodes[lost] = "dn7"
    got = cluster.reader(g).read_all()
    assert np.array_equal(got, data[: g.length])


# ---------------------------------------------------------- reconstruction
def test_reconstruction_batched_byte_exact(cluster, monkeypatch):
    """Offline repair through the batched pipeline: byte-exact rebuilt
    replica, device CRCs intact, commit covers every streamed batch."""
    from ozone_tpu.storage.reconstruction import (
        ECReconstructionCoordinator,
        ReconstructionCommand,
    )

    monkeypatch.setenv("OZONE_TPU_DECODE_BATCH", "2")
    rng = np.random.default_rng(13)
    data = rng.integers(0, 256, 10 * CELL + 77, dtype=np.uint8)
    groups = _write_key(cluster, data)
    g = groups[0]
    lost = 2
    dn_lost = next(d for d in cluster.dns if d.id == g.pipeline.nodes[lost])
    dn_lost.delete_container(g.container_id, force=True)

    sources = {
        u + 1: g.pipeline.nodes[u]
        for u in range(OPTS.all_units) if u != lost
    }
    cmd = ReconstructionCommand(
        g.container_id, OPTS, sources, {lost + 1: "dn7"})
    coord = ECReconstructionCoordinator(
        cluster.clients, bytes_per_checksum=1024)
    coord.reconstruct_container_group(cmd)

    dn7 = next(d for d in cluster.dns if d.id == "dn7")
    blk = dn7.get_block(g.block_id)
    assert blk.block_group_length == g.length
    # the commit record covers every batch's streamed chunks, in order
    assert [i.offset for i in blk.chunks] == sorted(
        i.offset for i in blk.chunks)
    for info in blk.chunks:  # device CRCs verify on read
        dn7.read_chunk(g.block_id, info, verify=True)
    # full key still readable using the rebuilt replica only
    g.pipeline.nodes[lost] = "dn7"
    got = cluster.reader(g).read_all()
    assert np.array_equal(got, data[: g.length])
