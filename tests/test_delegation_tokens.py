"""OM delegation tokens: issue/verify/renew/cancel, persistence, and the
authenticated-identity path over gRPC.

Mirrors the reference's delegation-token test surface
(TestOzoneDelegationTokenSecretManager, TestOzoneTokenIdentifier,
TestDelegationToken security integration): signature verification against
the persisted master key, renewer-only renewal bounded by max lifetime,
owner/renewer-only cancellation, expiry handling, token state surviving
an OM restart, and a token authenticating a remote caller's identity.
"""

import json
import time

import pytest

from ozone_tpu.om import dtokens
from ozone_tpu.om import requests as rq
from ozone_tpu.om.om import OzoneManager
from ozone_tpu.scm.scm import StorageContainerManager


@pytest.fixture
def om(tmp_path):
    scm = StorageContainerManager(stale_after_s=1e6, dead_after_s=2e6)
    for i in range(5):
        scm.register_datanode(f"dn{i}")
    om = OzoneManager(tmp_path / "om.db", scm)
    yield om
    om.close()


def test_issue_and_verify(om):
    with om.user_context("alice"):
        tok = om.get_delegation_token("yarn")
    assert tok["owner"] == "alice"
    assert tok["renewer"] == "yarn"
    row = om.verify_delegation_token(tok)
    assert row["owner"] == "alice"
    assert row["expiry"] <= row["max_date"]


def test_tampered_signature_rejected(om):
    tok = om.get_delegation_token("yarn")
    bad = dict(tok, owner="mallory")
    with pytest.raises(rq.OMError) as e:
        om.verify_delegation_token(bad)
    assert e.value.code == rq.TOKEN_ERROR
    # flipped signature byte
    bad2 = dict(tok, sig="0" * len(tok["sig"]))
    with pytest.raises(rq.OMError):
        om.verify_delegation_token(bad2)
    # missing field
    bad3 = {k: v for k, v in tok.items() if k != "renewer"}
    with pytest.raises(rq.OMError):
        om.verify_delegation_token(bad3)


def test_renew_extends_bounded_by_max(om):
    om.dtoken_renew_interval_s = 10.0
    om.dtoken_max_lifetime_s = 3600.0
    tok = om.get_delegation_token("yarn")
    first = om.verify_delegation_token(tok)["expiry"]
    om.dtoken_renew_interval_s = 1e9  # renewal would overshoot max_date
    with om.user_context("yarn"):
        new = om.renew_delegation_token(tok)
    assert new > first
    assert new == tok["max_date"]  # clamped to the hard lifetime


def test_only_renewer_may_renew(om):
    with om.user_context("alice"):
        tok = om.get_delegation_token("yarn")
    with om.user_context("mallory"):
        with pytest.raises(rq.OMError) as e:
            om.renew_delegation_token(tok)
    assert "not the renewer" in e.value.msg
    # even the owner cannot renew (reference semantics)
    with om.user_context("alice"):
        with pytest.raises(rq.OMError):
            om.renew_delegation_token(tok)


def test_cancel_owner_or_renewer_only(om):
    with om.user_context("alice"):
        tok = om.get_delegation_token("yarn")
    with om.user_context("mallory"):
        with pytest.raises(rq.OMError):
            om.cancel_delegation_token(tok)
    with om.user_context("yarn"):
        om.cancel_delegation_token(tok)
    with pytest.raises(rq.OMError) as e:
        om.verify_delegation_token(tok)
    assert "cancelled or unknown" in e.value.msg


def test_expired_token_rejected_and_unrenewable(om):
    om.dtoken_renew_interval_s = 0.05
    tok = om.get_delegation_token("yarn")
    time.sleep(0.1)
    with pytest.raises(rq.OMError) as e:
        om.verify_delegation_token(tok)
    assert "expired" in e.value.msg
    with om.user_context("yarn"):
        with pytest.raises(rq.OMError):
            om.renew_delegation_token(tok)


def test_purge_drops_expired_tokens_and_orphan_keys(om):
    om.dtoken_renew_interval_s = 0.05
    om.dtoken_max_lifetime_s = 0.05
    t1 = om.get_delegation_token("yarn")
    om.dtoken_renew_interval_s = 3600.0
    om.dtoken_max_lifetime_s = 3600.0
    t2 = om.get_delegation_token("yarn")
    time.sleep(0.1)
    assert om.run_dtoken_cleanup_once() == 1
    assert om.store.get("delegation_tokens", t1["token_id"]) is None
    om.verify_delegation_token(t2)  # survivor still valid
    # master key still referenced by t2 -> retained
    assert om.store.get("dtoken_keys", t2["key_id"]) is not None


def test_tokens_survive_om_restart(om, tmp_path):
    with om.user_context("alice"):
        tok = om.get_delegation_token("yarn")
    om.close()
    om2 = OzoneManager(tmp_path / "om.db", om.scm)
    try:
        row = om2.verify_delegation_token(tok)
        assert row["owner"] == "alice"
    finally:
        om2.close()


def test_token_authenticates_remote_caller(tmp_path):
    """The gRPC path: a token-bearing client acts as the token's owner
    even when asserting a different _user, and a forged token fails."""
    from ozone_tpu.net.daemons import ScmOmDaemon
    from ozone_tpu.net.om_service import GrpcOmClient

    meta = ScmOmDaemon(tmp_path / "om.db", stale_after_s=1e6,
                       dead_after_s=2e6)
    meta.start()
    try:
        om = meta.om
        om.enable_acls(superusers=("root",))
        with om.user_context("root"):
            om.create_volume("v1", owner="alice")
            om.create_bucket("v1", "b1", "rs-3-2-4096")
            om.modify_acl("volume", "v1", op="add",
                          acls=["user:alice:a"])
        with om.user_context("alice", ("users",)):
            tok = om.get_delegation_token("yarn")

        c = GrpcOmClient(meta.address, token=tok)
        # the token authenticates alice even with a forged _user field
        with c.user_context("root"):
            info = c.volume_info("v1")
        assert info["name"] == "v1"
        # token identity powers ACL decisions: alice owns v1, so a
        # bucket create succeeds where an anonymous caller is denied
        c.create_bucket("v1", "b2", "rs-3-2-4096")

        from ozone_tpu.storage.ids import StorageError

        anon = GrpcOmClient(meta.address)
        with anon.user_context("mallory"):
            with pytest.raises(StorageError):
                anon.create_bucket("v1", "b3", "rs-3-2-4096")

        forged = dict(tok, owner="root",
                      sig="0" * len(tok["sig"]))
        bad = GrpcOmClient(meta.address, token=forged)
        with pytest.raises(StorageError) as e:
            bad.volume_info("v1")
        assert e.value.code == "TOKEN_ERROR"

        # a token-authenticated caller must NOT mint fresh tokens — a
        # holder chaining tokens forever would defeat the max_date hard
        # lifetime (Hadoop AbstractDelegationTokenSecretManager refuses
        # exactly this)
        with pytest.raises(StorageError) as e:
            c.get_delegation_token("yarn")
        assert e.value.code == "TOKEN_ERROR"

        # anonymous remote renew/cancel is refused: possession of the
        # token file alone must not extend or revoke it
        anon2 = GrpcOmClient(meta.address)
        with anon2.user_context(None):
            with pytest.raises(StorageError):
                anon2.renew_delegation_token(tok)

        # remote renew/cancel round-trip
        yarn = GrpcOmClient(meta.address)
        with yarn.user_context("yarn"):
            new_expiry = yarn.renew_delegation_token(tok)
            assert new_expiry >= time.time()
            yarn.cancel_delegation_token(tok)
        with pytest.raises(StorageError):
            c.volume_info("v1")  # cancelled token no longer authenticates
    finally:
        meta.stop()


def test_cli_token_verbs(tmp_path, capsys):
    """sh token get/print/renew/cancel against a live daemon."""
    from ozone_tpu.net.daemons import ScmOmDaemon
    from ozone_tpu.tools.cli import main

    meta = ScmOmDaemon(tmp_path / "om.db", stale_after_s=1e6,
                       dead_after_s=2e6)
    meta.start()
    try:
        import getpass

        tf = tmp_path / "tok.json"
        assert main(["sh", "token", "get", "--om", meta.address,
                     "--renewer", "yarn", "--token", str(tf)]) == 0
        tok = json.loads(tf.read_text())
        assert tok["renewer"] == "yarn"
        assert main(["sh", "token", "print", "--token", str(tf)]) == 0
        out = capsys.readouterr().out
        assert "yarn" in out
        # renew/cancel act as the login user: only a token naming that
        # user as renewer may be renewed (anonymous remote renewal is
        # refused by the OM since round 4)
        assert main(["sh", "token", "renew", "--om", meta.address,
                     "--token", str(tf)]) != 0
        me = getpass.getuser()
        tf2 = tmp_path / "tok2.json"
        assert main(["sh", "token", "get", "--om", meta.address,
                     "--renewer", me, "--token", str(tf2)]) == 0
        tok2 = json.loads(tf2.read_text())
        assert main(["sh", "token", "renew", "--om", meta.address,
                     "--token", str(tf2)]) == 0
        assert main(["sh", "token", "cancel", "--om", meta.address,
                     "--token", str(tf2)]) == 0
        assert meta.om.store.get(
            "delegation_tokens", tok2["token_id"]) is None
    finally:
        meta.stop()


def test_canonical_signature_stability():
    """The canonical form covers exactly IDENT_FIELDS in sorted order —
    extra fields (like sig itself) never feed the MAC."""
    ident = {f: f for f in dtokens.IDENT_FIELDS}
    a = dtokens.canonical(ident)
    b = dtokens.canonical(dict(ident, sig="x", junk="y"))
    assert a == b


def test_daemon_background_sweeps_expired_tokens(tmp_path):
    """The daemon's slow-cadence background pass purges expired tokens
    and stale open sessions (ExpiredTokenRemover / OpenKeyCleanupService
    scheduling)."""
    from ozone_tpu.net.daemons import ScmOmDaemon

    meta = ScmOmDaemon(tmp_path / "om.db", stale_after_s=1e6,
                       dead_after_s=2e6, background_interval_s=0.02)
    meta.start()
    try:
        om = meta.om
        om.dtoken_renew_interval_s = 0.05
        om.dtoken_max_lifetime_s = 0.05
        tok = om.get_delegation_token("yarn")
        time.sleep(0.2)  # expired now; sweep fires every ~60 ticks
        deadline = time.time() + 15
        while time.time() < deadline:
            if om.store.get("delegation_tokens", tok["token_id"]) is None:
                break
            time.sleep(0.1)
        assert om.store.get("delegation_tokens", tok["token_id"]) is None
    finally:
        meta.stop()
