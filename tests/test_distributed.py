"""Distributed cluster tests: real gRPC transport between daemons
(in one process, loopback sockets — the multi-process topology without the
test overhead). Covers EC write/read through remote OM + datanodes, the
datanode heartbeat/command loop, and reconstruction across the wire.
"""

import time

import numpy as np
import pytest

from ozone_tpu.client.dn_client import DatanodeClientFactory
from ozone_tpu.client.ozone_client import OzoneClient
from ozone_tpu.net.daemons import DatanodeDaemon, ScmOmDaemon
from ozone_tpu.net.om_service import GrpcOmClient
from ozone_tpu.storage.ids import BlockID, ChunkInfo, StorageError

EC = "rs-3-2-4096"


@pytest.fixture
def cluster(tmp_path):
    meta = ScmOmDaemon(
        tmp_path / "om.db",
        block_size=4 * 4096,
        container_size=1024 * 1024,
        stale_after_s=1000.0,
        dead_after_s=2000.0,
        background_interval_s=0.2,
    )
    meta.start()
    dns = []
    for i in range(6):
        d = DatanodeDaemon(
            tmp_path / f"dn{i}", f"dn{i}", meta.address,
            heartbeat_interval_s=0.2,
        )
        d.start()
        dns.append(d)
    yield meta, dns
    for d in dns:
        d.stop()
    meta.stop()


def _client(meta) -> OzoneClient:
    clients = DatanodeClientFactory()
    om = GrpcOmClient(meta.address, clients=clients)
    return OzoneClient(om, clients)


def test_grpc_echo_roundtrip(cluster):
    meta, dns = cluster
    from ozone_tpu.net.dn_service import GrpcDatanodeClient

    c = GrpcDatanodeClient("dn0", dns[0].address)
    assert c.echo(b"hello") == b"hello"
    c.close()


def test_remote_chunk_io(cluster):
    meta, dns = cluster
    from ozone_tpu.net.dn_service import GrpcDatanodeClient
    from ozone_tpu.utils.checksum import Checksum, ChecksumType

    c = GrpcDatanodeClient("dn0", dns[0].address)
    c.create_container(99)
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, 10_000, dtype=np.uint8)
    cs = Checksum(ChecksumType.CRC32C, 4096).compute(data)
    info = ChunkInfo("c0", 0, data.size, cs)
    bid = BlockID(99, 1)
    c.write_chunk(bid, info, data)
    got = c.read_chunk(bid, info, verify=True)
    assert np.array_equal(got, data)
    c.close()


def test_ec_key_over_grpc(cluster):
    meta, dns = cluster
    oz = _client(meta)
    b = oz.create_volume("v").create_bucket("b", replication=EC)
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, 60_000, dtype=np.uint8)
    b.write_key("k", data)
    got = b.read_key("k")
    assert np.array_equal(got, data)
    # degraded read over the wire: stop one datanode hosting the key
    info = oz.om.lookup_key("v", "b", "k")
    victim_id = info["block_groups"][0]["nodes"][0]
    victim = next(d for d in dns if d.dn.id == victim_id)
    victim.server.stop()
    got2 = b.read_key("k")
    assert np.array_equal(got2, data)




def test_fresh_client_reads_via_located_lookup(cluster):
    """A client (or gateway) that never wrote and never fetched the SCM
    topology must still read: key lookups carry the datanode address
    book (the OmKeyLocationInfo DatanodeDetails analog)."""
    meta, dns = cluster
    writer = _client(meta)
    b = writer.create_volume("lv").create_bucket("lb", replication=EC)
    rng = np.random.default_rng(5)
    data = rng.integers(0, 256, 40_000, dtype=np.uint8)
    b.write_key("k", data)

    reader = _client(meta)  # fresh factory: EMPTY address book
    rb = reader.get_volume("lv").get_bucket("lb")
    assert np.array_equal(rb.read_key("k"), data)
    # positioned read on another fresh client
    reader2 = _client(meta)
    got = reader2.get_volume("lv").get_bucket("lb").read_key_range(
        "k", 10_000, 5_000)
    assert np.array_equal(got, data[10_000:15_000])


def _await_replica_rebuild(meta, groups, victim_id,
                           timeout_s: float = 20.0) -> None:
    """Wait until every group's full replica-index set exists off the
    victim (the reconstruction convergence condition both repair tests
    share)."""
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if all(
            {r.replica_index
             for dn_id, r in
             meta.scm.containers.get(g.container_id).replicas.items()
             if dn_id != victim_id} == {1, 2, 3, 4, 5}
            for g in groups
        ):
            return
        time.sleep(0.2)
    raise AssertionError("reconstruction did not complete in time")


def _repoint_groups(meta, groups, victim_id) -> None:
    """Point each group's unit slots at the post-repair replica homes.
    NOTE: reads here bypass OM placement refresh on purpose — the OM
    hands out the placement captured at write time; repair-aware reads
    go through SCM container state, which is what this mimics."""
    for g in groups:
        c = meta.scm.containers.get(g.container_id)
        for dn_id, r in c.replicas.items():
            if r.replica_index and dn_id != victim_id:
                g.pipeline.nodes[r.replica_index - 1] = dn_id


def test_reconstruction_over_grpc(cluster):
    meta, dns = cluster
    # the daemons' coordinators repair on the device mesh (8 virtual
    # devices under the test harness) — the production multi-chip path
    # fed by real gRPC datanode reads
    assert all(d.reconstruction.mesh is not None
               and d.reconstruction.mesh.devices.size == 8 for d in dns)
    oz = _client(meta)
    b = oz.create_volume("v").create_bucket("b", replication=EC)
    rng = np.random.default_rng(2)
    data = rng.integers(0, 256, 40_000, dtype=np.uint8)
    b.write_key("k", data)

    info = oz.om.lookup_key("v", "b", "k")
    groups = oz.om.key_block_groups(info)
    # close the containers so the replication manager treats them
    for g in groups:
        for dn in dns:
            if dn.dn.id in g.pipeline.nodes:
                try:
                    dn.dn.close_container(g.container_id)
                except Exception:
                    pass

    victim_id = groups[0].pipeline.nodes[1]
    victim = next(d for d in dns if d.dn.id == victim_id)
    victim.stop()
    # age out only the victim: an ancient heartbeat exceeds dead_after
    meta.scm.nodes.get(victim_id).last_heartbeat = -1e9
    meta.scm.nodes.check_liveness()

    # wait for reconstruction driven by background loop + heartbeats
    _await_replica_rebuild(meta, groups, victim_id)

    # repoint groups at live replicas and verify bytes
    _repoint_groups(meta, groups, victim_id)
    from ozone_tpu.client.ec_reader import ECBlockGroupReader
    from ozone_tpu.codec.api import CoderOptions

    clients = oz.clients
    for dn_id, addr in meta.scm_service.addresses.items():
        if clients.maybe_get(dn_id) is None:
            clients.register_remote(dn_id, addr)
    parts = [
        ECBlockGroupReader(
            g, CoderOptions.parse(EC), clients, bytes_per_checksum=16 * 1024
        ).read_all()
        for g in groups
    ]
    assert np.array_equal(np.concatenate(parts), data)


def test_container_close_converges(tmp_path):
    """A full container goes CLOSING on the SCM, the close command
    reaches every replica over heartbeats, replicas close and report
    back, and the SCM marks it CLOSED (CloseContainerCommand round
    trip) — making it scannable for the background scrubber."""
    from ozone_tpu.client.dn_client import DatanodeClientFactory
    from ozone_tpu.client.ozone_client import OzoneClient
    from ozone_tpu.net.om_service import GrpcOmClient
    from ozone_tpu.storage.ids import ContainerState

    meta = ScmOmDaemon(
        tmp_path / "om.db",
        block_size=64 * 1024,
        container_size=128 * 1024,  # two blocks fill a container
        stale_after_s=1000.0,
        dead_after_s=2000.0,
        background_interval_s=0.2,
    )
    meta.start()
    dns = [
        DatanodeDaemon(tmp_path / f"dn{i}", f"dn{i}", meta.address,
                       heartbeat_interval_s=0.1)
        for i in range(5)
    ]
    for d in dns:
        d.start()
    try:
        clients = DatanodeClientFactory()
        oz = OzoneClient(GrpcOmClient(meta.address, clients=clients),
                         clients)
        oz.create_volume("v")
        b = oz.get_volume("v").create_bucket("b",
                                             replication="rs-3-2-4096")
        payload = np.random.default_rng(8).integers(
            0, 256, 64 * 1024, dtype=np.uint8).tobytes()
        for i in range(4):  # spans multiple containers
            b.write_key(f"k{i}", payload)
        deadline = time.monotonic() + 15
        closed = []
        while time.monotonic() < deadline:
            closed = [c for c in meta.scm.containers.containers()
                      if c.state is ContainerState.CLOSED]
            if closed:
                break
            time.sleep(0.2)
        assert closed, [
            (c.id, c.state.value)
            for c in meta.scm.containers.containers()
        ]
        # the replicas themselves are closed on the datanodes
        cid = closed[0].id
        on_dns = [d for d in dns
                  if d.dn.containers.get_or_none(cid) is not None]
        assert on_dns
        for d in on_dns:
            assert d.dn.containers.get(cid).state in (
                ContainerState.CLOSED, ContainerState.QUASI_CLOSED)
        # read-back still works from closed containers
        for i in range(4):
            assert b.read_key(f"k{i}").tobytes() == payload
    finally:
        for d in dns:
            d.stop()
        meta.stop()


def test_ratis_container_close_rides_the_raft_ring(tmp_path):
    """Closing a RATIS container is ordered through the pipeline raft
    group (never a bare per-replica close racing replicated writes), and
    a writer that hits the closed container reallocates instead of
    blacklisting healthy nodes."""
    from ozone_tpu.client.dn_client import DatanodeClientFactory
    from ozone_tpu.client.ozone_client import OzoneClient
    from ozone_tpu.net.om_service import GrpcOmClient
    from ozone_tpu.net.ratis_service import RatisClientFactory
    from ozone_tpu.net.scm_service import GrpcScmClient
    from ozone_tpu.storage.ids import ContainerState

    meta = ScmOmDaemon(
        tmp_path / "om.db",
        block_size=64 * 1024,
        container_size=128 * 1024,
        stale_after_s=1000.0,
        dead_after_s=2000.0,
        background_interval_s=0.2,
    )
    meta.start()
    dns = [
        DatanodeDaemon(tmp_path / f"dn{i}", f"dn{i}", meta.address,
                       heartbeat_interval_s=0.1)
        for i in range(3)
    ]
    for d in dns:
        d.start()
    try:
        clients = DatanodeClientFactory()
        om = GrpcOmClient(meta.address, clients=clients)
        for dn_id, addr in GrpcScmClient(
                meta.address).node_addresses().items():
            clients.register_remote(dn_id, addr)
        ratis = RatisClientFactory(address_source=clients.remote_address)
        oz = OzoneClient(om, clients, ratis_clients=ratis)
        oz.create_volume("v")
        b = oz.get_volume("v").create_bucket("b",
                                             replication="RATIS/THREE")
        payload = np.random.default_rng(9).integers(
            0, 256, 64 * 1024, dtype=np.uint8).tobytes()
        # enough keys to fill and roll containers while writing
        for i in range(5):
            b.write_key(f"k{i}", payload)
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            closed = [c for c in meta.scm.containers.containers()
                      if c.state is ContainerState.CLOSED]
            if closed:
                break
            time.sleep(0.2)
        assert closed
        # datanode replicas of the closed container converge to CLOSED
        cid = closed[0].id
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            states = {d.dn.id: d.dn.containers.get_or_none(cid)
                      for d in dns}
            vals = [c.state for c in states.values() if c is not None]
            if vals and all(
                    s in (ContainerState.CLOSED,
                          ContainerState.QUASI_CLOSED) for s in vals):
                break
            time.sleep(0.2)
        assert vals and all(
            s in (ContainerState.CLOSED, ContainerState.QUASI_CLOSED)
            for s in vals), states
        for i in range(5):
            assert b.read_key(f"k{i}").tobytes() == payload
    finally:
        for d in dns:
            d.stop()
        meta.stop()


def test_decommission_survives_scm_restart(tmp_path):
    """The node persists its operational state (set-op-state command)
    and echoes it at registration, so a restarted SCM relearns an
    in-progress drain (persistedOpState round trip)."""
    from ozone_tpu.net.scm_service import GrpcScmClient

    # huge background interval: the decommission monitor must not
    # finalize the (container-less) node to DECOMMISSIONED mid-test
    metas = [ScmOmDaemon(tmp_path / "om.db", stale_after_s=1000.0,
                         dead_after_s=2000.0,
                         background_interval_s=1000.0)]
    metas[0].start()
    dns = [
        DatanodeDaemon(tmp_path / f"dn{i}", f"dn{i}", metas[0].address,
                       heartbeat_interval_s=0.1)
        for i in range(3)
    ]
    for d in dns:
        d.start()
    try:
        port = int(metas[0].address.rsplit(":", 1)[1])
        scm = GrpcScmClient(metas[0].address)
        scm.admin("decommission", "dn1")
        # wait for the set-op-state command to reach and persist on dn1
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if dns[1]._op_state == "DECOMMISSIONING":
                break
            time.sleep(0.1)
        assert dns[1]._op_state == "DECOMMISSIONING"
        scm.close()

        metas.pop().stop()
        meta2 = ScmOmDaemon(tmp_path / "om.db", port=port,
                            stale_after_s=1000.0, dead_after_s=2000.0,
                            background_interval_s=1000.0)
        metas.append(meta2)
        meta2.start()
        # the restarted SCM's durable store already knows the drain —
        # before any datanode even re-registers
        assert meta2.scm.nodes._seeded_op.get("dn1") == "DECOMMISSIONING"
        deadline = time.monotonic() + 10
        node = None
        while time.monotonic() < deadline:
            node = meta2.scm.nodes.get("dn1")
            if node is not None:
                break
            time.sleep(0.1)
        assert node is not None
        assert node.op_state.value == "DECOMMISSIONING"
        # healthy nodes come back IN_SERVICE
        assert meta2.scm.nodes.get("dn0") is None or \
            meta2.scm.nodes.get("dn0").op_state.value == "IN_SERVICE"
    finally:
        for d in dns:
            d.stop()
        for m in metas:
            m.stop()


def test_hsync_and_recover_lease_over_grpc(cluster):
    """hsync/recover-lease ride the remote OM protocol (GrpcOmClient
    CommitKey hsync flag + RecoverLease verb)."""
    meta, dns = cluster
    oz = _client(meta)
    oz.create_volume("hv")
    b = oz.get_volume("hv").create_bucket("hb", replication="RATIS/THREE")
    rng = np.random.default_rng(11)
    data = rng.integers(0, 256, 30_000, dtype=np.uint8)
    h = b.open_key("k")
    h.write(data[:20_000])
    h.hsync()
    assert np.array_equal(b.read_key("k"), data[:20_000])
    out = oz.om.recover_lease("hv", "hb", "k")
    assert out["recovered"] is True
    assert np.array_equal(b.read_key("k"), data[:20_000])
    # fenced: the stale writer's close fails against the sealed key
    h.write(data[20_000:])
    with pytest.raises(StorageError) as ei:
        h.close()
    assert ei.value.code == "KEY_NOT_FOUND"
    assert np.array_equal(b.read_key("k"), data[:20_000])


def test_reconstruction_of_encrypted_key(cluster):
    """TDE composes with EC repair: reconstruction operates on
    ciphertext units (no DEK anywhere near the datanodes), and the
    repaired key decrypts byte-exactly. Placement is repointed from
    SCM container state like the sibling test — OM-served post-repair
    placement is NOT what is covered here."""
    # client-side AES-CTR rides the optional `cryptography` module
    pytest.importorskip("cryptography")
    meta, dns = cluster
    oz = _client(meta)
    meta.om.kms_create_key("reck")
    oz.create_volume("ev")
    meta.om.create_bucket("ev", "enc", EC, encryption_key="reck")
    b = oz.get_volume("ev").get_bucket("enc")
    rng = np.random.default_rng(9)
    data = rng.integers(0, 256, 40_000, dtype=np.uint8)
    b.write_key("k", data)

    info = oz.om.lookup_key("ev", "enc", "k")
    assert "edek" in info["encryption"]
    groups = oz.om.key_block_groups(info)
    for g in groups:
        for dn in dns:
            if dn.dn.id in g.pipeline.nodes:
                try:
                    dn.dn.close_container(g.container_id)
                except Exception:
                    pass
    victim_id = groups[0].pipeline.nodes[0]  # a DATA unit this time
    victim = next(d for d in dns if d.dn.id == victim_id)
    victim.stop()
    meta.scm.nodes.get(victim_id).last_heartbeat = -1e9
    meta.scm.nodes.check_liveness()

    _await_replica_rebuild(meta, groups, victim_id)

    # fresh client + fresh lookup; placement then repointed from SCM
    oz2 = _client(meta)
    for dn_id, addr in meta.scm_service.addresses.items():
        if oz2.clients.maybe_get(dn_id) is None:
            oz2.clients.register_remote(dn_id, addr)
    info2 = oz2.om.lookup_key("ev", "enc", "k")
    g2 = oz2.om.key_block_groups(info2)
    _repoint_groups(meta, g2, victim_id)
    info2["block_groups"] = [g.to_json() for g in g2]
    got = oz2.get_volume("ev").get_bucket("enc").read_key_info(info2)
    assert np.array_equal(got, data)


def test_volume_failure_triggers_reconstruction(cluster):
    """Disk-death flow end-to-end: a datanode volume fails its disk
    check, the replicas drop out of the next full container report, the
    SCM's accounting sees the loss, and the replication manager repairs
    the missing EC unit on another node (the reference's failed-volume
    -> ICR -> ReplicationManager chain)."""
    import shutil

    meta, dns = cluster
    oz = _client(meta)
    b = oz.create_volume("vvf").create_bucket("b", replication=EC)
    rng = np.random.default_rng(5)
    data = rng.integers(0, 256, 40_000, dtype=np.uint8)
    b.write_key("k", data)

    info = oz.om.lookup_key("vvf", "b", "k")
    groups = oz.om.key_block_groups(info)
    for g in groups:
        for dn in dns:
            if dn.dn.id in g.pipeline.nodes:
                try:
                    dn.dn.close_container(g.container_id)
                except Exception:
                    pass

    # kill the DISK (not the node) under one data unit
    victim_id = groups[0].pipeline.nodes[1]
    victim = next(d for d in dns if d.dn.id == victim_id)
    vol = victim.dn.volumes[0]
    shutil.rmtree(vol.root)
    assert victim.dn.check_volumes() == [str(vol.root)]
    assert victim.dn.container_report() == []  # all replicas were there

    # the victim node stays alive and heartbeating; repair must come
    # from the report delta, not a dead-node event
    _await_replica_rebuild(meta, groups, victim_id)

    _repoint_groups(meta, groups, victim_id)
    from ozone_tpu.client.ec_reader import ECBlockGroupReader
    from ozone_tpu.codec.api import CoderOptions

    clients = oz.clients
    for dn_id, addr in meta.scm_service.addresses.items():
        if clients.maybe_get(dn_id) is None:
            clients.register_remote(dn_id, addr)
    parts = [
        ECBlockGroupReader(
            g, CoderOptions.parse(EC), clients, bytes_per_checksum=16 * 1024
        ).read_all()
        for g in groups
    ]
    assert np.array_equal(np.concatenate(parts)[: data.size], data)
