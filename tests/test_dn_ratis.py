"""Datanode Raft write pipeline + gRPC raft transport.

The reference covers this surface with ContainerStateMachine unit tests
and the MiniOzoneCluster Ratis write-path suites (TestXceiverServerRatis,
TestContainerStateMachine, watchForCommit tests in hadoop-hdds/client):
pipeline writes ordered through a per-pipeline Raft group, chunk data
persisted in the data phase and validated at the metadata commit point,
all-replica watch watermarks, and leader failover mid-stream.
"""

import threading

import itertools

import numpy as np
import pytest

from ozone_tpu.client.dn_client import DatanodeClientFactory
from ozone_tpu.client.ec_writer import BlockGroup
from ozone_tpu.client.ratis_client import RatisKeyWriter, XceiverClientRatis
from ozone_tpu.client.replicated import ReplicatedKeyReader
from ozone_tpu.consensus.raft import InProcessTransport, RaftConfig, RaftNode
from ozone_tpu.net.raft_transport import GrpcRaftTransport, RaftRpcService
from ozone_tpu.net.ratis_service import RatisClientFactory
from ozone_tpu.net.rpc import RpcServer
from ozone_tpu.scm.pipeline import Pipeline, ReplicationConfig
from ozone_tpu.storage.datanode import Datanode
from ozone_tpu.storage.ids import (
    BlockData,
    BlockID,
    ChunkInfo,
    ContainerState,
    StorageError,
)
from ozone_tpu.storage.ratis import ContainerStateMachine, RatisXceiverServer

FAST = RaftConfig(heartbeat_interval_s=0.05,
                  election_timeout_s=(0.15, 0.3))


# ------------------------------------------------------ state machine unit
def make_dn(tmp_path, name="dn0"):
    return Datanode(tmp_path / name, dn_id=name)


def test_state_machine_verbs(tmp_path):
    dn = make_dn(tmp_path)
    sm = ContainerStateMachine(dn)
    assert sm.apply({"verb": "create_container", "container_id": 1})["ok"]
    # idempotent re-apply (log replay after restart)
    assert sm.apply({"verb": "create_container", "container_id": 1})["ok"]

    bid = BlockID(1, 1)
    data = np.arange(100, dtype=np.uint8)
    info = ChunkInfo("c0", 0, 100)
    dn.write_chunk(bid, info, data)  # data phase
    out = sm.apply({"verb": "write_chunk_commit",
                    "block_id": bid.to_json(), "offset": 0, "length": 100})
    assert out["ok"]
    bd = BlockData(bid, [info])
    out = sm.apply({"verb": "put_block", "block": bd.to_json()})
    assert out["committed_length"] == 100
    assert sm.apply({"verb": "close_container", "container_id": 1})["ok"]
    assert dn.get_block(bid).committed
    dn.close()


def test_state_machine_missing_data_marks_unhealthy(tmp_path):
    """A member that missed the data phase must fail the commit apply and
    poison its replica for the replication manager."""
    dn = make_dn(tmp_path)
    sm = ContainerStateMachine(dn)
    sm.apply({"verb": "create_container", "container_id": 1})
    bid = BlockID(1, 1)
    with pytest.raises(StorageError) as ei:
        sm.apply({"verb": "write_chunk_commit",
                  "block_id": bid.to_json(), "offset": 0, "length": 4096})
    assert ei.value.code == "CHUNK_DATA_MISSING"
    assert dn.containers.get(1).state is ContainerState.UNHEALTHY
    dn.close()


# ------------------------------------------------- in-process pipeline ring
@pytest.fixture
def ring(tmp_path):
    """Three datanodes sharing one pipeline raft group, in-process."""
    transport = InProcessTransport()
    dns, xceivers = [], []
    ids = ["dn0", "dn1", "dn2"]
    peers = {i: "" for i in ids}
    for name in ids:
        dn = make_dn(tmp_path, name)
        xc = RatisXceiverServer(dn, tmp_path / name, "", config=FAST,
                                auto_timers=False)
        dns.append(dn)
        xceivers.append(xc)
    pipeline = Pipeline(ReplicationConfig.ratis(3), ids)
    for xc in xceivers:
        xc.join(pipeline.id, peers, transport=transport)
    # deterministic leadership: dn0
    assert xceivers[0].get(pipeline.id).start_election()
    yield dns, xceivers, pipeline
    for xc in xceivers:
        xc.stop()
    for dn in dns:
        dn.close()


#: module-global so successive write_key calls never re-issue a local
#: id — the datanode write fence (Container.bind_writer) now refuses a
#: second writer streaming into an existing block file, which is exactly
#: what a per-call counter restarting at 1 would do
_alloc_count = itertools.count(1)


def write_key(dns, xceivers, pipeline, payload, **kw):
    clients = DatanodeClientFactory()
    ratis = RatisClientFactory()
    for dn, xc in zip(dns, xceivers):
        clients.register_local(dn)
        ratis.register_local(xc, dn.id)
    alloc_count = _alloc_count

    def allocate_group(excluded):
        assert not set(pipeline.nodes) & set(excluded), \
            "pipeline members excluded mid-test"
        return BlockGroup(container_id=1, local_id=next(alloc_count),
                          pipeline=pipeline)

    w = RatisKeyWriter(allocate_group, clients, ratis, **kw)
    w.write(payload)
    groups = w.close()
    return groups, clients


def test_pipeline_write_replicates_to_all(ring):
    dns, xceivers, pipeline = ring
    payload = np.random.default_rng(7).integers(
        0, 256, 300_000, dtype=np.uint8)
    groups, clients = write_key(dns, xceivers, pipeline, payload,
                                chunk_size=64 * 1024)
    # read back through the normal replica-failover reader
    out = np.concatenate(
        [ReplicatedKeyReader(g, clients).read_all() for g in groups])
    assert np.array_equal(out, payload)
    # every member holds identical committed metadata (ordered history)
    for g in groups:
        lengths = {dn.id: dn.get_committed_block_length(g.block_id)
                   for dn in dns}
        assert set(lengths.values()) == {g.length}, lengths
        for dn in dns:
            assert dn.get_block(g.block_id).committed


def test_not_leader_rejected_and_hint_followed(ring):
    dns, xceivers, pipeline = ring
    # direct submit on a follower is rejected with the leader hint
    with pytest.raises(StorageError) as ei:
        xceivers[1].submit(pipeline.id, {"verb": "create_container",
                                         "container_id": 9})
    assert ei.value.code == "NOT_LEADER"
    assert ei.value.msg == "dn0"
    # the client-side xceiver follows the hint transparently
    ratis = RatisClientFactory()
    for dn, xc in zip(dns, xceivers):
        ratis.register_local(xc, dn.id)
    x = XceiverClientRatis(pipeline, ratis)
    x._leader = "dn1"  # wrong guess on purpose
    assert x.submit({"verb": "create_container", "container_id": 9})["ok"]
    assert x._leader == "dn0"


def test_watch_all_vs_majority(ring):
    dns, xceivers, pipeline = ring
    leader = xceivers[0].get(pipeline.id)
    transport = leader.transport
    # partition dn2 away from the leader: quorum (dn0+dn1) still commits
    transport.partition("dn0", "dn2")
    out = xceivers[0].submit(pipeline.id, {"verb": "create_container",
                                           "container_id": 2})
    idx = out["index"]
    # ALL cannot complete while dn2 is cut off...
    with pytest.raises(StorageError) as ei:
        xceivers[0].watch(pipeline.id, idx, policy="ALL", timeout=0.5)
    assert ei.value.code == "TIMEOUT"
    # ...MAJORITY can
    assert xceivers[0].watch(pipeline.id, idx, policy="MAJORITY",
                             timeout=5)["index"] == idx
    # heal: replication catches dn2 up and ALL completes
    transport.heal()
    assert xceivers[0].watch(pipeline.id, idx, policy="ALL",
                             timeout=5)["index"] == idx
    assert dns[2].containers.get_or_none(2) is not None


def test_leader_failover_mid_stream(ring):
    dns, xceivers, pipeline = ring
    payload = np.random.default_rng(3).integers(
        0, 256, 100_000, dtype=np.uint8)
    groups, clients = write_key(dns, xceivers, pipeline, payload,
                                chunk_size=32 * 1024)
    # depose dn0; dn1 takes over; further writes go through the new leader
    n0 = xceivers[0].get(pipeline.id)
    n1 = xceivers[1].get(pipeline.id)
    n0._step_down(n0.storage.term + 1)
    assert n1.start_election()
    more, _ = write_key(dns, xceivers, pipeline, payload,
                        chunk_size=32 * 1024)
    out = np.concatenate(
        [ReplicatedKeyReader(g, clients).read_all()
         for g in groups + more])
    assert np.array_equal(out, np.concatenate([payload, payload]))


def test_write_succeeds_with_minority_member_down(ring):
    """Raft availability: one of three members dead -> data phase reaches
    a quorum, commit goes through, watch degrades to MAJORITY."""
    dns, xceivers, pipeline = ring
    leader = xceivers[0].get(pipeline.id)
    transport = leader.transport
    transport.down.add("dn2")

    class DeadClient:
        dn_id = "dn2"

        def __getattr__(self, name):
            def boom(*a, **k):
                raise StorageError("IO_EXCEPTION", "dn2 is down")

            return boom

    clients = DatanodeClientFactory()
    ratis = RatisClientFactory()
    for dn, xc in zip(dns[:2], xceivers[:2]):
        clients.register_local(dn)
        ratis.register_local(xc, dn.id)
    clients._local["dn2"] = DeadClient()

    def allocate_group(excluded):
        return BlockGroup(container_id=1, local_id=1, pipeline=pipeline)

    payload = np.random.default_rng(9).integers(
        0, 256, 100_000, dtype=np.uint8)
    w = RatisKeyWriter(allocate_group, clients, ratis, chunk_size=32 * 1024,
                       watch_timeout_s=0.5)
    w.write(payload)
    groups = w.close()
    # the two live replicas hold the committed data
    out = ReplicatedKeyReader(groups[0], clients).read_all()
    assert np.array_equal(out, payload)
    for dn in dns[:2]:
        assert dn.get_committed_block_length(groups[0].block_id) \
            == groups[0].length
    # dn2 never saw the data; when it comes back and applies the log, the
    # commit apply poisons its replica for repair
    transport.heal()
    leader.tick()
    assert dns[2].containers.get(1).state is ContainerState.UNHEALTHY
    # the degrade is sticky: later watches skip the ALL timeout
    assert w._xceivers[pipeline.id]._degraded


def test_join_replaces_group_with_changed_membership(tmp_path):
    """Defense in depth: a served group whose announced membership
    differs is stale metadata — it must be replaced, never reused."""
    transport = InProcessTransport()
    dn = make_dn(tmp_path, "dnA")
    xc = RatisXceiverServer(dn, tmp_path / "dnA", "", config=FAST,
                            auto_timers=False)
    n1 = xc.join(77, {"dnA": "", "dnB": "", "dnC": ""},
                 transport=transport)
    assert set(n1.peer_ids) == {"dnB", "dnC"}
    n2 = xc.join(77, {"dnA": "", "dnB": "", "dnD": ""},
                 transport=InProcessTransport())
    assert n2 is not n1
    assert set(n2.peer_ids) == {"dnB", "dnD"}
    xc.stop()
    dn.close()


def test_pipeline_ids_survive_scm_restart(tmp_path):
    """Pipeline ids are persisted and the allocator advances past them on
    recovery: a restarted SCM can never re-issue an id a datanode still
    serves a raft group under."""
    from ozone_tpu.scm.container_manager import ContainerManager
    from ozone_tpu.scm.node_manager import NodeManager
    from ozone_tpu.scm.placement import RandomPlacement

    def make_cm():
        nodes = NodeManager(stale_after_s=1e6, dead_after_s=2e6)
        for i in range(3):
            nodes.register(f"dn{i}", "/r1", 0)
        return ContainerManager(nodes, RandomPlacement(nodes),
                                db_path=tmp_path / "scm.db")

    cm = make_cm()
    g = cm.allocate_block(ReplicationConfig.ratis(3), 1024)
    pid = g.pipeline.id

    cm2 = make_cm()  # restart on the same db
    recovered = {p.id: p for p in cm2.pipelines()}
    assert pid in recovered
    assert recovered[pid].nodes == g.pipeline.nodes
    g2 = cm2.allocate_block(ReplicationConfig.ratis(3), 1024)
    # same still-open container (and pipeline) is reused after recovery
    assert g2.pipeline.id == pid
    # forcing a new pipeline allocates a strictly fresh id
    cm2.finalize_container(g2.container_id)
    g3 = cm2.allocate_block(ReplicationConfig.ratis(3), 1024)
    assert g3.pipeline.id > pid


def test_closed_pipeline_is_retired(tmp_path):
    """Closing a container fires the pipeline-closed hook exactly once
    and drops the pipeline from the live set (the leave-pipeline path)."""
    from ozone_tpu.scm.container_manager import ContainerManager
    from ozone_tpu.scm.node_manager import NodeManager
    from ozone_tpu.scm.placement import RandomPlacement

    nodes = NodeManager(stale_after_s=1e6, dead_after_s=2e6)
    for i in range(3):
        nodes.register(f"dn{i}", "/r1", 0)
    cm = ContainerManager(nodes, RandomPlacement(nodes))
    closed = []
    cm.on_pipeline_closed = closed.append
    g = cm.allocate_block(ReplicationConfig.ratis(3), 1024)
    assert cm.pipelines() and not closed
    cm.finalize_container(g.container_id)
    cm.mark_closed(g.container_id)  # idempotent second transition
    assert [p.id for p in closed] == [g.pipeline.id]
    assert g.pipeline.id not in {p.id for p in cm.pipelines()}


# -------------------------------------------------------- full daemon wiring
def test_daemon_cluster_ratis_key_roundtrip(tmp_path):
    """SCM announces the pipeline, datanode daemons join the raft group
    over heartbeat commands, and a RATIS/THREE key write is ordered
    through the elected leader — the whole deployment shape."""
    import time as _time

    from ozone_tpu.client.ozone_client import OzoneClient
    from ozone_tpu.net.daemons import DatanodeDaemon, ScmOmDaemon
    from ozone_tpu.net.om_service import GrpcOmClient
    from ozone_tpu.net.ratis_service import RatisClientFactory

    meta = ScmOmDaemon(tmp_path / "om.db", block_size=256 * 1024,
                       stale_after_s=1000.0, dead_after_s=2000.0,
                       background_interval_s=0.2)
    meta.start()
    dns = []
    try:
        for i in range(3):
            d = DatanodeDaemon(tmp_path / f"dn{i}", f"dn{i}", meta.address,
                               heartbeat_interval_s=0.1)
            d.start()
            dns.append(d)
        for _ in range(50):
            if not meta.scm.safemode.in_safemode():
                break
            _time.sleep(0.1)

        clients = DatanodeClientFactory()
        om = GrpcOmClient(meta.address, clients=clients)
        from ozone_tpu.net.scm_service import GrpcScmClient

        for dn_id, addr in GrpcScmClient(
                meta.address).node_addresses().items():
            clients.register_remote(dn_id, addr)
        ratis = RatisClientFactory(address_source=clients.remote_address)
        oz = OzoneClient(om, clients, ratis_clients=ratis)

        oz.create_volume("v")
        b = oz.get_volume("v").create_bucket("b", replication="RATIS/THREE")
        payload = np.random.default_rng(5).integers(
            0, 256, 200_000, dtype=np.uint8).tobytes()
        b.write_key("k", payload)
        out = b.read_key("k")
        assert out.tobytes() == payload

        # each daemon serves the pipeline group; replicas agree
        served = [d.xceiver_ratis.pipelines() for d in dns]
        assert all(served[0] == s and s for s in served), served
        info = om.lookup_key("v", "b", "k")
        for g in om.key_block_groups(info):
            lengths = {d.dn.id: d.dn.get_committed_block_length(g.block_id)
                       for d in dns}
            assert set(lengths.values()) == {g.length}, lengths

        # restart a datanode: it rejoins its groups from local state
        dns[1].stop()
        d1 = DatanodeDaemon(tmp_path / "dn1", "dn1", meta.address,
                            heartbeat_interval_s=0.1)
        d1.start()
        dns[1] = d1
        assert d1.xceiver_ratis.pipelines() == served[0]
        b.write_key("k2", payload)
        assert b.read_key("k2").tobytes() == payload
    finally:
        for d in dns:
            d.stop()
        meta.stop()


# ------------------------------------------------------- grpc raft transport
def test_grpc_raft_transport_election_and_commit(tmp_path):
    """Three raft peers on real RpcServers: elect, commit, route around a
    stopped peer — the multi-process deployment path of consensus."""
    ids = ["a", "b", "c"]
    servers, services = {}, {}
    for nid in ids:
        srv = RpcServer("127.0.0.1", 0)
        services[nid] = RaftRpcService(srv)
        srv.start()
        servers[nid] = srv
    addrs = {nid: servers[nid].address for nid in ids}

    states = {nid: [] for nid in ids}
    nodes = {}
    for nid in ids:
        tr = GrpcRaftTransport("g1", addrs)
        node = RaftNode(
            node_id=nid, peer_ids=[p for p in ids if p != nid],
            storage_dir=tmp_path / nid,
            apply_fn=states[nid].append, config=FAST, transport=tr,
        )
        services[nid].register("g1", node)
        nodes[nid] = node
    try:
        assert nodes["a"].start_election()
        assert nodes["a"].propose({"op": "put", "k": 1}) is None
        nodes["a"].tick()
        assert states["a"] == [{"op": "put", "k": 1}]
        assert states["b"] == [{"op": "put", "k": 1}]
        assert states["c"] == [{"op": "put", "k": 1}]
        # peer c goes away: quorum continues
        services["c"].unregister("g1")
        servers["c"].stop()
        assert nodes["a"].propose({"op": "put", "k": 2}) is None
        nodes["a"].tick()  # push the commit index to b
        assert states["b"][-1] == {"op": "put", "k": 2}
    finally:
        for nid in ids:
            nodes[nid].stop()
        for nid in ("a", "b"):
            servers[nid].stop()


def test_grpc_ratis_pipeline_end_to_end(tmp_path):
    """Full remote shape: three datanodes with RatisXceiverServers over
    real gRPC (raft RPCs and client submit/watch both on the wire)."""
    from ozone_tpu.net.ratis_service import RatisGrpcService

    ids = ["dn0", "dn1", "dn2"]
    dns, xcs, rpc_servers = [], [], []
    for name in ids:
        dn = Datanode(tmp_path / name, dn_id=name)
        srv = RpcServer("127.0.0.1", 0)
        raft_svc = RaftRpcService(srv)
        xc = RatisXceiverServer(dn, tmp_path / name, "", rpc_service=raft_svc,
                                config=FAST)
        RatisGrpcService(xc, srv)
        srv.start()
        dns.append(dn)
        xcs.append(xc)
        rpc_servers.append(srv)
    addrs = {name: srv.address for name, srv in zip(ids, rpc_servers)}
    pipeline = Pipeline(ReplicationConfig.ratis(3), ids)
    try:
        for xc in xcs:
            xc.join(pipeline.id, addrs)
        assert xcs[0].get(pipeline.id).start_election()

        clients = DatanodeClientFactory()
        ratis = RatisClientFactory()
        for dn in dns:
            clients.register_local(dn)  # data phase stays in-process here
        for name, srv in zip(ids, rpc_servers):
            ratis.register_remote(name, srv.address)

        payload = np.random.default_rng(11).integers(
            0, 256, 150_000, dtype=np.uint8)

        def allocate_group(excluded):
            return BlockGroup(container_id=1, local_id=1, pipeline=pipeline)

        w = RatisKeyWriter(allocate_group, clients, ratis,
                           chunk_size=64 * 1024)
        w.write(payload)
        groups = w.close()
        out = np.concatenate(
            [ReplicatedKeyReader(g, clients).read_all() for g in groups])
        assert np.array_equal(out, payload)
        for dn in dns:
            assert dn.get_committed_block_length(groups[0].block_id) \
                == groups[0].length
    finally:
        for xc in xcs:
            xc.stop()
        for srv in rpc_servers:
            srv.stop()
        for dn in dns:
            dn.close()
