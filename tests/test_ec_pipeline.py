"""EC write/read pipeline tests over in-process datanodes.

Strategy mirrors the reference's TestECKeyOutputStream +
TestECContainerRecovery: write keys of awkward sizes, re-read, kill units,
assert degraded reads and targeted recovery are byte-exact, and verify the
rollback-to-new-group path on write failure.
"""

import itertools

import numpy as np
import pytest

from ozone_tpu.client.dn_client import DatanodeClientFactory, LocalDatanodeClient
from ozone_tpu.client.ec_reader import (
    ECBlockGroupReader,
    InsufficientLocationsError,
)
from ozone_tpu.client.ec_writer import BlockGroup, ECKeyWriter, block_lengths
from ozone_tpu.codec.api import CoderOptions
from ozone_tpu.scm.pipeline import Pipeline, ReplicationConfig
from ozone_tpu.storage.datanode import Datanode
from ozone_tpu.storage.ids import StorageError

CELL = 4096  # small cells keep tests fast
OPTS = CoderOptions(3, 2, "rs", cell_size=CELL)


class MiniEC:
    """Tiny in-process cluster: n datanodes + naive group allocator."""

    def __init__(self, tmp_path, n_dn=6, opts=OPTS):
        self.opts = opts
        self.dns = [Datanode(tmp_path / f"dn{i}", dn_id=f"dn{i}") for i in range(n_dn)]
        self.clients = DatanodeClientFactory()
        for dn in self.dns:
            self.clients.register_local(dn)
        self._cid = itertools.count(1)
        self._lid = itertools.count(1)
        self.allocated: list[BlockGroup] = []

    def allocate(self, excluded: list[str]) -> BlockGroup:
        nodes = [d.id for d in self.dns if d.id not in excluded][
            : self.opts.all_units
        ]
        if len(nodes) < self.opts.all_units:
            raise RuntimeError("not enough nodes")
        g = BlockGroup(
            container_id=next(self._cid),
            local_id=next(self._lid),
            pipeline=Pipeline(ReplicationConfig.from_ec(self.opts), nodes),
        )
        self.allocated.append(g)
        return g

    def writer(self, **kw) -> ECKeyWriter:
        kw.setdefault("block_size", 4 * CELL)  # 4 stripes per group
        kw.setdefault("bytes_per_checksum", 1024)
        kw.setdefault("stripe_batch", 3)
        return ECKeyWriter(self.opts, self.allocate, self.clients, **kw)

    def reader(self, g: BlockGroup, **kw) -> ECBlockGroupReader:
        kw.setdefault("bytes_per_checksum", 1024)
        return ECBlockGroupReader(g, self.opts, self.clients, **kw)

    def close(self):
        for d in self.dns:
            d.close()


@pytest.fixture
def cluster(tmp_path):
    c = MiniEC(tmp_path)
    yield c
    c.close()


def _write_key(cluster, data: np.ndarray, **kw) -> list[BlockGroup]:
    w = cluster.writer(**kw)
    # write in uneven pieces to exercise buffering
    pos = 0
    rng = np.random.default_rng(123)
    while pos < data.size:
        n = min(int(rng.integers(1, 3 * CELL)), data.size - pos)
        w.write(data[pos : pos + n])
        pos += n
    groups = w.close()
    assert w.bytes_written == data.size
    assert sum(g.length for g in groups) == data.size
    return groups


def _read_key(cluster, groups, **kw) -> np.ndarray:
    parts = [cluster.reader(g, **kw).read_all() for g in groups]
    return np.concatenate(parts) if parts else np.zeros(0, np.uint8)


@pytest.mark.parametrize(
    "size",
    [
        1,  # sub-cell
        CELL,  # exactly one cell
        CELL + 17,  # partial second cell
        3 * CELL,  # exactly one stripe
        3 * CELL + 1,  # stripe + 1 byte
        7 * CELL + 99,  # partial stripe in second stripe row
        12 * CELL,  # exactly one full group (4 stripes)
        25 * CELL + 5,  # multiple groups, partial tail
    ],
)
def test_write_read_roundtrip(cluster, size):
    rng = np.random.default_rng(size)
    data = rng.integers(0, 256, size, dtype=np.uint8)
    groups = _write_key(cluster, data)
    got = _read_key(cluster, groups)
    assert np.array_equal(got, data)


def test_block_lengths_math():
    # group_length=7*CELL+99 over k=3: block0 = 3*CELL, block1 = 2*CELL+99...
    k = 3
    L = 7 * CELL + 99
    bl = block_lengths(L, k, CELL)
    assert sum(bl) == L
    # stripe layout: s0: c0,c1,c2 | s1: c3,c4,c5 | s2: c6, partial(99), 0
    assert bl[0] == 3 * CELL
    assert bl[1] == 2 * CELL + 99
    assert bl[2] == 2 * CELL


def test_degraded_read_single_and_double_loss(cluster):
    rng = np.random.default_rng(42)
    # kill exactly n_kill distinct units per group (p=2 tolerable)
    for n_kill in (1, 2):
        data = rng.integers(0, 256, 10 * CELL + 7, dtype=np.uint8)
        groups = _write_key(cluster, data)
        for g in groups:
            for u in rng.choice(5, size=n_kill, replace=False).tolist():
                dn_id = g.pipeline.nodes[u]
                dn = next(d for d in cluster.dns if d.id == dn_id)
                try:
                    dn.delete_block(g.block_id)
                except StorageError:
                    pass
        got = _read_key(cluster, groups)
        assert np.array_equal(got, data), f"n_kill={n_kill}"


def test_ranged_reads_match_slices(cluster):
    """Cell-granular positioned reads (round 4): every awkward range
    equals the slice of a full read, on healthy AND degraded groups
    (where only the covering stripes may be reconstructed)."""
    rng = np.random.default_rng(31)
    data = rng.integers(0, 256, 9 * CELL + 123, dtype=np.uint8)
    groups = _write_key(cluster, data)
    g = groups[0]
    cases = [(0, 1), (CELL - 1, 2), (0, g.length), (g.length - 1, 1),
             (CELL // 2, 3 * CELL), (2 * CELL + 7, CELL + 100),
             (g.length, 0)]
    for off, ln in cases:
        got = cluster.reader(g).read(off, ln)
        assert np.array_equal(got, data[off:off + ln]), (off, ln)
    with pytest.raises(ValueError):
        cluster.reader(g).read(0, g.length + 1)
    with pytest.raises(ValueError):
        cluster.reader(g).read(-1, 1)
    # randomized sweep: any (offset, length) equals the slice
    for _ in range(20):
        off = int(rng.integers(0, g.length))
        ln = int(rng.integers(0, g.length - off + 1))
        got = cluster.reader(g).read(off, ln)
        assert np.array_equal(got, data[off:off + ln]), (off, ln)
    # degrade: drop one data unit and one parity unit
    for u in (1, 4):
        dn = next(d for d in cluster.dns if d.id == g.pipeline.nodes[u])
        dn.delete_block(g.block_id)
    for off, ln in cases:
        got = cluster.reader(g).read(off, ln)
        assert np.array_equal(got, data[off:off + ln]), \
            f"degraded range ({off},{ln})"
    for _ in range(20):
        off = int(rng.integers(0, g.length))
        ln = int(rng.integers(0, g.length - off + 1))
        got = cluster.reader(g).read(off, ln)
        assert np.array_equal(got, data[off:off + ln]), \
            f"degraded random range ({off},{ln})"


def test_replicated_ranged_read(cluster):
    from ozone_tpu.client.replicated import (
        ReplicatedKeyReader,
        ReplicatedKeyWriter,
    )

    def allocate(excluded, ec=()):
        g = cluster.allocate(excluded)
        g.pipeline.nodes = g.pipeline.nodes[:3]
        return g

    w = ReplicatedKeyWriter(allocate, cluster.clients,
                            block_size=16 * CELL, chunk_size=CELL)
    rng = np.random.default_rng(37)
    data = rng.integers(0, 256, 5 * CELL + 19, dtype=np.uint8)
    w.write(data)
    (g,) = w.close()
    for off, ln in [(0, 1), (CELL - 1, 2), (0, g.length),
                    (g.length - 1, 1), (2 * CELL + 5, 2 * CELL),
                    (g.length, 0)]:
        got = ReplicatedKeyReader(g, cluster.clients).read(off, ln)
        assert np.array_equal(got, data[off:off + ln]), (off, ln)
    with pytest.raises(ValueError):
        ReplicatedKeyReader(g, cluster.clients).read(1, g.length)


def test_ranged_read_off_missing_unit_needs_no_recovery(cluster):
    """A ranged read that never touches the missing unit must not pay a
    reconstruction: recover_cells is forbidden for the duration."""
    rng = np.random.default_rng(41)
    data = rng.integers(0, 256, 3 * CELL, dtype=np.uint8)  # one stripe
    groups = _write_key(cluster, data)
    g = groups[0]
    dn = next(d for d in cluster.dns if d.id == g.pipeline.nodes[2])
    dn.delete_block(g.block_id)  # data unit 2 gone
    r = cluster.reader(g)

    def boom(*a, **kw):
        raise AssertionError("range off the missing unit must not "
                             "trigger recovery")
    r.recover_cells = boom
    # bytes [0, 2*CELL) live on units 0 and 1 only
    got = r.read(CELL // 2, CELL)
    assert np.array_equal(got, data[CELL // 2 : CELL // 2 + CELL])
    # and a range ON the missing unit still reconstructs (fresh reader)
    got = cluster.reader(g).read(2 * CELL + 5, 100)
    assert np.array_equal(got, data[2 * CELL + 5 : 2 * CELL + 105])


def test_short_replica_fails_over_not_zero_fill(cluster):
    """A replica missing its tail chunk must fail over to the next
    replica, never serve zero-filled bytes (stale-replica safety)."""
    from ozone_tpu.client.replicated import (
        ReplicatedKeyReader,
        ReplicatedKeyWriter,
    )
    from ozone_tpu.storage.ids import BlockData

    def allocate(excluded, ec=()):
        g = cluster.allocate(excluded)
        g.pipeline.nodes = g.pipeline.nodes[:3]
        return g

    w = ReplicatedKeyWriter(allocate, cluster.clients,
                            block_size=8 * CELL, chunk_size=CELL)
    rng = np.random.default_rng(43)
    data = rng.integers(0, 256, 3 * CELL, dtype=np.uint8)
    w.write(data)
    (g,) = w.close()
    # truncate the FIRST replica's record to 2 chunks (a datanode that
    # died before the last commit; re-written record, chunk file stays)
    dn0 = next(d for d in cluster.dns if d.id == g.pipeline.nodes[0])
    bd = dn0.get_block(g.block_id)
    dn0.put_block(BlockData(g.block_id, bd.chunks[:2]))
    # whole and tail ranged reads must come from a healthy replica
    got = ReplicatedKeyReader(g, cluster.clients).read_all()
    assert np.array_equal(got, data)
    got = ReplicatedKeyReader(g, cluster.clients).read(2 * CELL + 1, 100)
    assert np.array_equal(got, data[2 * CELL + 1 : 2 * CELL + 101])


def test_too_many_losses_raises(cluster):
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, 4 * CELL, dtype=np.uint8)
    groups = _write_key(cluster, data)
    g = groups[0]
    for u in range(3):  # kill 3 of 5 units: only 2 remain < k=3
        dn = next(d for d in cluster.dns if d.id == g.pipeline.nodes[u])
        dn.delete_block(g.block_id)
    with pytest.raises(InsufficientLocationsError):
        cluster.reader(g).recover_cells([0, 1, 2])


def test_recover_cells_targeted(cluster):
    rng = np.random.default_rng(2)
    data = rng.integers(0, 256, 6 * CELL, dtype=np.uint8)  # 2 full stripes
    groups = _write_key(cluster, data)
    g = groups[0]
    # recover data unit 1 and parity unit 4 without killing anything
    rec = cluster.reader(g).recover_cells([1, 4])
    assert rec.shape == (2, 2, CELL)
    # expected data cells of unit 1: stripe s covers data[s*3*C + 1*C : +C]
    for s in range(2):
        expect = data[s * 3 * CELL + CELL : s * 3 * CELL + 2 * CELL]
        assert np.array_equal(rec[s, 0], expect)
    # parity unit must equal freshly encoded parity
    from ozone_tpu.codec import create_encoder

    stripes = data.reshape(2, 3, CELL)
    parity = create_encoder(OPTS, "numpy").encode(stripes)
    assert np.array_equal(rec[:, 1, :], parity[:, 1, :])


class FlakyClient(LocalDatanodeClient):
    """Fails the first `n_failures` write_chunk calls."""

    def __init__(self, dn, n_failures=1):
        super().__init__(dn)
        self.n_failures = n_failures

    def write_chunk(self, block_id, info, data, sync=False, writer=None):
        if self.n_failures > 0:
            self.n_failures -= 1
            raise StorageError("IO_EXCEPTION", "injected failure")
        return super().write_chunk(block_id, info, data, sync, writer=writer)


def test_write_failure_rolls_to_new_group(cluster):
    # make dn0 fail once: the first stripe write fails, the writer must
    # exclude dn0, allocate a new group, and replay
    cluster.clients._local["dn0"] = FlakyClient(cluster.dns[0], n_failures=1)
    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, 5 * CELL, dtype=np.uint8)
    groups = _write_key(cluster, data)
    assert all("dn0" not in g.pipeline.nodes for g in groups[0:1]) or len(
        cluster.allocated
    ) > len(groups)
    got = _read_key(cluster, groups)
    assert np.array_equal(got, data)


def test_checksums_stored_and_verified(cluster):
    rng = np.random.default_rng(4)
    data = rng.integers(0, 256, 3 * CELL, dtype=np.uint8)
    groups = _write_key(cluster, data)
    g = groups[0]
    dn = next(d for d in cluster.dns if d.id == g.pipeline.nodes[0])
    bd = dn.get_block(g.block_id)
    assert bd.chunks[0].checksum.checksums  # device CRCs persisted
    assert bd.block_group_length == data.size
    # corrupt unit 0 on disk; verified read must fall back to reconstruction
    path = dn.get_container(g.container_id).chunks.block_path(g.block_id)
    raw = bytearray(path.read_bytes())
    raw[10] ^= 0xFF
    path.write_bytes(bytes(raw))
    got = cluster.reader(g).read_all()
    assert np.array_equal(got, data)


class FlakyPutBlockClient(LocalDatanodeClient):
    """Fails put_block call number `fail_call` (0-based; chunks always
    succeed), so a chosen stripe's commit phase fails mid-flight."""

    def __init__(self, dn, fail_call=1):
        super().__init__(dn)
        self.fail_call = fail_call
        self.calls = 0

    def put_block(self, block, sync=False, writer=None):
        me = self.calls
        self.calls += 1
        if me == self.fail_call:
            raise StorageError("IO_EXCEPTION", "injected putBlock failure")
        return super().put_block(block, sync, writer=writer)


def _assert_no_inflated_survivors(cluster, groups):
    """Every datanode holding a finalized group must agree on its
    committed length (datanode metadata is what offline reconstruction
    trusts — no unit may report bytes the client never acked)."""
    first = cluster.allocated[0]
    if first.length and first is not groups[-1]:
        for u, dn_id in enumerate(first.pipeline.nodes):
            dn = next(d for d in cluster.dns if d.id == dn_id)
            try:
                bd = dn.get_block(first.block_id)
            except StorageError:
                continue  # failed node holds no commit: fine
            assert bd.block_group_length == first.length, \
                f"unit {u} on {dn_id} reports inflated group length " \
                f"{bd.block_group_length} != {first.length}"


def test_putblock_failure_rolls_back_survivor_commits(cluster):
    """Per-stripe path: a putBlock failure mid-stripe must not leave
    OTHER datanodes committed at the inflated group length — the
    concurrently dispatched putBlocks roll back to the pre-stripe
    watermark."""
    cluster.clients._local["dn0"] = FlakyPutBlockClient(
        cluster.dns[0], fail_call=1)  # stripe 0 commits; stripe 1 fails
    rng = np.random.default_rng(13)
    # two stripes: stripe 0 commits, stripe 1's putBlock fails on dn0
    # and replays into a fresh group after rollover
    data = rng.integers(0, 256, 2 * 3 * CELL, dtype=np.uint8)
    groups = _write_key(cluster, data, batched_rpc=False)
    got = _read_key(cluster, groups)
    assert np.array_equal(got, data)
    _assert_no_inflated_survivors(cluster, groups)


def test_batched_run_commit_failure_rolls_back_survivors(cluster):
    """Batched-RPC path: the run's piggybacked commit fails on one
    unit while the other units' streams committed the run-end record —
    survivors must roll back to the pre-run watermark and the run
    replays into a fresh group."""
    cluster.clients._local["dn0"] = FlakyPutBlockClient(
        cluster.dns[0], fail_call=0)  # the run's only commit fails
    rng = np.random.default_rng(17)
    data = rng.integers(0, 256, 2 * 3 * CELL, dtype=np.uint8)
    groups = _write_key(cluster, data)
    got = _read_key(cluster, groups)
    assert np.array_equal(got, data)
    _assert_no_inflated_survivors(cluster, groups)


class _NoStreamClient(LocalDatanodeClient):
    """A member without the WriteChunksCommit verb (pre-finalize layout
    / older server): refuses the batch, serves the per-chunk verbs."""

    calls = 0

    def write_chunks_commit(self, block_id, chunks, commit=None,
                            sync=False, writer=None):
        _NoStreamClient.calls += 1
        raise StorageError("NOT_SUPPORTED_OPERATION_PRIOR_FINALIZATION",
                           "WriteChunksCommit needs layout feature")


class _FlakyCombinedClient(LocalDatanodeClient):
    """Fails combined chunk+commit call number `fail_call` (0-based)."""

    def __init__(self, dn, fail_call=1):
        super().__init__(dn)
        self.fail_call = fail_call
        self.calls = 0

    def write_chunks_commit(self, block_id, chunks, commit=None,
                            sync=False, writer=None):
        me = self.calls
        self.calls += 1
        if me == self.fail_call:
            raise StorageError("IO_EXCEPTION", "injected combined failure")
        return super().write_chunks_commit(block_id, chunks, commit,
                                           sync, writer)


def test_replicated_combined_partial_failure_rolls_back_survivors(cluster):
    """A member failing the combined chunk+commit call must not leave
    the OTHER members committed with the unacked chunk (the split path
    never commits until every member took the data; replicas must not
    disagree on committed length)."""
    from ozone_tpu.client.replicated import (
        ReplicatedKeyReader,
        ReplicatedKeyWriter,
    )

    cluster.clients._local["dn2"] = _FlakyCombinedClient(
        cluster.dns[2], fail_call=1)  # chunk 0 lands; chunk 1 fails

    def allocate(excluded, ec=()):
        g = cluster.allocate(excluded)
        g.pipeline.nodes = g.pipeline.nodes[:3]
        return g

    w = ReplicatedKeyWriter(allocate, cluster.clients,
                            block_size=8 * CELL, chunk_size=CELL)
    rng = np.random.default_rng(29)
    data = rng.integers(0, 256, 2 * CELL, dtype=np.uint8)
    w.write(data)
    groups = w.close()
    got = np.concatenate(
        [ReplicatedKeyReader(g, cluster.clients).read_all()
         for g in groups])
    assert np.array_equal(got, data)
    # the first group finalized at chunk 0 only; the survivors that
    # took chunk 1's combined call must have rolled back to one chunk
    first = cluster.allocated[0]
    assert first.length == CELL
    for dn_id in first.pipeline.nodes[:2]:
        dn = next(d for d in cluster.dns if d.id == dn_id)
        bd = dn.get_block(first.block_id)
        assert len(bd.chunks) == 1, \
            f"{dn_id} kept the unacked chunk after rollback"


def test_replicated_writer_combined_commit_downgrade(cluster):
    """The replicated writer's combined chunk+commit fan-out downgrades
    to split phases when a member lacks the verb, with byte-exact data
    and no member excluded."""
    from ozone_tpu.client.replicated import (
        ReplicatedKeyReader,
        ReplicatedKeyWriter,
    )

    _NoStreamClient.calls = 0
    cluster.clients._local["dn1"] = _NoStreamClient(cluster.dns[1])

    def allocate(excluded, ec=()):
        g = cluster.allocate(excluded)
        g.pipeline.nodes = g.pipeline.nodes[:3]  # THREE-replica pipeline
        return g

    w = ReplicatedKeyWriter(allocate, cluster.clients,
                            block_size=8 * CELL, chunk_size=CELL)
    rng = np.random.default_rng(23)
    data = rng.integers(0, 256, 5 * CELL + 11, dtype=np.uint8)
    w.write(data)
    groups = w.close()
    assert w._combined_commit is False
    assert _NoStreamClient.calls == 1  # probed once, never again
    assert w._excluded == []
    assert sum(g.length for g in groups) == data.size
    got = np.concatenate(
        [ReplicatedKeyReader(g, cluster.clients).read_all()
         for g in groups])
    assert np.array_equal(got, data)


def test_mixed_version_member_falls_back_to_per_stripe(cluster):
    """One pipeline member refusing the batched verb downgrades the
    writer to per-stripe RPCs for the rest of the write (the
    allDataNodesSupportPiggybacking downgrade) — with a clean rollback,
    no reallocation, and byte-exact data."""
    _NoStreamClient.calls = 0
    cluster.clients._local["dn1"] = _NoStreamClient(cluster.dns[1])
    rng = np.random.default_rng(19)
    data = rng.integers(0, 256, 6 * 3 * CELL + 7, dtype=np.uint8)
    w = cluster.writer()
    w.write(data)
    groups = w.close()
    assert w._stream_writes is False
    assert _NoStreamClient.calls == 1  # probed once, never again
    assert sum(g.length for g in groups) == data.size
    got = _read_key(cluster, groups)
    assert np.array_equal(got, data)
    # the downgrade is not a node failure: nobody was excluded
    assert w._excluded == []
