"""Native filesystem failure injector (LD_PRELOAD interposer).

Mirrors the reference's fault-injection-service test intent
(tools/fault-injection-service): operations under a target path can be
failed with a chosen errno, delayed, or corrupted, while untargeted
paths are untouched — and a datanode whose chunk writes are corrupted
detects it via checksum verification on read.
"""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

from ozone_tpu.testing.fault_injection import FaultInjector, build_injector

pytestmark = pytest.mark.skipif(build_injector() is None,
                                reason="no native toolchain")


def _run_py(code: str, env: dict) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, "-c", code],
        env={**os.environ, **env, "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=60,
    )


def test_write_fail_with_errno(tmp_path):
    fi = FaultInjector(tmp_path)
    target = tmp_path / "data"
    target.mkdir()
    fi.fail("write", target, "ENOSPC")
    r = _run_py(
        "import sys\n"
        f"f = open({str(target / 'x')!r}, 'wb')\n"
        "try:\n"
        "    f.write(b'hello'); f.flush(); print('WROTE')\n"
        "except OSError as e:\n"
        "    print('ERR', e.errno)\n",
        fi.env(),
    )
    assert "ERR 28" in r.stdout  # ENOSPC


def test_open_fail_and_untargeted_path_unaffected(tmp_path):
    fi = FaultInjector(tmp_path)
    target = tmp_path / "blocked"
    other = tmp_path / "free"
    target.mkdir()
    other.mkdir()
    fi.fail("open", target, "EACCES")
    r = _run_py(
        "try:\n"
        f"    open({str(target / 'x')!r}, 'wb'); print('OPENED')\n"
        "except OSError as e:\n"
        "    print('ERR', e.errno)\n"
        f"open({str(other / 'y')!r}, 'wb').write(b'ok')\n"
        "print('OTHER_OK')\n",
        fi.env(),
    )
    assert "ERR 13" in r.stdout  # EACCES
    assert "OTHER_OK" in r.stdout


def test_write_corruption_detected_by_checksum(tmp_path):
    """End-to-end scanner story: a corrupted chunk write is caught by
    read-side checksum verification (the on-demand scanner trigger)."""
    fi = FaultInjector(tmp_path)
    dn_root = tmp_path / "dn"
    fi.corrupt_writes(dn_root)
    code = f"""
import numpy as np
from pathlib import Path
from ozone_tpu.storage.datanode import Datanode
from ozone_tpu.storage.ids import BlockID, ChunkInfo, ContainerState
from ozone_tpu.utils.checksum import Checksum, ChecksumType, ChecksumError
from ozone_tpu.storage.ids import StorageError

dn = Datanode(Path({str(dn_root)!r}), "dn0")
dn.create_container(1, replica_index=1)
data = np.arange(4096, dtype=np.uint8) % 251
cs = Checksum(ChecksumType.CRC32C, 1024).compute(data)
info = ChunkInfo("c0", 0, data.size, cs)
dn.write_chunk(BlockID(1, 1), info, data)
try:
    dn.read_chunk(BlockID(1, 1), info, verify=True)
    print("UNDETECTED")
except ChecksumError:
    print("CORRUPTION_DETECTED")
except StorageError as e:
    print("CORRUPTION_DETECTED" if e.code == "CHECKSUM_MISMATCH"
          else f"OTHER {{e.code}}")
"""
    r = _run_py(code, {**fi.env(), "PYTHONPATH": os.getcwd()})
    assert "CORRUPTION_DETECTED" in r.stdout, r.stdout + r.stderr


def test_batched_ec_write_survives_pwrite_faults(tmp_path):
    """Round-4 batched write path under REAL syscall faults: one
    datanode whose chunk pwrites fail with EIO is excluded mid-write
    (run rollback + fresh group) and the key lands byte-exact on the
    healthy members."""
    fi = FaultInjector(tmp_path)  # rules start empty: datanodes (and
    bad_root = tmp_path / "dn0"   # their volume DBs) must boot healthy
    code = f"""
import itertools
import os
import time
import numpy as np
from pathlib import Path
from ozone_tpu.client.dn_client import DatanodeClientFactory
from ozone_tpu.client.ec_reader import ECBlockGroupReader
from ozone_tpu.client.ec_writer import BlockGroup, ECKeyWriter
from ozone_tpu.codec.api import CoderOptions
from ozone_tpu.scm.pipeline import Pipeline, ReplicationConfig
from ozone_tpu.storage.datanode import Datanode

root = Path({str(tmp_path)!r})
opts = CoderOptions(3, 2, "rs", cell_size=4096)
dns = [Datanode(root / f"dn{{i}}", dn_id=f"dn{{i}}") for i in range(6)]
clients = DatanodeClientFactory()
for d in dns:
    clients.register_local(d)
cid, lid = itertools.count(1), itertools.count(1)

# datanodes are up: NOW fail dn0's disk (live rules reload; the shim
# compares whole-second mtimes, so bump well past the current one)
rules = Path({str(fi.rules_path)!r})
rules.write_text(f"pwrite {{root / 'dn0'}} fail EIO\\n"
                 f"write {{root / 'dn0'}} fail EIO\\n")
st = rules.stat()
os.utime(rules, (st.st_atime, int(st.st_mtime) + 2))
time.sleep(1.3)  # the shim's reload check is 1s-granular

def allocate(excluded, ec=()):
    nodes = [d.id for d in dns if d.id not in excluded][:5]
    assert len(nodes) == 5, nodes
    return BlockGroup(container_id=next(cid), local_id=next(lid),
                      pipeline=Pipeline(ReplicationConfig.from_ec(opts),
                                        nodes))

w = ECKeyWriter(opts, allocate, clients, block_size=4 * 4096,
                bytes_per_checksum=1024, stripe_batch=3)
data = np.random.default_rng(0).integers(0, 256, 5 * 4096,
                                         dtype=np.uint8)
w.write(data)
groups = w.close()
assert "dn0" in w._excluded, w._excluded
assert all("dn0" not in g.pipeline.nodes for g in groups)
parts = [ECBlockGroupReader(g, opts, clients,
                            bytes_per_checksum=1024).read_all()
         for g in groups]
got = np.concatenate(parts)
assert np.array_equal(got, data), "data mismatch"
print("FAULT_EXCLUDED_OK")
"""
    r = _run_py(code, {**fi.env(), "PYTHONPATH": os.getcwd()})
    assert "FAULT_EXCLUDED_OK" in r.stdout, r.stdout + r.stderr


def test_delay(tmp_path):
    fi = FaultInjector(tmp_path)
    target = tmp_path / "slow"
    target.mkdir()
    fi.delay("write", target, 300)
    # measure around the write inside the child: wall-clocking the whole
    # subprocess would pass vacuously from interpreter startup alone
    r = _run_py(
        "import time\n"
        f"f = open({str(target / 'x')!r}, 'wb')\n"
        "t0 = time.time(); f.write(b'z'); f.flush()\n"
        "print('ELAPSED', time.time() - t0)\n",
        fi.env(),
    )
    elapsed = float(r.stdout.split("ELAPSED")[1])
    assert elapsed >= 0.3, r.stdout


def test_fd_reuse_does_not_leak_rules(tmp_path):
    """After closing a targeted file, a recycled fd pointing at an
    untargeted file must not inherit its fault rules."""
    fi = FaultInjector(tmp_path)
    target = tmp_path / "t"
    other = tmp_path / "o"
    target.mkdir()
    other.mkdir()
    fi.fail("write", target, "EIO")
    r = _run_py(
        "import os\n"
        f"fd1 = os.open({str(target / 'x')!r}, os.O_WRONLY | os.O_CREAT)\n"
        "try:\n"
        "    os.write(fd1, b'x'); print('T_WROTE')\n"
        "except OSError as e:\n"
        "    print('T_ERR', e.errno)\n"
        "os.close(fd1)\n"
        # the very next open typically recycles the same fd number
        f"fd2 = os.open({str(other / 'y')!r}, os.O_WRONLY | os.O_CREAT)\n"
        "print('SAME_FD', fd1 == fd2)\n"
        "os.write(fd2, b'y'); print('O_WROTE')\n"
        "os.close(fd2)\n",
        fi.env(),
    )
    assert "T_ERR 5" in r.stdout
    assert "SAME_FD True" in r.stdout, r.stdout  # fd actually recycled
    assert "O_WROTE" in r.stdout, r.stdout


def test_live_retarget(tmp_path):
    """Rules can change while the victim process is running (the gRPC
    retargeting capability of the reference, minus the RPC)."""
    fi = FaultInjector(tmp_path)
    target = tmp_path / "d"
    target.mkdir()
    code = f"""
import sys
p = {str(target / 'x')!r}
open(p, 'wb').write(b'first')          # no rules yet -> fine
print('PHASE1_OK', flush=True)
sys.stdin.readline()                   # controller plants a rule now
try:
    f = open(p, 'wb'); f.write(b'second'); print('PHASE2_WROTE')
except OSError as e:
    print('PHASE2_ERR', e.errno)
"""
    proc = subprocess.Popen(
        [sys.executable, "-c", code],
        env={**os.environ, **fi.env(), "JAX_PLATFORMS": "cpu"},
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True,
    )
    line = proc.stdout.readline()
    assert "PHASE1_OK" in line
    fi.fail("open", target, "EIO")
    time.sleep(1.2)  # the shim's reload check is 1s-granular
    out, _ = proc.communicate(input="go\n", timeout=30)
    assert "PHASE2_ERR 5" in out  # EIO planted mid-flight
