"""Composite file checksums (getFileChecksum / ECFileChecksumHelper +
ECBlockChecksumComputer analog): the whole-key CRC composed from chunk
checksums stored on the datanodes, without reading data — and equal
across replication layouts, the distcp comparison property.
"""

import numpy as np
import pytest

from ozone_tpu.testing.minicluster import MiniOzoneCluster
from ozone_tpu.utils.checksum import crc32c

EC = "rs-3-2-4096"


@pytest.fixture
def cluster(tmp_path):
    c = MiniOzoneCluster(
        tmp_path,
        num_datanodes=5,
        block_size=4 * 4096,  # multi-block keys for multi-group compose
        container_size=1024 * 1024,
        stale_after_s=1000.0,
        dead_after_s=2000.0,
    )
    yield c
    c.close()


def _payload(n, seed):
    return np.random.default_rng(seed).integers(0, 256, n, dtype=np.uint8)


def test_replicated_composite_matches_whole_stream(cluster):
    oz = cluster.client()
    b = oz.create_volume("v").create_bucket("b", replication="RATIS/THREE")
    data = _payload(50_000, 0)  # spans multiple blocks
    b.write_key("k", data)
    out = b.file_checksum("k")
    assert out["algorithm"] == "COMPOSITE-CRC32C"
    assert out["length"] == data.size
    assert int(out["checksum"], 16) == crc32c(data)


def test_ec_composite_matches_whole_stream(cluster):
    oz = cluster.client()
    b = oz.create_volume("v").create_bucket("b", replication=EC)
    # multi-stripe with a partial last cell AND partial last stripe
    data = _payload(3 * 4096 * 2 + 4096 + 123, 1)
    b.write_key("k", data)
    out = b.file_checksum("k")
    assert out["length"] == data.size
    assert int(out["checksum"], 16) == crc32c(data)


def test_composite_equal_across_layouts(cluster):
    """The distcp property: identical bytes under EC and replication
    produce the same composite checksum."""
    oz = cluster.client()
    vol = oz.create_volume("v")
    ec_b = vol.create_bucket("ecb", replication=EC)
    rep_b = vol.create_bucket("repb", replication="RATIS/THREE")
    data = _payload(27_001, 2)
    ec_b.write_key("k", data)
    rep_b.write_key("k", data)
    assert ec_b.file_checksum("k") == rep_b.file_checksum("k")


def test_composite_differs_on_different_data(cluster):
    oz = cluster.client()
    b = oz.create_volume("v").create_bucket("b", replication=EC)
    b.write_key("a", _payload(10_000, 3))
    b.write_key("b", _payload(10_000, 4))
    assert b.file_checksum("a") != b.file_checksum("b")


def test_replicated_composite_survives_replica_loss(cluster):
    oz = cluster.client()
    b = oz.create_volume("v").create_bucket("b", replication="RATIS/THREE")
    data = _payload(20_000, 5)
    b.write_key("k", data)
    info = oz.om.lookup_key("v", "b", "k")
    dn0 = info["block_groups"][0]["nodes"][0]
    cluster.stop_datanode(dn0)
    out = b.file_checksum("k")
    assert int(out["checksum"], 16) == crc32c(data)


def test_ec_composite_fails_loudly_when_a_unit_is_unreachable(cluster):
    """An unreachable data unit must raise, never return a plausible
    short composition (the silent-shortening integrity hazard)."""
    oz = cluster.client()
    b = oz.create_volume("v").create_bucket("b", replication=EC)
    data = _payload(3 * 4096 * 2, 6)  # all units hold data
    b.write_key("k", data)
    info = oz.om.lookup_key("v", "b", "k")
    # unit 0's datanode dies
    cluster.stop_datanode(info["block_groups"][0]["nodes"][0])
    with pytest.raises(Exception):
        b.file_checksum("k")
