"""FSO bucket layout: directory tree semantics.

Mirrors the reference's FSO coverage (ozone-manager request/file tests,
TestObjectStoreWithFSO): nested file create with implicit parents, dir
rename moving subtrees in O(1), recursive delete via the directory
deleting service feeding the deleted-key purge chain.
"""

import numpy as np
import pytest

from ozone_tpu.om import fso
from ozone_tpu.om.requests import OMError, OMRequest
from ozone_tpu.testing.minicluster import MiniOzoneCluster


@pytest.fixture()
def cluster(tmp_path):
    c = MiniOzoneCluster(tmp_path, num_datanodes=5)
    c.client().create_volume("vol")
    c.om.create_bucket("vol", "fsb", replication="rs-3-2-64k",
                       layout="FILE_SYSTEM_OPTIMIZED")
    yield c
    c.close()


def _bucket(cluster):
    return cluster.client().get_volume("vol").get_bucket("fsb")


def test_nested_write_read(cluster):
    b = _bucket(cluster)
    data = np.frombuffer(np.random.default_rng(0).bytes(300_000), np.uint8)
    b.write_key("a/b/c/file.bin", data)
    out = b.read_key("a/b/c/file.bin")
    assert np.array_equal(out, data)
    # implicit parents exist as real directory entries
    st = cluster.om.get_file_status("vol", "fsb", "a/b")
    assert st["type"] == "DIRECTORY"
    st = cluster.om.get_file_status("vol", "fsb", "a/b/c/file.bin")
    assert st["type"] == "FILE" and st["size"] == data.size


def test_mkdir_and_list_status(cluster):
    om = cluster.om
    om.create_directory("vol", "fsb", "x/y/z")
    _bucket(cluster).write_key("x/y/f1", b"11111")
    _bucket(cluster).write_key("x/f2", b"22222")
    names = [(e["type"], e["path"]) for e in om.list_status("vol", "fsb", "x")]
    assert ("DIRECTORY", "x/y") in names
    assert ("FILE", "x/f2") in names
    assert ("DIRECTORY", "x/y/z") in [
        (e["type"], e["path"]) for e in om.list_status("vol", "fsb", "x/y")
    ]
    # root listing
    assert [e["path"] for e in om.list_status("vol", "fsb", "")] == ["x"]


def test_list_keys_recursive(cluster):
    b = _bucket(cluster)
    for p in ("d1/k1", "d1/d2/k2", "k0"):
        b.write_key(p, b"data")
    names = sorted(k["name"] for k in b.list_keys())
    assert names == ["d1/d2/k2", "d1/k1", "k0"]
    assert [k["name"] for k in b.list_keys(prefix="d1/")] == [
        "d1/d2/k2", "d1/k1"]


def test_dir_rename_moves_subtree(cluster):
    b = _bucket(cluster)
    b.write_key("src/deep/file", b"payload")
    cluster.om.rename_key("vol", "fsb", "src", "dst")
    assert bytes(b.read_key("dst/deep/file")) == b"payload"
    with pytest.raises(OMError):
        cluster.om.get_file_status("vol", "fsb", "src/deep/file")


def test_file_rename(cluster):
    b = _bucket(cluster)
    b.write_key("a/old", b"v")
    b.rename_key("a/old", "a/new")
    assert bytes(b.read_key("a/new")) == b"v"


def test_rename_into_own_subtree_rejected(cluster):
    om = cluster.om
    om.create_directory("vol", "fsb", "p/q")
    with pytest.raises(OMError):
        om.rename_key("vol", "fsb", "p", "p/q/p2")


def test_delete_nonrecursive_requires_empty(cluster):
    b = _bucket(cluster)
    b.write_key("d/f", b"x")
    with pytest.raises(OMError) as ei:
        cluster.om.delete_directory("vol", "fsb", "d")
    assert ei.value.code == fso.DIRECTORY_NOT_EMPTY
    b.delete_key("d/f")
    cluster.om.delete_directory("vol", "fsb", "d")
    with pytest.raises(OMError):
        cluster.om.get_file_status("vol", "fsb", "d")


def test_recursive_delete_purges_subtree(cluster):
    b = _bucket(cluster)
    for p in ("big/a/f1", "big/a/f2", "big/b/c/f3", "big/f4"):
        b.write_key(p, b"some bytes here")
    cluster.om.delete_directory("vol", "fsb", "big", recursive=True)
    # detached immediately: no longer visible
    with pytest.raises(OMError):
        cluster.om.get_file_status("vol", "fsb", "big/a/f1")
    # the background service drains the subtree into deleted_keys
    svc = fso.DirectoryDeletingService(cluster.om)
    svc.run_to_completion()
    assert list(cluster.om.store.iterate("deleted_dirs")) == []
    assert list(cluster.om.store.iterate("files", "/vol/fsb/")) == []
    deleted = list(cluster.om.store.iterate("deleted_keys"))
    assert len(deleted) == 4
    # and the key-deleting service hands their blocks to SCM for purge
    purged = cluster.om.run_key_deleting_service_once()
    assert purged == 4


def test_overwrite_and_type_conflicts(cluster):
    b = _bucket(cluster)
    b.write_key("c/f", b"one")
    b.write_key("c/f", b"two")  # overwrite allowed
    assert bytes(b.read_key("c/f")) == b"two"
    # a directory can't be opened as a file
    with pytest.raises(OMError):
        b.write_key("c", b"clobber")
    # a file can't be a parent directory
    with pytest.raises(OMError):
        b.write_key("c/f/under", b"x")


def test_fso_requests_roundtrip_wire_form(cluster):
    """FSO requests replicate through the HA log like any other request."""
    reqs = [
        fso.CreateDirectory("v", "b", "a/b", new_ids=["1", "2"], created=1.0),
        fso.OpenFile("v", "b", "a/f", "cid", "rs-3-2-64k",
                     new_dir_ids=["3"], created=2.0),
        fso.CommitFile("v", "b", "3", "f", "cid", 10, [], modified=3.0),
        fso.DeleteFile("v", "b", "a/f", ts=4.0),
        fso.DeleteDirectory("v", "b", "a", recursive=True, ts=5.0),
        fso.RenameEntry("v", "b", "a", "z", ts=6.0),
        fso.PurgeDirectories(drops=["k"], file_moves=[], dir_moves=[]),
    ]
    for r in reqs:
        wire = r.to_json()
        back = OMRequest.from_json(wire)
        assert back == r


def test_list_names_follow_ancestor_rename(cluster):
    """Listings derive names from the tree walk, not stored rows — an
    ancestor rename must be reflected everywhere."""
    b = _bucket(cluster)
    b.write_key("top/mid/leaf", b"v")
    cluster.om.rename_key("vol", "fsb", "top", "newtop")
    assert [k["name"] for k in b.list_keys()] == ["newtop/mid/leaf"]
    assert b.list_keys(prefix="newtop/") and not b.list_keys(prefix="top/")
    st = cluster.om.get_file_status("vol", "fsb", "newtop/mid/leaf")
    assert st["name"] == "newtop/mid/leaf"


def test_commit_into_deleted_dir_rejected(cluster):
    """A commit racing a recursive delete must not leak an unreachable
    file: the commit fails and the written blocks go to the purge chain."""
    om = cluster.om
    b = _bucket(cluster)
    h = b.open_key("gone/part")
    h.write(b"block data written before the delete")
    om.create_directory("vol", "fsb", "gone/sub")  # make it non-empty
    om.delete_directory("vol", "fsb", "gone", recursive=True)
    fso.DirectoryDeletingService(om).run_to_completion()
    with pytest.raises(OMError) as ei:
        h.close()
    assert ei.value.code == fso.DIRECTORY_NOT_FOUND
    # no unreachable row; blocks queued for reclaim
    assert list(om.store.iterate("files", "/vol/fsb/")) == []
    assert len(list(om.store.iterate("deleted_keys"))) == 1


def test_fs_ops_validate_bucket(cluster):
    om = cluster.om
    with pytest.raises(OMError):
        om.list_status("vol", "nope", "")
    with pytest.raises(OMError):
        om.get_file_status("vol", "nope", "")
    om.create_bucket("vol", "flat", replication="rs-3-2-64k")
    with pytest.raises(OMError):
        om.list_status("vol", "flat", "")


def test_overwrite_queues_old_blocks(cluster):
    """Rewriting a key must send the old version's blocks to the purge
    chain (both layouts)."""
    b = _bucket(cluster)
    b.write_key("ow/f", b"version one")
    b.write_key("ow/f", b"version two")
    dels = list(cluster.om.store.iterate("deleted_keys"))
    assert len(dels) == 1 and dels[0][1]["block_groups"]
    cluster.om.create_bucket("vol", "obs2", replication="rs-3-2-64k")
    ob = cluster.client().get_volume("vol").get_bucket("obs2")
    ob.write_key("k", b"one")
    ob.write_key("k", b"two")
    assert len(list(cluster.om.store.iterate("deleted_keys"))) == 2


def test_delete_bucket_requires_fso_empty(cluster):
    b = _bucket(cluster)
    b.write_key("d/f", b"x")
    with pytest.raises(OMError) as ei:
        cluster.om.delete_bucket("vol", "fsb")
    assert ei.value.code == "BUCKET_NOT_EMPTY"
    cluster.om.delete_directory("vol", "fsb", "d", recursive=True)
    # still not empty: detached subtree awaits the deleting service
    with pytest.raises(OMError):
        cluster.om.delete_bucket("vol", "fsb")
    fso.DirectoryDeletingService(cluster.om).run_to_completion()
    cluster.om.delete_bucket("vol", "fsb")


def test_obs_bucket_unaffected(cluster):
    """OBS flat layout continues to treat '/' as opaque key bytes."""
    cluster.om.create_bucket("vol", "obs", replication="rs-3-2-64k")
    ob = cluster.client().get_volume("vol").get_bucket("obs")
    ob.write_key("a/b/c", b"flat")
    assert bytes(ob.read_key("a/b/c")) == b"flat"
    with pytest.raises(OMError):
        cluster.om.create_directory("vol", "obs", "a")


def test_walk_files_paged_order_pruning_and_limits(cluster):
    """Paged FSO walk: lexicographic path order (a dir 'd' expands where
    'd/' sorts — before sibling file 'd0'), prefix/cursor subtree
    pruning, and limit stop; pages stitch to the exact full listing."""
    oz = cluster.client()
    oz.create_volume("v")
    oz.om.create_bucket("v", "fso", "rs-3-2-4096",
                        "FILE_SYSTEM_OPTIMIZED")
    b = oz.get_volume("v").get_bucket("fso")
    paths = ["a", "d/x", "d/y/deep", "d0", "m/1", "m/2", "z"]
    for p in paths:
        b.write_key(p, np.zeros(10, np.uint8))
    full = [k["name"] for k in oz.om.list_keys("v", "fso")]
    assert full == ["a", "d/x", "d/y/deep", "d0", "m/1", "m/2", "z"]
    # pages stitch exactly
    got, cursor = [], ""
    while True:
        page = oz.om.list_keys("v", "fso", start_after=cursor, limit=3)
        if not page:
            break
        got += [k["name"] for k in page]
        cursor = page[-1]["name"]
    assert got == full
    # prefix pruning only descends matching subtrees
    assert [k["name"] for k in oz.om.list_keys("v", "fso", prefix="m/")] \
        == ["m/1", "m/2"]
    # cursor inside a subtree resumes mid-directory
    assert [k["name"] for k in
            oz.om.list_keys("v", "fso", start_after="d/x", limit=2)] \
        == ["d/y/deep", "d0"]


def test_list_keys_limit_zero_is_empty_on_both_layouts(cluster):
    oz = cluster.client()
    oz.create_volume("lv")
    oz.om.create_bucket("lv", "obs", "rs-3-2-4096")
    oz.om.create_bucket("lv", "fso", "rs-3-2-4096",
                        "FILE_SYSTEM_OPTIMIZED")
    oz.get_volume("lv").get_bucket("obs").write_key(
        "k", np.zeros(10, np.uint8))
    oz.get_volume("lv").get_bucket("fso").write_key(
        "k", np.zeros(10, np.uint8))
    assert oz.om.list_keys("lv", "obs", limit=0) == []
    assert oz.om.list_keys("lv", "fso", limit=0) == []


def test_fso_set_key_attrs(cluster):
    """SETOWNER/SETPERMISSION/SETTIMES land on FSO file and dir rows
    (the HttpFS verbs' FSO backing) with merge + delete semantics."""
    b = _bucket(cluster)
    b.write_key("p/q/f.txt", np.frombuffer(b"data", np.uint8))
    om = cluster.om
    om.set_key_attrs("vol", "fsb", "p/q/f.txt",
                     {"owner": "alice", "permission": "640"})
    om.set_key_attrs("vol", "fsb", "p/q/f.txt", {"mtime": 1700.0})
    st = om.get_file_status("vol", "fsb", "p/q/f.txt")
    assert st["attrs"] == {"owner": "alice", "permission": "640",
                           "mtime": 1700.0}
    # dirs take attrs too; None deletes
    om.set_key_attrs("vol", "fsb", "p/q", {"permission": "700"})
    om.set_key_attrs("vol", "fsb", "p/q/f.txt", {"owner": None})
    assert om.get_file_status("vol", "fsb", "p/q")["attrs"] == \
        {"permission": "700"}
    assert "owner" not in om.get_file_status(
        "vol", "fsb", "p/q/f.txt")["attrs"]
    with pytest.raises(OMError):
        om.set_key_attrs("vol", "fsb", "p/nope", {"owner": "x"})


def test_fso_attr_preconds_atomic(cluster):
    """The xattr CREATE/REPLACE flag preconditions hold on the FSO
    path too (SetEntryAttrs.preconds, evaluated inside the apply)."""
    oz = cluster.client()
    oz.create_volume("xat")
    cluster.om.create_bucket("xat", "fb", "rs-3-2-4096",
                             layout="FILE_SYSTEM_OPTIMIZED")
    b = oz.get_volume("xat").get_bucket("fb")
    b.write_key("d/f", b"x")
    om = cluster.om
    om.set_key_attrs("xat", "fb", "d/f", {"xattr:user.a": "1"},
                     preconds={"xattr:user.a": False})
    with pytest.raises(OMError) as ei:
        om.set_key_attrs("xat", "fb", "d/f", {"xattr:user.a": "2"},
                         preconds={"xattr:user.a": False})
    assert ei.value.code == "XATTR_EXISTS"
    with pytest.raises(OMError) as ei:
        om.set_key_attrs("xat", "fb", "d/f", {"xattr:user.b": "2"},
                         preconds={"xattr:user.b": True})
    assert ei.value.code == "XATTR_NOT_FOUND"
    om.set_key_attrs("xat", "fb", "d/f", {"xattr:user.a": None},
                     preconds={"xattr:user.a": True})
