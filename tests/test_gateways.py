"""S3 gateway + filesystem adapter tests over a MiniOzoneCluster."""

import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET

import numpy as np
import pytest

from ozone_tpu.gateway.fs import OzoneFileSystem
from ozone_tpu.gateway.s3 import S3Gateway
from ozone_tpu.testing.minicluster import MiniOzoneCluster

EC = "rs-3-2-4096"


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    c = MiniOzoneCluster(
        tmp_path_factory.mktemp("gw"),
        num_datanodes=5,
        block_size=8 * 4096,
        container_size=4 * 1024 * 1024,
        stale_after_s=1000.0,
        dead_after_s=2000.0,
    )
    yield c
    c.close()


@pytest.fixture(scope="module")
def s3(cluster):
    gw = S3Gateway(cluster.client(), replication=EC)
    gw.start()
    yield gw
    gw.stop()


def _req(gw, method, path, data=None, headers=None):
    req = urllib.request.Request(
        f"http://{gw.address}{path}", data=data, method=method,
        headers=headers or {},
    )
    return urllib.request.urlopen(req)


def test_s3_bucket_lifecycle(s3):
    r = _req(s3, "PUT", "/b1")
    assert r.status == 200
    r = _req(s3, "GET", "/")
    tree = ET.fromstring(r.read())
    names = [e.text for e in tree.iter() if e.tag.endswith("Name")]
    assert "b1" in names


def test_s3_object_put_get_range_delete(s3):
    payload = bytes(np.random.default_rng(0).integers(0, 256, 30000,
                                                      dtype=np.uint8))
    _req(s3, "PUT", "/b1")
    r = _req(s3, "PUT", "/b1/dir/obj1", data=payload)
    assert r.status == 200 and r.headers["ETag"]
    r = _req(s3, "GET", "/b1/dir/obj1")
    assert r.read() == payload
    r = _req(s3, "GET", "/b1/dir/obj1", headers={"Range": "bytes=100-199"})
    assert r.status == 206
    assert r.read() == payload[100:200]
    # unsatisfiable range: 416 + star Content-Range, never a 206 whose
    # header would carry hi < lo (S3 / RFC 9110 semantics)
    with pytest.raises(urllib.error.HTTPError) as ei:
        _req(s3, "GET", "/b1/dir/obj1",
             headers={"Range": "bytes=30000-"})
    assert ei.value.code == 416
    assert ei.value.headers["Content-Range"] == "bytes */30000"
    assert b"InvalidRange" in ei.value.read()
    # syntactically inverted spec: header ignored, full 200 body
    # (RFC 9110 §14.1.1 / real-S3 behavior), not 416
    r = _req(s3, "GET", "/b1/dir/obj1",
             headers={"Range": "bytes=200-100"})
    assert r.status == 200
    assert r.read() == payload
    # list
    r = _req(s3, "GET", "/b1?list-type=2&prefix=dir/")
    tree = ET.fromstring(r.read())
    keys = [e.text for e in tree.iter() if e.tag.endswith("Key")]
    assert "dir/obj1" in keys
    r = _req(s3, "DELETE", "/b1/dir/obj1")
    assert r.status == 204
    with pytest.raises(urllib.error.HTTPError) as ei:
        _req(s3, "GET", "/b1/dir/obj1")
    assert ei.value.code == 404


def test_s3_multipart_upload(s3):
    _req(s3, "PUT", "/b1")
    r = _req(s3, "POST", "/b1/big?uploads")
    tree = ET.fromstring(r.read()); upload_id = next(e.text for e in tree.iter() if e.tag.endswith("UploadId"))
    rng = np.random.default_rng(1)
    parts = [bytes(rng.integers(0, 256, 9000, dtype=np.uint8))
             for _ in range(3)]
    for i, p in enumerate(parts, start=1):
        r = _req(s3, "PUT",
                 f"/b1/big?partNumber={i}&uploadId={upload_id}", data=p)
        assert r.status == 200
    # list parts while in flight
    r = _req(s3, "GET", f"/b1/big?uploadId={upload_id}")
    listing = r.read()
    assert listing.count(b"<PartNumber>") == 3
    r = _req(s3, "POST", f"/b1/big?uploadId={upload_id}", data=b"")
    assert r.status == 200
    got = _req(s3, "GET", "/b1/big").read()
    assert got == b"".join(parts)
    # ranged GET across the part boundary rides the positioned path
    whole = b"".join(parts)
    r = _req(s3, "GET", "/b1/big",
             headers={"Range": "bytes=8500-9500"})
    assert r.status == 206
    assert r.read() == whole[8500:9501]
    # upload state cleaned up at the OM
    with pytest.raises(urllib.error.HTTPError) as ei:
        _req(s3, "GET", f"/b1/big?uploadId={upload_id}")
    assert ei.value.code == 404


def test_s3_multipart_abort(s3):
    _req(s3, "PUT", "/b1")
    r = _req(s3, "POST", "/b1/aborted?uploads")
    tree = ET.fromstring(r.read())
    upload_id = next(e.text for e in tree.iter()
                     if e.tag.endswith("UploadId"))
    _req(s3, "PUT", f"/b1/aborted?partNumber=1&uploadId={upload_id}",
         data=b"x" * 5000)
    r = _req(s3, "DELETE", f"/b1/aborted?uploadId={upload_id}")
    assert r.status == 204
    with pytest.raises(urllib.error.HTTPError) as ei:
        _req(s3, "GET", "/b1/aborted")
    assert ei.value.code == 404


def test_lazy_file_handle_windows(cluster):
    """open() is lazy (round 4): one metadata lookup, bytes fetched in
    positioned readahead windows — a seek never materializes the
    skipped range, and sequential reads coalesce into few fetches."""
    from ozone_tpu.gateway.fs import OzoneFile

    oz = cluster.client()
    b = oz.create_volume("lzv").create_bucket("lzb", replication=EC)
    fs = OzoneFileSystem(b)
    rng = np.random.default_rng(7)
    data = bytes(rng.integers(0, 256, 64_000, dtype=np.uint8))
    fs.create("/big", data)

    calls: list[tuple[int, int]] = []
    real = type(b).read_key_info_range

    def spy(self, info, off, ln):
        calls.append((off, ln))
        return real(self, info, off, ln)

    import unittest.mock as mock

    with mock.patch.object(type(b), "read_key_info_range", spy), \
            mock.patch.object(OzoneFile, "_READAHEAD", 16_000):
        with fs.open("/big") as f:
            assert f.read(10) == data[:10]       # fetch window 1
            assert f.read(100) == data[10:110]   # served from buffer
            f.seek(60_000)                       # skip most of the file
            assert f.read() == data[60_000:]     # fetch tail only
    assert calls == [(0, 16_000), (60_000, 4_000)]


def test_s3_errors(s3):
    with pytest.raises(urllib.error.HTTPError) as ei:
        _req(s3, "GET", "/nosuchbucket?list-type=2")
    assert ei.value.code == 404


def test_fs_adapter(cluster):
    oz = cluster.client()
    b = oz.create_volume("fsvol").create_bucket("fsb", replication=EC)
    fs = OzoneFileSystem(b)
    rng = np.random.default_rng(2)
    data = bytes(rng.integers(0, 256, 20000, dtype=np.uint8))
    fs.create("/a/b/file1", data)
    assert fs.exists("/a/b/file1")
    assert fs.get_file_status("/a").is_dir
    with fs.open("/a/b/file1") as f:
        assert f.read(100) == data[:100]
        f.seek(19000)
        assert f.read() == data[19000:]
    ls = fs.list_status("/a")
    assert [s.path for s in ls] == ["a/b"]
    ls = fs.list_status("/a/b")
    assert [(s.path, s.is_dir) for s in ls] == [("a/b/file1", False)]
    fs.rename("/a/b/file1", "/a/b/file2")
    assert not fs.exists("/a/b/file1")
    assert fs.open("/a/b/file2").read() == data
    fs.mkdirs("/empty/dir")
    assert fs.get_file_status("/empty/dir").is_dir
    with pytest.raises(OSError):
        fs.delete("/a", recursive=False)
    fs.delete("/a", recursive=True)
    assert not fs.exists("/a/b/file2")


def test_s3_copy_object(s3):
    """CopyObject via x-amz-copy-source (ObjectEndpoint.put copyHeader),
    including cross-bucket copy."""
    payload = bytes(np.random.default_rng(5).integers(0, 256, 12_000,
                                                      dtype=np.uint8))
    _req(s3, "PUT", "/srcb")
    _req(s3, "PUT", "/dstb")
    _req(s3, "PUT", "/srcb/orig", data=payload)
    r = _req(s3, "PUT", "/dstb/copied",
             headers={"x-amz-copy-source": "/srcb/orig"})
    assert r.status == 200
    body = r.read()
    assert b"CopyObjectResult" in body and b"ETag" in body
    assert _req(s3, "GET", "/dstb/copied").read() == payload
    # source must be untouched
    assert _req(s3, "GET", "/srcb/orig").read() == payload
    # missing source -> 404
    with pytest.raises(urllib.error.HTTPError) as ei:
        _req(s3, "PUT", "/dstb/bad",
             headers={"x-amz-copy-source": "/srcb/nope"})
    assert ei.value.code == 404


def test_s3_upload_part_copy(s3):
    """UploadPartCopy: MPU parts sourced from an existing object with an
    optional x-amz-copy-source-range."""
    src = bytes(np.random.default_rng(6).integers(0, 256, 20_000,
                                                  dtype=np.uint8))
    _req(s3, "PUT", "/cpb")
    _req(s3, "PUT", "/cpb/src", data=src)
    r = _req(s3, "POST", "/cpb/assembled?uploads")
    tree = ET.fromstring(r.read())
    upload_id = next(e.text for e in tree.iter()
                     if e.tag.endswith("UploadId"))
    # part 1: first half of src via range copy; part 2: rest, plain upload
    r = _req(s3, "PUT",
             f"/cpb/assembled?partNumber=1&uploadId={upload_id}",
             headers={"x-amz-copy-source": "/cpb/src",
                      "x-amz-copy-source-range": "bytes=0-9999"})
    assert r.status == 200 and b"CopyPartResult" in r.read()
    r = _req(s3, "PUT",
             f"/cpb/assembled?partNumber=2&uploadId={upload_id}",
             data=src[10_000:])
    assert r.status == 200
    r = _req(s3, "POST", f"/cpb/assembled?uploadId={upload_id}", data=b"")
    assert r.status == 200
    assert _req(s3, "GET", "/cpb/assembled").read() == src


def test_s3_upload_part_copy_rejects_bad_ranges(s3):
    src = bytes(np.random.default_rng(7).integers(0, 256, 1_000,
                                                  dtype=np.uint8))
    _req(s3, "PUT", "/rgb")
    _req(s3, "PUT", "/rgb/src", data=src)
    r = _req(s3, "POST", "/rgb/part?uploads")
    tree = ET.fromstring(r.read())
    upload_id = next(e.text for e in tree.iter()
                     if e.tag.endswith("UploadId"))
    for rng, code in [("bytes=1000-1999", 416),  # past the end
                      ("bytes=500-100", 416),    # inverted
                      ("bytes=-500", 400),       # suffix form
                      ("bytes=0-", 400)]:        # open-ended
        with pytest.raises(urllib.error.HTTPError) as ei:
            _req(s3, "PUT", f"/rgb/part?partNumber=1&uploadId={upload_id}",
                 headers={"x-amz-copy-source": "/rgb/src",
                          "x-amz-copy-source-range": rng})
        assert ei.value.code == code, rng


def test_s3_list_delimiter_and_pagination(s3):
    _req(s3, "PUT", "/lb")
    for k in ["a/1", "a/2", "b/1", "top1", "top2"]:
        _req(s3, "PUT", f"/lb/{k}", data=b"x")
    # delimiter groups folders into CommonPrefixes
    r = _req(s3, "GET", "/lb?list-type=2&delimiter=/")
    tree = ET.fromstring(r.read())
    cps = [e.text for e in tree.iter() if e.tag.endswith("}Prefix")
           and e.text and e.text.endswith("/")]
    keys = [e.text for e in tree.iter() if e.tag.endswith("}Key")]
    assert sorted(cps) == ["a/", "b/"]
    assert sorted(keys) == ["top1", "top2"]
    # prefix + delimiter: inside a folder
    r = _req(s3, "GET", "/lb?list-type=2&prefix=a/&delimiter=/")
    tree = ET.fromstring(r.read())
    keys = [e.text for e in tree.iter() if e.tag.endswith("}Key")]
    assert sorted(keys) == ["a/1", "a/2"]
    # pagination: 2 per page across 5 entities (a/, b/, top1, top2 with
    # delimiter -> 4 entities; without delimiter 5 keys)
    seen = []
    token = ""
    for _ in range(5):
        qs = "/lb?list-type=2&max-keys=2" + (
            f"&continuation-token={token}" if token else "")
        tree = ET.fromstring(_req(s3, "GET", qs).read())
        seen += [e.text for e in tree.iter() if e.tag.endswith("}Key")]
        if (next((e.text for e in tree.iter()
                  if e.tag.endswith("IsTruncated")), "false") != "true"):
            break
        token = next(e.text for e in tree.iter()
                     if e.tag.endswith("NextContinuationToken"))
    assert seen == ["a/1", "a/2", "b/1", "top1", "top2"]


def test_s3_multi_delete(s3):
    _req(s3, "PUT", "/mdb")
    for k in ["d1", "d2", "keep"]:
        _req(s3, "PUT", f"/mdb/{k}", data=b"x")
    body = (b'<Delete xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
            b"<Object><Key>d1</Key></Object>"
            b"<Object><Key>d2</Key></Object>"
            b"<Object><Key>ghost</Key></Object></Delete>")
    r = _req(s3, "POST", "/mdb?delete", data=body)
    out = r.read()
    assert out.count(b"<Deleted>") == 3  # missing key counts as deleted
    tree = ET.fromstring(_req(s3, "GET", "/mdb?list-type=2").read())
    keys = [e.text for e in tree.iter() if e.tag.endswith("}Key")]
    assert keys == ["keep"]


def test_s3_list_edge_cases_and_quota_mapping(s3):
    _req(s3, "PUT", "/eb")
    _req(s3, "PUT", "/eb/k1", data=b"x")
    # MaxKeys=0: empty, NOT truncated (no dangling pagination)
    tree = ET.fromstring(_req(s3, "GET", "/eb?list-type=2&max-keys=0").read())
    assert next(e.text for e in tree.iter()
                if e.tag.endswith("IsTruncated")) == "false"
    assert not [e for e in tree.iter() if e.tag.endswith("}Key")]
    # bad max-keys -> 400
    with pytest.raises(urllib.error.HTTPError) as ei:
        _req(s3, "GET", "/eb?list-type=2&max-keys=abc")
    assert ei.value.code == 400
    # quota exceeded surfaces as 403 QuotaExceeded, not 500
    s3.client.om.set_quota(s3._vol, "eb", quota_bytes=2)
    with pytest.raises(urllib.error.HTTPError) as ei:
        _req(s3, "PUT", "/eb/too-big", data=b"xxxx")
    assert ei.value.code == 403
    assert b"QuotaExceeded" in ei.value.read()
    s3.client.om.set_quota(s3._vol, "eb", quota_bytes=-1)


def test_s3_user_metadata_roundtrip_and_copy_directives(s3):
    _req(s3, "PUT", "/mb")
    payload = b"hello-meta"
    r = _req(s3, "PUT", "/mb/obj", data=payload,
             headers={"x-amz-meta-owner": "alice",
                      "x-amz-meta-env": "prod"})
    assert r.status == 200
    r = _req(s3, "GET", "/mb/obj")
    assert r.read() == payload
    assert r.headers["x-amz-meta-owner"] == "alice"
    assert r.headers["x-amz-meta-env"] == "prod"
    r = _req(s3, "HEAD", "/mb/obj")
    assert r.headers["x-amz-meta-owner"] == "alice"
    # COPY directive (default): metadata travels with the copy
    r = _req(s3, "PUT", "/mb/copy1",
             headers={"x-amz-copy-source": "/mb/obj"})
    assert r.status == 200
    assert _req(s3, "HEAD", "/mb/copy1").headers["x-amz-meta-owner"] \
        == "alice"
    # REPLACE directive: request headers win
    r = _req(s3, "PUT", "/mb/copy2",
             headers={"x-amz-copy-source": "/mb/obj",
                      "x-amz-metadata-directive": "REPLACE",
                      "x-amz-meta-owner": "bob"})
    assert r.status == 200
    hd = _req(s3, "HEAD", "/mb/copy2").headers
    assert hd["x-amz-meta-owner"] == "bob"
    assert hd.get("x-amz-meta-env") is None


def test_s3_mpu_metadata_and_suffix_range(s3):
    _req(s3, "PUT", "/mrb")
    # MPU carries x-amz-meta-* from initiate through complete
    r = _req(s3, "POST", "/mrb/assembled?uploads",
             headers={"x-amz-meta-team": "storage"})
    tree = ET.fromstring(r.read())
    upload_id = next(e.text for e in tree.iter()
                     if e.tag.endswith("UploadId"))
    payload = bytes(np.random.default_rng(8).integers(0, 256, 9_000,
                                                      dtype=np.uint8))
    _req(s3, "PUT", f"/mrb/assembled?partNumber=1&uploadId={upload_id}",
         data=payload)
    _req(s3, "POST", f"/mrb/assembled?uploadId={upload_id}", data=b"")
    assert _req(s3, "HEAD", "/mrb/assembled").headers["x-amz-meta-team"] \
        == "storage"
    # suffix range returns the LAST n bytes
    r = _req(s3, "GET", "/mrb/assembled",
             headers={"Range": "bytes=-100"})
    assert r.status == 206
    assert r.read() == payload[-100:]
    assert r.headers["Content-Range"] == f"bytes 8900-8999/9000"


def test_s3_sdk_handshake_endpoints(s3):
    _req(s3, "PUT", "/hsb")
    r = _req(s3, "GET", "/hsb?location")
    assert b"LocationConstraint" in r.read()
    r = _req(s3, "GET", "/hsb?versioning")
    body = r.read()
    assert b"VersioningConfiguration" in body and b"Enabled" not in body
    with pytest.raises(urllib.error.HTTPError) as ei:
        _req(s3, "GET", "/nope-bucket?location")
    assert ei.value.code == 404


def test_s3_put_versioning_rejected_loudly(s3):
    _req(s3, "PUT", "/vvb")
    with pytest.raises(urllib.error.HTTPError) as ei:
        _req(s3, "PUT", "/vvb?versioning",
             data=b"<VersioningConfiguration/>")
    assert ei.value.code == 501


def test_om_list_pagination_pushdown():
    """om.list_keys honors start_after/limit on both layouts."""
    import tempfile

    from ozone_tpu.testing.minicluster import MiniOzoneCluster

    with tempfile.TemporaryDirectory() as td:
        c = MiniOzoneCluster(
            td, num_datanodes=5, block_size=4 * 4096,
            stale_after_s=1000.0, dead_after_s=2000.0)
        try:
            oz = c.client()
            b = oz.create_volume("pv").create_bucket(
                "pb", replication="rs-3-2-4096")
            for i in range(6):
                b.write_key(f"k{i}", np.zeros(10, np.uint8))
            page = oz.om.list_keys("pv", "pb", limit=2)
            assert [k["name"] for k in page] == ["k0", "k1"]
            page = oz.om.list_keys("pv", "pb", start_after="k1", limit=3)
            assert [k["name"] for k in page] == ["k2", "k3", "k4"]
            assert oz.om.list_keys("pv", "pb", start_after="k5") == []
        finally:
            c.close()


def test_s3_delimiter_rollup_pagination_stays_truncated(s3):
    """Many keys rolling into ONE CommonPrefix inside a small page must
    still report IsTruncated with a token — the over-fetch window being
    exhausted by roll-ups is not the end of the listing."""
    _req(s3, "PUT", "/rob")
    for i in range(8):
        _req(s3, "PUT", f"/rob/dir/{i:02d}", data=b"x")
    _req(s3, "PUT", "/rob/zz-tail", data=b"x")
    seen_keys, seen_cps = [], []
    token = ""
    for _ in range(12):
        qs = "/rob?list-type=2&delimiter=/&max-keys=2" + (
            f"&continuation-token={token}" if token else "")
        tree = ET.fromstring(_req(s3, "GET", qs).read())
        seen_keys += [e.text for e in tree.iter()
                      if e.tag.endswith("}Key")]
        seen_cps += [e.text for p in tree.iter()
                     if p.tag.endswith("CommonPrefixes")
                     for e in p if e.tag.endswith("Prefix")]
        if next((e.text for e in tree.iter()
                 if e.tag.endswith("IsTruncated")), "false") != "true":
            break
        token = next(e.text for e in tree.iter()
                     if e.tag.endswith("NextContinuationToken"))
    assert "zz-tail" in seen_keys          # the tail key is reached
    assert set(seen_cps) == {"dir/"}       # the rolled-up folder appears


def test_s3_raw_start_after_inside_group_emits_common_prefix(s3):
    """AWS semantics: start-after pointing INSIDE a delimiter group still
    yields that group's CommonPrefix (only server continuation tokens
    mark groups as already served)."""
    _req(s3, "PUT", "/sab")
    for i in range(4):
        _req(s3, "PUT", f"/sab/dir/{i:02d}", data=b"x")
    r = _req(s3, "GET",
             "/sab?list-type=2&delimiter=/&start-after=dir/01")
    tree = ET.fromstring(r.read())
    cps = [e.text for p in tree.iter()
           if p.tag.endswith("CommonPrefixes")
           for e in p if e.tag.endswith("Prefix")]
    assert cps == ["dir/"]


def test_s3_object_tagging(s3):
    """?tagging sub-resource + x-amz-tagging header (S3
    Put/Get/DeleteObjectTagging; reference ObjectEndpoint tagging)."""
    import urllib.error
    import urllib.request

    base = f"http://{s3.address}"
    urllib.request.urlopen(urllib.request.Request(
        f"{base}/tagbkt", method="PUT"))
    # tags on the PUT itself via header
    urllib.request.urlopen(urllib.request.Request(
        f"{base}/tagbkt/obj", data=b"tagged-bytes", method="PUT",
        headers={"x-amz-tagging": "team=storage&tier=hot"}))
    got = urllib.request.urlopen(f"{base}/tagbkt/obj?tagging").read()
    assert b"<Key>team</Key>" in got and b"<Value>storage</Value>" in got
    assert b"<Key>tier</Key>" in got
    # replace via PUT ?tagging XML
    xml = (b"<Tagging><TagSet><Tag><Key>owner</Key>"
           b"<Value>alice</Value></Tag></TagSet></Tagging>")
    urllib.request.urlopen(urllib.request.Request(
        f"{base}/tagbkt/obj?tagging", data=xml, method="PUT"))
    got = urllib.request.urlopen(f"{base}/tagbkt/obj?tagging").read()
    assert b"owner" in got and b"team" not in got
    # limits: >10 tags refused
    many = "&".join(f"k{i}=v" for i in range(11))
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(urllib.request.Request(
            f"{base}/tagbkt/obj2", data=b"x", method="PUT",
            headers={"x-amz-tagging": many}))
    assert ei.value.code == 400
    assert b"InvalidTag" in ei.value.read()
    # delete tagging
    r = urllib.request.urlopen(urllib.request.Request(
        f"{base}/tagbkt/obj?tagging", method="DELETE"))
    assert r.status == 204
    got = urllib.request.urlopen(f"{base}/tagbkt/obj?tagging").read()
    assert b"<Tag>" not in got


def test_s3_copy_carries_tags_and_bucket_tagging_answers(s3):
    import urllib.error
    import urllib.request

    base = f"http://{s3.address}"
    urllib.request.urlopen(urllib.request.Request(
        f"{base}/tagcp", method="PUT"))
    urllib.request.urlopen(urllib.request.Request(
        f"{base}/tagcp/src", data=b"copy-me", method="PUT",
        headers={"x-amz-tagging": "a=1"}))
    # COPY directive (default): destination inherits the source tags
    urllib.request.urlopen(urllib.request.Request(
        f"{base}/tagcp/dst", method="PUT",
        headers={"x-amz-copy-source": "/tagcp/src"}))
    got = urllib.request.urlopen(f"{base}/tagcp/dst?tagging").read()
    assert b"<Key>a</Key>" in got
    # REPLACE directive: the request's header wins
    urllib.request.urlopen(urllib.request.Request(
        f"{base}/tagcp/dst2", method="PUT",
        headers={"x-amz-copy-source": "/tagcp/src",
                 "x-amz-tagging-directive": "REPLACE",
                 "x-amz-tagging": "b=2"}))
    got = urllib.request.urlopen(f"{base}/tagcp/dst2?tagging").read()
    assert b"<Key>b</Key>" in got and b"<Key>a</Key>" not in got
    # bucket-level GET ?tagging answers NoSuchTagSet, not a listing
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(f"{base}/tagcp?tagging")
    assert ei.value.code == 404
    assert b"NoSuchTagSet" in ei.value.read()


def test_s3_object_acl_and_cli_paging(s3, cluster):
    import urllib.error
    import urllib.request

    base = f"http://{s3.address}"
    urllib.request.urlopen(urllib.request.Request(
        f"{base}/aclb", method="PUT"))
    urllib.request.urlopen(urllib.request.Request(
        f"{base}/aclb/o", data=b"acl-bytes", method="PUT"))
    got = urllib.request.urlopen(f"{base}/aclb/o?acl").read()
    assert b"AccessControlPolicy" in got and b"FULL_CONTROL" in got
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(urllib.request.Request(
            f"{base}/aclb/o?acl", data=b"<x/>", method="PUT"))
    assert ei.value.code == 501
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(f"{base}/aclb/nope?acl")
    assert ei.value.code == 404
    # a public bucket's object renders the AWS AllUsers group grant
    urllib.request.urlopen(urllib.request.Request(
        f"{base}/aclb?acl", data=b"", method="PUT",
        headers={"x-amz-acl": "public-read"}))
    got = urllib.request.urlopen(f"{base}/aclb/o?acl").read()
    assert b"AllUsers" in got and b"<Permission>READ" in got
    # real paged listing: limit + start_after + prefix (the OM surface
    # the CLI's --prefix/--start-after/--limit flags call)
    oz = cluster.client()
    b = oz.create_volume("pgv").create_bucket("pgb", replication=EC)
    for i in range(5):
        b.write_key(f"p/k{i}", np.zeros(64, np.uint8))
    om = oz.om
    page = om.list_keys("pgv", "pgb", "", "", 2)
    assert [k["name"] for k in page] == ["p/k0", "p/k1"]
    page2 = om.list_keys("pgv", "pgb", "", "p/k1", 2)
    assert [k["name"] for k in page2] == ["p/k2", "p/k3"]
    assert om.list_keys("pgv", "pgb", "p/k4", "", None)[0]["name"] \
        == "p/k4"


def test_s3_list_objects_v1_marker_paging(s3):
    """ListObjects V1 (no list-type=2): marker resumption, NextMarker
    on truncation, delimiter rollup — the protocol older SDKs speak."""
    _req(s3, "PUT", "/v1b")
    for name in ("a1", "a2", "dir/x", "dir/y", "z9"):
        _req(s3, "PUT", f"/v1b/{name}", data=b"v")
    seen, marker, pages = [], "", 0
    while True:
        url = "/v1b?max-keys=2&delimiter=/"
        if marker:
            url += f"&marker={marker}"
        tree = ET.fromstring(_req(s3, "GET", url).read())
        keys = [e.text for e in tree.iter() if e.tag.endswith("}Key")]
        cps = [e.text for p in tree.iter()
               if p.tag.endswith("CommonPrefixes")
               for e in p if e.tag.endswith("Prefix")]
        seen += keys + cps
        pages += 1
        trunc = next(e.text for e in tree.iter()
                     if e.tag.endswith("IsTruncated"))
        assert any(e.tag.endswith("}Marker") for e in tree.iter())
        if trunc != "true":
            assert not any(e.tag.endswith("NextMarker")
                           for e in tree.iter())
            break
        marker = next(e.text for e in tree.iter()
                      if e.tag.endswith("NextMarker"))
    # Contents render before CommonPrefixes within a page (the real
    # S3 XML shape); compare the merged entity set
    assert sorted(seen) == ["a1", "a2", "dir/", "z9"] and pages == 2
    # V2 responses still carry KeyCount/ContinuationToken fields
    tree = ET.fromstring(
        _req(s3, "GET", "/v1b?list-type=2&max-keys=1").read())
    assert any(e.tag.endswith("KeyCount") for e in tree.iter())
    assert any(e.tag.endswith("NextContinuationToken")
               for e in tree.iter())


def test_s3_v1_marker_inside_group_emits_prefix(s3):
    """A client-arbitrary V1 marker INSIDE a delimiter group must still
    emit the group's CommonPrefix (AWS start-after-like semantics); a
    marker EQUAL to the prefix consumes it."""
    _req(s3, "PUT", "/v1m")
    for name in ("dir/x", "dir/y", "z9"):
        _req(s3, "PUT", f"/v1m/{name}", data=b"v")
    tree = ET.fromstring(
        _req(s3, "GET", "/v1m?delimiter=/&marker=dir/x").read())
    cps = [e.text for p in tree.iter()
           if p.tag.endswith("CommonPrefixes")
           for e in p if e.tag.endswith("Prefix")]
    assert cps == ["dir/"]
    tree = ET.fromstring(
        _req(s3, "GET", "/v1m?delimiter=/&marker=dir/").read())
    cps = [e.text for p in tree.iter()
           if p.tag.endswith("CommonPrefixes")
           for e in p if e.tag.endswith("Prefix")]
    assert cps == []
    keys = [e.text for e in tree.iter() if e.tag.endswith("}Key")]
    assert keys == ["z9"]


def _initiate(s3, bucket, key):
    tree = ET.fromstring(
        _req(s3, "POST", f"/{bucket}/{key}?uploads").read())
    return next(e.text for e in tree.iter()
                if e.tag.endswith("UploadId"))


def _uploads_of(tree):
    return [
        (u.findtext("{*}Key"), u.findtext("{*}UploadId"))
        for u in tree.iter() if u.tag.endswith("}Upload")
    ]


def _prefixes_of(tree):
    return [e.text for p in tree.iter()
            if p.tag.endswith("CommonPrefixes")
            for e in p if e.tag.endswith("Prefix")]


def test_s3_list_multipart_uploads(s3):
    """GET ?uploads (ListMultipartUploads, BucketEndpoint.java:325):
    (key, uploadId) ordering, prefix filter, delimiter grouping,
    Initiated timestamps, and abort removing the entry."""
    _req(s3, "PUT", "/lmu")
    ids = {}
    for key in ("a/one", "a/two", "b/three", "plain"):
        ids[key] = _initiate(s3, "lmu", key)
    id2 = _initiate(s3, "lmu", "a/one")  # second upload, same key
    tree = ET.fromstring(_req(s3, "GET", "/lmu?uploads").read())
    assert tree.tag.endswith("ListMultipartUploadsResult")
    got = _uploads_of(tree)
    assert got == sorted(
        [("a/one", ids["a/one"]), ("a/one", id2), ("a/two", ids["a/two"]),
         ("b/three", ids["b/three"]), ("plain", ids["plain"])])
    assert tree.findtext("{*}IsTruncated") == "false"
    inits = [u.findtext("{*}Initiated") for u in tree.iter()
             if u.tag.endswith("}Upload")]
    assert all(i and i.endswith("Z") for i in inits)
    # prefix filter — and a key named exactly "a" must NOT match
    # prefix "a/" through the store-key /key/uploadId boundary
    ids["a"] = _initiate(s3, "lmu", "a")
    tree = ET.fromstring(_req(s3, "GET", "/lmu?uploads&prefix=a/").read())
    assert {k for k, _ in _uploads_of(tree)} == {"a/one", "a/two"}
    # delimiter grouping ("a" has no delimiter -> plain Upload entry)
    tree = ET.fromstring(
        _req(s3, "GET", "/lmu?uploads&delimiter=/").read())
    assert _prefixes_of(tree) == ["a/", "b/"]
    assert _uploads_of(tree) == [("a", ids["a"]),
                                 ("plain", ids["plain"])]
    # abort removes the entry
    _req(s3, "DELETE", f"/lmu/plain?uploadId={ids['plain']}")
    tree = ET.fromstring(_req(s3, "GET", "/lmu?uploads").read())
    assert ("plain", ids["plain"]) not in _uploads_of(tree)


def test_s3_list_multipart_uploads_paging(s3):
    """max-uploads truncation + NextKeyMarker/NextUploadIdMarker resume
    walks the full set exactly once, including same-key upload pairs."""
    _req(s3, "PUT", "/lmup")
    expect = set()
    for key in ("k1", "k1", "k2", "k3", "k4"):  # k1 twice
        expect.add((key, _initiate(s3, "lmup", key)))
    got = []
    key_marker, id_marker = "", ""
    for _ in range(10):
        tree = ET.fromstring(_req(
            s3, "GET", "/lmup?uploads&max-uploads=2"
            f"&key-marker={key_marker}&upload-id-marker={id_marker}"
        ).read())
        page = _uploads_of(tree)
        assert len(page) <= 2
        got.extend(page)
        if tree.findtext("{*}IsTruncated") != "true":
            break
        key_marker = tree.findtext("{*}NextKeyMarker")
        id_marker = tree.findtext("{*}NextUploadIdMarker") or ""
    assert sorted(got) == sorted(expect)
    assert len(got) == len(expect)
    # bad / out-of-range max-uploads -> InvalidArgument, never an
    # unpageable truncated response
    for bad in ("zz", "0", "-3", "1001"):
        with pytest.raises(urllib.error.HTTPError) as ei:
            _req(s3, "GET", f"/lmup?uploads&max-uploads={bad}")
        assert ei.value.code == 400


def test_s3_list_encoding_type_url(s3):
    """?encoding-type=url: response keys/prefixes are URL-encoded and
    the EncodingType element tells SDKs to decode (boto3 sends this by
    default; keys with XML-hostile bytes survive)."""
    _req(s3, "PUT", "/encb")
    _req(s3, "PUT", "/encb/plain.txt", data=b"a")
    _req(s3, "PUT", urllib.parse.quote("/encb/dir with space/k+1"),
         data=b"b")
    tree = ET.fromstring(_req(
        s3, "GET", "/encb?list-type=2&encoding-type=url").read())
    assert tree.findtext("{*}EncodingType") == "url"
    keys = [e.text for e in tree.iter() if e.tag.endswith("}Key")]
    assert "plain.txt" in keys
    assert "dir%20with%20space/k%2B1" in keys
    # delimiter grouping: the CommonPrefix is encoded too
    tree = ET.fromstring(_req(
        s3, "GET",
        "/encb?list-type=2&encoding-type=url&delimiter=/").read())
    cps = [e.text for p in tree.iter()
           if p.tag.endswith("CommonPrefixes")
           for e in p if e.tag.endswith("Prefix")]
    assert cps == ["dir%20with%20space/"]
    # without the param nothing is encoded (older SDKs)
    tree = ET.fromstring(_req(s3, "GET", "/encb?list-type=2").read())
    keys = [e.text for e in tree.iter() if e.tag.endswith("}Key")]
    assert "dir with space/k+1" in keys
    # V2 continuation tokens are OPAQUE and resume correctly for keys
    # with any bytes (no raw key text in the token element)
    tree = ET.fromstring(_req(
        s3, "GET", "/encb?list-type=2&max-keys=1").read())
    tok = tree.findtext("{*}NextContinuationToken")
    assert tok.startswith("t2:")
    tree = ET.fromstring(_req(
        s3, "GET",
        f"/encb?list-type=2&continuation-token={tok}").read())
    keys2 = [e.text for e in tree.iter() if e.tag.endswith("}Key")]
    assert keys2 and keys2 != keys[:1]
    # an in-flight LEGACY t1 token (pre-CRC format) still resumes at the
    # same key — the format bump to t2 exists so upgrades don't break
    # paginated listings mid-flight
    import base64

    from ozone_tpu.gateway.s3 import _parse_token

    resumed = _parse_token(tok)
    legacy = "t1:" + base64.urlsafe_b64encode(resumed.encode()).decode()
    assert _parse_token(legacy) == resumed
    # ...and the CRC-tagged t1 generation (the shape the immediately
    # previous release emitted) decodes too
    import zlib

    tagged = "t1:" + base64.urlsafe_b64encode(
        zlib.crc32(resumed.encode()).to_bytes(4, "big")
        + resumed.encode()).decode()
    assert _parse_token(tagged) == resumed
    # ListMultipartUploads honors encoding-type too
    _req(s3, "POST",
         "/encb/" + urllib.parse.quote("up space") + "?uploads")
    tree = ET.fromstring(
        _req(s3, "GET", "/encb?uploads&encoding-type=url").read())
    assert tree.findtext("{*}EncodingType") == "url"
    ks = [u.findtext("{*}Key") for u in tree.iter()
          if u.tag.endswith("}Upload")]
    assert "up%20space" in ks
