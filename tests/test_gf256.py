"""GF(2^8) field math tests."""

import numpy as np
import pytest

from ozone_tpu.codec import gf256


def test_field_axioms_on_samples():
    rng = np.random.default_rng(0)
    a = rng.integers(0, 256, 1000, dtype=np.uint8)
    b = rng.integers(0, 256, 1000, dtype=np.uint8)
    c = rng.integers(0, 256, 1000, dtype=np.uint8)
    # commutativity, associativity, distributivity over XOR (field addition)
    assert np.array_equal(gf256.gf_mul(a, b), gf256.gf_mul(b, a))
    assert np.array_equal(
        gf256.gf_mul(gf256.gf_mul(a, b), c), gf256.gf_mul(a, gf256.gf_mul(b, c))
    )
    assert np.array_equal(
        gf256.gf_mul(a, b ^ c), gf256.gf_mul(a, b) ^ gf256.gf_mul(a, c)
    )


def test_identity_and_zero():
    a = np.arange(256, dtype=np.uint8)
    assert np.array_equal(gf256.gf_mul(a, np.uint8(1)), a)
    assert np.array_equal(gf256.gf_mul(a, np.uint8(0)), np.zeros(256, np.uint8))


def test_inverse():
    a = np.arange(1, 256, dtype=np.uint8)
    inv = gf256.gf_inv(a)
    assert np.array_equal(gf256.gf_mul(a, inv), np.ones(255, np.uint8))
    assert gf256.gf_inv(np.uint8(0)) == 0


def test_known_values_match_reference_tables():
    # Spot values from the reference's generated antilog table
    # (GF256.java:31-84): EXP[8] = 0x1d (poly reduction), EXP[254] = 0x8e.
    assert gf256.EXP[0] == 1
    assert gf256.EXP[1] == 2
    assert gf256.EXP[8] == 0x1D
    assert gf256.EXP[254] == 0x8E
    assert gf256.EXP[255] == 1
    # mul via poly: 0x80 * 2 = 0x100 -> ^0x11d = 0x1d
    assert gf256.gf_mul(np.uint8(0x80), np.uint8(2)) == 0x1D


def test_matrix_inverse_roundtrip():
    rng = np.random.default_rng(1)
    for n in (1, 2, 5, 10):
        # random invertible matrix: retry until non-singular
        for _ in range(20):
            m = rng.integers(0, 256, (n, n), dtype=np.uint8)
            try:
                inv = gf256.gf_invert_matrix(m)
            except ValueError:
                continue
            prod = gf256.gf_matmul(m, inv)
            assert np.array_equal(prod, np.eye(n, dtype=np.uint8))
            break
        else:
            pytest.fail("could not find invertible matrix")


def test_singular_matrix_raises():
    m = np.array([[1, 2], [1, 2]], dtype=np.uint8)
    with pytest.raises(ValueError):
        gf256.gf_invert_matrix(m)
