"""Chaos over the metadata HA ring: replica restarts under write load.

The reference's mini-chaos-tests (fault-injection-test OzoneChaosCluster
+ FailureManager) randomly restart OMs while load generators assert
invariants; this is the same contract against the multi-process HA ring:
every ACKED write must be readable afterwards, no matter which replica
was down when, and the ring must converge back to one leader.
"""

import random
import threading
import time

import numpy as np
import pytest

from ozone_tpu.storage.ids import StorageError
from tests.test_meta_ha import (
    _await_leader,
    _client,
    _free_ports,
    _make_meta,
)
from ozone_tpu.net.daemons import DatanodeDaemon

N_META = 3


@pytest.mark.parametrize("seed", [11])
def test_meta_ha_chaos_replica_restarts(tmp_path, seed):
    rng = random.Random(seed)
    ports = _free_ports(N_META)
    peers = {f"m{i}": f"127.0.0.1:{ports[i]}" for i in range(N_META)}
    metas = {}
    dns = []
    stop = threading.Event()
    acked: list[str] = []
    write_errors: list[Exception] = []

    try:
        for i in range(N_META):
            d = _make_meta(tmp_path, i, peers)
            d.start()
            metas[f"m{i}"] = d
        _await_leader(metas)
        scm_addrs = ",".join(peers.values())
        for i in range(5):
            d = DatanodeDaemon(tmp_path / f"dn{i}", f"dn{i}", scm_addrs,
                               heartbeat_interval_s=0.15)
            d.start()
            dns.append(d)

        oz = _client(peers)
        oz.create_volume("v")
        bucket = oz.get_volume("v").create_bucket(
            "b", replication="rs-3-2-4096")
        payload = np.random.default_rng(seed).integers(
            0, 256, 60_000, dtype=np.uint8).tobytes()

        def writer():
            n = 0
            while not stop.is_set():
                key = f"k{n}"
                try:
                    bucket.write_key(key, payload)
                    acked.append(key)
                except StorageError:
                    pass  # un-acked: no durability claim, keep going
                except Exception as e:  # noqa: BLE001 - fail the test
                    write_errors.append(e)
                    return
                n += 1

        wt = threading.Thread(target=writer, daemon=True)
        wt.start()

        # chaos: three rounds of stop-a-random-replica / restart it
        for _ in range(3):
            time.sleep(1.5)
            victim = rng.choice(sorted(metas))
            idx = int(victim[1:])
            metas.pop(victim).stop()
            time.sleep(1.5)
            revived = _make_meta(tmp_path, idx, peers)
            revived.start()
            metas[victim] = revived

        time.sleep(1.0)
        stop.set()
        wt.join(timeout=30)
        assert not wt.is_alive(), "writer wedged"
        assert not write_errors, write_errors
        assert len(acked) >= 3, f"writer made no progress: {acked}"

        # invariant: every acked key reads back intact
        _await_leader(metas, timeout=20)
        for key in acked:
            out = bucket.read_key(key)
            assert out.tobytes() == payload, key
    finally:
        stop.set()
        for d in dns:
            d.stop()
        for d in metas.values():
            d.stop()


@pytest.mark.parametrize("seed", [23])
def test_dn_raft_chaos_pipeline_member_restarts(tmp_path, seed):
    """RATIS pipeline chaos: a member datanode is killed and revived
    while raft-ordered writes flow; every acked key reads back."""
    from ozone_tpu.net.daemons import ScmOmDaemon
    from ozone_tpu.client.dn_client import DatanodeClientFactory
    from ozone_tpu.client.ozone_client import OzoneClient
    from ozone_tpu.net.om_service import GrpcOmClient
    from ozone_tpu.net.ratis_service import RatisClientFactory
    from ozone_tpu.net.scm_service import GrpcScmClient

    rng = random.Random(seed)
    meta = ScmOmDaemon(tmp_path / "om.db", block_size=256 * 1024,
                       stale_after_s=1000.0, dead_after_s=2000.0,
                       background_interval_s=0.2)
    meta.start()
    dns = {}
    for i in range(3):
        d = DatanodeDaemon(tmp_path / f"dn{i}", f"dn{i}", meta.address,
                           heartbeat_interval_s=0.1)
        d.start()
        dns[f"dn{i}"] = d
    stop = threading.Event()
    acked: list[str] = []
    write_errors: list[Exception] = []
    try:
        clients = DatanodeClientFactory()
        om = GrpcOmClient(meta.address, clients=clients)
        for dn_id, addr in GrpcScmClient(
                meta.address).node_addresses().items():
            clients.register_remote(dn_id, addr)
        ratis = RatisClientFactory(address_source=clients.remote_address)
        oz = OzoneClient(om, clients, ratis_clients=ratis)
        oz.create_volume("v")
        bucket = oz.get_volume("v").create_bucket(
            "b", replication="RATIS/THREE")
        payload = np.random.default_rng(seed).integers(
            0, 256, 50_000, dtype=np.uint8).tobytes()

        def writer():
            n = 0
            while not stop.is_set():
                key = f"k{n}"
                try:
                    bucket.write_key(key, payload)
                    acked.append(key)
                except StorageError:
                    pass
                except Exception as e:  # noqa: BLE001
                    write_errors.append(e)
                    return
                n += 1

        wt = threading.Thread(target=writer, daemon=True)
        wt.start()
        for _ in range(2):
            time.sleep(1.5)
            victim = rng.choice(sorted(dns))
            dns.pop(victim).stop()
            time.sleep(1.5)
            revived = DatanodeDaemon(tmp_path / victim, victim,
                                     meta.address,
                                     heartbeat_interval_s=0.1)
            revived.start()
            dns[victim] = revived
        # after the last heal, wait for real progress (writes through a
        # degraded pipeline pay watch-degrade timeouts, so fixed sleeps
        # are too timing-sensitive)
        deadline = time.monotonic() + 45
        while time.monotonic() < deadline and len(acked) < 3:
            time.sleep(0.2)
        stop.set()
        wt.join(timeout=60)
        assert not wt.is_alive(), "writer wedged"
        assert not write_errors, write_errors
        assert len(acked) >= 3, f"no progress: {acked}"
        for key in acked:
            assert bucket.read_key(key).tobytes() == payload, key
    finally:
        stop.set()
        for d in dns.values():
            d.stop()
        meta.stop()


@pytest.mark.parametrize("seed", [23])
def test_leadership_transfers_under_write_load(tmp_path, seed):
    """Planned hand-offs interleaved with writes: every ACKED write
    survives repeated `ring transfer` round-robin across the replicas,
    and the ring always converges back to one leader."""
    from ozone_tpu.net.scm_service import GrpcScmClient

    rng = random.Random(seed)
    ports = _free_ports(N_META)
    peers = {f"m{i}": f"127.0.0.1:{ports[i]}" for i in range(N_META)}
    metas = {}
    dns = []
    stop = threading.Event()
    acked: list[str] = []
    write_errors: list[Exception] = []
    try:
        for i in range(N_META):
            d = _make_meta(tmp_path, i, peers)
            d.start()
            metas[f"m{i}"] = d
        _await_leader(metas)
        scm_addrs = ",".join(peers.values())
        for i in range(5):
            d = DatanodeDaemon(tmp_path / f"dn{i}", f"dn{i}", scm_addrs,
                               heartbeat_interval_s=0.15)
            d.start()
            dns.append(d)
        oz = _client(peers)
        oz.create_volume("v")
        bucket = oz.get_volume("v").create_bucket(
            "b", replication="rs-3-2-4096")
        payload = np.random.default_rng(seed).integers(
            0, 256, 40_000, dtype=np.uint8).tobytes()

        def writer():
            i = 0
            while not stop.is_set():
                key = f"k{i}"
                try:
                    bucket.write_key(key, payload)
                    acked.append(key)
                except StorageError:
                    pass  # mid-transfer refusals retry as new keys
                except Exception as e:  # noqa: BLE001
                    write_errors.append(e)
                    return  # fatal: capture once, exit cleanly
                i += 1

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        # load-adaptive: stop at 3 hand-offs, allow up to 45s under
        # full-suite CPU contention (elections + catch-up slow down)
        transfers = 0
        deadline = time.time() + 45
        while transfers < 3 and time.time() < deadline:
            leader = _await_leader(metas, timeout=15.0)
            target = rng.choice([m for m in peers if m != leader])
            scm = GrpcScmClient(peers[leader])
            try:
                out = scm.admin("ring-transfer", target)
                if out.get("transferred"):
                    transfers += 1
            except StorageError:
                pass  # leadership raced; next loop re-resolves
            finally:
                scm.close()
            time.sleep(1.0)
        stop.set()
        t.join(timeout=30)
        assert not t.is_alive(), "writer wedged"
        assert transfers >= 1, "no transfer completed in 45s"
        assert not write_errors, write_errors[:3]
        assert len(acked) > 0
        # EVERY acked write is readable after all the hand-offs (the
        # await below also asserts the ring converged to one leader).
        # HARD assertion: the round-3 duplicate-allocation corruption is
        # fixed by commit-first id issuance (scm/sequence_id.py) + the
        # datanode write fence (Container.bind_writer) — any mismatch
        # here is a regression, reported with the full fingerprint
        # (first bad offset, where the foreign bytes appear in the
        # payload, re-read stability, block-group layout)
        leader = _await_leader(metas, timeout=15.0)
        oz_om = metas[leader].om
        for key in acked:
            got = bucket.read_key(key).tobytes()
            if got != payload:
                n = min(len(got), len(payload))
                idx = next((i for i in range(n) if got[i] != payload[i]),
                           n)
                probe = got[idx:idx + 32]
                src = payload.find(probe)
                info = oz_om.lookup_key("v", "b", key)
                again = bucket.read_key(key).tobytes()
                raise AssertionError(
                    f"acked key corrupted across hand-off: {key} "
                    f"mismatch at {idx} (lens {len(got)}/{len(payload)}),"
                    f" foreign bytes at payload[{src}]; "
                    f"reread_same_wrong={again == got}; groups="
                    f"{[(g['container_id'], g['local_id'], g['nodes']) for g in info['block_groups']]}")
    finally:
        stop.set()
        for d in dns:
            d.stop()
        for d in metas.values():
            d.stop()
