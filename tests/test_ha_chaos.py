"""Chaos over the metadata HA ring: replica restarts under write load.

The reference's mini-chaos-tests (fault-injection-test OzoneChaosCluster
+ FailureManager) randomly restart OMs while load generators assert
invariants; this is the same contract against the multi-process HA ring:
every ACKED write must be readable afterwards, no matter which replica
was down when, and the ring must converge back to one leader.
"""

import random
import threading
import time

import numpy as np
import pytest

from ozone_tpu.storage.ids import StorageError
from tests.test_meta_ha import (
    _await_leader,
    _client,
    _free_ports,
    _make_meta,
)
from ozone_tpu.net.daemons import DatanodeDaemon

N_META = 3


@pytest.mark.parametrize("seed", [11])
def test_meta_ha_chaos_replica_restarts(tmp_path, seed):
    rng = random.Random(seed)
    ports = _free_ports(N_META)
    peers = {f"m{i}": f"127.0.0.1:{ports[i]}" for i in range(N_META)}
    metas = {}
    dns = []
    stop = threading.Event()
    acked: list[str] = []
    write_errors: list[Exception] = []

    try:
        for i in range(N_META):
            d = _make_meta(tmp_path, i, peers)
            d.start()
            metas[f"m{i}"] = d
        _await_leader(metas)
        scm_addrs = ",".join(peers.values())
        for i in range(5):
            d = DatanodeDaemon(tmp_path / f"dn{i}", f"dn{i}", scm_addrs,
                               heartbeat_interval_s=0.15)
            d.start()
            dns.append(d)

        oz = _client(peers)
        oz.create_volume("v")
        bucket = oz.get_volume("v").create_bucket(
            "b", replication="rs-3-2-4096")
        payload = np.random.default_rng(seed).integers(
            0, 256, 60_000, dtype=np.uint8).tobytes()

        def writer():
            n = 0
            while not stop.is_set():
                key = f"k{n}"
                try:
                    bucket.write_key(key, payload)
                    acked.append(key)
                except StorageError:
                    pass  # un-acked: no durability claim, keep going
                except Exception as e:  # noqa: BLE001 - fail the test
                    write_errors.append(e)
                    return
                n += 1

        wt = threading.Thread(target=writer, daemon=True)
        wt.start()

        # chaos: three rounds of stop-a-random-replica / restart it
        for _ in range(3):
            time.sleep(1.5)
            victim = rng.choice(sorted(metas))
            idx = int(victim[1:])
            metas.pop(victim).stop()
            time.sleep(1.5)
            revived = _make_meta(tmp_path, idx, peers)
            revived.start()
            metas[victim] = revived

        time.sleep(1.0)
        stop.set()
        wt.join(timeout=30)
        assert not wt.is_alive(), "writer wedged"
        assert not write_errors, write_errors
        assert len(acked) >= 3, f"writer made no progress: {acked}"

        # invariant: every acked key reads back intact
        _await_leader(metas, timeout=20)
        for key in acked:
            out = bucket.read_key(key)
            assert out.tobytes() == payload, key
    finally:
        stop.set()
        for d in dns:
            d.stop()
        for d in metas.values():
            d.stop()
