"""hsync + lease recovery (KeyOutputStream.hsync / OMKeyCommitRequest
isHsync / OMRecoverLeaseRequest + the ozonefs adapter's recoverLease).

Semantics under test: a mid-write hsync makes the key readable at the
synced length while the stream stays open; repeated hsyncs never push the
live blocks into the deletion chain; a final commit after hsyncs keeps the
data; recover-lease seals an abandoned hsynced write at its last durable
length and fences the dead writer; EC keys reject hsync.
"""

import numpy as np
import pytest

from ozone_tpu.om.requests import OMError
from ozone_tpu.storage.ids import StorageError
from ozone_tpu.testing.minicluster import MiniOzoneCluster

EC = "rs-3-2-4096"


@pytest.fixture
def cluster(tmp_path):
    c = MiniOzoneCluster(
        tmp_path,
        num_datanodes=5,
        block_size=4 * 4096,
        container_size=1024 * 1024,
        stale_after_s=1000.0,
        dead_after_s=2000.0,
    )
    yield c
    c.close()


def _rng_bytes(n, seed=0):
    return np.random.default_rng(seed).integers(0, 256, n, dtype=np.uint8)


def test_hsync_visible_at_synced_length_then_final_commit(cluster):
    oz = cluster.client()
    b = oz.create_volume("v").create_bucket("b", replication="RATIS/THREE")
    data = _rng_bytes(40_000)
    h = b.open_key("k")
    h.write(data[:25_000])
    h.hsync()
    # a concurrent reader sees exactly the synced prefix
    assert np.array_equal(b.read_key("k"), data[:25_000])
    # the stream keeps going and the final commit supersedes
    h.write(data[25_000:])
    h.close()
    assert np.array_equal(b.read_key("k"), data)


def test_repeated_hsync_does_not_purge_live_blocks(cluster):
    oz = cluster.client()
    b = oz.create_volume("v").create_bucket("b", replication="RATIS/THREE")
    data = _rng_bytes(60_000, seed=1)
    h = b.open_key("k")
    for i in range(3):
        h.write(data[i * 20_000 : (i + 1) * 20_000])
        h.hsync()
        assert np.array_equal(b.read_key("k"), data[: (i + 1) * 20_000])
    h.close()
    assert np.array_equal(b.read_key("k"), data)
    # the deletion chain must hold nothing from this stream: every hsync
    # version shared the same live blocks
    deleted = list(cluster.om.store.iterate("deleted_keys"))
    assert deleted == []
    # and the open session is gone after the final commit
    assert list(cluster.om.store.iterate("open_keys")) == []


def test_hsync_overwrite_enqueues_old_version_once(cluster):
    oz = cluster.client()
    b = oz.create_volume("v").create_bucket("b", replication="RATIS/THREE")
    b.write_key("k", _rng_bytes(10_000, seed=2))  # committed v1
    h = b.open_key("k")
    h.write(_rng_bytes(5_000, seed=3))
    h.hsync()  # v1 superseded here
    h.hsync()  # same stream again: no double-enqueue
    h.close()
    deleted = list(cluster.om.store.iterate("deleted_keys"))
    assert len(deleted) == 1


def test_ec_key_rejects_hsync(cluster):
    oz = cluster.client()
    b = oz.create_volume("v").create_bucket("b", replication=EC)
    h = b.open_key("k")
    h.write(_rng_bytes(1_000, seed=4))
    with pytest.raises(StorageError) as ei:
        h.hsync()
    assert ei.value.code == "NOT_SUPPORTED_OPERATION"
    h.close()


def test_recover_lease_seals_abandoned_write_and_fences_writer(cluster):
    oz = cluster.client()
    b = oz.create_volume("v").create_bucket("b", replication="RATIS/THREE")
    data = _rng_bytes(30_000, seed=5)
    h = b.open_key("k")
    h.write(data[:18_000])
    h.hsync()
    # writer "dies" here; another client recovers the lease
    out = oz.om.recover_lease("v", "b", "k")
    assert out["recovered"] is True
    info = oz.om.lookup_key("v", "b", "k")
    assert "hsync_client_id" not in info
    assert np.array_equal(b.read_key("k"), data[:18_000])
    # the dead writer is fenced: its final commit fails on the dropped
    # session and must not clobber the sealed key
    h.write(data[18_000:])
    with pytest.raises(OMError):
        h.close()
    assert np.array_equal(b.read_key("k"), data[:18_000])


def test_recover_lease_discards_never_hsynced_session(cluster):
    oz = cluster.client()
    b = oz.create_volume("v").create_bucket("b", replication="RATIS/THREE")
    h = b.open_key("k")
    h.write(_rng_bytes(9_000, seed=6))
    out = oz.om.recover_lease("v", "b", "k")
    assert out["recovered"] is False
    with pytest.raises(OMError):
        oz.om.lookup_key("v", "b", "k")
    # unknown key with no sessions: KEY_NOT_FOUND
    with pytest.raises(OMError):
        oz.om.recover_lease("v", "b", "nope")


def test_hsync_and_recover_lease_on_fso_bucket(cluster):
    oz = cluster.client()
    oz.create_volume("v")
    oz.om.create_bucket("v", "fso", "RATIS/THREE",
                        "FILE_SYSTEM_OPTIMIZED")
    b = oz.get_volume("v").get_bucket("fso")
    data = _rng_bytes(22_000, seed=7)
    h = b.open_key("dir/sub/f")
    h.write(data[:12_000])
    h.hsync()
    assert np.array_equal(b.read_key("dir/sub/f"), data[:12_000])
    out = oz.om.recover_lease("v", "fso", "dir/sub/f")
    assert out["recovered"] is True
    assert np.array_equal(b.read_key("dir/sub/f"), data[:12_000])
    # fenced final commit
    h.write(data[12_000:])
    with pytest.raises(OMError):
        h.close()


def test_cleanup_service_seals_expired_hsynced_sessions(cluster):
    oz = cluster.client()
    b = oz.create_volume("v").create_bucket("b", replication="RATIS/THREE")
    data = _rng_bytes(8_000, seed=8)
    h = b.open_key("k")
    h.write(data)
    h.hsync()
    # max_age 0: everything expires immediately
    n = cluster.om.run_open_key_cleanup_once(max_age_s=0.0)
    assert n == 1
    info = oz.om.lookup_key("v", "b", "k")
    assert "hsync_client_id" not in info
    assert np.array_equal(b.read_key("k"), data)
    assert list(cluster.om.store.iterate("open_keys")) == []


def test_fs_adapter_recover_lease(cluster):
    from ozone_tpu.gateway.fs import OzoneFileSystem

    oz = cluster.client()
    oz.create_volume("v")
    oz.om.create_bucket("v", "fso", "RATIS/THREE",
                        "FILE_SYSTEM_OPTIMIZED")
    b = oz.get_volume("v").get_bucket("fso")
    fs = OzoneFileSystem(b)
    h = b.open_key("d/f")
    h.write(_rng_bytes(5_000, seed=9))
    h.hsync()
    assert fs.recover_lease("/d/f") is True


def test_delete_of_hsynced_key_fences_the_writer(cluster):
    """Deleting a live hsync stream's key must fence the writer before the
    blocks reach the purge chain — its commit must not resurrect them."""
    oz = cluster.client()
    b = oz.create_volume("v").create_bucket("b", replication="RATIS/THREE")
    data = _rng_bytes(12_000, seed=10)
    h = b.open_key("k")
    h.write(data)
    h.hsync()
    b.delete_key("k")
    h.write(data)
    with pytest.raises(OMError):
        h.close()
    with pytest.raises(OMError):
        oz.om.lookup_key("v", "b", "k")


def test_overwrite_of_hsynced_key_fences_the_stale_writer(cluster):
    """A second client overwriting an hsynced key supersedes it: the stale
    hsync writer is fenced, the new version survives."""
    oz = cluster.client()
    b = oz.create_volume("v").create_bucket("b", replication="RATIS/THREE")
    stale = b.open_key("k")
    stale.write(_rng_bytes(6_000, seed=11))
    stale.hsync()
    fresh = _rng_bytes(4_000, seed=12)
    b.write_key("k", fresh)  # another client's committed overwrite
    stale.write(_rng_bytes(1_000, seed=13))
    with pytest.raises(OMError):
        stale.close()
    assert np.array_equal(b.read_key("k"), fresh)


def test_recover_lease_ignores_slash_extended_neighbors(cluster):
    """OBS key names contain slashes: recovering 'logs' must not fence the
    writer of 'logs/part-1'."""
    oz = cluster.client()
    b = oz.create_volume("v").create_bucket("b", replication="RATIS/THREE")
    neighbor = b.open_key("logs/part-1")
    neighbor.write(_rng_bytes(3_000, seed=14))
    target = b.open_key("logs")
    target.write(_rng_bytes(2_000, seed=15))
    target.hsync()
    assert oz.om.recover_lease("v", "b", "logs")["recovered"] is True
    # the neighbor's stream is untouched and commits fine
    neighbor.close()
    assert b.read_key("logs/part-1").size == 3_000


def test_cleanup_spares_actively_syncing_writer(cluster):
    """Expiry for hsync streams keys off the last sync, not stream
    creation: an actively syncing long-lived writer is never force-sealed."""
    oz = cluster.client()
    b = oz.create_volume("v").create_bucket("b", replication="RATIS/THREE")
    h = b.open_key("k")
    h.write(_rng_bytes(2_000, seed=16))
    h.hsync()  # refreshes modified
    # created is in the past relative to a tiny max_age, but the stream
    # synced "just now": cleanup must leave it alone
    import time as _time

    _time.sleep(0.05)
    n = cluster.om.run_open_key_cleanup_once(max_age_s=3600.0)
    assert n == 0
    h.write(_rng_bytes(2_000, seed=17))
    h.hsync()
    h.close()
    assert b.read_key("k").size == 4_000


def test_list_open_files_pages_and_reflects_lease_state(cluster):
    """OzoneManager.listOpenFiles analog: open sessions appear with
    client id + hsync flag, paginate via continuation, and vanish on
    commit/recover-lease."""
    oz = cluster.client()
    b = oz.create_volume("v").create_bucket("b", replication="RATIS/THREE")
    om = cluster.om
    handles = [b.open_key(f"open{i}") for i in range(5)]
    handles[0].write(_rng_bytes(3000))
    handles[0].hsync()

    out = om.list_open_files("v", "b")
    assert not out["truncated"]
    by_key = {e["key"]: e for e in out["open_files"]}
    assert set(by_key) == {f"open{i}" for i in range(5)}
    assert by_key["open0"]["hsync"] is True
    assert by_key["open1"]["hsync"] is False
    assert by_key["open0"]["size"] >= 0

    # pagination: two pages of 3 + 2, stitched by continuation
    page1 = om.list_open_files("v", "b", limit=3)
    assert page1["truncated"] and len(page1["open_files"]) == 3
    page2 = om.list_open_files("v", "b", limit=3,
                               start_after=page1["continuation"])
    assert not page2["truncated"]
    got = [e["open_key"] for e in page1["open_files"] + page2["open_files"]]
    assert len(got) == 5 and len(set(got)) == 5

    # prefix filter
    assert len(om.list_open_files("v", "b", prefix="open1")["open_files"]) == 1

    # sessions disappear as they commit / get sealed
    handles[1].close()
    h0 = handles[0]
    om.recover_lease("v", "b", "open0")
    names = {e["key"] for e in om.list_open_files("v", "b")["open_files"]}
    assert "open1" not in names
    assert "open0" not in names  # lease recovery sealed it
    for h in handles[2:]:
        h.close()
    assert om.list_open_files("v", "b")["open_files"] == []
    del h0


def test_list_open_files_excludes_snapshot_metadata(cluster):
    """Snapshot chain rows ride the open_keys table but are not open
    files."""
    oz = cluster.client()
    b = oz.create_volume("vs").create_bucket("bs", replication="RATIS/THREE")
    b.write_key("k1", _rng_bytes(1000))
    om = cluster.om
    om.create_snapshot("vs", "bs", "snap1")
    assert om.list_open_files()["open_files"] == []


def test_list_open_files_rejects_nonpositive_limit(cluster):
    with pytest.raises(OMError):
        cluster.om.list_open_files("v", "b", limit=0)
