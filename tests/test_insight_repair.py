"""Insight service, repair tool, and new freon generators.

Mirrors the reference's insight CLI tests (per-subsystem points, log
streaming via level bump) and freon generator coverage."""

import logging

import numpy as np
import pytest

from ozone_tpu.testing.minicluster import MiniOzoneCluster
from ozone_tpu.tools import freon
from ozone_tpu.utils.insight import (
    INSIGHT_POINTS,
    InsightClient,
    InsightService,
    RingLogHandler,
)

EC = "rs-3-2-4096"


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    c = MiniOzoneCluster(
        tmp_path_factory.mktemp("insight"),
        num_datanodes=5,
        block_size=8 * 4096,
        container_size=4 * 1024 * 1024,
        stale_after_s=1000.0,
        dead_after_s=2000.0,
    )
    yield c
    c.close()


# ------------------------------------------------------------------ insight
@pytest.fixture(scope="module")
def insight(cluster):
    from ozone_tpu.net.rpc import RpcServer

    server = RpcServer()
    InsightService(server, "test-daemon")
    server.start()
    cli = InsightClient(server.address)
    yield cli
    cli.close()
    server.stop()


def test_insight_points_catalog(insight):
    points = insight.list_points()["points"]
    assert "scm.replication-manager" in points
    assert "om.key-manager" in points
    for p in points.values():
        assert p["loggers"] and p["metrics"]
    assert set(points) == set(INSIGHT_POINTS)


def test_insight_metrics(cluster, insight):
    cluster.client().create_volume("insvol")
    regs = insight.metrics()["registries"]
    assert "om" in regs and "scm" in regs
    assert regs["scm"].get("heartbeats", 0) >= 0


def test_insight_logs_and_level(insight):
    log = logging.getLogger("ozone_tpu.scm.replication_manager")
    insight.set_log_level("ozone_tpu.scm.replication_manager", "DEBUG")
    log.debug("insight-test-debug-message %d", 42)
    records = insight.logs(n=50, logger="ozone_tpu.scm")
    assert any("insight-test-debug-message 42" in r["message"]
               for r in records)
    # level filter excludes DEBUG
    records = insight.logs(n=50, logger="ozone_tpu.scm", level="ERROR")
    assert not any("insight-test-debug-message" in r["message"]
                   for r in records)


def test_ring_handler_bounded():
    h = RingLogHandler(capacity=10)
    for i in range(100):
        h.emit(logging.LogRecord("x", logging.INFO, "", 0,
                                 f"m{i}", (), None))
    assert len(h.records) == 10
    assert h.tail(5)[-1]["message"] == "m99"


# ------------------------------------------------------------------- repair
def test_orphan_block_detection(cluster):
    from ozone_tpu.storage.ids import BlockData, BlockID, ChunkInfo

    oz = cluster.client()
    b = oz.create_volume("repvol").create_bucket("rb", replication=EC)
    b.write_key("legit", np.arange(9000, dtype=np.uint8) % 251)
    info = oz.om.lookup_key("repvol", "rb", "legit")
    g = info["block_groups"][0]
    cid = int(g["container_id"])
    dn_id = g["nodes"][0]
    # fabricate an orphan block in the same container on one datanode
    orphan = BlockID(cid, 999_999)
    client = oz.clients.get(dn_id)
    client.put_block(BlockData(orphan, chunks=[]))
    referenced = {
        (int(gg["container_id"]), int(gg["local_id"]))
        for v in oz.om.list_volumes()
        for bk in oz.om.list_buckets(v["name"])
        for k in oz.om.list_keys(v["name"], bk["name"])
        for gg in k.get("block_groups", [])
    }
    blocks = client.list_blocks(cid)
    orphans = [
        blk for blk in blocks
        if (blk.block_id.container_id, blk.block_id.local_id)
        not in referenced
    ]
    assert [o.block_id.local_id for o in orphans] == [999_999]
    client.delete_block(orphan)
    assert all(
        blk.block_id.local_id != 999_999
        for blk in client.list_blocks(cid)
    )


# ------------------------------------------------------------- freon gens
def test_freon_cmdw(tmp_path):
    rep = freon.cmdw(tmp_path / "chunks", n_chunks=20, size=64 * 1024,
                     threads=2)
    assert rep.failures == 0
    assert rep.summary()["throughput_mib_s"] > 0


def test_freon_scmtb(cluster):
    rep = freon.scmtb(cluster.client(), n_blocks=50, threads=4,
                      replication=EC)
    assert rep.failures == 0
    assert rep.summary()["ops_per_s"] > 0


def test_freon_dbgen(tmp_path):
    rep = freon.dbgen(tmp_path / "gen.db", n_keys=500)
    assert rep.failures == 0
    from ozone_tpu.om.metadata import OMMetadataStore

    store = OMMetadataStore(tmp_path / "gen.db")
    keys = list(store.iterate("keys"))
    store.close()
    assert len(keys) == 500


def test_freon_ommg(cluster):
    rep = freon.ommg(cluster.client(), n_ops=50, threads=4)
    assert rep.failures == 0


def test_repair_snapshot_chain_and_transaction_offline(tmp_path):
    """Offline db surgery (ozone repair snapshot-chain / transaction):
    dry-run shows state without writing; --apply re-points a snapshot's
    chain link / resets the raft applied marker."""
    import json

    from ozone_tpu.om.metadata import OMMetadataStore
    from ozone_tpu.om.om import OzoneManager
    from ozone_tpu.om.requests import snapmeta_key
    from ozone_tpu.scm.scm import StorageContainerManager
    from ozone_tpu.tools.cli import main as cli_main

    scm = StorageContainerManager(stale_after_s=1e6, dead_after_s=2e6)
    for i in range(5):
        scm.register_datanode(f"dn{i}")
    om = OzoneManager(tmp_path / "om.db", scm)
    om.create_volume("v")
    om.create_bucket("v", "b", "rs-3-2-4096")
    s1 = om.create_snapshot("v", "b", "s1")
    s2 = om.create_snapshot("v", "b", "s2")
    om.store.put("system", "raft_applied", {"index": 41})
    om.store.flush()
    om.close()
    db = str(tmp_path / "om.db")

    # dry-run: nothing changes
    assert cli_main(["repair", "snapshot-chain", "--db", db,
                     "--path", "/v/b", "--name", "s2",
                     "--previous", "none"]) == 0
    st = OMMetadataStore(tmp_path / "om.db")
    assert st.get("open_keys",
                  snapmeta_key("v", "b", "s2"))["previous"] == s1["snap_id"]
    st.close()

    # apply: chain link cleared
    assert cli_main(["repair", "snapshot-chain", "--db", db,
                     "--path", "/v/b", "--name", "s2",
                     "--previous", "none", "--apply"]) == 0
    st = OMMetadataStore(tmp_path / "om.db")
    assert st.get("open_keys",
                  snapmeta_key("v", "b", "s2"))["previous"] is None
    st.close()

    # re-point at s1 by id; bogus id refused
    assert cli_main(["repair", "snapshot-chain", "--db", db,
                     "--path", "/v/b", "--name", "s2",
                     "--previous", s1["snap_id"], "--apply"]) == 0
    assert cli_main(["repair", "snapshot-chain", "--db", db,
                     "--path", "/v/b", "--name", "s2",
                     "--previous", "bogus", "--apply"]) == 1
    st = OMMetadataStore(tmp_path / "om.db")
    assert st.get("open_keys",
                  snapmeta_key("v", "b", "s2"))["previous"] == s1["snap_id"]
    st.close()

    # transaction marker: dry-run leaves 41, apply sets 7
    assert cli_main(["repair", "transaction", "--db", db]) == 0
    assert cli_main(["repair", "transaction", "--db", db,
                     "--index", "7", "--apply"]) == 0
    st = OMMetadataStore(tmp_path / "om.db")
    assert st.get("system", "raft_applied")["index"] == 7
    st.close()
    del s2, json


def test_admin_reconfig_cli(tmp_path, capsys):
    """admin reconfig properties/set over the daemon's /reconfig
    endpoint (ozone admin reconfig analog)."""
    import json as _json

    from ozone_tpu.net.daemons import ScmOmDaemon
    from ozone_tpu.tools.cli import main as cli_main

    meta = ScmOmDaemon(tmp_path / "om.db", stale_after_s=1e6,
                       dead_after_s=2e6, http_port=0)
    meta.start()
    try:
        http = meta.http.address
        assert cli_main(["admin", "reconfig", "properties",
                         "--http", http]) == 0
        props = _json.loads(capsys.readouterr().out)
        assert isinstance(props, (list, dict)) and props
        # pick a registered property and set it
        name = (props[0]["key"] if isinstance(props, list)
                else sorted(props)[0])
        assert cli_main(["admin", "reconfig", "set", name,
                         "--http", http, "--value", "123"]) == 0
        out = capsys.readouterr().out
        assert "error" not in out.lower() or "123" in out
        # missing --http is a clean usage error
        assert cli_main(["admin", "reconfig", "properties"]) == 2
    finally:
        meta.stop()


def test_debug_container_offline_verbs(tmp_path, capsys):
    """ozone debug container list/inspect analog: offline exploration
    of a local datanode root."""
    import json as _json

    import numpy as np

    from pathlib import Path as pathlib_Path

    from ozone_tpu.storage.datanode import Datanode
    from ozone_tpu.storage.ids import BlockData, BlockID, ChunkInfo
    from ozone_tpu.tools.cli import main as cli_main
    from ozone_tpu.utils.checksum import Checksum

    root = tmp_path / "dnroot"
    dn = Datanode(root, "dx", num_volumes=2)
    data = np.random.default_rng(3).integers(0, 256, 5000, dtype=np.uint8)
    for cid in (1, 2):
        dn.create_container(cid)
        bid = BlockID(cid, 1)
        info = ChunkInfo("c0", 0, data.size,
                         Checksum().compute(data))
        dn.write_chunk(bid, info, data)
        dn.put_block(BlockData(bid, [info], data.size))
    dn.close()

    assert cli_main(["debug", "container-list", "--root", str(root)]) == 0
    rows = _json.loads(capsys.readouterr().out)
    assert [r["id"] for r in rows] == [1, 2]
    assert all(r["blocks"] == 1 and r["used_bytes"] == 5000 for r in rows)

    assert cli_main(["debug", "container-inspect", "1",
                     "--root", str(root)]) == 0
    out = _json.loads(capsys.readouterr().out)
    assert out["id"] == 1
    assert out["blocks"][0]["length"] == 5000
    assert out["scan_errors"] == []

    assert cli_main(["debug", "container-list", "--root",
                     str(tmp_path / "nope")]) == 2
    assert cli_main(["debug", "container-inspect", "abc",
                     "--root", str(root)]) == 2
    assert cli_main(["debug", "container-inspect", "777",
                     "--root", str(root)]) == 1
    capsys.readouterr()

    # non-contiguous volume names still load (vol1 removed by operator)
    import shutil

    shutil.rmtree(root / "vol1")
    (root / "vol3").mkdir()
    assert cli_main(["debug", "container-list", "--root", str(root)]) == 0
    rows = _json.loads(capsys.readouterr().out)
    assert len(rows) >= 1  # vol0's container still listed
    assert not (root / "vol1").exists()  # read-only: nothing fabricated
    # STRICTLY read-only: the bare vol3 dir gained no fabricated state
    assert list((root / "vol3").iterdir()) == []

    # a crash-truncated descriptor warns but never hides the healthy
    # containers
    bad = root / "vol0" / "containers" / "42"
    bad.mkdir(parents=True)
    (bad / "container.json").write_text('{"id": 42, "sta')
    assert cli_main(["debug", "container-list", "--root", str(root)]) == 0
    cap = capsys.readouterr()
    rows2 = _json.loads(cap.out)
    assert [r["id"] for r in rows2] == [r["id"] for r in rows]
    assert "bad descriptor" in cap.err
    shutil.rmtree(bad)

    # a container missing its chunks/ dir (crash before first write)
    # still lists, and the read-only path does NOT fabricate chunks/
    chunkless = root / "vol0" / "containers" / "43"
    chunkless.mkdir(parents=True)
    import json as _jj

    (chunkless / "container.json").write_text(_jj.dumps(
        {"id": 43, "state": "OPEN", "replica_index": 0,
         "created_at": 0}))
    assert cli_main(["debug", "container-list", "--root", str(root)]) == 0
    rows3 = _json.loads(capsys.readouterr().out)
    assert 43 in [r["id"] for r in rows3]
    assert not (chunkless / "chunks").exists()

    # a corrupt chunk reports scan_errors WITHOUT rewriting state
    import json as _j

    victim = rows[0]
    chunk_files = list((pathlib_Path(victim["path"]) / "chunks").glob("*"))
    chunk_files[0].write_bytes(b"garbage" * 100)
    desc_before = (pathlib_Path(victim["path"]) / "container.json").read_text()
    assert cli_main(["debug", "container-inspect", str(victim["id"]),
                     "--root", str(root)]) == 0
    out2 = _json.loads(capsys.readouterr().out)
    assert out2["scan_errors"]
    desc_after = (pathlib_Path(victim["path"]) / "container.json").read_text()
    assert desc_before == desc_after  # inspection never commits UNHEALTHY
    del _j
