"""LEGACY bucket layout: flat key table with filesystem path semantics.

The reference's third layout (BucketLayoutAwareOMKeyRequestFactory
routes LEGACY through the flat-table key requests with
`ozone.om.enable.filesystem.paths` behaviors): path normalization,
server-side parent directory markers on commit, and file/directory
conflict refusal.
"""

import numpy as np
import pytest

from ozone_tpu.om.requests import OMError, normalize_fs_path
from ozone_tpu.testing.minicluster import MiniOzoneCluster

EC = "rs-3-2-4096"


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    c = MiniOzoneCluster(
        tmp_path_factory.mktemp("legacy"),
        num_datanodes=5,
        block_size=8 * 4096,
        container_size=4 * 1024 * 1024,
        stale_after_s=1000.0,
        dead_after_s=2000.0,
    )
    c.client().create_volume("lv")
    c.om.create_bucket("lv", "lb", EC, layout="LEGACY")
    yield c
    c.close()


def _bucket(cluster):
    return cluster.client().get_volume("lv").get_bucket("lb")


def test_normalize_fs_path():
    assert normalize_fs_path("/a//b/c") == "a/b/c"
    assert normalize_fs_path("a/b/") == "a/b/"
    for bad in ("", "/", "a/../b", "./a"):
        with pytest.raises(OMError):
            normalize_fs_path(bad)


def test_unknown_layout_refused(cluster):
    with pytest.raises(OMError):
        cluster.om.create_bucket("lv", "bad", EC, layout="NOPE")


def test_legacy_normalizes_and_creates_parent_markers(cluster):
    b = _bucket(cluster)
    data = np.arange(9000, dtype=np.uint8) % 251
    # write through a denormalized path; read back via the clean one
    b.write_key("/d1//d2/f.bin", data)
    assert np.array_equal(b.read_key("d1/d2/f.bin"), data)
    # the OM materialized the parent markers server-side
    names = {k["name"] for k in cluster.om.list_keys("lv", "lb")}
    assert {"d1/", "d1/d2/", "d1/d2/f.bin"} <= names


def test_legacy_file_directory_conflicts_refused(cluster):
    b = _bucket(cluster)
    b.write_key("c1/leaf", np.zeros(100, np.uint8))
    # a file cannot shadow an existing directory
    with pytest.raises(Exception) as ei:
        b.write_key("c1", np.zeros(10, np.uint8))
    assert "FILE_ALREADY_EXISTS" in str(ei.value)
    # a key cannot be created under a file
    with pytest.raises(Exception) as ei:
        b.write_key("c1/leaf/under", np.zeros(10, np.uint8))
    assert "NOT_A_DIRECTORY" in str(ei.value)


def test_legacy_rename_delete_normalized(cluster):
    b = _bucket(cluster)
    b.write_key("r/a.txt", np.zeros(64, np.uint8))
    cluster.om.rename_key("lv", "lb", "//r/a.txt", "r/b.txt")
    assert np.array_equal(b.read_key("r/b.txt"),
                          np.zeros(64, np.uint8))
    b.delete_key("/r//b.txt")
    with pytest.raises(Exception):
        b.read_key("r/b.txt")


def test_legacy_webhdfs_roundtrip(cluster):
    """The rooted fs adapter + WebHDFS semantics work unchanged over a
    LEGACY bucket (the layout the reference's ozoneFS predates FSO
    with)."""
    from ozone_tpu.gateway.fs import RootedOzoneFileSystem

    fs = RootedOzoneFileSystem(cluster.client(), replication=EC)
    fs.create("/lv/lb/w/x/deep.bin", b"legacy-bytes")
    st = fs.get_file_status("/lv/lb/w/x/deep.bin")
    assert not st.is_dir and st.length == 12
    assert fs.get_file_status("/lv/lb/w/x").is_dir
    names = [s.path for s in fs.list_status("/lv/lb/w")]
    assert names == ["lv/lb/w/x"]
    with fs.open("/lv/lb/w/x/deep.bin") as f:
        assert f.read() == b"legacy-bytes"


def test_legacy_rename_enforces_fs_shape(cluster):
    b = _bucket(cluster)
    b.write_key("rn/file", np.zeros(32, np.uint8))
    b.write_key("rn/plain", np.zeros(32, np.uint8))
    # destination under a plain FILE is refused
    with pytest.raises(Exception) as ei:
        cluster.om.rename_key("lv", "lb", "rn/file", "rn/plain/x")
    assert "NOT_A_DIRECTORY" in str(ei.value)
    # rename into a fresh directory materializes its marker
    cluster.om.rename_key("lv", "lb", "rn/file", "rn/newdir/file")
    names = {k["name"] for k in cluster.om.list_keys("lv", "lb", "rn/")}
    assert "rn/newdir/" in names and "rn/newdir/file" in names


def test_legacy_mpu_normalized_with_markers(cluster):
    """Multipart uploads obey the same LEGACY path semantics as plain
    writes: denormalized names are normalized at initiate and the
    completed key gets parent markers."""
    oz = cluster.client()
    b = oz.get_volume("lv").get_bucket("lb")
    up = b.initiate_multipart_upload("//m1//deep/obj")
    data = np.arange(6000, dtype=np.uint8) % 251
    up.write_part(1, data)
    up.complete()
    assert np.array_equal(b.read_key("m1/deep/obj"), data)
    names = {k["name"] for k in cluster.om.list_keys("lv", "lb", "m1/")}
    assert {"m1/", "m1/deep/", "m1/deep/obj"} <= names


def test_legacy_quota_counts_markers(cluster):
    """Namespace quota accounting agrees across live enforcement,
    deletes, and RepairQuota when markers are materialized."""
    cluster.om.create_bucket("lv", "qb", EC, layout="LEGACY")
    oz = cluster.client()
    b = oz.get_volume("lv").get_bucket("qb")
    b.write_key("a/b/f", np.zeros(64, np.uint8))
    assert cluster.om.bucket_info("lv", "qb")["key_count"] == 3
    # the paged repair's recount agrees with live accounting
    repaired = cluster.om.repair_quota("lv")
    assert repaired["buckets"]["/lv/qb"]["key_count"] == 3
    # deleting a marker and the file settles back to agreement
    b.delete_key("a/b/f")
    b.delete_key("a/b/")
    b.delete_key("a/")
    assert cluster.om.bucket_info("lv", "qb")["key_count"] == 0
