"""libo3fs: the native C client (o3fs.c over WebHDFS/POSIX sockets).

Mirrors the reference's native-client surface
(hadoop-ozone/native-client/libo3fs + libo3fs-examples): connect,
mkdir, whole-file write/read roundtrip, path info, rename, delete —
exercised through the compiled shared library via ctypes, plus the two
example binaries end-to-end against a live HttpFS gateway.
"""

import ctypes
import subprocess
from pathlib import Path

import numpy as np
import pytest

from ozone_tpu.gateway.httpfs import HttpFSGateway
from ozone_tpu.native import build_shared
from ozone_tpu.testing.minicluster import MiniOzoneCluster

EC = "rs-3-2-4096"
LIB_DIR = Path(__file__).parent.parent / "ozone_tpu" / "native" / "libo3fs"


def _build_lib():
    return build_shared(LIB_DIR / "o3fs.c", LIB_DIR / "libo3fs.so",
                        compiler="gcc")


pytestmark = pytest.mark.skipif(_build_lib() is None,
                                reason="no native toolchain")


@pytest.fixture(scope="module")
def gw(tmp_path_factory):
    c = MiniOzoneCluster(
        tmp_path_factory.mktemp("o3fsnative"),
        num_datanodes=5,
        block_size=8 * 4096,
        container_size=4 * 1024 * 1024,
        stale_after_s=1000.0,
        dead_after_s=2000.0,
    )
    g = HttpFSGateway(c.client(), replication=EC)
    g.start()
    yield g
    g.stop()
    c.close()


@pytest.fixture(scope="module")
def lib():
    so = _build_lib()
    lib = ctypes.CDLL(str(so))
    lib.o3fsConnect.restype = ctypes.c_void_p
    lib.o3fsConnect.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.o3fsDisconnect.argtypes = [ctypes.c_void_p]
    lib.o3fsOpenFile.restype = ctypes.c_void_p
    lib.o3fsOpenFile.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_int, ctypes.c_int,
                                 ctypes.c_short, ctypes.c_int32]
    lib.o3fsCloseFile.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.o3fsWrite.restype = ctypes.c_int64
    lib.o3fsWrite.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                              ctypes.c_void_p, ctypes.c_int64]
    lib.o3fsRead.restype = ctypes.c_int64
    lib.o3fsRead.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                             ctypes.c_void_p, ctypes.c_int64]
    lib.o3fsSeek.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                             ctypes.c_int64]
    lib.o3fsCreateDirectory.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.o3fsDelete.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_int]
    lib.o3fsRename.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_char_p]
    lib.o3fsGetPathInfo.restype = ctypes.c_int64
    lib.o3fsGetPathInfo.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.POINTER(ctypes.c_int)]
    lib.o3fsExists.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    return lib


O3FS_RDONLY, O3FS_WRONLY = 1, 2


def test_c_client_roundtrip(gw, lib):
    fs = lib.o3fsConnect(b"127.0.0.1", gw.port)
    assert fs
    assert lib.o3fsCreateDirectory(fs, b"/cv/cb/dir") == 0
    isdir = ctypes.c_int(0)
    assert lib.o3fsGetPathInfo(fs, b"/cv/cb/dir", ctypes.byref(isdir)) == 0
    assert isdir.value == 1

    payload = np.random.default_rng(7).integers(
        0, 256, 200_000, dtype=np.uint8).tobytes()
    f = lib.o3fsOpenFile(fs, b"/cv/cb/dir/blob.bin", O3FS_WRONLY, 0, 0, 0)
    assert f
    # two writes exercise the client-side buffer growth
    assert lib.o3fsWrite(fs, f, payload[:70_000], 70_000) == 70_000
    n2 = len(payload) - 70_000
    assert lib.o3fsWrite(fs, f, payload[70_000:], n2) == n2
    assert lib.o3fsCloseFile(fs, f) == 0

    assert lib.o3fsGetPathInfo(fs, b"/cv/cb/dir/blob.bin", None) == \
        len(payload)
    f = lib.o3fsOpenFile(fs, b"/cv/cb/dir/blob.bin", O3FS_RDONLY, 0, 0, 0)
    assert f
    buf = ctypes.create_string_buffer(len(payload) + 10)
    got = b""
    while True:
        n = lib.o3fsRead(fs, f, buf, 65536)
        if n <= 0:
            break
        got += buf.raw[:n]
    assert got == payload
    # seek + partial re-read
    assert lib.o3fsSeek(fs, f, 100) == 0
    n = lib.o3fsRead(fs, f, buf, 16)
    assert buf.raw[:n] == payload[100:116]
    assert lib.o3fsCloseFile(fs, f) == 0

    assert lib.o3fsRename(fs, b"/cv/cb/dir/blob.bin",
                          b"/cv/cb/dir/blob2.bin") == 0
    assert lib.o3fsExists(fs, b"/cv/cb/dir/blob2.bin") == 0
    assert lib.o3fsExists(fs, b"/cv/cb/dir/blob.bin") == -1
    assert lib.o3fsDelete(fs, b"/cv/cb/dir/blob2.bin", 0) == 0
    assert lib.o3fsExists(fs, b"/cv/cb/dir/blob2.bin") == -1
    lib.o3fsDisconnect(fs)


def test_missing_file_open_fails(gw, lib):
    fs = lib.o3fsConnect(b"127.0.0.1", gw.port)
    f = lib.o3fsOpenFile(fs, b"/cv/cb/nope.bin", O3FS_RDONLY, 0, 0, 0)
    assert not f
    lib.o3fsDisconnect(fs)


def test_example_binaries(gw, lib, tmp_path):
    exdir = LIB_DIR / "examples"
    wbin, rbin = tmp_path / "o3fs_write", tmp_path / "o3fs_read"
    for src, out in ((exdir / "libo3fs_write.c", wbin),
                     (exdir / "libo3fs_read.c", rbin)):
        subprocess.run(
            ["gcc", "-O2", "-o", str(out), str(src),
             str(LIB_DIR / "o3fs.c")],
            check=True, capture_output=True, timeout=120)
    local = tmp_path / "in.bin"
    data = np.random.default_rng(8).integers(0, 256, 123_457,
                                             dtype=np.uint8).tobytes()
    local.write_bytes(data)
    r = subprocess.run(
        [str(wbin), "127.0.0.1", str(gw.port), "/cv/cb/fromc.bin",
         str(local)],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert "wrote 123457 bytes" in r.stdout
    r = subprocess.run(
        [str(rbin), "127.0.0.1", str(gw.port), "/cv/cb/fromc.bin"],
        capture_output=True, timeout=60)
    assert r.returncode == 0
    assert r.stdout == data
