"""Lifecycle subsystem tests: policy model, S3 lifecycle API, the
term-fenced sweeper (kill-9 / exactly-once regression), the batched
tiering executor, and the conflict fence."""

import urllib.error
import urllib.request

import numpy as np
import pytest

from ozone_tpu.lifecycle.policy import (
    ACTION_EXPIRE,
    ACTION_TRANSITION,
    LifecycleError,
    LifecycleRule,
    rules_from_s3_xml,
    rules_to_s3_xml,
)
from ozone_tpu.lifecycle.service import LifecycleService
from ozone_tpu.om import requests as rq
from ozone_tpu.storage.ids import BlockID, StorageError
from ozone_tpu.testing.minicluster import MiniOzoneCluster

EC = "rs-3-2-4096"


@pytest.fixture
def cluster(tmp_path):
    c = MiniOzoneCluster(
        tmp_path, num_datanodes=6, block_size=8 * 4096,
        container_size=4 * 1024 * 1024,
        stale_after_s=1000.0, dead_after_s=2000.0,
    )
    yield c
    c.close()


# ---------------------------------------------------------------- policy
def test_rule_validation():
    LifecycleRule("r", prefix="a/", age_days=3,
                  action=ACTION_TRANSITION, target=EC).validate()
    LifecycleRule("r", age_days=0, action=ACTION_EXPIRE).validate()
    with pytest.raises(LifecycleError):
        LifecycleRule("", action=ACTION_EXPIRE).validate()
    with pytest.raises(LifecycleError):
        LifecycleRule("r", action="SHRED").validate()
    with pytest.raises(LifecycleError):
        LifecycleRule("r", age_days=-1, action=ACTION_EXPIRE).validate()
    with pytest.raises(LifecycleError):
        # transition target must be an EC scheme
        LifecycleRule("r", action=ACTION_TRANSITION,
                      target="RATIS/THREE").validate()


def test_s3_xml_roundtrip_and_mapping():
    body = b"""<?xml version="1.0"?>
    <LifecycleConfiguration xmlns="http://s3.amazonaws.com/doc/2006-03-01/">
      <Rule>
        <ID>warm</ID>
        <Filter><Prefix>logs/</Prefix></Filter>
        <Status>Enabled</Status>
        <Transition><Days>30</Days>
          <StorageClass>STANDARD_IA</StorageClass></Transition>
        <Expiration><Days>90</Days></Expiration>
      </Rule>
      <Rule>
        <ID>pinned</ID>
        <Prefix>cold/</Prefix>
        <Status>Disabled</Status>
        <Transition><Days>1</Days>
          <StorageClass>rs-3-2-4096</StorageClass></Transition>
      </Rule>
    </LifecycleConfiguration>"""
    rules = rules_from_s3_xml(body, default_target="rs-6-3-1024k")
    # combined rule splits into transition + expiration
    assert [r["action"] for r in rules] == [
        ACTION_TRANSITION, ACTION_EXPIRE, ACTION_TRANSITION]
    # warm AWS storage class maps to the cluster default EC scheme; a
    # literal scheme passes through
    assert rules[0]["target"] == "rs-6-3-1024k"
    assert rules[2]["target"] == "rs-3-2-4096"
    assert rules[0]["prefix"] == "logs/" and rules[1]["age_days"] == 90
    assert rules[2]["enabled"] is False
    # render -> parse is stable
    again = rules_from_s3_xml(rules_to_s3_xml(rules),
                              default_target="rs-6-3-1024k")
    assert again == rules

    with pytest.raises(LifecycleError):
        rules_from_s3_xml(b"<LifecycleConfiguration/>")
    with pytest.raises(LifecycleError):
        rules_from_s3_xml(b"not xml at all")
    with pytest.raises(LifecycleError):  # Date schedules unsupported
        rules_from_s3_xml(
            b"<LifecycleConfiguration><Rule><ID>x</ID>"
            b"<Transition><Date>2026-01-01</Date></Transition>"
            b"</Rule></LifecycleConfiguration>")


def test_rules_persist_replicated_in_bucket_metadata(cluster):
    om = cluster.om
    om.submit(rq.CreateVolume("v"))
    om.create_bucket("v", "b", replication="RATIS/THREE")
    rules = [{"id": "r0", "prefix": "p/", "age_days": 2,
              "action": ACTION_TRANSITION, "target": EC}]
    om.set_bucket_lifecycle("v", "b", rules)
    got = om.get_bucket_lifecycle("v", "b")
    assert got[0]["prefix"] == "p/" and got[0]["target"] == EC
    # rules ride the bucket row -> they replicate + survive like any
    # bucket property
    assert om.bucket_info("v", "b")["lifecycle"] == got
    with pytest.raises(rq.OMError):
        om.set_bucket_lifecycle("v", "b", [{"id": "bad",
                                            "action": "SHRED"}])
    om.delete_bucket_lifecycle("v", "b")
    assert om.get_bucket_lifecycle("v", "b") == []
    # FSO buckets reject rules outright: the sweeper's flat prefix scan
    # can't see an id-keyed tree, and accepting the PUT would configure
    # a silent no-op the operator thinks is enforced
    om.create_bucket("v", "fso", replication="RATIS/THREE",
                     layout="FILE_SYSTEM_OPTIMIZED")
    with pytest.raises(rq.OMError) as ei:
        om.set_bucket_lifecycle("v", "fso", rules)
    assert ei.value.code == rq.INVALID_REQUEST


# ------------------------------------------------------- sweeper datapath
def _write_keys(cluster, bucket, names, size=30_000, seed=0):
    b = cluster.client().get_volume("v").get_bucket(bucket)
    rng = np.random.default_rng(seed)
    out = {}
    for name in names:
        d = rng.integers(0, 256, size, dtype=np.uint8)
        b.write_key(name, d)
        out[name] = d
    return b, out


def test_sweep_transitions_expires_and_reclaims(cluster):
    oz = cluster.client()
    oz.create_volume("v").create_bucket("b", replication="RATIS/THREE")
    b, datas = _write_keys(cluster, "b",
                           [f"cold-{i}" for i in range(4)])
    hot = np.arange(9000, dtype=np.uint64).astype(np.uint8)
    b.write_key("hot-x", hot)
    b.write_key("ttl-1", hot)
    cluster.om.set_bucket_lifecycle("v", "b", [
        {"id": "warm", "prefix": "cold-", "age_days": 0,
         "action": ACTION_TRANSITION, "target": EC},
        {"id": "ttl", "prefix": "ttl-", "age_days": 0,
         "action": ACTION_EXPIRE},
    ])
    # the old replicated blocks we expect reclaimed
    old = cluster.om.key_block_groups(
        cluster.om.lookup_key("v", "b", "cold-0"))
    svc = LifecycleService(cluster.om, clients=cluster.clients)
    stats = svc.run_once()
    assert stats["complete"] and stats["transitioned"] == 4
    assert stats["expired"] == 1 and stats["failed"] == 0
    for name, want in datas.items():
        info = cluster.om.lookup_key("v", "b", name)
        assert info["replication"] == EC
        assert np.array_equal(b.read_key(name), want)
    # untouched keys keep their replication; the expired key is gone
    assert cluster.om.lookup_key(
        "v", "b", "hot-x")["replication"].startswith("RATIS")
    with pytest.raises(rq.OMError):
        cluster.om.lookup_key("v", "b", "ttl-1")
    # old replicated blocks retire through scm/block_deletion.py — the
    # sweep queued them (post-commit only), heartbeats deliver deletes
    assert cluster.scm.deleted_blocks.pending_count() > 0
    cluster.tick(rounds=2)
    assert cluster.scm.deleted_blocks.pending_count() == 0
    g = old[0]
    bid = BlockID(g.container_id, g.local_id)
    for dn_id in g.pipeline.nodes:
        with pytest.raises(StorageError):
            cluster.clients.get(dn_id).get_block(bid)
    # a second sweep finds nothing to do (idempotent)
    stats2 = svc.run_once()
    assert stats2["transitioned"] == 0 and stats2["expired"] == 0


def test_many_keys_share_device_dispatches(cluster, monkeypatch):
    """The tentpole's batching claim: a sweep over many small keys must
    pack MANY keys per DeviceBatchPipeline submission — dispatches ~
    total_stripes / window, never one-plus per key."""
    monkeypatch.setenv("OZONE_TPU_TIER_BATCH", "8")
    oz = cluster.client()
    oz.create_volume("v").create_bucket("b", replication="RATIS/THREE")
    # 24576 bytes = exactly 2 rs-3-2-4096 stripes per key
    b, datas = _write_keys(cluster, "b",
                           [f"cold-{i}" for i in range(10)], size=24576)
    cluster.om.set_bucket_lifecycle("v", "b", [
        {"id": "warm", "prefix": "cold-", "age_days": 0,
         "action": ACTION_TRANSITION, "target": EC}])
    svc = LifecycleService(cluster.om, clients=cluster.clients)
    stats = svc.run_once()
    assert stats["transitioned"] == 10
    # 10 keys x 2 stripes = 20 stripes / window 8 -> 3 dispatches
    assert stats["dispatches"] == 3, stats
    for name, want in datas.items():
        assert np.array_equal(b.read_key(name), want)


def test_transition_conflict_fence_preserves_user_write(cluster):
    """A user overwrite racing the transition must win: the fenced
    commit loses deterministically, its EC blocks ride the deletion
    chain, and the user's bytes stay authoritative."""
    oz = cluster.client()
    oz.create_volume("v").create_bucket("b", replication="RATIS/THREE")
    b, _ = _write_keys(cluster, "b", ["cold-0"])
    newer = np.full(5000, 7, np.uint8)
    cluster.om.set_bucket_lifecycle("v", "b", [
        {"id": "warm", "prefix": "cold-", "age_days": 0,
         "action": ACTION_TRANSITION, "target": EC}])
    svc = LifecycleService(cluster.om, clients=cluster.clients)

    def overwrite(ks):
        b.write_key(ks.key, newer)

    svc.executor().pre_commit_hook = overwrite
    stats = svc.run_once()
    assert stats["conflicts"] == 1 and stats["transitioned"] == 0
    info = cluster.om.lookup_key("v", "b", "cold-0")
    assert info["replication"].startswith("RATIS")  # user version won
    assert np.array_equal(b.read_key("cold-0"), newer)
    # the abandoned EC version was routed into the purge chain; the
    # post-sweep purge pass already handed its blocks to the SCM
    # deletion log (the old replicated version stayed LIVE, so these
    # pending deletes can only be the fenced EC blocks)
    discarded = [v for _, v in cluster.om.store.iterate("deleted_keys")
                 if v.get("replication") == EC]
    assert discarded or cluster.scm.deleted_blocks.pending_count() > 0, \
        "fenced EC version must enter the deletion chain"
    cluster.tick(rounds=2)
    assert cluster.scm.deleted_blocks.pending_count() == 0


def test_kill9_term_fence_exactly_once(cluster):
    """The acceptance regression: kill -9 of the lifecycle leader
    mid-sweep neither loses nor double-applies a transition, and the
    deposed leader's late checkpoints are refused by the term fence."""
    oz = cluster.client()
    oz.create_volume("v").create_bucket("b", replication="RATIS/THREE")
    b, datas = _write_keys(cluster, "b",
                           [f"cold-{i}" for i in range(6)])
    cluster.om.set_bucket_lifecycle("v", "b", [
        {"id": "warm", "prefix": "cold-", "age_days": 0,
         "action": ACTION_TRANSITION, "target": EC}])
    # term-1 leader sweeps PART of the namespace, then is kill-9'd (its
    # in-memory state is simply abandoned — exactly what -9 leaves)
    old_leader = LifecycleService(cluster.om, clients=cluster.clients,
                                  term_fn=lambda: 1, page=2)
    stats1 = old_leader.run_once(max_keys=2)
    assert 0 < stats1["transitioned"] <= 2 and not stats1["complete"]
    assert cluster.om.lifecycle_status()["in_progress"]

    # the new leader (higher ring term) fences, resumes from the
    # replicated cursor, and finishes the sweep
    new_leader = LifecycleService(cluster.om, clients=cluster.clients,
                                  term_fn=lambda: 2, page=2)
    stats2 = new_leader.run_once()
    assert stats2["complete"]
    assert stats1["transitioned"] + stats2["transitioned"] == 6
    for name, want in datas.items():
        info = cluster.om.lookup_key("v", "b", name)
        assert info["replication"] == EC, name
        assert np.array_equal(b.read_key(name), want), name

    # the deposed leader wakes up and tries to keep sweeping: its very
    # first checkpoint is refused (LIFECYCLE_FENCED) and it applies
    # NOTHING — no transition double-applied, no cursor regression
    stats3 = old_leader.run_once()
    assert stats3.get("fenced") is True
    assert stats3["transitioned"] == 0
    with pytest.raises(rq.OMError) as ei:
        cluster.om.submit(rq.LifecycleCheckpoint(
            term=1, cursor={"bucket": "/v/b", "after": ""}))
    assert ei.value.code == rq.LIFECYCLE_FENCED
    # and the stored state still belongs to term 2, sweep complete
    st = cluster.om.lifecycle_status()
    assert st["term"] == 2 and not st["in_progress"]


def test_expire_fence_spares_concurrent_overwrite(cluster):
    """TTL expiry is fenced on the SCANNED version: a user overwrite
    racing the sweep must win, exactly like the transition fence."""
    oz = cluster.client()
    oz.create_volume("v").create_bucket("b", replication="RATIS/THREE")
    b, _ = _write_keys(cluster, "b", ["ttl-x"])
    stale_oid = cluster.om.lookup_key("v", "b", "ttl-x")["object_id"]
    fresh = np.full(4000, 9, np.uint8)
    b.write_key("ttl-x", fresh)  # user overwrite after the "scan"
    with pytest.raises(rq.OMError) as ei:
        cluster.om.submit(rq.DeleteKey("v", "b", "ttl-x",
                                       expect_object_id=stale_oid))
    assert ei.value.code == rq.KEY_MODIFIED
    assert np.array_equal(b.read_key("ttl-x"), fresh)  # data survived
    # the fresh version's own id still deletes (normal expiry)
    oid = cluster.om.lookup_key("v", "b", "ttl-x")["object_id"]
    cluster.om.submit(rq.DeleteKey("v", "b", "ttl-x",
                                   expect_object_id=oid))
    with pytest.raises(rq.OMError):
        cluster.om.lookup_key("v", "b", "ttl-x")


def test_sweep_deadline_bounds_work_and_resumes(cluster):
    oz = cluster.client()
    oz.create_volume("v").create_bucket("b", replication="RATIS/THREE")
    b, datas = _write_keys(cluster, "b",
                           [f"cold-{i}" for i in range(4)])
    cluster.om.set_bucket_lifecycle("v", "b", [
        {"id": "warm", "prefix": "cold-", "age_days": 0,
         "action": ACTION_TRANSITION, "target": EC}])
    tight = LifecycleService(cluster.om, clients=cluster.clients,
                             sweep_deadline_s=1e-6)
    stats = tight.run_once()
    assert stats.get("deadline_exceeded") is True
    assert stats["transitioned"] == 0
    # a later sweep with a sane budget finishes the job
    svc = LifecycleService(cluster.om, clients=cluster.clients)
    stats2 = svc.run_once()
    assert stats2["complete"] and stats2["transitioned"] == 4


def test_follower_never_sweeps():
    class _Om:  # the service must bail before touching anything
        def __getattr__(self, name):  # pragma: no cover
            raise AssertionError("follower touched OM state")

    svc = LifecycleService(_Om(), leader_fn=lambda: False)
    assert svc.run_once() == {"skipped": "not_leader"}


# ------------------------------------------------------------- S3 surface
def _http(method, url, data=None, headers=None):
    req = urllib.request.Request(url, data=data, method=method,
                                 headers=headers or {})
    with urllib.request.urlopen(req, timeout=30) as r:
        return r.status, r.read()


def test_s3_lifecycle_api_end_to_end(cluster):
    """Acceptance: keys written replicated under an age rule are
    transitioned to EC by the background sweeper and read back
    byte-exact THROUGH the S3 gateway; the lifecycle configuration
    round-trips over the S3 XML API."""
    from ozone_tpu.gateway.s3 import S3Gateway

    gw = S3Gateway(cluster.client(), replication="RATIS/THREE")
    gw.start()
    try:
        base = f"http://{gw.address}"
        _http("PUT", f"{base}/tierb")
        body = (b'<LifecycleConfiguration>'
                b'<Rule><ID>warm</ID><Filter><Prefix>cold/</Prefix>'
                b'</Filter><Status>Enabled</Status>'
                b'<Transition><Days>0</Days>'
                b'<StorageClass>rs-3-2-4096</StorageClass></Transition>'
                b'</Rule>'
                b'<Rule><ID>ttl</ID><Filter><Prefix>ttl/</Prefix>'
                b'</Filter><Status>Enabled</Status>'
                b'<Expiration><Days>0</Days></Expiration></Rule>'
                b'</LifecycleConfiguration>')
        status, _ = _http("PUT", f"{base}/tierb?lifecycle", data=body)
        assert status == 200
        # GET round-trips the stored rules as XML
        status, got = _http("GET", f"{base}/tierb?lifecycle")
        assert status == 200
        rt = rules_from_s3_xml(got)
        assert {r["id"] for r in rt} == {"warm", "ttl"}
        assert rt[0]["target"] == "rs-3-2-4096"

        rng = np.random.default_rng(3)
        payloads = {f"cold/{i}": rng.integers(
            0, 256, 40_000, dtype=np.uint8).tobytes() for i in range(3)}
        for k, v in payloads.items():
            _http("PUT", f"{base}/tierb/{k}", data=v)
        _http("PUT", f"{base}/tierb/ttl/x", data=b"doomed")
        _http("PUT", f"{base}/tierb/keep/x", data=b"hot stays")

        svc = LifecycleService(cluster.om, clients=cluster.clients)
        stats = svc.run_once()
        assert stats["transitioned"] == 3 and stats["expired"] == 1

        for k, v in payloads.items():
            status, got = _http("GET", f"{base}/tierb/{k}")
            assert status == 200 and got == v, k
            info = cluster.om.lookup_key("s3v", "tierb", k)
            assert info["replication"] == EC
        status, got = _http("GET", f"{base}/tierb/keep/x")
        assert got == b"hot stays"
        with pytest.raises(urllib.error.HTTPError) as ei:
            _http("GET", f"{base}/tierb/ttl/x")
        assert ei.value.code == 404
        # a ranged GET through the gateway decodes only covering cells
        status, part = _http("GET", f"{base}/tierb/cold/0",
                             headers={"Range": "bytes=100-199"})
        assert status == 206
        assert part == payloads["cold/0"][100:200]

        # DELETE clears; GET then answers NoSuchLifecycleConfiguration
        status, _ = _http("DELETE", f"{base}/tierb?lifecycle")
        assert status == 204
        with pytest.raises(urllib.error.HTTPError) as ei:
            _http("GET", f"{base}/tierb?lifecycle")
        assert ei.value.code == 404
        # malformed XML answers 400, not a 500
        with pytest.raises(urllib.error.HTTPError) as ei:
            _http("PUT", f"{base}/tierb?lifecycle", data=b"<junk")
        assert ei.value.code == 400
    finally:
        gw.stop()


def test_recon_lifecycle_endpoint(cluster):
    import json

    from ozone_tpu.recon.recon import ReconServer

    cluster.om.submit(rq.CreateVolume("v"))
    cluster.om.create_bucket("v", "b", replication="RATIS/THREE")
    cluster.om.set_bucket_lifecycle("v", "b", [
        {"id": "warm", "prefix": "", "age_days": 1,
         "action": ACTION_TRANSITION, "target": EC}])
    recon = ReconServer(cluster.om, cluster.scm)
    recon.start()
    try:
        out = json.loads(urllib.request.urlopen(
            f"http://{recon.address}/api/lifecycle", timeout=10).read())
        assert out["buckets"][0]["rules"][0]["id"] == "warm"
        assert "metrics" in out
        # the codec-service panel rides the same server (batch fill /
        # queue depth for the device's continuous batching)
        cx = json.loads(urllib.request.urlopen(
            f"http://{recon.address}/api/codec", timeout=10).read())
        if cx.get("enabled") is False:
            assert set(cx) == {"enabled"}
        elif cx.get("started") is False:
            # monitoring GET must not spawn the dispatcher itself
            assert set(cx) == {"enabled", "started"}
        else:
            for want in ("fill_ratio", "ops_per_dispatch",
                         "queue_depth", "linger_ms", "weights"):
                assert want in cx, want
        # the mesh-executor panel rides the same server (multi-chip
        # dispatch/coalescing/spill accounting); the GET must not
        # spawn the executor either
        mx = json.loads(urllib.request.urlopen(
            f"http://{recon.address}/api/mesh", timeout=10).read())
        if mx.get("enabled") is False:
            assert set(mx) == {"enabled"}
        elif mx.get("started") is False:
            assert "spill_enabled" in mx and "spill_watermark" in mx
        else:
            for want in ("fill_ratio", "ops_per_dispatch", "devices",
                         "mesh_depth", "programs", "max_inflight"):
                assert want in mx, want
        page = urllib.request.urlopen(
            f"http://{recon.address}/", timeout=10).read().decode()
        assert "Lifecycle tiering" in page and "/api/lifecycle" in page
        assert "Codec service" in page and "/api/codec" in page
        assert "Mesh executor" in page and "/api/mesh" in page
    finally:
        recon.stop()
