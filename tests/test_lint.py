"""ozlint tier-1 gate + analyzer unit tests (docs/LINT.md).

Three contracts:
1. ZERO unsuppressed findings over ozone_tpu/ — the committed baseline.
   Seeding any fixed violation back (a literal socket timeout in
   client/native_dn.py, an unfenced background DeleteKey, a jit keyed
   on an erasure pattern) fails this suite.
2. Each of the eight rules demonstrably trips on its known-bad fixture
   and stays quiet on the known-good one (tests/lint_fixtures/).
3. The CLI is fast and import-light: `python -m ozone_tpu.tools.lint
   --check` must run WITHOUT importing jax (OZONE_TPU_SKIP_JAX_PIN=1),
   so the gate costs seconds, not a jax cold start.
"""

from __future__ import annotations

import os
import subprocess
import sys
from pathlib import Path

import pytest

from ozone_tpu.tools.lint import (
    RULES,
    format_findings,
    lint_paths,
    lint_source,
    rewrite_legacy_suppressions,
)

ROOT = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).resolve().parent / "lint_fixtures"

RULE_IDS = [
    "deadline-propagation",
    "blocking-under-lock",
    "fence-carrying-commit",
    "dispatch-shape-stability",
    "error-swallowing",
    "span-on-dispatch",
    "datapath-no-copy",
    "bounded-queue",
]


# ------------------------------------------------------------ the gate
def test_zero_findings_on_tree():
    """The committed baseline: every violation in ozone_tpu/ is either
    fixed or carries an in-line `# ozlint: allow[...] -- reason`."""
    findings = lint_paths([str(ROOT / "ozone_tpu")], root=str(ROOT))
    assert not findings, format_findings(findings)


def test_dispatch_shape_stability_covers_lrc_math(tmp_path):
    """The LRC repair planner is dispatch-adjacent code: its recovery
    matrices feed the fused decode as TRACED arguments, so the shipped
    codec/lrc_math.py must stay clean under dispatch-shape-stability —
    and an lrc-flavored plan factory that jits per erasure pattern must
    still trip the rule (the scope covers the new module, not just the
    rs-era ones)."""
    findings = lint_paths(
        [str(ROOT / "ozone_tpu" / "codec" / "lrc_math.py")],
        root=str(ROOT))
    assert not [f for f in findings
                if f.rule == "dispatch-shape-stability"], \
        format_findings(findings)

    bad = tmp_path / "bad_lrc_plan.py"
    bad.write_text(
        "# ozlint: path ozone_tpu/codec/lrc_plan.py\n"
        "from functools import lru_cache\n"
        "import jax\n\n\n"
        "@lru_cache(maxsize=512)\n"
        "def lrc_repair_plan(options, erased):\n"
        "    @jax.jit\n"
        "    def fn(units):\n"
        "        return units\n\n"
        "    return fn\n")
    findings = lint_paths([str(bad)])
    assert any(f.rule == "dispatch-shape-stability" for f in findings), \
        "per-pattern jitted LRC plan factory must trip the rule"


def test_all_eight_rules_registered():
    for rid in RULE_IDS:
        assert rid in RULES, f"rule {rid} not registered"
        assert RULES[rid].summary and RULES[rid].rationale


def test_cli_check_exits_zero_without_importing_jax():
    """`--check` is the CI surface: exit 0 on the clean tree, and the
    whole run must not import jax (the <5 s budget is only possible
    import-light; OZONE_TPU_SKIP_JAX_PIN=1 bypasses the package
    __init__'s eager platform pin)."""
    proc = subprocess.run(
        [sys.executable, "-c",
         "import sys\n"
         "from ozone_tpu.tools.lint.__main__ import main\n"
         "rc = main(['--check', 'ozone_tpu'])\n"
         "assert 'jax' not in sys.modules, 'lint imported jax'\n"
         "sys.exit(rc)"],
        cwd=str(ROOT), capture_output=True, text=True, timeout=120,
        env={**os.environ, "OZONE_TPU_SKIP_JAX_PIN": "1"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert proc.stdout.strip().endswith("0 findings")


def test_cli_nonzero_on_findings_and_list_rules(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(FIXTURES.joinpath(
        "bad_error_swallowing.py").read_text())
    proc = subprocess.run(
        [sys.executable, "-m", "ozone_tpu.tools.lint", str(bad)],
        cwd=str(ROOT), capture_output=True, text=True, timeout=120,
        env={**os.environ, "OZONE_TPU_SKIP_JAX_PIN": "1"},
    )
    assert proc.returncode == 1
    assert "error-swallowing" in proc.stdout
    proc = subprocess.run(
        [sys.executable, "-m", "ozone_tpu.tools.lint", "--list-rules"],
        cwd=str(ROOT), capture_output=True, text=True, timeout=120,
        env={**os.environ, "OZONE_TPU_SKIP_JAX_PIN": "1"},
    )
    assert proc.returncode == 0
    for rid in RULE_IDS:
        assert rid in proc.stdout


# ------------------------------------------------- fixture corpus: bad
@pytest.mark.parametrize("rule", RULE_IDS)
def test_bad_fixture_trips_its_rule(rule):
    path = FIXTURES / f"bad_{rule.replace('-', '_')}.py"
    findings = lint_paths([str(path)])
    assert findings, f"{path.name} tripped nothing"
    assert {f.rule for f in findings} == {rule}, format_findings(findings)
    # each fixture packs several distinct violation shapes of its rule
    assert len(findings) >= 2, format_findings(findings)


@pytest.mark.parametrize("rule", RULE_IDS)
def test_good_fixture_is_clean(rule):
    path = FIXTURES / f"good_{rule.replace('-', '_')}.py"
    findings = lint_paths([str(path)])
    assert not findings, format_findings(findings)


# --------------------------------------------------- golden output pin
def test_finding_output_format_golden():
    """Pin the rendered finding format: `path:line: rule-id: message`.
    Tooling (editors, CI annotations) parses this shape."""
    src = (
        "# ozlint: path ozone_tpu/client/_fx.py\n"
        "import socket\n"
        "s = socket.create_connection(('h', 1), timeout=9.5)\n"
    )
    findings = lint_source(src, path="ozone_tpu/client/_fx.py")
    assert len(findings) == 1
    assert findings[0].render() == (
        "ozone_tpu/client/_fx.py:3: deadline-propagation: socket "
        "connect timeout is a numeric literal — derive it from "
        "resilience.op_timeout()/Deadline.timeout() or a documented "
        "env knob")
    assert format_findings(findings).endswith("\nozlint: 1 finding")
    assert format_findings([]).strip() == "ozlint: 0 findings"


# ----------------------------------------------- suppression semantics
def test_suppression_same_line_with_reason():
    src = ("# ozlint: path ozone_tpu/client/_fx.py\n"
           "s.settimeout(5)  # ozlint: allow[deadline-propagation]"
           " -- fixture reason\n")
    assert not lint_source(src, path="x.py")


def test_suppression_own_line_covers_next_statement():
    src = ("# ozlint: path ozone_tpu/client/_fx.py\n"
           "# ozlint: allow[deadline-propagation] -- fixture reason\n"
           "s.settimeout(\n    5)\n")
    assert not lint_source(src, path="x.py")


def test_suppression_requires_reason():
    src = ("# ozlint: path ozone_tpu/client/_fx.py\n"
           "s.settimeout(5)  # ozlint: allow[deadline-propagation]\n")
    findings = lint_source(src, path="x.py")
    assert [f.rule for f in findings] == ["suppression-format"]
    assert "missing `-- reason`" in findings[0].message


def test_suppression_unknown_rule_is_flagged():
    src = ("s = 1  # ozlint: allow[no-such-rule] -- whatever\n")
    findings = lint_source(src, path="x.py")
    assert [f.rule for f in findings] == ["suppression-format"]


def test_suppression_for_other_rule_does_not_mask():
    src = ("# ozlint: path ozone_tpu/client/_fx.py\n"
           "s.settimeout(5)  # ozlint: allow[error-swallowing]"
           " -- wrong rule\n")
    findings = lint_source(src, path="x.py")
    assert "deadline-propagation" in {f.rule for f in findings}


# ------------------------------------------ seeded-violation detection
def test_seeding_fixed_violation_back_fails(tmp_path):
    """The acceptance drill: re-introduce the PR 2 class of bug (a
    literal socket timeout in client/native_dn.py) and the analyzer
    must catch it — proving the committed baseline actually guards."""
    real = (ROOT / "ozone_tpu" / "client" / "native_dn.py").read_text()
    fenced = "timeout = resilience.op_timeout(_connect_timeout_s(), " \
             "\"connect\")"
    assert fenced in real, "native_dn connect no longer fenced?"
    seeded = real.replace(fenced, "timeout = 120.0")
    findings = lint_source(seeded, path="ozone_tpu/client/native_dn.py")
    assert any(f.rule == "deadline-propagation" for f in findings), \
        format_findings(findings)

    # and an unfenced background DeleteKey in re_encode (the PR 7 fix)
    re_enc = (ROOT / "ozone_tpu" / "client" / "re_encode.py").read_text()
    seeded = re_enc.replace(
        "om.commit_key(session, groups, writer.bytes_written)",
        "om.submit(rq.DeleteKey(volume, bucket, key))\n"
        "    om.commit_key(session, groups, writer.bytes_written)")
    findings = lint_source(seeded, path="ozone_tpu/client/re_encode.py")
    assert any(f.rule == "fence-carrying-commit" for f in findings)


# --------------------------------------------- legacy marker migration
def test_fix_suppressions_rewrites_legacy_marker(tmp_path):
    f = tmp_path / "legacy.py"
    f.write_text("# ozlint: path ozone_tpu/client/_fx.py\n"
                 "import time\n"
                 "time.sleep(d)  # resilience-lint: allow\n")
    changed = rewrite_legacy_suppressions([str(f)])
    assert changed == [str(f)]
    text = f.read_text()
    assert "resilience-lint" not in text
    assert "# ozlint: allow[deadline-propagation] -- " in text
    # the rewritten marker now suppresses the finding it used to
    assert not lint_paths([str(f)])


# ------------------------------------------------------- perf envelope
def test_analysis_is_fast_in_process():
    """The AST pass itself (imports excluded) stays comfortably inside
    the tier-1 budget: a second run over the whole tree must be cheap
    even on a loaded one-core rig."""
    import time

    t0 = time.monotonic()
    lint_paths([str(ROOT / "ozone_tpu")], root=str(ROOT))
    took = time.monotonic() - t0
    # generous load-aware ceiling: ~2.5 s quiet; scale by load like
    # test_acceptance._budget so contention doesn't flake the gate
    try:
        load = os.getloadavg()[0]
    except OSError:
        load = 1.0
    scale = min(4.0, max(1.0, load / max(1, os.cpu_count() or 1)))
    assert took < 10.0 * scale, f"lint pass took {took:.1f}s"
