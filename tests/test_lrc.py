"""Locally-repairable + wide code tests: byte-exact LRC encode/decode
vs the numpy reference over every single- and double-erasure pattern,
repair-planner read-set minimality (a local repair reads exactly
group-size units, spied at the DN clients), zero-recompile pattern
churn through the fused plan cache, a ReconstructionStorm drill over
LRC containers proving coalesced mesh dispatches still hold, storm
ordering by recoverability, lifecycle tiering to LRC targets, and wide
RS(20,4) end-to-end."""

import itertools

import numpy as np
import pytest

from tests.test_ec_pipeline import MiniEC, _read_key, _write_key
from ozone_tpu.codec import lrc_math, registry
from ozone_tpu.codec.api import CoderOptions
from ozone_tpu.codec.numpy_coder import _gf_apply

CELL = 4096
LRC = CoderOptions(12, 4, "lrc", cell_size=CELL, local_groups=2)


# ------------------------------------------------------------------ parse
def test_parse_roundtrip_and_geometry():
    o = CoderOptions.parse("lrc-12-2-2")
    assert o == CoderOptions(12, 4, "lrc", local_groups=2)
    assert o.group_size == 6 and o.global_parities == 2
    assert o.all_units == 16
    assert str(o) == "lrc-12-2-2-1m"
    assert CoderOptions.parse(str(o)) == o
    o2 = CoderOptions.parse("lrc-12-2-2-4096")
    assert o2.cell_size == 4096 and str(o2) == "lrc-12-2-2-4k"
    # wide RS parses as plain rs with a 24-unit group
    w = CoderOptions.parse("rs-20-4")
    assert (w.data_units, w.parity_units, w.local_groups) == (20, 4, 0)


def test_parse_rejects_unknown_codec_with_supported_list():
    """Satellite: "foo-6-3" must fail AT PARSE with the family list,
    not round-trip silently and explode at coder creation."""
    with pytest.raises(ValueError, match="supported families.*rs"):
        CoderOptions.parse("foo-6-3")
    with pytest.raises(ValueError, match="unknown EC codec"):
        CoderOptions.parse("foo-6-3-1024k")


def test_parse_rejects_bad_lrc_geometry():
    with pytest.raises(ValueError):
        CoderOptions.parse("lrc-12-2")  # missing r
    with pytest.raises(ValueError):
        CoderOptions.parse("lrc-12-5-2")  # 12 % 5 != 0
    with pytest.raises(ValueError):
        CoderOptions(12, 2, "lrc", local_groups=2)  # no global parity
    with pytest.raises(ValueError):
        CoderOptions(6, 3, "rs", local_groups=2)  # groups on non-lrc


# ------------------------------------------------------------- math/codec
def test_generator_shape_and_local_rows():
    pm = lrc_math.parity_matrix(LRC)
    assert pm.shape == (4, 12)
    # local rows are XOR indicators over their group
    assert np.array_equal(pm[0], np.array([1] * 6 + [0] * 6, np.uint8))
    assert np.array_equal(pm[1], np.array([0] * 6 + [1] * 6, np.uint8))
    # global rows touch every data unit with nonzero coefficients
    assert np.all(pm[2:] != 0)


def test_lrc_all_single_and_double_erasures_byte_exact():
    """Every 1- and 2-erasure pattern of LRC(12,2,2) decodes byte-exact
    against the raw generator (numpy reference backend)."""
    enc = registry.create_encoder(LRC, backend="numpy")
    dec = registry.create_decoder(LRC, backend="numpy")
    rng = np.random.default_rng(0)
    C = 64
    data = rng.integers(0, 256, (12, C), dtype=np.uint8)
    units = np.concatenate([data, enc.encode(data)], axis=0)
    n = LRC.all_units
    pats = [list(p) for r in (1, 2)
            for p in itertools.combinations(range(n), r)]
    assert len(pats) == 16 + 120
    for pat in pats:
        inputs = [None if i in pat else units[i] for i in range(n)]
        out = dec.decode(inputs, pat)
        assert np.array_equal(out, units[pat]), pat


def test_planner_classification_and_read_sets():
    n = LRC.all_units
    healthy = list(range(n))

    def plan(erased):
        return lrc_math.plan_valid(
            LRC, erased, [u for u in healthy if u not in erased])

    # single data loss: local, reads the 5 group siblings + local parity
    valid, kind = plan([2])
    assert kind == "local" and valid == [0, 1, 3, 4, 5, 12]
    # single local-parity loss: local, reads its 6 data units
    valid, kind = plan([13])
    assert kind == "local" and valid == [6, 7, 8, 9, 10, 11]
    # one loss in EACH group: still local, 6 reads per group
    valid, kind = plan([0, 7])
    assert kind == "local" and len(valid) == 12
    assert set(valid) == ({1, 2, 3, 4, 5, 12} | {6, 8, 9, 10, 11, 13})
    # two losses in ONE group: global decode
    valid, kind = plan([0, 1])
    assert kind == "global"
    # a lost global parity needs a global re-encode read
    valid, kind = plan([14])
    assert kind == "global" and len(valid) == 12
    # repair economics: any single data/local loss reads group_size
    for e in range(14):
        assert lrc_math.repair_read_units(LRC, [e]) == 6
    # unrecoverable: a whole group + its local + a global beyond r+1
    with pytest.raises(ValueError):
        plan([0, 1, 2, 3, 12, 14])


def test_recovery_rows_arbitrary_read_sets():
    """The GF solver recovers from read sets of ANY width: smaller than
    k (local), exactly k, and over-complete (redundant columns 0)."""
    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, (12, 32), dtype=np.uint8)
    units = np.concatenate(
        [data, _gf_apply(lrc_math.parity_matrix(LRC), data[None])[0]])
    # local: 6-wide
    rows = lrc_math.recovery_rows(LRC, [0, 1, 3, 4, 5, 12], [2])
    assert rows.shape == (1, 6)
    got = _gf_apply(rows, units[None, [0, 1, 3, 4, 5, 12]])[0]
    assert np.array_equal(got, units[[2]])
    # over-complete: 14 survivors for a 2-erasure, redundant cols solve 0
    valid = [u for u in range(16) if u not in (0, 13)]
    rows = lrc_math.recovery_rows(LRC, valid, [0, 13])
    got = _gf_apply(rows, units[None, valid])[0]
    assert np.array_equal(got, units[[0, 13]])


# ------------------------------------------------------------- fused path
def test_fused_lrc_encode_decode_matches_numpy(monkeypatch):
    monkeypatch.setenv("OZONE_TPU_FUSED_BACKEND", "jax")
    from ozone_tpu.codec import fused
    from ozone_tpu.utils.checksum import Checksum, ChecksumType

    opts = CoderOptions(12, 4, "lrc", cell_size=2048, local_groups=2)
    spec = fused.FusedSpec(opts, ChecksumType.CRC32C, 512)
    rng = np.random.default_rng(5)
    data = rng.integers(0, 256, (3, 12, 2048), dtype=np.uint8)
    parity, crcs = (np.asarray(x)
                    for x in fused.make_fused_encoder(spec)(data))
    assert np.array_equal(parity,
                          _gf_apply(lrc_math.parity_matrix(opts), data))
    units = np.concatenate([data, parity], axis=1)
    host = Checksum(ChecksumType.CRC32C, 512)
    for erased in ([3], [12], [14], [0, 1], [5, 15]):
        valid, _ = lrc_math.plan_valid(
            opts, erased, [u for u in range(16) if u not in erased])
        fn = fused.make_fused_decoder(spec, valid, erased)
        rec, rcrc = (np.asarray(x) for x in fn(units[:, valid]))
        assert np.array_equal(rec, units[:, erased]), erased
        got = tuple(int(v).to_bytes(4, "big") for v in rcrc[0, 0].tolist())
        assert got == host.compute(units[0, erased[0]]).checksums, erased


def test_lrc_pattern_churn_zero_recompiles(monkeypatch):
    """Acceptance: a NEW LRC erasure pattern swaps a device matrix,
    never compiles a new program — one executable per decode width
    (group_size for local repairs, k for global) serves all patterns."""
    monkeypatch.setenv("OZONE_TPU_FUSED_BACKEND", "jax")
    from ozone_tpu.codec import fused
    from ozone_tpu.utils.checksum import ChecksumType

    opts = CoderOptions(12, 4, "lrc", cell_size=1024, local_groups=2)
    spec = fused.FusedSpec(opts, ChecksumType.CRC32C, 512)
    rng = np.random.default_rng(6)
    data = rng.integers(0, 256, (2, 12, 1024), dtype=np.uint8)
    parity, _ = (np.asarray(x)
                 for x in fused.make_fused_encoder(spec)(data))
    units = np.concatenate([data, parity], axis=1)

    def run(erased):
        valid, _ = lrc_math.plan_valid(
            opts, erased, [u for u in range(16) if u not in erased])
        rec, _ = fused.make_fused_decoder(spec, valid, erased)(
            units[:, valid])
        assert np.array_equal(np.asarray(rec), units[:, erased]), erased
        return len(valid)

    # warm one local-width and one global-width program
    assert run([0]) == 6
    run([0, 1])
    before = fused.decode_jit_cache_size()
    # churn: every remaining single erasure (locals) + assorted globals
    for e in range(1, 14):
        assert run([e]) == 6
    for pat in ([2, 3], [8, 9], [14, 15], [0, 12]):
        run(list(pat))
    grew = fused.decode_jit_cache_size() - before
    assert grew == 0, (
        f"{grew} recompile(s) across LRC erasure-pattern churn — "
        "patterns must reuse the per-shape executables")


# ----------------------------------------------------------- reader/spy
def _spy_reads(clients):
    """Wrap every local DN client's chunk reads with a per-DN counter."""
    counts: dict[str, int] = {}

    def wrap(dn_id, fn):
        def spy(*a, **kw):
            counts[dn_id] = counts.get(dn_id, 0) + 1
            return fn(*a, **kw)
        return spy

    for dn_id, c in clients._local.items():
        c.read_chunk = wrap(dn_id, c.read_chunk)
        c.read_chunks = wrap(dn_id, c.read_chunks)
    return counts


def test_local_repair_reads_exactly_group_size_units(tmp_path):
    """Satellite: repairing one lost unit of LRC(12,2,2) touches exactly
    group_size datanodes — the lost unit's group siblings and its local
    parity — never the k=12 an RS repair would read."""
    opts = CoderOptions(12, 4, "lrc", cell_size=CELL, local_groups=2)
    cluster = MiniEC(tmp_path, n_dn=17, opts=opts)
    try:
        rng = np.random.default_rng(11)
        data = rng.integers(0, 256, 12 * 2 * CELL, dtype=np.uint8)
        groups = _write_key(cluster, data)
        g = groups[0]
        lost = 2  # data unit in group 0
        counts = _spy_reads(cluster.clients)
        rec = cluster.reader(g).recover_cells([lost])
        expect_dns = {g.pipeline.nodes[u]
                      for u in (0, 1, 3, 4, 5, 12)}
        assert set(counts) == expect_dns, (
            f"local repair read {sorted(counts)}, wanted exactly the "
            f"group survivors {sorted(expect_dns)}")
        assert len(counts) == opts.group_size
        # byte-exact against the unit's real content
        stripes = -(-g.length // (12 * CELL))
        want = np.zeros((stripes, CELL), np.uint8)
        flat = np.zeros(12 * stripes * CELL, np.uint8)
        flat[:data.size] = data
        cells = flat.reshape(stripes, 12, CELL)
        want = cells[:, lost, :]
        assert np.array_equal(rec[:, 0, :], want)
    finally:
        cluster.close()


def test_lrc_degraded_read_byte_exact(tmp_path):
    """Kill a data unit's node: the degraded read path must decode
    through the planner and still return the key byte-exact."""
    opts = CoderOptions(12, 4, "lrc", cell_size=CELL, local_groups=2)
    cluster = MiniEC(tmp_path, n_dn=17, opts=opts)
    try:
        rng = np.random.default_rng(13)
        data = rng.integers(0, 256, 12 * 3 * CELL + 777, dtype=np.uint8)
        groups = _write_key(cluster, data)
        from ozone_tpu.storage.ids import StorageError

        for g in groups:
            dn_id = g.pipeline.nodes[4]
            dn = next(d for d in cluster.dns if d.id == dn_id)
            try:
                dn.delete_block(g.block_id)
            except StorageError:
                pass
        got = _read_key(cluster, groups)
        assert np.array_equal(got, data)
    finally:
        cluster.close()


def test_wide_rs_write_read_and_repair(tmp_path):
    """rs-20-4: the 24-unit wide group writes, reads, and repairs a
    lost unit through the unchanged RS machinery."""
    opts = CoderOptions(20, 4, "rs", cell_size=CELL)
    cluster = MiniEC(tmp_path, n_dn=25, opts=opts)
    try:
        rng = np.random.default_rng(17)
        data = rng.integers(0, 256, 20 * 2 * CELL + 99, dtype=np.uint8)
        groups = _write_key(cluster, data)
        assert np.array_equal(_read_key(cluster, groups), data)
        g = groups[0]
        counts = _spy_reads(cluster.clients)
        cluster.reader(g).recover_cells([7])
        # RS repair reads k=20 units — the baseline LRC undercuts
        assert len(counts) == 20
    finally:
        cluster.close()


# ------------------------------------------------------------ storm drill
def test_lrc_storm_drill_coalesced_dispatches(tmp_path):
    """ReconstructionStorm over LRC containers: every container a dead
    node held repairs byte-exact AND the decode batches still coalesce
    into multi-stripe mesh dispatches (the PR 12 accounting holds for
    local-width LRC decodes)."""
    from ozone_tpu.client.reconstruction import ReconstructionStorm
    from ozone_tpu.scm.pipeline import ReplicationType
    from ozone_tpu.storage.ids import StorageError
    from ozone_tpu.testing.minicluster import MiniOzoneCluster

    cluster = MiniOzoneCluster(
        tmp_path, num_datanodes=10, container_size=100 * 1024,
        stale_after_s=1000.0, dead_after_s=2000.0)
    try:
        oz = cluster.client()
        bucket = oz.create_volume("storm").create_bucket(
            "b", replication=f"lrc-4-2-2-{CELL}")
        rng = np.random.default_rng(42)
        key_bytes = 6 * 4 * CELL  # 6 full stripes, one group per container
        for i in range(12):
            bucket.write_key(
                f"k{i}", rng.integers(0, 256, key_bytes, dtype=np.uint8))
        cluster.heartbeat_all()

        held: dict[str, list] = {}
        for c in cluster.scm.containers.containers():
            if c.replication.type is ReplicationType.EC:
                for dn_id in c.replicas:
                    held.setdefault(dn_id, []).append(c)
        victim = max(held, key=lambda d: len(held[d]))
        victim_containers = held[victim]
        assert len(victim_containers) >= 4
        victim_dn = cluster.datanode(victim)
        truth = {}
        for c in victim_containers:
            blocks = []
            for bd in victim_dn.list_blocks(c.id):
                chunks = [victim_dn.read_chunk(bd.block_id, info)
                          for info in bd.chunks]
                blocks.append((bd.block_id, chunks))
            truth[c.id] = (c.replicas[victim].replica_index, blocks)

        cluster.stop_datanode(victim)
        report = ReconstructionStorm(
            cluster.scm, cluster.clients).repair_datanode(victim)
        assert report.ok, f"storm failures: {report.failures}"
        assert report.containers_unrecoverable == 0
        # coalescing proof, same bar as the RS drill
        assert report.mesh_dispatches > 0, "storm never reached the mesh"
        assert report.mesh_stripes >= 2 * report.mesh_dispatches, (
            f"no batching: {report.mesh_stripes} stripes over "
            f"{report.mesh_dispatches} dispatches")

        for c in victim_containers:
            idx, blocks = truth[c.id]
            home = None
            for dn in cluster.datanodes:
                if dn.id == victim:
                    continue
                try:
                    rep = dn.get_container(c.id)
                except StorageError:
                    continue
                if rep.replica_index == idx:
                    home = dn
                    break
            assert home is not None, f"container {c.id} idx {idx} lost"
            for block_id, chunks in blocks:
                blk = home.get_block(block_id)
                for info, want in zip(blk.chunks, chunks):
                    got = home.read_chunk(block_id, info, verify=True)
                    assert np.array_equal(got, want)
    finally:
        cluster.close()


def test_storm_plan_orders_most_at_risk_first(tmp_path):
    """Carry-over fix: the storm plans the containers with the fewest
    surviving indexes first, so the stripes closest to data loss repair
    earliest."""
    from ozone_tpu.client.reconstruction import ReconstructionStorm
    from ozone_tpu.scm.pipeline import ReplicationType
    from ozone_tpu.testing.minicluster import MiniOzoneCluster

    cluster = MiniOzoneCluster(
        tmp_path, num_datanodes=8, container_size=100 * 1024,
        stale_after_s=1000.0, dead_after_s=2000.0)
    try:
        oz = cluster.client()
        bucket = oz.create_volume("v").create_bucket(
            "b", replication=f"rs-3-2-{CELL}")
        rng = np.random.default_rng(3)
        for i in range(6):
            bucket.write_key(
                f"k{i}", rng.integers(0, 256, 8 * 3 * CELL, dtype=np.uint8))
        cluster.heartbeat_all()

        ec = [c for c in cluster.scm.containers.containers()
              if c.replication.type is ReplicationType.EC]
        held: dict[str, list] = {}
        for c in ec:
            for dn_id in c.replicas:
                held.setdefault(dn_id, []).append(c)
        victim = max(held, key=lambda d: len(held[d]))
        victim_cs = held[victim]
        assert len(victim_cs) >= 2
        # knock one EXTRA sibling replica off one victim container: it
        # now has fewer survivors than its peers and must plan FIRST
        weakest = victim_cs[-1]
        other = next(d for d in sorted(weakest.replicas) if d != victim)
        cluster.datanode(other).delete_container(weakest.id, force=True)
        del weakest.replicas[other]
        cluster.stop_datanode(victim)

        cmds = ReconstructionStorm(
            cluster.scm, cluster.clients).plan(victim)
        assert cmds, "nothing planned"
        assert cmds[0].container_id == weakest.id, (
            "most at-risk container (fewest survivors) must repair first")
    finally:
        cluster.close()


# -------------------------------------------------------------- lifecycle
def test_lifecycle_tiering_to_lrc_target(tmp_path):
    """TRANSITION_TO_EC accepts an LRC scheme: replicated keys tier to
    lrc-4-2-2 containers through the existing TieringExecutor and read
    back byte-exact."""
    from ozone_tpu.lifecycle.service import LifecycleService
    from ozone_tpu.testing.minicluster import MiniOzoneCluster

    cluster = MiniOzoneCluster(
        tmp_path, num_datanodes=10, block_size=8 * CELL,
        container_size=4 * 1024 * 1024,
        stale_after_s=1000.0, dead_after_s=2000.0)
    try:
        oz = cluster.client()
        b = oz.create_volume("v").create_bucket(
            "b", replication="RATIS/THREE")
        rng = np.random.default_rng(23)
        datas = {}
        for i in range(2):
            d = rng.integers(0, 256, 4 * 4 * CELL + 31, dtype=np.uint8)
            b.write_key(f"cold-{i}", d)
            datas[f"cold-{i}"] = d
        cluster.om.set_bucket_lifecycle("v", "b", [
            {"id": "warm", "prefix": "cold-", "age_days": 0,
             "action": "TRANSITION_TO_EC",
             "target": f"lrc-4-2-2-{CELL}"}])
        svc = LifecycleService(cluster.om, clients=cluster.clients)
        stats = svc.run_once()
        assert stats["transitioned"] == 2, stats
        for name, want in datas.items():
            info = cluster.om.lookup_key("v", "b", name)
            assert info["replication"] == f"lrc-4-2-2-{CELL}"
            assert np.array_equal(b.read_key(name), want)
    finally:
        cluster.close()


def test_bucket_create_rejects_bad_scheme_eagerly(tmp_path):
    """The OM fails fast on a bad scheme string at bucket create and
    set-replication time — an unknown family or broken LRC geometry
    must not be stored and left to explode at first put."""
    from ozone_tpu.testing.minicluster import MiniOzoneCluster

    cluster = MiniOzoneCluster(tmp_path, num_datanodes=1)
    try:
        v = cluster.client().create_volume("v")
        with pytest.raises(ValueError, match="supported families"):
            v.create_bucket("bad", replication="zfec-6-3-4096")
        with pytest.raises(ValueError, match="local groups"):
            v.create_bucket("bad2", replication="lrc-5-2-2-4096")
        v.create_bucket("ok", replication=f"lrc-4-2-2-{CELL}")
        with pytest.raises(ValueError, match="supported families"):
            cluster.om.set_bucket_replication("v", "ok", "zfec-6-3")
    finally:
        cluster.close()
