"""Persistent mesh executor tests: program persistence, cross-operation
coalescing into full-width dispatches, depth-N in-flight buffering,
staging reuse, codec-service spill, and the `pad_batch` /
plan-cache-key edges the executor leans on."""

import threading
import time

import jax
import numpy as np
import pytest

from ozone_tpu.codec import create_encoder
from ozone_tpu.codec import service as codec_service
from ozone_tpu.codec.api import CoderOptions
from ozone_tpu.codec.fused import FusedSpec
from ozone_tpu.parallel import mesh_executor, sharded
from ozone_tpu.parallel.mesh_executor import (
    MeshExecutor,
    _MeshProgram,
)
from ozone_tpu.parallel.sharded import (
    _sharded_fused_encoder_cached,
    make_mesh,
    pad_batch,
)
from ozone_tpu.utils.checksum import ChecksumType

OPTS = CoderOptions(6, 3, "rs", cell_size=1024)
SPEC = FusedSpec(OPTS, ChecksumType.CRC32C, bytes_per_checksum=256)


@pytest.fixture
def executor():
    assert jax.device_count() == 8, "conftest must provide 8 CPU devices"
    ex = MeshExecutor(depth=2)
    yield ex
    ex.close()


# ------------------------------------------------------- pad_batch edges
def test_pad_batch_zero_rows():
    batch = np.empty((0, 6, 1024), dtype=np.uint8)
    padded, orig = pad_batch(batch, 8)
    assert orig == 0
    assert padded.shape == (0, 6, 1024)


def test_pad_batch_already_aligned():
    batch = np.arange(8 * 6 * 4, dtype=np.uint8).reshape(8, 6, 4)
    padded, orig = pad_batch(batch, 8)
    assert orig == 8
    assert padded is batch  # aligned input must not be copied


def test_pad_batch_pads_with_zeros():
    batch = np.ones((5, 2, 4), dtype=np.uint8)
    padded, orig = pad_batch(batch, 4)
    assert orig == 5 and padded.shape[0] == 8
    assert np.array_equal(padded[:5], batch)
    assert not padded[5:].any()


# ------------------------------------------------- plan cache key edges
def test_sharded_encoder_cache_isolated_across_meshes():
    """The lru_cache key includes the MESH: two meshes of different
    sizes must never share a compiled encoder (a 4-wide program fed an
    8-wide shard layout would mis-shard silently)."""
    mesh8 = make_mesh(8)
    mesh4 = make_mesh(4)
    fn8 = _sharded_fused_encoder_cached(
        OPTS, SPEC.checksum, SPEC.bytes_per_checksum, mesh8, "dn")
    fn4 = _sharded_fused_encoder_cached(
        OPTS, SPEC.checksum, SPEC.bytes_per_checksum, mesh4, "dn")
    assert fn8 is not fn4
    # same mesh object -> cache hit, the SAME long-lived program
    again = _sharded_fused_encoder_cached(
        OPTS, SPEC.checksum, SPEC.bytes_per_checksum, mesh8, "dn")
    assert again is fn8


def test_decode_program_isolated_across_patterns(executor):
    """Two erasure patterns of the same spec get distinct programs
    (pattern is part of the semantic key) and both stay resolved."""
    k1 = codec_service.decode_key(SPEC, [0, 1, 2, 3, 4, 5], [6])
    k2 = codec_service.decode_key(SPEC, [1, 2, 3, 4, 5, 6], [0])
    assert executor.accepts(k1) and executor.accepts(k2)
    assert executor._programs[k1] is not executor._programs[k2]
    assert executor.accepts_cached(k1) is True
    assert executor.accepts_cached(("decode", "never-seen")) is None


# --------------------------------------------------------- correctness
def test_executor_encode_matches_reference(executor):
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (16, 6, 1024), dtype=np.uint8)
    fut = executor.submit(codec_service.encode_key(SPEC), data, width=2)
    parity, crcs = fut.result(timeout=60)
    expect = create_encoder(OPTS, "numpy").encode(data)
    assert np.array_equal(np.asarray(parity), expect)
    assert crcs.shape[0] == 16


def test_executor_decode_matches_reference(executor):
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, (8, 6, 1024), dtype=np.uint8)
    enc = create_encoder(OPTS, "numpy")
    units = np.concatenate([data, enc.encode(data)], axis=1)
    erased = [1, 7]
    valid = [i for i in range(9) if i not in erased][:6]
    key = codec_service.decode_key(SPEC, valid, erased)
    fut = executor.submit(key, units[:, valid], width=2)
    rec, crcs = fut.result(timeout=60)
    assert np.array_equal(np.asarray(rec), units[:, erased])


def test_executor_unknown_key_raises(executor):
    with pytest.raises(KeyError):
        executor.submit(codec_service.reencode_key(SPEC, 2),
                        np.zeros((1, 6, 1024), dtype=np.uint8), width=1)
    with pytest.raises(KeyError):
        executor.pipeline(codec_service.reencode_key(SPEC, 2), width=1)


def test_warm_programs_no_new_compiles(executor, monkeypatch):
    """The zero-new-compile proof on the jitted SPMD path: steady-state
    rounds after the first dispatch must not grow the compiled-
    executable census (erasure-pattern churn included — each pattern
    compiles once, then stays warm)."""
    monkeypatch.setenv("OZONE_TPU_FUSED_BACKEND", "jax")
    rng = np.random.default_rng(2)
    enc_key = codec_service.encode_key(SPEC)
    data = rng.integers(0, 256, (8, 6, 1024), dtype=np.uint8)
    executor.submit(enc_key, data, width=1).result(timeout=120)
    assert not executor._programs[enc_key].host_twin
    warm = executor.compile_counts()
    assert warm >= 1
    for _ in range(3):
        executor.submit(enc_key, data, width=1).result(timeout=120)
    assert executor.compile_counts() == warm, \
        "steady-state dispatches recompiled the mesh program"


def test_host_twin_on_cpu(executor):
    """On CPU backends the lane resolves to the native host twin (no
    XLA program at all): same contract, zero compiles."""
    key = codec_service.encode_key(SPEC)
    assert executor.accepts(key)
    prog = executor._programs[key]
    assert prog.host_twin and prog.compile_count() == 0


# ------------------------------------------------ coalescing + depth-N
def _identity_program(delay_s: float = 0.0):
    """A synthetic lane program: returns its batch, optionally slowly —
    deterministic dispatcher-backpressure for the scheduling tests."""
    def fn(batch):
        if delay_s:
            time.sleep(delay_s)
        return (batch.copy(),)
    return _MeshProgram(fn, (), True)


def test_cross_operation_coalescing_single_dispatch(executor):
    """Submissions from many concurrent operations sharing one lane
    coalesce into ONE multi-op dispatch while the dispatcher is busy —
    the storm-shaped win over per-operation dribbles."""
    key = ("encode", "synthetic-coalesce")
    executor._programs[key] = _identity_program(delay_s=0.1)
    snap0 = mesh_executor.METRICS.snapshot()
    # occupy the dispatcher: one full-width submission dispatches
    # immediately and sleeps inside the program fn
    plug = executor.submit(key, np.zeros((8, 4), dtype=np.uint8), width=1)
    time.sleep(0.02)  # let the dispatcher pick it up
    subs = [
        executor.submit(
            key, np.full((2, 4), i, dtype=np.uint8), width=1)
        for i in range(4)
    ]
    outs = [f.result(timeout=30) for f in subs]
    plug.result(timeout=30)
    executor.quiesce()
    for i, out in enumerate(outs):
        assert np.array_equal(out[0], np.full((2, 4), i, dtype=np.uint8))
    snap1 = mesh_executor.METRICS.snapshot()
    dispatches = snap1["dispatches"] - snap0.get("dispatches", 0)
    multi = (snap1.get("multi_op_dispatches", 0)
             - snap0.get("multi_op_dispatches", 0))
    # 5 operations, 2 dispatches: the plug, then all 4 queued ops in one
    assert dispatches == 2, f"expected 2 dispatches, saw {dispatches}"
    assert multi == 1


def test_inflight_depth_reaches_window(executor):
    """Depth-N buffering: with a backlog of full batches the dispatcher
    keeps depth+1 dispatches outstanding before harvesting the oldest —
    launches never wait on pulls."""
    key = ("encode", "synthetic-depth")
    executor._programs[key] = _identity_program(delay_s=0.005)
    base = executor._max_inflight
    futs = [
        executor.submit(key, np.zeros((8, 4), dtype=np.uint8), width=1)
        for _ in range(8)
    ]
    for f in futs:
        f.result(timeout=30)
    executor.quiesce()
    assert executor._max_inflight >= executor.depth, \
        f"in-flight window never filled: {executor._max_inflight}"
    assert executor._max_inflight <= executor.depth + 1
    assert executor._max_inflight >= base


def test_staging_buffers_reused(executor):
    """Partial-batch dispatches pack into pooled staging buffers; the
    steady state recycles instead of allocating."""
    key = ("encode", "synthetic-staging")
    executor._programs[key] = _identity_program()
    snap0 = mesh_executor.METRICS.snapshot()
    for i in range(6):
        out = executor.submit(
            key, np.full((3, 4), i, dtype=np.uint8), width=1
        ).result(timeout=30)
        assert np.array_equal(out[0], np.full((3, 4), i, dtype=np.uint8))
    snap1 = mesh_executor.METRICS.snapshot()
    reuses = (snap1.get("staging_reuses", 0)
              - snap0.get("staging_reuses", 0))
    assert reuses >= 4, f"staging pool not recycling: {reuses} reuses"


def test_multi_dispatch_submission_reassembles(executor):
    """A submission wider than the lane splits across dispatches and
    reassembles in offset order."""
    key = ("encode", "synthetic-wide")
    executor._programs[key] = _identity_program()
    big = np.arange(20 * 4, dtype=np.uint8).reshape(20, 4)
    out = executor.submit(key, big, width=1).result(timeout=30)
    assert np.array_equal(out[0], big)


def test_program_error_fails_future(executor):
    key = ("encode", "synthetic-broken")

    def boom(batch):
        raise RuntimeError("kaboom")

    executor._programs[key] = _MeshProgram(boom, (), True)
    fut = executor.submit(key, np.zeros((2, 4), dtype=np.uint8), width=1)
    with pytest.raises(RuntimeError, match="kaboom"):
        fut.result(timeout=30)


def test_mesh_pipeline_contract(executor):
    """MeshPipeline mirrors ServicePipeline: submit returns the
    PREVIOUS submission's (ctx, outs); drain flushes the last."""
    pipe = executor.pipeline(codec_service.encode_key(SPEC), width=2)
    rng = np.random.default_rng(3)
    batches = [rng.integers(0, 256, (4, 6, 1024), dtype=np.uint8)
               for _ in range(3)]
    enc = create_encoder(OPTS, "numpy")
    got = []
    for i, b in enumerate(batches):
        out = pipe.submit(b, ctx=i)
        if out is not None:
            got.append(out)
    out = pipe.drain()
    if out is not None:
        got.append(out)
    assert [ctx for ctx, _ in got] == [0, 1, 2]
    for ctx, (parity, _crcs) in got:
        assert np.array_equal(np.asarray(parity),
                              enc.encode(batches[ctx]))
    assert pipe.drain() is None


def test_close_fails_pending_and_rejects_submits():
    ex = MeshExecutor(depth=1)
    key = ("encode", "synthetic-close")
    ex._programs[key] = _identity_program()
    ex.close()
    with pytest.raises(RuntimeError):
        ex.submit(key, np.zeros((1, 4), dtype=np.uint8), width=1)


# ----------------------------------------------------------- spill path
def test_service_spill_redirects_whole_lane(executor, monkeypatch):
    """Watermark-triggered overflow: with the service dispatcher pinned
    on a slow lane and the queue past the watermark, untouched lanes
    whose keys the mesh accepts move wholesale to the executor — and
    their futures still resolve bit-exactly."""
    monkeypatch.setenv("OZONE_TPU_MESH_SPILL", "1")
    monkeypatch.setenv("OZONE_TPU_MESH_SPILL_WATERMARK", "4")
    monkeypatch.setattr(mesh_executor, "_executor", executor)
    enc_key = codec_service.encode_key(SPEC)
    assert executor.accepts(enc_key)  # pre-warm: peek answers True

    rng = np.random.default_rng(4)
    datas = [rng.integers(0, 256, (1, 6, 1024), dtype=np.uint8)
             for _ in range(12)]
    release = threading.Event()

    def slow_fn(batch):
        release.wait(timeout=30)
        return (batch.copy(),)

    svc = codec_service.CodecService()
    snap0 = codec_service.METRICS.snapshot()
    msnap0 = mesh_executor.METRICS.snapshot()
    try:
        # pin the dispatcher: a full width-1 lane dispatches at once
        # and blocks inside slow_fn until released
        plug = svc.submit(("encode", "slow-plug"), slow_fn,
                          np.zeros((1, 4), dtype=np.uint8), width=1)
        time.sleep(0.05)
        futs = [svc.submit(enc_key, None, d, width=1) for d in datas]
        release.set()
        plug.result(timeout=30)
        results = [f.result(timeout=60) for f in futs]
    finally:
        release.set()
        svc.close()
    enc = create_encoder(OPTS, "numpy")
    for d, (parity, _crcs) in zip(datas, results):
        assert np.array_equal(np.asarray(parity), enc.encode(d))
    snap1 = codec_service.METRICS.snapshot()
    assert snap1.get("mesh_spill_lanes", 0) > snap0.get(
        "mesh_spill_lanes", 0)
    assert snap1.get("mesh_spill_stripes", 0) >= snap0.get(
        "mesh_spill_stripes", 0) + 8
    msnap1 = mesh_executor.METRICS.snapshot()
    assert msnap1.get("spilled_lanes", 0) > msnap0.get("spilled_lanes", 0)


def test_spill_off_by_default(executor, monkeypatch):
    """With OZONE_TPU_MESH_SPILL unset the service never redirects —
    the knob is opt-in."""
    monkeypatch.delenv("OZONE_TPU_MESH_SPILL", raising=False)
    svc = codec_service.CodecService()
    try:
        with svc._lock:
            assert svc._collect_spill_locked() == []
    finally:
        svc.close()
