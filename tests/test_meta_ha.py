"""Metadata HA across real daemon boundaries: one raft ring for OM+SCM.

Role analog of the reference's MiniOzoneHAClusterImpl suites: three
metadata replicas over real gRPC (raft RPCs on the wire), datanodes
heartbeating every replica, client failover across addresses, leader
kill mid-workload, and restart-rejoin of a deposed replica.
"""

import time

import numpy as np
import pytest

from ozone_tpu.client.dn_client import DatanodeClientFactory
from ozone_tpu.client.ozone_client import OzoneClient
from ozone_tpu.net.daemons import DatanodeDaemon, ScmOmDaemon
from ozone_tpu.net.om_service import GrpcOmClient
from ozone_tpu.net.ratis_service import RatisClientFactory
from ozone_tpu.storage.ids import StorageError

N_META = 3
EC = "rs-3-2-4096"


def _free_ports(n):
    from ozone_tpu.testing.minicluster import free_ports

    return free_ports(n)


def _make_meta(tmp_path, i, peers):
    from ozone_tpu.testing.minicluster import make_meta_daemon

    return make_meta_daemon(tmp_path, i, peers, block_size=256 * 1024)


@pytest.fixture
def ha_cluster(tmp_path):
    ports = _free_ports(N_META)
    peers = {f"m{i}": f"127.0.0.1:{ports[i]}" for i in range(N_META)}
    metas = {}
    dns = []
    try:
        for i in range(N_META):
            d = _make_meta(tmp_path, i, peers)
            d.start()
            metas[f"m{i}"] = d
        _await_leader(metas)
        scm_addrs = ",".join(peers.values())
        for i in range(5):
            d = DatanodeDaemon(tmp_path / f"dn{i}", f"dn{i}", scm_addrs,
                               heartbeat_interval_s=0.15)
            d.start()
            dns.append(d)
        yield metas, dns, peers, tmp_path
    finally:
        for d in dns:
            d.stop()
        for d in metas.values():
            d.stop()


from ozone_tpu.testing.minicluster import await_meta_leader as _await_leader  # noqa: E402


def _client(peers):
    clients = DatanodeClientFactory()
    om = GrpcOmClient(",".join(peers.values()), clients=clients)
    ratis = RatisClientFactory(address_source=clients.remote_address)
    return OzoneClient(om, clients, ratis_clients=ratis)


def test_ha_write_read_failover_and_rejoin(ha_cluster):
    metas, dns, peers, tmp_path = ha_cluster
    oz = _client(peers)
    payload = np.random.default_rng(2).integers(
        0, 256, 150_000, dtype=np.uint8).tobytes()

    oz.create_volume("v")
    b = oz.get_volume("v").create_bucket("b", replication=EC)
    b.write_key("k1", payload)
    assert b.read_key("k1").tobytes() == payload

    # every replica's OM tables converged (leader flushed; followers
    # applied the same committed entries)
    leader_id = _await_leader(metas)
    time.sleep(0.5)
    for mid, d in metas.items():
        vols = [v["name"] for v in d.om.list_volumes()]
        assert vols == ["v"], (mid, vols)

    # ---- kill the leader process-equivalent: clients fail over ----
    metas.pop(leader_id).stop()
    new_leader = _await_leader(metas, timeout=15.0)
    assert new_leader != leader_id

    b.write_key("k2", payload)
    assert b.read_key("k1").tobytes() == payload
    assert b.read_key("k2").tobytes() == payload

    # the new leader's SCM knows the pre-failover containers (decision
    # records were quorum-committed before the client ack)
    survivor = metas[new_leader]
    info = survivor.om.lookup_key("v", "b", "k1")
    for g in survivor.om.key_block_groups(info):
        assert survivor.scm.containers.get_or_none(g.container_id) \
            is not None

    # ---- restart the old leader: it rejoins as a follower and catches
    # up from the raft log / snapshot ----
    idx = int(leader_id[1:])
    revived = _make_meta(tmp_path, idx, peers)
    revived.start()
    metas[leader_id] = revived
    deadline = time.monotonic() + 15.0
    while time.monotonic() < deadline:
        vols = [v["name"] for v in revived.om.list_volumes()]
        keys = {k["name"] for k in revived.om.list_keys("v", "b")} \
            if vols == ["v"] else set()
        if keys >= {"k1", "k2"}:
            break
        time.sleep(0.1)
    assert {k["name"] for k in revived.om.list_keys("v", "b")} \
        >= {"k1", "k2"}
    # still exactly one leader
    _await_leader(metas, timeout=15.0)


def test_ha_follower_rejects_with_leader_hint(ha_cluster):
    metas, dns, peers, _ = ha_cluster
    leader_id = _await_leader(metas)
    follower_id = next(m for m in metas if m != leader_id)
    om = GrpcOmClient(peers[follower_id])
    # single-address client pointed at a follower: the error carries the
    # leader address for operators/proxies
    with pytest.raises(StorageError) as ei:
        om.create_volume("nope")
    assert ei.value.code in ("OM_NOT_LEADER", "IO_EXCEPTION")
    om.close()


def test_ha_scm_allocation_leader_gated(ha_cluster):
    """Direct block allocation on a follower must be rejected — a
    follower-local allocation would mutate state no decision record
    ever replicates."""
    from ozone_tpu.net.scm_service import GrpcScmClient

    metas, dns, peers, _ = ha_cluster
    leader_id = _await_leader(metas)
    follower_id = next(m for m in metas if m != leader_id)
    scm = GrpcScmClient(peers[follower_id])  # single follower address
    with pytest.raises(StorageError) as ei:
        scm.allocate_block("rs-3-2-4096", 4096)
    assert ei.value.code == "SCM_NOT_LEADER"
    scm.close()
    # with the full list the client follows the hint to the leader
    scm = GrpcScmClient(",".join(peers.values()))
    group, addresses = scm.allocate_block("rs-3-2-4096", 4096)
    assert group["container_id"] >= 1
    scm.close()


def test_ha_admin_ops_survive_failover(ha_cluster):
    """Operator decisions (decommission) replicate through the ring: a
    new leader must not silently forget a drain in progress."""
    from ozone_tpu.net.scm_service import GrpcScmClient

    metas, dns, peers, _ = ha_cluster
    scm = GrpcScmClient(",".join(peers.values()))
    out = scm.admin("decommission", "dn3")
    assert out["op_state"] == "DECOMMISSIONING"
    leader = _await_leader(metas)
    time.sleep(0.5)  # followers apply the replicated record
    metas.pop(leader).stop()
    new_leader = _await_leader(metas, timeout=15.0)
    # the new leader holds the committed record but applies it
    # asynchronously — poll instead of racing the apply thread
    deadline = time.monotonic() + 10.0
    state = None
    while time.monotonic() < deadline:
        node = metas[new_leader].scm.nodes.get("dn3")
        state = node.op_state.value if node else None
        if state in ("DECOMMISSIONING", "DECOMMISSIONED"):
            break
        time.sleep(0.1)
    assert state in ("DECOMMISSIONING", "DECOMMISSIONED"), state
    scm.admin("recommission", "dn3")
    scm.close()


def test_ha_om_prepare_quiesces_every_replica(ha_cluster):
    """Replicated upgrade quiesce: prepare rejects writes on the whole
    ring; cancelprepare resumes them."""
    from ozone_tpu.net.om_service import GrpcOmClient

    metas, dns, peers, _ = ha_cluster
    om = GrpcOmClient(",".join(peers.values()))
    oz_before = om.prepare()
    assert oz_before["txid"] >= 0
    time.sleep(0.5)  # followers apply the marker
    prepared = [d.om.prepared for d in metas.values()]
    assert all(prepared), prepared
    with pytest.raises(StorageError) as ei:
        om.create_volume("nope")
    assert ei.value.code == "OM_PREPARED"
    om.cancel_prepare()
    om.create_volume("resumed")
    assert any(v["name"] == "resumed"
               for d in metas.values() if d.ha.is_leader
               for v in d.om.list_volumes())
    om.close()


def test_ha_restart_does_not_reapply_flushed_entries(tmp_path):
    """Replay floor: entries flushed to the OM store before a restart are
    skipped on raft log replay (re-applying would duplicate
    non-idempotent effects)."""
    from ozone_tpu.consensus.meta_ring import MetaHARing
    from ozone_tpu.om import requests as rq
    from ozone_tpu.om.om import OzoneManager
    from ozone_tpu.scm.scm import StorageContainerManager

    def build():
        scm = StorageContainerManager(stale_after_s=1e6, dead_after_s=2e6)
        om = OzoneManager(tmp_path / "om.db", scm)
        ring = MetaHARing(om, scm, tmp_path / "raft", "m0", ["m0"])
        return om, scm, ring

    om, scm, ring = build()
    assert ring.node.start_election()
    ring.submit_om(rq.CreateVolume("v", "root"))
    ring.submit_om(rq.CreateBucket("v", "b", "rs-3-2-4096"))
    floor = ring._applied_floor
    assert floor == ring.node.last_applied > 0
    om.close()  # clean shutdown flushes the store (floor rides along)
    ring.node.stop()

    om2, scm2, ring2 = build()
    assert ring2._applied_floor == floor
    applied = []
    orig = rq.OMRequest.from_json
    rq.OMRequest.from_json = staticmethod(
        lambda d: (applied.append(d), orig(d))[1])
    try:
        assert ring2.node.start_election()  # commits + replays the log
        assert applied == [], "flushed entries were re-applied"
    finally:
        rq.OMRequest.from_json = orig
    assert [v["name"] for v in om2.list_volumes()] == ["v"]
    # new writes continue past the floor
    ring2.submit_om(rq.CreateVolume("v2", "root"))
    assert {v["name"] for v in om2.list_volumes()} == {"v", "v2"}
    om2.close()
    ring2.node.stop()


def test_ha_ratis_pipeline_write(ha_cluster):
    """RATIS/THREE through HA metadata: the leader announces the
    pipeline, datanodes join, writes ride the DN raft ring."""
    metas, dns, peers, _ = ha_cluster
    oz = _client(peers)
    payload = np.random.default_rng(4).integers(
        0, 256, 120_000, dtype=np.uint8).tobytes()
    oz.create_volume("rv")
    b = oz.get_volume("rv").create_bucket("rb", replication="RATIS/THREE")
    b.write_key("rk", payload)
    assert b.read_key("rk").tobytes() == payload
