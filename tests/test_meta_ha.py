"""Metadata HA across real daemon boundaries: one raft ring for OM+SCM.

Role analog of the reference's MiniOzoneHAClusterImpl suites: three
metadata replicas over real gRPC (raft RPCs on the wire), datanodes
heartbeating every replica, client failover across addresses, leader
kill mid-workload, and restart-rejoin of a deposed replica.
"""

import time

import numpy as np
import pytest

from ozone_tpu.client.dn_client import DatanodeClientFactory
from ozone_tpu.client.ozone_client import OzoneClient
from ozone_tpu.net.daemons import DatanodeDaemon, ScmOmDaemon
from ozone_tpu.net.om_service import GrpcOmClient
from ozone_tpu.net.ratis_service import RatisClientFactory
from ozone_tpu.storage.ids import StorageError

N_META = 3
EC = "rs-3-2-4096"


def _free_ports(n):
    from ozone_tpu.testing.minicluster import free_ports

    return free_ports(n)


def _make_meta(tmp_path, i, peers):
    from ozone_tpu.testing.minicluster import make_meta_daemon

    return make_meta_daemon(tmp_path, i, peers, block_size=256 * 1024)


@pytest.fixture
def ha_cluster(tmp_path):
    ports = _free_ports(N_META)
    peers = {f"m{i}": f"127.0.0.1:{ports[i]}" for i in range(N_META)}
    metas = {}
    dns = []
    try:
        for i in range(N_META):
            d = _make_meta(tmp_path, i, peers)
            d.start()
            metas[f"m{i}"] = d
        _await_leader(metas)
        scm_addrs = ",".join(peers.values())
        for i in range(5):
            d = DatanodeDaemon(tmp_path / f"dn{i}", f"dn{i}", scm_addrs,
                               heartbeat_interval_s=0.15)
            d.start()
            dns.append(d)
        yield metas, dns, peers, tmp_path
    finally:
        for d in dns:
            d.stop()
        for d in metas.values():
            d.stop()


from ozone_tpu.testing.minicluster import await_meta_leader as _await_leader  # noqa: E402


def _client(peers):
    clients = DatanodeClientFactory()
    om = GrpcOmClient(",".join(peers.values()), clients=clients)
    ratis = RatisClientFactory(address_source=clients.remote_address)
    return OzoneClient(om, clients, ratis_clients=ratis)


def test_restart_with_compacted_log_keeps_post_snapshot_writes(ha_cluster):
    """A replica restarting with a LOCAL compaction snapshot must not
    lose the window between the snapshot point and its sqlite state: the
    restart restores the (older) snapshot, and the replay floor must
    follow the store down — a floor captured from the pre-restore sqlite
    would skip replay of the reverted window, silently losing a
    contiguous range of ACKED keys (the round-4 soak failure)."""
    metas, dns, peers, tmp_path = ha_cluster
    oz = _client(peers)
    oz.create_volume("v")
    b = oz.get_volume("v").create_bucket("b", replication=EC)
    payload = np.random.default_rng(3).integers(
        0, 256, 5_000, dtype=np.uint8).tobytes()
    for i in range(5):
        b.write_key(f"pre-{i}", payload)

    leader_id = _await_leader(metas)
    victim_id = next(m for m in metas if m != leader_id)
    victim = metas[victim_id]
    # wait for the victim to apply the pre-keys, then compact ITS log
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        names = {k["name"] for k in victim.om.list_keys("v", "b")}
        if names >= {f"pre-{i}" for i in range(5)}:
            break
        time.sleep(0.1)
    import dataclasses

    victim.ha.node.config = dataclasses.replace(
        victim.ha.node.config, snapshot_trailing=0)
    victim.ha.node.take_snapshot()
    assert victim.ha.node.storage.snapshot_index > 0

    # acked writes PAST the victim's snapshot point
    for i in range(5):
        b.write_key(f"post-{i}", payload)
    deadline = time.monotonic() + 20.0
    while time.monotonic() < deadline:
        names = {k["name"] for k in victim.om.list_keys("v", "b")}
        if names >= {f"post-{i}" for i in range(5)}:
            break
        time.sleep(0.1)
    assert names >= {f"post-{i}" for i in range(5)}, \
        f"victim never applied the post-keys: {names}"

    # restart the victim on the same dirs: restore + log replay must
    # reproduce EVERY acked key, including the post-snapshot window
    victim.stop()
    revived = _make_meta(tmp_path, int(victim_id[1:]), peers)
    revived.start()
    metas[victim_id] = revived
    expect = ({f"pre-{i}" for i in range(5)}
              | {f"post-{i}" for i in range(5)})
    deadline = time.monotonic() + 40.0  # suite-load headroom
    names: set = set()
    while time.monotonic() < deadline:
        try:
            names = {k["name"] for k in revived.om.list_keys("v", "b")}
        except Exception:  # noqa: BLE001 - mid-catch-up/restore: retry
            names = set()
        if names >= expect:
            break
        time.sleep(0.2)
    assert names >= expect, f"lost after restart: {expect - names}"


def test_ha_write_read_failover_and_rejoin(ha_cluster):
    metas, dns, peers, tmp_path = ha_cluster
    oz = _client(peers)
    payload = np.random.default_rng(2).integers(
        0, 256, 150_000, dtype=np.uint8).tobytes()

    oz.create_volume("v")
    b = oz.get_volume("v").create_bucket("b", replication=EC)
    b.write_key("k1", payload)
    assert b.read_key("k1").tobytes() == payload

    # every replica's OM tables converged (leader flushed; followers
    # applied the same committed entries)
    leader_id = _await_leader(metas)
    time.sleep(0.5)
    for mid, d in metas.items():
        vols = [v["name"] for v in d.om.list_volumes()]
        assert vols == ["v"], (mid, vols)

    # ---- kill the leader process-equivalent: clients fail over ----
    metas.pop(leader_id).stop()
    new_leader = _await_leader(metas, timeout=15.0)
    assert new_leader != leader_id

    b.write_key("k2", payload)
    assert b.read_key("k1").tobytes() == payload
    assert b.read_key("k2").tobytes() == payload

    # the new leader's SCM knows the pre-failover containers (decision
    # records were quorum-committed before the client ack)
    survivor = metas[new_leader]
    info = survivor.om.lookup_key("v", "b", "k1")
    for g in survivor.om.key_block_groups(info):
        assert survivor.scm.containers.get_or_none(g.container_id) \
            is not None

    # ---- restart the old leader: it rejoins as a follower and catches
    # up from the raft log / snapshot ----
    idx = int(leader_id[1:])
    revived = _make_meta(tmp_path, idx, peers)
    revived.start()
    metas[leader_id] = revived
    deadline = time.monotonic() + 15.0
    while time.monotonic() < deadline:
        vols = [v["name"] for v in revived.om.list_volumes()]
        keys = {k["name"] for k in revived.om.list_keys("v", "b")} \
            if vols == ["v"] else set()
        if keys >= {"k1", "k2"}:
            break
        time.sleep(0.1)
    assert {k["name"] for k in revived.om.list_keys("v", "b")} \
        >= {"k1", "k2"}
    # still exactly one leader
    _await_leader(metas, timeout=15.0)


def test_ha_follower_rejects_with_leader_hint(ha_cluster):
    metas, dns, peers, _ = ha_cluster
    # leadership can move between resolving it and the asserted RPC
    # (elections under full-suite CPU load), so re-resolve inside a
    # retry loop and tolerate the raced round
    for attempt in range(5):
        leader_id = _await_leader(metas)
        follower_id = next(m for m in metas if m != leader_id)
        om = GrpcOmClient(peers[follower_id])
        try:
            # single-address client pointed at a follower: the error
            # carries the leader address for operators/proxies
            om.create_volume(f"nope{attempt}")
        except StorageError as e:
            assert e.code in ("OM_NOT_LEADER", "IO_EXCEPTION")
            om.close()
            return
        # no error: leadership moved onto our pick mid-race — the volume
        # was legitimately created on the (new) leader; try again
        om.close()
    raise AssertionError("leadership moved on every attempt (5x)")


def test_ha_scm_allocation_leader_gated(ha_cluster):
    """Direct block allocation on a follower must be rejected — a
    follower-local allocation would mutate state no decision record
    ever replicates."""
    from ozone_tpu.net.scm_service import GrpcScmClient

    metas, dns, peers, _ = ha_cluster
    leader_id = _await_leader(metas)
    follower_id = next(m for m in metas if m != leader_id)
    scm = GrpcScmClient(peers[follower_id])  # single follower address
    with pytest.raises(StorageError) as ei:
        scm.allocate_block("rs-3-2-4096", 4096)
    assert ei.value.code == "SCM_NOT_LEADER"
    scm.close()
    # with the full list the client follows the hint to the leader
    scm = GrpcScmClient(",".join(peers.values()))
    group, addresses = scm.allocate_block("rs-3-2-4096", 4096)
    assert group["container_id"] >= 1
    scm.close()


def test_ha_admin_ops_survive_failover(ha_cluster):
    """Operator decisions (decommission) replicate through the ring: a
    new leader must not silently forget a drain in progress."""
    from ozone_tpu.net.scm_service import GrpcScmClient

    metas, dns, peers, _ = ha_cluster
    scm = GrpcScmClient(",".join(peers.values()))
    out = scm.admin("decommission", "dn3")
    # dn3 holds no containers, so the drain monitor may complete the
    # decommission between the apply and this response under load
    assert out["op_state"] in ("DECOMMISSIONING", "DECOMMISSIONED")
    leader = _await_leader(metas)
    time.sleep(0.5)  # followers apply the replicated record
    metas.pop(leader).stop()
    new_leader = _await_leader(metas, timeout=15.0)
    # the new leader holds the committed record but applies it
    # asynchronously — poll instead of racing the apply thread
    deadline = time.monotonic() + 10.0
    state = None
    while time.monotonic() < deadline:
        node = metas[new_leader].scm.nodes.get("dn3")
        state = node.op_state.value if node else None
        if state in ("DECOMMISSIONING", "DECOMMISSIONED"):
            break
        time.sleep(0.1)
    assert state in ("DECOMMISSIONING", "DECOMMISSIONED"), state
    scm.admin("recommission", "dn3")
    scm.close()


def test_ha_om_prepare_quiesces_every_replica(ha_cluster):
    """Replicated upgrade quiesce: prepare rejects writes on the whole
    ring; cancelprepare resumes them."""
    from ozone_tpu.net.om_service import GrpcOmClient

    metas, dns, peers, _ = ha_cluster
    om = GrpcOmClient(",".join(peers.values()))
    oz_before = om.prepare()
    assert oz_before["txid"] >= 0
    time.sleep(0.5)  # followers apply the marker
    prepared = [d.om.prepared for d in metas.values()]
    assert all(prepared), prepared
    with pytest.raises(StorageError) as ei:
        om.create_volume("nope")
    assert ei.value.code == "OM_PREPARED"
    om.cancel_prepare()
    om.create_volume("resumed")
    assert any(v["name"] == "resumed"
               for d in metas.values() if d.ha.is_leader
               for v in d.om.list_volumes())
    om.close()


def test_ha_restart_does_not_reapply_flushed_entries(tmp_path):
    """Replay floor: entries flushed to the OM store before a restart are
    skipped on raft log replay (re-applying would duplicate
    non-idempotent effects)."""
    from ozone_tpu.consensus.meta_ring import MetaHARing
    from ozone_tpu.om import requests as rq
    from ozone_tpu.om.om import OzoneManager
    from ozone_tpu.scm.scm import StorageContainerManager

    def build():
        scm = StorageContainerManager(stale_after_s=1e6, dead_after_s=2e6)
        om = OzoneManager(tmp_path / "om.db", scm)
        ring = MetaHARing(om, scm, tmp_path / "raft", "m0", ["m0"])
        return om, scm, ring

    om, scm, ring = build()
    assert ring.node.start_election()
    ring.submit_om(rq.CreateVolume("v", "root"))
    ring.submit_om(rq.CreateBucket("v", "b", "rs-3-2-4096"))
    floor = ring._applied_floor
    assert floor == ring.node.last_applied > 0
    om.close()  # clean shutdown flushes the store (floor rides along)
    ring.node.stop()

    om2, scm2, ring2 = build()
    assert ring2._applied_floor == floor
    applied = []
    orig = rq.OMRequest.from_json
    rq.OMRequest.from_json = staticmethod(
        lambda d: (applied.append(d), orig(d))[1])
    try:
        assert ring2.node.start_election()  # commits + replays the log
        assert applied == [], "flushed entries were re-applied"
    finally:
        rq.OMRequest.from_json = orig
    assert [v["name"] for v in om2.list_volumes()] == ["v"]
    # new writes continue past the floor
    ring2.submit_om(rq.CreateVolume("v2", "root"))
    assert {v["name"] for v in om2.list_volumes()} == {"v", "v2"}
    om2.close()
    ring2.node.stop()


def test_ha_ratis_pipeline_write(ha_cluster):
    """RATIS/THREE through HA metadata: the leader announces the
    pipeline, datanodes join, writes ride the DN raft ring."""
    metas, dns, peers, _ = ha_cluster
    oz = _client(peers)
    payload = np.random.default_rng(4).integers(
        0, 256, 120_000, dtype=np.uint8).tobytes()
    oz.create_volume("rv")
    b = oz.get_volume("rv").create_bucket("rb", replication="RATIS/THREE")
    b.write_key("rk", payload)
    assert b.read_key("rk").tobytes() == payload


def test_ring_grows_three_to_five_under_load(tmp_path):
    import threading

    """VERDICT round-2 item 7: grow the metadata ring 3 -> 5 with the
    admin verbs while writes flow; new replicas bootstrap from the
    leader (snapshot install + log replay), converge to the same
    namespace, and the 5-ring tolerates two failures."""
    from ozone_tpu.net.scm_service import GrpcScmClient

    ports = _free_ports(5)
    peers3 = {f"m{i}": f"127.0.0.1:{ports[i]}" for i in range(3)}
    all_peers = {f"m{i}": f"127.0.0.1:{ports[i]}" for i in range(5)}
    metas, dns = {}, []
    stop = threading.Event()
    acked, write_errors = [], []
    try:
        for i in range(3):
            d = _make_meta(tmp_path, i, peers3)
            d.start()
            metas[f"m{i}"] = d
        _await_leader(metas)
        scm_addrs = ",".join(all_peers.values())
        for i in range(5):
            d = DatanodeDaemon(tmp_path / f"dn{i}", f"dn{i}", scm_addrs,
                               heartbeat_interval_s=0.15)
            d.start()
            dns.append(d)
        oz = _client(all_peers)
        oz.create_volume("v")
        bucket = oz.get_volume("v").create_bucket(
            "b", replication="rs-3-2-4096")
        payload = np.random.default_rng(5).integers(
            0, 256, 40_000, dtype=np.uint8).tobytes()

        def writer():
            n = 0
            while not stop.is_set():
                try:
                    bucket.write_key(f"k{n}", payload)
                    acked.append(f"k{n}")
                except StorageError:
                    pass
                except Exception as e:  # noqa: BLE001
                    write_errors.append(e)
                    return
                n += 1

        wt = threading.Thread(target=writer, daemon=True)
        wt.start()
        time.sleep(1.0)

        # ---- grow: start empty replicas, admit them one at a time ----
        scm_cli = GrpcScmClient(",".join(all_peers.values()))
        for i in (3, 4):
            # the joining replica knows the CURRENT ring plus itself
            joining = {**{k: v for k, v in all_peers.items()
                          if k in metas}, f"m{i}": all_peers[f"m{i}"]}
            d = _make_meta(tmp_path, i, joining)
            d.start()
            metas[f"m{i}"] = d
            out = scm_cli.admin("ring-add",
                                f"m{i}={all_peers[f'm{i}']}")
            assert f"m{i}" in out["members"]
            time.sleep(0.5)

        time.sleep(2.0)  # let the new replicas catch up under load
        stop.set()
        wt.join(timeout=10)
        assert not write_errors, write_errors[:1]
        assert len(acked) > 3

        # every replica converged to the same committed namespace
        deadline = time.time() + 20
        while time.time() < deadline:
            counts = {}
            for mid, d in metas.items():
                if d.ha.node.last_applied >= \
                        max(x.ha.node.commit_index for x in metas.values()):
                    counts[mid] = True
            if len(counts) == 5:
                break
            time.sleep(0.3)
        for mid, d in metas.items():
            assert [v["name"] for v in d.om.list_volumes()] == ["v"], mid
        assert all(len(d.ha.node.members) == 5 for d in metas.values())

        # ---- the 5-ring survives TWO failures (quorum 3) ----
        leader_id = _await_leader(metas)
        metas.pop(leader_id).stop()
        other = next(iter(metas))
        metas.pop(other).stop()
        _await_leader(metas, timeout=20.0)
        for key in acked[-2:]:
            assert bucket.read_key(key).tobytes() == payload

        # ---- shrink: retire a DEAD replica (the operator's headroom
        # restore: a 4-member ring with 3 alive commits at quorum 3) ----
        scm_cli2 = GrpcScmClient(
            ",".join(all_peers[m] for m in metas))
        out = scm_cli2.admin("ring-remove", other)
        assert other not in out["members"]
        assert all(len(d.ha.node.members) == 4 for d in metas.values())
        bucket.write_key("after-shrink", payload)
        assert bucket.read_key("after-shrink").tobytes() == payload
    finally:
        stop.set()
        for d in dns:
            d.stop()
        for d in metas.values():
            d.stop()


def test_datanodes_follow_ring_growth(tmp_path):
    """Datanodes configured with the ORIGINAL replica list must learn a
    newly added replica from heartbeat responses, register with it, and
    get it out of safemode — otherwise the new replica would be a
    zero-datanode leader candidate."""
    ports = _free_ports(4)
    peers3 = {f"m{i}": f"127.0.0.1:{ports[i]}" for i in range(3)}
    metas, dns = {}, []
    try:
        for i in range(3):
            d = _make_meta(tmp_path, i, peers3)
            d.start()
            metas[f"m{i}"] = d
        _await_leader(metas)
        # DNs know ONLY the original three replicas
        for i in range(2):
            d = DatanodeDaemon(tmp_path / f"dn{i}", f"dn{i}",
                               ",".join(peers3.values()),
                               heartbeat_interval_s=0.15)
            d.start()
            dns.append(d)
        time.sleep(0.5)

        m3_addr = f"127.0.0.1:{ports[3]}"
        joining = {**peers3, "m3": m3_addr}
        d3 = _make_meta(tmp_path, 3, joining)
        d3.start()
        metas["m3"] = d3
        from ozone_tpu.net.scm_service import GrpcScmClient

        scm_cli = GrpcScmClient(",".join(peers3.values()))
        out = scm_cli.admin("ring-add", f"m3={m3_addr}")
        assert "m3" in out["members"]

        deadline = time.time() + 15
        while time.time() < deadline:
            if (d3.scm.nodes.node_count() == 2
                    and not d3.scm.safemode.in_safemode()):
                break
            time.sleep(0.2)
        assert d3.scm.nodes.node_count() == 2, \
            "datanodes never registered with the added replica"
        assert not d3.scm.safemode.in_safemode()
        # and the DN clients now heartbeat all four replicas
        assert any(m3_addr in dn.scm.addresses for dn in dns)
    finally:
        for d in dns:
            d.stop()
        for d in metas.values():
            d.stop()


def test_failover_pool_reconciles_to_shipped_ring():
    """The client address pool adopts the full server-shipped ring:
    added replicas are dialed, retired ones are dropped (no heartbeat
    to a dead address forever), and the sticky index survives when its
    replica stays in the ring. The list mutates IN PLACE because
    GrpcScmClient aliases it."""
    from ozone_tpu.net.rpc import FailoverChannels

    pool = FailoverChannels("h0:1,h1:2,h2:3")
    alias = pool.addresses
    pool.follow_hint("h1:2")
    assert pool.current == "h1:2"
    # growth + retirement in one shipped ring
    pool.reconcile(["h1:2", "h2:3", "h3:4"])
    assert alias == ["h1:2", "h2:3", "h3:4"]  # alias still live
    assert pool.current == "h1:2"             # sticky index kept
    # current replica retired -> index resets to a live one
    pool.reconcile(["h2:3", "h3:4"])
    assert pool.current == "h2:3"
    # empty / unchanged rings are no-ops
    pool.reconcile([])
    pool.reconcile(["h3:4", "h2:3"])
    assert alias == ["h2:3", "h3:4"]


def test_ring_status_answered_by_any_replica(ha_cluster):
    """`admin ring status` (ozone admin om roles analog): every replica
    answers with its own role, a correct leader hint, and the member
    list — followers included (NOT leader-gated)."""
    from ozone_tpu.net.scm_service import GrpcScmClient

    metas, dns, peers, tmp_path = ha_cluster
    leaders = set()
    for mid, addr in peers.items():
        scm = GrpcScmClient(addr)
        # under full-suite CPU contention a replica can answer
        # UNAVAILABLE for a beat; ring-status itself is retry-safe
        st = None
        for attempt in range(20):
            try:
                st = scm.admin("ring-status")
                break
            except Exception:
                if attempt == 19:
                    raise
                time.sleep(0.25)
        assert st["replica_id"] == mid
        assert sorted(st["members"]) == sorted(peers)
        assert st["role"] in ("LEADER", "FOLLOWER")
        if st["role"] == "LEADER":
            assert st["leader"] == mid
            leaders.add(mid)
        elif st["leader"] is not None:
            leaders.add(st["leader"])
        scm.close()
    assert len(leaders) == 1, leaders


def test_ring_leadership_transfer(ha_cluster):
    """admin ring transfer (ozone admin om transfer --node analog): the
    leader hands off to the named follower and the cluster keeps
    serving writes through the new leader."""
    from ozone_tpu.net.om_service import GrpcOmClient
    from ozone_tpu.net.scm_service import GrpcScmClient

    metas, dns, peers, tmp_path = ha_cluster
    any_scm = GrpcScmClient(next(iter(peers.values())))

    # leader discovery + transfer, retrying transient suite-load flakes
    # (UNAVAILABLE, leadership moving between the status read and the
    # leader-addressed call)
    out = scm = target = None
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            leader = any_scm.admin("ring-status")["leader"]
            if leader is None:
                time.sleep(0.25)
                continue
            target = next(m for m in peers if m != leader)
            scm = GrpcScmClient(peers[leader])
            out = scm.admin("ring-transfer", target)
            break
        except StorageError:
            time.sleep(0.25)
    assert out is not None and out["transferred"] is True, out

    # the target is now the leader per ring-status (allow a beat)
    deadline = time.time() + 10
    new_leader = None
    while time.time() < deadline:
        new_leader = any_scm.admin("ring-status")["leader"]
        if new_leader == target:
            break
        time.sleep(0.2)
    assert new_leader == target

    # writes still land (failover client follows the new leader)
    om = GrpcOmClient(",".join(peers.values()))
    om.create_volume("vtransfer")
    assert any(v["name"] == "vtransfer" for v in om.list_volumes())
    scm.close()
    any_scm.close()


def test_delegation_tokens_replicate_across_ring(ha_cluster):
    """A token issued through the ring verifies on every replica and
    survives leader failover — token + master-key state rides the
    replicated OM store (the reference persists both via Raft)."""
    metas, dns, peers, tmp_path = ha_cluster
    om = GrpcOmClient(",".join(peers.values()))
    with om.user_context("alice"):
        tok = om.get_delegation_token("yarn")
    time.sleep(0.5)  # followers apply the committed entries

    # every replica's local store verifies the token identically
    for mid, d in metas.items():
        row = d.om.verify_delegation_token(tok)
        assert row["owner"] == "alice", mid

    # kill the leader; the token keeps authenticating via the new one
    leader = _await_leader(metas)
    metas.pop(leader).stop()
    _await_leader(metas, timeout=15.0)
    c = GrpcOmClient(",".join(peers.values()), token=tok)
    c.create_volume("vtok")
    vols = [v["name"] for v in c.list_volumes()]
    assert "vtok" in vols
    # renew still works post-failover (replicated row mutated)
    with om.user_context("yarn"):
        assert om.renew_delegation_token(tok) > 0
