"""End-to-end integration tests on the in-process MiniOzoneCluster:
namespace ops, EC + replicated keys, node death -> reconstruction,
replication repair, decommission, key deletion.
"""

import numpy as np
import pytest

from ozone_tpu.om.requests import OMError
from ozone_tpu.scm.node_manager import NodeState
from ozone_tpu.storage.ids import ContainerState
from ozone_tpu.testing.minicluster import MiniOzoneCluster

EC = "rs-3-2-4096"  # small cells for fast tests


@pytest.fixture
def cluster(tmp_path):
    c = MiniOzoneCluster(
        tmp_path,
        num_datanodes=7,
        block_size=4 * 4096,  # 4 stripes/group
        container_size=1024 * 1024,
        stale_after_s=1000.0,  # liveness driven manually in tests
        dead_after_s=2000.0,
    )
    yield c
    c.close()


def test_namespace_crud(cluster):
    oz = cluster.client()
    vol = oz.create_volume("vol1")
    vol.create_bucket("b1", replication=EC)
    assert [b["name"] for b in vol.list_buckets()] == ["b1"]
    with pytest.raises(OMError):
        oz.om.create_volume("vol1")
    with pytest.raises(OMError):
        oz.om.delete_volume("vol1")  # not empty
    oz.om.delete_bucket("vol1", "b1")
    oz.om.delete_volume("vol1")
    assert oz.list_volumes() == []


def test_ec_key_end_to_end(cluster):
    oz = cluster.client()
    b = oz.create_volume("v").create_bucket("b", replication=EC)
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, 50_000, dtype=np.uint8)
    b.write_key("k1", data)
    got = b.read_key("k1")
    assert np.array_equal(got, data)
    keys = b.list_keys()
    assert [k["name"] for k in keys] == ["k1"]
    assert keys[0]["size"] == 50_000


def test_replicated_key_end_to_end(cluster):
    oz = cluster.client()
    b = oz.create_volume("v").create_bucket("b", replication="RATIS/THREE")
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, 123_456, dtype=np.uint8)
    b.write_key("k", data)
    assert np.array_equal(b.read_key("k"), data)
    # kill one replica: read must fail over
    info = oz.om.lookup_key("v", "b", "k")
    dn0 = info["block_groups"][0]["nodes"][0]
    cluster.stop_datanode(dn0)
    assert np.array_equal(b.read_key("k"), data)


def test_key_rename_delete_purge(cluster):
    oz = cluster.client()
    b = oz.create_volume("v").create_bucket("b", replication=EC)
    data = np.arange(10_000, dtype=np.int64).astype(np.uint8)
    b.write_key("old", data)
    info = oz.om.lookup_key("v", "b", "old")
    groups = cluster.om.key_block_groups(info)
    b.rename_key("old", "new")
    assert np.array_equal(b.read_key("new"), data)
    with pytest.raises(OMError):
        b.read_key("old")
    b.delete_key("new")
    with pytest.raises(OMError):
        b.read_key("new")
    purged = cluster.om.run_key_deleting_service_once()
    assert purged == 1
    # deletion rides SCM heartbeat commands: tick drives the chain
    assert cluster.scm.deleted_blocks.pending_count() > 0
    cluster.tick(rounds=2)
    assert cluster.scm.deleted_blocks.pending_count() == 0
    # blocks physically gone from the datanodes
    from ozone_tpu.storage.ids import StorageError

    for g in groups:
        for dn_id in g.pipeline.nodes:
            with pytest.raises(StorageError):
                cluster.datanode(dn_id).get_block(g.block_id)


def test_node_death_triggers_reconstruction(cluster):
    oz = cluster.client()
    b = oz.create_volume("v").create_bucket("b", replication=EC)
    rng = np.random.default_rng(2)
    data = rng.integers(0, 256, 40_000, dtype=np.uint8)
    b.write_key("k", data)
    cluster.tick()  # report replicas

    info = oz.om.lookup_key("v", "b", "k")
    groups = cluster.om.key_block_groups(info)
    victim = groups[0].pipeline.nodes[1]

    # close containers first (reconstruction works on closed containers)
    for g in groups:
        for dn_id in g.pipeline.nodes:
            try:
                cluster.datanode(dn_id).close_container(g.container_id)
            except Exception:
                pass
    cluster.tick()

    # kill the victim: mark dead via the node manager clock trick
    cluster.stop_datanode(victim)
    cluster.scm.nodes.get(victim).last_heartbeat = -1e9
    cluster.scm.nodes.dead_after = 0.001
    cluster.scm.nodes.check_liveness()
    assert cluster.scm.nodes.get(victim).state is NodeState.DEAD

    cluster.tick(rounds=3)

    # replication manager must have emitted reconstruction; replicas healthy
    report = cluster.scm.replication.run_once()
    for g in groups:
        c = cluster.scm.containers.get(g.container_id)
        present = {
            r.replica_index
            for dn, r in c.replicas.items()
            if dn != victim
        }
        assert present == {1, 2, 3, 4, 5}, (g.container_id, present)
    assert not report.under_replicated

    # data still readable with the victim gone (new replicas in place)
    # repoint group nodes using SCM replica info
    for g in groups:
        c = cluster.scm.containers.get(g.container_id)
        for dn, r in c.replicas.items():
            if r.replica_index:
                g.pipeline.nodes[r.replica_index - 1] = dn
    from ozone_tpu.client.ec_reader import ECBlockGroupReader

    parts = []
    for g in groups:
        reader = ECBlockGroupReader(
            g, g.pipeline.replication.ec, cluster.clients,
            bytes_per_checksum=16 * 1024,
        )
        parts.append(reader.read_all())
    got = np.concatenate(parts)
    assert np.array_equal(got, data)


def test_safemode_blocks_allocation(tmp_path):
    c = MiniOzoneCluster(tmp_path / "c", num_datanodes=5)
    try:
        c.scm.safemode.force(True)
        oz = c.client()
        b = oz.create_volume("v").create_bucket("b", replication=EC)
        with pytest.raises(Exception):
            b.write_key("k", np.zeros(10, np.uint8))
        c.scm.safemode.force(None)
        b.write_key("k", np.zeros(10, np.uint8))
    finally:
        c.close()


def test_om_restart_preserves_metadata(tmp_path):
    c = MiniOzoneCluster(tmp_path / "c", num_datanodes=5)
    data = np.arange(5000, dtype=np.int32).astype(np.uint8)
    try:
        oz = c.client()
        b = oz.create_volume("v").create_bucket("b", replication=EC)
        b.write_key("k", data)
    finally:
        c.om.close()
    # reopen OM store on same path
    from ozone_tpu.om.om import OzoneManager

    om2 = OzoneManager(c.root / "om" / "om.db", c.scm, clients=c.clients)
    try:
        info = om2.lookup_key("v", "b", "k")
        assert info["size"] == data.size
        from ozone_tpu.client.ozone_client import OzoneClient

        oz2 = OzoneClient(om2, c.clients)
        assert np.array_equal(
            oz2.get_volume("v").get_bucket("b").read_key("k"), data
        )
    finally:
        om2.close()
        c.scm.stop()
        for dn in c.datanodes:
            dn.close()


def test_mini_ha_cluster_failover_roundtrip(tmp_path):
    """MiniOzoneHACluster (MiniOzoneHAClusterImpl analog): boot, write,
    kill the leader, write again, revive, converge."""
    import numpy as np

    from ozone_tpu.testing.minicluster import MiniOzoneHACluster

    cluster = MiniOzoneHACluster(tmp_path, num_meta=3, num_datanodes=5)
    try:
        oz = cluster.client()
        payload = np.random.default_rng(1).integers(
            0, 256, 100_000, dtype=np.uint8).tobytes()
        oz.create_volume("v")
        b = oz.get_volume("v").create_bucket("b",
                                             replication="rs-3-2-4096")
        b.write_key("k1", payload)
        leader = cluster.await_leader()
        cluster.stop_meta(leader)
        b.write_key("k2", payload)
        assert b.read_key("k1").tobytes() == payload
        cluster.revive_meta(leader)
        cluster.await_leader()
        assert b.read_key("k2").tobytes() == payload
    finally:
        cluster.shutdown()
