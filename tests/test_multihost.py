"""Multi-host mesh path: two OS processes × four virtual CPU devices
form ONE 8-device global mesh through `jax.distributed` (the comm-
backend bootstrap the reference does with Ratis/gRPC fan-out and HPC
stacks do with NCCL/MPI init), run the SAME sharded fused encoder the
single-host tests use, and prove a cross-process collective executes.

This is the proof that parallel/sharded.py is topology-agnostic: on a
real multi-host TPU slice only `multihost.initialize` changes.
"""

import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent

_WORKER = r"""
import os, sys
port, pid = sys.argv[1], int(sys.argv[2])
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, sys.argv[3])
from ozone_tpu.parallel import multihost
multihost.initialize(f"127.0.0.1:{port}", 2, pid, local_device_count=4)
import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

assert len(jax.devices()) == 8, jax.devices()
assert jax.process_count() == 2
assert len(jax.local_devices()) == 4

from ozone_tpu.codec import create_encoder
from ozone_tpu.codec.api import CoderOptions
from ozone_tpu.codec.fused import FusedSpec
from ozone_tpu.parallel import sharded
from ozone_tpu.utils.checksum import ChecksumType

opts = CoderOptions(3, 2, "rs", cell_size=1024)
spec = FusedSpec(opts, ChecksumType.CRC32C, 1024)
mesh = multihost.global_codec_mesh()
fn = sharded.make_sharded_fused_encoder(spec, mesh)

rng = np.random.default_rng(0)  # same seed both processes: shared view
batch = rng.integers(0, 256, (8, 3, 1024), dtype=np.uint8)
sh = NamedSharding(mesh, P("dn"))
local = batch[pid * 4:(pid + 1) * 4]
garr = jax.make_array_from_process_local_data(
    sh, local, global_shape=batch.shape)
parity, crcs = fn(garr)

# every process checks ITS addressable output shards bit-exactly
# against the single-host numpy coder
ref = create_encoder(opts, "numpy").encode(batch)
checked = 0
for shard in parity.addressable_shards:
    i0 = shard.index[0].start or 0
    got = np.asarray(shard.data)
    assert np.array_equal(got, ref[i0:i0 + got.shape[0]]), \
        f"proc {pid}: parity shard at {i0} mismatches host coder"
    checked += got.shape[0]
assert checked == 4, checked

# a collective that MUST cross the process boundary: psum over the
# hybrid (dcn, dn) mesh's both axes
from jax.experimental.shard_map import shard_map

h = multihost.hybrid_codec_mesh()
assert h.devices.shape == (2, 4)
hs = NamedSharding(h, P(("dcn", "dn")))
ones = jax.make_array_from_process_local_data(
    hs, np.full(4, pid + 1, np.float32), global_shape=(8,))
summed = shard_map(
    lambda x: jax.lax.psum(x, ("dcn", "dn")),
    mesh=h, in_specs=P(("dcn", "dn")), out_specs=P())(ones)
# proc0 contributes 4x1, proc1 4x2 -> 12; replicated everywhere
got = float(np.asarray(summed.addressable_shards[0].data).ravel()[0])
assert got == 12.0, got
print(f"WORKER_OK {pid}")
"""


#: the exact jaxlib error marking the known capability gap (the CPU
#: client rejects cross-process computations); anything else is a real
#: failure and must stay red
_CPU_MULTIPROC_UNSUPPORTED = (
    "Multiprocess computations aren't implemented on the CPU backend")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_global_mesh(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    port = _free_port()
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("XLA_FLAGS", None)  # the worker sets its own device count
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(port), str(i), str(REPO)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=str(REPO),
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=180)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode != 0 and _CPU_MULTIPROC_UNSUPPORTED in out:
            # known jaxlib capability gap, not a mesh-code regression:
            # this jaxlib's CPU client refuses cross-process XLA
            # computations outright (see KNOWN_ISSUES.md). The sharding
            # semantics stay covered by the single-process 8-device
            # suite; only the cross-process transport leg skips.
            pytest.skip(
                "jaxlib cannot run multiprocess computations on the "
                "CPU backend — cross-process leg requires a real "
                "accelerator runtime")
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"WORKER_OK {i}" in out, out
