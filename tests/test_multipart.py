"""Multipart upload at the client/OM level + cleanup services.

Mirrors the reference's MPU test surface (TestMultipartUpload*,
S3MultipartUpload* request tests): part write/replace/stitch semantics,
orphan-part and overwrite purging, abort, expiry services."""

import numpy as np
import pytest

from ozone_tpu.om.requests import OMError
from ozone_tpu.testing.minicluster import MiniOzoneCluster

EC = "rs-3-2-4096"


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    c = MiniOzoneCluster(
        tmp_path_factory.mktemp("mpu"),
        num_datanodes=5,
        block_size=8 * 4096,
        container_size=4 * 1024 * 1024,
        stale_after_s=1000.0,
        dead_after_s=2000.0,
    )
    yield c
    c.close()


@pytest.fixture(scope="module")
def bucket(cluster):
    oz = cluster.client()
    return oz.create_volume("mpuvol").create_bucket("b", replication=EC)


def _data(seed, n):
    return np.random.default_rng(seed).integers(0, 256, n, dtype=np.uint8)


def test_mpu_roundtrip(bucket):
    mpu = bucket.initiate_multipart_upload("big")
    parts = [_data(i, 9000 + i * 100) for i in range(3)]
    for i, p in enumerate(parts, start=1):
        mpu.write_part(i, p)
    assert len(mpu.list_parts()) == 3
    info = mpu.complete()
    assert info["size"] == sum(p.size for p in parts)
    got = bucket.read_key("big")
    np.testing.assert_array_equal(got, np.concatenate(parts))
    # upload record removed
    with pytest.raises(OMError):
        bucket.client.om.multipart_info("mpuvol", "b", "big", mpu.upload_id)


def test_mpu_part_replace_releases_blocks(bucket):
    om = bucket.client.om
    before = len(list(om.store.iterate("deleted_keys")))
    mpu = bucket.initiate_multipart_upload("replace")
    mpu.write_part(1, _data(10, 8000))
    mpu.write_part(1, _data(11, 8000))  # replaces; old blocks purged
    assert len(list(om.store.iterate("deleted_keys"))) == before + 1
    info = mpu.complete()
    assert info["size"] == 8000
    np.testing.assert_array_equal(bucket.read_key("replace"), _data(11, 8000))


def test_mpu_complete_subset_purges_orphans(bucket):
    om = bucket.client.om
    mpu = bucket.initiate_multipart_upload("subset")
    mpu.write_part(1, _data(20, 5000))
    mpu.write_part(2, _data(21, 5000))
    before = len(list(om.store.iterate("deleted_keys")))
    mpu.complete([{"part_number": 1, "etag": mpu._etags[1]}])
    # part 2 was uploaded but not listed: its blocks must reach the chain
    assert len(list(om.store.iterate("deleted_keys"))) == before + 1
    assert bucket.read_key("subset").size == 5000


def test_mpu_invalid_part_order(bucket):
    mpu = bucket.initiate_multipart_upload("bad")
    mpu.write_part(1, _data(30, 4096))
    mpu.write_part(2, _data(31, 4096))
    with pytest.raises(OMError):
        mpu.complete([
            {"part_number": 2, "etag": mpu._etags[2]},
            {"part_number": 1, "etag": mpu._etags[1]},
        ])
    mpu.abort()


def test_mpu_abort_purges_parts(bucket):
    om = bucket.client.om
    mpu = bucket.initiate_multipart_upload("gone")
    mpu.write_part(1, _data(40, 6000))
    before = len(list(om.store.iterate("deleted_keys")))
    mpu.abort()
    assert len(list(om.store.iterate("deleted_keys"))) == before + 1
    with pytest.raises(OMError):
        mpu.list_parts()


def test_mpu_overwrite_existing_key_purges_old(bucket):
    om = bucket.client.om
    bucket.write_key("victim", _data(50, 7000))
    mpu = bucket.initiate_multipart_upload("victim")
    mpu.write_part(1, _data(51, 3000))
    before = len(list(om.store.iterate("deleted_keys")))
    mpu.complete()
    assert len(list(om.store.iterate("deleted_keys"))) == before + 1
    assert bucket.read_key("victim").size == 3000


def test_mpu_cleanup_service_aborts_expired(bucket):
    om = bucket.client.om
    mpu = bucket.initiate_multipart_upload("stale")
    mpu.write_part(1, _data(60, 2000))
    assert om.run_mpu_cleanup_once(max_age_s=0.0) >= 1
    with pytest.raises(OMError):
        om.multipart_info("mpuvol", "b", "stale", mpu.upload_id)
    # fresh uploads survive
    keep = bucket.initiate_multipart_upload("fresh")
    assert om.run_mpu_cleanup_once(max_age_s=3600.0) == 0
    keep.abort()


def test_open_key_cleanup_service(bucket):
    om = bucket.client.om
    om.open_key("mpuvol", "b", "never-committed")
    assert om.run_open_key_cleanup_once(max_age_s=0.0) >= 1
    assert om.run_open_key_cleanup_once(max_age_s=0.0) == 0


def test_mpu_list_uploads(bucket):
    om = bucket.client.om
    a = bucket.initiate_multipart_upload("list/x")
    b = bucket.initiate_multipart_upload("list/y")
    names = {m["name"] for m in om.list_multipart_uploads("mpuvol", "b",
                                                          prefix="list/")}
    assert names == {"list/x", "list/y"}
    a.abort()
    b.abort()
