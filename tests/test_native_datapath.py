"""Native C++ datapath: protocol, parity with the gRPC verbs, checksum
enforcement, fences, tokens, and fallback behavior.

The sidecar (native/datapath.cpp + storage/fast_datapath.py) must be
semantically indistinguishable from the gRPC bulk verbs — same
file-per-block layout, same fence/token/layout gates, same
CHECKSUM_MISMATCH + unhealthy-container behavior — while moving the
per-chunk work out of the interpreter (reference analog:
GrpcXceiverService.java:42 native-epoll transport + ChunkUtils.java
mapped IO)."""

from __future__ import annotations

import numpy as np
import pytest

from ozone_tpu.net.dn_service import DatanodeGrpcService
from ozone_tpu.net.rpc import RpcServer
from ozone_tpu.client.native_dn import NativeDatanodeClient
from ozone_tpu.storage.datanode import Datanode
from ozone_tpu.storage.fast_datapath import DatapathSidecar, load_lib
from ozone_tpu.storage.ids import (
    BlockData,
    BlockID,
    ChunkInfo,
    StorageError,
)
from ozone_tpu.utils.checksum import Checksum, ChecksumType

pytestmark = pytest.mark.skipif(load_lib() is None,
                                reason="no native toolchain")


@pytest.fixture()
def cluster(tmp_path):
    """One datanode served by gRPC + the native sidecar, like the
    daemon wires them (minus SCM)."""
    dn = Datanode(tmp_path / "dn", dn_id="dn0")
    dn.create_container(1)
    server = RpcServer()
    sidecar = DatapathSidecar(dn)
    port = sidecar.start()
    assert port is not None
    DatanodeGrpcService(dn, server,
                        datapath_port=sidecar.advertise)
    server.start()
    client = NativeDatanodeClient("dn0", server.address)
    yield dn, client, sidecar
    client.close()
    sidecar.stop()
    server.stop()
    dn.close()


def _payload(seed: int, n: int = 256 * 1024) -> np.ndarray:
    return np.random.default_rng(seed).integers(0, 256, n, dtype=np.uint8)


def test_native_write_read_roundtrip(cluster):
    dn, client, _ = cluster
    assert client._native_port() is not None
    data = _payload(1)
    cs = Checksum(ChecksumType.CRC32C, 16 * 1024).compute(data)
    bid = BlockID(1, 1)
    infos = [ChunkInfo(f"c{j}", j * data.size, data.size, cs)
             for j in range(3)]
    client.write_chunks_commit(
        bid, [(i, data) for i in infos],
        commit=BlockData(bid, infos), sync=True)
    # committed through the Python control plane
    bd = dn.get_block(bid)
    assert [c.name for c in bd.chunks] == ["c0", "c1", "c2"]
    # read back through the native path, with CRC verification
    out = client.read_chunks(bid, infos, verify=True)
    assert len(out) == 3
    for arr in out:
        np.testing.assert_array_equal(arr, data)
    # single-chunk verbs ride the same path
    one = client.read_chunk(bid, infos[1], verify=True)
    np.testing.assert_array_equal(one, data)
    assert dn.metrics.counter("batched_write_streams").value >= 1
    assert dn.metrics.counter("batched_read_streams").value >= 1


def test_native_matches_grpc_bytes(cluster, tmp_path):
    """Bytes written natively and via gRPC land identically (same
    layout, same offsets), and either transport reads the other's."""
    dn, client, _ = cluster
    data = _payload(2, 64 * 1024)
    cs = Checksum(ChecksumType.CRC32C, 16 * 1024).compute(data)
    b_native = BlockID(1, 10)
    b_grpc = BlockID(1, 11)
    info = ChunkInfo("c0", 0, data.size, cs)
    client.write_chunk(b_native, info, data)
    # force the gRPC path for the twin write
    super(NativeDatanodeClient, client).write_chunk(b_grpc, info, data)
    f_native = dn.get_container(1).chunks.block_path(b_native)
    f_grpc = dn.get_container(1).chunks.block_path(b_grpc)
    assert f_native.read_bytes() == f_grpc.read_bytes()
    # cross-transport read
    got = super(NativeDatanodeClient, client).read_chunk(
        b_native, info, verify=True)
    np.testing.assert_array_equal(got, data)


def test_native_read_checksum_mismatch_marks_unhealthy(cluster):
    dn, client, _ = cluster
    data = _payload(3, 32 * 1024)
    cs = Checksum(ChecksumType.CRC32C, 16 * 1024).compute(data)
    bid = BlockID(1, 20)
    info = ChunkInfo("c0", 0, data.size, cs)
    client.write_chunk(bid, info, data)
    # corrupt on disk behind the store's back
    path = dn.get_container(1).chunks.block_path(bid)
    raw = bytearray(path.read_bytes())
    raw[100] ^= 0xFF
    path.write_bytes(bytes(raw))
    with pytest.raises(StorageError) as ei:
        client.read_chunk(bid, info, verify=True)
    assert ei.value.code == "CHECKSUM_MISMATCH"
    assert dn.get_container(1).state.value == "UNHEALTHY"
    assert dn.metrics.counter("checksum_failures").value == 1
    # verify=False still serves the bytes (scrub decides health)


def test_native_write_fence(cluster):
    """The single-writer fence holds across the native path: a second
    writer streaming into an owned block is refused before any byte
    lands (BLOCK_WRITE_CONFLICT, same as the gRPC verbs)."""
    dn, client, _ = cluster
    data = _payload(4, 16 * 1024)
    cs = Checksum(ChecksumType.CRC32C, 16 * 1024).compute(data)
    bid = BlockID(1, 30)
    info = ChunkInfo("c0", 0, data.size, cs)
    client.write_chunks_commit(bid, [(info, data)], writer="w1")
    with pytest.raises(StorageError) as ei:
        client.write_chunks_commit(bid, [(info, data)], writer="w2")
    assert ei.value.code == "BLOCK_WRITE_CONFLICT"
    assert dn.metrics.counter("write_fence_violations").value == 1


def test_native_commit_id_mismatch_refused(cluster):
    dn, client, _ = cluster
    data = _payload(5, 4096)
    cs = Checksum(ChecksumType.CRC32C, 16 * 1024).compute(data)
    bid = BlockID(1, 40)
    info = ChunkInfo("c0", 0, data.size, cs)
    with pytest.raises(StorageError) as ei:
        client.write_chunks_commit(
            bid, [(info, data)],
            commit=BlockData(BlockID(1, 41), [info]))
    assert ei.value.code == "INVALID_ARGUMENT"


def test_native_missing_container(cluster):
    _, client, _ = cluster
    data = _payload(6, 4096)
    info = ChunkInfo("c0", 0, data.size,
                     Checksum(ChecksumType.CRC32C).compute(data))
    with pytest.raises(StorageError) as ei:
        client.write_chunks_commit(BlockID(999, 1), [(info, data)])
    assert ei.value.code == "CONTAINER_NOT_FOUND"
    # the connection survives an early refusal (drain-to-END protocol)
    bid = BlockID(1, 50)
    client.write_chunks_commit(bid, [(info, data)],
                               commit=BlockData(bid, [info]))


def test_fallback_when_no_sidecar(tmp_path):
    """A server without a native listener serves everything over gRPC
    through the same client."""
    dn = Datanode(tmp_path / "dn", dn_id="dn0")
    dn.create_container(1)
    server = RpcServer()
    DatanodeGrpcService(dn, server)  # no datapath_port provider
    server.start()
    client = NativeDatanodeClient("dn0", server.address)
    try:
        assert client._native_port() is None
        data = _payload(7, 8192)
        cs = Checksum(ChecksumType.CRC32C, 16 * 1024).compute(data)
        bid = BlockID(1, 1)
        info = ChunkInfo("c0", 0, data.size, cs)
        client.write_chunks_commit(bid, [(info, data)],
                                   commit=BlockData(bid, [info]))
        got = client.read_chunk(bid, info, verify=True)
        np.testing.assert_array_equal(got, data)
    finally:
        client.close()
        server.stop()
        dn.close()


def test_native_block_tokens_enforced(tmp_path):
    """Token enforcement holds on the native path: no token -> refused,
    OM-granted token -> served (BlockTokenVerifier parity)."""
    from ozone_tpu.client.dn_client import TokenStore
    from ozone_tpu.utils.security import (
        AccessMode,
        BlockTokenIssuer,
        BlockTokenVerifier,
        SecretKeyManager,
    )

    secrets = SecretKeyManager()
    verifier = BlockTokenVerifier(secrets, enabled=True)
    issuer = BlockTokenIssuer(secrets)
    dn = Datanode(tmp_path / "dn", dn_id="dn0")
    dn.create_container(1)
    server = RpcServer()
    sidecar = DatapathSidecar(dn, verifier=verifier)
    assert sidecar.start() is not None
    DatanodeGrpcService(dn, server, verifier=verifier,
                        datapath_port=sidecar.advertise)
    server.start()
    data = _payload(8, 4096)
    cs = Checksum(ChecksumType.CRC32C, 16 * 1024).compute(data)
    bid = BlockID(1, 1)
    info = ChunkInfo("c0", 0, data.size, cs)

    bare = NativeDatanodeClient("dn0", server.address)
    tokens = TokenStore()
    tokens.put_block_token(
        bid, issuer.issue(bid, [AccessMode.READ, AccessMode.WRITE],
                          owner="u"))
    authed = NativeDatanodeClient("dn0", server.address, tokens=tokens)
    try:
        with pytest.raises(StorageError) as ei:
            bare.write_chunks_commit(bid, [(info, data)])
        assert ei.value.code == "BLOCK_TOKEN_VERIFICATION_FAILED"
        authed.write_chunks_commit(bid, [(info, data)],
                                   commit=BlockData(bid, [info]))
        got = authed.read_chunk(bid, info, verify=True)
        np.testing.assert_array_equal(got, data)
    finally:
        bare.close()
        authed.close()
        sidecar.stop()
        server.stop()
        dn.close()


def test_native_partition_rules_apply(cluster):
    """Chaos rules keyed on the gRPC address cover the native path."""
    from ozone_tpu.net import partition

    dn, client, _ = cluster
    data = _payload(9, 4096)
    info = ChunkInfo("c0", 0, data.size,
                     Checksum(ChecksumType.CRC32C).compute(data))
    partition.block(client.address)
    try:
        with pytest.raises(StorageError) as ei:
            client.write_chunks_commit(BlockID(1, 60), [(info, data)])
        assert ei.value.code == "UNAVAILABLE"
    finally:
        partition.clear()
    bid = BlockID(1, 60)
    client.write_chunks_commit(bid, [(info, data)],
                               commit=BlockData(bid, [info]))
