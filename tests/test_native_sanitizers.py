"""Sanitizer builds of the native coder (the TPU-build substitute for
JVM-land's lack of native race detection — SURVEY.md §5: "C++ pieces
should get TSan/ASan in tests"): the selftest driver exercises every
exported entry point, including the multithreaded batch path, under
AddressSanitizer+UBSan and ThreadSanitizer. Any sanitizer finding aborts
the binary and fails the test.
"""

import shutil
import subprocess
from pathlib import Path

import pytest

NATIVE = Path(__file__).parent.parent / "ozone_tpu" / "native"
SRC = [str(NATIVE / "gf_coder.cpp"), str(NATIVE / "gf_coder_selftest.cpp")]


def _have_gxx() -> bool:
    return shutil.which("g++") is not None


def _build_and_run(tmp_path, label, san_flags):
    exe = tmp_path / f"selftest-{label}"

    def compile_with(flags, out):
        return subprocess.run(
            ["g++", "-O1", "-g", "-march=native",
             "-fno-omit-frame-pointer", *flags, "-o", str(out), *SRC,
             "-lpthread"],
            capture_output=True, text=True, timeout=180,
        )

    build = compile_with(san_flags, exe)
    if build.returncode != 0:
        # a plain build failing means the SOURCE is broken — that must
        # fail, not skip; only a missing sanitizer runtime may skip
        plain = compile_with([], tmp_path / f"selftest-{label}-plain")
        assert plain.returncode == 0, (
            f"native sources fail to compile:\n{plain.stderr[-1000:]}"
        )
        pytest.skip(f"{label} runtime unavailable: {build.stderr[-300:]}")
    run = subprocess.run([str(exe)], capture_output=True, text=True,
                         timeout=180)
    assert run.returncode == 0, (
        f"{label} selftest failed (rc={run.returncode}):\n"
        f"{run.stdout}\n{run.stderr}"
    )
    assert "selftest ok" in run.stdout


@pytest.mark.skipif(not _have_gxx(), reason="no g++ toolchain")
def test_native_coder_under_asan_ubsan(tmp_path):
    _build_and_run(tmp_path, "asan",
                   ["-fsanitize=address,undefined",
                    "-fno-sanitize-recover=all"])


@pytest.mark.skipif(not _have_gxx(), reason="no g++ toolchain")
def test_native_coder_under_tsan(tmp_path):
    """The multithreaded batch coder's one-shot thread pool must be
    data-race-free over disjoint stripe ranges."""
    _build_and_run(tmp_path, "tsan", ["-fsanitize=thread"])
