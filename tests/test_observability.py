"""Recon, tracing, and container packer tests."""

import json
import urllib.request

import numpy as np
import pytest

from ozone_tpu.recon.recon import ReconServer
from ozone_tpu.storage.container_packer import export_container, import_container
from ozone_tpu.testing.minicluster import MiniOzoneCluster
from ozone_tpu.utils.tracing import Tracer

EC = "rs-3-2-4096"


@pytest.fixture
def cluster(tmp_path):
    c = MiniOzoneCluster(
        tmp_path, num_datanodes=5, block_size=8 * 4096,
        container_size=4 * 1024 * 1024,
        stale_after_s=1000.0, dead_after_s=2000.0,
    )
    yield c
    c.close()


def test_recon_endpoints(cluster, monkeypatch):
    oz = cluster.client()
    b = oz.create_volume("v").create_bucket("b", replication=EC)
    rng = np.random.default_rng(0)
    for i, size in enumerate((100, 5000, 60_000)):
        b.write_key(f"k{i}", rng.integers(0, 256, size, dtype=np.uint8))
    cluster.tick()

    recon = ReconServer(cluster.om, cluster.scm)
    recon.start()
    try:
        base = f"http://{recon.address}"
        ns = json.loads(urllib.request.urlopen(base + "/api/namespace").read())
        assert ns["keys"] == 3 and ns["bytes"] == 65_100
        hist = json.loads(urllib.request.urlopen(base + "/api/filesizes").read())
        assert sum(hist.values()) == 3
        ck = json.loads(
            urllib.request.urlopen(base + "/api/containers/keys").read()
        )
        assert any("/v/b/k2" in keys for keys in ck.values())
        health = json.loads(
            urllib.request.urlopen(base + "/api/containers/health").read()
        )
        assert not health["missing"]
        nodes = json.loads(urllib.request.urlopen(base + "/api/nodes").read())
        assert len(nodes) == 5
        heat = json.loads(
            urllib.request.urlopen(base + "/api/heatmap").read()
        )
        assert heat["cells"] == [
            {"volume": "v", "bucket": "b", "keys": 3, "bytes": 65_100}
        ]
        # slow-request flight recorder: any PUT beats a 0ms SLO, so the
        # next write is retained and queryable with its critical path
        monkeypatch.setenv("OZONE_TPU_TRACE_SLO_CLIENT_PUT_MS", "0")
        b.write_key("k3", rng.integers(0, 256, 100, dtype=np.uint8))
        sl = json.loads(
            urllib.request.urlopen(base + "/api/traces/slow").read())
        assert any(t["root"] == "client:put" for t in sl["traces"])
        tid = next(t["traceId"] for t in sl["traces"]
                   if t["root"] == "client:put")
        detail = json.loads(urllib.request.urlopen(
            base + "/api/traces/slow?id=" + tid).read())
        assert detail["criticalPath"] and detail["spans"]
        assert sum(s["micros"] for s in detail["criticalPath"]) > 0
        # admission panel: the view peeks at the controller cache (it
        # must never install one), so a fresh process reports empty
        ad = json.loads(
            urllib.request.urlopen(base + "/api/admission").read())
        assert set(ad) == {"enabled", "hops", "counters"}
        # now install a controller the way a serving hop would and
        # confirm the view surfaces its snapshot + rejection counters
        from ozone_tpu import admission

        admission.reset_for_tests()
        try:
            ctl = admission.controller("gateway")
            with ctl.admit("GET"):
                ad = json.loads(
                    urllib.request.urlopen(base + "/api/admission").read())
            assert "gateway" in ad["hops"]
            assert ad["hops"]["gateway"]["inflight"] == 1
            assert ad["counters"]["gateway_admitted"] >= 1
        finally:
            admission.reset_for_tests()
        # the dashboard page renders the heat panel
        page = urllib.request.urlopen(base + "/").read().decode()
        assert "Namespace heat" in page and "/api/heatmap" in page
        assert "Slow requests" in page and "/api/traces/slow" in page
        assert "Admission control" in page and "/api/admission" in page
        # base endpoints still work
        prom = urllib.request.urlopen(base + "/prom").read().decode()
        assert "om_" in prom
    finally:
        recon.stop()


def test_prometheus_text_golden_every_registry_renders():
    """Golden contract for the /prom surface: EVERY registered registry
    renders each metric with a # HELP + # TYPE pair and a stable
    sanitized name — including the lifecycle.* counters and the
    client.resilience counters scrape dashboards already key on. A
    rename or a dropped help/type line breaks operator dashboards
    silently, so this test pins the exposition shape itself."""
    import re

    # import-effects register the registries this test pins
    import ozone_tpu.client.resilience  # noqa: F401
    import ozone_tpu.lifecycle.service as lc_service
    from ozone_tpu.utils import metrics as m

    # touch the documented counter sets so a fresh process renders them
    # (registries materialize counters on first use)
    for name in ("keys_scanned", "transitions", "bytes_tiered",
                 "expirations", "leader_fences"):
        lc_service.METRICS.counter(name).inc(0)
    lc_service.METRICS.timer("sweep_seconds").update(0.0)
    from ozone_tpu.client.resilience import METRICS as RES

    RES.counter("deadline_exceeded").inc(0)
    RES.counter("hedges_fired").inc(0)
    # the shared codec service's documented family (docs/OPERATIONS.md
    # "Shared codec service"): dashboards key on these names
    from ozone_tpu.codec.service import METRICS as CODEC

    for name in ("submissions", "dispatches", "stripes_dispatched",
                 "slots_dispatched", "coalesced_operations",
                 "multi_op_dispatches", "forced_flushes",
                 "deadline_flushes", "tail_flushes",
                 "starvation_guard_trips"):
        CODEC.counter(name).inc(0)
    CODEC.gauge("queue_depth").set(0)
    CODEC.gauge("batch_fill_pct").set(0.0)
    # hot-path latency families are HISTOGRAMS (log-spaced buckets, so
    # p50/p95/p99 are scrapeable); one observation carries a trace-id
    # exemplar to pin the OpenMetrics exemplar syntax
    CODEC.histogram("queue_wait_seconds").observe(0.0)
    CODEC.histogram("dispatch_seconds").observe(
        0.25, trace_id="deadbeefcafef00d")
    from ozone_tpu.client.ozone_client import METRICS as OPS

    OPS.histogram("put_seconds").observe(0.001)
    OPS.histogram("get_seconds").observe(0.001)
    # the mesh-executor family (docs/OPERATIONS.md "Mesh executor"):
    # touching the module-level registry must NOT require (or create)
    # a running executor — dashboards scrape single-chip hosts too
    from ozone_tpu.parallel.mesh_executor import METRICS as MESH

    for name in ("submissions", "dispatches", "stripes_dispatched",
                 "slots_dispatched", "coalesced_operations",
                 "multi_op_dispatches", "spilled_lanes",
                 "spilled_stripes", "staging_reuses"):
        MESH.counter(name).inc(0)
    for name in ("devices", "depth", "queue_depth", "batch_fill_pct",
                 "inflight_depth", "inflight_per_device",
                 "max_inflight_depth"):
        MESH.gauge(name).set(0)
    MESH.histogram("queue_wait_seconds").observe(0.0)
    MESH.histogram("dispatch_seconds").observe(0.0)
    # the geo-replication family (docs/OPERATIONS.md "Geo replication"):
    # the lag gauges are the numbers operators alarm on
    from ozone_tpu.replication_geo.shipper import METRICS as GEO

    for name in ("keys_shipped", "bytes_shipped", "deletes_shipped",
                 "conflicts", "ship_failures", "pages_shipped",
                 "leader_fences", "bootstraps", "journal_gaps",
                 "cycles"):
        GEO.counter(name).inc(0)
    GEO.gauge("lag_entries").set(0)
    GEO.gauge("lag_seconds").set(0.0)
    GEO.timer("ship_seconds").update(0.0)
    # the sharded-metadata-plane family (docs/OPERATIONS.md "Sharded
    # metadata plane"): routing, 2PC, and follower-read counters the
    # Recon shard panel keys on
    from ozone_tpu.om.sharding.plane import METRICS as SHARD

    for name in ("routes", "moved_rejections", "cross_shard_prepares",
                 "cross_shard_commits", "cross_shard_aborts",
                 "follower_read_hits", "follower_read_misses",
                 "lease_renewals", "slots_migrated"):
        SHARD.counter(name).inc(0)
    # the small-object family (docs/OPERATIONS.md "Small-object
    # path"): inline hits, needles packed, slabs flushed, fill pct,
    # compaction accounting — the Recon smallobj panel keys on these
    from ozone_tpu.client.slab import METRICS as SMALLOBJ

    for name in ("inline_puts", "inline_bytes", "inline_gets",
                 "needle_gets", "needles_packed", "needles_committed",
                 "commit_batches", "slabs_flushed", "slab_bytes",
                 "compaction_slabs", "compaction_bytes",
                 "compaction_conflicts", "slabs_retired",
                 "put_rejected_queue", "flush_failures",
                 "needle_crc_errors"):
        SMALLOBJ.counter(name).inc(0)
    SMALLOBJ.gauge("queue_depth").set(0)
    SMALLOBJ.gauge("slab_fill_pct").set(0.0)
    SMALLOBJ.histogram("flush_seconds").observe(0.0)
    # the admission-control family (docs/OPERATIONS.md "Admission
    # control"): per-hop, per-reason rejection counters — the numbers
    # that separate healthy shed from collapse on the Recon panel —
    # plus the client-side server_busy pushback counter (deliberately
    # distinct from deadline_exceeded: pushback is not a fault)
    from ozone_tpu.admission import METRICS as ADMIT

    for name in ("gateway_admitted", "gateway_rejected_total",
                 "gateway_rejected_queue", "gateway_rejected_ops",
                 "gateway_rejected_bytes", "gateway_rejected_slo_p99",
                 "gateway_tenant_rejections", "om_admitted",
                 "om_rejected_total", "om_rejected_ops",
                 "om_tenant_rejections"):
        ADMIT.counter(name).inc(0)
    ADMIT.gauge("gateway_inflight").set(0)
    RES.counter("server_busy").inc(0)
    text = m.prometheus_text()
    lines = text.splitlines()
    name_re = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
    seen_metrics = set()
    for i, line in enumerate(lines):
        if not line.startswith("# TYPE "):
            continue
        _, _, metric, mtype = line.split(" ")
        assert mtype in ("counter", "gauge", "summary", "histogram"), line
        assert name_re.match(metric), f"unstable metric name {metric!r}"
        # the HELP line immediately precedes its TYPE line
        assert lines[i - 1].startswith(f"# HELP {metric} "), \
            f"missing HELP for {metric}"
        # and a sample for the metric follows before the next family
        nxt = lines[i + 1]
        assert nxt.startswith(metric), f"no sample after TYPE {metric}"
        seen_metrics.add(metric)
    # every registered registry contributed at least its known metrics
    for reg_name, reg in list(m._all_registries.items()):
        base = reg_name.replace(".", "_").replace("-", "_")
        for k in reg._counters:
            want = f"{base}_{k.replace('.', '_').replace('-', '_')}"
            assert want in seen_metrics, f"{reg_name}: missing {want}"
    # the documented lifecycle + resilience + codec-service families
    for want in ("lifecycle_keys_scanned", "lifecycle_transitions",
                 "lifecycle_bytes_tiered", "lifecycle_expirations",
                 "lifecycle_leader_fences", "lifecycle_sweep_seconds",
                 "client_resilience_deadline_exceeded",
                 "client_resilience_hedges_fired",
                 "codec_service_submissions", "codec_service_dispatches",
                 "codec_service_stripes_dispatched",
                 "codec_service_slots_dispatched",
                 "codec_service_coalesced_operations",
                 "codec_service_multi_op_dispatches",
                 "codec_service_forced_flushes",
                 "codec_service_deadline_flushes",
                 "codec_service_tail_flushes",
                 "codec_service_starvation_guard_trips",
                 "codec_service_queue_depth",
                 "codec_service_batch_fill_pct",
                 "codec_service_queue_wait_seconds",
                 "codec_service_dispatch_seconds",
                 "mesh_submissions", "mesh_dispatches",
                 "mesh_stripes_dispatched", "mesh_slots_dispatched",
                 "mesh_coalesced_operations", "mesh_multi_op_dispatches",
                 "mesh_spilled_lanes", "mesh_spilled_stripes",
                 "mesh_staging_reuses", "mesh_devices", "mesh_depth",
                 "mesh_queue_depth", "mesh_batch_fill_pct",
                 "mesh_inflight_depth", "mesh_inflight_per_device",
                 "mesh_max_inflight_depth", "mesh_queue_wait_seconds",
                 "mesh_dispatch_seconds",
                 "replication_keys_shipped", "replication_bytes_shipped",
                 "replication_deletes_shipped", "replication_conflicts",
                 "replication_ship_failures", "replication_pages_shipped",
                 "replication_leader_fences", "replication_bootstraps",
                 "replication_journal_gaps", "replication_cycles",
                 "replication_lag_entries", "replication_lag_seconds",
                 "replication_ship_seconds",
                 "om_shard_routes", "om_shard_moved_rejections",
                 "om_shard_cross_shard_prepares",
                 "om_shard_cross_shard_commits",
                 "om_shard_cross_shard_aborts",
                 "om_shard_follower_read_hits",
                 "om_shard_follower_read_misses",
                 "om_shard_lease_renewals", "om_shard_slots_migrated",
                 "admission_gateway_admitted",
                 "admission_gateway_rejected_total",
                 "admission_gateway_rejected_queue",
                 "admission_gateway_rejected_ops",
                 "admission_gateway_rejected_bytes",
                 "admission_gateway_rejected_slo_p99",
                 "admission_gateway_tenant_rejections",
                 "admission_gateway_inflight",
                 "admission_om_admitted", "admission_om_rejected_total",
                 "admission_om_rejected_ops",
                 "admission_om_tenant_rejections",
                 "client_resilience_server_busy",
                 "smallobj_inline_puts", "smallobj_inline_gets",
                 "smallobj_needles_packed", "smallobj_needle_gets",
                 "smallobj_needles_committed", "smallobj_commit_batches",
                 "smallobj_slabs_flushed", "smallobj_slab_bytes",
                 "smallobj_compaction_slabs", "smallobj_compaction_bytes",
                 "smallobj_compaction_conflicts",
                 "smallobj_slabs_retired", "smallobj_queue_depth",
                 "smallobj_slab_fill_pct", "smallobj_flush_seconds"):
        stem = want.removesuffix("_seconds")
        assert any(s.startswith(stem) for s in seen_metrics), want
    assert "# TYPE client_resilience_deadline_exceeded counter" in text
    assert "# HELP client_resilience_hedges_fired " in text
    assert "# TYPE codec_service_dispatches counter" in text
    assert "# HELP codec_service_tail_flushes " in text
    assert "# TYPE codec_service_batch_fill_pct gauge" in text
    assert "# TYPE replication_keys_shipped counter" in text
    assert "# TYPE admission_gateway_rejected_total counter" in text
    assert "# TYPE admission_gateway_inflight gauge" in text
    assert "# TYPE client_resilience_server_busy counter" in text
    assert "# TYPE replication_lag_entries gauge" in text
    assert "# HELP replication_lag_seconds " in text
    assert "# TYPE om_shard_routes counter" in text
    assert "# HELP om_shard_follower_read_hits " in text
    # -- histogram exposition: the hot-path latency families render
    # Prometheus histograms with cumulative buckets, _sum, and _count
    for fam in ("codec_service_queue_wait_seconds",
                "codec_service_dispatch_seconds",
                "mesh_queue_wait_seconds", "mesh_dispatch_seconds",
                "client_ops_put_seconds", "client_ops_get_seconds"):
        assert f"# TYPE {fam} histogram" in text, fam
        buckets = [s for s in lines
                   if s.startswith(f'{fam}_bucket{{le="')]
        assert buckets, f"no _bucket lines for {fam}"
        assert any(s.startswith(f'{fam}_bucket{{le="+Inf"}}')
                   for s in buckets), fam
        assert any(s.startswith(f"{fam}_sum ") for s in lines), fam
        assert any(s.startswith(f"{fam}_count ") for s in lines), fam
    # the outlier observation carries an OpenMetrics exemplar with the
    # trace id a scrape can pivot into /api/traces/slow
    assert re.search(
        r'codec_service_dispatch_seconds_bucket\{le="[^"]+"\} \d+ '
        r'# \{trace_id="deadbeefcafef00d"\} 0\.25 \d+(\.\d+)?', text), \
        "missing trace exemplar on dispatch_seconds bucket"
    # rendering is deterministic (sorted registries + sorted names), so
    # successive scrapes diff cleanly
    assert m.prometheus_text() == text


def test_tracing_spans_nest_and_propagate():
    t = Tracer.instance()
    with t.span("outer") as outer:
        with t.span("inner") as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
            ctx = t.inject()
            assert ctx == f"{inner.trace_id}:{inner.span_id}"
    # import the exported context as a remote child
    with t.span("remote", child_of=ctx) as remote:
        assert remote.trace_id == outer.trace_id
        assert remote.parent_id == inner.span_id
    # count only this trace: the tracer is a process-global singleton and
    # background daemon threads from other tests may emit spans too
    assert len(t.traces(trace_id=outer.trace_id)) == 3


def test_rpc_carries_trace_context(cluster):
    # spans from client and server share one trace across the gRPC boundary
    from ozone_tpu.net.daemons import ScmOmDaemon  # noqa: F401 (import check)
    from ozone_tpu.net.dn_service import DatanodeGrpcService, GrpcDatanodeClient
    from ozone_tpu.net.rpc import RpcServer

    srv = RpcServer()
    DatanodeGrpcService(cluster.datanodes[0], srv)
    srv.start()
    try:
        c = GrpcDatanodeClient("dn0", srv.address)
        t = Tracer.instance()
        with t.span("test-root") as root:
            c.echo(b"x")
        spans = t.traces(root.trace_id)
        names = {s.name for s in spans}
        assert any(n.startswith("client:") for n in names)
        assert any(n.startswith("server:") for n in names)
        c.close()
    finally:
        srv.stop()


def test_container_export_import(cluster, tmp_path):
    oz = cluster.client()
    b = oz.create_volume("v").create_bucket("b", replication=EC)
    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, 30_000, dtype=np.uint8)
    b.write_key("k", data)
    info = oz.om.lookup_key("v", "b", "k")
    g = oz.om.key_block_groups(info)[0]
    src_dn = cluster.datanode(g.pipeline.nodes[0])
    src_dn.close_container(g.container_id)  # export requires closed
    src = src_dn.get_container(g.container_id)
    for compress in (False, True):
        blob = export_container(src, compress=compress)
        from ozone_tpu.storage.datanode import Datanode

        dst_dn = Datanode(tmp_path / f"import{compress}", dn_id="dnX")
        c = import_container(dst_dn, blob)
        assert c.id == src.id
        assert c.replica_index == src.replica_index
        src_blocks = src.list_blocks()
        dst_blocks = c.list_blocks()
        assert [b_.to_json() for b_ in dst_blocks] == [
            b_.to_json() for b_ in src_blocks
        ]
        for blk in dst_blocks:
            for ci in blk.chunks:
                got = dst_dn.read_chunk(blk.block_id, ci, verify=True)
                expect = src_dn.read_chunk(blk.block_id, ci)
                assert np.array_equal(got, expect)
        dst_dn.close()


def test_trace_collector_assembles_across_services():
    """Exporter -> collector over real gRPC: spans reported by distinct
    services stitch into ONE queryable trace (the Jaeger
    collector/query role the round-1 tracing lacked)."""
    from ozone_tpu.net.rpc import RpcServer
    from ozone_tpu.utils.tracing import (
        SpanExporter,
        TraceCollector,
        Tracer,
    )

    srv = RpcServer()
    collector = TraceCollector(srv)
    srv.start()
    try:
        t = Tracer.instance()
        exp = SpanExporter(t, "svc-a", srv.address, interval_s=60.0)
        with t.span("a-root") as root:
            with t.span("a-child"):
                ctx = t.inject()
        exp.flush()
        # a second service continues the SAME trace (context import)
        with t.span("b-remote", child_of=ctx):
            pass
        exp.service = "svc-b"
        exp.flush()
        assert exp.exported == 3
        spans = collector.trace(root.trace_id)
        assert {s["name"] for s in spans} == {"a-root", "a-child",
                                              "b-remote"}
        recent = collector.recent()
        row = next(r for r in recent if r["traceId"] == root.trace_id)
        assert set(row["services"]) == {"svc-a", "svc-b"}
        assert row["root"] == "a-root"
        exp.stop()
    finally:
        srv.stop()


def test_daemon_spans_ship_to_metadata_collector(tmp_path):
    """Live daemons: a key write's datanode-side spans ship to the
    scm-om collector and assemble with the OM service spans under the
    trace id the client propagated."""
    import time as _time

    from ozone_tpu.client.dn_client import DatanodeClientFactory
    from ozone_tpu.client.ozone_client import OzoneClient
    from ozone_tpu.net.daemons import DatanodeDaemon, ScmOmDaemon
    from ozone_tpu.net.om_service import GrpcOmClient
    from ozone_tpu.utils.tracing import Tracer

    meta = ScmOmDaemon(tmp_path / "om.db", block_size=4 * 4096,
                       container_size=1024 * 1024,
                       stale_after_s=1000.0, dead_after_s=2000.0)
    meta.start()
    dns = [DatanodeDaemon(tmp_path / f"dn{i}", f"dn{i}", meta.address,
                          heartbeat_interval_s=0.2)
           for i in range(5)]
    for d in dns:
        d.start()
    try:
        clients = DatanodeClientFactory()
        oz = OzoneClient(GrpcOmClient(meta.address, clients=clients),
                         clients)
        b = oz.create_volume("tv").create_bucket(
            "tb", replication="rs-3-2-4096")
        t = Tracer.instance()
        with t.span("client-write") as root:
            b.write_key("k", np.zeros(20_000, np.uint8))
        # exporters run on an interval; force the ship now. NOTE: in
        # one process every daemon shares the singleton tracer, so all
        # spans drain through one exporter — per-service attribution is
        # exercised by the unit test above and the live multi-process
        # drill; this test proves the daemon plumbing end to end.
        deadline = _time.time() + 10
        spans = []
        while _time.time() < deadline:
            for d in dns:
                d.trace_exporter.flush()
            meta.trace_exporter.flush()
            spans = meta.trace_collector.trace(root.trace_id)
            names = {s["name"] for s in spans}
            if "client-write" in names and any(
                    "OmService" in n for n in names) and any(
                    "Datanode" in n for n in names):
                break
            _time.sleep(0.2)
        names = {s["name"] for s in spans}
        assert "client-write" in names, names
        # the OM verbs and the datapath writes assembled under ONE id
        assert any("OmService" in n for n in names), names
        assert any("Datanode" in n for n in names), names
        assert all(s.get("service") for s in spans)
    finally:
        for d in dns:
            d.stop()
        meta.stop()
