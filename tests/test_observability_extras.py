"""Profiler endpoint, thread dump, live reconfiguration, event watcher,
and the Recon UI page.

Mirrors the reference's auxiliary observability surface: ProfileServlet
(flamegraph sampling), /stacks, ReconfigureProtocol (live key updates
without restart), EventWatcher lease/retry semantics, and the Recon web
UI served from the observability service.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from ozone_tpu.net.daemons import ScmOmDaemon
from ozone_tpu.utils.events import EventQueue, EventWatcher
from ozone_tpu.utils.http_server import sample_stacks, thread_dump


# ----------------------------------------------------------- profiler
def test_sample_stacks_sees_worker_thread():
    stop = threading.Event()

    def spin_about():
        while not stop.is_set():
            time.sleep(0.001)

    t = threading.Thread(target=spin_about, name="prof-victim")
    t.start()
    try:
        out = sample_stacks(duration_s=0.3, interval_s=0.01)
    finally:
        stop.set()
        t.join()
    # collapsed flamegraph lines: "frame;frame count"
    assert "spin_about" in out
    line = next(ln for ln in out.splitlines() if "spin_about" in ln)
    assert line.rsplit(" ", 1)[1].isdigit()
    assert ";" in line


def test_thread_dump_lists_threads():
    out = thread_dump()
    assert "Thread " in out
    assert "MainThread" in out


# ----------------------------------------------------------- event watcher
def test_event_watcher_completion_and_lease_retry():
    q = EventQueue()
    started: list = []
    timed_out: list = []
    q.subscribe("cmd", started.append)
    w = EventWatcher(q, "cmd", "cmd-done", lease_timeout_s=0.05,
                     on_timeout=timed_out.append, max_retries=2)
    # completion before the lease expires -> no retries
    w.watch("a", {"id": "a"})
    assert started == [{"id": "a"}]
    q.publish("cmd-done", "a")
    assert w.pending_count() == 0
    assert w.check_leases() == []
    assert started == [{"id": "a"}]

    # no completion: re-published max_retries times, then dropped with hook
    w.watch("b", {"id": "b"})
    for i in range(2):
        time.sleep(0.06)
        assert w.check_leases() == []
        assert len(started) == 2 + i + 1  # retry republished
    time.sleep(0.06)
    assert w.check_leases() == ["b"]
    assert timed_out == [{"id": "b"}]
    assert w.pending_count() == 0


def test_event_watcher_rewatch_during_expiry_keeps_fresh_lease():
    """A completion + fresh watch of the same id landing between lease
    collection and expiry action must leave the new lease untouched:
    no spurious timeout, no stale retry-count overwrite."""
    q = EventQueue()
    timed_out: list = []
    w = EventWatcher(q, "cmd", "cmd-done", lease_timeout_s=0.01,
                     on_timeout=timed_out.append, max_retries=0)
    w.watch("x", {"gen": 1})
    time.sleep(0.02)  # let the lease expire
    # simulate the race: completion + re-watch land before check_leases
    # acts on its expired-lease snapshot
    q.publish("cmd-done", "x")
    w.watch("x", {"gen": 2})
    assert w.check_leases() == []  # fresh lease: not expired, not touched
    assert timed_out == []
    assert w.pending_count() == 1
    # and the surviving lease is the new one: expiring it reports gen 2
    time.sleep(0.02)
    assert w.check_leases() == ["x"]
    assert timed_out == [{"gen": 2}]


# ----------------------------------------------------------- http extras
@pytest.fixture
def daemon(tmp_path):
    d = ScmOmDaemon(tmp_path / "om.db", stale_after_s=1000.0,
                    dead_after_s=2000.0, http_port=0)
    d.start()
    yield d
    d.stop()


def _get(addr, path):
    return urllib.request.urlopen(f"http://{addr}{path}", timeout=10)


def test_live_reconfiguration_over_http(daemon):
    addr = daemon.http.address
    props = json.load(_get(addr, "/reconfig/properties"))
    keys = {p["key"] for p in props}
    assert "ozone.scm.stale.node.interval" in keys
    assert "ozone.om.block.size" in keys

    # change a live value, no restart
    r = json.load(_get(
        addr, "/reconfig?key=ozone.scm.stale.node.interval&value=123.5"))
    assert r["new"] == 123.5
    assert daemon.scm.nodes.stale_after == 123.5
    json.load(_get(addr, "/reconfig?key=ozone.om.block.size&value=65536"))
    assert daemon.om.block_size == 65536

    # unknown key rejected
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(addr, "/reconfig?key=not.a.key&value=1")
    assert ei.value.code == 400


def test_prof_and_stacks_endpoints(daemon):
    addr = daemon.http.address
    out = _get(addr, "/prof?duration=0.2&interval=0.02").read().decode()
    assert out == "" or all(
        ln.rsplit(" ", 1)[1].isdigit() for ln in out.splitlines())
    dump = _get(addr, "/stacks").read().decode()
    assert "Thread " in dump


def test_recon_ui_served(tmp_path):
    from ozone_tpu.recon.recon import ReconServer
    from ozone_tpu.scm.scm import StorageContainerManager
    from ozone_tpu.om.om import OzoneManager

    scm = StorageContainerManager(stale_after_s=1e6, dead_after_s=2e6)
    for i in range(3):
        scm.register_datanode(f"dn{i}")
        scm.heartbeat(f"dn{i}", container_report=[])
    om = OzoneManager(tmp_path / "om.db", scm)
    srv = ReconServer(om, scm)
    srv.start()
    try:
        html = _get(srv.address, "/").read().decode()
        assert "Recon" in html and "viz-root" in html
        # status uses icon + label, never color alone
        assert "badge" in html
        # the APIs the page fetches exist
        s = json.load(_get(srv.address, "/api/summary"))
        assert len(s["nodes"]) == 3
        json.load(_get(srv.address, "/api/filesizes"))
        assert json.load(_get(srv.address, "/api/pipelines")) == []
    finally:
        srv.stop()
        om.close()
