"""OM HA tests: request serde, log replication, recovery, failover."""

import numpy as np
import pytest

from ozone_tpu.om import requests as rq
from ozone_tpu.om.ha import (
    NotLeaderError,
    OMFailoverProxy,
    ReplicatedOzoneManager,
)
from ozone_tpu.om.om import OzoneManager
from ozone_tpu.scm.scm import StorageContainerManager


def _scm(n=5):
    scm = StorageContainerManager(stale_after_s=1e6, dead_after_s=2e6)
    for i in range(n):
        scm.register_datanode(f"dn{i}")
    return scm


def _replica(tmp_path, scm, name, leader=False):
    om = OzoneManager(tmp_path / name / "om.db", scm)
    return ReplicatedOzoneManager(om, tmp_path / name / "wal.jsonl", name,
                                  is_leader=leader)


def test_request_serde_roundtrip():
    r = rq.CreateBucket("v", "b", "rs-6-3-1024k")
    r.created = 123.0
    d = r.to_json()
    r2 = rq.OMRequest.from_json(d)
    assert isinstance(r2, rq.CreateBucket)
    assert r2 == r


def test_replication_and_follower_state(tmp_path):
    scm = _scm()
    leader = _replica(tmp_path, scm, "om1", leader=True)
    f1 = _replica(tmp_path, scm, "om2")
    f2 = _replica(tmp_path, scm, "om3")
    leader.peers = [f1, f2]
    f1.peers = [leader, f2]
    f2.peers = [leader, f1]

    leader.submit(rq.CreateVolume("v"))
    leader.submit(rq.CreateBucket("v", "b", "rs-3-2-4096"))
    # followers hold identical namespace state
    for f in (f1, f2):
        assert f.om.volume_info("v")["name"] == "v"
        assert f.om.bucket_info("v", "b")["replication"] == "rs-3-2-4096"
    with pytest.raises(NotLeaderError):
        f1.submit(rq.CreateVolume("nope"))


def test_recovery_from_wal(tmp_path):
    scm = _scm()
    leader = _replica(tmp_path, scm, "om1", leader=True)
    leader.submit(rq.CreateVolume("v"))
    leader.submit(rq.CreateBucket("v", "b"))
    idx = leader.applied_index
    leader.om.close()
    leader.wal.close()

    # restart from the same wal + a FRESH db (full log replay)
    om2 = OzoneManager(tmp_path / "om1-fresh" / "om.db", scm)
    r2 = ReplicatedOzoneManager(om2, tmp_path / "om1" / "wal.jsonl", "om1",
                                is_leader=True)
    assert r2.applied_index == idx
    assert r2.om.bucket_info("v", "b")["name"] == "b"


def test_failover_promotes_caught_up_follower(tmp_path):
    scm = _scm()
    leader = _replica(tmp_path, scm, "om1", leader=True)
    f1 = _replica(tmp_path, scm, "om2")
    leader.peers = [f1]
    f1.peers = [leader]

    proxy = OMFailoverProxy([leader, f1])
    proxy.submit(rq.CreateVolume("v"))
    proxy.submit(rq.CreateBucket("v", "b"))

    # leader dies; follower promotes and takes writes
    f1.promote()
    assert not leader.is_leader
    proxy.submit(rq.CreateBucket("v", "b2"))
    assert f1.om.bucket_info("v", "b2")["name"] == "b2"
    # old leader rejoining as follower catches up
    leader.catch_up()
    assert leader.om.bucket_info("v", "b2")["name"] == "b2"


def test_follower_gap_catch_up(tmp_path):
    scm = _scm()
    leader = _replica(tmp_path, scm, "om1", leader=True)
    f1 = _replica(tmp_path, scm, "om2")
    leader.peers = []  # f1 misses entries
    f1.peers = [leader]
    leader.submit(rq.CreateVolume("v"))
    leader.submit(rq.CreateBucket("v", "b"))
    leader.peers = [f1]
    # next replicated entry has a gap -> follower pulls missing entries
    leader.submit(rq.CreateBucket("v", "b2"))
    assert f1.applied_index == 3
    assert f1.om.bucket_info("v", "b")["name"] == "b"


def test_atomic_apply_never_tears_across_flush_boundary(tmp_path):
    """A multi-row request (rename = delete+put) must land in ONE
    durable batch: with flush_every=1 and no atomic(), the delete would
    commit alone, and a crash before the put loses the key under BOTH
    names — the round-4 soak's lost-rename failure. atomic() defers the
    auto-flush so the disk only ever shows both-or-neither."""
    from ozone_tpu.om.metadata import OMMetadataStore, key_key

    db = tmp_path / "atomic.db"
    store = OMMetadataStore(db, flush_every=1)
    src, dst = key_key("v", "b", "k"), key_key("v", "b", "k2")
    store.put("keys", src, {"name": "k", "size": 1})
    store.flush()

    flushes: list[int] = []
    orig = store._flush_locked

    def counting_flush():
        flushes.append(1)
        orig()

    store._flush_locked = counting_flush
    with store.atomic():
        rq.RenameKey("v", "b", "k", "k2").apply(store)
        assert flushes == [], "a commit escaped mid-request"
        # simulated crash INSIDE the request: the disk image must still
        # hold the ORIGINAL row (both-or-neither, never neither)
        crash = OMMetadataStore(db, flush_every=100)
        assert crash.get("keys", src) is not None
        assert crash.get("keys", dst) is None
        crash.close()
    assert len(flushes) == 1  # one batch carried both rows
    store.flush()
    after = OMMetadataStore(db, flush_every=100)
    assert after.get("keys", src) is None
    assert after.get("keys", dst) is not None
    after.close()
    store.close()


def test_flush_group_commit_batches_and_propagates(tmp_path):
    """Group commit (OzoneManagerDoubleBuffer.flushTransactions:293
    analog): concurrent appliers share sqlite commits, everything acked
    is durable, and a flush error reaches the waiters."""
    import threading
    import time

    from ozone_tpu.om.metadata import OMMetadataStore

    store = OMMetadataStore(tmp_path / "gc.db")
    N, PER = 8, 40
    commits = {"n": 0}
    orig = store._flush_locked

    def counting_slow_flush():
        # the sleep forces a pile-up: while one flusher sleeps, other
        # workers apply and enqueue, so later flushes cover MANY ops —
        # without batching this test takes 320 commits, with it far fewer
        commits["n"] += 1
        time.sleep(0.004)
        orig()

    store._flush_locked = counting_slow_flush

    def worker(tid):
        for i in range(PER):
            store.put("keys", f"/v/b/k{tid}-{i}", {"size": i})
            store.flush_group()

    ts = [threading.Thread(target=worker, args=(t,)) for t in range(N)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    # durability: a FRESH store sees every row
    store2 = OMMetadataStore(tmp_path / "gc.db")
    for tid in range(N):
        for i in range(PER):
            assert store2.get("keys", f"/v/b/k{tid}-{i}") == {"size": i}
    # batching: concurrent appliers MUST share commits (the double-
    # buffer property). One-commit-per-op would be exactly N*PER = 320;
    # the bound leaves scheduler slack while still failing a silent
    # revert to unbatched per-request commits
    assert commits["n"] < N * PER * 3 // 4, commits

    # error propagation: a failing flush surfaces to group waiters
    def broken_flush():
        raise RuntimeError("disk gone")

    store._flush_locked = broken_flush
    store.put("keys", "/v/b/doomed", {"size": 1})
    try:
        store.flush_group()
        raise AssertionError("flush_group swallowed the flush error")
    except RuntimeError:
        pass
    # a transient failure must NOT wedge the write path: once the
    # "disk" recovers, the next flush_group retries and succeeds
    store._flush_locked = orig
    store.flush_group()
    assert OMMetadataStore(tmp_path / "gc.db").get(
        "keys", "/v/b/doomed") == {"size": 1}
