"""OM HA tests: request serde, log replication, recovery, failover."""

import numpy as np
import pytest

from ozone_tpu.om import requests as rq
from ozone_tpu.om.ha import (
    NotLeaderError,
    OMFailoverProxy,
    ReplicatedOzoneManager,
)
from ozone_tpu.om.om import OzoneManager
from ozone_tpu.scm.scm import StorageContainerManager


def _scm(n=5):
    scm = StorageContainerManager(stale_after_s=1e6, dead_after_s=2e6)
    for i in range(n):
        scm.register_datanode(f"dn{i}")
    return scm


def _replica(tmp_path, scm, name, leader=False):
    om = OzoneManager(tmp_path / name / "om.db", scm)
    return ReplicatedOzoneManager(om, tmp_path / name / "wal.jsonl", name,
                                  is_leader=leader)


def test_request_serde_roundtrip():
    r = rq.CreateBucket("v", "b", "rs-6-3-1024k")
    r.created = 123.0
    d = r.to_json()
    r2 = rq.OMRequest.from_json(d)
    assert isinstance(r2, rq.CreateBucket)
    assert r2 == r


def test_replication_and_follower_state(tmp_path):
    scm = _scm()
    leader = _replica(tmp_path, scm, "om1", leader=True)
    f1 = _replica(tmp_path, scm, "om2")
    f2 = _replica(tmp_path, scm, "om3")
    leader.peers = [f1, f2]
    f1.peers = [leader, f2]
    f2.peers = [leader, f1]

    leader.submit(rq.CreateVolume("v"))
    leader.submit(rq.CreateBucket("v", "b", "rs-3-2-4096"))
    # followers hold identical namespace state
    for f in (f1, f2):
        assert f.om.volume_info("v")["name"] == "v"
        assert f.om.bucket_info("v", "b")["replication"] == "rs-3-2-4096"
    with pytest.raises(NotLeaderError):
        f1.submit(rq.CreateVolume("nope"))


def test_recovery_from_wal(tmp_path):
    scm = _scm()
    leader = _replica(tmp_path, scm, "om1", leader=True)
    leader.submit(rq.CreateVolume("v"))
    leader.submit(rq.CreateBucket("v", "b"))
    idx = leader.applied_index
    leader.om.close()
    leader.wal.close()

    # restart from the same wal + a FRESH db (full log replay)
    om2 = OzoneManager(tmp_path / "om1-fresh" / "om.db", scm)
    r2 = ReplicatedOzoneManager(om2, tmp_path / "om1" / "wal.jsonl", "om1",
                                is_leader=True)
    assert r2.applied_index == idx
    assert r2.om.bucket_info("v", "b")["name"] == "b"


def test_failover_promotes_caught_up_follower(tmp_path):
    scm = _scm()
    leader = _replica(tmp_path, scm, "om1", leader=True)
    f1 = _replica(tmp_path, scm, "om2")
    leader.peers = [f1]
    f1.peers = [leader]

    proxy = OMFailoverProxy([leader, f1])
    proxy.submit(rq.CreateVolume("v"))
    proxy.submit(rq.CreateBucket("v", "b"))

    # leader dies; follower promotes and takes writes
    f1.promote()
    assert not leader.is_leader
    proxy.submit(rq.CreateBucket("v", "b2"))
    assert f1.om.bucket_info("v", "b2")["name"] == "b2"
    # old leader rejoining as follower catches up
    leader.catch_up()
    assert leader.om.bucket_info("v", "b2")["name"] == "b2"


def test_follower_gap_catch_up(tmp_path):
    scm = _scm()
    leader = _replica(tmp_path, scm, "om1", leader=True)
    f1 = _replica(tmp_path, scm, "om2")
    leader.peers = []  # f1 misses entries
    f1.peers = [leader]
    leader.submit(rq.CreateVolume("v"))
    leader.submit(rq.CreateBucket("v", "b"))
    leader.peers = [f1]
    # next replicated entry has a gap -> follower pulls missing entries
    leader.submit(rq.CreateBucket("v", "b2"))
    assert f1.applied_index == 3
    assert f1.om.bucket_info("v", "b")["name"] == "b"
