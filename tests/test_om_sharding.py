"""Sharded metadata plane: hash-partitioned OM rings + root shard map.

Failure-drill coverage for ISSUE 15's acceptance claims:

- routing: every (volume, bucket) op lands on the owning shard, and a
  SHARD_MOVED rejection retries transparently through a root-map
  refresh (client-side cache invalidation);
- cross-shard rename/link 2PC: both-or-neither under coordinator
  crashes at every phase (presumed abort) and under a shard-leader
  kill -9 mid-transaction, with byte-exact readback on the data path;
- rebalance: migrate_slot fences the source, moves the rows, and
  in-flight clients bounce + retry through the bumped epoch;
- follower reads: lease-holding followers serve the read mix locally
  (>= 80% hit rate), and staleness is bounded by the lease — once the
  leader is gone longer than the lease window, followers refuse and
  reads fall back to the (new) leader.
"""

import os
import time

import numpy as np
import pytest

from ozone_tpu.om import requests as rq
from ozone_tpu.om.sharding.plane import ShardedMetaPlane
from ozone_tpu.om.sharding.shardmap import (
    SHARD_MOVED,
    ShardMap,
    slot_for,
)
from ozone_tpu.om.sharding.txn import ShardPrepare, TxnJournal
from ozone_tpu.utils.metrics import registry

METRICS = registry("om.shard")


def _bucket_on(m: ShardMap, volume: str, shard_id: str,
               prefix: str = "b") -> str:
    """A bucket name whose slot hashes onto `shard_id`."""
    for i in range(10_000):
        name = f"{prefix}{i}"
        if m.shard_for(volume, name) == shard_id:
            return name
    raise AssertionError(f"no bucket hashes to {shard_id}")


def _put_meta(facade, volume: str, bucket: str, key: str,
              size: int = 0) -> None:
    s = facade.open_key(volume, bucket, key)
    facade.commit_key(s, [], size)


# ---------------------------------------------------------------- shard map
def test_slot_math_partitions_namespace():
    m = ShardMap.uniform(["s0", "s1", "s2", "s3"])
    # every slot owned by exactly one shard, all shards used
    owned = [m.owned_slots(s) for s in m.shards]
    assert sorted(sum(owned, [])) == list(range(m.slot_count))
    assert all(owned)
    # routing is deterministic and in-range
    assert m.shard_for("v", "b") == m.shard_for("v", "b")
    assert slot_for("v", "b") == slot_for("v", "b")
    assert 0 <= slot_for("v", "b") < m.slot_count


def test_move_slot_bumps_epoch_and_reassigns():
    m = ShardMap.uniform(["s0", "s1"])
    slot = m.owned_slots("s0")[0]
    m2 = m.move_slot(slot, "s1")
    assert m2.epoch == m.epoch + 1
    assert slot in m2.owned_slots("s1")
    assert slot not in m2.owned_slots("s0")
    # round-trips through the root-ring row format
    assert ShardMap.from_json(m2.to_json()).owned_slots("s1") \
        == m2.owned_slots("s1")


# ---------------------------------------------------------------- routing
def test_plain_plane_routes_to_owning_shard(tmp_path):
    plane = ShardedMetaPlane(tmp_path, n_shards=2)
    try:
        m = plane.current_map()
        f = plane.facade
        f.create_volume("v")
        b0 = _bucket_on(m, "v", "s0")
        b1 = _bucket_on(m, "v", "s1")
        f.create_bucket("v", b0, replication="RATIS/1")
        f.create_bucket("v", b1, replication="RATIS/1")
        _put_meta(f, "v", b0, "k0")
        _put_meta(f, "v", b1, "k1")
        # each shard's store holds ONLY its own bucket's rows
        s0 = plane.shards["s0"].om.store
        s1 = plane.shards["s1"].om.store
        from ozone_tpu.om.metadata import key_key

        assert s0.get("keys", key_key("v", b0, "k0")) is not None
        assert s0.get("keys", key_key("v", b1, "k1")) is None
        assert s1.get("keys", key_key("v", b1, "k1")) is not None
        assert s1.get("keys", key_key("v", b0, "k0")) is None
        # facade reads see both through routing
        assert f.lookup_key("v", b0, "k0")["name"] == "k0"
        assert f.lookup_key("v", b1, "k1")["name"] == "k1"
        assert {b["name"] for b in f.list_buckets("v")} == {b0, b1}
    finally:
        plane.close()


def test_misrouted_write_rejected_shard_moved(tmp_path):
    plane = ShardedMetaPlane(tmp_path, n_shards=2)
    try:
        m = plane.current_map()
        plane.facade.create_volume("v")
        b0 = _bucket_on(m, "v", "s0")
        # bypass routing: drive b0's create straight into s1's OM
        with pytest.raises(rq.OMError) as ei:
            plane.shards["s1"].om.create_bucket(
                "v", b0, replication="RATIS/1")
        assert ei.value.code == SHARD_MOVED
    finally:
        plane.close()


def test_epoch_bump_mid_op_retries_through_refreshed_map(tmp_path):
    plane = ShardedMetaPlane(tmp_path, n_shards=2)
    try:
        f = plane.facade
        m = plane.current_map()
        f.create_volume("v")
        b0 = _bucket_on(m, "v", "s0")
        f.create_bucket("v", b0, replication="RATIS/1")
        _put_meta(f, "v", b0, "k", size=7)
        # operator rebalance: the facade still holds the old map
        moved = registry("om.shard").counter("moved_rejections").value
        plane.migrate_slot(slot_for("v", b0), "s1")
        # stale-map read bounces off s0 (fenced) and retries through
        # the refreshed root map onto s1 — invisible to the caller
        info = f.lookup_key("v", b0, "k")
        assert info["name"] == "k" and int(info["size"]) == 7
        assert registry("om.shard").counter("moved_rejections").value \
            > moved
        # writes follow the new owner too
        _put_meta(f, "v", b0, "k2")
        assert {k["name"] for k in f.list_keys("v", b0)} >= {"k", "k2"}
    finally:
        plane.close()


# ------------------------------------------------------------ cross-shard 2PC
def test_cross_shard_rename_moves_key_exactly_once(tmp_path):
    plane = ShardedMetaPlane(tmp_path, n_shards=2)
    try:
        f = plane.facade
        m = plane.current_map()
        f.create_volume("v")
        src = _bucket_on(m, "v", "s0")
        dst = _bucket_on(m, "v", "s1")
        f.create_bucket("v", src, replication="RATIS/1")
        f.create_bucket("v", dst, replication="RATIS/1")
        _put_meta(f, "v", src, "old", size=11)
        info = f.rename_key_cross("v", src, "old", dst, "new")
        assert info["name"] == "new"
        # visible under exactly one name
        assert f.lookup_key("v", dst, "new")["size"] == 11
        with pytest.raises(rq.OMError):
            f.lookup_key("v", src, "old")
        # no journal rows or intents left behind
        assert not list(plane.root.store.iterate("system", "txn/"))
        for sid in plane.shard_ids:
            assert not list(plane.shards[sid].om.store.iterate(
                "system", "txn_intent/"))
    finally:
        plane.close()


def test_cross_shard_rename_aborts_clean_on_dst_conflict(tmp_path):
    plane = ShardedMetaPlane(tmp_path, n_shards=2)
    try:
        f = plane.facade
        m = plane.current_map()
        f.create_volume("v")
        src = _bucket_on(m, "v", "s0")
        dst = _bucket_on(m, "v", "s1")
        f.create_bucket("v", src, replication="RATIS/1")
        f.create_bucket("v", dst, replication="RATIS/1")
        _put_meta(f, "v", src, "k", size=5)
        _put_meta(f, "v", dst, "taken", size=3)
        with pytest.raises(rq.OMError):
            f.rename_key_cross("v", src, "k", dst, "taken")
        # abort restored the source; destination untouched
        assert f.lookup_key("v", src, "k")["size"] == 5
        assert f.lookup_key("v", dst, "taken")["size"] == 3
        assert not list(plane.root.store.iterate("system", "txn/"))
    finally:
        plane.close()


def test_coordinator_crash_before_decide_presumed_abort(tmp_path):
    """kill -9 the coordinator after prepare, before the decision: the
    root journal holds `begin`, the source shard holds a staged intent
    with the key already deleted. recover() must abort and restore."""
    plane = ShardedMetaPlane(tmp_path, n_shards=2)
    try:
        f = plane.facade
        m = plane.current_map()
        f.create_volume("v")
        src = _bucket_on(m, "v", "s0")
        dst = _bucket_on(m, "v", "s1")
        f.create_bucket("v", src, replication="RATIS/1")
        f.create_bucket("v", dst, replication="RATIS/1")
        _put_meta(f, "v", src, "k", size=9)
        # replay the coordinator's writes up to the crash point
        rec = {"kind": "rename", "volume": "v", "src_bucket": src,
               "key": "k", "dst_bucket": dst, "new_key": "n",
               "src_shard": "s0", "dst_shard": "s1", "epoch": m.epoch}
        plane.root.submit(TxnJournal("t-crash", "begin", rec))
        plane.shards["s0"].submit(ShardPrepare(
            "t-crash", "rename_src",
            {"volume": "v", "bucket": src, "key": "k"}, epoch=m.epoch))
        # the prepare DID delete the source row (intent staged)
        with pytest.raises(rq.OMError):
            f.lookup_key("v", src, "k")
        resolved = plane.recover()
        assert [r["txn_id"] for r in resolved] == ["t-crash"]
        # both-or-neither: key back under its original name only
        assert f.lookup_key("v", src, "k")["size"] == 9
        with pytest.raises(rq.OMError):
            f.lookup_key("v", dst, "n")
        assert not list(plane.root.store.iterate("system", "txn/"))
    finally:
        plane.close()


def test_coordinator_crash_after_decide_commits_on_recovery(tmp_path):
    """Crash AFTER decide-commit is journaled but before either shard
    saw its commit: recovery must finish the rename, not undo it."""
    plane = ShardedMetaPlane(tmp_path, n_shards=2)
    try:
        f = plane.facade
        m = plane.current_map()
        f.create_volume("v")
        src = _bucket_on(m, "v", "s0")
        dst = _bucket_on(m, "v", "s1")
        f.create_bucket("v", src, replication="RATIS/1")
        f.create_bucket("v", dst, replication="RATIS/1")
        _put_meta(f, "v", src, "k", size=13)
        rec = {"kind": "rename", "volume": "v", "src_bucket": src,
               "key": "k", "dst_bucket": dst, "new_key": "n",
               "src_shard": "s0", "dst_shard": "s1", "epoch": m.epoch}
        plane.root.submit(TxnJournal("t-c2", "begin", rec))
        info = plane.shards["s0"].submit(ShardPrepare(
            "t-c2", "rename_src",
            {"volume": "v", "bucket": src, "key": "k"}, epoch=m.epoch))
        plane.shards["s1"].submit(ShardPrepare(
            "t-c2", "rename_dst",
            {"volume": "v", "bucket": dst, "new_key": "n",
             "info": info}, epoch=m.epoch))
        plane.root.submit(TxnJournal("t-c2", "decide-commit", rec))
        plane.recover()
        assert f.lookup_key("v", dst, "n")["size"] == 13
        with pytest.raises(rq.OMError):
            f.lookup_key("v", src, "k")
        assert not list(plane.root.store.iterate("system", "txn/"))
    finally:
        plane.close()


def test_stale_epoch_prepare_fenced(tmp_path):
    """A coordinator holding a pre-rebalance map must not stage 2PC
    state: the participant's replicated shard config fences it."""
    plane = ShardedMetaPlane(tmp_path, n_shards=2)
    try:
        f = plane.facade
        m = plane.current_map()
        f.create_volume("v")
        b0 = _bucket_on(m, "v", "s0")
        f.create_bucket("v", b0, replication="RATIS/1")
        _put_meta(f, "v", b0, "k")
        # rebalance some OTHER slot: epoch moves past the stale map
        other = next(s for s in plane.current_map().owned_slots("s0")
                     if s != slot_for("v", b0))
        plane.migrate_slot(other, "s1")
        with pytest.raises(rq.OMError) as ei:
            plane.shards["s0"].submit(ShardPrepare(
                "t-stale", "rename_src",
                {"volume": "v", "bucket": b0, "key": "k"},
                epoch=m.epoch))  # the pre-bump epoch
        assert ei.value.code == SHARD_MOVED
        assert not list(plane.shards["s0"].om.store.iterate(
            "system", "txn_intent/"))
    finally:
        plane.close()


def test_cross_shard_bucket_link_resolves_across_rings(tmp_path):
    plane = ShardedMetaPlane(tmp_path, n_shards=2)
    try:
        f = plane.facade
        m = plane.current_map()
        f.create_volume("v")
        src = _bucket_on(m, "v", "s0")
        f.create_bucket("v", src, replication="RATIS/1")
        _put_meta(f, "v", src, "k", size=4)
        link = _bucket_on(m, "v", "s1", prefix="ln")
        f.create_bucket_link("v", src, "v", link)
        # reads through the link route to the source's shard
        assert f.resolve_bucket("v", link) == ("v", src)
        assert f.lookup_key("v", link, "k")["size"] == 4
        # effective replication surfaces through the link row
        assert f.bucket_info("v", link)["replication"] == "RATIS/1"
    finally:
        plane.close()


# -------------------------------------------------- ring mode: leader kills
def test_ring_shard_survives_leader_kill(tmp_path):
    plane = ShardedMetaPlane(tmp_path, n_shards=2, mode="ring",
                             replicas=3)
    try:
        f = plane.facade
        m = plane.current_map()
        f.create_volume("v")
        b0 = _bucket_on(m, "v", "s0")
        f.create_bucket("v", b0, replication="RATIS/1")
        _put_meta(f, "v", b0, "before")
        killed = plane.shards["s0"].kill_leader()
        # failover: writes keep working on the new leader
        _put_meta(f, "v", b0, "after")
        new_leader = plane.shards["s0"].await_leader()
        assert new_leader.node.node_id != killed
        assert {k["name"] for k in f.list_keys("v", b0)} \
            == {"before", "after"}
    finally:
        plane.close()


def test_leader_kill_mid_cross_shard_rename_both_or_neither(tmp_path):
    """The ISSUE 15 drill: kill -9 the source shard's leader while a
    cross-shard rename is in flight (after its prepare replicated).
    The staged intent must survive failover, the commit must land on
    the NEW leader, and the key must be visible under exactly one
    name."""
    plane = ShardedMetaPlane(tmp_path, n_shards=2, mode="ring",
                             replicas=3)
    try:
        f = plane.facade
        m = plane.current_map()
        f.create_volume("v")
        src = _bucket_on(m, "v", "s0")
        dst = _bucket_on(m, "v", "s1")
        f.create_bucket("v", src, replication="RATIS/1")
        f.create_bucket("v", dst, replication="RATIS/1")
        _put_meta(f, "v", src, "old", size=21)

        real = plane.coordinator._shard_submit
        state = {"killed": False}

        def kill_after_src_prepare(sid, request):
            result = real(sid, request)
            if isinstance(request, ShardPrepare) \
                    and request.op == "rename_src" \
                    and not state["killed"]:
                state["killed"] = True
                plane.shards["s0"].kill_leader()
            return result

        plane.coordinator._shard_submit = kill_after_src_prepare
        info = f.rename_key_cross("v", src, "old", dst, "new")
        assert state["killed"], "drill never fired"
        assert info["name"] == "new" and int(info["size"]) == 21
        assert f.lookup_key("v", dst, "new")["size"] == 21
        with pytest.raises(rq.OMError):
            f.lookup_key("v", src, "old")
        # the new leader's replicated store drained the intent
        for sid in plane.shard_ids:
            assert not list(plane.shards[sid].om.store.iterate(
                "system", "txn_intent/"))
    finally:
        plane.close()


# ------------------------------------------------------- data-path readback
def test_cross_shard_rename_byte_exact_readback(tmp_path):
    """Acceptance: after a cross-shard rename (with a mid-flight
    coordinator crash + recovery on the way), the key reads back
    byte-exact under its new name on the full data path."""
    from ozone_tpu.testing.minicluster import MiniOzoneCluster

    mini = MiniOzoneCluster(tmp_path / "data", num_datanodes=5,
                            block_size=256 * 1024)
    plane = ShardedMetaPlane(tmp_path / "meta", n_shards=2,
                             scm=mini.scm, clients=mini.clients)
    try:
        oz = plane.client(mini.clients)
        vol = oz.create_volume("v")
        m = plane.current_map()
        src = _bucket_on(m, "v", "s0")
        dst = _bucket_on(m, "v", "s1")
        vol.create_bucket(src, replication="RATIS/THREE")
        vol.create_bucket(dst, replication="RATIS/THREE")
        rng = np.random.default_rng(7)
        payload = rng.integers(0, 256, 700_000, dtype=np.uint8)
        oz.get_volume("v").get_bucket(src).write_key("blob", payload)

        # crash the coordinator between the prepares, then recover:
        # presumed abort, blob intact at the source, byte-exact
        rec = {"kind": "rename", "volume": "v", "src_bucket": src,
               "key": "blob", "dst_bucket": dst, "new_key": "moved",
               "src_shard": "s0", "dst_shard": "s1", "epoch": m.epoch}
        plane.root.submit(TxnJournal("t-io", "begin", rec))
        plane.shards["s0"].submit(ShardPrepare(
            "t-io", "rename_src",
            {"volume": "v", "bucket": src, "key": "blob"},
            epoch=m.epoch))
        plane.recover()
        got = oz.get_volume("v").get_bucket(src).read_key("blob")
        np.testing.assert_array_equal(got, payload)

        # now the rename completes for real: readable under exactly
        # the new name, bytes identical (block groups moved with it)
        plane.facade.rename_key_cross("v", src, "blob", dst, "moved")
        got = oz.get_volume("v").get_bucket(dst).read_key("moved")
        np.testing.assert_array_equal(got, payload)
        with pytest.raises(rq.OMError):
            plane.facade.lookup_key("v", src, "blob")
    finally:
        plane.close()


# ---------------------------------------------------------- follower reads
def test_follower_reads_serve_mix_and_bound_staleness(
        tmp_path, monkeypatch):
    monkeypatch.setenv("OZONE_TPU_OM_FOLLOWER_READS", "1")
    # timers off: elections are driven on demand, so a killed leader's
    # followers are NOT re-leased by a fast re-election before the
    # staleness assertion below can observe the refusal
    plane = ShardedMetaPlane(tmp_path, n_shards=1, mode="ring",
                             replicas=3, follower_reads=True,
                             timers=False)
    try:
        f = plane.facade
        m = plane.current_map()
        f.create_volume("v")
        b0 = _bucket_on(m, "v", "s0")
        f.create_bucket("v", b0, replication="RATIS/1")
        _put_meta(f, "v", b0, "k", size=3)
        hits0 = METRICS.counter("follower_read_hits").value
        # read-your-writes: the facade threads the applied floor, so a
        # fresh lease-holding follower answers immediately post-write
        for _ in range(10):
            assert f.lookup_key("v", b0, "k")["size"] == 3
        hits = METRICS.counter("follower_read_hits").value - hits0
        assert hits >= 8, f"only {hits}/10 reads served by followers"
        leader = plane.shards["s0"].await_leader().node.node_id
        served_by_leader = any(
            r.node.node_id == leader and r.node.is_leader
            for r in plane.shards["s0"].replicas)
        assert served_by_leader  # sanity: a leader exists

        # staleness bound: kill the leader and outwait the lease —
        # every follower must REFUSE (no heartbeats renew the lease)
        # and the read must fall back to an elected leader
        from ozone_tpu.om.sharding.leases import lease_duration_s

        plane.shards["s0"].kill_leader()
        time.sleep(lease_duration_s() + 0.1)
        misses0 = METRICS.counter("follower_read_misses").value
        assert f.lookup_key("v", b0, "k")["size"] == 3
        assert METRICS.counter("follower_read_misses").value > misses0
    finally:
        plane.close()


def test_follower_read_hit_rate_over_80_percent(tmp_path, monkeypatch):
    """Acceptance: the ommg lookup/list mix is served >= 80% by
    followers without touching a leader."""
    monkeypatch.setenv("OZONE_TPU_OM_FOLLOWER_READS", "1")
    from ozone_tpu.tools import freon

    plane = ShardedMetaPlane(tmp_path, n_shards=2, mode="ring",
                             replicas=3, follower_reads=True)
    try:
        h0 = METRICS.counter("follower_read_hits").value
        m0 = METRICS.counter("follower_read_misses").value
        freon.ommg(plane.client(), n_ops=200, threads=4, mix="rl",
                   buckets=4)
        hits = METRICS.counter("follower_read_hits").value - h0
        misses = METRICS.counter("follower_read_misses").value - m0
        assert hits + misses > 0
        rate = hits / (hits + misses)
        assert rate >= 0.8, f"follower-read hit rate {rate:.2f}"
    finally:
        plane.close()


# --------------------------------------------------------------- over gRPC
def test_minisharded_cluster_routes_and_rebalances(tmp_path):
    """The wire-level plane: per-shard daemons with replicated shard
    configs, a shard-aware client routing by the fetched map, and a
    live rebalance the client rides out via SHARD_MOVED + refetch."""
    from ozone_tpu.testing.minicluster import MiniShardedCluster

    cluster = MiniShardedCluster(tmp_path, n_shards=2)
    om = None
    try:
        om = cluster.om_client()
        om.create_volume("v")
        b0 = _bucket_on(cluster.map, "v", "s0")
        om.create_bucket("v", b0, replication="RATIS/1")
        s = om.open_key("v", b0, "k")
        om.commit_key(s, [], 0)
        assert [k["name"] for k in om.list_keys("v", b0)] == ["k"]
        # rebalance the bucket's slot out from under the client
        cluster.move_slot(slot_for("v", b0), "s1")
        assert [k["name"] for k in om.list_keys("v", b0)] == ["k"]
        s = om.open_key("v", b0, "k2")
        om.commit_key(s, [], 0)
        assert {k["name"] for k in om.list_keys("v", b0)} == {"k", "k2"}
    finally:
        if om is not None:
            om.close()
        cluster.shutdown()


@pytest.mark.serial
def test_shardd_processes_route_and_stop_clean(tmp_path):
    """Deployment shape: one `ozone_tpu.tools.shardd` OS process per
    shard, a shard-aware client routing across them, SIGTERM exits 0."""
    import signal
    import socket
    import subprocess
    import sys

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    from ozone_tpu.net.om_service import GrpcOmClient

    book = {f"s{i}": f"127.0.0.1:{free_port()}" for i in range(2)}
    arg = ",".join(f"{k}={v}" for k, v in book.items())
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    procs = [subprocess.Popen(
        [sys.executable, "-m", "ozone_tpu.tools.shardd",
         "--base", str(tmp_path / sid), "--shard-id", sid,
         "--shards", arg],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        for sid in book]
    om = None
    try:
        deadline = time.time() + 120
        while time.time() < deadline:
            try:
                ready = 0
                for a in book.values():
                    c = GrpcOmClient(a, shard_aware=False)
                    try:
                        if c.get_shard_map():
                            ready += 1
                    finally:
                        c.close()
                if ready == len(book):
                    break
            except Exception:
                pass
            time.sleep(0.3)
        else:
            raise TimeoutError("shardd processes never became ready")
        om = GrpcOmClient(",".join(book.values()), shard_aware=True)
        om.create_volume("v")
        m = ShardMap.from_json(om.get_shard_map())
        for sid in book:
            b = _bucket_on(m, "v", sid)
            om.create_bucket("v", b, replication="RATIS/1")
            s = om.open_key("v", b, "k")
            om.commit_key(s, [], 0)
            assert [k["name"] for k in om.list_keys("v", b)] == ["k"]
    finally:
        if om is not None:
            om.close()
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            assert p.wait(timeout=30) == 0
