"""Pallas fused kernel correctness (interpreter mode on CPU)."""

import numpy as np
import pytest

from ozone_tpu.codec.api import CoderOptions
from ozone_tpu.codec.fused import FusedSpec
from ozone_tpu.codec.numpy_coder import NumpyRSEncoder
from ozone_tpu.codec.pallas_kernel import make_pallas_fused_encoder
from ozone_tpu.utils.checksum import ChecksumType, crc32c


@pytest.mark.parametrize("sb", [1, 2])
def test_pallas_fused_matches_reference(sb):
    bpc, cell = 512, 2048
    opts = CoderOptions(3, 2, "rs", cell_size=cell)
    spec = FusedSpec(opts, ChecksumType.CRC32C, bpc)
    fn = make_pallas_fused_encoder(spec, stripes_per_block=sb, interpret=True)
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (4, 3, cell), dtype=np.uint8)
    parity, crcs = (np.asarray(x) for x in fn(data))
    expect = NumpyRSEncoder(opts).encode(data)
    assert np.array_equal(parity, expect)
    units = np.concatenate([data, expect], axis=1)
    s = cell // bpc
    for b in range(4):
        for u in range(5):
            for si in range(s):
                assert int(crcs[b, u, si]) == crc32c(
                    units[b, u, si * bpc : (si + 1) * bpc]
                ), (b, u, si)
