"""Network-partition fault injection — the blockade-test analog.

The reference drives iptables partitions around docker containers
(fault-injection-test/network-tests/src/test/blockade/: datanode
isolation, SCM isolation scenarios). Here the injection lives in the RPC
layer (net/partition.py): outbound calls to a blocked destination fail
exactly like a cut wire, scoped per channel owner so one replica of an
in-process ring can be isolated from its peers.
"""

import time

import pytest

from ozone_tpu.net import partition
from ozone_tpu.net.rpc import RpcChannel, RpcServer
from ozone_tpu.storage.ids import StorageError
from ozone_tpu.testing.minicluster import (
    await_meta_leader as _await_leader,
    make_meta_daemon as _make_meta,
)

N_META = 3


@pytest.fixture(autouse=True)
def _clean_partitions():
    partition.clear()
    yield
    partition.clear()


def test_blocked_channel_fails_like_a_cut_wire():
    server = RpcServer()
    server.add_service("echo", {"Echo": lambda b: b})
    server.start()
    try:
        ch = RpcChannel(server.address)
        assert ch.call("echo", "Echo", b"hi") == b"hi"
        partition.block(server.address)
        with pytest.raises(StorageError) as ei:
            ch.call("echo", "Echo", b"hi")
        assert ei.value.code == "UNAVAILABLE"
        partition.heal(server.address)
        assert ch.call("echo", "Echo", b"hi") == b"hi"
        ch.close()
    finally:
        server.stop()


def test_owner_scoped_block_only_cuts_tagged_channels():
    server = RpcServer()
    server.add_service("echo", {"Echo": lambda b: b})
    server.start()
    try:
        tagged = RpcChannel(server.address, owner="m0")
        plain = RpcChannel(server.address)
        partition.block(server.address, owner="m0")
        with pytest.raises(StorageError):
            tagged.call("echo", "Echo", b"x")
        assert plain.call("echo", "Echo", b"x") == b"x"
        tagged.close()
        plain.close()
    finally:
        server.stop()


def test_insight_rpc_controls_partitions():
    """The remote control plane: Partition/Heal/PartitionList verbs on any
    daemon's insight service (how multi-process drills cut links)."""
    from ozone_tpu.utils.insight import InsightClient

    server = RpcServer()
    from ozone_tpu.utils.insight import InsightService

    InsightService(server, "test")
    server.start()
    try:
        cli = InsightClient(server.address)
        cli.partition("10.0.0.9:1234")
        cli.partition("10.0.0.7:1234", owner="m2")
        got = cli.partition_list()
        assert [tuple(x) for x in got] == [
            ("*", "10.0.0.9:1234"), ("m2", "10.0.0.7:1234")]
        cli.heal("10.0.0.9:1234")
        assert [tuple(x) for x in cli.partition_list()] == [
            ("m2", "10.0.0.7:1234")]
        cli.heal()  # no dst -> clear all
        assert cli.partition_list() == []
        cli.close()
    finally:
        server.stop()


def test_leader_isolation_elects_new_leader_and_heals(tmp_path):
    """SCM/OM-isolation blockade scenario: sever both directions of the
    raft links between the leader and its followers. The majority side
    elects a new leader and keeps serving; the isolated ex-leader cannot
    commit; healing the partition deposes it and it converges."""
    from ozone_tpu.testing.minicluster import free_ports

    ports = free_ports(N_META)
    peers = {f"m{i}": f"127.0.0.1:{ports[i]}" for i in range(N_META)}
    metas = {}
    try:
        for i in range(N_META):
            d = _make_meta(tmp_path, i, peers)
            d.start()
            metas[f"m{i}"] = d
        old = _await_leader(metas)
        followers = [m for m in metas if m != old]

        # cut leader <-> follower links in both directions (one blockade
        # rule per endpoint, like netfilter in each container)
        for f in followers:
            partition.block(peers[old], owner=f)   # f -> old
            partition.block(peers[f], owner=old)   # old -> f
        new = _await_leader(metas, timeout=15.0, among=followers)
        assert new != old

        # majority side serves writes (client dials the followers only;
        # the deposed side would hold a write for its full ack timeout)
        from ozone_tpu.net.om_service import GrpcOmClient

        om = GrpcOmClient(",".join(peers[f] for f in followers))
        om.create_volume("pv")
        assert "pv" in [v["name"] for v in om.list_volumes()]

        # the isolated ex-leader never saw the write
        assert "pv" not in [v["name"]
                            for v in metas[old].om.list_volumes()]

        # ---- heal: ex-leader hears the higher term, steps down, catches
        # up from the raft log
        partition.clear()
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            vols = [v["name"] for v in metas[old].om.list_volumes()]
            if "pv" in vols and not metas[old].ha.is_leader:
                break
            time.sleep(0.1)
        assert "pv" in [v["name"] for v in metas[old].om.list_volumes()]
        assert not metas[old].ha.is_leader
        _await_leader(metas)  # exactly one leader cluster-wide
        om.close()
    finally:
        for d in metas.values():
            try:
                d.stop()
            except Exception:
                pass


def test_ec_writes_and_reads_survive_partitioned_datanode(tmp_path):
    """Datanode-isolation blockade scenario on the datapath: with the
    client's link to one datanode cut, EC writes exclude it and succeed;
    reads of keys holding a unit there fall back to degraded (decode)
    reads. Healing restores direct reads."""
    import numpy as np

    from ozone_tpu.client.dn_client import DatanodeClientFactory
    from ozone_tpu.client.ozone_client import OzoneClient
    from ozone_tpu.net.daemons import DatanodeDaemon, ScmOmDaemon
    from ozone_tpu.net.om_service import GrpcOmClient

    meta = ScmOmDaemon(tmp_path / "om.db", block_size=4 * 4096,
                       stale_after_s=1000.0, dead_after_s=2000.0,
                       background_interval_s=0.5)
    meta.start()
    dns = [DatanodeDaemon(tmp_path / f"dn{i}", f"dn{i}", meta.address,
                          heartbeat_interval_s=0.2) for i in range(6)]
    for d in dns:
        d.start()
    try:
        clients = DatanodeClientFactory()
        oz = OzoneClient(GrpcOmClient(meta.address, clients=clients),
                         clients)
        b = oz.create_volume("v").create_bucket("b",
                                                replication="rs-3-2-4096")
        rng = np.random.default_rng(0)
        pre = rng.integers(0, 256, 30_000, dtype=np.uint8)
        b.write_key("pre", pre)

        # cut the client's link to the datanode holding unit 1 of "pre"
        info = oz.om.lookup_key("v", "b", "pre")
        victim = info["block_groups"][0]["nodes"][0]
        partition.block(dns[[d.dn.id for d in dns].index(victim)].address)

        # degraded read: unit 1 is unreachable -> decode from survivors
        assert np.array_equal(b.read_key("pre"), pre)

        # writes keep flowing: the writer excludes the unreachable node
        during = rng.integers(0, 256, 25_000, dtype=np.uint8)
        b.write_key("during", during)
        assert np.array_equal(b.read_key("during"), during)
        nodes_used = {
            n
            for g in oz.om.lookup_key("v", "b", "during")["block_groups"]
            for n in g["nodes"]
        }
        assert victim not in nodes_used

        # heal: direct reads of the original key work again
        partition.clear()
        assert np.array_equal(b.read_key("pre"), pre)
    finally:
        for d in dns:
            d.stop()
        meta.stop()


def test_replicated_writes_survive_partitioned_datanode(tmp_path):
    """STANDALONE/ONE writes reallocate away from a member whose link is
    cut at group-creation time (the StripeWriteError exclusion path)."""
    import numpy as np

    from ozone_tpu.client.dn_client import DatanodeClientFactory
    from ozone_tpu.client.ozone_client import OzoneClient
    from ozone_tpu.net.daemons import DatanodeDaemon, ScmOmDaemon
    from ozone_tpu.net.om_service import GrpcOmClient

    meta = ScmOmDaemon(tmp_path / "om.db", block_size=4 * 4096,
                       stale_after_s=1000.0, dead_after_s=2000.0,
                       background_interval_s=0.5)
    meta.start()
    dns = [DatanodeDaemon(tmp_path / f"dn{i}", f"dn{i}", meta.address,
                          heartbeat_interval_s=0.2) for i in range(3)]
    for d in dns:
        d.start()
    try:
        clients = DatanodeClientFactory()
        oz = OzoneClient(GrpcOmClient(meta.address, clients=clients),
                         clients)
        b = oz.create_volume("v").create_bucket(
            "b", replication="STANDALONE/ONE")
        partition.block(dns[0].address)  # cut one member preemptively
        rng = np.random.default_rng(1)
        for i in range(4):  # enough writes to hit the cut node's turn
            data = rng.integers(0, 256, 6_000, dtype=np.uint8)
            b.write_key(f"k{i}", data)
            assert np.array_equal(b.read_key(f"k{i}"), data)
            nodes = {
                n
                for g in oz.om.lookup_key("v", "b", f"k{i}")["block_groups"]
                for n in g["nodes"]
            }
            assert dns[0].dn.id not in nodes
    finally:
        for d in dns:
            d.stop()
        meta.stop()


def test_block_rollover_survives_partitioned_datanode(tmp_path):
    """A key spanning multiple blocks keeps writing when the rollover
    allocation lands on a partitioned member (the rollover _ensure_group
    must ride the same exclude+retry handler)."""
    import numpy as np

    from ozone_tpu.client.dn_client import DatanodeClientFactory
    from ozone_tpu.client.ozone_client import OzoneClient
    from ozone_tpu.net.daemons import DatanodeDaemon, ScmOmDaemon
    from ozone_tpu.net.om_service import GrpcOmClient

    meta = ScmOmDaemon(tmp_path / "om.db", block_size=2 * 4096,
                       stale_after_s=1000.0, dead_after_s=2000.0,
                       background_interval_s=0.5)
    meta.start()
    dns = [DatanodeDaemon(tmp_path / f"dn{i}", f"dn{i}", meta.address,
                          heartbeat_interval_s=0.2) for i in range(3)]
    for d in dns:
        d.start()
    try:
        from ozone_tpu.client.replicated import ReplicatedKeyWriter

        clients = DatanodeClientFactory()
        oz = OzoneClient(GrpcOmClient(meta.address, clients=clients),
                         clients)
        b = oz.create_volume("v").create_bucket(
            "b", replication="STANDALONE/ONE")
        partition.block(dns[1].address)
        data = np.random.default_rng(2).integers(
            0, 256, 40_000, dtype=np.uint8)
        om = oz.om
        session = om.open_key("v", "b", "multi")
        # small chunks force flushes and block rollovers mid-write
        writer = ReplicatedKeyWriter(
            lambda excluded, ec=(): om.allocate_block(session, excluded, ec),
            clients, block_size=8192, chunk_size=4096,
        )
        writer.write(data)
        groups_out = writer.close()
        om.commit_key(session, groups_out, writer.bytes_written)
        assert np.array_equal(b.read_key("multi"), data)
        groups = om.lookup_key("v", "b", "multi")["block_groups"]
        assert len(groups) >= 3  # the rollover path really ran
        assert all(dns[1].dn.id not in g["nodes"] for g in groups)
    finally:
        for d in dns:
            d.stop()
        meta.stop()


def test_delay_injection_slows_but_does_not_break():
    """The blockade slow-network scenario: a delayed link still works,
    with the injected latency; latency past the caller's deadline fails
    like a real slow link; heal removes the rule."""
    server = RpcServer()
    server.add_service("echo", {"Echo": lambda b: b})
    server.start()
    try:
        ch = RpcChannel(server.address)
        ch.call("echo", "Echo", b"x")
        partition.delay(server.address, 0.25)
        t0 = time.perf_counter()
        assert ch.call("echo", "Echo", b"x") == b"x"
        slow = time.perf_counter() - t0
        assert slow >= 0.25
        # latency exceeding the deadline -> UNAVAILABLE, like a real
        # slow link tripping DEADLINE_EXCEEDED
        with pytest.raises(StorageError) as ei:
            ch.call("echo", "Echo", b"x", timeout=0.05)
        assert ei.value.code == "UNAVAILABLE"
        partition.heal(server.address)
        t0 = time.perf_counter()
        ch.call("echo", "Echo", b"x")
        healed = time.perf_counter() - t0
        assert healed < slow / 2  # relative bound: no flaky wall-clock cap
        ch.close()
    finally:
        server.stop()


def test_delay_remote_control_plane():
    from ozone_tpu.utils.insight import InsightClient, InsightService

    server = RpcServer()
    InsightService(server, "test")
    server.start()
    try:
        cli = InsightClient(server.address)
        cli.delay("10.0.0.9:1", 0.5)
        assert partition.delay_for("10.0.0.9:1") == 0.5
        cli.heal("10.0.0.9:1")
        assert partition.delay_for("10.0.0.9:1") == 0.0
        cli.close()
    finally:
        server.stop()


# ------------------------------------------------------- verb-level rules
def test_verb_rule_matching_and_expiry():
    """Byteman-analog method-boundary rules: verb-scoped, count-limited
    (deterministic fail-first-N), folded with the legacy tables."""
    partition.clear()
    try:
        rid = partition.add_rule(dst="a:1", verb="Watch",
                                 drop_pct=100, count=2)
        assert partition.consult("a:1", "/svc/Watch", None) == (True, 0.0)
        # other verbs and other peers unaffected
        assert partition.consult("a:1", "/svc/Submit", None) == (False, 0.0)
        assert partition.consult("b:2", "/svc/Watch", None) == (False, 0.0)
        assert partition.consult("a:1", "/svc/Watch", None) == (True, 0.0)
        # count exhausted: rule auto-expired
        assert partition.consult("a:1", "/svc/Watch", None) == (False, 0.0)
        assert all(r["id"] != rid for r in partition.rules())

        # delay rules merge with address-level delays (max wins)
        partition.add_rule(verb="Watch", delay_s=0.4)
        partition.delay("a:1", 0.1)
        assert partition.consult("a:1", "/svc/Watch", None) == (False, 0.4)
        assert partition.consult("a:1", "/svc/Other", None) == (False, 0.1)
    finally:
        partition.clear()


def test_verb_rule_fires_through_rpc_channel():
    partition.clear()
    server = RpcServer()
    server.add_service("t.Svc", {"Echo": lambda req: req,
                                 "Other": lambda req: req})
    server.start()
    try:
        ch = RpcChannel(server.address)
        assert ch.call("t.Svc", "Echo", b"x") == b"x"
        partition.add_rule(dst=server.address, verb="Echo",
                           drop_pct=100, count=1)
        with pytest.raises(StorageError) as ei:
            ch.call("t.Svc", "Echo", b"x")
        assert ei.value.code == "UNAVAILABLE"
        assert ch.call("t.Svc", "Other", b"y") == b"y"  # untouched verb
        assert ch.call("t.Svc", "Echo", b"x") == b"x"  # rule expired
        ch.close()
    finally:
        partition.clear()
        server.stop()


def test_watch_downgrade_deterministic_slow_follower(tmp_path):
    """Verdict item 9's drill: a verb rule delaying raft append_entries
    to ONE follower reproduces the slow-follower interleaving
    deterministically — the client's watchForCommit(ALL) times out,
    degrades to MAJORITY (XceiverClientRatis watch-degrade), the write
    completes, and healing the rule lets ALL complete again."""
    import numpy as np

    from ozone_tpu.client.dn_client import DatanodeClientFactory
    from ozone_tpu.client.ozone_client import OzoneClient
    from ozone_tpu.client.ratis_client import XceiverClientRatis
    from ozone_tpu.net.daemons import DatanodeDaemon, ScmOmDaemon
    from ozone_tpu.net.om_service import GrpcOmClient
    from ozone_tpu.net.ratis_service import RatisClientFactory
    from ozone_tpu.net.scm_service import GrpcScmClient

    partition.clear()
    meta = ScmOmDaemon(tmp_path / "om.db", stale_after_s=1000.0,
                       dead_after_s=2000.0)
    meta.start()
    dns = [DatanodeDaemon(tmp_path / f"dn{i}", f"dn{i}", meta.address,
                          heartbeat_interval_s=0.1) for i in range(3)]
    for d in dns:
        d.start()
    rule_id = None
    try:
        clients = DatanodeClientFactory()
        om = GrpcOmClient(meta.address, clients=clients)
        scm = GrpcScmClient(meta.address)
        for dn_id, addr in scm.node_addresses().items():
            clients.register_remote(dn_id, addr)
        ratis = RatisClientFactory(address_source=clients.remote_address)
        oz = OzoneClient(om, clients, ratis_clients=ratis)
        oz.create_volume("v")
        b = oz.get_volume("v").create_bucket("b",
                                             replication="RATIS/THREE")
        payload = np.random.default_rng(1).integers(
            0, 256, 50_000, dtype=np.uint8)
        b.write_key("k0", payload)
        info = oz.om.lookup_key("v", "b", "k0")
        g = info["block_groups"][0]
        from ozone_tpu.scm.pipeline import Pipeline, ReplicationConfig

        pipeline = Pipeline(ReplicationConfig.ratis(3),
                            list(g["nodes"]), id=int(g["pipeline_id"]))
        x = XceiverClientRatis(pipeline, ratis)
        # discover the leader with a harmless ordered no-op
        x.submit({"verb": "create_container", "container_id": 776})
        leader = x._leader
        follower = next(n for n in pipeline.nodes if n != leader)

        # deterministic lagging follower: appends to it fail FAST
        # (drop, not delay — the raft leader replicates sequentially,
        # so a delayed leg would starve the healthy peer's heartbeats
        # and trigger elections), and its own election attempts go
        # nowhere (without the vote rule the starved follower campaigns
        # with ever-higher terms and deposes the leader — the
        # disruptive-server problem pre-vote exists for)
        rule_id = partition.add_rule(
            dst=clients.remote_address(follower),
            verb="append_entries", drop_pct=100)
        vote_rule = partition.add_rule(
            owner=follower, verb="request_vote", drop_pct=100)
        out = x.submit({"verb": "create_container",
                        "container_id": 777})
        idx = int(out["index"])
        assert not x._degraded
        got = x.watch_for_commit(idx, timeout=1.5)
        assert x._degraded, "watch(ALL) should have degraded to MAJORITY"
        assert int(got["index"]) >= idx
        # sticky: later watches skip straight to MAJORITY, still served
        assert int(x.watch_for_commit(idx, timeout=1.5)["index"]) >= idx

        # heal: the follower catches up and ALL completes again
        partition.remove_rule(rule_id)
        partition.remove_rule(vote_rule)
        rule_id = None
        fresh = XceiverClientRatis(pipeline, ratis)
        deadline = time.monotonic() + 20
        while True:
            try:
                fresh.watch_for_commit(idx, timeout=2.0)
                assert not fresh._degraded
                break
            except StorageError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.5)
        scm.close()
        om.close()
        clients.close()
    finally:
        if rule_id is not None:
            partition.remove_rule(rule_id)
        partition.clear()
        for d in dns:
            d.stop()
        meta.stop()
