"""Volume/bucket quotas: space + namespace enforcement on the commit
path, usage accounting across commit/overwrite/delete/hsync/multipart,
and the quota repair recompute (reference: OmBucketInfo usedBytes /
usedNamespace, OMKeyCommitRequest quota check, quota repair service).
"""

import numpy as np
import pytest

from ozone_tpu.om.requests import OMError
from ozone_tpu.testing.minicluster import MiniOzoneCluster

EC = "rs-3-2-4096"


@pytest.fixture
def cluster(tmp_path):
    c = MiniOzoneCluster(
        tmp_path,
        num_datanodes=5,
        block_size=4 * 4096,
        container_size=1024 * 1024,
        stale_after_s=1000.0,
        dead_after_s=2000.0,
    )
    yield c
    c.close()


def _data(n, seed=0):
    return np.random.default_rng(seed).integers(0, 256, n, dtype=np.uint8)


def test_bucket_space_quota_enforced(cluster):
    oz = cluster.client()
    b = oz.create_volume("v").create_bucket("b", replication=EC)
    oz.om.set_quota("v", "b", quota_bytes=10_000)
    b.write_key("ok", _data(8_000))
    with pytest.raises(OMError) as ei:
        b.write_key("too-big", _data(5_000, 1))
    assert ei.value.code == "QUOTA_EXCEEDED"
    # usage unchanged by the rejected write
    assert oz.om.bucket_info("v", "b")["used_bytes"] == 8_000
    # freeing space lets writes through again
    b.delete_key("ok")
    b.write_key("fits", _data(5_000, 1))
    assert oz.om.bucket_info("v", "b")["used_bytes"] == 5_000


def test_namespace_quota_enforced(cluster):
    oz = cluster.client()
    b = oz.create_volume("v").create_bucket("b", replication=EC)
    oz.om.set_quota("v", "b", quota_namespace=2)
    b.write_key("k1", _data(100))
    b.write_key("k2", _data(100, 1))
    with pytest.raises(OMError) as ei:
        b.write_key("k3", _data(100, 2))
    assert ei.value.code == "QUOTA_EXCEEDED"
    # overwrite is not a new name: allowed
    b.write_key("k2", _data(200, 3))
    assert oz.om.bucket_info("v", "b")["key_count"] == 2


def test_volume_quota_spans_buckets(cluster):
    oz = cluster.client()
    vol = oz.create_volume("v")
    b1 = vol.create_bucket("b1", replication=EC)
    b2 = vol.create_bucket("b2", replication=EC)
    oz.om.set_quota("v", quota_bytes=10_000)
    b1.write_key("k", _data(6_000))
    with pytest.raises(OMError):
        b2.write_key("k", _data(6_000, 1))
    b2.write_key("k", _data(3_000, 1))
    assert oz.om.volume_info("v")["used_bytes"] == 9_000


def test_usage_accounting_overwrite_and_multipart(cluster):
    oz = cluster.client()
    b = oz.create_volume("v").create_bucket("b", replication=EC)
    b.write_key("k", _data(5_000))
    b.write_key("k", _data(2_000, 1))  # overwrite shrinks usage
    assert oz.om.bucket_info("v", "b")["used_bytes"] == 2_000
    mpu = b.initiate_multipart_upload("big")
    mpu.write_part(1, _data(6_000, 2))
    mpu.write_part(2, _data(6_000, 3))
    mpu.complete()
    info = oz.om.bucket_info("v", "b")
    assert info["used_bytes"] == 2_000 + 12_000
    assert info["key_count"] == 2


def test_hsync_stream_charges_incrementally(cluster):
    oz = cluster.client()
    b = oz.create_volume("v").create_bucket("b", replication="RATIS/THREE")
    oz.om.set_quota("v", "b", quota_bytes=30_000)
    h = b.open_key("k")
    h.write(_data(10_000))
    h.hsync()
    assert oz.om.bucket_info("v", "b")["used_bytes"] == 10_000
    h.write(_data(10_000, 1))
    h.hsync()
    assert oz.om.bucket_info("v", "b")["used_bytes"] == 20_000
    h.close()
    info = oz.om.bucket_info("v", "b")
    assert info["used_bytes"] == 20_000 and info["key_count"] == 1


def test_quota_repair_recomputes_from_tables(cluster):
    oz = cluster.client()
    b = oz.create_volume("v").create_bucket("b", replication=EC)
    b.write_key("k1", _data(4_000))
    b.write_key("k2", _data(6_000, 1))
    # corrupt the counters to simulate drift
    oz.om.set_quota("v", "b")  # no-op write keeps row shape
    store = cluster.om.store
    row = store.get("buckets", "/v/b")
    row["used_bytes"] = 999_999
    store.put("buckets", "/v/b", row)
    out = oz.om.repair_quota("v")
    assert out["buckets"]["/v/b"] == {"used_bytes": 10_000, "key_count": 2}
    assert oz.om.bucket_info("v", "b")["used_bytes"] == 10_000
    assert oz.om.volume_info("v")["used_bytes"] == 10_000


def test_fso_files_count_against_quota(cluster):
    oz = cluster.client()
    oz.create_volume("v")
    oz.om.create_bucket("v", "fso", "RATIS/THREE",
                        "FILE_SYSTEM_OPTIMIZED")
    b = oz.get_volume("v").get_bucket("fso")
    oz.om.set_quota("v", "fso", quota_bytes=5_000)
    b.write_key("d/f1", _data(3_000))
    with pytest.raises(OMError):
        b.write_key("d/f2", _data(3_000, 1))
    assert oz.om.bucket_info("v", "fso")["used_bytes"] == 3_000
    # recursive dir delete releases the space
    oz.om.delete_directory("v", "fso", "d", recursive=True)
    from ozone_tpu.om import fso

    fso.DirectoryDeletingService(cluster.om).run_to_completion()
    info = oz.om.bucket_info("v", "fso")
    assert info["used_bytes"] == 0 and info["key_count"] == 0


def test_setquota_preserves_other_dimension(cluster):
    oz = cluster.client()
    oz.create_volume("v").create_bucket("b", replication=EC)
    oz.om.set_quota("v", "b", quota_namespace=7)
    oz.om.set_quota("v", "b", quota_bytes=1_000)  # must not wipe ns quota
    info = oz.om.bucket_info("v", "b")
    assert info["quota_namespace"] == 7 and info["quota_bytes"] == 1_000
    oz.om.set_quota("v", "b", quota_namespace=-1)  # explicit clear
    info = oz.om.bucket_info("v", "b")
    assert info["quota_namespace"] == -1 and info["quota_bytes"] == 1_000


def test_volume_namespace_quota_enforced(cluster):
    oz = cluster.client()
    vol = oz.create_volume("v")
    b1 = vol.create_bucket("b1", replication=EC)
    b2 = vol.create_bucket("b2", replication=EC)
    oz.om.set_quota("v", quota_namespace=2)
    b1.write_key("k1", _data(100))
    b2.write_key("k2", _data(100, 1))
    with pytest.raises(OMError) as ei:
        b1.write_key("k3", _data(100, 2))
    assert ei.value.code == "QUOTA_EXCEEDED"
    assert oz.om.volume_info("v")["key_count"] == 2


def test_mpu_complete_quota_failure_leaves_upload_retryable(cluster):
    """A QUOTA_EXCEEDED complete must not purge any part blocks: after
    freeing space the same complete succeeds with intact data."""
    oz = cluster.client()
    b = oz.create_volume("v").create_bucket("b", replication=EC)
    oz.om.set_quota("v", "b", quota_bytes=5_000)
    mpu = b.initiate_multipart_upload("big")
    data = _data(8_000, 9)
    mpu.write_part(1, data[:4_000])
    mpu.write_part(2, data[4_000:])
    with pytest.raises(OMError) as ei:
        mpu.complete()
    assert ei.value.code == "QUOTA_EXCEEDED"
    oz.om.set_quota("v", "b", quota_bytes=-1)
    mpu.complete()
    assert np.array_equal(b.read_key("big"), data)
